"""Transport manager: per-remote queues, batching, breakers, snapshot jobs.

Reference: ``internal/transport/transport.go`` — lazily spawned per-remote
sender (CockroachDB async-send pattern, ``transport.go:16-18``), message
batching up to 64MB, per-address circuit breaker, deployment-id filtering on
receive, and the chunked snapshot send plane (``snapshot.go``/``job.go``).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Optional

from ..logger import get_logger
from ..settings import Soft
from ..wire import Chunk, Message, MessageBatch, MessageType
from .registry import Registry
from .rpc import IRaftRPC, TransportError

plog = get_logger("transport")


class CircuitBreaker:
    """Minimal failure-fast breaker (plays the role of the reference's
    rubyist/circuitbreaker usage, ``transport.go:268``)."""

    def __init__(self, fail_threshold: int = 3, reset_seconds: float = 5.0):
        self.fail_threshold = fail_threshold
        self.reset_seconds = reset_seconds
        self._mu = threading.Lock()
        self._failures = 0
        self._opened_at = 0.0

    def ready(self) -> bool:
        with self._mu:
            if self._failures < self.fail_threshold:
                return True
            # half-open after the reset window
            return time.monotonic() - self._opened_at >= self.reset_seconds

    def success(self) -> None:
        with self._mu:
            self._failures = 0

    def fail(self) -> None:
        with self._mu:
            self._failures += 1
            if self._failures >= self.fail_threshold:
                self._opened_at = time.monotonic()


class SendQueue:
    def __init__(self, size: int):
        self.q: "queue.Queue[Optional[Message]]" = queue.Queue(maxsize=size)


class Transport:
    """Reference ``transport.go:156`` ``Transport``."""

    def __init__(
        self,
        source_address: str,
        deployment_id: int,
        registry: Registry,
        raft_rpc_factory: Callable[..., IRaftRPC],
        message_handler: Callable[[MessageBatch], None],
        snapshot_status_handler: Callable[[int, int, bool], None],
        unreachable_handler: Optional[Callable[[int, int], None]] = None,
        sys_events=None,
        snapshot_dir_fn: Optional[Callable[[int, int], str]] = None,
        max_send_queue_size: int = 0,
        snapshot_received_handler: Optional[Callable[[int, int, int], None]] = None,
        max_snapshot_send_bytes_per_second: int = 0,
        metrics_registry=None,
    ):
        self.source_address = source_address
        self.deployment_id = deployment_id
        self.registry = registry
        self.message_handler = message_handler
        self.snapshot_status_handler = snapshot_status_handler
        self.snapshot_received_handler = snapshot_received_handler
        self.unreachable_handler = unreachable_handler
        self.sys_events = sys_events
        self._mu = threading.Lock()
        self._queues: Dict[str, SendQueue] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._stopped = threading.Event()
        self._queue_len = max_send_queue_size or Soft.send_queue_length
        # partition injection (monkey.go:82 transport drop-hook role):
        # addr -> blocked predicate, wired by the chaos harness (the fast
        # lane blocks its native streams itself; this filter covers the
        # paths that do NOT ride them — Python-socket sends, snapshot
        # jobs, inbound chunks and Python-received batches)
        self.partition_filter: Optional[Callable[[str], bool]] = None
        # per-peer RTT injection (ISSUE 10, transport/latency.py): the
        # per-remote sender thread sleeps the link's one-way delay before
        # each batch — that link gains latency while messages queued
        # during the sleep coalesce into the same batch (latency, not a
        # bandwidth collapse).  None (default) adds zero cost.
        self.latency = None
        self._snapshot_count_mu = threading.Lock()
        self._snapshot_jobs = 0
        from .bandwidth import TokenBucket
        from .metrics import TransportMetrics

        # the owning NodeHost's registry (ISSUE 14 satellite) — the
        # dragonboat_transport_* families then ride the same exposition
        # write_health_metrics and the /metrics endpoint serve
        self.metrics = TransportMetrics(registry=metrics_registry)
        # snapshot-plane bandwidth cap (reference tcp.go:430-437); 0 = off
        self.snapshot_bucket = TokenBucket(max_snapshot_send_bytes_per_second)
        from .chunks import Chunks

        def _snapshot_received(cluster_id, node_id, index, from_):
            self.metrics.snapshot_received()
            if self.sys_events is not None:
                from ..events import SystemEvent, SystemEventType

                self.sys_events.publish(
                    SystemEvent(
                        type=SystemEventType.SNAPSHOT_RECEIVED,
                        cluster_id=cluster_id,
                        node_id=node_id,
                        index=index,
                        from_=from_,
                    )
                )
            if self.snapshot_received_handler is not None:
                # ack the sender (SNAPSHOT_RECEIVED wire message) so its
                # feedback tracker releases the send status quickly
                self.snapshot_received_handler(cluster_id, node_id, from_)

        self.chunks = Chunks(
            deployment_id=deployment_id,
            snapshot_dir_fn=snapshot_dir_fn or (lambda c, n: ""),
            message_handler=message_handler,
            source_address=source_address,
            on_received=_snapshot_received,
        )
        self.rpc = raft_rpc_factory(
            source_address, self.handle_request, self._add_chunk_filtered
        )
        self.rpc.start()

    def _add_chunk_filtered(self, c) -> bool:
        """Inbound snapshot chunks from a partitioned sender are refused
        (False poisons the transfer connection — what a netsplit does)."""
        pf = self.partition_filter
        if pf is not None:
            addr = self.registry.resolve(c.cluster_id, c.from_)
            if addr is not None and pf(addr):
                return False
        ok = self.chunks.add_chunk(c)
        if ok:
            # count only ACCEPTED chunks (the family's HELP contract) —
            # a stale/out-of-order chunk add_chunk rejects must not
            # inflate the receive counter against the sender's
            self.metrics.snapshot_chunks_received()
        return ok

    # ---- send path ----

    def breaker(self, addr: str) -> CircuitBreaker:
        with self._mu:
            b = self._breakers.get(addr)
            if b is None:
                b = CircuitBreaker()
                self._breakers[addr] = b
            return b

    def send(self, m: Message) -> bool:
        if self._stopped.is_set():
            return False
        addr = self.registry.resolve(m.cluster_id, m.to)
        if addr is None:
            return False
        pf = self.partition_filter
        if pf is not None and pf(addr):
            return False  # injected netsplit: unreachable
        b = self.breaker(addr)
        if not b.ready():
            return False
        with self._mu:
            sq = self._queues.get(addr)
            spawn = sq is None
            if spawn:
                sq = SendQueue(self._queue_len)
                self._queues[addr] = sq
        if spawn:
            t = threading.Thread(
                target=self._process_queue,
                args=(addr, sq),
                name=f"sender-{addr}",
                daemon=True,
            )
            t.start()
        try:
            sq.q.put_nowait(m)
            return True
        except queue.Full:
            self.metrics.message_dropped()
            return False

    def _process_queue(self, addr: str, sq: SendQueue) -> None:
        b = self.breaker(addr)
        conn = None
        try:
            conn = self.rpc.get_connection(addr)
            b.success()
            self._publish_conn_event(addr, failed=False)
            while not self._stopped.is_set():
                try:
                    m = sq.q.get(timeout=1.0)
                except queue.Empty:
                    continue
                if m is None:
                    return
                lat = self.latency
                if lat is not None:
                    # injected link delay (latency.py): sleep FIRST so
                    # everything arriving meanwhile rides this batch
                    d = lat.delay(self.source_address, addr)
                    if d > 0:
                        time.sleep(d)
                batch = MessageBatch(
                    requests=[m],
                    deployment_id=self.deployment_id,
                    source_address=self.source_address,
                )
                size = _msg_size(m)
                # batch everything already queued, up to the cap
                while size < Soft.max_message_batch_size:
                    try:
                        nxt = sq.q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        return
                    batch.requests.append(nxt)
                    size += _msg_size(nxt)
                conn.send_message_batch(batch)
                self.metrics.message_sent(len(batch.requests))
                self.metrics.batch_sent(size)
        except (TransportError, OSError) as e:
            plog.warning("sender to %s failed: %s", addr, e)
            self.metrics.message_connection_failed()
            b.fail()
            self._publish_conn_event(addr, failed=True)
            self._notify_unreachable(addr)
        finally:
            if conn is not None:
                conn.close()
            with self._mu:
                self._queues.pop(addr, None)

    def _publish_conn_event(self, addr: str, failed: bool, snapshot: bool = False) -> None:
        if self.sys_events is None:
            return
        from ..events import SystemEvent, SystemEventType

        if snapshot:
            t = (
                SystemEventType.SEND_SNAPSHOT_ABORTED
                if failed
                else SystemEventType.SEND_SNAPSHOT_COMPLETED
            )
        else:
            t = (
                SystemEventType.CONNECTION_FAILED
                if failed
                else SystemEventType.CONNECTION_ESTABLISHED
            )
        self.sys_events.publish(SystemEvent(type=t, address=addr))

    def _notify_unreachable(self, addr: str) -> None:
        if self.unreachable_handler is None:
            return
        for cluster_id, node_id in self.registry.reverse_resolve(addr):
            self.unreachable_handler(cluster_id, node_id)

    # ---- snapshot send plane (reference snapshot.go/job.go) ----

    def send_snapshot(self, m: Message) -> bool:
        if m.type != MessageType.INSTALL_SNAPSHOT or m.snapshot is None:
            return False
        if self._stopped.is_set():
            return False
        addr = self.registry.resolve(m.cluster_id, m.to)
        if addr is None:
            return False
        pf = self.partition_filter
        if pf is not None and pf(addr):
            return False  # injected netsplit: snapshot path blocked too
        with self._snapshot_count_mu:
            if self._snapshot_jobs >= Soft.max_snapshot_connections:
                return False
            self._snapshot_jobs += 1
        t = threading.Thread(
            target=self._snapshot_job,
            args=(m, addr),
            name=f"snapshot-to-{addr}",
            daemon=True,
        )
        t.start()
        return True

    def _snapshot_job(self, m: Message, addr: str) -> None:
        from .snapshotsender import send_snapshot_chunks, split_snapshot_message

        failed = False
        conn = None
        if self.sys_events is not None:
            from ..events import SystemEvent, SystemEventType

            self.sys_events.publish(
                SystemEvent(
                    type=SystemEventType.SEND_SNAPSHOT_STARTED,
                    cluster_id=m.cluster_id,
                    node_id=m.to,
                    address=addr,
                )
            )
        try:
            chunks = split_snapshot_message(
                m, self.deployment_id, Soft.snapshot_chunk_size
            )
            conn = self.rpc.get_snapshot_connection(addr)
            send_snapshot_chunks(
                conn, chunks, self._stopped, bucket=self.snapshot_bucket
            )
            self.metrics.snapshot_sent()
            self.metrics.snapshot_chunks_sent(len(chunks))
        except (TransportError, OSError, RuntimeError) as e:
            plog.warning("snapshot send to %s failed: %s", addr, e)
            self.metrics.snapshot_connection_failed()
            failed = True
        finally:
            if conn is not None:
                conn.close()
            with self._snapshot_count_mu:
                self._snapshot_jobs -= 1
        self._publish_conn_event(addr, failed=failed, snapshot=True)
        self.snapshot_status_handler(m.cluster_id, m.to, failed)

    # ---- streaming plane (reference GetStreamSink snapshot.go:65) ----

    def get_stream_sink(self, cluster_id: int, node_id: int):
        """A Sink streaming chunks to ``(cluster_id, node_id)`` over a
        dedicated connection, or None when unreachable/at capacity."""
        from .job import Sink, StreamJob

        if self._stopped.is_set():
            return None
        addr = self.registry.resolve(cluster_id, node_id)
        if addr is None:
            return None
        b = self.breaker(addr)
        if not b.ready():
            return None
        with self._snapshot_count_mu:
            if self._snapshot_jobs >= Soft.max_concurrent_streaming_snapshots:
                return None
            self._snapshot_jobs += 1
        if self.sys_events is not None:
            from ..events import SystemEvent, SystemEventType

            self.sys_events.publish(
                SystemEvent(
                    type=SystemEventType.SEND_SNAPSHOT_STARTED,
                    cluster_id=cluster_id,
                    node_id=node_id,
                    address=addr,
                )
            )

        def on_done(cid, nid, failed):
            with self._snapshot_count_mu:
                self._snapshot_jobs -= 1
            if failed:
                b.fail()
                self.metrics.snapshot_connection_failed()
            else:
                b.success()
                self.metrics.snapshot_sent()
            self._publish_conn_event(addr, failed=failed, snapshot=True)
            self.snapshot_status_handler(cid, nid, failed)

        job = StreamJob(
            self.rpc, addr, cluster_id, node_id, on_done,
            bucket=self.snapshot_bucket,
        )
        return Sink(job)

    # ---- receive path ----

    def handle_request(self, batch: MessageBatch) -> None:
        """Reference ``transport.go:289`` ``handleRequest``: filter by
        deployment id, then hand to the nodehost message router."""
        if batch.deployment_id != self.deployment_id:
            plog.warning(
                "dropped batch from %s: deployment id %d != %d",
                batch.source_address,
                batch.deployment_id,
                self.deployment_id,
            )
            self.metrics.message_receive_dropped(len(batch.requests))
            return
        pf = self.partition_filter
        if pf is not None and batch.source_address and pf(batch.source_address):
            self.metrics.message_receive_dropped(len(batch.requests))
            return  # injected netsplit: Python-received batch dropped
        self.metrics.message_received(len(batch.requests))
        self.metrics.batch_received(sum(_msg_size(m) for m in batch.requests))
        self.message_handler(batch)

    def tick(self) -> None:
        self.chunks.tick()

    def stop(self) -> None:
        self._stopped.set()
        with self._mu:
            queues = list(self._queues.values())
        for sq in queues:
            try:
                sq.q.put_nowait(None)
            except queue.Full:
                pass
        self.rpc.stop()


def _msg_size(m: Message) -> int:
    return 64 + sum(len(e.cmd) + 48 for e in m.entries)


def create_transport(
    nhconfig,
    registry: Registry,
    message_handler,
    snapshot_status_handler,
    unreachable_handler=None,
    snapshot_dir_fn=None,
    sys_events=None,
    snapshot_received_handler=None,
    metrics_registry=None,
) -> Transport:
    """Reference ``nodehost.go:1677`` ``createTransport``: pick the RPC module
    from config (factory override, else TCP; chan under in-memory test runs)."""
    factory = nhconfig.raft_rpc_factory
    if factory is None:
        from .tcp import TCPTransport

        def factory(addr, rh, ch):
            return TCPTransport(
                addr,
                rh,
                ch,
                listen_address=nhconfig.get_listen_address(),
                mutual_tls=nhconfig.mutual_tls,
                ca_file=nhconfig.ca_file,
                cert_file=nhconfig.cert_file,
                key_file=nhconfig.key_file,
            )

    return Transport(
        source_address=nhconfig.raft_address,
        deployment_id=nhconfig.get_deployment_id(),
        registry=registry,
        raft_rpc_factory=factory,
        message_handler=message_handler,
        snapshot_status_handler=snapshot_status_handler,
        unreachable_handler=unreachable_handler,
        snapshot_dir_fn=snapshot_dir_fn,
        max_send_queue_size=nhconfig.max_send_queue_size,
        sys_events=sys_events,
        snapshot_received_handler=snapshot_received_handler,
        max_snapshot_send_bytes_per_second=(
            nhconfig.max_snapshot_send_bytes_per_second
        ),
        metrics_registry=metrics_registry,
    )
