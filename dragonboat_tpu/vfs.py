"""Virtual filesystem abstraction + fault injection.

Reference: ``internal/vfs/vfs.go:28-45`` (``IFS`` wrapper over goutils vfs),
``internal/vfs/memfs.go`` (in-memory FS for whole-stack single-process
tests) and ``internal/vfs/error.go:25-52`` (``ErrorFS``/``Injector``
wrapping an FS to inject I/O errors, auto-detected by NodeHost to enable
panic capture, ``nodehost.go:321-327``).

Three implementations:

- :class:`OSFS` — the real filesystem (module default :data:`DEFAULT`).
- :class:`MemFS` — fully in-memory; lets snapshot/logdb paths run without
  touching disk, the analog of the reference memfs test builds.
- :class:`ErrorFS` — wraps another FS and consults an :class:`Injector`
  before every operation; used by fault-injection tests to prove failed
  saves leave no partial state behind.
"""
from __future__ import annotations

import io
import os
import threading
from typing import Callable, Dict, List, Optional


class IFS:
    """Operation surface the framework's file IO goes through."""

    def open(self, path: str, mode: str):  # "rb" | "wb" | "ab" | "r+b"
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def replace(self, src: str, dst: str) -> None:
        raise NotImplementedError

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        raise NotImplementedError

    def rmdir(self, path: str) -> None:
        raise NotImplementedError

    def rmtree(self, path: str) -> None:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def isdir(self, path: str) -> bool:
        raise NotImplementedError

    def getsize(self, path: str) -> int:
        raise NotImplementedError

    def fsync(self, f) -> None:
        raise NotImplementedError

    def fsync_dir(self, path: str) -> None:
        raise NotImplementedError


class OSFS(IFS):
    """Pass-through to the real filesystem."""

    def open(self, path: str, mode: str):
        return open(path, mode)

    def remove(self, path: str) -> None:
        os.unlink(path)

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        os.makedirs(path, exist_ok=exist_ok)

    def rmdir(self, path: str) -> None:
        os.rmdir(path)

    def rmtree(self, path: str) -> None:
        import shutil

        shutil.rmtree(path)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def getsize(self, path: str) -> int:
        return os.path.getsize(path)

    def fsync(self, f) -> None:
        f.flush()
        os.fsync(f.fileno())

    def fsync_dir(self, path: str) -> None:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


class _MemFile(io.BytesIO):
    """File handle over a MemFS entry; content lands in the FS on flush."""

    def __init__(self, fs: "MemFS", path: str, data: bytes, append: bool):
        super().__init__(data)
        if append:
            self.seek(0, io.SEEK_END)
        self._fs = fs
        self._path = path

    def flush(self) -> None:
        super().flush()
        # store only while the entry still exists: a handle left open
        # across remove()/rmtree() must not resurrect the file when it is
        # eventually flushed or GC-closed (BytesIO.__del__ calls close →
        # flush) — POSIX writes to an unlinked file vanish with the inode.
        # Without this, an abandoned writer handle (e.g. a fault-injected
        # SnapshotWriter kept alive by the exception traceback) re-created
        # its file AFTER the snapshot temp-dir cleanup had removed it.
        self._fs._store_if_tracked(self._path, self.getvalue())

    def close(self) -> None:
        if not self.closed:
            self.flush()
        super().close()

    def fileno(self) -> int:  # keep os.fsync() off memfs handles
        raise io.UnsupportedOperation("memfs file has no fd")


class MemFS(IFS):
    """In-memory filesystem (reference ``internal/vfs/memfs.go``)."""

    def __init__(self) -> None:
        self._files: Dict[str, bytes] = {}
        self._dirs = {"/"}
        self._mu = threading.RLock()

    @staticmethod
    def _norm(path: str) -> str:
        return os.path.normpath(path)

    def _store(self, path: str, data: bytes) -> None:
        with self._mu:
            self._files[self._norm(path)] = bytes(data)

    def _store_if_tracked(self, path: str, data: bytes) -> None:
        """Flush-path store: a no-op once the entry was removed (the
        unlinked-inode semantics _MemFile.flush relies on).  ``open``
        registers the entry up front, so live handles always store."""
        path = self._norm(path)
        with self._mu:
            if path in self._files:
                self._files[path] = bytes(data)

    def open(self, path: str, mode: str):
        path = self._norm(path)
        with self._mu:
            if "r" in mode and "+" not in mode:
                if path not in self._files:
                    raise FileNotFoundError(path)
                f = io.BytesIO(self._files[path])
                return f
            existing = self._files.get(path, b"")
            if "w" in mode:
                existing = b""
            parent = os.path.dirname(path)
            if parent and parent not in self._dirs:
                raise FileNotFoundError(f"no directory {parent}")
            mf = _MemFile(self, path, existing, append="a" in mode)
            self._files.setdefault(path, existing)
            return mf

    def remove(self, path: str) -> None:
        path = self._norm(path)
        with self._mu:
            if path not in self._files:
                raise FileNotFoundError(path)
            del self._files[path]

    def replace(self, src: str, dst: str) -> None:
        src, dst = self._norm(src), self._norm(dst)
        with self._mu:
            if src in self._dirs:  # directory rename moves the subtree
                prefix = src + os.sep
                self._files = {
                    (dst + k[len(src) :] if k.startswith(prefix) else k): v
                    for k, v in self._files.items()
                }
                self._dirs = {
                    (dst + d[len(src) :] if d == src or d.startswith(prefix) else d)
                    for d in self._dirs
                }
                return
            if src not in self._files:
                raise FileNotFoundError(src)
            self._files[dst] = self._files.pop(src)

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        path = self._norm(path)
        with self._mu:
            if path in self._dirs and not exist_ok:
                raise FileExistsError(path)
            parts = path.split(os.sep)
            cur = "" if not path.startswith(os.sep) else os.sep
            for p in parts:
                if not p:
                    continue
                cur = os.path.join(cur, p) if cur else p
                self._dirs.add(cur)

    def rmdir(self, path: str) -> None:
        path = self._norm(path)
        with self._mu:
            if self.listdir(path):
                raise OSError(f"directory not empty: {path}")
            self._dirs.discard(path)

    def rmtree(self, path: str) -> None:
        path = self._norm(path)
        prefix = path + os.sep
        with self._mu:
            self._files = {
                k: v for k, v in self._files.items() if not k.startswith(prefix)
            }
            self._dirs = {
                d for d in self._dirs if d != path and not d.startswith(prefix)
            }

    def listdir(self, path: str) -> List[str]:
        path = self._norm(path)
        with self._mu:
            if path not in self._dirs:
                raise FileNotFoundError(path)
            prefix = path + os.sep
            out = set()
            for k in list(self._files) + list(self._dirs):
                if k.startswith(prefix):
                    rest = k[len(prefix) :]
                    out.add(rest.split(os.sep)[0])
            return sorted(out)

    def exists(self, path: str) -> bool:
        path = self._norm(path)
        with self._mu:
            return path in self._files or path in self._dirs

    def isdir(self, path: str) -> bool:
        with self._mu:
            return self._norm(path) in self._dirs

    def getsize(self, path: str) -> int:
        path = self._norm(path)
        with self._mu:
            if path not in self._files:
                raise FileNotFoundError(path)
            return len(self._files[path])

    def fsync(self, f) -> None:
        f.flush()

    def fsync_dir(self, path: str) -> None:
        pass


class Injector:
    """Decides which operations fail (reference ``error.go`` ``Injector``).

    ``policy(op, path) -> bool`` returns True to inject.  Helpers build the
    common shapes: fail every op matching a substring, or start failing
    after N matching ops (to hit the middle of a multi-write sequence).
    """

    def __init__(self, policy: Callable[[str, str], bool]):
        self._policy = policy
        self.injected = 0

    def maybe_fail(self, op: str, path: str) -> None:
        if self._policy(op, path):
            self.injected += 1
            raise OSError(f"injected error: {op} {path}")

    @classmethod
    def on_path(cls, substr: str, ops: Optional[set] = None) -> "Injector":
        return cls(
            lambda op, path: substr in path and (ops is None or op in ops)
        )

    @classmethod
    def after_n(
        cls, n: int, ops: Optional[set] = None, substr: str = ""
    ) -> "Injector":
        count = [0]

        def policy(op: str, path: str) -> bool:
            if (ops is None or op in ops) and substr in path:
                count[0] += 1
                return count[0] > n
            return False

        return cls(policy)


class _ErrorFile:
    """Wraps a file handle so write/fsync go through the injector."""

    def __init__(self, efs: "ErrorFS", path: str, f):
        self._efs = efs
        self._path = path
        self._f = f

    def write(self, data):
        self._efs.injector.maybe_fail("write", self._path)
        return self._f.write(data)

    def read(self, *a):
        self._efs.injector.maybe_fail("read", self._path)
        return self._f.read(*a)

    def __getattr__(self, name):
        return getattr(self._f, name)

    # dunder lookups bypass __getattr__ (type-level resolution), so the
    # context-manager protocol must be explicit — without it every
    # `with fs.open(...)` in the snapshot path fails under ErrorFS,
    # which silently exempted that whole path from fault injection
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()  # through the wrapper: keeps close injectable
        return False


class ErrorFS(IFS):
    """FS wrapper injecting errors per an :class:`Injector`."""

    def __init__(self, fs: IFS, injector: Injector):
        self.fs = fs
        self.injector = injector

    def open(self, path: str, mode: str):
        self.injector.maybe_fail("open", path)
        return _ErrorFile(self, path, self.fs.open(path, mode))

    def remove(self, path: str) -> None:
        self.injector.maybe_fail("remove", path)
        self.fs.remove(path)

    def replace(self, src: str, dst: str) -> None:
        self.injector.maybe_fail("replace", dst)
        self.fs.replace(src, dst)

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        self.injector.maybe_fail("makedirs", path)
        self.fs.makedirs(path, exist_ok=exist_ok)

    def rmdir(self, path: str) -> None:
        self.injector.maybe_fail("rmdir", path)
        self.fs.rmdir(path)

    def rmtree(self, path: str) -> None:
        self.injector.maybe_fail("rmtree", path)
        self.fs.rmtree(path)

    def listdir(self, path: str) -> List[str]:
        self.injector.maybe_fail("listdir", path)
        return self.fs.listdir(path)

    def exists(self, path: str) -> bool:
        return self.fs.exists(path)

    def isdir(self, path: str) -> bool:
        return self.fs.isdir(path)

    def getsize(self, path: str) -> int:
        self.injector.maybe_fail("getsize", path)
        return self.fs.getsize(path)

    def fsync(self, f) -> None:
        path = getattr(f, "_path", "")
        self.injector.maybe_fail("fsync", path)
        inner = getattr(f, "_f", f)
        self.fs.fsync(inner)

    def fsync_dir(self, path: str) -> None:
        self.injector.maybe_fail("fsync_dir", path)
        self.fs.fsync_dir(path)


DEFAULT = OSFS()


def is_error_fs(fs: IFS) -> bool:
    """NodeHost auto-detects an ErrorFS to enable engine panic capture
    (reference ``nodehost.go:321-327``)."""
    return isinstance(fs, ErrorFS)
