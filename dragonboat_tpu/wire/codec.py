"""Deterministic binary codec for the wire/state types.

The reference serializes with gogo-protobuf plus hand-optimized marshal paths
(reference ``raftpb/raft_optimized.go``).  Protobuf is not a requirement of the
system — what matters is (a) determinism (same object → same bytes, required
for cross-replica hashes and for the differential scalar-vs-TPU tests), (b)
self-describing framing with integrity checks, and (c) speed for the hot
Entry/Message paths.  We use a compact little-endian format with varint field
packing for the hot types and explicit length prefixes; CRC32 integrity lives
one layer up in the transport framing and the snapshot block format, mirroring
the reference's layering (``internal/transport/tcp.go:57-114``,
``internal/rsm/rw.go``).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

from .types import (
    Bootstrap,
    Chunk,
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
    SnapshotFile,
    State,
    StateMachineType,
)

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")


class CodecError(ValueError):
    pass


def _load_native():
    """CPython extension accelerating the per-field varint plumbing of the
    hot Message/Entry paths (the reference's hand-optimized marshal,
    ``raftpb/raft_optimized.go``, is the analogous native component).
    Built on demand next to the native KV engine; None = pure Python."""
    import importlib.util
    import os
    import subprocess
    import sysconfig
    import tempfile

    d = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
    so = os.path.join(d, "dbtpu_wirecodec.so")
    src = os.path.join(d, "wirecodec.c")
    try:
        # a prebuilt .so without the source present is simply used; the
        # staleness check only applies when both exist
        have_so = os.path.exists(so)
        stale = (
            os.path.exists(src)
            and (not have_so or os.path.getmtime(so) < os.path.getmtime(src))
        )
        if not have_so and not os.path.exists(src):
            return None
        if stale:
            # compile against THIS interpreter's headers, into a temp file
            # promoted atomically — concurrent importers then either see
            # the old .so or the complete new one, never a partial write
            # (build recipe mirrored in native/Makefile for manual builds)
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=d)
            os.close(fd)
            r = subprocess.run(
                [
                    os.environ.get("CC", "cc"), "-O2", "-fPIC", "-shared",
                    f"-I{sysconfig.get_paths()['include']}",
                    "-o", tmp, src,
                ],
                capture_output=True, text=True,
            )
            if r.returncode != 0:
                os.unlink(tmp)
                return None
            os.replace(tmp, so)
        spec = importlib.util.spec_from_file_location("dbtpu_wirecodec", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


_native = _load_native()


def _write_uvarint(buf: bytearray, v: int) -> None:
    if v < 0 or v >= 1 << 64:
        raise CodecError(f"varint out of uint64 range: {v}")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _read_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise CodecError("truncated varint")
        b = data[pos]
        pos += 1
        # values are uint64 exactly: a 10th byte may only contribute one
        # bit, and an 11th byte is always invalid (kept identical to the
        # native decoder so the same wire bytes can never decode
        # differently across implementations)
        if shift == 63 and (b & 0x7F) > 1:
            raise CodecError("varint overflows uint64")
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise CodecError("varint too long")


def _write_bytes(buf: bytearray, b: bytes) -> None:
    _write_uvarint(buf, len(b))
    buf += b


def _read_bytes(data: bytes, pos: int) -> Tuple[bytes, int]:
    n, pos = _read_uvarint(data, pos)
    if pos + n > len(data):
        raise CodecError("truncated bytes field")
    return data[pos : pos + n], pos + n


def _write_str(buf: bytearray, s: str) -> None:
    _write_bytes(buf, s.encode("utf-8"))


def _read_str(data: bytes, pos: int) -> Tuple[str, int]:
    b, pos = _read_bytes(data, pos)
    return b.decode("utf-8"), pos


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------

def encode_entry_into(buf: bytearray, e: Entry) -> None:
    # entries are immutable once term/index are assigned (raft
    # append_entries clears the cache when it assigns them), so the wire
    # bytes are computed once and reused across Replicate fan-out + WAL
    enc = e._enc
    if enc is None:
        if _native is not None:
            tmp = bytearray()
            try:
                _native.encode_entry_fields(
                    tmp, e.term, e.index, int(e.type), e.key, e.client_id,
                    e.series_id, e.responded_to, e.cmd,
                )
            except _native.CodecError as exc:
                raise CodecError(str(exc)) from None
            enc = bytes(tmp)
        else:
            tmp = bytearray()
            _write_uvarint(tmp, e.term)
            _write_uvarint(tmp, e.index)
            _write_uvarint(tmp, int(e.type))
            _write_uvarint(tmp, e.key)
            _write_uvarint(tmp, e.client_id)
            _write_uvarint(tmp, e.series_id)
            _write_uvarint(tmp, e.responded_to)
            _write_bytes(tmp, e.cmd)
            enc = bytes(tmp)
        e._enc = enc
    buf += enc


def decode_entry_from(data: bytes, pos: int) -> Tuple[Entry, int]:
    start = pos
    if _native is not None:
        try:
            (
                term, index, etype, key, client_id, series_id, responded_to,
                cmd_start, cmd_end, pos,
            ) = _native.parse_entry_fields(data, pos)
        except _native.CodecError as exc:
            raise CodecError(str(exc)) from None
        cmd = data[cmd_start:cmd_end]
    else:
        term, pos = _read_uvarint(data, pos)
        index, pos = _read_uvarint(data, pos)
        etype, pos = _read_uvarint(data, pos)
        key, pos = _read_uvarint(data, pos)
        client_id, pos = _read_uvarint(data, pos)
        series_id, pos = _read_uvarint(data, pos)
        responded_to, pos = _read_uvarint(data, pos)
        cmd, pos = _read_bytes(data, pos)
    e = Entry(
        term=term,
        index=index,
        type=EntryType(etype),
        key=key,
        client_id=client_id,
        series_id=series_id,
        responded_to=responded_to,
        cmd=cmd,
    )
    # the wire slice IS the canonical encoding — seed the cache so the
    # follower's WAL write doesn't re-encode
    e._enc = data[start:pos]
    return e, pos


def encode_entry(e: Entry) -> bytes:
    buf = bytearray()
    encode_entry_into(buf, e)
    return bytes(buf)


def decode_entry(data: bytes) -> Entry:
    e, pos = decode_entry_from(data, 0)
    if pos != len(data):
        raise CodecError("trailing garbage after Entry")
    return e


def encode_entry_batch(entries: List[Entry]) -> bytes:
    """Encode an entry batch record (reference ``EntryBatch``,
    ``raftpb/raft.proto:118``)."""
    buf = bytearray()
    _write_uvarint(buf, len(entries))
    for e in entries:
        encode_entry_into(buf, e)
    return bytes(buf)


def decode_entry_batch(data: bytes) -> List[Entry]:
    n, pos = _read_uvarint(data, 0)
    out = []
    for _ in range(n):
        e, pos = decode_entry_from(data, pos)
        out.append(e)
    if pos != len(data):
        raise CodecError("trailing garbage after EntryBatch")
    return out


# ---------------------------------------------------------------------------
# State / Membership / Bootstrap / ConfigChange
# ---------------------------------------------------------------------------

def encode_state(st: State) -> bytes:
    return _U64.pack(st.term) + _U64.pack(st.vote) + _U64.pack(st.commit)


def decode_state(data: bytes) -> State:
    if len(data) != 24:
        raise CodecError("bad State record size")
    return State(
        term=_U64.unpack_from(data, 0)[0],
        vote=_U64.unpack_from(data, 8)[0],
        commit=_U64.unpack_from(data, 16)[0],
    )


def _write_addr_map(buf: bytearray, m: Dict[int, str]) -> None:
    _write_uvarint(buf, len(m))
    for k in sorted(m):  # sorted => deterministic bytes
        _write_uvarint(buf, k)
        _write_str(buf, m[k])


def _read_addr_map(data: bytes, pos: int) -> Tuple[Dict[int, str], int]:
    n, pos = _read_uvarint(data, pos)
    out: Dict[int, str] = {}
    for _ in range(n):
        k, pos = _read_uvarint(data, pos)
        v, pos = _read_str(data, pos)
        out[k] = v
    return out, pos


def encode_membership_into(buf: bytearray, m: Membership) -> None:
    _write_uvarint(buf, m.config_change_id)
    _write_addr_map(buf, m.addresses)
    _write_uvarint(buf, len(m.removed))
    for k in sorted(m.removed):
        _write_uvarint(buf, k)
    _write_addr_map(buf, m.observers)
    _write_addr_map(buf, m.witnesses)


def decode_membership_from(data: bytes, pos: int) -> Tuple[Membership, int]:
    ccid, pos = _read_uvarint(data, pos)
    addresses, pos = _read_addr_map(data, pos)
    nremoved, pos = _read_uvarint(data, pos)
    removed: Dict[int, bool] = {}
    for _ in range(nremoved):
        k, pos = _read_uvarint(data, pos)
        removed[k] = True
    observers, pos = _read_addr_map(data, pos)
    witnesses, pos = _read_addr_map(data, pos)
    return (
        Membership(
            config_change_id=ccid,
            addresses=addresses,
            removed=removed,
            observers=observers,
            witnesses=witnesses,
        ),
        pos,
    )


def encode_membership(m: Membership) -> bytes:
    buf = bytearray()
    encode_membership_into(buf, m)
    return bytes(buf)


def decode_membership(data: bytes) -> Membership:
    m, pos = decode_membership_from(data, 0)
    if pos != len(data):
        raise CodecError("trailing garbage after Membership")
    return m


def encode_bootstrap(b: Bootstrap) -> bytes:
    buf = bytearray()
    _write_addr_map(buf, b.addresses)
    buf.append(1 if b.join else 0)
    _write_uvarint(buf, int(b.type))
    return bytes(buf)


def decode_bootstrap(data: bytes) -> Bootstrap:
    addresses, pos = _read_addr_map(data, 0)
    if pos >= len(data):
        raise CodecError("truncated Bootstrap")
    join = data[pos] == 1
    pos += 1
    smtype, pos = _read_uvarint(data, pos)
    if pos != len(data):
        raise CodecError("trailing garbage after Bootstrap")
    return Bootstrap(addresses=addresses, join=join, type=StateMachineType(smtype))


def encode_config_change(cc: ConfigChange) -> bytes:
    buf = bytearray()
    _write_uvarint(buf, cc.config_change_id)
    _write_uvarint(buf, int(cc.type))
    _write_uvarint(buf, cc.node_id)
    _write_str(buf, cc.address)
    buf.append(1 if cc.initialize else 0)
    return bytes(buf)


def decode_config_change(data: bytes) -> ConfigChange:
    ccid, pos = _read_uvarint(data, 0)
    cctype, pos = _read_uvarint(data, pos)
    node_id, pos = _read_uvarint(data, pos)
    address, pos = _read_str(data, pos)
    if pos >= len(data):
        raise CodecError("truncated ConfigChange")
    initialize = data[pos] == 1
    pos += 1
    if pos != len(data):
        raise CodecError("trailing garbage after ConfigChange")
    return ConfigChange(
        config_change_id=ccid,
        type=ConfigChangeType(cctype),
        node_id=node_id,
        address=address,
        initialize=initialize,
    )


# ---------------------------------------------------------------------------
# Snapshot
# ---------------------------------------------------------------------------

def encode_snapshot_file_into(buf: bytearray, f: SnapshotFile) -> None:
    _write_str(buf, f.filepath)
    _write_uvarint(buf, f.file_size)
    _write_uvarint(buf, f.file_id)
    _write_bytes(buf, f.metadata)


def decode_snapshot_file_from(data: bytes, pos: int) -> Tuple[SnapshotFile, int]:
    filepath, pos = _read_str(data, pos)
    file_size, pos = _read_uvarint(data, pos)
    file_id, pos = _read_uvarint(data, pos)
    metadata, pos = _read_bytes(data, pos)
    return (
        SnapshotFile(
            filepath=filepath, file_size=file_size, file_id=file_id, metadata=metadata
        ),
        pos,
    )


def encode_snapshot_into(buf: bytearray, s: Snapshot) -> None:
    _write_str(buf, s.filepath)
    _write_uvarint(buf, s.file_size)
    _write_uvarint(buf, s.index)
    _write_uvarint(buf, s.term)
    encode_membership_into(buf, s.membership)
    _write_uvarint(buf, len(s.files))
    for f in s.files:
        encode_snapshot_file_into(buf, f)
    _write_bytes(buf, s.checksum)
    flags = (1 if s.dummy else 0) | (2 if s.imported else 0) | (4 if s.witness else 0)
    buf.append(flags)
    _write_uvarint(buf, s.cluster_id)
    _write_uvarint(buf, int(s.type))
    _write_uvarint(buf, s.on_disk_index)


def decode_snapshot_from(data: bytes, pos: int) -> Tuple[Snapshot, int]:
    filepath, pos = _read_str(data, pos)
    file_size, pos = _read_uvarint(data, pos)
    index, pos = _read_uvarint(data, pos)
    term, pos = _read_uvarint(data, pos)
    membership, pos = decode_membership_from(data, pos)
    nfiles, pos = _read_uvarint(data, pos)
    files = []
    for _ in range(nfiles):
        f, pos = decode_snapshot_file_from(data, pos)
        files.append(f)
    checksum, pos = _read_bytes(data, pos)
    if pos >= len(data):
        raise CodecError("truncated Snapshot")
    flags = data[pos]
    pos += 1
    cluster_id, pos = _read_uvarint(data, pos)
    smtype, pos = _read_uvarint(data, pos)
    on_disk_index, pos = _read_uvarint(data, pos)
    return (
        Snapshot(
            filepath=filepath,
            file_size=file_size,
            index=index,
            term=term,
            membership=membership,
            files=files,
            checksum=checksum,
            dummy=bool(flags & 1),
            imported=bool(flags & 2),
            witness=bool(flags & 4),
            cluster_id=cluster_id,
            type=StateMachineType(smtype),
            on_disk_index=on_disk_index,
        ),
        pos,
    )


def encode_snapshot(s: Snapshot) -> bytes:
    buf = bytearray()
    encode_snapshot_into(buf, s)
    return bytes(buf)


def decode_snapshot(data: bytes) -> Snapshot:
    s, pos = decode_snapshot_from(data, 0)
    if pos != len(data):
        raise CodecError("trailing garbage after Snapshot")
    return s


# ---------------------------------------------------------------------------
# Message / MessageBatch
# ---------------------------------------------------------------------------

_MSG_HAS_SNAPSHOT = 1
_MSG_REJECT = 2
_MSG_HAS_TRACE = 4  # replication-trace context appended (ISSUE 14)

# the six ReplTrace wall-clock stamps, in dataclass field order
_TRACE_TS = struct.Struct("<6d")


def _encode_repl_trace_into(buf: bytearray, t) -> None:
    _write_uvarint(buf, t.tid)
    _write_str(buf, t.origin)
    _write_uvarint(buf, t.index)
    buf += _TRACE_TS.pack(
        t.t_send, t.t_recv, t.t_append, t.t_fsync, t.t_ack, t.t_ack_recv
    )


def _decode_repl_trace_from(data: bytes, pos: int):
    from .types import ReplTrace

    tid, pos = _read_uvarint(data, pos)
    origin, pos = _read_str(data, pos)
    index, pos = _read_uvarint(data, pos)
    if pos + _TRACE_TS.size > len(data):
        raise CodecError("truncated ReplTrace")
    ts = _TRACE_TS.unpack_from(data, pos)
    return (
        ReplTrace(
            tid=tid,
            origin=origin,
            index=index,
            t_send=ts[0],
            t_recv=ts[1],
            t_append=ts[2],
            t_fsync=ts[3],
            t_ack=ts[4],
            t_ack_recv=ts[5],
        ),
        pos + _TRACE_TS.size,
    )


def encode_message_into(buf: bytearray, m: Message) -> None:
    flags = 0
    if m.snapshot is not None:
        flags |= _MSG_HAS_SNAPSHOT
    if m.reject:
        flags |= _MSG_REJECT
    if m.trace is not None:
        flags |= _MSG_HAS_TRACE
    if _native is not None:
        try:
            _native.encode_message_header(
                buf, int(m.type), flags, m.to, m.from_, m.cluster_id, m.term,
                m.log_term, m.log_index, m.commit, m.hint, m.hint_high,
                len(m.entries),
            )
        except _native.CodecError as exc:
            raise CodecError(str(exc)) from None
    else:
        _write_uvarint(buf, int(m.type))
        buf.append(flags)
        _write_uvarint(buf, m.to)
        _write_uvarint(buf, m.from_)
        _write_uvarint(buf, m.cluster_id)
        _write_uvarint(buf, m.term)
        _write_uvarint(buf, m.log_term)
        _write_uvarint(buf, m.log_index)
        _write_uvarint(buf, m.commit)
        _write_uvarint(buf, m.hint)
        _write_uvarint(buf, m.hint_high)
        _write_uvarint(buf, len(m.entries))
    for e in m.entries:
        encode_entry_into(buf, e)
    if m.snapshot is not None:
        encode_snapshot_into(buf, m.snapshot)
    if m.trace is not None:
        _encode_repl_trace_into(buf, m.trace)


def decode_message_from(data: bytes, pos: int) -> Tuple[Message, int]:
    if _native is not None:
        try:
            (
                mtype, flags, to, from_, cluster_id, term, log_term,
                log_index, commit, hint, hint_high, nentries, pos,
            ) = _native.parse_message_fields(data, pos)
        except _native.CodecError as exc:
            raise CodecError(str(exc)) from None
    else:
        mtype, pos = _read_uvarint(data, pos)
        if pos >= len(data):
            raise CodecError("truncated Message")
        flags = data[pos]
        pos += 1
        to, pos = _read_uvarint(data, pos)
        from_, pos = _read_uvarint(data, pos)
        cluster_id, pos = _read_uvarint(data, pos)
        term, pos = _read_uvarint(data, pos)
        log_term, pos = _read_uvarint(data, pos)
        log_index, pos = _read_uvarint(data, pos)
        commit, pos = _read_uvarint(data, pos)
        hint, pos = _read_uvarint(data, pos)
        hint_high, pos = _read_uvarint(data, pos)
        nentries, pos = _read_uvarint(data, pos)
    entries = []
    for _ in range(nentries):
        e, pos = decode_entry_from(data, pos)
        entries.append(e)
    snapshot = None
    if flags & _MSG_HAS_SNAPSHOT:
        snapshot, pos = decode_snapshot_from(data, pos)
    trace = None
    if flags & _MSG_HAS_TRACE:
        trace, pos = _decode_repl_trace_from(data, pos)
    return (
        Message(
            type=MessageType(mtype),
            to=to,
            from_=from_,
            cluster_id=cluster_id,
            term=term,
            log_term=log_term,
            log_index=log_index,
            commit=commit,
            reject=bool(flags & _MSG_REJECT),
            hint=hint,
            entries=entries,
            snapshot=snapshot,
            hint_high=hint_high,
            trace=trace,
        ),
        pos,
    )


def encode_message(m: Message) -> bytes:
    buf = bytearray()
    encode_message_into(buf, m)
    return bytes(buf)


def decode_message(data: bytes) -> Message:
    m, pos = decode_message_from(data, 0)
    if pos != len(data):
        raise CodecError("trailing garbage after Message")
    return m


def encode_message_batch(b: MessageBatch) -> bytes:
    buf = bytearray()
    _write_uvarint(buf, b.deployment_id)
    _write_str(buf, b.source_address)
    _write_uvarint(buf, b.bin_ver)
    _write_uvarint(buf, len(b.requests))
    for m in b.requests:
        encode_message_into(buf, m)
    return bytes(buf)


def decode_message_batch(data: bytes) -> MessageBatch:
    deployment_id, pos = _read_uvarint(data, 0)
    source_address, pos = _read_str(data, pos)
    bin_ver, pos = _read_uvarint(data, pos)
    n, pos = _read_uvarint(data, pos)
    requests = []
    for _ in range(n):
        m, pos = decode_message_from(data, pos)
        requests.append(m)
    if pos != len(data):
        raise CodecError("trailing garbage after MessageBatch")
    return MessageBatch(
        requests=requests,
        deployment_id=deployment_id,
        source_address=source_address,
        bin_ver=bin_ver,
    )


# ---------------------------------------------------------------------------
# Chunk
# ---------------------------------------------------------------------------

def encode_chunk(c: Chunk) -> bytes:
    buf = bytearray()
    _write_uvarint(buf, c.cluster_id)
    _write_uvarint(buf, c.node_id)
    _write_uvarint(buf, c.from_)
    _write_uvarint(buf, c.chunk_id)
    _write_uvarint(buf, c.chunk_size)
    _write_uvarint(buf, c.chunk_count)
    _write_bytes(buf, c.data)
    _write_uvarint(buf, c.index)
    _write_uvarint(buf, c.term)
    encode_membership_into(buf, c.membership)
    _write_str(buf, c.filepath)
    _write_uvarint(buf, c.file_size)
    _write_uvarint(buf, c.deployment_id)
    _write_uvarint(buf, c.file_chunk_id)
    _write_uvarint(buf, c.file_chunk_count)
    flags = (1 if c.has_file_info else 0) | (2 if c.witness else 0)
    buf.append(flags)
    encode_snapshot_file_into(buf, c.file_info)
    _write_uvarint(buf, c.bin_ver)
    _write_uvarint(buf, c.on_disk_index)
    return bytes(buf)


def decode_chunk(data: bytes) -> Chunk:
    cluster_id, pos = _read_uvarint(data, 0)
    node_id, pos = _read_uvarint(data, pos)
    from_, pos = _read_uvarint(data, pos)
    chunk_id, pos = _read_uvarint(data, pos)
    chunk_size, pos = _read_uvarint(data, pos)
    chunk_count, pos = _read_uvarint(data, pos)
    chunk_data, pos = _read_bytes(data, pos)
    index, pos = _read_uvarint(data, pos)
    term, pos = _read_uvarint(data, pos)
    membership, pos = decode_membership_from(data, pos)
    filepath, pos = _read_str(data, pos)
    file_size, pos = _read_uvarint(data, pos)
    deployment_id, pos = _read_uvarint(data, pos)
    file_chunk_id, pos = _read_uvarint(data, pos)
    file_chunk_count, pos = _read_uvarint(data, pos)
    if pos >= len(data):
        raise CodecError("truncated Chunk")
    flags = data[pos]
    pos += 1
    file_info, pos = decode_snapshot_file_from(data, pos)
    bin_ver, pos = _read_uvarint(data, pos)
    on_disk_index, pos = _read_uvarint(data, pos)
    if pos != len(data):
        raise CodecError("trailing garbage after Chunk")
    return Chunk(
        cluster_id=cluster_id,
        node_id=node_id,
        from_=from_,
        chunk_id=chunk_id,
        chunk_size=chunk_size,
        chunk_count=chunk_count,
        data=chunk_data,
        index=index,
        term=term,
        membership=membership,
        filepath=filepath,
        file_size=file_size,
        deployment_id=deployment_id,
        file_chunk_id=file_chunk_id,
        file_chunk_count=file_chunk_count,
        has_file_info=bool(flags & 1),
        file_info=file_info,
        bin_ver=bin_ver,
        witness=bool(flags & 2),
        on_disk_index=on_disk_index,
    )
