"""Wire and state types for dragonboat_tpu.

TPU-native re-design of the reference raftpb package (reference:
``raftpb/raft.proto``).  The reference uses gogo-protobuf generated Go structs;
here the wire/state model is a small set of slotted Python dataclasses with a
deterministic hand-rolled binary codec (:mod:`dragonboat_tpu.wire.codec`).
Numeric enum values intentionally match ``raftpb/raft.proto:26-77`` so that the
conformance fixtures and the batched device kernels (which bucket messages by
integer type) agree on one vocabulary.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

NO_NODE = 0
NO_LEADER = 0


class MessageType(enum.IntEnum):
    """Message vocabulary (reference ``raftpb/raft.proto:26-53``)."""

    LOCAL_TICK = 0
    ELECTION = 1
    LEADER_HEARTBEAT = 2
    CONFIG_CHANGE_EVENT = 3
    NOOP = 4
    PING = 5
    PONG = 6
    PROPOSE = 7
    SNAPSHOT_STATUS = 8
    UNREACHABLE = 9
    CHECK_QUORUM = 10
    BATCHED_READ_INDEX = 11
    REPLICATE = 12
    REPLICATE_RESP = 13
    REQUEST_VOTE = 14
    REQUEST_VOTE_RESP = 15
    INSTALL_SNAPSHOT = 16
    HEARTBEAT = 17
    HEARTBEAT_RESP = 18
    READ_INDEX = 19
    READ_INDEX_RESP = 20
    QUIESCE = 21
    SNAPSHOT_RECEIVED = 22
    LEADER_TRANSFER = 23
    TIMEOUT_NOW = 24
    RATE_LIMIT = 25


NUM_MESSAGE_TYPES = 26


class EntryType(enum.IntEnum):
    """Entry payload kinds (reference ``raftpb/raft.proto:55-60``)."""

    APPLICATION = 0
    CONFIG_CHANGE = 1
    ENCODED = 2
    METADATA = 3


class ConfigChangeType(enum.IntEnum):
    """Membership change kinds (reference ``raftpb/raft.proto:62-67``)."""

    ADD_NODE = 0
    REMOVE_NODE = 1
    ADD_OBSERVER = 2
    ADD_WITNESS = 3


class StateMachineType(enum.IntEnum):
    """User state machine kinds (reference ``raftpb/raft.proto:69-74``)."""

    UNKNOWN = 0
    REGULAR = 1
    CONCURRENT = 2
    ON_DISK = 3


class CompressionType(enum.IntEnum):
    NO_COMPRESSION = 0
    SNAPPY = 1


class ChecksumType(enum.IntEnum):
    CRC32IEEE = 0
    HIGHWAY = 1


@dataclass(slots=True)
class Entry:
    """A raft log entry (reference ``raftpb/raft.proto:106-116``)."""

    term: int = 0
    index: int = 0
    type: EntryType = EntryType.APPLICATION
    key: int = 0
    client_id: int = 0
    series_id: int = 0
    responded_to: int = 0
    cmd: bytes = b""
    # cached wire encoding (codec.encode_entry_into).  An entry is encoded
    # up to 3× on the leader (one Replicate per follower + the WAL record)
    # and once more on each follower; the bytes are identical every time.
    # Populated lazily by the codec, pre-populated from the wire slice on
    # decode, and cleared by raft.append_entries when term/index are
    # assigned.  Excluded from init/compare/repr — it is not part of the
    # value.
    _enc: Optional[bytes] = field(default=None, init=False, repr=False, compare=False)

    def is_config_change(self) -> bool:
        return self.type == EntryType.CONFIG_CHANGE

    def is_noop_session(self) -> bool:
        return self.client_id == NOOP_CLIENT_ID

    def is_new_session_request(self) -> bool:
        return self.series_id == SERIES_ID_FOR_REGISTER

    def is_end_of_session_request(self) -> bool:
        return self.series_id == SERIES_ID_FOR_UNREGISTER

    def is_session_managed(self) -> bool:
        return not self.is_noop_session()

    def is_empty(self) -> bool:
        return (
            not self.is_config_change()
            and len(self.cmd) == 0
            and self.client_id == NOOP_CLIENT_ID
            and self.series_id == NOOP_SERIES_ID
        )

    def is_update(self) -> bool:
        return not self.is_config_change() and len(self.cmd) > 0

    def size(self) -> int:
        """Approximate in-memory footprint, used by rate limiting."""
        return len(self.cmd) + 64

    def clone(self) -> "Entry":
        return replace(self)


# client/session sentinels (reference client/session.go:23-41)
NOOP_CLIENT_ID = 0
NOOP_SERIES_ID = 0
SERIES_ID_FOR_REGISTER = 0
SERIES_ID_FOR_UNREGISTER = 2**64 - 1
SERIES_ID_FIRST_PROPOSAL = 1


@dataclass(slots=True)
class State:
    """Persistent raft state (reference ``raftpb/raft.proto:100-104``)."""

    term: int = 0
    vote: int = 0
    commit: int = 0

    def is_empty(self) -> bool:
        return self.term == 0 and self.vote == 0 and self.commit == 0


@dataclass(slots=True)
class Membership:
    """Applied membership view (reference ``raftpb/raft.proto:120-126``)."""

    config_change_id: int = 0
    addresses: Dict[int, str] = field(default_factory=dict)
    removed: Dict[int, bool] = field(default_factory=dict)
    observers: Dict[int, str] = field(default_factory=dict)
    witnesses: Dict[int, str] = field(default_factory=dict)

    def clone(self) -> "Membership":
        return Membership(
            config_change_id=self.config_change_id,
            addresses=dict(self.addresses),
            removed=dict(self.removed),
            observers=dict(self.observers),
            witnesses=dict(self.witnesses),
        )


@dataclass(slots=True)
class SnapshotFile:
    """External file attached to a snapshot (``raftpb/raft.proto:129-134``)."""

    filepath: str = ""
    file_size: int = 0
    file_id: int = 0
    metadata: bytes = b""


@dataclass(slots=True)
class Snapshot:
    """Snapshot metadata record (reference ``raftpb/raft.proto:137-152``)."""

    filepath: str = ""
    file_size: int = 0
    index: int = 0
    term: int = 0
    membership: Membership = field(default_factory=Membership)
    files: List[SnapshotFile] = field(default_factory=list)
    checksum: bytes = b""
    dummy: bool = False
    cluster_id: int = 0
    type: StateMachineType = StateMachineType.UNKNOWN
    imported: bool = False
    on_disk_index: int = 0
    witness: bool = False

    def is_empty(self) -> bool:
        return self.index == 0 and self.term == 0


@dataclass(slots=True)
class SystemCtx:
    """128-bit ReadIndex correlation id (reference ``raftpb/raft.go``)."""

    low: int = 0
    high: int = 0

    def __hash__(self) -> int:  # usable as a dict key like the Go struct
        return hash((self.low, self.high))

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, SystemCtx)
            and self.low == other.low
            and self.high == other.high
        )

    def is_empty(self) -> bool:
        return self.low == 0 and self.high == 0


@dataclass(slots=True)
class ReadyToRead:
    """A confirmed ReadIndex result handed back to the runtime."""

    index: int = 0
    system_ctx: SystemCtx = field(default_factory=SystemCtx)
    # True when served locally under a leader lease (ISSUE 10) with no
    # confirmation round — in-process only (never wire-encoded); the
    # request tracer uses it to stamp "lease_read" vs "read_confirm"
    lease: bool = False


@dataclass(slots=True)
class ReplTrace:
    """Compact replication-trace context riding a sampled REPLICATE and
    its REPLICATE_RESP across the transport boundary (ISSUE 14).

    Carried only when the leader's request tracer sampled the proposal
    the message replicates — every other message keeps ``Message.trace``
    at ``None`` and its wire encoding bit-identical to the pre-trace
    build (the ``trace=None`` latch, asserted structurally in
    tests/test_repltrace.py).

    Timestamps are ``time.time()`` wall-clock **in the stamping host's
    own clock**: ``t_send``/``t_ack_recv`` tick on the leader,
    ``t_recv``/``t_append``/``t_fsync``/``t_ack`` on the follower.  The
    leader's attribution plane (obs/replattr.py) reconciles the two
    clocks with the NTP-style ack-pair estimate
    ``offset = ((t_recv - t_send) + (t_ack - t_ack_recv)) / 2``, which
    makes the five stage deltas sum to the measured RTT exactly.
    """

    tid: int = 0          # leader trace id (the sampled proposal's)
    origin: str = ""      # leader host raft address (multi-host merge key)
    index: int = 0        # traced entry index this context attributes
    t_send: float = 0.0   # leader: REPLICATE handed to the transport
    t_recv: float = 0.0   # follower: message reached the inbound router
    t_append: float = 0.0  # follower: raft step appended the entries
    t_fsync: float = 0.0  # follower: WAL made the entries durable
    t_ack: float = 0.0    # follower: RESP handed to the transport
    t_ack_recv: float = 0.0  # leader: RESP reached the inbound router

    def clone(self) -> "ReplTrace":
        return replace(self)


@dataclass(slots=True)
class Message:
    """Raft protocol message (reference ``raftpb/raft.proto:155-169``)."""

    type: MessageType = MessageType.NOOP
    to: int = 0
    from_: int = 0
    cluster_id: int = 0
    term: int = 0
    log_term: int = 0
    log_index: int = 0
    commit: int = 0
    reject: bool = False
    hint: int = 0
    entries: List[Entry] = field(default_factory=list)
    snapshot: Optional[Snapshot] = None
    hint_high: int = 0
    # replication-trace context (ISSUE 14): None for every non-sampled
    # message — the wire codec emits NOTHING for None (no flag bit, no
    # payload), so the trace-off encoding stays bit-identical
    trace: Optional[ReplTrace] = None


@dataclass(slots=True)
class ConfigChange:
    """Proposed membership change (reference ``raftpb/raft.proto:171-177``)."""

    config_change_id: int = 0
    type: ConfigChangeType = ConfigChangeType.ADD_NODE
    node_id: int = 0
    address: str = ""
    initialize: bool = False


@dataclass(slots=True)
class Bootstrap:
    """Initial membership record (reference ``raftpb/raft.proto:79-84``)."""

    addresses: Dict[int, str] = field(default_factory=dict)
    join: bool = False
    type: StateMachineType = StateMachineType.UNKNOWN

    def validate(self) -> bool:
        # reference raftpb/raft.go Bootstrap.Validate: either joining an
        # existing group or carrying a non-empty initial membership.
        return self.join or len(self.addresses) > 0


@dataclass(slots=True)
class MessageBatch:
    """A batch of messages moving between two hosts (``raft.proto:199-204``)."""

    requests: List[Message] = field(default_factory=list)
    deployment_id: int = 0
    source_address: str = ""
    bin_ver: int = 0


@dataclass(slots=True)
class Chunk:
    """One chunk of a streamed snapshot (reference ``raft.proto:207-228``)."""

    cluster_id: int = 0
    node_id: int = 0
    from_: int = 0
    chunk_id: int = 0
    chunk_size: int = 0
    chunk_count: int = 0
    data: bytes = b""
    index: int = 0
    term: int = 0
    membership: Membership = field(default_factory=Membership)
    filepath: str = ""
    file_size: int = 0
    deployment_id: int = 0
    file_chunk_id: int = 0
    file_chunk_count: int = 0
    has_file_info: bool = False
    file_info: SnapshotFile = field(default_factory=SnapshotFile)
    bin_ver: int = 0
    on_disk_index: int = 0
    witness: bool = False

    def is_last_chunk(self) -> bool:
        # streamed transfers don't know the total count upfront: the final
        # chunk carries the LAST_CHUNK_COUNT sentinel instead (reference
        # raftpb/raft.go LastChunkCount)
        return (
            self.chunk_id + 1 == self.chunk_count
            or self.chunk_count == LAST_CHUNK_COUNT
        )

    def is_last_file_chunk(self) -> bool:
        return self.file_chunk_id + 1 == self.file_chunk_count

    def is_poison(self) -> bool:
        return self.chunk_count == POISON_CHUNK_COUNT


# chunk_count sentinel values (reference raftpb/raft.go LastChunkCount etc.)
LAST_CHUNK_COUNT = 2**64 - 1
POISON_CHUNK_COUNT = 2**64 - 2


@dataclass(slots=True)
class UpdateCommit:
    """Progress acknowledgement applied back into the raft log after the
    runtime has processed an :class:`Update` (reference ``raftpb/raft.go``
    ``UpdateCommit``)."""

    processed: int = 0
    last_applied: int = 0
    stable_log_to: int = 0
    stable_log_term: int = 0
    stable_snapshot_to: int = 0
    ready_to_read: int = 0


@dataclass(slots=True)
class Update:
    """Everything a raft step produced that the runtime must act on
    (reference ``raftpb/raft.go`` ``Update``)."""

    cluster_id: int = 0
    node_id: int = 0
    state: State = field(default_factory=State)
    entries_to_save: List[Entry] = field(default_factory=list)
    committed_entries: List[Entry] = field(default_factory=list)
    snapshot: Optional[Snapshot] = None
    ready_to_reads: List[ReadyToRead] = field(default_factory=list)
    messages: List[Message] = field(default_factory=list)
    last_applied: int = 0
    more_committed_entries: bool = False
    fast_apply: bool = False
    update_commit: UpdateCommit = field(default_factory=UpdateCommit)
    dropped_entries: List[Entry] = field(default_factory=list)
    dropped_read_indexes: List[SystemCtx] = field(default_factory=list)

    def has_update(self) -> bool:
        return (
            not self.state.is_empty()
            or len(self.entries_to_save) > 0
            or len(self.committed_entries) > 0
            or len(self.messages) > 0
            or len(self.ready_to_reads) > 0
            or (self.snapshot is not None and not self.snapshot.is_empty())
            or len(self.dropped_entries) > 0
            or len(self.dropped_read_indexes) > 0
        )


def is_empty_state(st: State) -> bool:
    return st.is_empty()


def is_empty_snapshot(ss: Optional[Snapshot]) -> bool:
    return ss is None or ss.is_empty()


def is_state_equal(a: State, b: State) -> bool:
    return a.term == b.term and a.vote == b.vote and a.commit == b.commit


def entries_size(entries: List[Entry]) -> int:
    return sum(e.size() for e in entries)


def config_change_from_entry(e: Entry) -> "ConfigChange":
    from .codec import decode_config_change

    return decode_config_change(e.cmd)
