"""Hello-world: one replicated KV group across three in-process NodeHosts.

The reference's ``examples/helloworld`` starts one NodeHost per process;
for a copy-paste-runnable single file this uses three NodeHosts in one
process over the in-memory chan transport (the same shape the test suite
and the reference's memfs build use).  See ``examples/multigroup.py`` for
the multi-process TCP + native-fast-lane deployment shape.

Run:  python examples/helloworld.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonboat_tpu import Config, IStateMachine, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.transport import ChanRouter, ChanTransport


class KVStore(IStateMachine):
    """The user state machine: commands are ``key=value`` bytes."""

    def __init__(self, cluster_id, node_id):
        self.kv = {}
        self.applied = 0

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        self.applied += 1
        return Result(value=self.applied)

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = repr(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import ast

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(ast.literal_eval(r.read(n).decode()))

    def close(self):
        pass


def main():
    router = ChanRouter()  # in-memory wire between the three hosts
    addrs = {1: "hello1:1", 2: "hello2:1", 3: "hello3:1"}
    nhs = []
    for node_id, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=20,
            raft_address=addr,
            raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                src, rh, ch, router=router
            ),
        ))
        nh.start_cluster(
            addrs, False, KVStore,
            Config(cluster_id=128, node_id=node_id,
                   election_rtt=10, heartbeat_rtt=1, snapshot_entries=100),
        )
        nhs.append(nh)

    # wait for an election, then find the leader
    leader = None
    deadline = time.time() + 60
    while leader is None:
        if time.time() > deadline:
            raise SystemExit("no leader elected within 60s")
        for nh in nhs:
            leader_id, ok = nh.get_leader_id(128)
            if ok:
                leader = nhs[leader_id - 1]
                break
        time.sleep(0.05)
    print(f"leader elected: replica {leader.get_leader_id(128)[0]}")

    # replicated writes (a no-op session: at-least-once; see the session
    # API in docs/getting-started.md for exactly-once)
    session = leader.get_noop_session(128)
    for i in range(10):
        result = leader.sync_propose(session, f"key{i}=value{i}".encode(),
                                     timeout=10.0)
        print(f"applied #{result.value}: key{i}")

    # linearizable read through any replica (ReadIndex protocol)
    for nh in nhs:
        assert nh.sync_read(128, "key9", timeout=10.0) == "value9"
    print("linearizable read from all 3 replicas: key9=value9")

    for nh in nhs:
        nh.stop()
    print("done")


if __name__ == "__main__":
    main()
