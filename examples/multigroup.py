"""Multi-group deployment shape: many raft groups, real TCP, the native
fast lane, and (optionally) the batched device quorum engine.

This is the production shape of this framework (one process per
NodeHost; run three copies with RANK=0/1/2, or let this script fork all
three).  Each group's steady-state data plane runs in C++ once enrolled
(``ExpertConfig.fast_lane``); the device engine (``quorum_engine="tpu"``)
tallies elections/commits for everything else in one fused dispatch per
tick across ALL groups.

Run:  python examples/multigroup.py            (forks 3 local ranks)
      GROUPS=256 ENGINE=tpu python examples/multigroup.py
"""
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GROUPS = int(os.environ.get("GROUPS", "64"))
ENGINE = os.environ.get("ENGINE", "scalar")


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.n = 0

    def update(self, cmd):
        from dragonboat_tpu import Result

        self.n += 1
        return Result(value=self.n)

    def lookup(self, query):
        return self.n

    def save_snapshot(self, w, files, done):
        w.write(self.n.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.n = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def rank_main(rank: int, ports: list, base_dir: str) -> None:
    from dragonboat_tpu import Config, NodeHostConfig, hostplatform
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.nodehost import NodeHost

    if ENGINE == "tpu":
        hostplatform.force_cpu()  # demo: don't require a TPU

    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nh = NodeHost(NodeHostConfig(
        node_host_dir=os.path.join(base_dir, f"nh{rank}"),
        rtt_millisecond=100,
        raft_address=addrs[rank + 1],
        expert=ExpertConfig(
            quorum_engine=ENGINE if rank == 0 else "scalar",
            engine_block_groups=max(GROUPS, 64),
            fast_lane=True,
            fast_lane_commit_window_ms=4.0,
        ),
    ))
    cids = list(range(1, GROUPS + 1))
    for cid in cids:
        nh.start_cluster(addrs, False, CounterSM, Config(
            cluster_id=cid, node_id=rank + 1,
            election_rtt=20, heartbeat_rtt=1, snapshot_entries=10_000,
        ))
    # deterministic spread: rank (cid % 3) campaigns its share
    mine = [cid for cid in cids if cid % 3 == rank]
    for cid in mine:
        nh.get_node(cid).request_campaign()
    led = set()
    deadline = time.time() + 120
    while len(led) < len(mine) and time.time() < deadline:
        led = {c for c in mine if nh.get_node(c).is_leader()}
        time.sleep(0.1)
    print(f"rank{rank}: leading {len(led)}/{len(mine)} groups", flush=True)

    # drive writes on the groups this rank leads
    from dragonboat_tpu.requests import RequestError

    t0 = time.time()
    done = 0
    sessions = {c: nh.get_noop_session(c) for c in led}
    while time.time() - t0 < 10:
        for c in led:
            try:
                nh.sync_propose(sessions[c], b"x", timeout=15.0)
                done += 1
            except RequestError:
                pass  # leadership moved (another rank adopted the group)
    enrolled = sum(1 for c in led if nh.get_node(c).fast_lane)
    print(
        f"rank{rank}: {done} writes in {time.time()-t0:.1f}s "
        f"({done/(time.time()-t0):.0f} w/s serial-per-group), "
        f"{enrolled}/{len(led)} led groups enrolled in the native lane",
        flush=True,
    )
    time.sleep(2)  # let peers finish before tearing down quorum
    nh.stop()


def main():
    if "RANK" in os.environ:
        rank_main(
            int(os.environ["RANK"]),
            [int(p) for p in os.environ["PORTS"].split(",")],
            os.environ["BASE_DIR"],
        )
        return
    socks, ports = [], []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    base = tempfile.mkdtemp(prefix="dbtpu-example-")
    env = dict(os.environ, PORTS=",".join(map(str, ports)), BASE_DIR=base)
    children = [
        subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                         env=dict(env, RANK=str(r)))
        for r in range(3)
    ]
    rc = max(c.wait() for c in children)
    sys.exit(rc)


if __name__ == "__main__":
    main()
