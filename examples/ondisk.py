"""On-disk state machine example (reference ``examples/ondisk``).

An ``IOnDiskStateMachine`` owns its own durable store and tells raft, at
``open()``, the index it has already applied — raft then replays only the
tail.  Snapshots ship just a point-in-time image for slow followers; the
SM's own files are its checkpoint.

Run:  python examples/ondisk.py
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.transport import ChanRouter, ChanTransport


class DiskKV:
    """A (deliberately simple) durable KV: one JSON file, rewritten on
    sync().  A real implementation would use the native KV engine or any
    embedded store."""

    STATE_DIR = tempfile.mkdtemp(prefix="dbtpu-ondisk-")  # fresh per run

    def __init__(self, cluster_id, node_id):
        self.path = os.path.join(
            self.STATE_DIR, f"sm-{cluster_id}-{node_id}.json"
        )
        self.kv = {}
        self.applied_index = 0

    def open(self, stopc):
        if os.path.exists(self.path):
            with open(self.path) as f:
                state = json.load(f)
            self.kv = state["kv"]
            self.applied_index = state["applied"]
        return self.applied_index  # raft replays from here

    def update(self, entries):
        for e in entries:
            k, v = e.cmd.decode().split("=", 1)
            self.kv[k] = v
            self.applied_index = e.index
            e.result = Result(value=e.index)
        return entries

    def sync(self):
        with open(self.path + ".tmp", "w") as f:
            json.dump({"kv": self.kv, "applied": self.applied_index}, f)
        os.replace(self.path + ".tmp", self.path)

    def lookup(self, query):
        return self.kv.get(query)

    def prepare_snapshot(self):
        return dict(self.kv)  # point-in-time view

    def save_snapshot(self, ctx, w, done):
        data = json.dumps(ctx).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, done):
        n = int.from_bytes(r.read(8), "little")
        self.kv = json.loads(r.read(n).decode())

    def close(self):
        pass


def main():
    router = ChanRouter()
    addr = "ondisk1:1"
    nh = NodeHost(NodeHostConfig(
        node_host_dir=":memory:", rtt_millisecond=20, raft_address=addr,
        raft_rpc_factory=lambda s, rh, ch: ChanTransport(
            s, rh, ch, router=router
        ),
    ))
    nh.start_on_disk_cluster(
        {1: addr}, False, DiskKV,
        Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=1),
    )
    while not nh.get_leader_id(1)[1]:
        time.sleep(0.05)
    s = nh.get_noop_session(1)
    for i in range(5):
        nh.sync_propose(s, f"disk{i}=v{i}".encode(), timeout=10.0)
    print("applied 5 writes; value of disk4:",
          nh.sync_read(1, "disk4", timeout=10.0))
    nh.stop()


if __name__ == "__main__":
    main()
