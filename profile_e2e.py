"""Sampling profiler for the e2e NodeHost hot path (VERDICT r2 item 2).

cProfile is per-thread and the runtime's work happens on step/apply/sender
worker threads, so this uses a wall-clock sampler over
``sys._current_frames()``: every ``interval`` seconds it records the
innermost N frames of every live thread and aggregates inclusive sample
counts per function.  GIL-serialized Python work shows up in proportion to
the time it holds the interpreter, which is exactly the budget we are
spending (reference perf bar: BASELINE.md).

Run:  python profile_e2e.py [groups] [duration_s]
Emits a sorted report to stdout and PROFILE_e2e.txt.
"""
from __future__ import annotations

import collections
import os
import sys
import threading
import time


class Sampler:
    def __init__(self, interval: float = 0.002, depth: int = 40):
        self.interval = interval
        self.depth = depth
        self.inclusive = collections.Counter()  # func -> samples anywhere on stack
        self.leaf = collections.Counter()  # func -> samples as innermost frame
        self.samples = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._main, daemon=True)
        self._me = threading.get_ident()

    def _main(self) -> None:
        while not self._stop.is_set():
            frames = sys._current_frames()
            self.samples += 1
            for tid, frame in frames.items():
                if tid == self._me:
                    continue
                seen = set()
                f = frame
                depth = 0
                is_leaf = True
                while f is not None and depth < self.depth:
                    code = f.f_code
                    key = f"{code.co_filename.split('/')[-1]}:{code.co_firstlineno}:{code.co_name}"
                    if is_leaf:
                        self.leaf[key] += 1
                        is_leaf = False
                    if key not in seen:
                        self.inclusive[key] += 1
                        seen.add(key)
                    f = f.f_back
                    depth += 1
            time.sleep(self.interval)

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)

    def report(self, top: int = 40) -> str:
        lines = [f"samples: {self.samples} (interval {self.interval*1e3:.1f}ms)"]
        lines.append("\n== leaf (time spent IN the function) ==")
        for k, v in self.leaf.most_common(top):
            lines.append(f"{v:7d}  {k}")
        lines.append("\n== inclusive (function anywhere on stack) ==")
        for k, v in self.inclusive.most_common(top):
            lines.append(f"{v:7d}  {k}")
        return "\n".join(lines)


def main() -> None:
    groups = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    duration = float(sys.argv[2]) if len(sys.argv) > 2 else 8.0
    os.environ.setdefault("E2E_GROUPS", str(groups))
    os.environ.setdefault("E2E_DURATION", str(duration))
    os.environ.setdefault("E2E_ENGINE", "scalar")
    # the sampler only sees THIS process — force the single-process bench
    # (for multiprocess profiles use E2E_PROFILE_DIR, sampled per rank)
    os.environ.setdefault("E2E_PROCS", "1")
    import bench_e2e

    bench_e2e._force_cpu_for_engine()
    s = Sampler()
    s.start()
    res = bench_e2e.run_quick()
    s.stop()
    rep = s.report()
    rep += (
        f"\n\nwrites_per_sec={res['writes_per_sec']}"
        f" commit_latency_ms={res['commit_latency_ms']}"
    )
    print(rep)
    with open("PROFILE_e2e.txt", "w") as f:
        f.write(rep + "\n")


if __name__ == "__main__":
    main()
