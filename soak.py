"""Soak driver — the Drummer analog (reference ``docs/test.md:6-36``).

Opt-in, minutes-long chaos soak over the REAL deployment shape: three
NodeHost processes on framed TCP with durable native storage and the fast
lane on, G Raft groups replicated across all three.  For N minutes the
parent repeatedly ``kill -9``s a random rank and restarts it against the
same data dirs (WAL replay + snapshot catch-up), while every rank runs
continuous client load.  Aggressive snapshot settings keep snapshot
save/compact/stream churning throughout.

Verification, continuously and at the end:

- **cross-replica state hashes** (reference ``monkey.go:110-144``): at
  every converge window the parent pauses load, waits for equal applied
  indices on every live rank, and compares per-group state hashes;
- **linearizability** (reference Jepsen/Knossos role): every rank records
  an invoke/response history of puts and linearizable reads on per-group
  shared keys (wall-clock timestamps — one box); the parent merges all
  histories and runs ``linearizability.check_linearizable`` per key;
- **fast-lane invariants**: dropped apply spans must be 0 on every rank.

On failure the run's artifacts (per-rank histories, rank stderr logs, the
failure report) are preserved in the run directory and its path printed.

Usage::

    python soak.py --minutes 10 --groups 16        # the make soak target
    python soak.py --minutes 1 --groups 8          # quick smoke

**Churn mode** (``--churn``, ISSUE 17 — the BlackWater soak): four hosts
(three voters + a standby host carrying observers), ≥100 groups with
witness-heavy quorums, check-quorum + lease groups, and a seeded round
schedule of leader-flap storms, netsplits, SIGSTOP freezes, kill -9
restarts and membership recycles.  The health detectors run on every
host in BOTH arms; ``--recover`` additionally turns on the closed-loop
recovery plane (``NodeHostConfig.auto_recover``).  The run is scored by
automated MTTR — per-detector open→close durations merged fleet-wide —
while keeping the base soak's gates: linearizable histories, no
same-applied divergence, zero dropped fast-lane spans.  ``bench_e2e.py
--churn-soak`` runs both arms on the same seed and compares::

    python soak.py --churn --minutes 2 --groups 100 --seed 7            # OFF arm
    python soak.py --churn --minutes 2 --groups 100 --seed 7 --recover  # ON arm

``--hier`` (ISSUE 18) layers the hierarchical commit plane onto churn
mode: hosts 1+2 form domain A, hosts 3+4 domain B, and the netsplit
wave becomes domain-correlated (both B hosts cut at once) — every
commit closed during the hold closed through A's sub-quorum, and the
same linearizability gate scores them.

Exit code 0 = green.  Prints one JSON summary line last.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

# --------------------------------------------------------------------- rank


def _churn_layout(groups):
    """Deterministic group layout for churn mode, shared by the parent
    and every rank (both derive it from ``SOAK_GROUPS`` alone):

    - ``sample``  (cids 1..8): check-quorum voters {1,2,3} plus a
      standing observer (node 4) on the standby host — the groups the
      quorum_at_risk detector watches and the recovery plane repairs
      (evict the dead voter, promote the observer);
    - ``lease``   (cids 1..4): additionally ``read_lease=True`` — lease
      grant/expiry churns with every flap and split;
    - ``flap``    (cids 9..14): the leader-flap storm targets;
    - ``witness`` (cids 16..47, every 4th): witness-heavy quorums —
      voters {1,2} plus witness node 3, one voter loss from stall;
    - everything else: plain 3-voter groups {1,2,3}.
    """
    cids = list(range(1, groups + 1))
    witness = [c for c in cids if 16 <= c <= 47 and c % 4 == 0]
    sample = [c for c in cids if c <= 8]
    lease = [c for c in cids if c <= 4]
    flap = [c for c in cids if 9 <= c <= 14]
    return cids, witness, sample, lease, flap


class _KVSM:
    def __init__(self, cluster_id, node_id):
        self.kv = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        from dragonboat_tpu import Result

        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = json.dumps(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(json.loads(r.read(n).decode()))

    def close(self):
        pass


def rank_main() -> int:
    import faulthandler

    # divergence triage: the parent sends SIGUSR2 before teardown so the
    # rank's stderr log captures every thread's stack at failure time
    faulthandler.register(signal.SIGUSR2, all_threads=True)

    from dragonboat_tpu import Config, NodeHost, NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig

    rank = int(os.environ["SOAK_RANK"])
    groups = int(os.environ["SOAK_GROUPS"])
    threads = int(os.environ.get("SOAK_THREADS", "4"))
    addrs = {
        i + 1: a for i, a in enumerate(os.environ["SOAK_ADDRS"].split(","))
    }
    base = os.environ["SOAK_DIR"]
    nid = rank + 1

    churn = os.environ.get("SOAK_CHURN") == "1"
    recover = os.environ.get("SOAK_RECOVER") == "1"
    hier = os.environ.get("SOAK_HIER") == "1"
    nhc_kw = {}
    if churn:
        # BlackWater churn profile (ISSUE 17): the health detectors run
        # at a tight cadence on EVERY host in BOTH arms (MTTR is scored
        # from detector open→close); the recovery plane only in the ON
        # arm.  Slower ticks than the base soak: 4 hosts x 100+ groups
        # on one box.
        nhc_kw.update(
            health_sample_ms=int(os.environ.get("SOAK_HEALTH_MS", "100")),
            enable_metrics=True,
            # both arms: on the oversubscribed box a partitioned
            # leader's tick loop starves, and a purely tick-valid lease
            # can outlive the majority's wall-time election (a stale
            # read the checker caught at 100 groups) — the wall guard
            # expires it instead
            lease_wall_guard=True,
        )
        if recover:
            nhc_kw.update(
                auto_recover=True,
                auto_recover_knobs=dict(
                    # cooldown > the flap quiet window: one escape
                    # transfer per open event — repeat transfers are
                    # themselves leader changes and would hold the
                    # detector open (MTTR regression, not remediation)
                    rate_limit_s=0.5, cooldown_s=8.0, retry_delay_s=0.2,
                    max_attempts=10, max_reopens=4, reopen_window_s=30.0,
                ),
                # boot in dry-run: 100-group elections on one vCPU look
                # exactly like quorum risk, and a controller that evicts
                # live voters mid-bootstrap wrecks the SETUP config
                # changes.  The first RESUME (parent sends it when setup
                # is complete) arms the controller for real.
                auto_recover_dry_run=True,
            )
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=os.path.join(base, f"nh{rank}"),
            rtt_millisecond=50 if churn else 20,
            raft_address=addrs[nid],
            expert=ExpertConfig(fast_lane=True, logdb_shards=2),
            **nhc_kw,
        )
    )
    if churn and nh.health is not None:
        # shrink the flap window so leader_flap can close (and be
        # scored) inside a single churn round; 4+ changes = a real flap
        # (a single election contributes 2-3: leader -> none -> leader)
        nh.health.flap_window_s = 6.0
        nh.health.leader_flap_changes = 4
        # startup elections produce sub-second unreachability blips; a
        # 2-sample debounce would let the ON arm evict LIVE voters while
        # the fleet is still settling.  8 sustained samples (~0.8s at the
        # 100ms cadence) ignores election noise yet still detects a real
        # kill/netsplit an order of magnitude faster than the 12s hold.
        nh.health.quorum_risk_samples = 8
    cids = list(range(1, groups + 1))
    if churn:
        _, witness_cids, sample_cids, lease_cids, _ = _churn_layout(groups)
    else:
        witness_cids, sample_cids, lease_cids = [], [], []
    user_sms = {}

    # SOAK_NATIVE_SM=1: the C-ABI KV + native session store — enrolled
    # groups then apply (and dedup) natively, so the churn exercises the
    # native apply/session path instead of the Python RSM rim
    native_sm = os.environ.get("SOAK_NATIVE_SM") == "1"
    if native_sm:
        from dragonboat_tpu.native.natsm import NativeKVStateMachine

    def _mk_sm(cluster_id, node_id):
        if native_sm:
            sm = NativeKVStateMachine(cluster_id, node_id)
        else:
            sm = _KVSM(cluster_id, node_id)
        user_sms[cluster_id] = sm
        return sm

    def _cfg(cid, node_id, **kw):
        base_kw = dict(
            cluster_id=cid, node_id=node_id, election_rtt=10,
            heartbeat_rtt=1,
            # aggressive: constant snapshot + compaction churn, and a
            # restarted replica far behind catches up via streaming
            # (churn mode relaxes a notch: 6x the groups on one box)
            snapshot_entries=200 if churn else 100,
            compaction_overhead=50 if churn else 20,
        )
        if churn and cid in sample_cids:
            base_kw["check_quorum"] = True
        if churn and cid in lease_cids:
            base_kw["read_lease"] = True
        if churn and hier:
            # hier arm (ISSUE 18): hosts 1+2 form near domain A, hosts
            # 3+4 domain B — the parent's domain-correlated waves then
            # take B down WHOLE, and linearizability is asserted with
            # sub-quorum commits live.  Recycled standbys (nid >= 5)
            # stay unassigned: never in a sub-quorum, always safe.
            base_kw["hier_commit"] = True
            base_kw["hier_domains"] = {1: "A", 2: "A", 3: "B", 4: "B"}
        base_kw.update(kw)
        if base_kw.get("is_witness"):
            # "witness node cannot take snapshot" (config.validate):
            # witnesses replicate metadata only, nothing to snapshot
            base_kw["snapshot_entries"] = 0
        return Config(**base_kw)

    if not churn:
        for cid in cids:
            nh.start_cluster(addrs, False, _mk_sm, _cfg(cid, nid))
    elif rank <= 1:
        # voter on every group; witness groups bootstrap with {1,2} only
        for cid in cids:
            members = (
                {1: addrs[1], 2: addrs[2]} if cid in witness_cids
                else {n: addrs[n] for n in (1, 2, 3)}
            )
            nh.start_cluster(members, False, _mk_sm, _cfg(cid, nid))
    elif rank == 2:
        # voter on plain groups; witness replica on the witness groups,
        # started join-style with an empty config — it sits idle until
        # the SETUP config change registers it and the leader streams
        # state (restart-safe: the saved bootstrap replays the same way)
        for cid in cids:
            if cid in witness_cids:
                nh.start_cluster({}, True, _mk_sm,
                                 _cfg(cid, 3, is_witness=True))
            else:
                nh.start_cluster({n: addrs[n] for n in (1, 2, 3)}, False,
                                 _mk_sm, _cfg(cid, 3))
    else:
        # rank 3 = the standby host: standing observers on the
        # quorum-sample groups (the replicas the recovery plane promotes)
        for cid in sample_cids:
            nh.start_cluster({}, True, _mk_sm,
                             _cfg(cid, 4, is_observer=True))

    hist_path = os.path.join(base, f"history.r{rank}.{os.getpid()}.jsonl")
    hist_f = open(hist_path, "a", buffering=1)
    hist_mu = threading.Lock()

    # WRITE-AHEAD history (Jepsen-style invoke/ret pairs): the invoke
    # line lands on disk BEFORE the operation is issued, so a kill -9
    # between "proposal committed server-side" and "completion recorded"
    # leaves an unmatched invoke that the checker treats as an op with
    # UNKNOWN outcome — not a hole.  (A 32-group soak caught exactly
    # this: a killed rank's committed put vanished from its history and
    # two other ranks' reads of it looked like phantom values.)
    op_seq = [0]

    def record_invoke(client, kind, key, value, t0):
        with hist_mu:
            op_seq[0] += 1
            oid = op_seq[0]
            hist_f.write(json.dumps({
                "ev": "inv", "id": oid, "client": client, "kind": kind,
                "key": key, "value": value, "invoke": t0,
            }) + "\n")
            return oid

    def record_ret(oid, value, t1, ok):
        with hist_mu:
            hist_f.write(json.dumps({
                "ev": "ret", "id": oid, "value": value, "ret": t1,
                "ok": ok,
            }) + "\n")

    paused = threading.Event()
    stopped = threading.Event()
    if churn:
        # churn ranks boot PAUSED so initial elections and the
        # witness/observer SETUP run without client load competing for
        # the single vCPU; the parent RESUMEs every rank once setup
        # lands.  Without this, setup config changes time out and the
        # recovery plane acts on startup transients.
        paused.set()
    # linearizability histories only for SAMPLED groups, written by ONE
    # paced client per rank: the Wing & Gong search cost scales with
    # per-key history length and concurrency, so the recorded stream is
    # deliberately low-rate while the unrecorded load threads provide the
    # actual stress (reference: Drummer checks sampled keys too)
    sampled = cids[: max(1, int(os.environ.get("SOAK_SAMPLE", "4")))]

    # SOAK_SESSIONS=1: history puts use REGISTERED sessions (exactly-once).
    # The payoff under kill -9 churn: an op whose first attempt times out
    # can be RETRIED with the same series id — the dedup store guarantees
    # at-most-once apply, so a successful retry RESOLVES the outcome
    # (committed, cached result) instead of leaving it unknown to the
    # checker.  Noop sessions can never do that (a retry would double-
    # apply).  Reference: client session semantics, session.go.
    use_sessions = os.environ.get("SOAK_SESSIONS") == "1"

    def _get(cid):
        # churn mode: not every rank hosts every group (witness/observer
        # layout, recycled nids) — absent is normal, not an error
        try:
            return nh.get_node(cid)
        except Exception:  # noqa: BLE001 — ClusterNotFoundError
            return None

    def history_client():
        client = rank
        rng = random.Random(client * 7919 + os.getpid())
        session = {}
        while not stopped.is_set():
            if paused.is_set():
                time.sleep(0.05)
                continue
            cid = rng.choice(sampled)
            node = _get(cid)
            if node is None:
                time.sleep(0.05)
                continue
            is_put = rng.random() < 0.6
            # puts go to the leader; linearizable GETs run at ANY replica
            # (follower-forwarded native ReadIndex) — history checking
            # then covers cross-replica read consistency, not just the
            # leader's own view
            if is_put and not node.is_leader():
                time.sleep(0.05)
                continue
            key = f"g{cid}:x{rng.randrange(2)}"
            t0 = time.time()
            if is_put:
                val = f"r{rank}n{rng.randrange(1 << 30)}"
                oid = record_invoke(client, "put", key, val, t0)
            else:
                val = None
                oid = record_invoke(client, "get", key, None, t0)
            try:
                if is_put:
                    s = session.get(cid)
                    if s is None:
                        if use_sessions:
                            s = nh.sync_get_session(cid, timeout=5.0)
                        else:
                            s = nh.get_noop_session(cid)
                        session[cid] = s
                    cmd = f"{key}={val}".encode()
                    attempts = 3 if not s.is_noop_session() else 1
                    done = False
                    for a in range(attempts):
                        try:
                            r = nh.propose(s, cmd, timeout=5.0).wait(5.0)
                        except Exception:
                            if a + 1 == attempts:
                                raise
                            continue
                        if r.completed:
                            done = True
                            break
                        # rejected/dropped with a session: the series was
                        # never applied under this id — safe to re-propose
                    if done and not s.is_noop_session():
                        s.proposal_completed()
                    record_ret(oid, val, time.time() if done else None, done)
                    if not done and not s.is_noop_session():
                        # unknown outcome on a session: the series id is
                        # burned (a later reuse could dedup against a
                        # quietly-committed first attempt and break the
                        # exactly-once bookkeeping) — re-register
                        session.pop(cid, None)
                else:
                    v = nh.sync_read(cid, key, timeout=5.0)
                    record_ret(oid, v, time.time(), True)
            except Exception:
                # timeout/dropped: outcome unknown — the checker treats a
                # None ret as an op concurrent with everything after it
                record_ret(oid, val, None, False)
                if is_put:
                    session.pop(cid, None)
            time.sleep(0.4)  # pace: bounded per-key history length

    def load(tid):
        rng = random.Random((rank * 100 + tid) * 104729 + os.getpid())
        session = {}
        while not stopped.is_set():
            if paused.is_set():
                time.sleep(0.05)
                continue
            cid = rng.choice(cids)
            node = _get(cid)
            if node is None or not node.is_leader():
                time.sleep(0.002)
                continue
            try:
                s = session.get(cid)
                if s is None:
                    s = session[cid] = nh.get_noop_session(cid)
                k = f"w{rng.randrange(64)}"
                rs = nh.propose(
                    s, f"{k}=t{tid}n{rng.randrange(1 << 30)}".encode(),
                    timeout=5.0,
                )
                rs.wait(5.0)
                if rng.random() < 0.1:
                    nh.sync_read(cid, k, timeout=5.0)
            except Exception:
                time.sleep(0.02)

    threading.Thread(target=history_client, daemon=True).start()
    for tid in range(threads):
        threading.Thread(target=load, args=(tid,), daemon=True).start()

    def emit(tag, obj=None):
        sys.stdout.write(tag + (" " + json.dumps(obj) if obj else "") + "\n")
        sys.stdout.flush()

    emit("READY", {"rank": rank, "pid": os.getpid()})
    try:
        for line in sys.stdin:
            cmd = line.strip()
            if cmd == "PAUSE":
                paused.set()
                time.sleep(0.3)  # let in-flight ops drain
                emit("PAUSED")
            elif cmd == "RESUME":
                paused.clear()
                if nh.recovery is not None:
                    nh.recovery.dry_run = False  # arm post-bootstrap
                emit("RESUMED")
            elif cmd == "HASHES":
                import zlib

                out = {}
                for cid in cids:
                    node = _get(cid)
                    if node is None:
                        continue  # churn: not every rank hosts every group
                    sm = node.sm
                    # manager hash (sessions+applied+membership) PLUS the
                    # user SM content hash — the manager hash alone would
                    # miss divergent KV state at equal applied indices
                    # (kvtest.go GetHash role)
                    user = user_sms.get(cid)
                    if user is None:
                        kv_hash = 0
                    elif native_sm:
                        kv_hash = user.get_hash()
                    else:
                        kv_hash = zlib.crc32(
                            repr(sorted(user.kv.items())).encode()
                        )
                    r = node.peer.raft if node.peer is not None else None
                    member = 1
                    if r is not None and node.node_id not in (
                        set(r.remotes) | set(r.observers) | set(r.witnesses)
                    ):
                        member = 0
                    out[cid] = [
                        sm.get_last_applied(), sm.get_hash(), kv_hash,
                        # exactly-once session store (compared too: a
                        # diverging dedup history is a consistency bug
                        # even while the KV content still agrees)
                        sm.get_session_hash(),
                        # diagnostics (not compared): raft view + lane state
                        r.log.committed if r else -1,
                        r.state.name if r else "?",
                        int(node.fast_lane),
                        # churn-mode comparison guards: witness replicas
                        # hold no user state; a replica whose own view says
                        # it left the membership (evicted/recycled) is
                        # excused from convergence (the lin gate covers it)
                        int(node.config.is_witness),
                        member,
                        # settle targeting: this replica's node id and its
                        # membership view — the parent trusts the
                        # MAX-applied cell's view (zombies replaying a
                        # pre-eviction bootstrap sit strictly below it)
                        node.node_id,
                        sorted(
                            set(r.remotes) | set(r.observers)
                            | set(r.witnesses)
                        ) if r else [],
                    ]
                fl = nh.fastlane
                emit("HASHES", {
                    "rank": rank, "groups": out,
                    "dropped_spans": fl.dropped_spans if fl else 0,
                    "enrolled": (
                        fl.stats().get("enrolled_replicas", 0) if fl else 0
                    ),
                })
            elif cmd.startswith("PART "):
                # "PART <addr> <0|1>": (un)block the remote at the native
                # transport — a true netsplit over TCP (both planes ride
                # the native streams; see fastlane.set_partition).  A rank
                # without a fast lane must NOT ack success: the parent
                # would count a netsplit that was never injected.
                _, part_addr, on = cmd.split()
                # the reply echoes the command so the parent can match
                # acks to requests (a timed-out attempt's late ack must
                # not satisfy a LATER command's wait)
                if nh.fastlane is not None:
                    nh.fastlane.set_partition(part_addr, on == "1")
                    emit("PART", {"ok": True, "addr": part_addr, "on": on})
                else:
                    emit("PART", {"ok": False, "addr": part_addr, "on": on})
            elif cmd == "SETUP":
                # churn setup (issued to rank 0 once): runtime config
                # changes — witness node 3 onto the witness groups, the
                # standing observer node 4 onto the quorum-sample groups.
                # Proposals forward to the leader, so one rank drives all
                # of them; a change that timed out but actually committed
                # is detected via the membership view and not retried.
                errs = []

                def _ensure(cid, want_nid, fn, field):
                    stop_at = time.time() + 240.0
                    while True:
                        try:
                            fn()
                            return
                        except Exception as e:  # noqa: BLE001
                            try:
                                m = nh.sync_get_cluster_membership(
                                    cid, timeout=5.0
                                )
                                if want_nid in getattr(m, field):
                                    return
                            except Exception:
                                pass
                            if time.time() > stop_at:
                                errs.append(
                                    f"{field}:{cid}:{type(e).__name__}"
                                )
                                return
                            time.sleep(0.5)

                for cid in witness_cids:
                    _ensure(
                        cid, 3,
                        lambda cid=cid: nh.sync_request_add_witness(
                            cid, 3, addrs[3], timeout=10.0
                        ),
                        "witnesses",
                    )
                for cid in sample_cids:
                    _ensure(
                        cid, 4,
                        lambda cid=cid: nh.sync_request_add_observer(
                            cid, 4, addrs[4], timeout=10.0
                        ),
                        "observers",
                    )
                emit("SETUP", {"ok": not errs, "errors": errs[:8]})
            elif cmd.startswith("XFER "):
                # drive a leader transfer if THIS host currently leads
                # the group (the parent's flap storm sends these to the
                # flapping pair only — once the recovery plane lands
                # leadership outside the pair they all no-op)
                _, c, t = cmd.split()
                c, t = int(c), int(t)
                node = _get(c)
                issued = False
                if node is not None and node.is_leader():
                    try:
                        nh.request_leader_transfer(c, t)
                        issued = True
                    except Exception:  # noqa: BLE001
                        pass
                emit("XFER", {"cid": c, "target": t, "issued": issued})
            elif cmd.startswith("RECYCLE "):
                # membership recycle (rank 0): retire the group's standby
                # nid and register a fresh one at the standby host — node
                # ids never rejoin after removal, so the recycle always
                # moves forward
                _, c, old, new = cmd.split()
                c, old, new = int(c), int(old), int(new)
                err = None
                try:
                    nh.sync_request_delete_node(c, old, timeout=15.0)
                    nh.sync_request_add_observer(
                        c, new, addrs[4], timeout=15.0
                    )
                except Exception as e:  # noqa: BLE001
                    err = f"{type(e).__name__}: {e}"[:160]
                emit("RECYCLE", {"cid": c, "ok": err is None, "error": err})
            elif cmd.startswith("REJOIN "):
                # rank 3: drop the retired observer replica and join the
                # fresh nid that RECYCLE just registered
                _, c, new = cmd.split()
                c, new = int(c), int(new)
                err = None
                try:
                    try:
                        nh.stop_cluster(c)
                    except Exception:  # noqa: BLE001
                        pass
                    nh.start_cluster({}, True, _mk_sm,
                                     _cfg(c, new, is_observer=True))
                except Exception as e:  # noqa: BLE001
                    err = f"{type(e).__name__}: {e}"[:160]
                emit("REJOIN", {"cid": c, "ok": err is None, "error": err})
            elif cmd == "RECOV":
                # MTTR collection: raw per-detector open→close durations
                # (the parent merges across hosts and recomputes fleet
                # percentiles), ages of still-open events (censored lower
                # bounds), and the recovery plane's action report
                h = nh.health
                open_ages = {}
                if h is not None:
                    for e in h.open_events():
                        open_ages.setdefault(e["detector"], []).append(
                            round(time.monotonic() - e["opened_mono"], 3)
                        )
                emit("RECOV", {
                    "rank": rank,
                    "durations": h.recovery_durations() if h else {},
                    "open_ages": open_ages,
                    "opened": dict(h.opened) if h else {},
                    "recovery": nh.recovery_report(),
                })
            elif cmd == "EXIT":
                break
    finally:
        stopped.set()
        hist_f.close()
        try:
            nh.stop()
        except Exception:
            pass
    return 0


# ------------------------------------------------------------------- parent


def _ports(n):
    out = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        out.append(s.getsockname()[1])
        s.close()
    return out


class Rank:
    def __init__(self, idx, env, logdir):
        self.idx = idx
        self.env = env
        self.logdir = logdir
        self.proc = None
        self.log = None
        self.lines = None

    def start(self):
        import queue as _q

        self.log = open(
            os.path.join(self.logdir, f"rank{self.idx}.{int(time.time())}.log"),
            "w",
        )
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--rank"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self.log, env=self.env, text=True,
        )
        self.lines = _q.Queue()

        def _reader(p, q):
            for ln in p.stdout:
                q.put(ln)
            q.put(None)

        threading.Thread(
            target=_reader, args=(self.proc, self.lines), daemon=True
        ).start()

    def expect(self, tag, timeout):
        import queue as _q

        deadline = time.time() + timeout
        while True:
            left = deadline - time.time()
            if left <= 0:
                raise TimeoutError(f"rank{self.idx}: no {tag} in {timeout}s")
            try:
                ln = self.lines.get(timeout=min(left, 1.0))
            except _q.Empty:
                continue
            if ln is None:
                raise RuntimeError(f"rank{self.idx} died waiting for {tag}")
            if ln.startswith(tag):
                rest = ln[len(tag):].strip()
                return json.loads(rest) if rest else None

    def send(self, cmd):
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.flush()

    def kill9(self):
        self.proc.kill()  # SIGKILL
        self.proc.wait()
        self.log.close()

    def pause(self):
        """SIGSTOP: the partition analog — the rank goes silent without
        dying (peers see timeouts; its own threads freeze mid-state)."""
        self.proc.send_signal(signal.SIGSTOP)

    def resume(self):
        self.proc.send_signal(signal.SIGCONT)

    def alive(self):
        return self.proc is not None and self.proc.poll() is None


def _set_split(ranks, addr_list, victim, on):
    """Symmetric netsplit {victim} | {others} at the native wire
    (the reference monkey's partitionTests shape).  Returns True
    when every live rank confirmed the change.  A rank that fails
    to HEAL is kill -9'd and restarted: its blocks live in process
    memory, so the restart clears them — a stale block would
    otherwise fail every later converge check with a misleading
    divergence report."""
    flag = "1" if on else "0"
    ok = True

    def apply_one(r):
        cmds = (
            [a for j, a in enumerate(addr_list) if j != victim.idx]
            if r is victim
            else [addr_list[victim.idx]]
        )
        for a in cmds:
            r.send(f"PART {a} {flag}")
            # match the echoed command: a late ack from a timed-out
            # earlier attempt must not satisfy this wait
            deadline_ack = time.time() + 10
            while True:
                rep = r.expect("PART", max(0.1, deadline_ack - time.time()))
                if rep and rep.get("addr") == a and rep.get("on") == flag:
                    break
            if not rep.get("ok"):
                raise RuntimeError("partition injection refused")

    for r in ranks:
        if not r.alive():
            continue  # a killed rank holds no blocks
        for attempt in (1, 2):
            try:
                apply_one(r)
                break
            except Exception:
                if attempt == 2:
                    ok = False
                    if not on and r.alive():
                        print(
                            f"# rank{r.idx} failed to heal; "
                            "kill -9 to clear its blocks",
                            file=sys.stderr,
                        )
                        r.kill9()
                        time.sleep(1.0)
                        r.start()
                        r.expect("READY", 180)
    return ok


def _converge_check(ranks, groups, timeout=90.0):
    """Pause load everywhere, wait for equal applied indices per group on
    every live rank, compare state hashes.  Returns the hash map or raises."""
    live = [r for r in ranks if r.alive()]
    for r in live:
        r.send("PAUSE")
    for r in live:
        r.expect("PAUSED", 30)
    deadline = time.time() + timeout
    last = None
    try:
        while True:
            reports = []
            for r in live:
                r.send("HASHES")
                reports.append(r.expect("HASHES", 30))
            for rep in reports:
                assert rep["dropped_spans"] == 0, (
                    f"rank{rep['rank']} dropped apply spans"
                )
            bad = []
            for cid in range(1, groups + 1):
                cells = [rep["groups"][str(cid)] for rep in reports]
                applied = {c[0] for c in cells}
                # manager + user SM + session store
                hashes = {tuple(c[1:4]) for c in cells}
                if len(applied) != 1 or len(hashes) != 1:
                    bad.append((cid, cells))
            last = bad
            if not bad:
                return reports
            if time.time() > deadline:
                for r in live:  # stack dumps into the rank logs
                    try:
                        r.proc.send_signal(signal.SIGUSR2)
                    except Exception:
                        pass
                time.sleep(1.0)
                raise AssertionError(
                    f"replicas diverged after {timeout}s settle: "
                    f"{len(bad)} groups, sample {bad[:3]}"
                )
            time.sleep(1.0)
    finally:
        for r in live:
            if r.alive():
                r.send("RESUME")
                r.expect("RESUMED", 30)


def _check_histories(base, groups):
    from dragonboat_tpu.linearizability import Op, check_linearizable

    INF = float("inf")
    ops = []
    for fn in sorted(os.listdir(base)):
        if not fn.startswith("history."):
            continue
        # write-ahead pairs: "inv" lines land BEFORE the op is issued,
        # "ret" lines after.  An inv with no ret (the rank was killed
        # mid-op, or its ret line was torn) is an op with UNKNOWN
        # outcome — a killed rank's committed-but-unrecorded put must
        # stay representable or other ranks' reads of it look phantom.
        pend = {}
        with open(os.path.join(base, fn)) as f:
            lines = f.readlines()
        for ln in lines:
            try:
                d = json.loads(ln)
            except json.JSONDecodeError:
                continue  # torn final line from a kill -9
            if d.get("ev") == "inv":
                pend[d["id"]] = d
            elif d.get("ev") == "ret":
                inv = pend.pop(d["id"], None)
                if inv is None:
                    continue  # ret whose inv line was torn: drop
                ops.append(Op(
                    client=inv["client"], kind=inv["kind"],
                    key=inv["key"], value=d["value"],
                    invoke=inv["invoke"],
                    ret=d["ret"] if d["ret"] is not None else INF,
                    ok=bool(d["ok"]),
                ))
        for inv in pend.values():  # unmatched: unknown outcome
            ops.append(Op(
                client=inv["client"], kind=inv["kind"], key=inv["key"],
                value=inv["value"], invoke=inv["invoke"],
                ret=INF, ok=False,
            ))
    ok, bad = check_linearizable(ops)
    return ok, bad, len(ops)


# -------------------------------------------------------------- churn parent


def _pct(durs, p):
    s = sorted(durs)
    if not s:
        return None
    i = min(len(s) - 1, max(0, int(round((p / 100.0) * (len(s) - 1)))))
    return s[i]


def _mttr_stats(durs_by_det, open_by_det):
    """Fleet-level MTTR per detector: closed open→close durations merged
    across hosts plus the ages of still-open events (censored LOWER
    bounds — counting them can only make MTTR look worse, never
    better)."""
    out = {}
    for det in sorted(set(durs_by_det) | set(open_by_det)):
        closed = list(durs_by_det.get(det, ()))
        censored = list(open_by_det.get(det, ()))
        all_d = closed + censored
        if not all_d:
            continue
        out[det] = {
            "n": len(all_d),
            "unclosed": len(censored),
            "p50_s": round(_pct(all_d, 50), 3),
            "p99_s": round(_pct(all_d, 99), 3),
            "max_s": round(max(all_d), 3),
        }
    return out


def _collect_recov(ranks):
    """Merge every live rank's RECOV report: raw durations, open-event
    ages, detector open counts and (ON arm) recovery action counts."""
    durs, open_ages, opened, actions = {}, {}, {}, {}
    for r in ranks:
        if not r.alive():
            continue
        r.send("RECOV")
        rep = r.expect("RECOV", 30)
        for det, d in (rep.get("durations") or {}).items():
            durs.setdefault(det, []).extend(d)
        for det, ages in (rep.get("open_ages") or {}).items():
            open_ages.setdefault(det, []).extend(ages)
        for det, n in (rep.get("opened") or {}).items():
            if n:
                opened[det] = opened.get(det, 0) + n
        rec = rep.get("recovery") or {}
        if rec.get("enabled"):
            for k, v in (rec.get("actions") or {}).items():
                actions[k] = actions.get(k, 0) + v
    return durs, open_ages, opened, actions


def _churn_converge(ranks, groups, timeout=150.0, settle=False):
    """Relaxed churn-mode convergence.  Membership is deliberately in
    motion (witness adds, observer promotions, evictions, recycles), so
    equal-applied-everywhere is not a reachable fixpoint mid-run.  The
    invariant that IS checked continuously: two member (non-witness)
    replicas at the SAME applied index must have identical state —
    divergence, never lag.  With ``settle=True`` (final check) it also
    waits until, per group, every replica that the MAX-applied cell's
    membership view still lists matches that cell's applied index and
    hashes.  Replicas that replayed a pre-eviction bootstrap (zombies)
    sit strictly below the max — the eviction entry itself separates
    them — and are not in the reference view, so they are excused; the
    linearizability gate covers their reads."""
    live = [r for r in ranks if r.alive()]
    for r in live:
        r.send("PAUSE")
    for r in live:
        r.expect("PAUSED", 30)
    deadline = time.time() + timeout
    try:
        while True:
            reports = []
            for r in live:
                r.send("HASHES")
                reports.append(r.expect("HASHES", 60))
            for rep in reports:
                assert rep["dropped_spans"] == 0, (
                    f"rank{rep['rank']} dropped apply spans"
                )
            diverged, lagging = [], []
            for cid in range(1, groups + 1):
                cells = []
                for rep in reports:
                    c = rep["groups"].get(str(cid))
                    if c is not None and len(c) >= 9 and c[7] == 0 \
                            and c[8] == 1:
                        cells.append(c)
                if not cells:
                    continue
                byapp = {}
                for c in cells:
                    byapp.setdefault(c[0], set()).add(tuple(c[1:4]))
                if any(len(h) > 1 for h in byapp.values()):
                    diverged.append((cid, cells))
                    continue
                if settle and len(cells) >= 2:
                    ref = max(cells, key=lambda c: c[0])
                    mset = set(ref[10]) if len(ref) >= 11 else set()
                    for c in cells:
                        if c is ref or len(c) < 11 or c[9] not in mset:
                            continue
                        if c[5] == "OBSERVER":
                            # non-voting: an observer a couple of
                            # entries behind the commit frontier is
                            # eventual-consistency, not divergence (the
                            # same-applied hash check above still
                            # covers it; reads forward to the leader)
                            continue
                        if c[0] != ref[0] or c[1:4] != ref[1:4]:
                            lagging.append((cid, cells))
                            break
            if not diverged and not lagging:
                return reports
            if time.time() > deadline:
                for r in live:  # stack dumps into the rank logs
                    try:
                        r.proc.send_signal(signal.SIGUSR2)
                    except Exception:
                        pass
                time.sleep(1.0)
                kind = "diverged" if diverged else "failed to settle"
                raise AssertionError(
                    f"churn converge {kind} after {timeout}s: "
                    f"{len(diverged)} diverged / {len(lagging)} lagging, "
                    f"sample {(diverged or lagging)[:3]}"
                )
            time.sleep(2.0)
    finally:
        for r in live:
            if r.alive():
                r.send("RESUME")
                r.expect("RESUMED", 30)


def churn_main(args) -> int:
    """BlackWater churn soak (ISSUE 17).  Four hosts — three voters plus
    a standby host carrying standing observers — run ``--groups`` Raft
    groups through a seeded round schedule: leader-flap storm → settle →
    netsplit the third voter host → heal → SIGSTOP freeze → membership
    recycle (odd rounds) or kill -9 + restart (even rounds) → converge
    check.  Detectors run in both arms; ``--recover`` arms the recovery
    plane.  Scored by fleet-merged per-detector MTTR; gated on
    linearizable histories, zero same-applied divergence and zero
    dropped fast-lane spans."""
    seed = args.seed or int(time.time())
    rng = random.Random(seed)
    groups = args.groups
    base = tempfile.mkdtemp(prefix="dbtpu-churn-")
    ports = _ports(4)
    addr_list = [f"127.0.0.1:{p}" for p in ports]
    addrs = ",".join(addr_list)
    arm = "on" if args.recover else "off"
    print(
        f"# churn soak: {args.minutes} min, {groups} groups, "
        f"recover={arm}, seed {seed}, dir {base}",
        file=sys.stderr,
    )

    _, witness_cids, sample_cids, _, flap_cids = _churn_layout(groups)
    ranks = []
    for i in range(4):
        env = dict(os.environ)
        env.update({
            "SOAK_RANK": str(i), "SOAK_GROUPS": str(groups),
            "SOAK_ADDRS": addrs, "SOAK_DIR": base,
            "SOAK_CHURN": "1",
            "SOAK_RECOVER": "1" if args.recover else "0",
            "SOAK_HIER": "1" if getattr(args, "hier", False) else "0",
            "SOAK_THREADS": os.environ.get("SOAK_THREADS", "2"),
            "SOAK_SAMPLE": "8",
            # at 100+ groups the 100ms sampler pass itself is load on
            # the 1-vCPU box; 250ms keeps detection an order of
            # magnitude under the 12s netsplit hold while widening the
            # debounce window (quorum_risk_samples x cadence) enough to
            # ride out CPU-starvation heartbeat lapses
            "SOAK_HEALTH_MS": os.environ.get(
                "SOAK_HEALTH_MS", "100" if args.groups <= 32 else "250"
            ),
        })
        ranks.append(Rank(i, env, base))

    counts = {
        "rounds": 0, "kills": 0, "sigstops": 0, "netsplits": 0,
        "recycles": 0, "xfers": 0, "converges": 0,
    }
    failure = None
    mttr, recovery_actions, opened = {}, {}, {}
    n_ops = 0
    lin_ok = True
    obs_nid = {cid: 4 for cid in sample_cids}
    next_nid = 5
    recycle_i = 0
    t0 = time.time()
    deadline = t0 + args.minutes * 60
    try:
        for r in ranks:
            r.start()
        for r in ranks:
            r.expect("READY", 240)
        # initial elections across all groups (load is paused until
        # after SETUP) — 100 groups x 3-4 replicas on one vCPU elect
        # much slower than the smoke shape
        time.sleep(10.0 if groups <= 32 else 25.0)
        ranks[0].send("SETUP")
        setup = ranks[0].expect("SETUP", 900)
        if not setup.get("ok"):
            raise RuntimeError(
                f"churn setup incomplete: {setup.get('errors')}"
            )
        for r in ranks:
            r.send("RESUME")
            r.expect("RESUMED", 30)
        time.sleep(5.0)  # witness/observer catch-up under load

        def _xfer(rk, cid, target):
            rk.send(f"XFER {cid} {target}")
            rep = rk.expect("XFER", 20)
            if rep.get("issued"):
                counts["xfers"] += 1

        while counts["rounds"] < 2 or time.time() < deadline:
            rnd = counts["rounds"] + 1
            # ---- leader-flap storm: bounce the flap groups 1<->2.  The
            # drive goes only to the flapping pair's hosts — once the
            # recovery plane transfers leadership OUT of the pair the
            # remaining drive no-ops and the flap dies; with recovery
            # off it churns for the whole phase.
            print(f"# t+{time.time() - t0:.0f}s round {rnd}: flap storm",
                  file=sys.stderr)
            for cid in flap_cids:  # land leadership inside the pair first
                for rk in ranks[:3]:
                    _xfer(rk, cid, 1)
            time.sleep(1.5)
            # 24 ticks ≈ 19s: long enough that an OFF-arm event must
            # outlast the storm while the ON arm's escape transfer
            # (plus one cooldown-spaced retry if the first fails to
            # land) kills it mid-phase — the measured MTTR gap IS this
            # difference
            for tick in range(24):
                target = 2 if tick % 2 == 0 else 1
                rk = ranks[0] if target == 2 else ranks[1]
                for cid in flap_cids:
                    _xfer(rk, cid, target)
                time.sleep(0.8)
            time.sleep(10.0)  # settle: flap windows slide shut
            # ---- netsplit the third voter host (the quorum_at_risk arm:
            # recovery evicts the dead voter and promotes the observer).
            # hier arm: the wave is domain-CORRELATED — rank3 (the other
            # domain-B host) goes down with it, so every commit closed
            # during the hold closed through domain A's sub-quorum and
            # the final linearizability gate scores exactly those
            split_victims = [ranks[2]]
            if getattr(args, "hier", False):
                split_victims.append(ranks[3])
            print(
                f"# t+{time.time() - t0:.0f}s round {rnd}: netsplit "
                f"rank{'2+3' if len(split_victims) > 1 else '2'} for 12s",
                file=sys.stderr,
            )
            if any(
                _set_split(ranks, addr_list, v, True)
                for v in split_victims
            ):
                counts["netsplits"] += 1
            time.sleep(12.0)
            for v in split_victims:
                _set_split(ranks, addr_list, v, False)
            time.sleep(6.0)
            # ---- SIGSTOP freeze: silence without death
            print(
                f"# t+{time.time() - t0:.0f}s round {rnd}: SIGSTOP "
                "rank1 for 4s", file=sys.stderr,
            )
            if ranks[1].alive():
                ranks[1].pause()
                time.sleep(4.0)
                ranks[1].resume()
                counts["sigstops"] += 1
            time.sleep(3.0)
            if rnd % 2 == 1:
                # ---- membership recycle: retire + re-register standbys
                for k in range(2):
                    cid = sample_cids[
                        (recycle_i + k) % len(sample_cids)
                    ]
                    old, new = obs_nid[cid], next_nid
                    ranks[0].send(f"RECYCLE {cid} {old} {new}")
                    rep = ranks[0].expect("RECYCLE", 60)
                    if rep.get("ok"):
                        ranks[3].send(f"REJOIN {cid} {new}")
                        rep2 = ranks[3].expect("REJOIN", 60)
                        if rep2.get("ok"):
                            obs_nid[cid] = new
                            next_nid += 1
                            counts["recycles"] += 1
                    else:
                        print(
                            f"# recycle {cid} skipped: {rep.get('error')}",
                            file=sys.stderr,
                        )
                recycle_i += 2
            else:
                # ---- kill -9 + restart: WAL replay under churn
                print(
                    f"# t+{time.time() - t0:.0f}s round {rnd}: "
                    "kill -9 rank1", file=sys.stderr,
                )
                ranks[1].kill9()
                counts["kills"] += 1
                time.sleep(rng.uniform(3, 6))
                ranks[1].start()
                ranks[1].expect("READY", 240)
                ranks[1].send("RESUME")  # churn ranks boot paused
                ranks[1].expect("RESUMED", 30)
                time.sleep(3.0)
            _churn_converge(ranks, groups)
            counts["converges"] += 1
            counts["rounds"] = rnd

        # final: quiet long enough for open windows to close, settle
        # strictly among max-applied members, score, stop, lin-check
        print("# final settle + converge", file=sys.stderr)
        time.sleep(10.0)
        _churn_converge(ranks, groups, timeout=240.0, settle=True)
        counts["converges"] += 1
        durs, open_ages, opened, recovery_actions = _collect_recov(ranks)
        mttr = _mttr_stats(durs, open_ages)
        for r in ranks:
            if r.alive():
                r.send("EXIT")
        for r in ranks:
            try:
                r.proc.wait(timeout=30)
            except Exception:
                r.proc.kill()
        lin_ok, bad, n_ops = _check_histories(base, groups)
        if not lin_ok:
            failure = f"history not linearizable on keys {bad[:8]}"
    except Exception as e:  # noqa: BLE001 — summarize, keep artifacts
        failure = f"{type(e).__name__}: {e}"
        lin_ok = False
    finally:
        for r in ranks:
            try:
                if r.alive():
                    r.proc.kill()
            except Exception:
                pass

    summary = {
        "churn_ok": failure is None,
        "recover": bool(args.recover),
        "hier": bool(getattr(args, "hier", False)),
        "seed": seed,
        "minutes": args.minutes,
        "groups": groups,
        "witness_groups": len(witness_cids),
        **counts,
        "history_ops": n_ops,
        "linearizable": bool(lin_ok) and failure is None,
        "detectors_opened": opened,
        "recovery_actions": recovery_actions,
        "mttr": mttr,
        "error": failure,
        "artifacts": base if (failure or args.keep) else None,
    }
    if failure is None and not args.keep:
        import shutil

        shutil.rmtree(base, ignore_errors=True)
    print(json.dumps(summary))
    return 0 if failure is None else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--groups", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--keep", action="store_true",
                    help="keep the run dir even on success")
    ap.add_argument("--churn", action="store_true",
                    help="BlackWater churn soak (ISSUE 17): 4 hosts, "
                         "witness quorums, MTTR-scored round schedule")
    ap.add_argument("--recover", action="store_true",
                    help="churn mode: arm the closed-loop recovery plane "
                         "(the A/B ON arm)")
    ap.add_argument("--hier", action="store_true",
                    help="churn mode: hierarchical commit plane ON "
                         "(ISSUE 18) with 2+2 domains and the netsplit "
                         "wave taking domain B down whole")
    args = ap.parse_args()
    if args.churn:
        return churn_main(args)

    rng = random.Random(args.seed or int(time.time()))
    base = tempfile.mkdtemp(prefix="dbtpu-soak-")
    ports = _ports(3)
    addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
    print(f"# soak: {args.minutes} min, {args.groups} groups, dir {base}",
          file=sys.stderr)

    ranks = []
    for i in range(3):
        env = dict(os.environ)
        env.update({
            "SOAK_RANK": str(i), "SOAK_GROUPS": str(args.groups),
            "SOAK_ADDRS": addrs, "SOAK_DIR": base,
        })
        ranks.append(Rank(i, env, base))
    t0 = time.time()
    deadline = t0 + args.minutes * 60
    kills = 0
    pauses = 0
    splits = 0
    converges = 0
    failure = None
    try:
        for r in ranks:
            r.start()
        for r in ranks:
            r.expect("READY", 120)
        time.sleep(5.0)  # initial elections + load ramp

        next_kill = time.time() + rng.uniform(10, 25)
        next_pause = time.time() + rng.uniform(20, 35)
        next_split = time.time() + rng.uniform(25, 40)
        next_converge = time.time() + 30.0
        addr_list = addrs.split(",")

        def set_split(victim, on):
            return _set_split(ranks, addr_list, victim, on)
        while time.time() < deadline:
            time.sleep(1.0)
            now = time.time()
            if now >= next_pause:
                # partition-freeze fault: SIGSTOP a rank for 2-6s (long
                # enough to cross election timeouts sometimes), then wake
                # it into a world that moved on — exercises check-quorum,
                # elections without a crash, post-wake stale-term traffic
                # and fast-lane eject/re-enroll on both sides
                victim = rng.choice(ranks)
                dur = rng.uniform(2, 6)
                print(f"# t+{now - t0:.0f}s SIGSTOP rank{victim.idx} "
                      f"for {dur:.1f}s", file=sys.stderr)
                victim.pause()
                time.sleep(dur)
                victim.resume()
                pauses += 1
                next_pause = time.time() + rng.uniform(20, 45)
            if now >= next_split:
                victim = rng.choice(ranks)
                dur = rng.uniform(2, 8)
                print(f"# t+{now - t0:.0f}s netsplit rank{victim.idx} "
                      f"for {dur:.1f}s", file=sys.stderr)
                injected = set_split(victim, True)
                time.sleep(dur)
                set_split(victim, False)
                if injected:  # only count splits that actually happened
                    splits += 1
                next_split = time.time() + rng.uniform(25, 50)
            if now >= next_kill:
                victim = rng.choice(ranks)
                print(f"# t+{now - t0:.0f}s kill -9 rank{victim.idx}",
                      file=sys.stderr)
                victim.kill9()
                kills += 1
                time.sleep(rng.uniform(2, 8))
                victim.start()
                victim.expect("READY", 180)
                next_kill = time.time() + rng.uniform(15, 40)
            if now >= next_converge:
                print(f"# t+{now - t0:.0f}s converge check", file=sys.stderr)
                _converge_check(ranks, args.groups)
                converges += 1
                next_converge = time.time() + rng.uniform(30, 60)

        # final: settle, converge, stop cleanly, check histories
        print("# final converge", file=sys.stderr)
        reports = _converge_check(ranks, args.groups, timeout=120.0)
        converges += 1
        enrolled = [rep.get("enrolled", 0) for rep in reports]
        for r in ranks:
            if r.alive():
                r.send("EXIT")
        for r in ranks:
            try:
                r.proc.wait(timeout=20)
            except Exception:
                r.proc.kill()
        ok, bad, n_ops = _check_histories(base, args.groups)
        if not ok:
            failure = f"history not linearizable on keys {bad[:8]}"
    except Exception as e:  # noqa: BLE001 — summarize, keep artifacts
        failure = f"{type(e).__name__}: {e}"
        ok = False
        n_ops = 0
        enrolled = []
    finally:
        for r in ranks:
            try:
                if r.alive():
                    r.proc.kill()
            except Exception:
                pass

    summary = {
        "soak_ok": failure is None,
        "minutes": args.minutes,
        "groups": args.groups,
        "kills": kills,
        "pauses": pauses,
        "netsplits": splits,
        "converge_checks": converges,
        "history_ops": n_ops,
        "enrolled_final": enrolled,
        "error": failure,
        "artifacts": base if (failure or args.keep) else None,
    }
    if failure is None and not args.keep:
        import shutil

        shutil.rmtree(base, ignore_errors=True)
    print(json.dumps(summary))
    return 0 if failure is None else 1


if __name__ == "__main__":
    if "--rank" in sys.argv:
        sys.exit(rank_main())
    sys.exit(main())
