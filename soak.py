"""Soak driver — the Drummer analog (reference ``docs/test.md:6-36``).

Opt-in, minutes-long chaos soak over the REAL deployment shape: three
NodeHost processes on framed TCP with durable native storage and the fast
lane on, G Raft groups replicated across all three.  For N minutes the
parent repeatedly ``kill -9``s a random rank and restarts it against the
same data dirs (WAL replay + snapshot catch-up), while every rank runs
continuous client load.  Aggressive snapshot settings keep snapshot
save/compact/stream churning throughout.

Verification, continuously and at the end:

- **cross-replica state hashes** (reference ``monkey.go:110-144``): at
  every converge window the parent pauses load, waits for equal applied
  indices on every live rank, and compares per-group state hashes;
- **linearizability** (reference Jepsen/Knossos role): every rank records
  an invoke/response history of puts and linearizable reads on per-group
  shared keys (wall-clock timestamps — one box); the parent merges all
  histories and runs ``linearizability.check_linearizable`` per key;
- **fast-lane invariants**: dropped apply spans must be 0 on every rank.

On failure the run's artifacts (per-rank histories, rank stderr logs, the
failure report) are preserved in the run directory and its path printed.

Usage::

    python soak.py --minutes 10 --groups 16        # the make soak target
    python soak.py --minutes 1 --groups 8          # quick smoke

Exit code 0 = green.  Prints one JSON summary line last.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

# --------------------------------------------------------------------- rank


class _KVSM:
    def __init__(self, cluster_id, node_id):
        self.kv = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        from dragonboat_tpu import Result

        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = json.dumps(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(json.loads(r.read(n).decode()))

    def close(self):
        pass


def rank_main() -> int:
    import faulthandler

    # divergence triage: the parent sends SIGUSR2 before teardown so the
    # rank's stderr log captures every thread's stack at failure time
    faulthandler.register(signal.SIGUSR2, all_threads=True)

    from dragonboat_tpu import Config, NodeHost, NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig

    rank = int(os.environ["SOAK_RANK"])
    groups = int(os.environ["SOAK_GROUPS"])
    threads = int(os.environ.get("SOAK_THREADS", "4"))
    addrs = {
        i + 1: a for i, a in enumerate(os.environ["SOAK_ADDRS"].split(","))
    }
    base = os.environ["SOAK_DIR"]
    nid = rank + 1

    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=os.path.join(base, f"nh{rank}"),
            rtt_millisecond=20,
            raft_address=addrs[nid],
            expert=ExpertConfig(fast_lane=True, logdb_shards=2),
        )
    )
    cids = list(range(1, groups + 1))
    user_sms = {}

    # SOAK_NATIVE_SM=1: the C-ABI KV + native session store — enrolled
    # groups then apply (and dedup) natively, so the churn exercises the
    # native apply/session path instead of the Python RSM rim
    native_sm = os.environ.get("SOAK_NATIVE_SM") == "1"
    if native_sm:
        from dragonboat_tpu.native.natsm import NativeKVStateMachine

    def _mk_sm(cluster_id, node_id):
        if native_sm:
            sm = NativeKVStateMachine(cluster_id, node_id)
        else:
            sm = _KVSM(cluster_id, node_id)
        user_sms[cluster_id] = sm
        return sm

    for cid in cids:
        nh.start_cluster(
            addrs, False, _mk_sm,
            Config(
                cluster_id=cid, node_id=nid, election_rtt=10,
                heartbeat_rtt=1,
                # aggressive: constant snapshot + compaction churn, and a
                # restarted replica far behind catches up via streaming
                snapshot_entries=100, compaction_overhead=20,
            ),
        )

    hist_path = os.path.join(base, f"history.r{rank}.{os.getpid()}.jsonl")
    hist_f = open(hist_path, "a", buffering=1)
    hist_mu = threading.Lock()

    # WRITE-AHEAD history (Jepsen-style invoke/ret pairs): the invoke
    # line lands on disk BEFORE the operation is issued, so a kill -9
    # between "proposal committed server-side" and "completion recorded"
    # leaves an unmatched invoke that the checker treats as an op with
    # UNKNOWN outcome — not a hole.  (A 32-group soak caught exactly
    # this: a killed rank's committed put vanished from its history and
    # two other ranks' reads of it looked like phantom values.)
    op_seq = [0]

    def record_invoke(client, kind, key, value, t0):
        with hist_mu:
            op_seq[0] += 1
            oid = op_seq[0]
            hist_f.write(json.dumps({
                "ev": "inv", "id": oid, "client": client, "kind": kind,
                "key": key, "value": value, "invoke": t0,
            }) + "\n")
            return oid

    def record_ret(oid, value, t1, ok):
        with hist_mu:
            hist_f.write(json.dumps({
                "ev": "ret", "id": oid, "value": value, "ret": t1,
                "ok": ok,
            }) + "\n")

    paused = threading.Event()
    stopped = threading.Event()
    # linearizability histories only for SAMPLED groups, written by ONE
    # paced client per rank: the Wing & Gong search cost scales with
    # per-key history length and concurrency, so the recorded stream is
    # deliberately low-rate while the unrecorded load threads provide the
    # actual stress (reference: Drummer checks sampled keys too)
    sampled = cids[: max(1, int(os.environ.get("SOAK_SAMPLE", "4")))]

    # SOAK_SESSIONS=1: history puts use REGISTERED sessions (exactly-once).
    # The payoff under kill -9 churn: an op whose first attempt times out
    # can be RETRIED with the same series id — the dedup store guarantees
    # at-most-once apply, so a successful retry RESOLVES the outcome
    # (committed, cached result) instead of leaving it unknown to the
    # checker.  Noop sessions can never do that (a retry would double-
    # apply).  Reference: client session semantics, session.go.
    use_sessions = os.environ.get("SOAK_SESSIONS") == "1"

    def history_client():
        client = rank
        rng = random.Random(client * 7919 + os.getpid())
        session = {}
        while not stopped.is_set():
            if paused.is_set():
                time.sleep(0.05)
                continue
            cid = rng.choice(sampled)
            node = nh.get_node(cid)
            if node is None:
                time.sleep(0.05)
                continue
            is_put = rng.random() < 0.6
            # puts go to the leader; linearizable GETs run at ANY replica
            # (follower-forwarded native ReadIndex) — history checking
            # then covers cross-replica read consistency, not just the
            # leader's own view
            if is_put and not node.is_leader():
                time.sleep(0.05)
                continue
            key = f"g{cid}:x{rng.randrange(2)}"
            t0 = time.time()
            if is_put:
                val = f"r{rank}n{rng.randrange(1 << 30)}"
                oid = record_invoke(client, "put", key, val, t0)
            else:
                val = None
                oid = record_invoke(client, "get", key, None, t0)
            try:
                if is_put:
                    s = session.get(cid)
                    if s is None:
                        if use_sessions:
                            s = nh.sync_get_session(cid, timeout=5.0)
                        else:
                            s = nh.get_noop_session(cid)
                        session[cid] = s
                    cmd = f"{key}={val}".encode()
                    attempts = 3 if not s.is_noop_session() else 1
                    done = False
                    for a in range(attempts):
                        try:
                            r = nh.propose(s, cmd, timeout=5.0).wait(5.0)
                        except Exception:
                            if a + 1 == attempts:
                                raise
                            continue
                        if r.completed:
                            done = True
                            break
                        # rejected/dropped with a session: the series was
                        # never applied under this id — safe to re-propose
                    if done and not s.is_noop_session():
                        s.proposal_completed()
                    record_ret(oid, val, time.time() if done else None, done)
                    if not done and not s.is_noop_session():
                        # unknown outcome on a session: the series id is
                        # burned (a later reuse could dedup against a
                        # quietly-committed first attempt and break the
                        # exactly-once bookkeeping) — re-register
                        session.pop(cid, None)
                else:
                    v = nh.sync_read(cid, key, timeout=5.0)
                    record_ret(oid, v, time.time(), True)
            except Exception:
                # timeout/dropped: outcome unknown — the checker treats a
                # None ret as an op concurrent with everything after it
                record_ret(oid, val, None, False)
                if is_put:
                    session.pop(cid, None)
            time.sleep(0.4)  # pace: bounded per-key history length

    def load(tid):
        rng = random.Random((rank * 100 + tid) * 104729 + os.getpid())
        session = {}
        while not stopped.is_set():
            if paused.is_set():
                time.sleep(0.05)
                continue
            cid = rng.choice(cids)
            node = nh.get_node(cid)
            if node is None or not node.is_leader():
                time.sleep(0.002)
                continue
            try:
                s = session.get(cid)
                if s is None:
                    s = session[cid] = nh.get_noop_session(cid)
                k = f"w{rng.randrange(64)}"
                rs = nh.propose(
                    s, f"{k}=t{tid}n{rng.randrange(1 << 30)}".encode(),
                    timeout=5.0,
                )
                rs.wait(5.0)
                if rng.random() < 0.1:
                    nh.sync_read(cid, k, timeout=5.0)
            except Exception:
                time.sleep(0.02)

    threading.Thread(target=history_client, daemon=True).start()
    for tid in range(threads):
        threading.Thread(target=load, args=(tid,), daemon=True).start()

    def emit(tag, obj=None):
        sys.stdout.write(tag + (" " + json.dumps(obj) if obj else "") + "\n")
        sys.stdout.flush()

    emit("READY", {"rank": rank, "pid": os.getpid()})
    try:
        for line in sys.stdin:
            cmd = line.strip()
            if cmd == "PAUSE":
                paused.set()
                time.sleep(0.3)  # let in-flight ops drain
                emit("PAUSED")
            elif cmd == "RESUME":
                paused.clear()
                emit("RESUMED")
            elif cmd == "HASHES":
                import zlib

                out = {}
                for cid in cids:
                    node = nh.get_node(cid)
                    sm = node.sm
                    # manager hash (sessions+applied+membership) PLUS the
                    # user SM content hash — the manager hash alone would
                    # miss divergent KV state at equal applied indices
                    # (kvtest.go GetHash role)
                    user = user_sms.get(cid)
                    if user is None:
                        kv_hash = 0
                    elif native_sm:
                        kv_hash = user.get_hash()
                    else:
                        kv_hash = zlib.crc32(
                            repr(sorted(user.kv.items())).encode()
                        )
                    r = node.peer.raft if node.peer is not None else None
                    out[cid] = [
                        sm.get_last_applied(), sm.get_hash(), kv_hash,
                        # exactly-once session store (compared too: a
                        # diverging dedup history is a consistency bug
                        # even while the KV content still agrees)
                        sm.get_session_hash(),
                        # diagnostics (not compared): raft view + lane state
                        r.log.committed if r else -1,
                        r.state.name if r else "?",
                        int(node.fast_lane),
                    ]
                fl = nh.fastlane
                emit("HASHES", {
                    "rank": rank, "groups": out,
                    "dropped_spans": fl.dropped_spans if fl else 0,
                    "enrolled": (
                        fl.stats().get("enrolled_replicas", 0) if fl else 0
                    ),
                })
            elif cmd.startswith("PART "):
                # "PART <addr> <0|1>": (un)block the remote at the native
                # transport — a true netsplit over TCP (both planes ride
                # the native streams; see fastlane.set_partition).  A rank
                # without a fast lane must NOT ack success: the parent
                # would count a netsplit that was never injected.
                _, part_addr, on = cmd.split()
                # the reply echoes the command so the parent can match
                # acks to requests (a timed-out attempt's late ack must
                # not satisfy a LATER command's wait)
                if nh.fastlane is not None:
                    nh.fastlane.set_partition(part_addr, on == "1")
                    emit("PART", {"ok": True, "addr": part_addr, "on": on})
                else:
                    emit("PART", {"ok": False, "addr": part_addr, "on": on})
            elif cmd == "EXIT":
                break
    finally:
        stopped.set()
        hist_f.close()
        try:
            nh.stop()
        except Exception:
            pass
    return 0


# ------------------------------------------------------------------- parent


def _ports(n):
    out = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        out.append(s.getsockname()[1])
        s.close()
    return out


class Rank:
    def __init__(self, idx, env, logdir):
        self.idx = idx
        self.env = env
        self.logdir = logdir
        self.proc = None
        self.log = None
        self.lines = None

    def start(self):
        import queue as _q

        self.log = open(
            os.path.join(self.logdir, f"rank{self.idx}.{int(time.time())}.log"),
            "w",
        )
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--rank"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self.log, env=self.env, text=True,
        )
        self.lines = _q.Queue()

        def _reader(p, q):
            for ln in p.stdout:
                q.put(ln)
            q.put(None)

        threading.Thread(
            target=_reader, args=(self.proc, self.lines), daemon=True
        ).start()

    def expect(self, tag, timeout):
        import queue as _q

        deadline = time.time() + timeout
        while True:
            left = deadline - time.time()
            if left <= 0:
                raise TimeoutError(f"rank{self.idx}: no {tag} in {timeout}s")
            try:
                ln = self.lines.get(timeout=min(left, 1.0))
            except _q.Empty:
                continue
            if ln is None:
                raise RuntimeError(f"rank{self.idx} died waiting for {tag}")
            if ln.startswith(tag):
                rest = ln[len(tag):].strip()
                return json.loads(rest) if rest else None

    def send(self, cmd):
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.flush()

    def kill9(self):
        self.proc.kill()  # SIGKILL
        self.proc.wait()
        self.log.close()

    def pause(self):
        """SIGSTOP: the partition analog — the rank goes silent without
        dying (peers see timeouts; its own threads freeze mid-state)."""
        self.proc.send_signal(signal.SIGSTOP)

    def resume(self):
        self.proc.send_signal(signal.SIGCONT)

    def alive(self):
        return self.proc is not None and self.proc.poll() is None


def _converge_check(ranks, groups, timeout=90.0):
    """Pause load everywhere, wait for equal applied indices per group on
    every live rank, compare state hashes.  Returns the hash map or raises."""
    live = [r for r in ranks if r.alive()]
    for r in live:
        r.send("PAUSE")
    for r in live:
        r.expect("PAUSED", 30)
    deadline = time.time() + timeout
    last = None
    try:
        while True:
            reports = []
            for r in live:
                r.send("HASHES")
                reports.append(r.expect("HASHES", 30))
            for rep in reports:
                assert rep["dropped_spans"] == 0, (
                    f"rank{rep['rank']} dropped apply spans"
                )
            bad = []
            for cid in range(1, groups + 1):
                cells = [rep["groups"][str(cid)] for rep in reports]
                applied = {c[0] for c in cells}
                # manager + user SM + session store
                hashes = {tuple(c[1:4]) for c in cells}
                if len(applied) != 1 or len(hashes) != 1:
                    bad.append((cid, cells))
            last = bad
            if not bad:
                return reports
            if time.time() > deadline:
                for r in live:  # stack dumps into the rank logs
                    try:
                        r.proc.send_signal(signal.SIGUSR2)
                    except Exception:
                        pass
                time.sleep(1.0)
                raise AssertionError(
                    f"replicas diverged after {timeout}s settle: "
                    f"{len(bad)} groups, sample {bad[:3]}"
                )
            time.sleep(1.0)
    finally:
        for r in live:
            if r.alive():
                r.send("RESUME")
                r.expect("RESUMED", 30)


def _check_histories(base, groups):
    from dragonboat_tpu.linearizability import Op, check_linearizable

    INF = float("inf")
    ops = []
    for fn in sorted(os.listdir(base)):
        if not fn.startswith("history."):
            continue
        # write-ahead pairs: "inv" lines land BEFORE the op is issued,
        # "ret" lines after.  An inv with no ret (the rank was killed
        # mid-op, or its ret line was torn) is an op with UNKNOWN
        # outcome — a killed rank's committed-but-unrecorded put must
        # stay representable or other ranks' reads of it look phantom.
        pend = {}
        with open(os.path.join(base, fn)) as f:
            lines = f.readlines()
        for ln in lines:
            try:
                d = json.loads(ln)
            except json.JSONDecodeError:
                continue  # torn final line from a kill -9
            if d.get("ev") == "inv":
                pend[d["id"]] = d
            elif d.get("ev") == "ret":
                inv = pend.pop(d["id"], None)
                if inv is None:
                    continue  # ret whose inv line was torn: drop
                ops.append(Op(
                    client=inv["client"], kind=inv["kind"],
                    key=inv["key"], value=d["value"],
                    invoke=inv["invoke"],
                    ret=d["ret"] if d["ret"] is not None else INF,
                    ok=bool(d["ok"]),
                ))
        for inv in pend.values():  # unmatched: unknown outcome
            ops.append(Op(
                client=inv["client"], kind=inv["kind"], key=inv["key"],
                value=inv["value"], invoke=inv["invoke"],
                ret=INF, ok=False,
            ))
    ok, bad = check_linearizable(ops)
    return ok, bad, len(ops)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--groups", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--keep", action="store_true",
                    help="keep the run dir even on success")
    args = ap.parse_args()

    rng = random.Random(args.seed or int(time.time()))
    base = tempfile.mkdtemp(prefix="dbtpu-soak-")
    ports = _ports(3)
    addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
    print(f"# soak: {args.minutes} min, {args.groups} groups, dir {base}",
          file=sys.stderr)

    ranks = []
    for i in range(3):
        env = dict(os.environ)
        env.update({
            "SOAK_RANK": str(i), "SOAK_GROUPS": str(args.groups),
            "SOAK_ADDRS": addrs, "SOAK_DIR": base,
        })
        ranks.append(Rank(i, env, base))
    t0 = time.time()
    deadline = t0 + args.minutes * 60
    kills = 0
    pauses = 0
    splits = 0
    converges = 0
    failure = None
    try:
        for r in ranks:
            r.start()
        for r in ranks:
            r.expect("READY", 120)
        time.sleep(5.0)  # initial elections + load ramp

        next_kill = time.time() + rng.uniform(10, 25)
        next_pause = time.time() + rng.uniform(20, 35)
        next_split = time.time() + rng.uniform(25, 40)
        next_converge = time.time() + 30.0
        addr_list = addrs.split(",")

        def set_split(victim, on):
            """Symmetric netsplit {victim} | {others} at the native wire
            (the reference monkey's partitionTests shape).  Returns True
            when every live rank confirmed the change.  A rank that fails
            to HEAL is kill -9'd and restarted: its blocks live in process
            memory, so the restart clears them — a stale block would
            otherwise fail every later converge check with a misleading
            divergence report."""
            flag = "1" if on else "0"
            ok = True

            def apply_one(r):
                cmds = (
                    [a for j, a in enumerate(addr_list) if j != victim.idx]
                    if r is victim
                    else [addr_list[victim.idx]]
                )
                for a in cmds:
                    r.send(f"PART {a} {flag}")
                    # match the echoed command: a late ack from a timed-out
                    # earlier attempt must not satisfy this wait
                    deadline_ack = time.time() + 10
                    while True:
                        rep = r.expect("PART", max(0.1, deadline_ack - time.time()))
                        if rep and rep.get("addr") == a and rep.get("on") == flag:
                            break
                    if not rep.get("ok"):
                        raise RuntimeError("partition injection refused")

            for r in ranks:
                if not r.alive():
                    continue  # a killed rank holds no blocks
                for attempt in (1, 2):
                    try:
                        apply_one(r)
                        break
                    except Exception:
                        if attempt == 2:
                            ok = False
                            if not on and r.alive():
                                print(
                                    f"# rank{r.idx} failed to heal; "
                                    "kill -9 to clear its blocks",
                                    file=sys.stderr,
                                )
                                r.kill9()
                                time.sleep(1.0)
                                r.start()
                                r.expect("READY", 180)
            return ok
        while time.time() < deadline:
            time.sleep(1.0)
            now = time.time()
            if now >= next_pause:
                # partition-freeze fault: SIGSTOP a rank for 2-6s (long
                # enough to cross election timeouts sometimes), then wake
                # it into a world that moved on — exercises check-quorum,
                # elections without a crash, post-wake stale-term traffic
                # and fast-lane eject/re-enroll on both sides
                victim = rng.choice(ranks)
                dur = rng.uniform(2, 6)
                print(f"# t+{now - t0:.0f}s SIGSTOP rank{victim.idx} "
                      f"for {dur:.1f}s", file=sys.stderr)
                victim.pause()
                time.sleep(dur)
                victim.resume()
                pauses += 1
                next_pause = time.time() + rng.uniform(20, 45)
            if now >= next_split:
                victim = rng.choice(ranks)
                dur = rng.uniform(2, 8)
                print(f"# t+{now - t0:.0f}s netsplit rank{victim.idx} "
                      f"for {dur:.1f}s", file=sys.stderr)
                injected = set_split(victim, True)
                time.sleep(dur)
                set_split(victim, False)
                if injected:  # only count splits that actually happened
                    splits += 1
                next_split = time.time() + rng.uniform(25, 50)
            if now >= next_kill:
                victim = rng.choice(ranks)
                print(f"# t+{now - t0:.0f}s kill -9 rank{victim.idx}",
                      file=sys.stderr)
                victim.kill9()
                kills += 1
                time.sleep(rng.uniform(2, 8))
                victim.start()
                victim.expect("READY", 180)
                next_kill = time.time() + rng.uniform(15, 40)
            if now >= next_converge:
                print(f"# t+{now - t0:.0f}s converge check", file=sys.stderr)
                _converge_check(ranks, args.groups)
                converges += 1
                next_converge = time.time() + rng.uniform(30, 60)

        # final: settle, converge, stop cleanly, check histories
        print("# final converge", file=sys.stderr)
        reports = _converge_check(ranks, args.groups, timeout=120.0)
        converges += 1
        enrolled = [rep.get("enrolled", 0) for rep in reports]
        for r in ranks:
            if r.alive():
                r.send("EXIT")
        for r in ranks:
            try:
                r.proc.wait(timeout=20)
            except Exception:
                r.proc.kill()
        ok, bad, n_ops = _check_histories(base, args.groups)
        if not ok:
            failure = f"history not linearizable on keys {bad[:8]}"
    except Exception as e:  # noqa: BLE001 — summarize, keep artifacts
        failure = f"{type(e).__name__}: {e}"
        ok = False
        n_ops = 0
        enrolled = []
    finally:
        for r in ranks:
            try:
                if r.alive():
                    r.proc.kill()
            except Exception:
                pass

    summary = {
        "soak_ok": failure is None,
        "minutes": args.minutes,
        "groups": args.groups,
        "kills": kills,
        "pauses": pauses,
        "netsplits": splits,
        "converge_checks": converges,
        "history_ops": n_ops,
        "enrolled_final": enrolled,
        "error": failure,
        "artifacts": base if (failure or args.keep) else None,
    }
    if failure is None and not args.keep:
        import shutil

        shutil.rmtree(base, ignore_errors=True)
    print(json.dumps(summary))
    return 0 if failure is None else 1


if __name__ == "__main__":
    if "--rank" in sys.argv:
        sys.exit(rank_main())
    sys.exit(main())
