"""Test harness config: force a deterministic 8-device CPU platform.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (same XLA collectives, same GSPMD partitioner) — the driver
separately dry-run-compiles the multi-chip path via ``__graft_entry__``.
Must run before jax is imported anywhere.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the axon TPU plugin ignores JAX_PLATFORMS; PLATFORM_NAME still wins
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
