"""Test harness config: force a deterministic 8-device CPU platform.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (same XLA collectives, same GSPMD partitioner) — the driver
separately dry-run-compiles the multi-chip path via ``__graft_entry__``.

The environment ships a tunneled TPU backend ("axon") registered by a
``sitecustomize`` at interpreter startup — i.e. jax is already imported and
configured for the tunnel before this file runs.  Tests must neither run on
the tunnel (slow remote compiles) nor hang when it is down, so the platform
is forced to cpu via ``jax.config`` and the axon backend factory is
deregistered outright.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (already imported by sitecustomize anyway)

jax.config.update("jax_platforms", "cpu")
try:  # drop the tunneled backend so no code path can dial it
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: minutes-long scale tests (rung 4+ of the ladder)"
    )
