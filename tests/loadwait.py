"""Load-aware wall-clock margins for contention-sensitive tests.

The r07 tier-1 sweep carried 4 flakes that reproduce at HEAD under the
FULL suite on the 2-vCPU box but pass standalone — classic contention
flakes: the test's logic is sound, its wall-clock margin is calibrated
for an idle machine.  Raw ``time.sleep``/deadline thresholds turn
scheduler pressure into failures; this module replaces them with margins
that SCALE with the observed load (ISSUE 7 satellite).

Two primitives:

- :func:`scale` — a multiplier derived from the 1-minute loadavg per
  CPU, clamped to [1, 6].  An idle box changes nothing (factor 1.0); a
  box running the whole tier-1 sweep on 1-2 vCPUs stretches deadlines up
  to 6×.  Deliberately re-sampled per call: load changes over a long
  chaos test's lifetime.
- :func:`wait_until` — deadline polling with the scaled timeout and a
  descriptive AssertionError, for sites that used fixed sleep loops.

These widen only the TIMEOUT side.  Lower bounds (e.g. "the token
bucket must have throttled for >= X") must NOT be scaled — contention
can only make elapsed time longer, so a scaled lower bound would mask
real regressions.
"""
from __future__ import annotations

import os
import time

#: upper clamp: beyond ~6x the box is so oversubscribed that failures
#: are load signal the sweep SHOULD surface, not margins to absorb
MAX_SCALE = 6.0


def scale() -> float:
    """Wall-clock margin multiplier: 1-minute loadavg per CPU, clamped
    to [1, MAX_SCALE].  1.0 on an idle machine."""
    try:
        la = os.getloadavg()[0]
    except OSError:  # platform without getloadavg
        return 1.0
    cpus = os.cpu_count() or 1
    return min(MAX_SCALE, max(1.0, la / cpus))


def scaled(seconds: float) -> float:
    """A deadline/timeout stretched by the current load factor."""
    return seconds * scale()


def wait_until(pred, timeout: float, interval: float = 0.05, what: str = ""):
    """Poll ``pred`` until truthy; the deadline is ``scaled(timeout)``,
    RE-SAMPLED while waiting.  Returns the predicate's value; raises
    AssertionError on timeout.

    The re-sampling closes the r14 flake window: a budget computed once
    at entry underprices waits that START on a momentarily-idle box and
    then share it with a heavy neighbor spinning up (the test_lease
    live-tpu site failed at "67.8s (load 3.04)" — the 60s base was
    scaled by the ~1.1 load of the instant it began).  The budget only
    ever GROWS toward ``timeout * current_scale``, so idle-box behavior
    and the no-scaled-lower-bounds rule are unchanged."""
    start = time.time()
    budget = scaled(timeout)
    while True:
        v = pred()
        if v:
            return v
        budget = max(budget, timeout * scale())
        if time.time() - start >= budget:
            raise AssertionError(
                f"{what or 'condition'} not reached within "
                f"{budget:.1f}s (base {timeout:.1f}s x load {scale():.2f})"
            )
        time.sleep(interval)


def ports(n: int):
    """``n`` distinct ephemeral 127.0.0.1 ports for in-proc TCP hosts.

    All sockets stay open until every port is collected: the historical
    close-then-rebind loop let the OS hand the same ephemeral port out
    twice under a loaded sweep (observed r14: two ranks launched on one
    port, ``check_launch_request`` duplicate-address rejection)."""
    import socket

    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()
