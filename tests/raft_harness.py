"""etcd-style in-memory multi-node raft test harness.

Models the network/black-hole harness used by the reference's ported etcd
conformance tests (``internal/raft/raft_etcd_test.go``): a set of Raft state
machines wired through an in-memory message router with drop/isolate/cut
controls.  Deterministic: peers are stepped in sorted id order and all
randomness comes from per-node seeded PRNGs.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from dragonboat_tpu.config import Config
from dragonboat_tpu.raft import InMemLogDB, Raft
from dragonboat_tpu.raft.raft import RaftState
from dragonboat_tpu.wire import Entry, Message, MessageType

MT = MessageType


def new_test_config(
    node_id: int, election: int = 10, heartbeat: int = 1, check_quorum: bool = False
) -> Config:
    return Config(
        node_id=node_id,
        cluster_id=1,
        election_rtt=election,
        heartbeat_rtt=heartbeat,
        check_quorum=check_quorum,
    )


def new_test_raft(
    node_id: int,
    peers: List[int],
    election: int = 10,
    heartbeat: int = 1,
    logdb: Optional[InMemLogDB] = None,
    check_quorum: bool = False,
    seed: int = 0,
) -> Raft:
    logdb = logdb or InMemLogDB()
    r = Raft(
        new_test_config(node_id, election, heartbeat, check_quorum),
        logdb,
        seed=seed + node_id,
    )
    for p in peers:
        if p not in r.remotes:
            r.remotes[p] = __import__(
                "dragonboat_tpu.raft.remote", fromlist=["Remote"]
            ).Remote(next=1)
    r.reset_match_value_array()
    # the reference exposes this test-only hook to ease porting the etcd
    # conformance suite (raft.go:1463-1469): the harness applies nothing, so
    # the committed>applied campaign guard would otherwise always trip
    r.has_not_applied_config_change = lambda: False
    return r


def ents_with_config(terms: List[int], node_id: int = 1) -> Raft:
    """Raft whose stable log holds one entry per term in ``terms``
    (reference ``entsWithConfig`` raft_etcd_test.go:2790)."""
    storage = InMemLogDB()
    for i, term in enumerate(terms):
        storage.append([Entry(index=i + 1, term=term)])
    r = Raft(new_test_config(node_id, 5, 1), storage, seed=node_id)
    r.reset(terms[-1])
    return r


def voted_with_config(vote: int, term: int, node_id: int = 1) -> Raft:
    """Raft that voted in ``term`` but has an empty log (reference
    ``votedWithConfig`` raft_etcd_test.go:2809)."""
    from dragonboat_tpu.wire import State

    storage = InMemLogDB()
    storage.set_state(State(vote=vote, term=term))
    r = Raft(new_test_config(node_id, 5, 1), storage, seed=node_id)
    r.reset(term)
    return r


class BlackHole:
    """Drops everything (etcd's nopStepper)."""

    node_id = -1

    def handle(self, m: Message) -> None:
        pass

    @property
    def msgs(self) -> List[Message]:
        return []


class Network:
    """Reference etcd `network` harness."""

    def __init__(self, *peers, election: int = 10, heartbeat: int = 1):
        from dragonboat_tpu.raft.remote import Remote

        self.peers: Dict[int, object] = {}
        self.storage: Dict[int, InMemLogDB] = {}
        self.dropm: Dict[Tuple[int, int], float] = {}
        self.ignorem: Dict[MessageType, bool] = {}
        size = len(peers)
        ids = list(range(1, size + 1))
        for i, p in enumerate(peers):
            nid = ids[i]
            if p is None:
                logdb = InMemLogDB()
                self.storage[nid] = logdb
                self.peers[nid] = new_test_raft(
                    nid, ids, election, heartbeat, logdb
                )
            elif isinstance(p, BlackHole):
                self.peers[nid] = p
            elif isinstance(p, Raft):
                # reference newNetworkWithConfig's *raft branch
                # (raft_etcd_test.go:2858-2881): rebuild the peer sets for
                # this network's id space, keeping observer/witness marks
                observers = set(p.observers)
                witnesses = set(p.witnesses)
                p.node_id = nid
                p.remotes = {}
                p.observers = {}
                p.witnesses = {}
                for pid in ids:
                    if pid in observers:
                        p.observers[pid] = Remote()
                    elif pid in witnesses:
                        p.witnesses[pid] = Remote()
                    else:
                        p.remotes[pid] = Remote()
                p.reset(p.term)
                if isinstance(p.log.logdb, InMemLogDB):
                    self.storage[nid] = p.log.logdb
                self.peers[nid] = p
            else:
                raise TypeError(f"unexpected peer type {type(p)}")

    def raft(self, nid: int) -> Raft:
        p = self.peers[nid]
        assert isinstance(p, Raft)
        return p

    def send(self, *msgs: Message) -> None:
        queue = list(msgs)
        while queue:
            m = queue.pop(0)
            p = self.peers.get(m.to)
            if p is None:
                continue
            p.handle(m)
            if isinstance(p, Raft):
                queue.extend(self.filter(self.take_msgs(p)))

    def take_msgs(self, r: Raft) -> List[Message]:
        msgs = read_messages(r)
        for m in msgs:
            m.cluster_id = 1
        return msgs

    def drop(self, from_: int, to: int, perc: float) -> None:
        self.dropm[(from_, to)] = perc

    def cut(self, one: int, other: int) -> None:
        self.drop(one, other, 1.0)
        self.drop(other, one, 1.0)

    def isolate(self, nid: int) -> None:
        for i in self.peers:
            if i != nid:
                self.cut(nid, i)

    def ignore(self, t: MessageType) -> None:
        self.ignorem[t] = True

    def recover(self) -> None:
        self.dropm = {}
        self.ignorem = {}

    def filter(self, msgs: List[Message]) -> List[Message]:
        out = []
        for m in msgs:
            if self.ignorem.get(m.type):
                continue
            if m.type == MT.ELECTION:
                raise RuntimeError("unexpected Election message")
            perc = self.dropm.get((m.from_, m.to), 0.0)
            if perc >= 1.0:
                continue
            out.append(m)
        return out


def campaign(r: Raft) -> Message:
    """Fire an Election message locally (what a timeout would do)."""
    return Message(from_=r.node_id, to=r.node_id, type=MT.ELECTION)


def propose(nid: int, data: bytes = b"somedata") -> Message:
    return Message(
        from_=nid, to=nid, type=MT.PROPOSE, entries=[Entry(cmd=data)]
    )


def readindex(nid: int, low: int = 1, high: int = 1) -> Message:
    return Message(from_=nid, to=nid, type=MT.READ_INDEX, hint=low, hint_high=high)


def tick_until_election(r: Raft) -> None:
    """Tick a raft node just past its randomized election timeout."""
    for _ in range(r.randomized_election_timeout + 1):
        r.tick()


def ids_by_size(size: int) -> List[int]:
    return list(range(1, size + 1))


def read_messages(r: Raft) -> List[Message]:
    """Drain a raft node's outbox (reference etcd readMessages)."""
    msgs = r.msgs
    r.msgs = []
    return msgs


def accept_and_reply(m: Message) -> Message:
    """Acknowledge a Replicate as fully appended (etcd acceptAndReply)."""
    assert m.type == MT.REPLICATE, m.type
    return Message(
        from_=m.to,
        to=m.from_,
        term=m.term,
        type=MT.REPLICATE_RESP,
        log_index=m.log_index + len(m.entries),
    )


def commit_noop_entry(r: Raft, s: InMemLogDB) -> None:
    """Replicate + commit the noop the leader appended on promotion, then
    mark it saved/processed (etcd commitNoopEntry)."""
    from dragonboat_tpu.wire import UpdateCommit

    assert r.is_leader(), "commit_noop_entry requires a leader"
    r.broadcast_replicate_message()
    for m in read_messages(r):
        assert (
            m.type == MT.REPLICATE
            and len(m.entries) == 1
            and not m.entries[0].cmd
        ), "not a noop append"
        r.handle(accept_and_reply(m))
    read_messages(r)  # drop commit-refresh broadcasts
    s.append(r.log.entries_to_save())
    r.log.commit_update(
        UpdateCommit(
            processed=r.log.committed,
            stable_log_to=r.log.last_index(),
            stable_log_term=r.log.last_term(),
        )
    )


NO_LIMIT = 1 << 62


def get_all_entries(log) -> List:
    """Every entry currently in the log view (etcd getAllEntries)."""
    if log.last_index() < log.first_index():
        return []
    return log.get_entries(log.first_index(), log.last_index() + 1, NO_LIMIT)


def ent_sig(entries) -> List[Tuple[int, int]]:
    """(term, index) signature list for log-content comparisons."""
    return [(e.term, e.index) for e in entries]


def logs_equal(a, b) -> bool:
    """Full log-view equality: committed watermark + entry signatures
    (the etcd ltoa/diffu check)."""
    return (
        a.committed == b.committed
        and ent_sig(get_all_entries(a)) == ent_sig(get_all_entries(b))
    )


__all__ = [
    "BlackHole",
    "Network",
    "RaftState",
    "accept_and_reply",
    "campaign",
    "commit_noop_entry",
    "ent_sig",
    "get_all_entries",
    "ids_by_size",
    "logs_equal",
    "new_test_config",
    "new_test_raft",
    "propose",
    "read_messages",
    "readindex",
    "tick_until_election",
]
