"""Keep bench_micro.py honest: every section must produce numbers, not
an error dict (the runner swallows per-section exceptions so one broken
probe can't hide the rest — which also means API drift would rot
silently without this gate).  Every section runs for real, including
both durable LogDB variants."""
from __future__ import annotations

import bench_micro


def test_cheap_sections_produce_numbers():
    for name in ("entry_queue", "pending_proposal", "marshal_entry",
                 "transport_framing", "sm_step"):
        fn = dict(bench_micro.SECTIONS)[name]
        out = fn()
        assert "error" not in out, (name, out)
        assert any(
            isinstance(v, (int, float)) for v in out.values()
        ), (name, out)


def test_logdb_and_fsync_sections():
    out = bench_micro.bench_logdb_save(False)
    assert "error" not in out and out, out
    out = bench_micro.bench_logdb_save(True)
    assert "error" not in out and out, out
    out = bench_micro.bench_fsync()
    assert out.get("ops_s", 0) > 0, out


def test_encoded_and_natsm_sections():
    out = bench_micro.bench_encoded_payload()
    assert "error" not in out, out
    out = bench_micro.bench_natsm_update()
    assert "error" not in out, out
