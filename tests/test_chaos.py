"""Chaos/monkey tests: partitions + restarts under concurrent clients,
verified by cross-replica state hashes and a linearizability check.

Reference model: the monkey-test harness described in SURVEY.md §4.5
(partition injection, kill/restart, Jepsen Knossos/porcupine history
checking, cross-replica hash comparison via rsm.GetHash).
"""
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu import monkey
from dragonboat_tpu.linearizability import (
    INF,
    HistoryRecorder,
    Op,
    check_linearizable,
)
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT_MS = 5
CID = 42


# ---------------------------------------------------------------------------
# checker unit tests (hand-built histories)
# ---------------------------------------------------------------------------


def test_checker_accepts_sequential_history():
    h = [
        Op(1, "put", "k", "1", 0.0, 1.0),
        Op(1, "get", "k", "1", 2.0, 3.0),
        Op(1, "put", "k", "2", 4.0, 5.0),
        Op(1, "get", "k", "2", 6.0, 7.0),
    ]
    ok, bad = check_linearizable(h)
    assert ok, bad


def test_checker_accepts_concurrent_overlap():
    # get overlapping a put may see either value
    h = [
        Op(1, "put", "k", "1", 0.0, 10.0),
        Op(2, "get", "k", None, 1.0, 2.0),
        Op(3, "get", "k", "1", 3.0, 4.0),
    ]
    ok, bad = check_linearizable(h)
    assert ok, bad


def test_checker_rejects_stale_read():
    # put completed before the get started, but the get saw the old value
    h = [
        Op(1, "put", "k", "1", 0.0, 1.0),
        Op(2, "get", "k", None, 2.0, 3.0),
    ]
    ok, bad = check_linearizable(h)
    assert not ok and bad == ["k"]


def test_checker_rejects_value_from_nowhere():
    h = [
        Op(1, "put", "k", "1", 0.0, 1.0),
        Op(2, "get", "k", "99", 2.0, 3.0),
    ]
    ok, _ = check_linearizable(h)
    assert not ok


def test_checker_allows_unknown_put_to_be_unapplied():
    # timed-out put (ret=INF) may never take effect
    h = [
        Op(1, "put", "k", "1", 0.0, 1.0),
        Op(2, "put", "k", "2", 2.0, INF, ok=False),
        Op(3, "get", "k", "1", 3.0, 4.0),
    ]
    ok, bad = check_linearizable(h)
    assert ok, bad


def test_checker_allows_unknown_put_to_be_applied():
    h = [
        Op(1, "put", "k", "1", 0.0, 1.0),
        Op(2, "put", "k", "2", 2.0, INF, ok=False),
        Op(3, "get", "k", "2", 3.0, 4.0),
    ]
    ok, bad = check_linearizable(h)
    assert ok, bad


def test_checker_rejects_read_reordering():
    # two sequential gets observing values in an order no serialization of
    # the two sequential puts can produce
    h = [
        Op(1, "put", "k", "1", 0.0, 1.0),
        Op(1, "put", "k", "2", 2.0, 3.0),
        Op(2, "get", "k", "2", 4.0, 5.0),
        Op(2, "get", "k", "1", 6.0, 7.0),
    ]
    ok, _ = check_linearizable(h)
    assert not ok


# ---------------------------------------------------------------------------
# live chaos run
# ---------------------------------------------------------------------------


class KVSM(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.kv = {}
        self.count = 0

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = repr(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import ast

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(ast.literal_eval(r.read(n).decode()))
        self.count = len(self.kv)

    def close(self):
        pass


def _mk_nh(addr, router):
    return NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                src, rh, ch, router=router
            ),
        )
    )


def _wait_leader(nhs, cid, timeout=15.0):
    # load-scaled deadline (tests/loadwait.py): the r07 contention-flake
    # class — sound standalone, starved under the full sweep
    from tests.loadwait import scaled

    deadline = time.time() + scaled(timeout)
    while time.time() < deadline:
        for nh in nhs:
            _, ok = nh.get_leader_id(cid)
            if ok:
                return
        time.sleep(0.01)
    raise TimeoutError("no leader")


@pytest.mark.slow
def test_chaos_partitions_with_linearizability():
    """Random minority partitions + drop-rate churn under concurrent
    clients; afterwards replicas must converge to identical hashes and the
    recorded history must be linearizable."""
    router = ChanRouter()
    addrs = {i: f"cn{i}:1" for i in (1, 2, 3)}
    nhs = [_mk_nh(addrs[i], router) for i in (1, 2, 3)]
    rec = HistoryRecorder()
    stop = threading.Event()
    try:
        for nh in nhs:
            nh.start_cluster(
                addrs, False, KVSM,
                Config(
                    cluster_id=CID,
                    node_id=int(nh.raft_address()[2]),
                    election_rtt=10,
                    heartbeat_rtt=1,
                    check_quorum=True,
                ),
            )
        _wait_leader(nhs, CID)

        def client(tid: int) -> None:
            nh = nhs[tid % len(nhs)]
            session = nh.get_noop_session(CID)
            i = 0
            while not stop.is_set():
                key = f"key-{tid}-{i % 64}"
                i += 1
                if i % 3 == 0:
                    done = rec.invoke(tid, "get", key, None)
                    try:
                        v = nh.sync_read(CID, key, timeout=2.0)
                        done(v)
                    except Exception:
                        done(unknown=True)
                else:
                    val = str(i)
                    done = rec.invoke(tid, "put", key, val)
                    try:
                        nh.sync_propose(session, f"{key}={val}".encode(), 2.0)
                        done(True)
                    except Exception:
                        done(unknown=True)

        clients = [
            threading.Thread(target=client, args=(t,), daemon=True)
            for t in range(4)
        ]
        for c in clients:
            c.start()

        inj = monkey.PartitionInjector(router, list(addrs.values()), seed=7)
        t_end = time.time() + 6.0
        while time.time() < t_end:
            minority = inj.partition_random_minority()
            time.sleep(0.4)
            inj.heal_all()
            monkey.set_drop_rate(router, 0.05, seed=13)
            time.sleep(0.3)
            monkey.set_drop_rate(router, 0.0)
            assert minority  # chaos actually ran

        stop.set()
        for c in clients:
            c.join(timeout=10)
        # settle: heal, one barrier write, wait replicas to catch up
        inj.heal_all()
        monkey.set_drop_rate(router, 0.0)
        _wait_leader(nhs, CID)
        barrier_done = rec.invoke(99, "put", "barrier", "1")
        for attempt in range(20):
            try:
                s = nhs[0].get_noop_session(CID)
                nhs[0].sync_propose(s, b"barrier=1", timeout=3.0)
                barrier_done(True)
                break
            except Exception:
                time.sleep(0.3)
        else:
            barrier_done(unknown=True)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                monkey.assert_replicas_converged(nhs, CID)
                break
            except AssertionError:
                time.sleep(0.2)
        monkey.assert_replicas_converged(nhs, CID)

        history = rec.history()
        assert len(history) > 50, "chaos produced too little history"
        ok, bad = check_linearizable(history)
        assert ok, f"non-linearizable keys: {bad}"
    finally:
        stop.set()
        for nh in nhs:
            nh.stop()


@pytest.mark.slow
def test_chaos_node_restart_rejoins_and_converges():
    """Kill one replica's node (stop_cluster) mid-traffic, restart it, and
    require convergence — the restart path under load."""
    router = ChanRouter()
    addrs = {i: f"rn{i}:1" for i in (1, 2, 3)}
    nhs = [_mk_nh(addrs[i], router) for i in (1, 2, 3)]
    try:
        for i, nh in enumerate(nhs, start=1):
            nh.start_cluster(
                addrs, False, KVSM,
                Config(
                    cluster_id=CID, node_id=i,
                    election_rtt=10, heartbeat_rtt=1,
                ),
            )
        _wait_leader(nhs, CID)
        s = nhs[0].get_noop_session(CID)

        def propose_ok(cmd, tries=10):
            for _ in range(tries):
                try:
                    nhs[0].sync_propose(s, cmd, timeout=3.0)
                    return
                except Exception:
                    time.sleep(0.2)
            raise TimeoutError(f"could not commit {cmd!r}")

        for i in range(10):
            propose_ok(f"a{i}=1".encode())
        # stop replica 3 (may be the leader: the survivors must re-elect),
        # keep writing through the remaining quorum
        nhs[2].stop_cluster(CID)
        for i in range(10):
            propose_ok(f"b{i}=1".encode())
        # restart replica 3: bootstrap record exists, so empty initial
        # members + join=False is the reference restart idiom
        nhs[2].start_cluster(
            {}, False, KVSM,
            Config(cluster_id=CID, node_id=3, election_rtt=10, heartbeat_rtt=1),
        )
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                monkey.assert_replicas_converged(nhs, CID)
                break
            except Exception:
                time.sleep(0.2)
        monkey.assert_replicas_converged(nhs, CID)
    finally:
        for nh in nhs:
            nh.stop()
