"""Multi-group kill/restart chaos over TCP: 48 groups × 3 replicas with
the fast lane AND the native C-ABI state machine on every replica.

The single-group chaos matrix (test_chaos_tcp.py) checks protocol
liveness; the soak driver (soak.py) runs minutes-long.  This test sits
between them at CI time: the reference's published 3-server shape
(48 groups, ``docs/test.md:47``) with leaders spread across hosts, a
follower kill/restart and a host kill that deposes a THIRD of the
leaders at once, continuous load on every group, and cross-replica
state-hash equality on every group at the end (``monkey.py`` hashes ≙
``monkey.go:110-144``).

Progress-gated throughout (no fixed-rate asserts — VERDICT r3 weak #7).
"""
from __future__ import annotations

import socket

from tests import loadwait
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.monkey import get_state_hash
from dragonboat_tpu.native import natraft, natsm

# heavy multi-NodeHost tests serialize on one xdist worker
# (--dist loadgroup): 4-way-parallel multiprocess clusters
# starve each other on an 8-vCPU box
pytestmark = [pytest.mark.skipif(
    not natraft.available(), reason="libnatraft unavailable"
), pytest.mark.xdist_group("heavy-multiprocess")]

RTT = 20
GROUPS = 48


def _ports(n):
    return loadwait.ports(n)


def _mk(i, addrs, tmp_path):
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / f"nh{i}"),
            rtt_millisecond=RTT,
            raft_address=addrs[i],
            expert=ExpertConfig(fast_lane=True, logdb_shards=2),
        )
    )

    def create(cluster_id, node_id):
        return natsm.NativeKVStateMachine(cluster_id, node_id)

    for g in range(GROUPS):
        nh.start_cluster(
            addrs, False, create,
            Config(cluster_id=100 + g, node_id=i, election_rtt=10,
                   heartbeat_rtt=1, snapshot_entries=0,
                   compaction_overhead=5),
        )
    return nh


def _spread_leaders(nhs, timeout=90.0):
    """One leader per group, striped across hosts (the e2e bench's
    placement); returns when every group has SOME leader.  The deadline
    is load-scaled: this module is one of the r07 contention flakes —
    sound under an idle box, starved under the full tier-1 sweep."""
    from tests.loadwait import scaled

    timeout = scaled(timeout)
    for g in range(GROUPS):
        target = 1 + (g % 3)
        try:
            nhs[target].get_node(100 + g).request_campaign()
        except Exception:
            pass
    deadline = time.time() + timeout
    led = set()
    while time.time() < deadline and len(led) < GROUPS:
        for g in range(GROUPS):
            if g in led:
                continue
            for nh in nhs.values():
                lid, ok = nh.get_leader_id(100 + g)
                if ok and lid in nhs:
                    led.add(g)
                    break
        time.sleep(0.1)
    assert len(led) == GROUPS, f"only {len(led)}/{GROUPS} groups led"


def _wait_total(counts, target, timeout=240.0, what="load"):
    from tests.loadwait import wait_until

    wait_until(
        lambda: sum(counts.values()) >= target, timeout, interval=0.1,
        what=f"{what}: {target} completed writes",
    )


def test_multigroup_kill_restart_hash_equal(tmp_path):
    ports = _ports(3)
    addrs = {i: f"127.0.0.1:{p}" for i, p in enumerate(ports, start=1)}
    nhs = {i: _mk(i, addrs, tmp_path) for i in (1, 2, 3)}
    stop = threading.Event()
    counts = {g: 0 for g in range(GROUPS)}

    def load(worker):
        rng_groups = [g for g in range(GROUPS) if g % 4 == worker % 4]
        sessions = {}
        j = 0
        while not stop.is_set():
            g = rng_groups[j % len(rng_groups)]
            j += 1
            cid = 100 + g
            # route to the current leader's host (snapshot: the main
            # thread kills/restores hosts while we iterate)
            leader = None
            for nh in list(nhs.values()):
                try:
                    lid, ok = nh.get_leader_id(cid)
                    if ok:
                        leader = nhs.get(lid)
                        break
                except Exception:
                    pass
            if leader is None:
                time.sleep(0.02)
                continue
            try:
                s = sessions.get((id(leader), cid))
                if s is None:
                    s = leader.get_noop_session(cid)
                    sessions[(id(leader), cid)] = s
                rs = leader.propose(
                    s, b"k%d=v%d" % (j % 64, j), timeout=15.0
                )
                if rs.wait(15.0).completed:
                    counts[g] += 1
            except Exception:
                time.sleep(0.02)

    try:
        _spread_leaders(nhs)
        workers = [
            threading.Thread(target=load, args=(w,), daemon=True)
            for w in range(4)
        ]
        for t in workers:
            t.start()
        _wait_total(counts, 120, what="warm-up")

        # --- kill host 2 (deposing ~a third of the leaders at once) ---
        nhs[2].stop()
        del nhs[2]
        base = sum(counts.values())
        # every group must keep committing on the surviving 2/3 quorum
        _wait_total(counts, base + 150, what="2/3-quorum")
        nhs[2] = _mk(2, addrs, tmp_path)
        base = sum(counts.values())
        _wait_total(counts, base + 150, what="post-restart")

        stop.set()
        for t in workers:
            t.join(timeout=15)
            assert not t.is_alive(), "load worker failed to stop"

        # --- every group: replicas converge to identical state hashes ---
        from tests.loadwait import scaled

        deadline = time.time() + scaled(120)
        lagging = dict.fromkeys(range(GROUPS))
        while lagging and time.time() < deadline:
            for g in list(lagging):
                hashes = []
                for nh in nhs.values():
                    try:
                        hashes.append(get_state_hash(nh, 100 + g))
                    except Exception:
                        hashes.append(None)
                if None not in hashes and len(set(hashes)) == 1:
                    del lagging[g]
            time.sleep(0.25)
        assert not lagging, (
            f"{len(lagging)} groups never converged: {sorted(lagging)[:8]}"
        )
        # sanity: every group CAN commit.  Drive any zero-count group
        # directly — the round-robin load gates on TOTAL progress, so on
        # a throttled box one group can starve behind a worker's 15s
        # timeout storms while being perfectly healthy (its convergence
        # check above already passed); asserting the counter would flake
        # on scheduling, not on correctness.
        for g in range(GROUPS):
            if counts[g]:
                continue
            cid = 100 + g
            deadline = time.time() + scaled(60)
            ok = False
            while time.time() < deadline and not ok:
                for nh in list(nhs.values()):
                    try:
                        lid, okl = nh.get_leader_id(cid)
                        if not okl or nhs.get(lid) is None:
                            continue
                        leader = nhs[lid]
                        s = leader.get_noop_session(cid)
                        rs = leader.propose(s, b"sanity=1", timeout=15.0)
                        if rs.wait(15.0).completed:
                            ok = True
                            break
                    except Exception:
                        pass
                time.sleep(0.1)
            assert ok, f"group {g} cannot commit (counts={counts})"
    finally:
        stop.set()
        for nh in nhs.values():
            try:
                nh.stop()
            except Exception:
                pass
