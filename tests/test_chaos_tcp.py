"""Kill/restart chaos under load over the real TCP transport.

VERDICT r2 weak #8 wanted kill/restart under load over TCP; VERDICT r3
item 5 widens it to the full engine matrix: [scalar, fastlane, tpu,
tpu+fastlane], each run checked with BOTH a linearizability pass over a
recorded shared-key history (Wing & Gong via ``linearizability.py`` — the
reference's Jepsen/Knossos role, ``docs/test.md:6,11-36``) and
cross-replica state-hash equality (``monkey.py`` ≙ ``monkey.go:110-144``).

The scenario: a 3-replica group over framed TCP with durable storage;
a follower is stopped and restarted under client load, then the leader is
killed; a new leader must take over, the restarted replicas must catch
up, and a linearizable read must see the newest write (the round-3
fast-lane liveness bug wedged exactly here).
"""
from __future__ import annotations

import socket

from tests import loadwait
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.linearizability import HistoryRecorder, check_linearizable
from dragonboat_tpu.monkey import get_applied_index, get_state_hash

# heavy multi-NodeHost tests serialize on one xdist worker
# (--dist loadgroup): 4-way-parallel multiprocess clusters
# starve each other on an 8-vCPU box
pytestmark = pytest.mark.xdist_group("heavy-multiprocess")


RTT = 20
CID = 9
SHARED_KEYS = ["x0", "x1", "x2", "x3"]

# engine matrix: (quorum_engine, fast_lane)
MODES = {
    "scalar": ("scalar", False),
    "fastlane": ("scalar", True),
    "tpu": ("tpu", False),
    "tpu+fastlane": ("tpu", True),
}


class KVSM:
    def __init__(self, cluster_id, node_id):
        self.kv = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def get_hash(self):
        import zlib

        return zlib.crc32(repr(sorted(self.kv.items())).encode())

    def save_snapshot(self, w, files, done):
        import json

        data = json.dumps(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import json

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(json.loads(r.read(n).decode()))

    def close(self):
        pass


def _ports(n):
    return loadwait.ports(n)


def _mk(i, addrs, tmp_path, sms, mode):
    from dragonboat_tpu.config import ExpertConfig

    engine, fast_lane = MODES[mode]
    # the scalar variant keeps the original default configuration; the
    # fast-lane variants narrow the shard count (fewer fds/threads)
    expert = ExpertConfig(
        quorum_engine=engine,
        fast_lane=fast_lane,
        logdb_shards=2 if fast_lane else 4,
    )
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / f"nh{i}"),
            rtt_millisecond=RTT,
            raft_address=addrs[i],
            expert=expert,
        )
    )

    def create(cluster_id, node_id):
        sm = KVSM(cluster_id, node_id)
        sms[i] = sm
        return sm

    nh.start_cluster(
        addrs, False, create,
        Config(cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
               snapshot_entries=25, compaction_overhead=5),
    )
    return nh


def _leader(nhs, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs.values():
            try:
                lid, ok = nh.get_leader_id(CID)
                if ok and lid in nhs:
                    return lid, nhs[lid]
            except Exception:
                pass
        time.sleep(0.05)
    raise AssertionError("no leader")


def _wait_writes(written, target, timeout=60.0, what="load"):
    """Block until the client has completed ``target`` writes.

    Progress-gated instead of sleep-gated: on a loaded CI box the write
    rate varies by an order of magnitude, so asserting a fixed count after
    a fixed sleep is exactly the load-dependent flake VERDICT r3 weak #7
    bans.  Here load only stretches the wait (up to a generous deadline),
    never the verdict.
    """
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(written) >= target:
            return
        time.sleep(0.05)
    raise AssertionError(
        f"{what}: stalled at {len(written)}/{target} writes after {timeout}s"
    )


@pytest.mark.parametrize("mode", list(MODES), ids=list(MODES))
def test_kill_restart_under_load_over_tcp(tmp_path, mode):
    fast_lane = MODES[mode][1]
    addrs = {i: f"127.0.0.1:{p}" for i, p in enumerate(_ports(3), start=1)}
    sms = {}
    nhs = {i: _mk(i, addrs, tmp_path, sms, mode) for i in (1, 2, 3)}
    stop_load = threading.Event()
    written = []
    rec = HistoryRecorder()

    def load():
        """Single client thread: monotonic puts on k{j} for progress
        tracking, plus a shared-key put/get mix whose recorded history
        feeds the linearizability checker."""
        j = 0
        while not stop_load.is_set():
            j += 1
            try:
                lid, leader = _leader(nhs, timeout=10.0)
                s = leader.get_noop_session(CID)
                rs = leader.propose(s, f"k{j}=v{j}".encode(), timeout=5.0)
                if rs.wait(5.0).completed:
                    written.append(j)
                else:
                    continue
                key = SHARED_KEYS[j % len(SHARED_KEYS)]
                if j % 3:
                    done = rec.invoke(0, "put", key, f"s{j}")
                    rs = leader.propose(
                        s, f"{key}=s{j}".encode(), timeout=5.0
                    )
                    r = rs.wait(5.0)
                    done(True) if r.completed else done(unknown=True)
                else:
                    done = rec.invoke(0, "get", key, None)
                    try:
                        v = leader.sync_read(CID, key, timeout=5.0)
                        done(v)
                    except Exception:
                        done(unknown=True)
            except Exception:
                time.sleep(0.05)

    try:
        nhs[1].get_node(CID).request_campaign()
        _leader(nhs)
        t = threading.Thread(target=load, daemon=True)
        t.start()
        _wait_writes(written, 10, what="warm-up")

        # --- stop a follower under load, keep writing, restart it ---
        lid, _ = _leader(nhs)
        follower_id = next(i for i in (1, 2, 3) if i != lid)
        nhs[follower_id].stop()
        del nhs[follower_id]
        # writes must continue on the 2/3 quorum
        _wait_writes(written, len(written) + 15, what="2/3-quorum")
        mid_progress = len(written)
        nhs[follower_id] = _mk(follower_id, addrs, tmp_path, sms, mode)
        _wait_writes(written, mid_progress + 15, what="post-restart")

        # --- stop the LEADER under load; a new leader must take over ---
        lid, _ = _leader(nhs)
        nhs[lid].stop()
        del nhs[lid]
        new_lid, _ = _leader(nhs, timeout=60.0)
        assert new_lid != lid
        pre_failover = len(written)
        nhs[lid] = _mk(lid, addrs, tmp_path, sms, mode)
        # writes must resume under the new leader
        _wait_writes(written, pre_failover + 15, what="post-failover")

        stop_load.set()
        t.join(timeout=15)
        # progress itself was enforced by the _wait_writes gates above;
        # here assert the load thread actually stopped (a wedged client
        # would hang in a 10s sync path and miss the join window)
        assert not t.is_alive(), "load thread failed to stop"

        # --- convergence: linearizable read sees the newest write and all
        # replicas converge on it ---
        last = written[-1]
        v = None
        for attempt in range(2):  # one retry: a post-churn leader may
            try:                  # still be settling; clients retry
                _, leader = _leader(nhs)
                v = leader.sync_read(CID, f"k{last}", timeout=20.0)
                break
            except Exception:
                if attempt:
                    raise
                time.sleep(3.0)
        assert v == f"v{last}"
        deadline = time.time() + 60
        while time.time() < deadline:
            vals = {i: sms[i].kv.get(f"k{last}") for i in (1, 2, 3)}
            if all(x == f"v{last}" for x in vals.values()):
                break
            time.sleep(0.2)
        assert all(
            sms[i].kv.get(f"k{last}") == f"v{last}" for i in (1, 2, 3)
        ), {i: len(sms[i].kv) for i in (1, 2, 3)}

        # --- linearizability over the recorded shared-key history ---
        ok, bad = check_linearizable(rec.history())
        assert ok, f"history not linearizable on keys {bad}"

        # --- cross-replica hash equality (monkey.go:110-144 role) ---
        deadline = time.time() + 30
        while time.time() < deadline:
            applied = {get_applied_index(nh, CID) for nh in nhs.values()}
            if len(applied) == 1:
                break
            time.sleep(0.2)
        hashes = {i: get_state_hash(nh, CID) for i, nh in nhs.items()}
        assert len(set(hashes.values())) == 1, f"state hashes diverged: {hashes}"
        # the manager hash covers sessions+applied+membership; compare the
        # user SM state itself too (reference kvtest.go GetHash role)
        kv0 = sorted(sms[1].kv.items())
        for i in (2, 3):
            assert sorted(sms[i].kv.items()) == kv0, (
                f"replica {i} SM state diverged "
                f"({len(sms[i].kv)} vs {len(kv0)} keys)"
            )

        # regression pin (round-3 chaos failure): an apply span delivered
        # before the group's Python node was registered was DROPPED,
        # silently losing committed entries from the apply stream and
        # wedging every later linearizable read at that index
        if fast_lane:
            for i, nh in nhs.items():
                fl = nh.fastlane
                if fl is not None and fl.enabled:
                    assert fl.dropped_spans == 0, (
                        f"rank {i} dropped {fl.dropped_spans} apply spans"
                    )
    finally:
        stop_load.set()
        for nh in nhs.values():
            try:
                nh.stop()
            except Exception:
                pass
