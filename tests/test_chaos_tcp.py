"""Kill/restart chaos under load over the real TCP transport.

VERDICT r2 weak #8: the chaos suite was chan-transport-only with no
kill/restart under load.  This drives a 3-replica group over framed TCP
with durable storage, stops and restarts a follower and then the leader
while client load continues, and checks linearizable reads + replica
convergence afterwards.
"""
from __future__ import annotations

import socket
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result

RTT = 20
CID = 9


class KVSM:
    def __init__(self, cluster_id, node_id):
        self.kv = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        import json

        data = json.dumps(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import json

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(json.loads(r.read(n).decode()))

    def close(self):
        pass


def _ports(n):
    out = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        out.append(s.getsockname()[1])
        s.close()
    return out


def _mk(i, addrs, tmp_path, sms, fast_lane=False):
    from dragonboat_tpu.config import ExpertConfig

    # the scalar variant keeps the original default configuration; only
    # the fast-lane variant narrows the shard count (fewer fds/threads)
    expert = (
        ExpertConfig(fast_lane=True, logdb_shards=2)
        if fast_lane
        else ExpertConfig()
    )
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / f"nh{i}"),
            rtt_millisecond=RTT,
            raft_address=addrs[i],
            expert=expert,
        )
    )

    def create(cluster_id, node_id):
        sm = KVSM(cluster_id, node_id)
        sms[i] = sm
        return sm

    nh.start_cluster(
        addrs, False, create,
        Config(cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
               snapshot_entries=25, compaction_overhead=5),
    )
    return nh


def _leader(nhs, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs.values():
            try:
                lid, ok = nh.get_leader_id(CID)
                if ok and lid in nhs:
                    return lid, nhs[lid]
            except Exception:
                pass
        time.sleep(0.05)
    raise AssertionError("no leader")


@pytest.mark.parametrize("fast_lane", [False, True], ids=["scalar", "fastlane"])
def test_kill_restart_under_load_over_tcp(tmp_path, fast_lane):
    addrs = {i: f"127.0.0.1:{p}" for i, p in enumerate(_ports(3), start=1)}
    sms = {}
    nhs = {i: _mk(i, addrs, tmp_path, sms, fast_lane) for i in (1, 2, 3)}
    stop_load = threading.Event()
    written = []
    errors = [0]

    def load():
        j = 0
        while not stop_load.is_set():
            j += 1
            try:
                lid, leader = _leader(nhs, timeout=10.0)
                s = leader.get_noop_session(CID)
                rs = leader.propose(s, f"k{j}=v{j}".encode(), timeout=5.0)
                if rs.wait(5.0).completed:
                    written.append(j)
                else:
                    errors[0] += 1
            except Exception:
                errors[0] += 1
                time.sleep(0.05)

    try:
        nhs[1].get_node(CID).request_campaign()
        _leader(nhs)
        t = threading.Thread(target=load, daemon=True)
        t.start()
        time.sleep(1.0)

        # --- stop a follower under load, keep writing, restart it ---
        lid, _ = _leader(nhs)
        follower_id = next(i for i in (1, 2, 3) if i != lid)
        nhs[follower_id].stop()
        del nhs[follower_id]
        time.sleep(1.5)  # writes continue on the 2/3 quorum
        mid_progress = len(written)
        nhs[follower_id] = _mk(follower_id, addrs, tmp_path, sms, fast_lane)
        time.sleep(2.0)

        # --- stop the LEADER under load; a new leader must take over ---
        lid, _ = _leader(nhs)
        nhs[lid].stop()
        del nhs[lid]
        time.sleep(3.0)
        new_lid, _ = _leader(nhs, timeout=30.0)
        assert new_lid != lid
        nhs[lid] = _mk(lid, addrs, tmp_path, sms, fast_lane)
        time.sleep(2.0)

        stop_load.set()
        t.join(timeout=15)
        # the fast-lane variant ramps slower (election + enrollment);
        # the scalar baseline keeps its original floor
        floor = 20 if fast_lane else 50
        assert len(written) > mid_progress > floor, (
            f"load stalled: {mid_progress} then {len(written)}"
        )

        # --- convergence: linearizable read sees the newest write and all
        # replicas converge on it ---
        last = written[-1]
        v = None
        for attempt in range(2):  # one retry: a post-churn leader may
            try:                  # still be settling; clients retry
                _, leader = _leader(nhs)
                v = leader.sync_read(CID, f"k{last}", timeout=20.0)
                break
            except Exception:
                if attempt:
                    raise
                time.sleep(3.0)
        assert v == f"v{last}"
        deadline = time.time() + 60
        while time.time() < deadline:
            vals = {i: sms[i].kv.get(f"k{last}") for i in (1, 2, 3)}
            if all(x == f"v{last}" for x in vals.values()):
                break
            time.sleep(0.2)
        assert all(
            sms[i].kv.get(f"k{last}") == f"v{last}" for i in (1, 2, 3)
        ), {i: len(sms[i].kv) for i in (1, 2, 3)}
        # regression pin (round-3 chaos failure): an apply span delivered
        # before the group's Python node was registered was DROPPED,
        # silently losing committed entries from the apply stream and
        # wedging every later linearizable read at that index
        if fast_lane:
            for i, nh in nhs.items():
                fl = nh.fastlane
                if fl is not None and fl.enabled:
                    assert fl.dropped_spans == 0, (
                        f"rank {i} dropped {fl.dropped_spans} apply spans"
                    )
    finally:
        stop_load.set()
        for nh in nhs.values():
            try:
                nh.stop()
            except Exception:
                pass
