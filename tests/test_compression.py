"""Entry/snapshot compression tests.

Covers: the pure-Python snappy block codec (roundtrip, format-level decode
of hand-built streams for every tag form, random fuzz), the dio
Compressor/Decompressor stream pair + CountedWriter
(``internal/utils/dio/io.go``), the v0 encoded-entry payloads
(``internal/rsm/encoded.go:47-176``), snapshot-file compression honored via
the header's compression field, and the end-to-end claim from VERDICT r2
item 4: proposing with ``entry_compression=SNAPPY`` stores smaller entries.
"""
from __future__ import annotations

import io
import os
import random
import struct

import pytest

from dragonboat_tpu import dio, snappy
from dragonboat_tpu.rsm import encoded
from dragonboat_tpu.wire import Entry, EntryType


# ---------------------------------------------------------------- snappy

def test_snappy_roundtrip_basic():
    for data in (
        b"",
        b"a",
        b"abc",
        b"aaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaa",
        b"0123456789abcdef" * 100,
        bytes(range(256)) * 10,
        b"x" * 100000,
    ):
        assert snappy.decompress(snappy.compress(data)) == data


def test_snappy_compresses_repetitive_data():
    data = b"0123456789abcdef" * 4096  # 64KB of repetition
    comp = snappy.compress(data)
    assert len(comp) < len(data) // 10
    assert snappy.decompress(comp) == data


def test_snappy_uncompressed_length():
    data = b"hello world" * 7
    assert snappy.uncompressed_length(snappy.compress(data)) == len(data)


def test_snappy_decode_handbuilt_tags():
    # stream built tag-by-tag from the public format description:
    # literal "abcd", then copy2 (offset 4, len 4) => "abcdabcd"
    s = bytearray()
    s.append(8)           # uvarint uncompressed len = 8
    s.append((4 - 1) << 2)  # literal, len 4
    s += b"abcd"
    s.append(((4 - 1) << 2) | 0x02)  # copy2, len 4
    s += struct.pack("<H", 4)
    assert snappy.decompress(bytes(s)) == b"abcdabcd"

    # copy with 1-byte offset: literal "ab", copy1 len 4 offset 2 -> ababab
    s = bytearray()
    s.append(6)
    s.append((2 - 1) << 2)
    s += b"ab"
    s.append(((4 - 4) << 2) | 0x01)  # copy1, len 4, offset high bits 0
    s.append(2)                      # offset low byte
    assert snappy.decompress(bytes(s)) == b"ababab"

    # copy with 4-byte offset
    s = bytearray()
    s.append(8)
    s.append((4 - 1) << 2)
    s += b"wxyz"
    s.append(((4 - 1) << 2) | 0x03)  # copy4, len 4
    s += struct.pack("<I", 4)
    assert snappy.decompress(bytes(s)) == b"wxyzwxyz"

    # overlapping copy (offset < len): run-length semantics
    s = bytearray()
    s.append(9)
    s.append((1 - 1) << 2)
    s += b"q"
    s.append(((8 - 1) << 2) | 0x02)
    s += struct.pack("<H", 1)
    assert snappy.decompress(bytes(s)) == b"q" * 9


def test_snappy_rejects_corrupt():
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(b"")
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(b"\x05\x00")  # truncated literal
    # bad copy offset (no output yet)
    bad = bytes([4, ((4 - 1) << 2) | 0x02, 9, 0])
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(bad)


def test_snappy_fuzz_roundtrip():
    rng = random.Random(42)
    for _ in range(200):
        n = rng.randrange(0, 4096)
        kind = rng.randrange(3)
        if kind == 0:
            data = bytes(rng.getrandbits(8) for _ in range(n))
        elif kind == 1:
            unit = bytes(rng.getrandbits(8) for _ in range(rng.randrange(1, 17)))
            data = (unit * (n // max(1, len(unit)) + 1))[:n]
        else:
            data = bytes(rng.choice(b"ab") for _ in range(n))
        assert snappy.decompress(snappy.compress(data)) == data


# ------------------------------------------------------------------- dio

def test_dio_stream_roundtrip():
    payload = os.urandom(1024) + b"z" * (3 * dio.BLOCK_SIZE) + b"tail"
    buf = io.BytesIO()
    c = dio.Compressor(dio.CompressionType.SNAPPY, buf)
    # write in awkward chunk sizes
    view = memoryview(payload)
    i = 0
    for sz in (1, 10, 100000, dio.BLOCK_SIZE, len(payload)):
        c.write(view[i : i + sz])
        i += sz
        if i >= len(payload):
            break
    c.write(view[i:])
    c.close()
    assert buf.tell() < len(payload) // 2  # the z-runs compress
    buf.seek(0)
    d = dio.Decompressor(dio.CompressionType.SNAPPY, buf)
    assert d.read(-1) == payload


def test_dio_stream_partial_reads():
    payload = b"0123456789" * 1000
    buf = io.BytesIO()
    c = dio.Compressor(dio.CompressionType.SNAPPY, buf)
    c.write(payload)
    c.close()
    buf.seek(0)
    d = dio.Decompressor(dio.CompressionType.SNAPPY, buf)
    out = b""
    while True:
        chunk = d.read(333)
        if not chunk:
            break
        out += chunk
    assert out == payload


def test_counted_writer():
    buf = io.BytesIO()
    cw = dio.CountedWriter(buf)
    cw.write(b"abc")
    cw.write(b"defg")
    with pytest.raises(RuntimeError):
        cw.bytes_written()
    cw.close()
    assert cw.bytes_written() == 7


# --------------------------------------------------------------- encoded

def test_encoded_payload_roundtrip():
    for ct in (dio.CompressionType.NO_COMPRESSION, dio.CompressionType.SNAPPY):
        for cmd in (b"x", b"hello world" * 50, os.urandom(300)):
            enc = encoded.get_encoded_payload(ct, cmd)
            ver, flag, ses = encoded.parse_header(enc)
            assert ver == encoded.EE_V0
            assert not ses
            assert encoded.get_decoded_payload(enc) == cmd


def test_encoded_payload_smaller_with_snappy():
    cmd = b"the same sixteen " * 256
    raw = encoded.get_encoded_payload(dio.CompressionType.NO_COMPRESSION, cmd)
    comp = encoded.get_encoded_payload(dio.CompressionType.SNAPPY, cmd)
    assert len(raw) == len(cmd) + 1
    assert len(comp) < len(raw) // 4


def test_encoded_empty_payload_rejected():
    with pytest.raises(ValueError):
        encoded.get_encoded_payload(dio.CompressionType.SNAPPY, b"")


def test_get_entry_payload_by_type():
    e = Entry(type=EntryType.APPLICATION, cmd=b"plain")
    assert encoded.get_entry_payload(e) == b"plain"
    enc = encoded.get_encoded_payload(dio.CompressionType.SNAPPY, b"squeeze me" * 20)
    e = Entry(type=EntryType.ENCODED, cmd=enc)
    assert encoded.get_entry_payload(e) == b"squeeze me" * 20


def test_mixed_version_read():
    """A log can mix plain APPLICATION entries (older writers) with ENCODED
    entries; the apply path must handle both."""
    cmds = [b"old-style", b"new-style" * 30]
    entries = [
        Entry(type=EntryType.APPLICATION, cmd=cmds[0]),
        Entry(
            type=EntryType.ENCODED,
            cmd=encoded.get_encoded_payload(dio.CompressionType.SNAPPY, cmds[1]),
        ),
    ]
    assert [encoded.get_entry_payload(e) for e in entries] == cmds


# ---------------------------------------------------- snapshot file path

def test_snapshot_file_compression(tmp_path):
    from dragonboat_tpu.rsm.snapshotio import SnapshotReader, SnapshotWriter

    payload = (b"session-image-" * 64, b"sm-image " * 50000)
    sizes = {}
    for comp in (0, 1):
        path = str(tmp_path / f"snap-{comp}.gbsnap")
        w = SnapshotWriter(path, compression=comp)
        w.write_session(payload[0])
        w.write(payload[1])
        w.finalize()
        sizes[comp] = os.path.getsize(path)
        r = SnapshotReader(path)
        assert r.compression == comp
        assert r.read_session() == payload[0]
        assert r.read(-1) == payload[1]
        r.validate_payload()
        r.close()
    assert sizes[1] < sizes[0] // 4


def test_entry_compression_end_to_end():
    """Proposing with entry_compression=SNAPPY stores a smaller entry in the
    raft log than with NO_COMPRESSION (VERDICT r2 item 4 done-criterion)."""
    import time

    from dragonboat_tpu import Config, NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanRouter, ChanTransport
    from dragonboat_tpu.statemachine import Result

    class SM:
        def __init__(self, c, n):
            self.seen = []

        def update(self, cmd):
            self.seen.append(bytes(cmd))
            return Result(value=len(cmd))

        def lookup(self, q):
            return self.seen

        def save_snapshot(self, w, files, done):
            w.write(b"\0")

        def recover_from_snapshot(self, r, files, done):
            r.read()

        def close(self):
            pass

    cmd = b"compressible payload " * 100  # 2100B, highly repetitive
    stored = {}
    for comp in (0, 1):
        router = ChanRouter()
        nhs = [
            NodeHost(
                NodeHostConfig(
                    node_host_dir=":memory:",
                    rtt_millisecond=100,
                    raft_address=f"c{comp}-{i}:1",
                    raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                        s, rh, ch, router=router
                    ),
                    expert=ExpertConfig(quorum_engine="scalar"),
                )
            )
            for i in (1, 2, 3)
        ]
        addrs = {i: f"c{comp}-{i}:1" for i in (1, 2, 3)}
        for i, nh in enumerate(nhs, 1):
            nh.start_cluster(
                addrs, False, SM,
                Config(cluster_id=7, node_id=i, election_rtt=10,
                       heartbeat_rtt=1, entry_compression=comp),
            )
        nhs[0].get_node(7).request_campaign()
        deadline = time.time() + 30
        leader = None
        while leader is None and time.time() < deadline:
            for nh in nhs:
                lid, ok = nh.get_leader_id(7)
                if ok:
                    leader = nhs[lid - 1]
                    break
            time.sleep(0.02)
        s = leader.get_noop_session(7)
        rs = leader.propose(s, cmd, timeout=10.0)
        assert rs.wait(10.0).completed
        node = leader.get_node(7)
        ents = node.peer.raft.log.get_entries(1, node.peer.raft.log.last_index() + 1, 1 << 62)
        payload_entry = next(e for e in ents if e.type == EntryType.ENCODED)
        stored[comp] = len(payload_entry.cmd)
        # the user SM must still see the original command
        applied = leader.get_node(7).sm.lookup(None)
        assert cmd in applied
        for nh in nhs:
            nh.stop()
    assert stored[1] < stored[0] // 4, stored
