"""Enforce the etcd conformance manifest: every live reference test
function must map to a port that actually exists in this suite
(SURVEY.md §4.1 — the etcd-derived corpus is the protocol core's
conformance oracle)."""
import os
import re

from etcd_conformance_manifest import MANIFEST

HERE = os.path.dirname(os.path.abspath(__file__))


def _defined_tests(fname):
    with open(os.path.join(HERE, fname)) as f:
        return set(re.findall(r"^def (test\w+)", f.read(), flags=re.M))


def test_manifest_complete_and_ports_exist():
    by_file = {}
    gaps = []
    for ref_file, ref_fn, port_file, port_fn in MANIFEST:
        if port_fn is None:
            gaps.append((ref_file, ref_fn))
            continue
        if port_file not in by_file:
            by_file[port_file] = _defined_tests(port_file)
        assert port_fn in by_file[port_file], (
            f"manifest maps {ref_fn} -> {port_file}::{port_fn}, "
            f"which does not exist"
        )
    assert not gaps, f"unported reference tests: {gaps}"
    assert len(MANIFEST) >= 125  # the live corpus size at porting time
