"""Live device tick path + rung-3-scale coordinator tests (VERDICT r2 #8).

With ``quorum_engine="tpu"`` the device tick kernel owns the per-tick
firing decisions: ``raft.device_ticks`` suppresses the scalar election/
heartbeat/check-quorum fire sites, so leaders electing and heartbeats
flowing in these tests PROVES the device path is live — nothing else can
fire them.  Runs on the CPU backend in CI; the kernels are identical on
TPU.
"""
from __future__ import annotations

import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.transport import ChanRouter, ChanTransport

# serialized with the other heavy system tests under xdist
pytestmark = pytest.mark.xdist_group("heavy-multiprocess")


GROUPS = 64


class CountSM:
    def __init__(self, cluster_id, node_id):
        self.n = 0

    def update(self, cmd):
        self.n += 1
        return Result(value=self.n)

    def lookup(self, query):
        return self.n

    def save_snapshot(self, w, files, done):
        w.write(self.n.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.n = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _build(engine):
    router = ChanRouter()
    nhs = [
        NodeHost(
            NodeHostConfig(
                node_host_dir=":memory:",
                # test-driven virtual clock: the wall tick worker fires
                # every 1000s (i.e. never within the test); _drive_ticks
                # injects ticks at controlled points instead, so suite
                # load cannot burst queued ticks into spurious elections
                # (the flake the old retry-patch papered over)
                rtt_millisecond=1_000_000,
                raft_address=f"dt-{engine}{i}:1",
                raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                    s, rh, ch, router=router
                ),
                expert=ExpertConfig(
                    quorum_engine=engine, engine_block_groups=max(GROUPS, 64)
                ),
            )
        )
        for i in (1, 2, 3)
    ]
    addrs = {i: f"dt-{engine}{i}:1" for i in (1, 2, 3)}
    for g in range(GROUPS):
        for i, nh in enumerate(nhs, 1):
            nh.start_cluster(
                addrs, False, CountSM,
                # election_rtt 20: virtual ticks are enqueued and can be
                # processed in bursts; a wide randomized window (20..40
                # ticks, per-replica seeded) keeps a few-tick burst from
                # landing two replicas' campaigns in the same step
                Config(cluster_id=100 + g, node_id=i, election_rtt=20,
                       heartbeat_rtt=1, snapshot_entries=0),
            )
    return nhs, [100 + g for g in range(GROUPS)]


def _drive_ticks(nhs, n=1):
    """Inject n virtual ticks into every replica (what the wall-clock tick
    worker would do, minus the wall clock — nodehost._tick_worker_main)."""
    for _ in range(n):
        for nh in nhs:
            for node in list(nh._clusters.values()):
                node.request_tick()
            if nh.quorum_coordinator is not None:
                nh.quorum_coordinator.request_tick()


def _stable_leaders(nhs, cids):
    """Leaders iff EVERY replica of every group agrees on one live leader
    and no candidacy is in flight; None otherwise.  Once this holds with
    the clocks frozen, no message in the system can change leadership."""
    leaders = {}
    for cid in cids:
        lid0 = None
        for nh in nhs:
            node = nh.get_node(cid)
            if node.peer.raft.is_candidate():
                return None
            lid, ok = nh.get_leader_id(cid)
            if not ok or (lid0 is not None and lid != lid0):
                return None
            lid0 = lid
        if not nhs[lid0 - 1].get_node(cid).peer.raft.is_leader():
            return None
        leaders[cid] = nhs[lid0 - 1]
    return leaders


def _run_workload(engine):
    """No explicit campaigns: elections must fire from tick processing."""
    nhs, cids = _build(engine)
    try:
        deadline = time.time() + 120
        leaders = None
        while time.time() < deadline:
            _drive_ticks(nhs)
            leaders = _stable_leaders(nhs, cids)
            if leaders:
                # settle: let in-flight election traffic drain with the
                # clocks already frozen, then re-verify — a candidacy
                # racing the freeze would otherwise depose a recorded
                # leader with nobody left to re-campaign
                time.sleep(0.1)
                leaders = _stable_leaders(nhs, cids)
                if leaders:
                    break
            time.sleep(0.01)
        if not leaders:
            diag = {}
            for cid in cids:
                views = [
                    (
                        nh.get_node(cid).peer.raft.state.name,
                        nh.get_node(cid).peer.raft.term,
                        nh.get_node(cid).peer.raft.leader_id,
                    )
                    for nh in nhs
                ]
                if len({v[2] for v in views}) > 1 or any(
                    v[2] == 0 for v in views
                ):
                    diag[cid] = views
            raise AssertionError(
                f"{engine}: leadership did not stabilize; "
                f"{len(diag)} unstable groups, sample: "
                f"{dict(list(diag.items())[:4])}"
            )
        if engine == "tpu":
            # the device REALLY owns tick firing for these groups
            n_dev = sum(
                1
                for nh in nhs
                for node in nh._clusters.values()
                if node.peer.raft.device_ticks
            )
            assert n_dev == 3 * GROUPS, f"device_ticks on {n_dev} replicas"
        # commit workload on every group.  NO ticks are driven from here
        # on: commits ride the message flow alone, and with the clocks
        # frozen a loaded suite cannot fire spurious elections — so one
        # attempt per group suffices (no retry patch)
        for cid in cids:
            nh = leaders[cid]
            s = nh.get_noop_session(cid)
            rss = [nh.propose(s, b"w", timeout=60.0) for _ in range(5)]
            for rs in rss:
                r = rs.wait(60.0)
                assert r.completed, (engine, cid, r)
        return {
            cid: leaders[cid].get_node(cid).peer.raft.log.committed
            for cid in cids
        }
    finally:
        for nh in nhs:
            nh.stop()


def test_device_ticks_differential_64_groups():
    """Identical outcomes scalar vs device-ticks at 64 groups: every group
    elects a leader via tick processing and commits the same workload."""
    scalar = _run_workload("scalar")
    device = _run_workload("tpu")
    assert set(scalar) == set(device)
    for cid in scalar:
        # noop index may differ by election timing; committed progress must
        # cover the 5 workload entries past the promotion noop on both
        assert scalar[cid] >= 6 and device[cid] >= 6, (
            cid, scalar[cid], device[cid],
        )


# ------------------------------------------------- rung-3 coordinator scale


class FakeNode:
    """Minimal node shim for driving the coordinator at scale."""

    def __init__(self, cid, raft):
        self.cluster_id = cid
        self.raft_mu = threading.RLock()

        class _P:
            pass

        self.peer = _P()
        self.peer.raft = raft
        self.commits = []

    def offload_commit(self, q):
        r = self.peer.raft
        with self.raft_mu:
            if r.is_leader() and r.log.try_commit(q, r.term):
                self.commits.append(q)

    def offload_election(self, won, term):
        pass

    def offload_tick_elect(self):
        pass

    def offload_tick_heartbeat(self):
        pass

    def offload_tick_demote(self):
        pass


def test_coordinator_rung3_scale_with_churn_and_event_overflow():
    """1024 registered groups on one coordinator: commit parity with the
    scalar oracle under ack floods larger than the event cap, plus
    register/unregister churn recycling rows."""
    from dragonboat_tpu.raft import InMemLogDB
    from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator
    from dragonboat_tpu.wire import Entry
    from tests.raft_harness import new_test_raft

    N = 1024
    coord = TpuQuorumCoordinator(capacity=N, n_peers=4, drive_ticks=False)
    try:
        nodes = {}
        for g in range(N):
            cid = 1 + g
            r = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
            r.cluster_id = cid
            r.become_candidate()
            r.become_leader()
            n = FakeNode(cid, r)
            r.offload = coord
            nodes[cid] = n
            coord._nodes[cid] = n
            with coord._mu:
                coord._sync_row_locked(n)
        # ack flood: every group gets 8 rounds of acks from both followers
        # (2 * 8 * 1024 = 16384 events > event_cap 4096 → chunked dispatch)
        for round_i in range(1, 9):
            for cid, n in nodes.items():
                r = n.peer.raft
                r.append_entries([Entry(cmd=b"x")])
                idx = r.log.last_index()
                coord.ack(cid, 2, idx)
                coord.ack(cid, 3, idx)
        coord.flush()
        bad = [
            cid
            for cid, n in nodes.items()
            if n.peer.raft.log.committed != n.peer.raft.log.last_index()
        ]
        assert not bad, f"{len(bad)} groups failed to commit: {bad[:5]}"
        # churn: retire 256 groups, register 256 fresh ones into the
        # recycled rows, verify they commit too
        retired = list(nodes)[:256]
        for cid in retired:
            coord.unregister(cid)
            del nodes[cid]
        fresh = {}
        for g in range(256):
            cid = 100000 + g
            r = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
            r.cluster_id = cid
            r.become_candidate()
            r.become_leader()
            n = FakeNode(cid, r)
            r.offload = coord
            fresh[cid] = n
            coord._nodes[cid] = n
            with coord._mu:
                coord._sync_row_locked(n)
        for cid, n in fresh.items():
            r = n.peer.raft
            r.append_entries([Entry(cmd=b"y")])
            coord.ack(cid, 2, r.log.last_index())
        coord.flush()
        bad = [
            cid
            for cid, n in fresh.items()
            if n.peer.raft.log.committed != n.peer.raft.log.last_index()
        ]
        assert not bad, f"churned rows broken: {bad[:5]}"
        # surviving old rows are untouched by the churn
        for cid, n in list(nodes.items())[:16]:
            assert n.peer.raft.log.committed == n.peer.raft.log.last_index()
    finally:
        coord.stop()
