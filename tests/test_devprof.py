"""Device capacity & profiling plane suite (ISSUE 15).

Contracts under test:

- profile-OFF structural identity: ``device_profile=0`` constructs
  nothing — ``NodeHost.devprof`` is None, the engine keeps its
  bit-identical ``_devprof=None`` latch, no ``dragonboat_devprof_*``
  families exist and ``profile_device`` refuses;
- the HBM ledger prices EXACTLY the live device arrays (cpu backend:
  byte-identical per plane across devsm/read/vote shape combinations,
  including the in-flight pipelined double buffer), and the capacity
  model's prediction matches the measured resident bytes (0% error by
  construction — the acceptance bound is 10%);
- the capacity model's per-dispatch term reproduces the engine's own
  ``upload_nbytes`` accounting for a padded fused dispatch (the shared
  helper can't drift from the tensors actually shipped);
- the program registry covers the WHOLE warm set (``warm_plan`` is the
  single enumeration) with non-zero cost/memory analysis per program;
- padding-waste accounting against a forced K=16 backlog with 2 live
  rounds (14 provable no-op rounds);
- the read-only ``/debug/devprof`` endpoint round-trips (404 while the
  plane is off) and ``NodeHost.profile_device`` opens/closes a
  ``jax.profiler`` capture window whose artifact lands on disk.
"""
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.events import MetricsRegistry
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.obs import FlightRecorder
from dragonboat_tpu.obs.devprof import DevProf, predict_bytes
from dragonboat_tpu.ops.engine import (
    WARM_K_BUCKETS,
    BatchedQuorumEngine,
    upload_nbytes,
)
from dragonboat_tpu.ops.state import (
    DEVSM_PLANE_FIELDS,
    READ_PLANE_FIELDS,
    field_plane,
    state_layout,
)
from dragonboat_tpu.transport import ChanRouter, ChanTransport

from tests.loadwait import wait_until

RTT_MS = 5
CID = 940


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_host(addr="dp:1", router=None, engine="tpu", device_profile=0,
             metrics_addr="", tmpdir=None):
    router = router or ChanRouter()
    return NodeHost(
        NodeHostConfig(
            node_host_dir=tmpdir or ":memory:",
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            enable_metrics=True,
            device_profile=device_profile,
            metrics_addr=metrics_addr,
            expert=ExpertConfig(
                quorum_engine=engine,
                engine_block_groups=64,
                engine_warm_fused=False,
            ),
        )
    )


def _start(nh, cid=CID):
    nh.start_cluster(
        {1: nh.raft_address()}, False, CounterSM,
        Config(cluster_id=cid, node_id=1, election_rtt=10, heartbeat_rtt=1),
    )
    wait_until(
        lambda: nh.get_leader_id(cid)[1], timeout=10.0, what="leader"
    )


def _mk_engine(g=64, p=3, **kw):
    return BatchedQuorumEngine(n_groups=g, n_peers=p, **kw)


def _lead(eng, cid=1, n=3):
    eng.add_group(cid, list(range(1, n + 1)), self_id=1)
    eng.set_leader(cid, term=1, term_start=1, last_index=1)


def _live_plane_bytes(eng):
    planes = {}
    for name, arr in eng._dev._asdict().items():
        p = field_plane(name)
        planes[p] = planes.get(p, 0) + int(arr.nbytes)
    return planes


# ----------------------------------------------------------------------
# profile OFF: structural identity
# ----------------------------------------------------------------------


def test_devprof_off_structural_identity():
    eng = _mk_engine()
    assert eng._devprof is None
    _lead(eng)
    eng.ack(1, 2, 3)
    eng.step()
    assert eng._devprof is None  # the latch never flips on its own

    nh = _mk_host(device_profile=0)
    try:
        _start(nh)
        assert nh.devprof is None
        assert nh.quorum_coordinator.devprof is None
        assert nh.quorum_coordinator.eng._devprof is None
        s = nh.get_noop_session(CID)
        for _ in range(3):
            assert nh.sync_propose(s, b"x", timeout=10.0)
        assert nh.quorum_coordinator.eng._devprof is None
        assert not any(
            f.startswith("dragonboat_devprof_")
            for f in nh.metrics_registry.families()
        )
        with pytest.raises(RuntimeError):
            nh.profile_device(10)
    finally:
        nh.stop()


def test_plane_fields_match_engine_latch_keys():
    """The ledger's plane classification and the engine's latch-gated
    sync keys are the SAME field sets — a field added to one but not
    the other would let resident state escape its plane."""
    assert tuple(READ_PLANE_FIELDS) == tuple(BatchedQuorumEngine._READ_KEYS)
    assert tuple(DEVSM_PLANE_FIELDS) == tuple(BatchedQuorumEngine._KV_KEYS)
    from dragonboat_tpu.ops.state import TELEM_PLANE_FIELDS
    assert tuple(TELEM_PLANE_FIELDS) == tuple(BatchedQuorumEngine._TELEM_KEYS)


# ----------------------------------------------------------------------
# pillar 1: HBM ledger ≡ live arrays, across shape combinations
# ----------------------------------------------------------------------


@pytest.mark.parametrize(
    "g,p,kw",
    [
        (64, 3, {}),
        (32, 5, {}),
        (16, 3, dict(n_kv_slots=8, n_kv_ents=8)),
    ],
)
def test_ledger_matches_live_bytes(g, p, kw):
    eng = _mk_engine(g, p, **kw)
    dp = DevProf(registry=MetricsRegistry(), sample_every=1)
    dp.bind_engine(eng)
    _lead(eng, n=min(p, 3))

    def check():
        led = dp.hbm_ledger()
        live = _live_plane_bytes(eng)
        assert led["planes"]["quorum"] == live["quorum"]
        assert led["planes"]["read"] == live["read"]
        assert led["planes"]["devsm"] == live["devsm"]
        assert led["state_bytes"] == sum(live.values())
        cap = led["capacity"]
        # acceptance bound is 10%; on the cpu backend the eval_shape
        # walk is exact by construction
        assert abs(cap["model_error_pct"]) < 10.0
        assert cap["bytes_per_group"] * g == cap["state_bytes"]
        return led

    check()  # bare engine
    eng.ack(1, 2, 3)
    eng.vote(1, 2, True)
    eng.step()
    check()  # after a vote-carrying dispatch
    # read plane live
    eng.stage_read(1, count=2, index=1)
    eng.read_ack(1, 2, 0)
    eng.step()
    check()
    # devsm plane live
    eng.stage_kv_ops(1, [2], [0], [7])
    eng.step()
    check()


def test_ledger_prices_inflight_double_buffer():
    eng = _mk_engine()
    dp = DevProf(sample_every=10_000)  # no registry, no sampling block
    dp.bind_engine(eng)
    _lead(eng)
    eng.ack(1, 2, 3)
    eng.begin_round()
    assert eng.step_rounds(pipelined=True) is None  # leaves one in flight
    led = dp.hbm_ledger()
    assert led["artifacts"]["dispatch"]["inflight_egress"] > 0
    assert led["total_bytes"] > led["state_bytes"]
    eng.harvest()
    led = dp.hbm_ledger()
    assert "dispatch" not in led["artifacts"]


# ----------------------------------------------------------------------
# pillar 1b: capacity model
# ----------------------------------------------------------------------


def test_capacity_model_extrapolates_linearly_and_budgets():
    a = predict_bytes(1024, 3)
    b = predict_bytes(2048, 3)
    assert b["state_bytes"] == 2 * a["state_bytes"]
    assert a["bytes_per_group"] == b["bytes_per_group"]
    # geometry changes the per-group figure
    wide = predict_bytes(1024, 8)
    assert wide["bytes_per_group"] > a["bytes_per_group"]

    eng = _mk_engine(64, 3)
    dp = DevProf()
    dp.bind_engine(eng)
    cap = dp.capacity_model(budget_bytes=1 << 30)
    per = cap["bytes_per_group_with_dispatch"]
    assert cap["max_groups"] == int((1 << 30) // per)
    # cpu backend reports no memory budget: max_groups degrades to None
    assert dp.capacity_model()["max_groups"] is None


def test_dispatch_term_matches_upload_accounting():
    """The capacity model's per-dispatch upload term reproduces the
    engine's own ``upload_nbytes`` accounting for a padded fused
    dispatch — the consolidation satellite's no-drift guarantee,
    asserted through the recorded span."""
    from dragonboat_tpu import obs as obs_mod

    g, p = 64, 3
    eng = _mk_engine(g, p)
    rec = FlightRecorder(capacity=16, stall_ms=0)
    eng.enable_obs(recorder=rec, registry=MetricsRegistry())
    _lead(eng)
    k = max(WARM_K_BUCKETS)
    eng.ack(1, 2, 3)
    eng.begin_round()
    eng.step_rounds(do_tick=True, pad_rounds_to=k, tick_rounds=2)
    span = [s for s in rec.spans() if s["kind"] == "fused"][-1]
    pred = predict_bytes(g, p, k_bucket=k)
    assert span["upload_bytes"] == pred["dispatch_bytes"], (
        span["upload_bytes"], pred["dispatch_bytes"],
    )


def test_predict_dispatch_term_matches_variant_spec_all_planes():
    """The closed-form dispatch term agrees with the abstract argument
    spec the warmup/lowering builder produces, for EVERY plane
    combination (the no-drift guard the capacity model's live path now
    derives from directly — a stage-tensor dtype/shape change breaks
    this test instead of silently mispricing the model)."""
    import numpy as np
    from dragonboat_tpu.obs.devprof import _spec_nbytes

    g, p = 16, 3
    eng = _mk_engine(g, p)
    k = max(WARM_K_BUCKETS)
    for ir in (False, True):
        for ik in (False, True):
            _, args, _ = eng._variant_args(
                "fused", k, ir, ik, abstract=True
            )
            pred = predict_bytes(
                g, p, k_bucket=k, include_reads=ir, include_kv=ik
            )
            assert _spec_nbytes(args) == pred["dispatch_bytes"], (ir, ik)


# ----------------------------------------------------------------------
# pillar 2: program registry covers the warm set
# ----------------------------------------------------------------------


def test_program_registry_covers_whole_warm_set():
    reg = MetricsRegistry()
    eng = _mk_engine(16, 3, event_cap=64)
    dp = DevProf(registry=reg)
    dp.bind_engine(eng)
    rows = dp.collect_programs(include_kv=True)
    plan = eng.warm_plan(include_kv=True)
    assert [r["variant"] for r in rows] == [
        eng.variant_label(*v) for v in plan
    ]
    for r in rows:
        assert "error" not in r, r
        assert r["flops"] > 0, r
        assert r["bytes_accessed"] > 0, r
        assert r["temp_bytes"] >= 0 and r["output_bytes"] > 0, r
        assert r["compile_ms"] > 0, r
    # every variant's gauges published
    for r in rows:
        assert reg.gauge_value(
            "dragonboat_devprof_program_flops",
            labels={"variant": r["variant"]},
        ) == r["flops"]
    assert reg.gauge_value("dragonboat_devprof_programs") == len(rows)
    # cached: a second collect returns the same rows without recompiling
    t0 = time.perf_counter()
    again = dp.collect_programs()
    assert again == rows
    assert time.perf_counter() - t0 < 1.0


# ----------------------------------------------------------------------
# pillar 3: device-time estimator + padding waste
# ----------------------------------------------------------------------


def test_padding_waste_gauge_against_forced_k16_backlog():
    reg = MetricsRegistry()
    eng = _mk_engine()
    rec = FlightRecorder(capacity=16, stall_ms=0)
    eng.enable_obs(recorder=rec, registry=reg)
    dp = DevProf(registry=reg, sample_every=1)
    dp.bind_engine(eng)
    _lead(eng)
    eng.ack(1, 2, 3)
    eng.begin_round()
    eng.step_rounds(do_tick=True, pad_rounds_to=16, tick_rounds=2)
    st = dp.estimator_stats()
    assert st["padded_rounds"] == 16
    assert st["wasted_rounds"] == 14  # 16-round program, 2 live rounds
    assert st["padding_waste_ratio"] == round(14 / 16, 4)
    assert st["sampled"] == 1 and st["device_ms"]["n"] == 1
    assert reg.counter_value(
        "dragonboat_devprof_wasted_rounds_total"
    ) == 14
    assert reg.counter_value(
        "dragonboat_devprof_padded_rounds_total"
    ) == 16
    assert reg.gauge_value(
        "dragonboat_devprof_padding_waste_ratio"
    ) == round(14 / 16, 4)
    h = reg.histogram_value("dragonboat_devprof_device_ms")
    assert h is not None and h[3] >= 1
    # the sampled delta lands on the dispatch's recorder span
    span = [s for s in rec.spans() if s["kind"] == "fused"][-1]
    assert span.get("device_ms", 0) > 0


def test_estimator_sampling_stride():
    eng = _mk_engine()
    dp = DevProf(sample_every=4)
    dp.bind_engine(eng)
    _lead(eng)
    for i in range(8):
        eng.ack(1, 2, 2 + i)
        eng.step()
    st = dp.estimator_stats()
    assert st["dispatches"] == 8
    assert st["sampled"] == 2  # the 1st and the 5th (stride 4)


# ----------------------------------------------------------------------
# pillar 4 + endpoint: capture windows, /debug/devprof, profile_device
# ----------------------------------------------------------------------


def test_capture_window_lifecycle(tmp_path):
    eng = _mk_engine(16, 3)
    reg = MetricsRegistry()
    dp = DevProf(registry=reg, artifact_dir=str(tmp_path),
                 sample_every=10_000)
    dp.bind_engine(eng)
    _lead(eng)
    d = dp.capture(ms=200)
    assert dp.capture_active
    assert d.startswith(str(tmp_path))
    with pytest.raises(RuntimeError):
        dp.capture(ms=10)  # one window at a time
    assert reg.counter_value("dragonboat_devprof_captures_total") == 1
    assert reg.gauge_value("dragonboat_devprof_capture_active") == 1
    eng.ack(1, 2, 3)
    eng.step()  # device work inside the window
    wait_until(lambda: not dp.capture_active, timeout=10.0,
               what="capture window closed")
    assert reg.gauge_value("dragonboat_devprof_capture_active") == 0
    files = [
        os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs
    ]
    assert files, "capture window wrote no artifact"
    caps = dp.captures()
    assert len(caps) == 1 and caps[0]["stopped"] is not None
    # early stop path
    d2 = dp.capture(ms=60_000)
    assert dp.stop_capture() == d2
    assert not dp.capture_active
    # to_json is read-only and carries all four pillars
    j = dp.to_json()
    assert j["ledger"]["state_bytes"] > 0
    assert j["estimator"]["dispatches"] >= 1
    assert len(j["captures"]) == 2
    assert j["programs"] is None  # reading never triggered compiles


def test_debug_devprof_endpoint_round_trip(tmp_path):
    nh = _mk_host(
        device_profile=1, metrics_addr="127.0.0.1:0",
        tmpdir=str(tmp_path),
    )
    try:
        _start(nh)
        assert nh.devprof is not None
        assert nh.quorum_coordinator.eng._devprof is nh.devprof
        s = nh.get_noop_session(CID)
        for _ in range(5):
            nh.sync_propose(s, b"x", timeout=10.0)
        port = nh.metrics_server.port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/devprof", timeout=10
        ) as resp:
            assert resp.status == 200
            d = json.loads(resp.read())
        assert d["ledger"]["planes"]["quorum"] > 0
        assert d["ledger"]["capacity"]["bytes_per_group"] > 0
        assert d["estimator"]["dispatches"] > 0
        # the devprof families ride the same /metrics exposition
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        assert "dragonboat_devprof_hbm_plane_bytes" in text
        # profile_device writes its artifact beside the host dir
        cap_dir = nh.profile_device(150)
        assert cap_dir.startswith(str(tmp_path))
        wait_until(
            lambda: not nh.devprof.capture_active, timeout=10.0,
            what="profile window closed",
        )
        assert any(os.scandir(cap_dir))
    finally:
        nh.stop()


def test_debug_devprof_endpoint_404_when_off():
    nh = _mk_host(engine="scalar", metrics_addr="127.0.0.1:0")
    try:
        _start(nh)
        port = nh.metrics_server.port
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/devprof", timeout=10
            )
        assert ei.value.code == 404
    finally:
        nh.stop()
