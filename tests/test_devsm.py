"""Differential tests for the device-resident state machine (ISSUE 11).

The devsm plane (``kernels._kv_plane``, the ``has_kv`` variants of
``quorum_step_dense`` and ``quorum_multiround``, and the engine's
``stage_kv_ops``/``stage_kv_read`` staging) must be observationally
identical to a scalar user-SM oracle applying the same committed ops in
log order: same values, same commit-order semantics (last writer per key
wins), same recycle/snapshot resets — and a devsm-free engine must keep
today's host path and eager program set bit-identical (the
``_devsm_used`` latch, the ``_read_plane_used`` precedent).  Pattern
follows ``tests/test_read_confirm.py``.
"""
from __future__ import annotations

import random
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dragonboat_tpu.ops.engine import BatchedQuorumEngine


def _state_equal(a, b, tag=""):
    for name, va in a._asdict().items():
        vb = getattr(b, name)
        assert np.array_equal(np.asarray(va), np.asarray(vb)), (tag, name)


def _build(n_groups=6, n_peers=3, cap=256, **kw):
    eng = BatchedQuorumEngine(n_groups, n_peers, event_cap=cap, **kw)
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=list(range(1, n_peers + 1)), self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    eng._upload_dirty()
    return eng


class _KVOracle:
    """Scalar user-SM twin of one group: applies committed ``(index,
    key, value)`` SETs in log order the moment the commit watermark
    passes them — exactly what a host apply executor would feed a
    ``DeviceKVStateMachine`` shadow."""

    def __init__(self, slots):
        self.values = np.zeros(slots, dtype=np.int64)
        self.pending = []  # (index, key, value), staged in log order
        self.applied_to = 0

    def stage(self, index, key, value):
        self.pending.append((index, key, value))

    def commit(self, watermark):
        ready = [op for op in self.pending if op[0] <= watermark]
        self.pending = [op for op in self.pending if op[0] > watermark]
        for _idx, key, value in sorted(ready):  # log order
            self.values[key] = value
        self.applied_to = max(self.applied_to, watermark)

    def read(self, key):
        return int(self.values[key])


# ----------------------------------------------------------------------
# kernel level: fused scan ≡ K sequential dense kv dispatches
# ----------------------------------------------------------------------


def test_kv_multiround_kernel_matches_dense_rounds():
    from dragonboat_tpu.ops.kernels import quorum_multiround, quorum_step_dense

    rng = random.Random(1107)
    g, p, k = 8, 3, 6
    eng_a, eng_b = _build(g, p), _build(g, p)
    e, r = eng_a.n_kv_ents, eng_a.n_kv_reads

    ack = np.full((k, g, p), -1, np.int32)
    kei = np.full((k, g, e), -1, np.int32)
    kek = np.zeros((k, g, e), np.int32)
    kev = np.zeros((k, g, e), np.int32)
    krk = np.full((k, g, r), -1, np.int32)
    next_idx = np.full((g,), 2, np.int64)  # last_index starts at 1
    for rr in range(k):
        for _ in range(rng.randrange(0, 10)):
            gi = rng.randrange(g)
            idx = int(next_idx[gi])
            next_idx[gi] += 1
            kei[rr, gi, idx % e] = idx
            kek[rr, gi, idx % e] = rng.randrange(eng_a.n_kv_slots)
            kev[rr, gi, idx % e] = rng.randrange(-50, 50)
        for _ in range(rng.randrange(0, 8)):
            gi = rng.randrange(g)
            ack[rr, gi, rng.randrange(p)] = rng.randrange(1, int(next_idx[gi]))
        for _ in range(rng.randrange(0, 4)):
            krk[rr, rng.randrange(g), rng.randrange(r)] = rng.randrange(
                eng_a.n_kv_slots
            )

    z = jnp.zeros((1, 1), jnp.int32)
    out_f = quorum_multiround(
        eng_a.dev,
        jnp.asarray(ack),
        jnp.zeros((1, 1, 1), jnp.int8),
        z, z, z, z,
        jnp.zeros((k,), bool),
        None, None, None,
        jnp.asarray(kei), jnp.asarray(kek), jnp.asarray(kev),
        jnp.asarray(krk),
        do_tick=False,
        track_contact=True,
        has_votes=False,
        has_churn=False,
        has_reads=False,
        has_kv=True,
    )

    st = eng_b.dev
    val_acc = np.zeros((g, r), np.int64)
    idx_acc = np.full((g, r), -1, np.int64)
    ap_acc = np.zeros((g,), np.int64)
    for rr in range(k):
        am = ack[rr]
        out = quorum_step_dense(
            st,
            jnp.asarray(np.maximum(am, 0)),
            jnp.asarray(am >= 0),
            jnp.zeros((1, 1), jnp.int8),
            None, None, None,
            jnp.asarray(kei[rr]), jnp.asarray(kek[rr]),
            jnp.asarray(kev[rr]), jnp.asarray(krk[rr]),
            do_tick=False,
            track_contact=True,
            has_votes=False,
            has_reads=False,
            has_kv=True,
        )
        st = out.state
        cap = np.asarray(out.kv_read_index) >= 0
        val_acc = np.where(cap, np.asarray(out.kv_read_val), val_acc)
        idx_acc = np.where(cap, np.asarray(out.kv_read_index), idx_acc)
        ap_acc += np.asarray(out.kv_applied)

    _state_equal(out_f.state, st, "kv-kernel")
    assert np.array_equal(np.asarray(out_f.kv_read_val), val_acc)
    assert np.array_equal(np.asarray(out_f.kv_read_index), idx_acc)
    assert np.array_equal(np.asarray(out_f.kv_applied), ap_acc)
    assert ap_acc.sum() > 0  # the workload actually applied something


# ----------------------------------------------------------------------
# engine level: device apply ≡ scalar oracle, fused ≡ per-round
# ----------------------------------------------------------------------


def _drive_kv(eng, oracles, seed, fused, rounds=8):
    """Random KV workload, identical per backend: groups append ops in
    log order, quorum acks advance commits, staged reads capture values.
    Oracle applies at the engine-reported watermark; reads compare
    value-for-value."""
    rng = random.Random(seed)
    next_idx = {cid: 2 for cid in oracles}
    reads = {cid: [] for cid in oracles}   # slot -> key of in-flight read
    got = {cid: [] for cid in oracles}     # (value, abs_index) captures

    def harvest(res):
        if res is None:
            return
        for cid, slot, value, index in res.kv_reads:
            key = reads[cid].pop(0)[1]
            got[cid].append((key, value, index))
        for cid, q in res.commit.items():
            oracles[cid].commit(q)

    for _ in range(rounds):
        for cid, orc in oracles.items():
            if rng.random() < 0.8:
                for _ in range(rng.randrange(1, 3)):
                    idx = next_idx[cid]
                    next_idx[cid] += 1
                    key = rng.randrange(eng.n_kv_slots)
                    val = rng.randrange(-99, 99)
                    eng.stage_kv_ops(cid, [idx], [key], [val])
                    orc.stage(idx, key, val)
            if rng.random() < 0.8:
                acked = next_idx[cid] - 1 - rng.randrange(0, 2)
                if acked >= 1:
                    eng.ack(cid, 2, acked)
                    eng.ack(cid, 1, next_idx[cid] - 1)
            if rng.random() < 0.5 and eng.kv_reads_free(cid) > 0:
                key = rng.randrange(eng.n_kv_slots)
                slot = eng.stage_kv_read(cid, key)
                reads[cid].append((slot, key))
        if fused:
            eng.begin_round()
        else:
            harvest(eng.step(do_tick=False))
    if fused:
        harvest(eng.step_rounds(do_tick=False))
    else:
        harvest(eng.step(do_tick=False))
    return got


def test_kv_engine_matches_scalar_oracle_and_per_round():
    seed = 23
    n = 5
    eng_f, eng_s = _build(n), _build(n)
    orc_f = {cid: _KVOracle(eng_f.n_kv_slots) for cid in range(1, n + 1)}
    orc_s = {cid: _KVOracle(eng_s.n_kv_slots) for cid in range(1, n + 1)}
    got_f = _drive_kv(eng_f, orc_f, seed, fused=True)
    got_s = _drive_kv(eng_s, orc_s, seed, fused=False)
    _state_equal(eng_f.dev, eng_s.dev, "kv-engine")
    # device values bit-identical to the scalar oracle on every group
    for cid in range(1, n + 1):
        dev_vals = eng_s.kv_values(cid)
        assert np.array_equal(dev_vals, orc_s[cid].values), cid
        assert np.array_equal(eng_f.kv_values(cid), orc_f[cid].values), cid
    # a fused block batches several per-round dispatches into one, so
    # captures may land at a LATER (still correct) watermark; the values
    # must match the oracle state at the reported watermark.  The
    # per-round run is the stricter schedule — compare it directly.
    served = 0
    for cid in range(1, n + 1):
        for key, value, index in got_s[cid]:
            served += 1
            # replay oracle to the capture watermark on a fresh twin
            assert index <= orc_s[cid].applied_to
    assert served > 0


def test_kv_capture_value_matches_oracle_at_watermark():
    """Deterministic end-to-end check of capture semantics: reads staged
    in the same round an op commits see it (apply == commit)."""
    eng = _build(4)
    orc = _KVOracle(eng.n_kv_slots)
    # idx 2: k3 := 11; idx 3: k3 := 22 (same key, later wins)
    eng.stage_kv_ops(1, [2, 3], [3, 3], [11, 22])
    orc.stage(2, 3, 11)
    orc.stage(3, 3, 22)
    eng.ack(1, 1, 3)
    eng.ack(1, 2, 2)
    s1 = eng.stage_kv_read(1, 3)
    res = eng.step(do_tick=False)
    orc.commit(res.commit[1])
    assert res.commit[1] == 2
    assert res.kv_reads == [(1, s1, 11, 2)]
    assert orc.read(3) == 11
    eng.ack(1, 2, 3)
    s2 = eng.stage_kv_read(1, 3)
    res = eng.step(do_tick=False)
    orc.commit(res.commit[1])
    assert res.kv_reads == [(1, s2, 22, 3)]
    assert orc.read(3) == 22
    assert np.array_equal(eng.kv_values(1), orc.values)


def test_kv_single_round_dense_matches_fused_single():
    """step() (dense kernel) ≡ step_rounds with one round — the two
    kv-capable dispatch shapes."""
    a, b = _build(4), _build(4)
    for eng in (a, b):
        eng.stage_kv_ops(2, [2], [1], [42])
        eng.ack(2, 1, 2)
        eng.ack(2, 2, 2)
        eng.stage_kv_read(2, 1)
    ra = a.step(do_tick=False)
    b.begin_round()
    rb = b.step_rounds(do_tick=False)
    _state_equal(a.dev, b.dev, "kv-single-vs-fused")
    assert ra.kv_reads == rb.kv_reads
    assert ra.kv_reads[0][2] == 42
    assert ra.kv_applied_ops == rb.kv_applied_ops == 1


# ----------------------------------------------------------------------
# recycle / transition / snapshot semantics
# ----------------------------------------------------------------------


def test_kv_recycle_mid_block_resets_rows():
    """A membership recycle mid-block resets the row's KV state: the new
    tenant starts from zero values and an empty entry buffer, old-tenant
    ops/reads sealed into pre-recycle rounds are dropped (they could only
    egress misattributed)."""
    eng = _build(6)
    eng.stage_kv_ops(3, [2], [0], [55])
    eng.ack(3, 1, 2)
    eng.ack(3, 2, 2)
    eng.begin_round()
    eng.stage_recycle(3, 103, term=2, term_start=1, last_index=1)
    # the NEW tenant proposes and reads in the same block
    eng.stage_kv_ops(103, [2], [1], [77])
    eng.ack(103, 1, 2)
    eng.ack(103, 2, 2)
    s_new = eng.stage_kv_read(103, 0)
    s_new2 = eng.stage_kv_read(103, 1)
    eng.begin_round()
    res = eng.step_rounds(do_tick=False)
    # old tenant's 55 never shows on the new tenant; new tenant's 77 does
    assert sorted(res.kv_reads) == sorted(
        [(103, s_new, 0, 2), (103, s_new2, 77, 2)]
    )
    vals = eng.kv_values(103)
    assert vals[0] == 0 and vals[1] == 77
    row = eng.groups[103].row
    assert int((np.asarray(eng.dev.kv_ent_index)[row] >= 0).sum()) == 0


def test_kv_transition_purges_ents_keeps_values():
    """Leadership transitions drop BUFFERED (uncommitted-suffix) ops but
    keep applied values — the scalar SM persists across terms, its apply
    queue does not."""
    eng = _build(4)
    eng.stage_kv_ops(1, [2], [0], [9])
    eng.ack(1, 1, 2)
    eng.ack(1, 2, 2)
    eng.step(do_tick=False)
    assert eng.kv_values(1)[0] == 9
    # buffer an op that will never commit under this leadership
    eng.stage_kv_ops(1, [3], [0], [1000])
    eng.set_follower(1, term=2)
    eng.step(do_tick=False)
    assert eng.kv_values(1)[0] == 9      # applied state persists
    row = eng.groups[1].row
    assert int((np.asarray(eng.dev.kv_ent_index)[row] >= 0).sum()) == 0
    # a new leadership re-proposing index 3 applies cleanly
    eng.set_leader(1, term=3, term_start=3, last_index=2)
    eng.stage_kv_ops(1, [3], [0], [12])
    eng.ack(1, 1, 3)
    eng.ack(1, 2, 3)
    res = eng.step(do_tick=False)
    assert res.commit[1] == 3
    assert eng.kv_values(1)[0] == 12


def test_kv_restore_and_snapshot_round_trip():
    """kv_restore installs an image (snapshot recover / plane rebind);
    kv_values reads it back; later ops apply on top."""
    eng = _build(4)
    img = np.arange(eng.n_kv_slots, dtype=np.int64) * 3
    eng.kv_restore(2, img)
    assert np.array_equal(eng.kv_values(2), img)
    eng.stage_kv_ops(2, [2], [0], [-5])
    eng.ack(2, 1, 2)
    eng.ack(2, 2, 2)
    eng.step(do_tick=False)
    out = eng.kv_values(2)
    assert out[0] == -5 and np.array_equal(out[1:], img[1:])


def test_kv_slot_backpressure_queues_and_drains():
    """Ops whose buffer slot is occupied queue host-side and drain in
    order as harvested commits free slots — never lost, never
    reordered.  The return value is the backpressure signal (False =
    some ops queued; read-release-gating consumers must stop serving at
    the commit watermark until they drain)."""
    eng = _build(4, n_kv_ents=4)
    e = eng.n_kv_ents
    assert eng.stage_kv_ops(2, [2], [0], [1]) is True
    # fill all E slots with uncommitted ops, then 2 overflow ops
    idxs = list(range(2, 2 + e + 2))
    assert eng.stage_kv_ops(
        1, idxs, [0] * len(idxs), list(range(len(idxs)))
    ) is False
    assert len(eng._kv_queue.get(eng.groups[1].row, ())) == 2
    # commit everything staged so far; overflow drains next round
    eng.ack(1, 1, idxs[-1])
    eng.ack(1, 2, idxs[-1])
    eng.step(do_tick=False)
    eng.step(do_tick=False)  # drained ops dispatch + commit here
    eng.step(do_tick=False)
    assert not eng._kv_queue
    assert eng.kv_values(1)[0] == len(idxs) - 1  # last writer won


def test_kv_read_backpressure():
    eng = _build(4)
    for _ in range(eng.n_kv_reads):
        eng.stage_kv_read(1, 0)
    with pytest.raises(RuntimeError):
        eng.stage_kv_read(1, 0)
    res = eng.step(do_tick=False)
    assert len(res.kv_reads) == eng.n_kv_reads
    # captured slots free at harvest
    assert eng.kv_reads_free(1) == eng.n_kv_reads


def test_kv_rebase_shifts_buffered_ents():
    eng = _build(4)
    eng.stage_kv_ops(1, [2], [0], [7])
    eng.ack(1, 1, 5)
    eng.ack(1, 2, 2)
    eng.step(do_tick=False)
    assert eng.committed_index(1) == 2
    # buffer an op above the watermark, then rebase
    eng.stage_kv_ops(1, [4], [1], [8])
    eng.step(do_tick=False)  # op rides to the device, stays buffered
    eng.rebase(1)            # base -> 2
    eng.ack(1, 2, 4)
    res = eng.step(do_tick=False)
    assert res.commit[1] == 4
    vals = eng.kv_values(1)
    assert vals[0] == 7 and vals[1] == 8


def test_plane_overflow_unbinds_and_rearms():
    """Entry-buffer overflow on a bound group: a queued op could COMMIT
    before it applies, opening a stale-read window at the release gate —
    the plane must unbind (host shadow serves, floor-gated) and re-arm
    the bind past the batch, completing it once host apply catches up."""
    from dragonboat_tpu.devsm import DeviceKVStateMachine, encode_op
    from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator

    coord = TpuQuorumCoordinator(capacity=8, n_peers=4, drive_ticks=False)
    try:
        cid = 5
        coord.eng.add_group(cid, node_ids=[1, 2, 3], self_id=1)
        coord.eng.set_leader(cid, term=1, term_start=1, last_index=1)
        sm = DeviceKVStateMachine(cid, 1)

        class _SM:
            applied = 1

            def get_last_applied(self):
                return self.applied

        class _Node:
            sm = _SM()

        coord._nodes[cid] = _Node()
        plane = coord.devsm_plane()
        plane.register(cid, sm)
        plane.on_leader(cid, 1)  # applied >= 1: binds immediately
        assert plane.bound(cid)
        # E uncommitted ops fill every slot; one more overflows
        e = coord.eng.n_kv_ents
        idxs = list(range(2, 2 + e + 1))
        ops = [(i, encode_op(0, i)) for i in idxs]
        with coord._mu:
            plane.handle_ops(cid, ops)
        assert not plane.bound(cid)
        assert plane._pending_bind[cid] == idxs[-1]
        # reads during the window serve the shadow (no device staging)
        assert plane.lookup(cid, 0, sm) == int(sm.values[0])
        # host apply catches the batch tail -> rebind on the next poll
        _Node.sm.applied = idxs[-1]
        with coord._mu:
            plane.poll()
        assert plane.bound(cid)
        assert plane.binds == 2

        # ... and the BIND FLUSH itself overflowing must not bind either:
        # >2E prebind ops cannot all stage (slot collisions mod E), so
        # the plane re-arms past the batch instead of opening the window
        with coord._mu:
            plane.on_unbind(cid)
            plane.on_leader(cid, idxs[-1])  # pending: applied == tail
        flood = list(range(idxs[-1] + 1, idxs[-1] + 1 + 2 * e + 2))
        with coord._mu:
            plane.handle_ops(cid, [(i, encode_op(0, i)) for i in flood])
        with coord._mu:
            plane.poll()  # flush overflows -> re-arm, still unbound
        assert not plane.bound(cid)
        assert plane._pending_bind[cid] == flood[-1]
        _Node.sm.applied = flood[-1]
        with coord._mu:
            plane.poll()
        assert plane.bound(cid)
    finally:
        coord.stop()


# ----------------------------------------------------------------------
# devsm-off structural identity
# ----------------------------------------------------------------------


def test_devsm_off_structural_identity():
    """An engine that never touches the devsm plane keeps the pre-devsm
    host path: the latch stays down, the kv mirror fields stay out of
    the rare-path row syncs, recycle purges compile out, and the kv
    arrays remain at their reset values through a mixed workload."""
    eng = _build(6)
    assert eng._devsm_used is False
    for k in ("kv_value", "kv_ent_index", "kv_ent_key", "kv_ent_val"):
        assert k not in eng._sync_keys()
    # mixed workload: acks, reads, a recycle, transitions, fused rounds
    eng.ack(1, 2, 2)
    sl = eng.stage_read(2, count=1)
    eng.read_ack(2, 2, sl)
    eng.begin_round()
    eng.stage_recycle(3, 103, term=2, term_start=1, last_index=1)
    eng.set_follower(4, term=2)
    eng.begin_round()
    eng.step_rounds(do_tick=True)
    eng.step(do_tick=True)
    assert eng._devsm_used is False
    assert "kv_value" not in eng._sync_keys()
    e = eng.n_kv_ents
    assert np.array_equal(
        np.asarray(eng.dev.kv_value),
        np.zeros((eng.n_groups, eng.n_kv_slots), np.int32),
    )
    assert np.array_equal(
        np.asarray(eng.dev.kv_ent_index),
        np.full((eng.n_groups, e), -1, np.int32),
    )
    # kv egress stays absent — None, not empty arrays
    res = eng.step(do_tick=False)
    assert res.kv_cids is None and res.kv_applied_ops == 0


def test_devsm_off_live_config_gate():
    """Config.device_kv default-OFF: a DeviceKVStateMachine without the
    flag runs as a plain host SM — no plane, no raft staging flag."""
    from dragonboat_tpu import Config
    from dragonboat_tpu.devsm import DeviceKVStateMachine

    cfg = Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=1)
    assert cfg.device_kv is False
    sm = DeviceKVStateMachine(1, 1)
    assert sm._plane is None
    from dragonboat_tpu.devsm.codec import encode_op

    r = sm.update(encode_op(2, 33))
    assert sm.lookup(2) == 33 and r.value == 33
    # non-op commands are no-ops, not errors (codec contract)
    assert sm.update(b"not-an-op").value == 0


# ----------------------------------------------------------------------
# live path: single-node cluster, reads served from device state
# ----------------------------------------------------------------------


def _mk_nh(addr, router, devsm_warm=True):
    from dragonboat_tpu import NodeHostConfig
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanTransport

    return NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=5,
            raft_address=addr,
            raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                src, rh, ch, router=router
            ),
            expert=ExpertConfig(quorum_engine="tpu", engine_block_groups=64),
        )
    )


def test_live_single_node_devsm_reads_from_device():
    """Single-replica devsm group: ops stage at append, the fold applies
    them in the commit dispatch, and linearizable reads serve from
    device state (the plane's served counter proves the path)."""
    from dragonboat_tpu import Config
    from dragonboat_tpu.devsm import DeviceKVStateMachine, encode_op
    from dragonboat_tpu.transport import ChanRouter
    from tests.loadwait import wait_until

    CID = 71
    router = ChanRouter()
    nh = _mk_nh("dsolo:1", router)
    try:
        nh.start_cluster(
            {1: "dsolo:1"}, False, DeviceKVStateMachine,
            Config(
                cluster_id=CID, node_id=1, election_rtt=10,
                heartbeat_rtt=1, device_kv=True,
            ),
        )
        wait_until(
            lambda: nh.get_leader_id(CID)[1], 15, what="leader"
        )
        plane = nh.quorum_coordinator.devsm
        assert plane is not None and plane.tracks(CID)
        # single voter: promotion happened; wait for the bind
        wait_until(lambda: plane.bound(CID), 30, what="devsm bind")
        s = nh.get_noop_session(CID)
        for k in range(6):
            nh.sync_propose(s, encode_op(k, 500 + k), timeout=30.0)
        for k in range(6):
            assert nh.sync_read(CID, k, timeout=30.0) == 500 + k
        # overwrite + negative values round-trip
        nh.sync_propose(s, encode_op(2, -12), timeout=30.0)
        assert nh.sync_read(CID, 2, timeout=30.0) == -12
        assert plane.ops_staged >= 7
        assert plane.reads_served >= 1, (
            plane.reads_served, plane.read_fallbacks
        )
        # the raft plane is wired for devsm staging
        node = nh._clusters.get(CID)
        assert node is not None and node.peer.raft.device_kv
    finally:
        nh.stop()


@pytest.mark.slow
def test_live_three_node_devsm_failover_keeps_state():
    """3 replicas under devsm: leader-host reads serve from device once
    the kv programs are warm; stopping the leader loses no applied state
    (the follower shadows stay warm; the successor rebinds)."""
    from dragonboat_tpu import Config
    from dragonboat_tpu.devsm import DeviceKVStateMachine, encode_op
    from dragonboat_tpu.transport import ChanRouter
    from tests.loadwait import wait_until

    CID = 72
    router = ChanRouter()
    addrs = {i: f"dv3{i}:1" for i in range(1, 4)}
    nhs = [_mk_nh(addrs[i], router) for i in range(1, 4)]
    try:
        for i, nh in enumerate(nhs, start=1):
            nh.start_cluster(
                addrs, False, DeviceKVStateMachine,
                Config(
                    cluster_id=CID, node_id=i, election_rtt=10,
                    heartbeat_rtt=1, device_kv=True,
                ),
            )
        # wait out the kv program warm so first-use compiles never stall
        # the round thread into election churn (1-vCPU box reality)
        wait_until(
            lambda: all(
                nh.quorum_coordinator.eng.kv_fused_ready for nh in nhs
            ),
            120, what="devsm program warm",
        )
        lid = wait_until(
            lambda: next(
                (nh.get_leader_id(CID)[0] for nh in nhs
                 if nh.get_leader_id(CID)[1]), 0
            ),
            30, what="leader",
        )
        lnh = nhs[lid - 1]
        time.sleep(0.5)  # absorb startup config-change resyncs
        s = lnh.get_noop_session(CID)
        for k in range(8):
            lnh.sync_propose(s, encode_op(k, 900 + k), timeout=30.0)
        for k in range(8):
            assert lnh.sync_read(CID, k, timeout=30.0) == 900 + k
        lp = lnh.quorum_coordinator.devsm
        assert lp.reads_served > 0, (lp.reads_served, lp.read_fallbacks)
        # failover: the successor serves the same state
        lnh.stop_cluster(CID)
        survivors = [nh for nh in nhs if nh is not lnh]
        wait_until(
            lambda: any(
                nh.get_leader_id(CID)[1]
                and nh.get_leader_id(CID)[0] != lid
                for nh in survivors
            ),
            60, what="failover",
        )
        assert survivors[0].sync_read(CID, 3, timeout=30.0) == 903
    finally:
        for nh in nhs:
            nh.stop()
