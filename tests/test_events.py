"""Event/metrics plumbing tests.

Reference surface: ``event.go`` (raftEventListener metrics +
LeaderUpdated forwarding, sysEventListener serialization,
WriteHealthMetrics) and ``raftio/listener.go`` interfaces.
"""
import io
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.events import (
    MetricsRegistry,
    RaftEventListener,
    SysEventListener,
    SystemEvent,
    SystemEventType,
)
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT_MS = 5


def test_metrics_registry_counter_gauge_and_exposition():
    reg = MetricsRegistry()
    reg.counter_add("x_total", labels={"cluster_id": "1"})
    reg.counter_add("x_total", 2, labels={"cluster_id": "1"})
    reg.gauge_set("y", 7.5)
    assert reg.counter_value("x_total", {"cluster_id": "1"}) == 3
    assert reg.gauge_value("y") == 7.5
    out = io.StringIO()
    reg.write_health_metrics(out)
    text = out.getvalue()
    assert '# TYPE x_total counter\nx_total{cluster_id="1"} 3' in text
    assert "# TYPE y gauge\ny 7.5" in text


def test_exposition_one_type_per_name_and_escaped_labels():
    """ISSUE 5 satellite audit: the exposition spec allows exactly one
    ``# TYPE`` per metric name (the old formatter re-emitted it per label
    set), and label values must escape ``\\``, ``"`` and newlines (an
    unescaped value corrupted the whole scrape)."""
    reg = MetricsRegistry()
    reg.counter_add("m_total", labels={"cluster_id": "1"})
    reg.counter_add("m_total", labels={"cluster_id": "2"})
    reg.gauge_set("g", 1, labels={"v": 'a"b\\c\nd'})
    out = io.StringIO()
    reg.write_health_metrics(out)
    text = out.getvalue()
    assert text.count("# TYPE m_total counter") == 1
    assert 'm_total{cluster_id="1"} 1' in text
    assert 'm_total{cluster_id="2"} 1' in text
    assert 'g{v="a\\"b\\\\c\\nd"} 1' in text  # escaped, single line


def test_exposition_help_lines_round_trip():
    """ISSUE 9 satellite: every family carries exactly one ``# HELP``
    line, immediately before its ``# TYPE``; described families round-trip
    their text (escaped), undescribed ones get the deterministic
    placeholder; first describe wins (a family must read the same across
    scrapes)."""
    reg = MetricsRegistry()
    reg.describe("m_total", "described\nfamily")
    reg.describe("m_total", "second describe loses")
    reg.counter_add("m_total", labels={"cluster_id": "1"})
    reg.counter_add("m_total", labels={"cluster_id": "2"})  # one family
    reg.gauge_set("g", 1)
    reg.histogram_observe("h_ms", 2.0, buckets=(1.0, 5.0))
    out = io.StringIO()
    reg.write_health_metrics(out)
    lines = out.getvalue().splitlines()
    assert lines.count("# HELP m_total described\\nfamily") == 1
    assert "# HELP g dragonboat_tpu metric g" in lines
    assert "# HELP h_ms dragonboat_tpu metric h_ms" in lines
    # adjacency: each # TYPE's predecessor is its own # HELP
    for i, line in enumerate(lines):
        if line.startswith("# TYPE "):
            name = line.split(" ")[2]
            assert lines[i - 1].startswith(f"# HELP {name} "), lines[i - 1]
    assert reg.help_text("m_total") == "described\nfamily"
    # a second write is byte-identical (stable ordering incl. HELP)
    out2 = io.StringIO()
    reg.write_health_metrics(out2)
    assert out2.getvalue().splitlines() == lines


def test_devsm_families_help_round_trip():
    """ISSUE 11 satellite: every ``dragonboat_devsm_*`` family an
    EngineObs registers carries its described ``# HELP`` immediately
    before its ``# TYPE``, and the apply_kernel/devsm_egress pair lands
    the expected values in the exposition."""
    from dragonboat_tpu.obs import FlightRecorder
    from dragonboat_tpu.obs.instruments import EngineObs

    reg = MetricsRegistry()
    obs = EngineObs(FlightRecorder(capacity=4, stall_ms=0), reg)
    span = obs.apply_kernel(ops=5, reads=2, rounds=3, slot_occupancy=4)
    obs.devsm_egress(span, applied=5, reads_served=2)
    out = io.StringIO()
    reg.write_health_metrics(out)
    lines = out.getvalue().splitlines()
    families = (
        "dragonboat_devsm_ops_staged_total",
        "dragonboat_devsm_applied_total",
        "dragonboat_devsm_reads_staged_total",
        "dragonboat_devsm_reads_served_total",
        "dragonboat_devsm_slot_occupancy",
    )
    for name in families:
        tidx = [
            i for i, l in enumerate(lines) if l.startswith(f"# TYPE {name} ")
        ]
        assert len(tidx) == 1, name
        help_line = lines[tidx[0] - 1]
        assert help_line.startswith(f"# HELP {name} "), help_line
        assert "dragonboat_tpu metric" not in help_line, help_line
    assert "dragonboat_devsm_ops_staged_total 5" in lines
    assert "dragonboat_devsm_applied_total 5" in lines
    assert "dragonboat_devsm_reads_served_total 2" in lines
    assert "dragonboat_devsm_slot_occupancy 4" in lines


def test_devprof_families_help_round_trip():
    """ISSUE 15 satellite: every ``dragonboat_devprof_*`` family a
    DevProfObs registers carries its described ``# HELP`` immediately
    before its ``# TYPE``, the ledger/program/estimator publishers land
    the expected values, and the exposition is write-stable."""
    from dragonboat_tpu.obs.instruments import DevProfObs

    reg = MetricsRegistry()
    obs = DevProfObs(reg)
    obs.device_ms(1.5)
    obs.flush_dispatch(
        dispatches=4, sampled=1, padded=16, wasted=14,
        waste_ratio=14 / 16, duty_cycle=0.25,
    )
    obs.ledger(
        artifacts={("quorum", "match"): 1024, ("read", "read_acks"): 256},
        planes={"quorum": 1024, "read": 256},
        bytes_per_group=384.0,
        capacity_groups=1000,
        model_error_pct=0.0,
    )
    obs.program(
        variant="fused:k4", flops=100.0, bytes_accessed=2048.0,
        temp_bytes=512, compile_ms=3.0,
    )
    obs.programs_done(1)
    obs.capture(active=True)
    obs.capture(active=False)
    out = io.StringIO()
    reg.write_health_metrics(out)
    lines = out.getvalue().splitlines()
    families = (
        "dragonboat_devprof_hbm_bytes",
        "dragonboat_devprof_hbm_plane_bytes",
        "dragonboat_devprof_bytes_per_group",
        "dragonboat_devprof_capacity_groups",
        "dragonboat_devprof_model_error_pct",
        "dragonboat_devprof_device_ms",
        "dragonboat_devprof_duty_cycle",
        "dragonboat_devprof_dispatches_total",
        "dragonboat_devprof_sampled_total",
        "dragonboat_devprof_padded_rounds_total",
        "dragonboat_devprof_wasted_rounds_total",
        "dragonboat_devprof_padding_waste_ratio",
        "dragonboat_devprof_programs",
        "dragonboat_devprof_program_compile_ms",
        "dragonboat_devprof_program_flops",
        "dragonboat_devprof_program_bytes",
        "dragonboat_devprof_program_temp_bytes",
        "dragonboat_devprof_captures_total",
        "dragonboat_devprof_capture_active",
    )
    for name in families:
        tidx = [
            i for i, l in enumerate(lines) if l.startswith(f"# TYPE {name} ")
        ]
        assert len(tidx) == 1, name
        help_line = lines[tidx[0] - 1]
        assert help_line.startswith(f"# HELP {name} "), help_line
        assert "dragonboat_tpu metric" not in help_line, help_line
    assert "dragonboat_devprof_wasted_rounds_total 14" in lines
    assert "dragonboat_devprof_capacity_groups 1000" in lines
    assert (
        'dragonboat_devprof_hbm_bytes{artifact="match",plane="quorum"} 1024'
        in lines
        or 'dragonboat_devprof_hbm_bytes{plane="quorum",artifact="match"} '
        "1024" in lines
    )
    assert "dragonboat_devprof_capture_active 0" in lines
    assert "dragonboat_devprof_captures_total 1" in lines
    # a second write is byte-identical (stable ordering incl. HELP)
    out2 = io.StringIO()
    reg.write_health_metrics(out2)
    assert out2.getvalue().splitlines() == lines


def test_hier_families_help_round_trip():
    """ISSUE 18 satellite: every ``dragonboat_hier_*`` family a HierObs
    registers carries its described ``# HELP`` immediately before its
    ``# TYPE`` (pre-registered at zero, so a scrape sees the whole
    surface before the first sub-quorum close), and the close/read/hold
    instruments land the expected values."""
    from dragonboat_tpu.raft.hier import HierObs

    reg = MetricsRegistry()
    obs = HierObs(reg)
    obs.commit_close(via_sub=True)
    obs.commit_close(via_sub=True)
    obs.commit_close(via_sub=False)
    obs.far_lag(7)
    obs.read_batch()
    obs.read_coalesced()
    obs.read_coalesced()
    obs.election_hold()
    out = io.StringIO()
    reg.write_health_metrics(out)
    lines = out.getvalue().splitlines()
    families = (
        "dragonboat_hier_subquorum_commit_total",
        "dragonboat_hier_fallback_commit_total",
        "dragonboat_hier_far_lag_entries",
        "dragonboat_hier_read_batches_total",
        "dragonboat_hier_reads_coalesced_total",
        "dragonboat_hier_election_holds_total",
    )
    for name in families:
        tidx = [
            i for i, l in enumerate(lines) if l.startswith(f"# TYPE {name} ")
        ]
        assert len(tidx) == 1, name
        help_line = lines[tidx[0] - 1]
        assert help_line.startswith(f"# HELP {name} "), help_line
        assert "dragonboat_tpu metric" not in help_line, help_line
    assert "dragonboat_hier_subquorum_commit_total 2" in lines
    assert "dragonboat_hier_fallback_commit_total 1" in lines
    assert "dragonboat_hier_far_lag_entries 7" in lines
    assert "dragonboat_hier_read_batches_total 1" in lines
    assert "dragonboat_hier_reads_coalesced_total 2" in lines
    assert "dragonboat_hier_election_holds_total 1" in lines
    # a second write is byte-identical (stable ordering incl. HELP)
    out2 = io.StringIO()
    reg.write_health_metrics(out2)
    assert out2.getvalue().splitlines() == lines


def test_recovery_families_help_round_trip():
    """ISSUE 17 satellite: every ``dragonboat_recovery_*`` family a
    RecoveryObs registers carries its described ``# HELP`` immediately
    before its ``# TYPE``, the actuation/skip/suppression publishers
    land the expected values, and the exposition is write-stable."""
    from dragonboat_tpu.obs.instruments import RecoveryObs
    from dragonboat_tpu.obs.recovery import MATRIX

    reg = MetricsRegistry()
    obs = RecoveryObs(reg, matrix=MATRIX)
    obs.action("quorum_at_risk", "evict_dead", duration_s=0.12)
    obs.dryrun("leader_flap", "transfer_leader")
    obs.skipped("rate_limited")
    obs.failure("devsm_rebind", "devsm_release")
    obs.suppressed("leader_flap", 1)
    out = io.StringIO()
    reg.write_health_metrics(out)
    lines = out.getvalue().splitlines()
    families = (
        "dragonboat_recovery_actions_total",
        "dragonboat_recovery_dryrun_total",
        "dragonboat_recovery_skipped_total",
        "dragonboat_recovery_suppressed_keys",
        "dragonboat_recovery_failures_total",
        "dragonboat_recovery_action_seconds",
    )
    for name in families:
        tidx = [
            i for i, l in enumerate(lines) if l.startswith(f"# TYPE {name} ")
        ]
        assert len(tidx) == 1, name
        help_line = lines[tidx[0] - 1]
        assert help_line.startswith(f"# HELP {name} "), help_line
        assert "dragonboat_tpu metric" not in help_line, help_line
    # the full matrix is zero-registered: a scrape distinguishes
    # "recovery off" (families absent) from "on but idle" (zeros)
    for det, action in MATRIX:
        assert any(
            l.startswith("dragonboat_recovery_actions_total")
            and f'detector="{det}"' in l and f'action="{action}"' in l
            for l in lines
        ), (det, action)
    assert any(
        l.startswith("dragonboat_recovery_actions_total")
        and 'detector="quorum_at_risk"' in l and 'action="evict_dead"' in l
        and l.endswith(" 1")
        for l in lines
    ), [l for l in lines if l.startswith("dragonboat_recovery_actions")]
    assert any(
        l.startswith("dragonboat_recovery_skipped_total")
        and 'reason="rate_limited"' in l and l.endswith(" 1")
        for l in lines
    )
    assert 'dragonboat_recovery_suppressed_keys{detector="leader_flap"} 1' \
        in lines
    # a second write is byte-identical (stable ordering incl. HELP)
    out2 = io.StringIO()
    reg.write_health_metrics(out2)
    assert out2.getvalue().splitlines() == lines


def test_mesh_families_help_round_trip():
    """ISSUE 16 satellite: every ``dragonboat_mesh_*`` family a MeshObs
    registers carries its described ``# HELP`` immediately before its
    ``# TYPE``, the placement/migration/concurrency publishers land the
    expected values, and the exposition round-trips byte-identically."""
    from dragonboat_tpu.obs import FlightRecorder
    from dragonboat_tpu.obs.instruments import MeshObs

    reg = MetricsRegistry()
    obs = MeshObs(FlightRecorder(capacity=4, stall_ms=0), reg, n_shards=2)
    obs.placement([3, 1])
    obs.migration(7, src=0, dst=1, wall_ms=2.5, counts=[2, 2])
    obs.concurrency(2)
    out = io.StringIO()
    reg.write_health_metrics(out)
    lines = out.getvalue().splitlines()
    families = (
        "dragonboat_mesh_shards",
        "dragonboat_mesh_groups",
        "dragonboat_mesh_migrations_total",
        "dragonboat_mesh_migration_ms",
        "dragonboat_mesh_dispatch_concurrency",
    )
    for name in families:
        tidx = [
            i for i, l in enumerate(lines) if l.startswith(f"# TYPE {name} ")
        ]
        assert len(tidx) == 1, name
        help_line = lines[tidx[0] - 1]
        assert help_line.startswith(f"# HELP {name} "), help_line
        assert "dragonboat_tpu metric" not in help_line, help_line
    assert "dragonboat_mesh_shards 2" in lines
    assert 'dragonboat_mesh_groups{shard="0"} 2' in lines
    assert 'dragonboat_mesh_groups{shard="1"} 2' in lines
    assert "dragonboat_mesh_migrations_total 1" in lines
    # any concurrency observation above 1 is the overlap evidence the
    # retired global dispatch mutex made impossible
    assert any(
        l.startswith('dragonboat_mesh_dispatch_concurrency_bucket{le="2"} 1')
        for l in lines
    ), [l for l in lines if l.startswith("dragonboat_mesh_dispatch")]
    # a second write is byte-identical (stable ordering incl. HELP)
    out2 = io.StringIO()
    reg.write_health_metrics(out2)
    assert out2.getvalue().splitlines() == lines


def test_lease_families_help_round_trip():
    """ISSUE 10 satellite: every ``dragonboat_lease_*`` family a LeaseObs
    registers (and the coordinator table's gauge) carries its described
    ``# HELP`` immediately before its ``# TYPE``, and the exposition
    round-trips byte-identically."""
    from dragonboat_tpu.lease import LeaseObs, LeaseTable

    reg = MetricsRegistry()
    obs = LeaseObs(reg)
    obs.grant()
    obs.read_local(6)
    obs.read_fallback()
    obs.expire()
    obs.cede()
    lt = LeaseTable()
    lt.configure(1, quorum=2, duration=8, self_id=1, voters=[1, 2, 3])
    lt.note_round({1: {2}}, 10)
    lt.publish(reg, 11)
    out = io.StringIO()
    reg.write_health_metrics(out)
    lines = out.getvalue().splitlines()
    families = (
        "dragonboat_lease_grants_total",
        "dragonboat_lease_expiries_total",
        "dragonboat_lease_ceded_total",
        "dragonboat_lease_reads_local_total",
        "dragonboat_lease_reads_fallback_total",
        "dragonboat_lease_remaining_validity_ticks",
        "dragonboat_lease_groups_held",
    )
    for name in families:
        tidx = [
            i for i, l in enumerate(lines) if l.startswith(f"# TYPE {name} ")
        ]
        assert len(tidx) == 1, name
        help_line = lines[tidx[0] - 1]
        assert help_line.startswith(f"# HELP {name} "), help_line
        # described, not the placeholder
        assert "dragonboat_tpu metric" not in help_line, help_line
    assert "dragonboat_lease_groups_held 1" in lines
    assert "dragonboat_lease_reads_local_total 1" in lines
    # a second write is byte-identical (stable ordering incl. HELP)
    out2 = io.StringIO()
    reg.write_health_metrics(out2)
    assert out2.getvalue().splitlines() == lines


def test_raft_event_listener_metrics_and_forwarding():
    reg = MetricsRegistry()
    seen = []

    class UserListener:
        def leader_updated(self, info):
            seen.append(info)

    lst = RaftEventListener(UserListener(), registry=reg, enabled=True)
    lst.campaign_launched(5, 1, 2)
    lst.leader_updated(5, 1, leader_id=1, term=2)
    lst.proposal_dropped(5, 1, [object(), object()])
    labels = {"cluster_id": "5", "node_id": "1"}
    assert (
        reg.counter_value("dragonboat_raftnode_campaign_launched_total", labels)
        == 1
    )
    assert reg.gauge_value("dragonboat_raftnode_has_leader", labels) == 1
    assert reg.gauge_value("dragonboat_raftnode_term", labels) == 2
    assert (
        reg.counter_value("dragonboat_raftnode_proposal_dropped_total", labels)
        == 2
    )
    assert len(seen) == 1 and seen[0].leader_id == 1 and seen[0].term == 2


def test_raft_event_listener_survives_user_exception():
    class Bad:
        def leader_updated(self, info):
            raise RuntimeError("boom")

    lst = RaftEventListener(Bad(), registry=MetricsRegistry())
    lst.leader_updated(1, 1, 1, 1)  # must not raise


def test_sys_event_listener_serialized_delivery():
    got = []
    done = threading.Event()

    class UserListener:
        def node_ready(self, ev):
            got.append(ev)

        def membership_changed(self, ev):
            raise RuntimeError("user bug")  # must not kill delivery

        def snapshot_created(self, ev):
            got.append(ev)
            done.set()

    lst = SysEventListener(UserListener())
    lst.publish(SystemEvent(type=SystemEventType.NODE_READY, cluster_id=9))
    lst.publish(SystemEvent(type=SystemEventType.MEMBERSHIP_CHANGED))
    lst.publish(
        SystemEvent(type=SystemEventType.SNAPSHOT_CREATED, cluster_id=9, index=4)
    )
    assert done.wait(5)
    lst.stop()
    assert [e.type for e in got] == [
        SystemEventType.NODE_READY,
        SystemEventType.SNAPSHOT_CREATED,
    ]
    assert got[1].index == 4
    # counters track all publishes regardless of listener
    assert (
        lst.registry.counter_value(
            "dragonboat_system_event_total", {"type": "node_ready"}
        )
        >= 1
    )


class _CountSM:
    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def test_nodehost_end_to_end_events(tmp_path):
    """NODE_READY, LeaderUpdated, snapshot + log-compaction events and
    shutdown events all fire across a real single-replica lifecycle."""
    events = []
    leaders = []
    ready = threading.Event()
    created = threading.Event()

    class SysListener:
        def __getattr__(self, name):  # record everything
            def cb(ev):
                events.append(ev)
                if ev.type is SystemEventType.NODE_READY:
                    ready.set()
                if ev.type is SystemEventType.SNAPSHOT_CREATED:
                    created.set()

            return cb

    class RaftListener:
        def leader_updated(self, info):
            leaders.append(info)

    router = ChanRouter()

    def rpc_factory(src, rh, ch):
        return ChanTransport(src, rh, ch, router=router)

    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT_MS,
            raft_address="ev:1",
            raft_rpc_factory=rpc_factory,
            enable_metrics=True,
            system_event_listener=SysListener(),
            raft_event_listener=RaftListener(),
        )
    )
    try:
        nh.start_cluster(
            {1: "ev:1"},
            False,
            lambda c, n: _CountSM(c, n),
            Config(
                cluster_id=11,
                node_id=1,
                election_rtt=10,
                heartbeat_rtt=1,
                compaction_overhead=2,
            ),
        )
        assert ready.wait(5)
        deadline = time.time() + 5
        while time.time() < deadline:
            _, ok = nh.get_leader_id(11)
            if ok:
                break
            time.sleep(0.01)
        s = nh.get_noop_session(11)
        for _ in range(5):
            nh.sync_propose(s, b"x", timeout=5.0)
        nh.sync_request_snapshot(11, timeout=5.0)
        assert created.wait(5)
    finally:
        nh.stop()
    types = {e.type for e in events}
    assert SystemEventType.NODE_READY in types
    assert SystemEventType.SNAPSHOT_CREATED in types
    assert SystemEventType.NODE_HOST_SHUTTING_DOWN in types
    assert any(li.leader_id for li in leaders)
    # metrics populated under enable_metrics
    assert (
        nh.raft_events.registry.gauge_value(
            "dragonboat_raftnode_has_leader",
            {"cluster_id": "11", "node_id": "1"},
        )
        == 1
    )


def test_hostproc_families_help_round_trip():
    """ISSUE 12 satellite: every ``dragonboat_hostproc_*`` family a
    HostProcObs registers carries its described ``# HELP`` immediately
    before its ``# TYPE`` (the lease/devsm pattern), labeled families
    expose one series per role, and the counters land where the hooks
    put them."""
    from dragonboat_tpu.obs.instruments import HostProcObs

    reg = MetricsRegistry()
    obs = HostProcObs(reg)
    obs.workers_alive(3)
    obs.restart()
    obs.ring_depth(512)
    obs.ring_full("encode")
    obs.fallback("apply")
    obs.call("wal", 1.25)
    out = io.StringIO()
    reg.write_health_metrics(out)
    lines = out.getvalue().splitlines()
    families = (
        "dragonboat_hostproc_workers_alive",
        "dragonboat_hostproc_worker_restarts_total",
        "dragonboat_hostproc_ring_depth",
        "dragonboat_hostproc_ring_full_total",
        "dragonboat_hostproc_fallbacks_total",
        "dragonboat_hostproc_calls_total",
        "dragonboat_hostproc_worker_wall_ms",
    )
    for name in families:
        tidx = [
            i for i, l in enumerate(lines)
            if l.startswith(f"# TYPE {name} ")
        ]
        assert len(tidx) == 1, name
        assert lines[tidx[0] - 1].startswith(f"# HELP {name} "), name
    assert "dragonboat_hostproc_workers_alive 3" in lines
    assert "dragonboat_hostproc_worker_restarts_total 1" in lines
    assert "dragonboat_hostproc_ring_depth 512" in lines
    assert 'dragonboat_hostproc_ring_full_total{role="encode"} 1' in lines
    assert 'dragonboat_hostproc_fallbacks_total{role="apply"} 1' in lines
    assert 'dragonboat_hostproc_calls_total{role="wal"} 1' in lines
    # the per-stage worker-wall histogram has sum/count per role
    assert any(
        l.startswith('dragonboat_hostproc_worker_wall_ms_count{role="wal"}')
        for l in lines
    )
