"""Integration tests for the native replication fast lane.

Three NodeHosts over the real framed-TCP transport with the durable native
LogDB — the deployment shape where `ExpertConfig.fast_lane` activates.
Covers: enrollment at quiescence, native steady-state replication with
client completion, in-lane ReadIndex on both leader and followers (zero
ejects), observer/witness-bearing enrollment, follower and leader
kill/restart recovery through the eject protocol, and full-cluster
restart replaying natively written WAL records through the Python path.
"""
from __future__ import annotations

import socket

from tests import loadwait
import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.native import natraft

# heavy multi-NodeHost tests serialize on one xdist worker
# (--dist loadgroup): 4-way-parallel multiprocess clusters
# starve each other on an 8-vCPU box
pytestmark = [pytest.mark.skipif(
    not natraft.available(), reason="libnatraft unavailable"
), pytest.mark.xdist_group("heavy-multiprocess")]

RTT = 20
CID = 31


class CountSM:
    def __init__(self, cluster_id, node_id):
        self.applied = []

    def update(self, cmd):
        self.applied.append(bytes(cmd))
        return Result(value=len(self.applied))

    def lookup(self, query):
        return list(self.applied)

    def save_snapshot(self, w, files, done):
        import json

        data = json.dumps([c.decode() for c in self.applied]).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import json

        n = int.from_bytes(r.read(8), "little")
        self.applied = [c.encode() for c in json.loads(r.read(n).decode())]

    def close(self):
        pass


def _ports(n):
    return loadwait.ports(n)


def _mk(i, addrs, tmp_path, sms, snapshot_entries=0, join=False,
        is_observer=False, is_witness=False, initial=None):
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / f"nh{i}"),
            rtt_millisecond=RTT,
            raft_address=addrs[i],
            expert=ExpertConfig(fast_lane=True, logdb_shards=2),
        )
    )
    assert nh.fastlane is not None and nh.fastlane.enabled

    def create(cluster_id, node_id):
        sm = CountSM(cluster_id, node_id)
        sms[i] = sm
        return sm

    nh.start_cluster(
        {} if join else (initial if initial is not None else addrs),
        join, create,
        Config(cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
               snapshot_entries=snapshot_entries, compaction_overhead=5,
               is_observer=is_observer, is_witness=is_witness),
    )
    return nh


def _cluster(tmp_path, sms, n=3):
    ports = _ports(n)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(n)}
    nhs = {i: _mk(i, addrs, tmp_path, sms) for i in addrs}
    return nhs, addrs


def _leader(nhs, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs.values():
            try:
                lid, ok = nh.get_leader_id(CID)
                if ok and lid in nhs:
                    return lid, nhs[lid]
            except Exception:
                pass
        time.sleep(0.05)
    raise TimeoutError("no leader")


def _wait_enrolled(nh, timeout=45.0, want=True):
    node = nh.get_node(CID)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if node.fast_lane == want:
            return True
        time.sleep(0.05)
    return False


def _propose_all(nh, payloads, deadline_s=180.0):
    """Exact-count helper: every payload must complete exactly once, so
    timed-out proposes are NOT retried (outcome unknown -> duplicate
    risk); instead the tick budget is generous and completion is waited
    to a shared wall deadline, so CI starvation stretches runtime, not
    the verdict."""
    s = nh.get_noop_session(CID)
    deadline = time.time() + deadline_s
    pending = [nh.propose(s, p, timeout=60.0) for p in payloads]
    for rs in pending:
        r = rs.wait(max(0.1, deadline - time.time()))
        assert r.completed, r
    return len(pending)


def _wait_converged(sms, count, timeout=90.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        lens = [len(sm.applied) for sm in sms.values()]
        if all(n == count for n in lens):
            return True
        time.sleep(0.1)
    raise AssertionError(
        f"replicas did not converge: {[len(sm.applied) for sm in sms.values()]}"
        f" != {count}"
    )


def _stop_all(nhs):
    # regression pin (round-3 chaos failure): a span delivered before the
    # node was registered was dropped, losing committed entries from the
    # apply stream; registration now precedes native enrollment, so this
    # must never fire
    drops = {
        i: nh.fastlane.dropped_spans
        for i, nh in nhs.items()
        if nh.fastlane is not None and nh.fastlane.enabled
    }
    for nh in nhs.values():
        try:
            nh.stop()
        except Exception:
            pass
    assert all(v == 0 for v in drops.values()), f"dropped apply spans: {drops}"


def test_enroll_and_native_replication(tmp_path):
    sms = {}
    nhs, _ = _cluster(tmp_path, sms)
    try:
        lid, leader = _leader(nhs)
        assert _wait_enrolled(leader), "leader never enrolled"
        n = _propose_all(leader, [b"k%d" % i for i in range(200)])
        _wait_converged(sms, n)
        # under full-suite load an eject window can push a slice of a
        # batch to the scalar path while the cluster stays healthy (the
        # r07 contention-flake class): top up through re-enrollment
        # until the lane has provably carried >= 200 proposals; a
        # genuinely broken lane never accumulates them and still fails
        for attempt in range(4):
            if leader.fastlane.stats()["proposed"] >= 200:
                break
            assert _wait_enrolled(leader), "lane never re-enrolled"
            n += _propose_all(
                leader, [b"t%d-%d" % (attempt, i) for i in range(100)]
            )
            _wait_converged(sms, n)
        st = leader.fastlane.stats()
        assert st["proposed"] >= 200, st
        assert st["commits_advanced"] > 0
        # followers served acks natively once enrolled
        total_fast = sum(nh.fastlane.stats()["ingested_fast"] for nh in nhs.values())
        assert total_fast > 0
        # order is identical across replicas
        base = sms[lid].applied
        for i, sm in sms.items():
            assert sm.applied == base, f"replica {i} diverged"
    finally:
        _stop_all(nhs)


def test_leader_read_index_served_natively(tmp_path):
    """Historic name: reads used to force an eject; since the native
    ReadIndex (hinted heartbeats + echo quorum) the leader serves them
    in-lane — assert the read completes AND costs no eject."""
    sms = {}
    nhs, _ = _cluster(tmp_path, sms)
    try:
        lid, leader = _leader(nhs)
        assert _wait_enrolled(leader)
        _propose_all(leader, [b"a", b"b", b"c"])
        node = leader.get_node(CID)
        before = dict(leader.fastlane.eject_reasons)
        got = leader.sync_read(CID, None, timeout=10.0)
        assert len(got) == 3
        assert node.fast_lane, "leader read should not leave the lane"
        assert leader.fastlane.eject_reasons == before
        _propose_all(leader, [b"d"])
        _wait_converged(sms, 4)
        assert not node._stopped.is_set()
    finally:
        _stop_all(nhs)


def test_follower_kill_and_restart(tmp_path):
    sms = {}
    nhs, addrs = _cluster(tmp_path, sms)
    try:
        lid, leader = _leader(nhs)
        assert _wait_enrolled(leader)
        _propose_all(leader, [b"w%d" % i for i in range(20)])
        victim = next(i for i in nhs if i != lid)
        nhs[victim].stop()
        # quorum holds: native leader keeps committing with one follower
        _propose_all(leader, [b"x%d" % i for i in range(20)])
        # restart the follower; recovery runs through the scalar path
        nhs[victim] = _mk(victim, addrs, tmp_path, sms)
        _propose_all(leader, [b"y%d" % i for i in range(20)])
        _wait_converged(sms, 60, timeout=60.0)
    finally:
        _stop_all(nhs)


def test_leader_kill_failover(tmp_path):
    sms = {}
    nhs, addrs = _cluster(tmp_path, sms)
    try:
        lid, leader = _leader(nhs)
        assert _wait_enrolled(leader)
        _propose_all(leader, [b"p%d" % i for i in range(10)])
        nhs.pop(lid).stop()
        # followers eject on contact loss and elect a new leader scalar-side
        new_lid, new_leader = _leader(nhs, timeout=90.0)
        assert new_lid != lid
        _propose_all(new_leader, [b"q%d" % i for i in range(10)])
        live = {i: sm for i, sm in sms.items() if i in nhs}
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(len(sm.applied) == 20 for sm in live.values()):
                break
            time.sleep(0.1)
        assert all(len(sm.applied) == 20 for sm in live.values())
    finally:
        _stop_all(nhs)


def test_full_restart_replays_native_wal(tmp_path):
    """Entries written by the native core must replay through the normal
    Python recovery path (byte-identical record formats)."""
    sms = {}
    nhs, addrs = _cluster(tmp_path, sms)
    lid, leader = _leader(nhs)
    assert _wait_enrolled(leader)
    _propose_all(leader, [b"r%d" % i for i in range(30)])
    _wait_converged(sms, 30)
    _stop_all(nhs)

    sms2 = {}
    nhs2 = {i: _mk(i, addrs, tmp_path, sms2) for i in addrs}
    try:
        lid2, leader2 = _leader(nhs2, timeout=90.0)
        _propose_all(leader2, [b"s%d" % i for i in range(5)])
        _wait_converged(sms2, 35, timeout=120.0)
        base = sms2[lid2].applied
        assert base[:30] == [b"r%d" % i for i in range(30)]
    finally:
        _stop_all(nhs2)


def test_periodic_snapshot_forces_eject(tmp_path):
    """snapshot_entries > 0: the enrolled step detects the due snapshot,
    ejects, and the normal auto-snapshot machinery runs."""
    sms = {}
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = {
        i: _mk(i, addrs, tmp_path, sms, snapshot_entries=25) for i in addrs
    }
    try:
        lid, leader = _leader(nhs)
        node = leader.get_node(CID)
        _propose_all(leader, [b"z%d" % i for i in range(80)])
        _wait_converged(sms, 80)
        deadline = time.time() + 30
        while time.time() < deadline:
            if node.sm.get_snapshot_index() > 0:
                break
            time.sleep(0.2)
        assert node.sm.get_snapshot_index() > 0, "auto snapshot never ran"
    finally:
        _stop_all(nhs)


def test_propose_batch_both_paths(tmp_path):
    """propose_batch == N propose calls: one future per command, applied
    in order, on the native lane and on the scalar fallback."""
    sms = {}
    nhs, _ = _cluster(tmp_path, sms)
    try:
        lid, leader = _leader(nhs)
        assert _wait_enrolled(leader)
        s = leader.get_noop_session(CID)
        states = leader.propose_batch(s, [b"b%d" % i for i in range(40)], 10.0)
        assert len(states) == 40
        for rs in states:
            assert rs.wait(30.0).completed
        # force the scalar path (eject via a leader transfer request slot
        # check is heavyweight; simply eject directly) and batch again
        node = leader.get_node(CID)
        node.fast_eject()
        states = leader.propose_batch(s, [b"c%d" % i for i in range(40)], 10.0)
        for rs in states:
            assert rs.wait(30.0).completed
        _wait_converged(sms, 80)
        base = sms[lid].applied
        assert base == [b"b%d" % i for i in range(40)] + [
            b"c%d" % i for i in range(40)
        ]
        for i, sm in sms.items():
            assert sm.applied == base
    finally:
        _stop_all(nhs)


def test_follower_read_served_natively_no_eject(tmp_path):
    """A linearizable read on an enrolled FOLLOWER forwards natively
    (READ_INDEX to the leader, READ_INDEX_RESP back — natraft twins of
    handle_follower_read_index / handle_follower_read_index_resp,
    raft.py:1258,1271) and completes without costing the group an
    eject/re-enroll cycle."""
    sms = {}
    nhs, _ = _cluster(tmp_path, sms)
    try:
        lid, leader = _leader(nhs)
        _propose_all(leader, [b"a", b"b", b"c"])
        fid = next(i for i in nhs if i != lid)
        follower = nhs[fid]
        assert _wait_enrolled(follower)
        node = follower.get_node(CID)
        before = dict(follower.fastlane.eject_reasons)
        for _ in range(5):
            got = follower.sync_read(CID, None, timeout=10.0)
            assert len(got) == 3
        assert node.fast_lane, "follower read should not leave the lane"
        after = follower.fastlane.eject_reasons
        assert after.get("read", 0) == before.get("read", 0)
        assert after.get("read-fallback", 0) == before.get("read-fallback", 0)
        # the leader meanwhile keeps its own native read service
        assert len(leader.sync_read(CID, None, timeout=10.0)) == 3
    finally:
        _stop_all(nhs)


def test_observer_group_enrolls_and_replicates(tmp_path):
    """A group WITH an observer still enrolls (observers become
    non-voting native replication targets — reference nonVoting member
    semantics); proposals commit at voter quorum through the lane, and
    the observer's SM catches up from natively-proposed entries."""
    sms = {}
    ports = _ports(4)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(4)}
    voters = {i: addrs[i] for i in (1, 2, 3)}
    nhs = {i: _mk(i, addrs, tmp_path, sms, initial=voters) for i in (1, 2, 3)}
    try:
        lid, leader = _leader(nhs)
        _propose_all(leader, [b"a", b"b"])
        leader.sync_request_add_observer(CID, 4, addrs[4], timeout=30.0)
        nhs[4] = _mk(4, addrs, tmp_path, sms, join=True, is_observer=True)
        # the config change ejected; the group must RE-enroll with the
        # observer present (the old eligibility refused observer-bearing
        # groups outright)
        assert _wait_enrolled(leader), "observer-bearing group never enrolled"
        st0 = leader.fastlane.stats()
        _propose_all(leader, [b"c%d" % i for i in range(30)])
        st1 = leader.fastlane.stats()
        assert st1["proposed"] > st0["proposed"], (
            "proposals bypassed the native lane"
        )
        # the observer (never part of quorum) still receives everything
        deadline = time.time() + 30
        while time.time() < deadline:
            if sms.get(4) is not None and len(sms[4].applied) == 32:
                break
            time.sleep(0.05)
        assert sms.get(4) is not None and len(sms[4].applied) == 32, (
            "observer did not catch up through the native lane"
        )
        # quorum stays voter-only: stop BOTH non-leader voters; with only
        # the leader + observer alive a proposal must NOT complete
        for i in (1, 2, 3):
            if i != lid:
                nhs[i].stop()
                del nhs[i]
        s = nhs[lid].get_noop_session(CID)
        rs = nhs[lid].propose(s, b"never", timeout=2.0)
        assert not rs.wait(3.0).completed, (
            "observer was counted toward the commit quorum"
        )
    finally:
        _stop_all(nhs)


def test_witness_group_enrolls_and_witness_ack_commits(tmp_path):
    """A witness-bearing group enrolls; the witness receives metadata-only
    native replication and its ack CARRIES quorum weight: with one voter
    stopped, leader + witness keep committing (reference witness role)."""
    sms = {}
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    voters = {i: addrs[i] for i in (1, 2)}
    nhs = {i: _mk(i, addrs, tmp_path, sms, initial=voters) for i in (1, 2)}
    try:
        lid, leader = _leader(nhs)
        _propose_all(leader, [b"pre"])
        leader.sync_request_add_witness(CID, 3, addrs[3], timeout=30.0)
        nhs[3] = _mk(3, addrs, tmp_path, sms, join=True, is_witness=True)
        deadline = time.time() + 20
        while time.time() < deadline:
            m = leader.sync_get_cluster_membership(CID, timeout=10.0)
            if 3 in m.witnesses:
                break
            time.sleep(0.1)
        assert 3 in m.witnesses
        assert _wait_enrolled(leader), "witness-bearing group never enrolled"
        # the lane can EJECT under full-suite load between the enroll
        # check and the proposals (liveness timeouts on a starved box —
        # the r07 contention-flake class): retry through re-enrollment
        # instead of asserting on a single window.  A genuinely broken
        # lane never carries a batch and still fails here.
        for attempt in range(4):
            st0 = leader.fastlane.stats()
            _propose_all(
                leader, [b"w%d-%d" % (attempt, i) for i in range(20)]
            )
            if leader.fastlane.stats()["proposed"] > st0["proposed"]:
                break
            assert _wait_enrolled(leader), "lane never re-enrolled"
        else:
            raise AssertionError(
                f"fast lane carried no proposals in 4 batches: "
                f"{leader.fastlane.stats()}"
            )
        # the witness's scalar log holds only metadata twins
        r3 = nhs[3].get_node(CID).peer.raft
        deadline = time.time() + 20
        while time.time() < deadline and r3.log.last_index() < 22:
            time.sleep(0.05)
        from dragonboat_tpu.wire import EntryType

        ents = r3.log.get_entries(
            r3.log.first_index(), r3.log.last_index() + 1, 1 << 62
        )
        assert ents and all(
            e.type in (EntryType.METADATA, EntryType.CONFIG_CHANGE)
            for e in ents
        ), "witness log must hold only METADATA/CONFIG_CHANGE entries"
        # stop the OTHER voter: leader + witness = 2 of 3 voting members,
        # proposals must still complete (the witness ack is the quorum)
        other = next(i for i in (1, 2) if i != lid)
        nhs[other].stop()
        del nhs[other]
        _propose_all(nhs[lid], [b"after-voter-loss"])
    finally:
        _stop_all(nhs)
