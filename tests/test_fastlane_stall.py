"""Scheduling-stall robustness of the native fast lane.

BENCH_r04's flagship run on a contended box collapsed enrollment duty to
0.706 with 828 ejects; the idle-box capture of the same HEAD held 0.9998
with 0.  The mechanism: wall-clock liveness timeouts (contact-loss,
check-quorum) firing when the PROCESS was off-CPU, not when a peer was
actually silent — each spurious eject exiles the group to the scalar path
for 2+ election windows.  The reference never meets this failure mode
because its benchmarks own their machines (README.md Performance §); a
framework that shares a box must not shed a third of its throughput to
scheduler noise.

Defenses under test (natraft.cpp ``clock_pass``/``clock_main``):

1. **Stall compensation** — the clock thread measures the gap between its
   own passes; a gap beyond the stall threshold is time nobody observed
   the peers (remote heartbeats sat unread in socket buffers), so every
   eject stamp shifts forward by it.  A SIGSTOP'd replica must resume
   without a single contact-loss eject: the leader's queued heartbeats
   re-establish contact the moment the readers wake.
2. **Dedicated clock thread** — heartbeats/timeouts no longer ride behind
   the round thread's batch staging, so a heavy data-plane pass cannot
   starve them.
3. **2x contact-loss window** — eject is a fallback (scalar raft re-runs
   its own election clock after the handoff), so the margin absorbs
   remote-side heartbeat jitter at little failover cost.

The replica is frozen for ~4 election timeouts — far past both the 1x
and 2x windows, so the test discriminates compensation from margin.
A subprocess harness (one NodeHost per process, real TCP) is required:
SIGSTOP must freeze every thread of one replica while its peers run on.
"""
from __future__ import annotations

import json
import os
import signal
import socket

from tests import loadwait
import subprocess
import sys
import threading
import time

import pytest

pytestmark = pytest.mark.xdist_group("heavy-multiprocess")

CID_COUNT = 4
RTT = 20
ELECTION_RTT = 10  # elect window 400ms; native eject window 2x = 800ms


def _rank_main() -> int:
    from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
    from dragonboat_tpu.config import ExpertConfig

    rank = int(os.environ["STALL_RANK"])
    addrs = {
        i + 1: a for i, a in enumerate(os.environ["STALL_ADDRS"].split(","))
    }
    nid = rank + 1
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=os.path.join(os.environ["STALL_DIR"], f"nh{rank}"),
            rtt_millisecond=RTT,
            raft_address=addrs[nid],
            expert=ExpertConfig(fast_lane=True, logdb_shards=2),
        )
    )

    class KVSM:
        def __init__(self, cluster_id, node_id):
            self.kv = {}

        def update(self, cmd):
            k, v = cmd.decode().split("=", 1)
            self.kv[k] = v
            return Result(value=len(self.kv))

        def lookup(self, query):
            return self.kv.get(query)

        def get_hash(self):
            return 0

        def save_snapshot(self, w, files, done):
            data = json.dumps(sorted(self.kv.items())).encode()
            w.write(len(data).to_bytes(8, "little") + data)

        def recover_from_snapshot(self, r, files, done):
            n = int.from_bytes(r.read(8), "little")
            self.kv = dict(json.loads(r.read(n).decode()))

        def close(self):
            pass

    for cid in range(1, CID_COUNT + 1):
        nh.start_cluster(
            addrs, False, lambda c, n: KVSM(c, n),
            Config(cluster_id=cid, node_id=nid, election_rtt=ELECTION_RTT,
                   heartbeat_rtt=1),
        )

    def emit(tag, obj=None):
        sys.stdout.write(tag + (" " + json.dumps(obj) if obj else "") + "\n")
        sys.stdout.flush()

    emit("READY")
    for line in sys.stdin:
        cmd = line.strip()
        if cmd == "ENROLLED":
            n = sum(
                1 for cid in range(1, CID_COUNT + 1)
                if (nd := nh.get_node(cid)) is not None and nd.fast_lane
            )
            emit("ENROLLED", {"n": n})
        elif cmd == "CAMPAIGN":
            for cid in range(1, CID_COUNT + 1):
                nd = nh.get_node(cid)
                if nd is not None:
                    nd.request_campaign()
            emit("CAMPAIGNED")
        elif cmd.startswith("WRITE "):
            j = int(cmd.split()[1])
            done = 0
            for cid in range(1, CID_COUNT + 1):
                nd = nh.get_node(cid)
                if nd is None or not nd.is_leader():
                    continue
                s = nh.get_noop_session(cid)
                rs = nh.propose(s, f"k{j}=v{j}".encode(), timeout=5.0)
                if rs.wait(5.0).completed:
                    done += 1
            emit("WROTE", {"done": done})
        elif cmd == "LEADERS":
            n = sum(
                1 for cid in range(1, CID_COUNT + 1)
                if (nd := nh.get_node(cid)) is not None and nd.is_leader()
            )
            emit("LEADERS", {"n": n})
        elif cmd == "STATS":
            st = nh.fastlane.stats() if nh.fastlane else {}
            emit("STATS", {
                "eject_reasons": st.get("eject_reasons", {}),
                "clock_stalls": st.get("clock_stalls", 0),
                "clock_stall_ms": st.get("clock_stall_ms", 0),
                "enrolled_replicas": st.get("enrolled_replicas", 0),
            })
        elif cmd == "EXIT":
            break
    nh.stop()
    return 0


class _Host:
    def __init__(self, idx, env):
        self.idx = idx
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--rank"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            env=env, text=True,
        )
        import queue as _q

        self.lines = _q.Queue()

        def _reader(p, q):
            for ln in p.stdout:
                q.put(ln)
            q.put(None)

        threading.Thread(
            target=_reader, args=(self.proc, self.lines), daemon=True
        ).start()

    def send(self, cmd):
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.flush()

    def expect(self, tag, timeout=60.0):
        import queue as _q

        from tests.loadwait import scaled

        # load-scaled: the subprocess replies ride three Python processes
        # sharing the sweep's starved cores (r07 contention-flake class)
        timeout = scaled(timeout)
        deadline = time.time() + timeout
        while True:
            left = deadline - time.time()
            if left <= 0:
                raise TimeoutError(f"host{self.idx}: no {tag} in {timeout}s")
            try:
                ln = self.lines.get(timeout=min(left, 1.0))
            except _q.Empty:
                continue
            if ln is None:
                raise RuntimeError(f"host{self.idx} died waiting for {tag}")
            if ln.startswith(tag):
                rest = ln[len(tag):].strip()
                return json.loads(rest) if rest else None


def _ports(n):
    return loadwait.ports(n)


def test_sigstop_resume_without_contact_loss_ejects(tmp_path):
    addrs = ",".join(f"127.0.0.1:{p}" for p in _ports(3))
    hosts = []
    try:
        for i in range(3):
            env = dict(os.environ)
            env.update(
                STALL_RANK=str(i), STALL_ADDRS=addrs,
                STALL_DIR=str(tmp_path),
                PYTHONPATH=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                # keep the subprocesses off any device plugin
                JAX_PLATFORMS="cpu",
            )
            hosts.append(_Host(i, env))
        for h in hosts:
            h.expect("READY", 120)
        hosts[0].send("CAMPAIGN")
        hosts[0].expect("CAMPAIGNED")

        # wait until every replica of every group is enrolled
        deadline = time.time() + 120
        while time.time() < deadline:
            n = 0
            for h in hosts:
                h.send("ENROLLED")
                n += h.expect("ENROLLED")["n"]
            if n == 3 * CID_COUNT:
                break
            time.sleep(0.3)
        else:
            raise AssertionError("groups never fully enrolled")

        hosts[0].send("WRITE 1")
        assert hosts[0].expect("WROTE")["done"] >= 1

        # ---- freeze a follower host for ~4 election windows ----
        victim = hosts[2]
        victim.proc.send_signal(signal.SIGSTOP)
        time.sleep(4 * 2 * ELECTION_RTT * RTT / 1000.0)
        victim.proc.send_signal(signal.SIGCONT)

        # liveness through and after the freeze
        hosts[0].send("WRITE 2")
        assert hosts[0].expect("WROTE")["done"] >= 1
        time.sleep(1.0)

        victim.send("STATS")
        st = victim.expect("STATS")
        # the compensation must have observed the freeze...
        assert st["clock_stalls"] >= 1, st
        # ...and converted it into shifted stamps instead of ejects
        assert "contact-lost" not in st["eject_reasons"], st
        assert "quorum-lost" not in st["eject_reasons"], st
        # the frozen replica stays enrolled (no eject => no re-enroll churn)
        assert st["enrolled_replicas"] == CID_COUNT, st

        # peers must not have ejected either: with 3 replicas the leader
        # still holds check-quorum through the other live follower
        for h in hosts[:2]:
            h.send("STATS")
            s2 = h.expect("STATS")
            assert "quorum-lost" not in s2["eject_reasons"], (h.idx, s2)
    finally:
        for h in hosts:
            try:
                h.proc.send_signal(signal.SIGCONT)
            except Exception:
                pass
            try:
                h.send("EXIT")
            except Exception:
                pass
        for h in hosts:
            try:
                h.proc.wait(timeout=20)
            except Exception:
                h.proc.kill()


def test_dead_leader_still_detected_despite_compensation(tmp_path):
    """The complement guard: stall compensation must never mask a
    GENUINE failure.  Here the host holding every leader freezes for far
    longer than the eject window while its followers keep running — the
    followers' clocks are healthy (no local stall to compensate), so
    contact-loss MUST fire, the groups must eject to scalar raft, and a
    new leader on a live host must accept writes while the old one is
    still frozen."""
    addrs = ",".join(f"127.0.0.1:{p}" for p in _ports(3))
    hosts = []
    try:
        for i in range(3):
            env = dict(os.environ)
            env.update(
                STALL_RANK=str(i), STALL_ADDRS=addrs,
                STALL_DIR=str(tmp_path),
                PYTHONPATH=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                JAX_PLATFORMS="cpu",
            )
            hosts.append(_Host(i, env))
        for h in hosts:
            h.expect("READY", 120)
        # host 0 campaigns every group: it must lead ALL of them before
        # the freeze — otherwise a leader naturally elected elsewhere
        # during setup lets the post-freeze write succeed WITHOUT any
        # failover and the eject assertion below is vacuous (the flake)
        deadline = time.time() + 120
        while time.time() < deadline:
            hosts[0].send("CAMPAIGN")
            hosts[0].expect("CAMPAIGNED")
            time.sleep(0.5)
            hosts[0].send("LEADERS")
            if hosts[0].expect("LEADERS")["n"] == CID_COUNT:
                break
        else:
            raise AssertionError("host 0 never led every group")
        deadline = time.time() + 120
        while time.time() < deadline:
            n = 0
            for h in hosts:
                h.send("ENROLLED")
                n += h.expect("ENROLLED")["n"]
            if n == 3 * CID_COUNT:
                break
            time.sleep(0.3)
        else:
            raise AssertionError("groups never fully enrolled")
        hosts[0].send("WRITE 1")
        assert hosts[0].expect("WROTE")["done"] >= 1
        # leadership may have moved while enrolling; re-verify the premise
        hosts[0].send("LEADERS")
        assert hosts[0].expect("LEADERS")["n"] == CID_COUNT, (
            "premise lost: host 0 no longer leads every group"
        )

        # ---- freeze the LEADER host; followers stay healthy ----
        hosts[0].proc.send_signal(signal.SIGSTOP)
        try:
            # new leaders must emerge on the live hosts and accept writes
            deadline = time.time() + 90
            j = 1
            done = 0
            while time.time() < deadline and not done:
                j += 1
                for h in hosts[1:]:
                    h.send(f"WRITE {j}")
                    done += h.expect("WROTE", 30)["done"]
                time.sleep(0.2)
            assert done >= 1, "no live-host leader emerged while the " \
                "leader host was frozen"
            # ...and the genuine-failure detector is what fired
            fired = 0
            for h in hosts[1:]:
                h.send("STATS")
                st = h.expect("STATS")
                fired += st["eject_reasons"].get("contact-lost", 0)
            assert fired >= 1, "failover happened without a contact-loss " \
                "eject — compensation may be masking real failures"
        finally:
            hosts[0].proc.send_signal(signal.SIGCONT)
    finally:
        for h in hosts:
            try:
                h.proc.send_signal(signal.SIGCONT)
            except Exception:
                pass
            try:
                h.send("EXIT")
            except Exception:
                pass
        for h in hosts:
            try:
                h.proc.wait(timeout=20)
            except Exception:
                h.proc.kill()


if __name__ == "__main__" and "--rank" in sys.argv:
    sys.exit(_rank_main())
