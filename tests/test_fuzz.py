"""Codec + framing fuzz, snapshot bandwidth cap, transport counters.

Reference: ``raftpb/fuzz.go`` and ``internal/transport/fuzz.go`` (go-fuzz
entry points over wire decoding), ``tcp.go:430-437`` (snapshot token
bucket), ``internal/transport/metrics.go:21`` (counters).  VERDICT r2
item 9.
"""
from __future__ import annotations

import io
import random
import struct
import time
import zlib

import pytest

from dragonboat_tpu.wire import (
    Chunk,
    Entry,
    Message,
    MessageBatch,
    MessageType,
)
from dragonboat_tpu.wire.codec import (
    CodecError,
    decode_chunk,
    decode_entry,
    decode_message_batch,
    encode_chunk,
    encode_entry,
    encode_message_batch,
)

N_FUZZ = 10000


def _rand_bytes(rng, max_len=256):
    return bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, max_len)))


# ------------------------------------------------------------- codec fuzz

def test_fuzz_decode_random_bytes_never_crashes():
    """10k random inputs: every decoder either succeeds or raises a typed
    CodecError/ValueError — never IndexError/KeyError/MemoryError/hang."""
    rng = random.Random(1234)
    allowed = (CodecError, ValueError)
    for i in range(N_FUZZ):
        data = _rand_bytes(rng)
        for dec in (decode_entry, decode_message_batch, decode_chunk):
            try:
                dec(data)
            except allowed:
                pass
            except OverflowError:
                pass  # declared lengths beyond practical bounds
            # anything else (IndexError, struct.error, ...) fails the test


def test_fuzz_mutated_valid_encodings():
    """Bit-flipped valid encodings must decode or raise typed errors."""
    rng = random.Random(99)
    base = encode_message_batch(
        MessageBatch(
            requests=[
                Message(
                    type=MessageType.REPLICATE,
                    cluster_id=7,
                    from_=1,
                    to=2,
                    term=3,
                    entries=[Entry(index=i, term=2, cmd=b"payload") for i in range(1, 5)],
                )
            ],
            deployment_id=42,
            source_address="a:1",
        )
    )
    for _ in range(2000):
        buf = bytearray(base)
        for _ in range(rng.randrange(1, 6)):
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        try:
            decode_message_batch(bytes(buf))
        except (CodecError, ValueError, OverflowError):
            pass


def test_fuzz_roundtrip_random_messages():
    rng = random.Random(7)
    types = list(MessageType)
    for _ in range(500):
        m = Message(
            type=rng.choice(types),
            cluster_id=rng.getrandbits(32),
            from_=rng.getrandbits(16),
            to=rng.getrandbits(16),
            term=rng.getrandbits(24),
            log_term=rng.getrandbits(24),
            log_index=rng.getrandbits(24),
            commit=rng.getrandbits(24),
            reject=bool(rng.getrandbits(1)),
            hint=rng.getrandbits(40),
            hint_high=rng.getrandbits(40),
            entries=[
                Entry(
                    index=rng.getrandbits(16),
                    term=rng.getrandbits(16),
                    cmd=_rand_bytes(rng, 64),
                )
                for _ in range(rng.randrange(0, 4))
            ],
        )
        b = MessageBatch(requests=[m], deployment_id=1, source_address="x:1")
        out = decode_message_batch(encode_message_batch(b))
        got, want = out.requests[0], m
        assert (got.type, got.cluster_id, got.from_, got.to, got.term) == (
            want.type, want.cluster_id, want.from_, want.to, want.term
        )
        assert [e.cmd for e in got.entries] == [e.cmd for e in want.entries]


def test_fuzz_chunk_roundtrip():
    rng = random.Random(3)
    for _ in range(300):
        c = Chunk(
            cluster_id=rng.getrandbits(20),
            node_id=rng.getrandbits(8),
            from_=rng.getrandbits(8),
            index=rng.getrandbits(20),
            term=rng.getrandbits(16),
            chunk_id=rng.getrandbits(10),
            chunk_count=rng.getrandbits(10),
            chunk_size=rng.getrandbits(10),
            deployment_id=5,
            data=_rand_bytes(rng, 128),
        )
        out = decode_chunk(encode_chunk(c))
        assert (out.cluster_id, out.chunk_id, out.data) == (
            c.cluster_id, c.chunk_id, c.data
        )


# ---------------------------------------------------------- tcp framing

def test_fuzz_tcp_frames_rejected_cleanly():
    """Random/corrupted frames through the framing decoder raise
    TransportError/ConnectionError — never crash the serving loop."""
    from dragonboat_tpu.transport import tcp

    class FakeSock:
        def __init__(self, data):
            self._b = io.BytesIO(data)

        def recv(self, n):
            return self._b.read(n)

    rng = random.Random(5)
    for _ in range(2000):
        blob = _rand_bytes(rng, 64)
        try:
            tcp._recv_frame(FakeSock(blob))
        except (tcp.TransportError, ConnectionError):
            pass
    # a correct frame with a flipped payload byte must fail the crc
    payload = b"hello world"
    pcrc = zlib.crc32(payload)
    hdr_wo = struct.pack(">HHQI", tcp.MAGIC, tcp.RAFT_METHOD, len(payload), pcrc)
    frame = bytearray(hdr_wo + struct.pack(">I", zlib.crc32(hdr_wo)) + payload)
    frame[-1] ^= 0xFF
    with pytest.raises(tcp.TransportError):
        tcp._recv_frame(FakeSock(bytes(frame)))


# ------------------------------------------------- bandwidth token bucket

def test_token_bucket_limits_rate():
    from dragonboat_tpu.transport.bandwidth import TokenBucket

    tb = TokenBucket(100_000)  # 100KB/s, 100KB burst
    tb.take(100_000)  # drain the initial burst
    t0 = time.monotonic()
    tb.take(50_000)  # needs ~0.5s of refill
    elapsed = time.monotonic() - t0
    assert elapsed >= 0.35, f"bucket let 50KB through in {elapsed:.2f}s"


def test_token_bucket_unlimited_is_noop():
    from dragonboat_tpu.transport.bandwidth import TokenBucket
    from tests.loadwait import scaled

    tb = TokenBucket(0)
    t0 = time.monotonic()
    for _ in range(1000):
        tb.take(1 << 20)
    # load-aware margin: 1000 no-op takes cost microseconds; anything
    # near the bound is scheduler preemption, not the bucket sleeping
    assert time.monotonic() - t0 < scaled(0.5)


def test_snapshot_send_respects_bandwidth_cap(tmp_path):
    """A chunked snapshot file send through send_snapshot_chunks with a
    bucket takes at least bytes/rate seconds."""
    import threading

    from dragonboat_tpu.transport.bandwidth import TokenBucket
    from dragonboat_tpu.transport.snapshotsender import send_snapshot_chunks

    sent = []

    class Conn:
        def send_chunk(self, c):
            sent.append(c)

    blob = tmp_path / "snap.bin"
    blob.write_bytes(b"x" * 200_000)
    chunks = [
        Chunk(chunk_id=i, chunk_count=4, chunk_size=50_000,
              filepath=str(blob), data=(i * 50_000, 50_000))
        for i in range(4)
    ]
    bucket = TokenBucket(200_000)  # 200KB/s; 200KB payload, 200KB burst
    bucket.take(200_000)  # drain burst: the 4 chunks now need ~1s
    t0 = time.monotonic()
    send_snapshot_chunks(Conn(), chunks, threading.Event(), bucket=bucket)
    elapsed = time.monotonic() - t0
    assert len(sent) == 4
    assert elapsed >= 0.7, f"cap not enforced: {elapsed:.2f}s"


# -------------------------------------------------------------- counters

def test_transport_counters_on_live_traffic():
    from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
    from dragonboat_tpu.transport import ChanRouter, ChanTransport

    class SM:
        def __init__(self, c, n):
            self.n = 0

        def update(self, cmd):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, files, done):
            w.write(b"\0")

        def recover_from_snapshot(self, r, files, done):
            r.read()

        def close(self):
            pass

    router = ChanRouter()
    nhs = [
        NodeHost(
            NodeHostConfig(
                node_host_dir=":memory:",
                rtt_millisecond=10,
                raft_address=f"tm{i}:1",
                raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                    s, rh, ch, router=router
                ),
            )
        )
        for i in (1, 2, 3)
    ]
    addrs = {i: f"tm{i}:1" for i in (1, 2, 3)}
    try:
        for i, nh in enumerate(nhs, 1):
            nh.start_cluster(
                addrs, False, SM,
                Config(cluster_id=3, node_id=i, election_rtt=10, heartbeat_rtt=1),
            )
        nhs[0].get_node(3).request_campaign()
        deadline = time.time() + 20
        leader = None
        while leader is None and time.time() < deadline:
            for nh in nhs:
                lid, ok = nh.get_leader_id(3)
                if ok:
                    leader = nhs[lid - 1]
            time.sleep(0.02)
        s = leader.get_noop_session(3)
        for _ in range(10):
            assert leader.propose(s, b"x", timeout=5.0).wait(5.0).completed
        sent = leader.transport.metrics.value("dragonboat_transport_message_sent")
        recvd = leader.transport.metrics.value(
            "dragonboat_transport_message_received"
        )
        assert sent > 0, "no sent messages counted"
        assert recvd > 0, "no received messages counted"
    finally:
        for nh in nhs:
            nh.stop()


# ----------------------------------------------------------------------
# native fast-lane stream parser (natraft.cpp process_stream): the C
# frame reassembler faces raw network bytes — it must never crash, must
# reject corruption by signalling 0xFFFF, and must reproduce valid
# leftover frames byte-identically across arbitrary chunkings
# ----------------------------------------------------------------------


def _natraft_engine(tmp_path_factory=None):
    from dragonboat_tpu.native import natraft

    if not natraft.available():
        pytest.skip("libnatraft unavailable")
    return natraft.NatRaft("fuzz:1", deployment_id=7)


def _frame(method: int, payload: bytes) -> bytes:
    hdr = struct.pack(">HHQI", 0xAE7D, method, len(payload), zlib.crc32(payload))
    return hdr + struct.pack(">I", zlib.crc32(hdr)) + payload


def test_fuzz_natraft_stream_random_bytes_never_crash():
    nat = _natraft_engine()
    rng = random.Random(0xF57)
    try:
        for _ in range(300):
            conn = nat.conn_new()
            try:
                for _ in range(rng.randint(1, 5)):
                    blob = bytes(
                        rng.getrandbits(8) for _ in range(rng.randint(0, 400))
                    )
                    frames = nat.ingest_stream(conn, blob)
                    for method, _payload in frames:
                        assert 0 <= method <= 0xFFFF
            finally:
                nat.conn_free(conn)
    finally:
        nat.close()


def test_fuzz_natraft_stream_corrupt_frames_flagged():
    nat = _natraft_engine()
    rng = random.Random(0xF58)
    try:
        for _ in range(200):
            good = _frame(200, bytes(rng.getrandbits(8) for _ in range(40)))
            bad = bytearray(good)
            pos = rng.randrange(len(bad))
            bad[pos] ^= 1 << rng.randrange(8)
            conn = nat.conn_new()
            try:
                frames = nat.ingest_stream(conn, bytes(bad))
                # either the mutation survived CRC coincidences (frame
                # surfaces intact) or the stream is flagged fatal; silent
                # acceptance of corrupted bytes is the only failure mode
                for method, payload in frames:
                    if method == 200:
                        assert payload == good[20:]
                    else:
                        assert method == 0xFFFF
            finally:
                nat.conn_free(conn)
    finally:
        nat.close()


def test_fuzz_natraft_stream_chunking_invariance():
    """Any split of the byte stream yields the same leftover frames."""
    nat = _natraft_engine()
    rng = random.Random(0xF59)
    try:
        for _ in range(60):
            frames_in = []
            stream = b""
            for _ in range(rng.randint(1, 6)):
                method = rng.choice([200, 999, 555])
                payload = bytes(
                    rng.getrandbits(8) for _ in range(rng.randint(0, 200))
                )
                frames_in.append((method, payload))
                stream += _frame(method, payload)
            # reference parse: one shot
            conn = nat.conn_new()
            expect = nat.ingest_stream(conn, stream)
            nat.conn_free(conn)
            assert expect == frames_in
            # chunked parse: random split points
            conn = nat.conn_new()
            got = []
            pos = 0
            while pos < len(stream):
                n = rng.randint(1, max(1, len(stream) - pos))
                got.extend(nat.ingest_stream(conn, stream[pos : pos + n]))
                pos += n
            nat.conn_free(conn)
            assert got == frames_in
    finally:
        nat.close()


def test_fuzz_native_session_image_never_crashes():
    """natsm_sess_recover on adversarial snapshot images: random bytes,
    truncations of a valid image, and huge-varint length prefixes must
    reject cleanly (rc -1) or load — never crash, never accept an image
    whose re-serialization disagrees with a clean reload."""
    import random

    from dragonboat_tpu.native import natsm as natsm_mod

    if not natsm_mod.available():
        import pytest as _pytest

        _pytest.skip("native natsm unavailable")
    from dragonboat_tpu.native.natsm import (
        NativeKVStateMachine, NativeSessionManager,
    )
    from dragonboat_tpu.rsm.session import SessionManager
    from dragonboat_tpu.statemachine import Result

    rng = random.Random(123)
    py = SessionManager()
    for cid in range(1, 30):
        py.register_client_id(cid)
        s = py.client_registered(cid)
        for sid in range(1, rng.randrange(2, 6)):
            s.add_response(sid, Result(value=rng.randrange(1000),
                                       data=bytes(rng.randrange(20))))
    valid = py.save()
    user = NativeKVStateMachine(1, 1)
    try:
        nat = NativeSessionManager(user)
        # random garbage
        for _ in range(300):
            blob = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200)))
            try:
                nat.recover_image(blob)
                assert nat.save() is not None  # loaded: must re-serialize
            except ValueError:
                pass
        # truncations and single-byte mutations of a valid image; when
        # BOTH planes accept a mutated image they must load the IDENTICAL
        # store (duplicate-client-id images exercised the OrderedDict
        # replace-in-place semantics the native side now mirrors)
        for _ in range(300):
            if rng.random() < 0.5:
                blob = valid[: rng.randrange(len(valid))]
            else:
                b = bytearray(valid)
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
                blob = bytes(b)
            nat_ok = py_ok = False
            try:
                nat.recover_image(blob)
                nat_ok = True
            except ValueError:
                pass
            try:
                py_twin = SessionManager.load(blob)
                py_ok = True
            except Exception:
                py_ok = False
            if nat_ok and py_ok:
                assert nat.save() == py_twin.save()
                assert nat.hash() == py_twin.hash()
                assert len(nat) == len(py_twin)
        # huge varint count prefix (the 2^64-length class of attack)
        for pfx in (b"\xff" * 9 + b"\x01", b"\x80" * 10, b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x7f"):
            try:
                nat.recover_image(pfx + valid)
            except ValueError:
                pass
        # crafted duplicate-client-id image: first occurrence keeps its
        # position, value replaced — both planes must agree byte-for-byte
        dup = SessionManager()
        dup.register_client_id(2)
        dup.register_client_id(3)
        img = bytearray(dup.save())
        # rewrite the second session's client_id (3) to 2 in the image
        pos = img.rindex(3)
        img[pos] = 2
        crafted = bytes(img)
        nat.recover_image(crafted)
        twin = SessionManager.load(crafted)
        assert len(nat) == len(twin) == 1
        assert nat.save() == twin.save()
        # and the store still works after all that
        nat.recover_image(valid)
        assert nat.save() == valid
        assert nat.hash() == py.hash()
    finally:
        user.close()
