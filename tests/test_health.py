"""Cluster health plane suite (ISSUE 13).

Contracts under test:

- health-OFF structural identity: ``health_sample_ms=0`` constructs
  nothing — no sampler, no endpoint, no ``dragonboat_health_*``
  families, ``Node._health_track`` stays False and ``offload_commit``
  keeps its bit-identical path;
- detectors under injected faults: an ErrorFS-induced WAL stall opens
  ``commit_stall`` and closes on heal with a measured recovery
  duration; a netsplit opens ``quorum_at_risk`` on the check-quorum
  leader and closes on heal; ``kill -9`` of a hostproc worker opens
  ``worker_flap`` with a measured recovery duration;
- detector unit semantics on synthetic samples (apply-lag hysteresis,
  leader-flap windowing, lease-thrash, devsm-rebind, group-gone
  close);
- the live scrape endpoint: ``/metrics`` round-trips the full
  exposition (every ``# TYPE`` immediately preceded by its ``# HELP``),
  ``/healthz`` flips 200→503 on an open detector, ``/debug/health``
  serves the ring;
- sampler overhead: per-sample wall cost stays bounded (the <5%
  throughput assertion lives in the bench health axis).
"""
import json
import os
import signal
import time
import urllib.error
import urllib.request

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result, vfs
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.logdb import open_logdb
from dragonboat_tpu.logdb.kv import WalKV
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.obs.health import DETECTORS, HealthSampler
from dragonboat_tpu.events import MetricsRegistry
from dragonboat_tpu.transport import ChanRouter, ChanTransport

from tests.loadwait import scaled, wait_until

RTT_MS = 5
CID = 930


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_host(addr="hl:1", router=None, health_ms=0, metrics_addr="",
             metrics=True, engine="scalar", compartments=False,
             host_workers=0, tmpdir=None, logdb_factory=None, fs=None):
    router = router or ChanRouter()
    return NodeHost(
        NodeHostConfig(
            node_host_dir=tmpdir or ":memory:",
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            enable_metrics=metrics,
            health_sample_ms=health_ms,
            metrics_addr=metrics_addr,
            logdb_factory=logdb_factory,
            expert=ExpertConfig(
                quorum_engine=engine,
                engine_block_groups=64,
                engine_warm_fused=False,
                host_compartments=compartments,
                host_workers=host_workers,
                fs=fs,
            ),
        )
    )


def _start(nh, cid=CID, check_quorum=False):
    nh.start_cluster(
        {1: nh.raft_address()}, False, CounterSM,
        Config(cluster_id=cid, node_id=1, election_rtt=10, heartbeat_rtt=1,
               check_quorum=check_quorum),
    )
    wait_until(
        lambda: nh.get_leader_id(cid)[1], timeout=10.0, what="leader"
    )


def _tune(sampler, **kw):
    """Shrink detector knobs for test cadence."""
    for k, v in kw.items():
        setattr(sampler, k, v)


# ----------------------------------------------------------------------
# health OFF: structural identity
# ----------------------------------------------------------------------


def test_health_off_structural_identity():
    nh = _mk_host(health_ms=0)
    try:
        _start(nh)
        assert nh.health is None
        assert nh.metrics_server is None
        node = nh.get_node(CID)
        assert node._health_track is False
        s = nh.get_noop_session(CID)
        for _ in range(3):
            assert nh.sync_propose(s, b"x", timeout=10.0)
        # the off path never touched the gated watermark tracking
        assert node._health_track is False
        assert node._dev_commit_seen == 0
        # no health families registered
        assert not any(
            f.startswith("dragonboat_health_")
            for f in nh.metrics_registry.families()
        )
        assert nh.health_report() == {"status": "ok", "health_plane": "off"}
    finally:
        nh.stop()


# ----------------------------------------------------------------------
# live sampling: ring schema, overhead, host-plane depths
# ----------------------------------------------------------------------


def test_sampler_ring_schema_and_overhead():
    nh = _mk_host(health_ms=20, compartments=True)
    try:
        _start(nh)
        s = nh.get_noop_session(CID)
        for _ in range(5):
            nh.sync_propose(s, b"x", timeout=10.0)
        # the ring holds pre-election samples too — wait for one that
        # observed the committed proposals
        wait_until(
            lambda: (nh.health.samples()[-1]["groups"].get(CID) or {}).get(
                "committed", 0
            ) >= 5,
            timeout=10.0, what="post-commit sample",
        )
        samp = nh.health.samples()[-1]
        g = samp["groups"][CID]
        for field in ("state", "term", "leader_id", "committed", "applied",
                      "voters", "quorum", "pending_proposals"):
            assert field in g, (field, g)
        assert g["state"] == "LEADER" and g["committed"] >= 5
        # compartmentalized host-plane depths ride the sample
        hp = samp["host"]["hostplane"]
        assert hp["ingress"]["shards"] and "wal" in hp
        assert "apply_depth" in hp and "egress_depth" in hp
        # sampler-overhead assertion: a per-sample cost anywhere near
        # the cadence would make the plane a load source, not a meter
        walls = sorted(
            s["wall_ms"] for s in nh.health.samples() if "wall_ms" in s
        )
        assert walls[len(walls) // 2] < scaled(25.0), walls[-5:]
        reg = nh.metrics_registry
        assert reg.counter_value("dragonboat_health_samples_total") >= 5
        h = reg.histogram_value("dragonboat_health_sample_ms")
        assert h is not None and h[3] >= 5
        assert reg.gauge_value("dragonboat_health_groups") == 1
        rep = nh.health_report()
        assert rep["status"] == "ok" and rep["samples"] >= 5
    finally:
        nh.stop()


# ----------------------------------------------------------------------
# fault injection: ErrorFS WAL stall -> commit_stall
# ----------------------------------------------------------------------


def test_errorfs_wal_stall_opens_commit_stall(tmp_path):
    """vfs.ErrorFS fails every fsync: commitIndex flattens with
    proposals pending, commit_stall opens; healing the fs lets the
    committer retry land and the event closes with a recovery
    duration."""
    failing = [False]
    inj = vfs.Injector(lambda op, path: failing[0] and op == "fsync")
    efs = vfs.ErrorFS(vfs.OSFS(), inj)
    ldb_dir = str(tmp_path / "wal")

    def logdb_factory(nhc):
        return open_logdb(
            ldb_dir, shards=2,
            kv_factory=lambda d: WalKV(d, fsync=True, fs=efs),
        )

    nh = _mk_host(
        health_ms=25, tmpdir=str(tmp_path / "nh"),
        logdb_factory=logdb_factory, fs=efs,
    )
    try:
        _start(nh)
        _tune(nh.health, commit_stall_samples=2)
        s = nh.get_noop_session(CID)
        assert nh.sync_propose(s, b"pre", timeout=10.0).value == 1
        failing[0] = True
        rs = nh.propose(s, b"stuck", timeout=60.0)
        assert not rs.wait(0.5).completed
        wait_until(
            lambda: any(
                e["detector"] == "commit_stall"
                for e in nh.health.open_events()
            ),
            timeout=10.0, what="commit_stall open",
        )
        reg = nh.metrics_registry
        assert reg.counter_value(
            "dragonboat_health_events_total", {"detector": "commit_stall"}
        ) >= 1
        assert nh.health_report()["status"] == "degraded"
        # heal: the committer retry lands the entry, commit advances,
        # the detector closes and the recovery duration is recorded
        failing[0] = False
        assert rs.wait(10.0).completed
        wait_until(
            lambda: not nh.health.open_events(), timeout=10.0,
            what="commit_stall close",
        )
        recov = nh.health.recovery_stats()
        assert recov["commit_stall"]["n"] >= 1
        assert recov["commit_stall"]["p99_s"] > 0
        h = reg.histogram_value(
            "dragonboat_health_recovery_seconds",
            {"detector": "commit_stall"},
        )
        assert h is not None and h[3] >= 1
    finally:
        nh.stop()


# ----------------------------------------------------------------------
# fault injection: netsplit -> quorum_at_risk, closes on heal
# ----------------------------------------------------------------------


def test_netsplit_opens_quorum_at_risk_and_closes_on_heal():
    router = ChanRouter()
    nhs = [
        _mk_host(addr=f"qr{i}:1", router=router, health_ms=25)
        for i in range(1, 4)
    ]
    addrs = {i: f"qr{i}:1" for i in range(1, 4)}
    try:
        for i, nh in enumerate(nhs, start=1):
            nh.start_cluster(
                addrs, False, CounterSM,
                Config(cluster_id=CID, node_id=i, election_rtt=10,
                       heartbeat_rtt=1, check_quorum=True),
            )
        # deterministic leadership on host 1
        def _drive_leader1():
            n1 = nhs[0].get_node(CID)
            if n1.is_leader():
                return True
            lid, ok = n1.get_leader_id()
            if ok and lid != 1 and 1 <= lid <= 3:
                try:
                    nhs[lid - 1].request_leader_transfer(CID, 1)
                except Exception:
                    pass
            else:
                n1.request_campaign()
            return False

        wait_until(_drive_leader1, timeout=20.0, interval=0.2,
                   what="leader on host 1")
        s = nhs[0].get_noop_session(CID)
        nhs[0].sync_propose(s, b"x", timeout=30.0)
        health = nhs[0].health
        _tune(health, quorum_risk_samples=2)
        # a couple of healthy windows first so the activity flags are
        # warm, then cut host 3 from everyone
        wait_until(lambda: len(health) >= 3, timeout=10.0, what="samples")
        router.partition("qr3:1", "qr1:1")
        router.partition("qr3:1", "qr2:1")
        wait_until(
            lambda: any(
                e["detector"] == "quorum_at_risk"
                for e in health.open_events()
            ),
            timeout=15.0, what="quorum_at_risk open",
        )
        ev = [e for e in health.open_events()
              if e["detector"] == "quorum_at_risk"][0]
        assert ev["detail"]["reachable"] <= ev["detail"]["quorum"]
        # heal: the partitioned follower reconnects, activity flags
        # refresh, the detector closes (on this host directly, or via
        # the leadership-moved close if the rejoin deposed host 1)
        router.heal()
        wait_until(
            lambda: not any(
                e["detector"] == "quorum_at_risk"
                for e in health.open_events()
            ),
            timeout=20.0, what="quorum_at_risk close",
        )
        assert health.recovery_stats()["quorum_at_risk"]["n"] >= 1
    finally:
        for nh in nhs:
            nh.stop()


# ----------------------------------------------------------------------
# fault injection: kill -9 hostproc worker -> worker_flap
# ----------------------------------------------------------------------


def test_kill9_hostproc_worker_opens_worker_flap(tmp_path):
    nh = _mk_host(
        health_ms=20, host_workers=1, tmpdir=str(tmp_path / "nh"),
    )
    if nh.hostproc is None:
        nh.stop()
        pytest.skip("hostproc spawn unavailable")
    try:
        _start(nh)
        wait_until(lambda: len(nh.health) >= 2, timeout=10.0, what="samples")
        pid = nh.hostproc.worker_pid(0)
        assert pid
        os.kill(pid, signal.SIGKILL)
        wait_until(
            lambda: any(
                e["detector"] == "worker_flap"
                for e in nh.health.open_events()
            ) or nh.health.recovery_stats().get("worker_flap"),
            timeout=15.0, what="worker_flap open",
        )
        # the monitor respawns (bounded budget) and the event closes
        # with a measured recovery duration
        wait_until(
            lambda: nh.health.recovery_stats().get("worker_flap"),
            timeout=30.0, what="worker_flap close",
        )
        recov = nh.health.recovery_stats()["worker_flap"]
        assert recov["n"] >= 1 and recov["p99_s"] > 0
        assert nh.metrics_registry.counter_value(
            "dragonboat_health_events_total", {"detector": "worker_flap"}
        ) >= 1
    finally:
        nh.stop()


def test_hostproc_dead_lane_ring_depth_not_ghosted(tmp_path):
    """ISSUE 13 satellite: a dead lane's rings hold the dead epoch's
    backlog — ring_depth() must exclude them, and the monitor must
    republish the gauges at death so a scrape never shows a ghost
    ring."""
    nh = _mk_host(health_ms=0, host_workers=1, tmpdir=str(tmp_path / "nh"))
    if nh.hostproc is None:
        nh.stop()
        pytest.skip("hostproc spawn unavailable")
    try:
        plane = nh.hostproc
        rec = plane._workers[0]
        # exhaust the restart budget FIRST so the monitor cannot respawn
        # (and ring-reset) the lane — the ghost epoch then persists, the
        # exact regime the old gauge misread forever
        rec.restarts = plane.MAX_RESTARTS
        pid = plane.worker_pid(0)
        os.kill(pid, signal.SIGKILL)
        wait_until(lambda: rec.down, timeout=10.0, what="lane marked down")
        # stage dead-epoch bytes on the dead lane's request ring
        assert rec.pairs[0].req.push(b"ghost-record")
        assert rec.pairs[0].req.depth() > 0
        # the live depth excludes the dead lane...
        assert plane.ring_depth() == 0
        # ...and the monitor republishes the gauge, so a scrape between
        # death and (never-coming) respawn shows 0, not the ghost
        wait_until(
            lambda: nh.metrics_registry.gauge_value(
                "dragonboat_hostproc_ring_depth"
            ) == 0,
            timeout=10.0, what="ring_depth gauge zeroed",
        )
        assert nh.metrics_registry.gauge_value(
            "dragonboat_hostproc_workers_alive"
        ) == 0
    finally:
        nh.stop()


# ----------------------------------------------------------------------
# detector unit semantics (synthetic samples)
# ----------------------------------------------------------------------


def _sample(groups=None, hostproc=None, mono=None):
    return {
        "ts": time.time(),
        "mono": mono if mono is not None else time.monotonic(),
        "groups": groups or {},
        "host": {"hostproc": hostproc},
    }


def _unit_sampler(**kw):
    return HealthSampler(nh=None, registry=MetricsRegistry(), **kw)


def test_unit_apply_lag_hysteresis():
    hs = _unit_sampler(apply_lag_entries=100)
    g = {"committed": 1000, "applied": 980, "leader_id": 1}
    hs.ingest(_sample({7: dict(g)}))
    assert not hs.open_events()
    g["applied"] = 850  # lag 150 > 100 -> open
    hs.ingest(_sample({7: dict(g)}))
    assert [e["detector"] for e in hs.open_events()] == ["apply_lag"]
    g["applied"] = 920  # lag 80: above close threshold (50) -> stays open
    hs.ingest(_sample({7: dict(g)}))
    assert hs.open_events()
    g["applied"] = 960  # lag 40 <= 50 -> closes
    hs.ingest(_sample({7: dict(g)}))
    assert not hs.open_events()
    assert hs.recovery_stats()["apply_lag"]["n"] == 1


def test_unit_leader_flap_window():
    hs = _unit_sampler(leader_flap_changes=3, flap_window_s=5.0)
    base = time.monotonic()
    lid = 1
    for i in range(4):
        lid = 2 if lid == 1 else 1
        hs.ingest(_sample(
            {7: {"leader_id": lid, "committed": i}}, mono=base + i * 0.1
        ))
    assert any(e["detector"] == "leader_flap" for e in hs.open_events())
    # a quiet window ages the changes out and closes the event
    hs.ingest(_sample(
        {7: {"leader_id": lid, "committed": 9}}, mono=base + 20.0
    ))
    assert not hs.open_events()
    assert hs.recovery_stats()["leader_flap"]["n"] == 1


def test_unit_lease_thrash_and_devsm_rebind():
    hs = _unit_sampler(lease_thrash_events=3, devsm_rebind_binds=2,
                       flap_window_s=5.0)
    base = time.monotonic()
    g0 = {
        "leader_id": 1, "committed": 1,
        "lease": {"grants": 0, "expiries": 0, "held": True},
        "devsm": {"binds": 0, "bound": True},
    }
    hs.ingest(_sample({7: g0}, mono=base))
    g1 = {
        "leader_id": 1, "committed": 2,
        "lease": {"grants": 2, "expiries": 2, "held": False},
        "devsm": {"binds": 3, "bound": False},
    }
    hs.ingest(_sample({7: g1}, mono=base + 0.1))
    dets = {e["detector"] for e in hs.open_events()}
    assert dets == {"lease_thrash", "devsm_rebind"}
    # a quiet window alone does NOT close a thrash that settled into
    # permanently-expired (review-caught: the aged-out deque used to
    # close it and record a bogus recovery while the lease was down)
    g_expired = {
        "leader_id": 1, "committed": 3,
        "lease": {"grants": 2, "expiries": 2, "held": False},
        "devsm": {"binds": 3, "bound": True},
    }
    hs.ingest(_sample({7: g_expired}, mono=base + 30.0))
    assert {e["detector"] for e in hs.open_events()} == {"lease_thrash"}
    # quiet window + lease held again -> closes
    g2 = {
        "leader_id": 1, "committed": 3,
        "lease": {"grants": 2, "expiries": 2, "held": True},
        "devsm": {"binds": 3, "bound": True},
    }
    hs.ingest(_sample({7: g2}, mono=base + 31.0))
    assert not hs.open_events()


def test_unit_commit_stall_requires_pending():
    hs = _unit_sampler(commit_stall_samples=2)
    g = {"committed": 5, "pending_proposals": False, "leader_id": 1}
    for _ in range(4):  # flat but nothing pending: idle, not stalled
        hs.ingest(_sample({7: dict(g)}))
    assert not hs.open_events()
    g["pending_proposals"] = True
    for _ in range(3):
        hs.ingest(_sample({7: dict(g)}))
    assert [e["detector"] for e in hs.open_events()] == ["commit_stall"]
    g["committed"] = 6  # progress closes it
    hs.ingest(_sample({7: dict(g)}))
    assert not hs.open_events()


def test_unit_group_gone_closes_events_and_drops_memory():
    hs = _unit_sampler(commit_stall_samples=1, leader_flap_changes=2)
    base = time.monotonic()
    g = {"committed": 5, "pending_proposals": True, "leader_id": 1}
    hs.ingest(_sample({7: dict(g)}, mono=base))
    g["leader_id"] = 2  # one change lands in the flap deque
    hs.ingest(_sample({7: dict(g)}, mono=base + 0.1))
    assert hs.open_events()
    hs.ingest(_sample({}, mono=base + 0.2))  # stop_cluster
    assert not hs.open_events()
    # every per-cid evaluation memory dropped (review-caught: a
    # restarted incarnation must not inherit the old one's flap
    # history, and churned groups must not leak dict entries)
    for d in (hs._prev, hs._stall_streak, hs._leader_changes,
              hs._lease_events, hs._devsm_binds):
        assert 7 not in d
    # restart the cid: its first real leader change must NOT trip the
    # flap threshold off the dead incarnation's deque
    hs.ingest(_sample({7: {"leader_id": 1, "committed": 1}},
                      mono=base + 0.3))
    hs.ingest(_sample({7: {"leader_id": 2, "committed": 1}},
                      mono=base + 0.4))
    assert not any(
        e["detector"] == "leader_flap" for e in hs.open_events()
    )


def test_unit_subscription_callbacks_and_ordering():
    """ISSUE 17 satellite: on_open/on_close subscribers fire per
    transition, and a close callback observes the recovery attribution
    ALREADY including its event (the controller's MTTR contract)."""
    hs = _unit_sampler(commit_stall_samples=2)
    events = []
    hs.on_open(lambda ev: events.append((
        "open", ev["detector"], ev["key"],
        hs.recovery_stats().get("commit_stall", {}).get("n", 0),
    )))
    hs.on_close(lambda ev: events.append((
        "close", ev["detector"], ev["duration_s"],
        hs.recovery_stats().get("commit_stall", {}).get("n", 0),
    )))
    g = {"committed": 5, "pending_proposals": True, "leader_id": 1}
    for _ in range(3):
        hs.ingest(_sample({7: dict(g)}))
    assert events and events[0][:3] == ("open", "commit_stall", "group:7")
    assert events[0][3] == 0  # open: nothing attributed yet
    g["committed"] = 6
    hs.ingest(_sample({7: dict(g)}))
    closes = [e for e in events if e[0] == "close"]
    assert len(closes) == 1
    assert closes[0][1] == "commit_stall"
    assert closes[0][2] is not None  # duration_s carried on the event
    # ordering: when the callback ran, the duration was ALREADY in the
    # recovery attribution
    assert closes[0][3] == 1
    # the event copies are snapshots: mutating one must not corrupt the
    # sampler's records
    assert hs.recovery_stats()["commit_stall"]["n"] == 1


def test_unit_subscription_exception_guarded():
    """A failing subscriber is logged and skipped — sampling continues,
    later subscribers still run, the event still records."""
    hs = _unit_sampler(commit_stall_samples=1)
    seen = []

    def _bad(ev):
        raise RuntimeError("subscriber boom")

    hs.on_open(_bad)
    hs.on_open(lambda ev: seen.append(ev["detector"]))
    hs.on_close(_bad)
    hs.on_close(lambda ev: seen.append("closed:" + ev["detector"]))
    g = {"committed": 5, "pending_proposals": True, "leader_id": 1}
    hs.ingest(_sample({7: dict(g)}))
    hs.ingest(_sample({7: dict(g)}))
    assert "commit_stall" in seen
    g["committed"] = 6
    hs.ingest(_sample({7: dict(g)}))
    assert "closed:commit_stall" in seen
    assert hs.recovery_stats()["commit_stall"]["n"] == 1
    assert not hs.open_events()


def test_unit_unsubscribed_latch_stays_none():
    """The _subs latch follows the _obs discipline: no subscription,
    no structure — an event dispatch is one attribute load."""
    hs = _unit_sampler(commit_stall_samples=1)
    g = {"committed": 5, "pending_proposals": True, "leader_id": 1}
    hs.ingest(_sample({7: dict(g)}))
    hs.ingest(_sample({7: dict(g)}))
    assert hs.open_events()
    assert hs._subs is None


def test_unit_worker_flap_restart_bump():
    hs = _unit_sampler()
    hs.ingest(_sample(hostproc={"alive": 2, "workers": 2, "restarts": 0}))
    assert not hs.open_events()
    # death + instant respawn inside one monitor tick: liveness never
    # dipped, only the restart counter moved
    hs.ingest(_sample(hostproc={"alive": 2, "workers": 2, "restarts": 1}))
    assert [e["detector"] for e in hs.open_events()] == ["worker_flap"]
    hs.ingest(_sample(hostproc={"alive": 2, "workers": 2, "restarts": 1}))
    assert not hs.open_events()
    assert hs.recovery_stats()["worker_flap"]["n"] == 1


# ----------------------------------------------------------------------
# the live scrape endpoint
# ----------------------------------------------------------------------


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    )


def test_endpoint_metrics_healthz_and_dumps():
    nh = _mk_host(health_ms=20, metrics_addr="127.0.0.1:0")
    try:
        _start(nh)
        s = nh.get_noop_session(CID)
        for _ in range(3):
            nh.sync_propose(s, b"x", timeout=10.0)
        wait_until(lambda: len(nh.health) >= 2, timeout=10.0, what="samples")
        port = nh.metrics_server.port
        # /metrics: the full exposition round-trips — every # TYPE is
        # immediately preceded by its # HELP (the acceptance criterion)
        r = _get(port, "/metrics")
        assert r.status == 200
        assert "text/plain" in r.headers["Content-Type"]
        lines = r.read().decode().splitlines()
        assert any(l.startswith("dragonboat_health_samples_total") for l in lines)
        for i, line in enumerate(lines):
            if line.startswith("# TYPE"):
                name = line.split()[2]
                assert i > 0 and lines[i - 1].startswith(f"# HELP {name} "), (
                    f"# TYPE without preceding # HELP: {line}"
                )
        # /healthz: ok -> 200
        r = _get(port, "/healthz")
        assert r.status == 200 and json.loads(r.read())["status"] == "ok"
        # force-open a detector -> 503 with the event in the body
        nh.health._set(
            "commit_stall", "group:999", True, time.monotonic(),
            {"cluster_id": 999},
        )
        try:
            _get(port, "/healthz")
            assert False, "degraded /healthz must 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            body = json.loads(e.read())
            assert body["status"] == "degraded"
            assert body["open"][0]["detector"] == "commit_stall"
        nh.health._set("commit_stall", "group:999", False,
                       time.monotonic(), {})
        assert _get(port, "/healthz").status == 200
        # /debug/health: the ring dump parses and carries samples
        d = json.loads(_get(port, "/debug/health").read())
        assert d["count"] >= 2 and d["samples"]
        assert d["report"]["status"] == "ok"
        # /debug/trace 404s while tracing is off; unknown paths 404
        for path in ("/debug/trace", "/nope"):
            try:
                _get(port, path)
                assert False, f"{path} must 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
    finally:
        nh.stop()


def test_endpoint_survives_restarted_scrapes_and_stop():
    nh = _mk_host(health_ms=0, metrics_addr="127.0.0.1:0")
    try:
        _start(nh)
        port = nh.metrics_server.port
        for _ in range(3):
            assert _get(port, "/metrics").status == 200
        # health off: /healthz still answers (plain ok stub), the ring
        # dump honestly 404s
        assert json.loads(_get(port, "/healthz").read())["health_plane"] == "off"
        try:
            _get(port, "/debug/health")
            assert False
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        nh.stop()
    # after stop the port is released
    try:
        _get(port, "/metrics")
        assert False, "endpoint must stop with the host"
    except (ConnectionError, urllib.error.URLError, OSError):
        pass


def test_malformed_metrics_addr_degrades_not_crashes():
    """Review-caught: a malformed metrics_addr (possibly from the env
    fallback) raises ValueError, which must degrade to a warning — the
    raft planes are fine, only the scrape surface is not."""
    for bad in ("9090", "127.0.0.1:nope"):
        nh = _mk_host(metrics_addr=bad)
        try:
            assert nh.metrics_server is None
        finally:
            nh.stop()


def test_health_families_help_round_trip():
    """Every dragonboat_health_* family carries # HELP + # TYPE (the
    test_events satellite pattern)."""
    import io

    nh = _mk_host(health_ms=20)
    try:
        _start(nh)
        wait_until(lambda: len(nh.health) >= 1, timeout=10.0, what="sample")
        buf = io.StringIO()
        nh.write_health_metrics(buf)
        text = buf.getvalue()
        for fam, kind in (
            ("dragonboat_health_samples_total", "counter"),
            ("dragonboat_health_events_total", "counter"),
            ("dragonboat_health_open", "gauge"),
            ("dragonboat_health_groups", "gauge"),
            ("dragonboat_health_sample_ms", "histogram"),
            ("dragonboat_health_recovery_seconds", "histogram"),
        ):
            assert f"# HELP {fam} " in text, fam
            assert f"# TYPE {fam} {kind}" in text, fam
        # zero-registered per detector so a scrape distinguishes
        # "healthy" from "health off"
        for det in DETECTORS:
            assert f'dragonboat_health_open{{detector="{det}"}} 0' in text, det
    finally:
        nh.stop()
