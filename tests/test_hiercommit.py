"""Hierarchical commit plane tests (ISSUE 18).

Coverage map:

- HierPlane rule arithmetic: sub-quorum/intersection bounds and the
  pigeonhole identity that makes them safe.
- sub-quorum ≡ classic when domains are symmetric (all voters in one
  domain the sub-quorum degenerates to the classic majority), and the
  asymmetric speedup: a near-domain majority closes commits the classic
  quorum still has in flight.
- fused ≡ scalar: the batched engine with the (G,P) class mask replays
  the exact ack streams of a hier-enabled scalar leader bit-for-bit.
- leader-change intersection safety: a candidate holding the classic
  quorum but missing the near-domain intersection bound is HELD (the
  classic rule would promote it and lose a sub-quorum-committed entry —
  the counterexample is asserted on a classic twin).
- far-domain catch-up convergence, far-read batching (FarReadBatcher
  unit + raft-level), invalidation on leader/term change.
- off-path structural identity: ``hier_commit=False`` constructs
  nothing — ``raft.hier is None``, engine latch down, hier state fields
  zero and excluded from row syncs.
- end-to-end (slow): 4-node 2+2 domain cluster under whole-domain
  partitions with a HistoryRecorder; history must check linearizable
  and the leader must have closed commits through the sub-quorum.
"""
import threading
import time
import random

import pytest

from dragonboat_tpu.config import Config, ConfigError
from dragonboat_tpu.raft import InMemLogDB, Raft
from dragonboat_tpu.raft.hier import (
    MIN_DOMAIN_VOTERS,
    FarReadBatcher,
    HierPlane,
    intersect_threshold,
    seed_domains_from_latency,
    seed_domains_from_rtt,
    sub_quorum_size,
)
from dragonboat_tpu.raft.remote import Remote
from dragonboat_tpu.wire import Entry, Message, MessageType, SystemCtx
from raft_harness import new_test_raft

MT = MessageType

DOMS_32 = {1: "A", 2: "A", 3: "A", 4: "B", 5: "B"}  # 3 near + 2 far
DOMS_22 = {1: "A", 2: "A", 3: "B", 4: "B"}


def hier_raft(node_id, peers, domains, election=10, heartbeat=1):
    """new_test_raft twin with the hier plane enabled."""
    c = Config(
        node_id=node_id,
        cluster_id=1,
        election_rtt=election,
        heartbeat_rtt=heartbeat,
        hier_commit=True,
        hier_domains=dict(domains),
    )
    c.validate()
    r = Raft(c, InMemLogDB(), seed=node_id)
    for p in peers:
        if p not in r.remotes:
            r.remotes[p] = Remote(next=1)
    r.reset_match_value_array()
    r.has_not_applied_config_change = lambda: False
    return r


def elect(r, peers):
    """Grant the campaign from every other voter (domain-complete, so
    the intersection rule is trivially satisfied)."""
    r.handle(Message(from_=r.node_id, to=r.node_id, type=MT.ELECTION))
    for p in peers:
        if p != r.node_id and not r.is_leader():
            r.handle(
                Message(from_=p, to=r.node_id, term=r.term,
                        type=MT.REQUEST_VOTE_RESP)
            )
    assert r.is_leader()
    return r


def ack(r, p, idx):
    r.handle(
        Message(from_=p, to=r.node_id, term=r.term,
                type=MT.REPLICATE_RESP, log_index=idx)
    )


def propose(r):
    r.handle(
        Message(from_=r.node_id, to=r.node_id, type=MT.PROPOSE,
                entries=[Entry(cmd=b"x")])
    )
    return r.log.last_index()


# ======================================================================
# rule arithmetic
# ======================================================================


def test_subquorum_intersection_pigeonhole():
    # (|D|+1)//2 grants + |D|//2+1 sub-quorum members > |D| — every
    # elected leader's granted set meets every possible sub-quorum
    for n in range(1, 12):
        assert intersect_threshold(n) + sub_quorum_size(n) == n + 1
    # one grant fewer admits a disjoint counterexample
    for n in range(2, 12):
        assert (intersect_threshold(n) - 1) + sub_quorum_size(n) <= n


def test_eligibility_and_near_voters():
    hp = HierPlane({1: "A", 2: "A", 3: "B", 5: "C"}, node_id=1)
    elig = hp.eligible_domains([1, 2, 3, 4, 5])
    assert set(elig) == {"A"}  # B and C are singletons, 4 unassigned
    assert sorted(elig["A"]) == [1, 2]
    assert hp.near_voters([1, 2, 3, 4, 5]) == [1, 2]
    # a departed near peer drops the domain below eligibility
    assert hp.near_voters([1, 3, 4, 5]) == []
    assert hp.commit_quorum({1: 9, 3: 9, 4: 9, 5: 9}, [1, 3, 4, 5]) == 0
    # the unassigned replica never forms a sub-quorum
    assert HierPlane({2: "A", 3: "A"}, node_id=1).near_voters([1, 2, 3]) == []
    assert MIN_DOMAIN_VOTERS == 2


def test_commit_quorum_is_domain_majority_kth_largest():
    hp = HierPlane(DOMS_32, node_id=1)
    voters = [1, 2, 3, 4, 5]
    # near = {1,2,3}, sub-quorum 2: second-largest near match
    assert hp.commit_quorum({1: 7, 2: 5, 3: 0, 4: 0, 5: 0}, voters) == 5
    # far matches never contribute, however large
    assert hp.commit_quorum({1: 3, 2: 0, 3: 0, 4: 99, 5: 99}, voters) == 0


def test_election_ok_requires_every_eligible_domain():
    hp = HierPlane(DOMS_32, node_id=4)
    voters = [1, 2, 3, 4, 5]
    # A needs 2 grants, B needs 1
    assert not hp.election_ok({3: True, 4: True, 5: True}, voters)
    assert hp.election_ok({2: True, 3: True, 4: True, 5: True}, voters)
    assert not hp.election_ok({1: True, 2: True, 3: True}, voters)  # no B


# ======================================================================
# scalar-plane differential: sub-quorum vs classic
# ======================================================================


def test_subquorum_closes_ahead_of_classic():
    """The tentpole claim at the scalar level: near-domain acks alone
    advance the hier leader's commit while the classic twin (same
    stream) still waits on the third voter."""
    peers = [1, 2, 3, 4, 5]
    rh = elect(hier_raft(1, peers, DOMS_32), peers)
    rc = new_test_raft(1, peers)
    rc.handle(Message(from_=1, to=1, type=MT.ELECTION))
    for p in (2, 3, 4, 5):
        if not rc.is_leader():
            rc.handle(Message(from_=p, to=1, term=rc.term,
                              type=MT.REQUEST_VOTE_RESP))
    assert rc.is_leader()
    # identical stream: propose, then ONE near follower ack (node 2)
    for r in (rh, rc):
        idx = propose(r)
        ack(r, 2, r.log.last_index())
    assert rh.log.committed == idx  # self + node2 = A-majority
    assert rc.log.committed == 0    # classic still needs a 3rd ack
    assert rh.hier.subquorum_closes >= 1
    # the classic quorum stays the floor: far acks close it too
    idx2 = propose(rh)
    ack(rh, 4, idx2)
    ack(rh, 5, idx2)
    assert rh.log.committed == idx2
    assert rh.hier.fallback_closes >= 1


def test_symmetric_domains_identical_to_classic():
    """All voters in one domain: sub_quorum_size(n) == quorum(n), so the
    hier rule degenerates to classic — committed must track the classic
    twin bit-for-bit over a randomized stale/dup ack stream."""
    peers = [1, 2, 3, 4, 5]
    doms = {p: "A" for p in peers}
    rh = elect(hier_raft(1, peers, doms), peers)
    rc = new_test_raft(1, peers)
    rc.handle(Message(from_=1, to=1, type=MT.ELECTION))
    for p in (2, 3):
        rc.handle(Message(from_=p, to=1, term=rc.term,
                          type=MT.REQUEST_VOTE_RESP))
    assert rc.is_leader()
    # align logs: commit the promotion noops identically
    for r in (rh, rc):
        for p in (2, 3):
            ack(r, p, r.log.last_index())
    rng = random.Random(5)
    for _ in range(60):
        if rng.random() < 0.5:
            for r in (rh, rc):
                propose(r)
        p = rng.choice(peers[1:])
        idx = rng.randrange(0, rh.log.last_index() + 1)
        for r in (rh, rc):
            ack(r, p, idx)
        assert rh.log.committed == rc.log.committed
    assert rh.log.committed > 0
    # never via_sub: q_near can equal but never exceed q_classic
    assert rh.hier.subquorum_closes == 0


def test_far_catchup_convergence():
    """Far voters trail the sub-quorum close, then converge: far lag is
    positive right after a near-only close and zero once the far acks
    arrive; committed never moves backwards."""
    peers = [1, 2, 3, 4, 5]
    r = elect(hier_raft(1, peers, DOMS_32), peers)

    def far_lag():
        vm = r.voting_members()
        return r.hier.note_far_lag(
            {nid: rm.match for nid, rm in vm.items()}, vm.keys(),
            r.log.committed,
        )

    for _ in range(5):
        idx = propose(r)
        ack(r, 2, idx)
    assert r.log.committed == idx
    assert far_lag() == idx  # far domain never acked anything
    before = r.log.committed
    for p in (4, 5):
        ack(r, p, idx)
    assert far_lag() == 0
    assert r.log.committed == before


# ======================================================================
# leader-change safety
# ======================================================================


def test_election_held_until_domain_intersection():
    """Candidate 4 (far domain) collects the classic quorum {3,4,5} but
    only one grant inside the 3-voter near domain (threshold 2): hier
    HOLDS the promotion; the classic twin promotes on the same tally —
    and would elect a leader whose voters may all miss a sub-quorum
    commit closed inside A by {1,2}."""
    peers = [1, 2, 3, 4, 5]
    r4 = hier_raft(4, peers, DOMS_32)
    r4.handle(Message(from_=4, to=4, type=MT.ELECTION))
    assert r4.is_candidate()
    for p in (5, 3):
        r4.handle(Message(from_=p, to=4, term=r4.term,
                          type=MT.REQUEST_VOTE_RESP))
    assert r4.is_candidate()           # held: A∩granted = {3} < 2
    assert r4.hier.election_holds >= 1
    # classic twin: identical grants → leader (the unsafe promotion)
    c4 = new_test_raft(4, peers)
    c4.handle(Message(from_=4, to=4, type=MT.ELECTION))
    for p in (5, 3):
        c4.handle(Message(from_=p, to=4, term=c4.term,
                          type=MT.REQUEST_VOTE_RESP))
    assert c4.is_leader()
    # a second near grant satisfies the bound: {2,3} intersects every
    # 2-member sub-quorum of {1,2,3}
    r4.handle(Message(from_=2, to=4, term=r4.term,
                      type=MT.REQUEST_VOTE_RESP))
    assert r4.is_leader()


def test_election_rejections_still_demote():
    """The hier branch keeps etcd's reject-majority demotion."""
    peers = [1, 2, 3, 4, 5]
    r = hier_raft(1, peers, DOMS_32)
    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    for p in (2, 3, 4):
        r.handle(Message(from_=p, to=1, term=r.term,
                         type=MT.REQUEST_VOTE_RESP, reject=True))
    assert r.is_follower()


# ======================================================================
# fused ≡ scalar with the device class mask
# ======================================================================


jax = pytest.importorskip("jax")


def _mk_engine_pair(peers, domains, n_groups=2):
    from dragonboat_tpu.ops import BatchedQuorumEngine

    r = elect(hier_raft(1, peers, domains), peers)
    eng = BatchedQuorumEngine(n_groups=n_groups, n_peers=len(peers))
    eng.add_group(1, node_ids=peers, self_id=1)
    near = r.hier.near_voters(peers)
    eng.set_hier(1, near, sub_quorum_size(len(near)) if near else 0)
    eng.set_leader(
        1, term=r.term, term_start=r.log.last_index(),
        last_index=r.log.last_index(),
    )
    return r, eng


def test_fused_commit_matches_scalar_hier_oracle():
    """The engine's has_hier commit rule replays a hier leader's exact
    ack stream with bit-identical committed watermarks (the scalar
    _hier_try_commit twin of kernels._finish_step)."""
    peers = [1, 2, 3, 4, 5]
    r, eng = _mk_engine_pair(peers, DOMS_32)
    rng = random.Random(17)
    for _ in range(40):
        for _ in range(rng.randrange(0, 3)):
            idx = propose(r)
            eng.ack(1, 1, idx)
        last = r.log.last_index()
        for _ in range(rng.randrange(0, 5)):
            p = rng.choice(peers[1:])
            idx = rng.randrange(0, last + 1)  # stale/dup included
            ack(r, p, idx)
            eng.ack(1, p, idx)
        eng.step(do_tick=False)
        assert eng.committed_index(1) == r.log.committed
    assert r.log.committed > 0
    assert r.hier.subquorum_closes > 0  # the mask actually engaged


def test_fused_commit_matches_scalar_near_only_stream():
    """Near-domain-only acks: the engine must close at the sub-quorum
    (classic kth-largest alone would stay at 0 forever)."""
    peers = [1, 2, 3, 4, 5]
    r, eng = _mk_engine_pair(peers, DOMS_32)
    for _ in range(8):
        idx = propose(r)
        eng.ack(1, 1, idx)
        ack(r, 2, idx)
        eng.ack(1, 2, idx)
        eng.step(do_tick=False)
        assert eng.committed_index(1) == r.log.committed == idx


def test_engine_ineligible_domain_stays_classic():
    """sub_quorum=0 (ineligible/unassigned) keeps the classic rule on a
    hier-latched engine — the where() discards the clamped column."""
    from dragonboat_tpu.ops import BatchedQuorumEngine

    peers = [1, 2, 3, 4, 5]
    eng = BatchedQuorumEngine(n_groups=2, n_peers=5)
    eng.add_group(1, node_ids=peers, self_id=1)
    eng.set_hier(1, [1, 2], 2)        # latch the plane on group 1
    eng.add_group(2, node_ids=peers, self_id=1)
    eng.set_hier(2, [], 0)            # group 2: ineligible
    for cid in (1, 2):
        eng.set_leader(cid, term=1, term_start=0, last_index=0)
    for cid in (1, 2):
        eng.ack(cid, 1, 5)
        eng.ack(cid, 2, 5)
    eng.step(do_tick=False)
    assert eng.committed_index(1) == 5   # sub-quorum {1,2} closed
    assert eng.committed_index(2) == 0   # classic needs 3 of 5


# ======================================================================
# off-path structural identity
# ======================================================================


def test_hier_off_structural_identity():
    """hier_commit=False constructs NOTHING: no plane, no batcher, no
    engine latch, hier fields excluded from the row syncs and all-zero
    on device after real dispatches."""
    import numpy as np

    from dragonboat_tpu.ops import BatchedQuorumEngine

    peers = [1, 2, 3]
    r = new_test_raft(1, peers)
    assert r.hier is None and r.far_reads is None
    # domains without the switch stay inert too
    c = Config(node_id=1, cluster_id=1, election_rtt=10, heartbeat_rtt=1,
               hier_domains={1: "A", 2: "A"})
    c.validate()
    assert Raft(c, InMemLogDB(), seed=1).hier is None

    eng = BatchedQuorumEngine(n_groups=2, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1)
    eng.set_leader(1, term=1, term_start=0, last_index=0)
    eng.set_hier(1, (), 0)  # disable on a never-enabled engine: no-op
    assert not eng._hier_used
    for k in eng._HIER_KEYS:
        assert k not in eng._sync_keys()
    eng.ack(1, 1, 3)
    eng.ack(1, 2, 3)
    eng.step(do_tick=False)
    assert eng.committed_index(1) == 3
    assert not eng._hier_used
    assert not np.asarray(eng.dev.near).any()
    assert not np.asarray(eng.dev.sub_quorum).any()


def test_config_gate_validation():
    bad = [
        {0: "A"},            # node ids start at 1
        {"1": "A"},          # keys are ints
        {1: 2},              # labels are strings
    ]
    for doms in bad:
        with pytest.raises(ConfigError):
            Config(node_id=1, cluster_id=1, election_rtt=10,
                   heartbeat_rtt=1, hier_commit=True,
                   hier_domains=doms).validate()
    with pytest.raises(ConfigError):
        Config(node_id=1, cluster_id=1, election_rtt=10, heartbeat_rtt=1,
               hier_domains="A").validate()


# ======================================================================
# far-read batching
# ======================================================================


def test_far_read_batcher_unit():
    b = FarReadBatcher()
    c1, c2, c3 = (SystemCtx(low=i, high=1) for i in (1, 2, 3))
    assert b.admit(c1)            # representative, forward
    assert not b.admit(c2)        # mid-flight: held for next fetch
    assert not b.admit(c3)
    assert b.pending == 3 and b.batches == 1 and b.coalesced == 2
    released, nxt = b.on_resp(c1)
    assert released == [c1] and nxt == c2 and b.batches == 2
    released, nxt = b.on_resp(c2)
    assert released == [c2, c3] and nxt is None and b.pending == 0
    # stale resp (post-invalidate) releases only itself
    assert b.admit(c1)
    dropped = b.invalidate()
    assert dropped == [c1] and b.pending == 0
    released, nxt = b.on_resp(c1)
    assert released == [c1] and nxt is None


def test_far_follower_coalesces_read_round_trips():
    peers = [1, 2, 3, 4]
    r = hier_raft(3, peers, DOMS_22)
    r.become_follower(1, 1)  # leader 1 sits in the far domain A
    r.msgs.clear()

    def read(low):
        r.handle(Message(type=MT.READ_INDEX, from_=3, to=3,
                         hint=low, hint_high=1))

    read(11)
    fwd = [m for m in r.msgs if m.type == MT.READ_INDEX]
    assert len(fwd) == 1 and fwd[0].to == 1 and fwd[0].hint == 11
    read(12)
    read(13)
    assert len([m for m in r.msgs if m.type == MT.READ_INDEX]) == 1
    assert r.far_reads.coalesced == 2
    # leader answers the first fetch: its ctx releases, the next
    # representative goes out, the held member waits for IT
    r.handle(Message(type=MT.READ_INDEX_RESP, from_=1, to=3, term=r.term,
                     log_index=7, hint=11, hint_high=1))
    assert [(x.index, x.system_ctx.low) for x in r.ready_to_read] == [(7, 11)]
    fwd = [m for m in r.msgs if m.type == MT.READ_INDEX]
    assert len(fwd) == 2 and fwd[1].hint == 12
    r.handle(Message(type=MT.READ_INDEX_RESP, from_=1, to=3, term=r.term,
                     log_index=9, hint=12, hint_high=1))
    assert sorted(
        (x.index, x.system_ctx.low) for x in r.ready_to_read
    ) == [(7, 11), (9, 12), (9, 13)]
    assert r.far_reads.pending == 0


def test_near_follower_forwards_every_read():
    peers = [1, 2, 3, 4]
    r = hier_raft(2, peers, DOMS_22)
    r.become_follower(1, 1)  # same domain as the leader
    r.msgs.clear()
    for low in (21, 22):
        r.handle(Message(type=MT.READ_INDEX, from_=2, to=2,
                         hint=low, hint_high=1))
    assert len([m for m in r.msgs if m.type == MT.READ_INDEX]) == 2
    assert r.far_reads.batches == 0


def test_far_reads_invalidated_on_term_change():
    peers = [1, 2, 3, 4]
    r = hier_raft(3, peers, DOMS_22)
    r.become_follower(1, 1)
    r.msgs.clear()
    for low in (31, 32):
        r.handle(Message(type=MT.READ_INDEX, from_=3, to=3,
                         hint=low, hint_high=1))
    assert r.far_reads.pending == 2
    r.handle(Message(type=MT.HEARTBEAT, from_=2, to=3, term=5))
    assert r.far_reads.pending == 0
    assert sorted(c.low for c in r.dropped_read_indexes) == [31, 32]


# ======================================================================
# domain seeding helpers
# ======================================================================


def test_seed_domains_from_latency_injector():
    from dragonboat_tpu.transport.latency import crossdomain

    inj = crossdomain(["a1:1", "a2:1"], ["b1:1", "b2:1"])
    doms = seed_domains_from_latency(
        inj, {1: "a1:1", 2: "a2:1", 3: "b1:1", 4: "b2:1", 5: "c:1"}
    )
    assert doms == {1: "A", 2: "A", 3: "B", 4: "B", 5: ""}


def test_seed_domains_from_rtt_classifier():
    doms = seed_domains_from_rtt(
        1, {2: 0.0004, 3: 0.002, 4: 0.040, 5: 0.0}, near_ratio=4.0
    )
    assert doms[1] == "near" and doms[2] == "near"
    assert doms[3] == "far" and doms[4] == "far"  # 0.002 > 4*0.0004
    assert doms[5] == "far"  # unmeasured stays out of the sub-quorum


# ======================================================================
# end-to-end: domain partitions under a linearizability recorder
# ======================================================================


@pytest.mark.slow
def test_domain_partition_soak_linearizable():
    """2+2 domain cluster: partition the non-leader domain away whole;
    writes must keep committing through the leader domain's sub-quorum
    (classic quorum is unreachable), the history must check
    linearizable, and all replicas must converge after the heal."""
    from dragonboat_tpu import NodeHostConfig, monkey
    from dragonboat_tpu.linearizability import (
        HistoryRecorder, check_linearizable,
    )
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanRouter, ChanTransport
    from test_chaos import KVSM, _wait_leader

    CID = 18
    router = ChanRouter()
    addrs = {i: f"hc{i}:1" for i in (1, 2, 3, 4)}
    nhs = [
        NodeHost(
            NodeHostConfig(
                node_host_dir=":memory:",
                rtt_millisecond=5,
                raft_address=addrs[i],
                raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                    src, rh, ch, router=router
                ),
            )
        )
        for i in (1, 2, 3, 4)
    ]
    rec = HistoryRecorder()
    stop = threading.Event()
    try:
        for i, nh in enumerate(nhs, start=1):
            nh.start_cluster(
                addrs, False, KVSM,
                Config(
                    cluster_id=CID, node_id=i,
                    election_rtt=10, heartbeat_rtt=1,
                    hier_commit=True, hier_domains=dict(DOMS_22),
                ),
            )
        _wait_leader(nhs, CID)
        leader_id = next(
            lid for nh in nhs
            for lid, ok in [nh.get_leader_id(CID)] if ok
        )
        near = (1, 2) if leader_id in (1, 2) else (3, 4)
        far = (3, 4) if near == (1, 2) else (1, 2)

        def client(tid):
            nh = nhs[near[tid % 2] - 1]  # leader-domain hosts only
            session = nh.get_noop_session(CID)
            i = 0
            while not stop.is_set():
                key = f"k-{tid}-{i % 32}"
                val = str(i)
                i += 1
                done = rec.invoke(tid, "put", key, val)
                try:
                    nh.sync_propose(session, f"{key}={val}".encode(), 2.0)
                    done(True)
                except Exception:
                    done(unknown=True)

        clients = [
            threading.Thread(target=client, args=(t,), daemon=True)
            for t in range(2)
        ]
        for c in clients:
            c.start()
        time.sleep(0.5)
        # whole-domain partition: cut BOTH far replicas at once — the
        # domain-correlated failure the random-minority chaos never draws
        for a in far:
            for b in near:
                router.partition(addrs[a], addrs[b])
        time.sleep(2.0)
        router.heal()
        time.sleep(1.0)
        stop.set()
        for c in clients:
            c.join(timeout=10)
        _wait_leader(nhs, CID)
        barrier_done = rec.invoke(99, "put", "barrier", "1")
        for _ in range(20):
            try:
                s = nhs[near[0] - 1].get_noop_session(CID)
                nhs[near[0] - 1].sync_propose(s, b"barrier=1", timeout=3.0)
                barrier_done(True)
                break
            except Exception:
                time.sleep(0.3)
        else:
            barrier_done(unknown=True)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                monkey.assert_replicas_converged(nhs, CID)
                break
            except AssertionError:
                time.sleep(0.2)
        monkey.assert_replicas_converged(nhs, CID)
        history = rec.history()
        assert len(history) > 20, "soak produced too little history"
        ok, bad = check_linearizable(history)
        assert ok, f"non-linearizable keys: {bad}"
        # the sub-quorum actually carried the partition window
        closes = sum(
            nh.get_node(CID).peer.raft.hier.subquorum_closes for nh in nhs
        )
        assert closes > 0
    finally:
        stop.set()
        for nh in nhs:
            nh.stop()
