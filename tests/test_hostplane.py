"""Compartmentalized host plane (hostplane.py, ISSUE 8) differential suite.

Contracts under test:

- batched-ingress path ≡ N direct ``propose`` calls: same completion set,
  same apply order (result values), same session ``responded_to`` /
  exactly-once tracking;
- SystemBusy semantics (a full staging ring raises synchronously, a full
  ``entry_q`` mid-drain resolves the tail DROPPED — the direct
  ``propose_batch`` behavior) and PayloadTooBig stays synchronous;
- the group-commit flusher never acks before its fsync (``vfs.ErrorFS``
  fault injection on the WAL's fsync), merges concurrent committers into
  one cycle, and propagates flush errors to every rider;
- compartments OFF constructs none of it — the scalar host path is
  structurally identical to the pre-compartment build.
"""
import os
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu import vfs
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.hostplane import GroupCommitWAL
from dragonboat_tpu.logdb import open_logdb
from dragonboat_tpu.logdb.kv import WalKV
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.queue import EntryQueue
from dragonboat_tpu.requests import (
    PayloadTooBigError,
    SystemBusyError,
)
from dragonboat_tpu.transport import ChanRouter, ChanTransport
from dragonboat_tpu.wire import Entry

RTT_MS = 5
CID = 900


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_host(addr, router, compartments, tmpdir=None, logdb_factory=None,
             **expert_kw):
    expert = ExpertConfig(host_compartments=compartments, **expert_kw)
    return NodeHost(
        NodeHostConfig(
            node_host_dir=tmpdir or ":memory:",
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            logdb_factory=logdb_factory,
            expert=expert,
        )
    )


def _wait_leader(nhs, cid, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs:
            lid, ok = nh.get_leader_id(cid)
            if ok:
                return lid
        time.sleep(0.02)
    raise AssertionError("no leader")


def _single(compartments, config_kw=None, **expert_kw):
    router = ChanRouter()
    nh = _mk_host("hp:1", router, compartments, **expert_kw)
    nh.start_cluster(
        {1: "hp:1"}, False, CounterSM,
        Config(
            cluster_id=CID, node_id=1, election_rtt=10, heartbeat_rtt=1,
            **(config_kw or {}),
        ),
    )
    _wait_leader([nh], CID)
    return nh


# ----------------------------------------------------------------------
# batched ingress ≡ direct proposes
# ----------------------------------------------------------------------


def _drive(nh, n):
    """n singles + one burst; returns the completed result values in
    completion order (apply order assigns them, so a reordering ANYWHERE
    in ingress→step→commit→apply→egress shows up here)."""
    s = nh.get_noop_session(CID)
    states = [nh.propose(s, b"x", timeout=10.0) for _ in range(n)]
    states += nh.propose_batch(s, [b"y"] * n, timeout=10.0)
    vals = []
    for rs in states:
        r = rs.wait(10.0)
        assert r.completed, r.code
        vals.append(r.result.value)
    return vals


def test_batched_ingress_matches_direct():
    on = _single(True)
    try:
        vals_on = _drive(on, 16)
        assert on.hostplane is not None
        st = on.hostplane.stats()
        # bursts always ring; singles ring only when the shard is active
        # (adaptive inline staging), so at least the burst went through
        assert st["ingress"]["submitted"] >= 16
        assert st["ingress"]["drained"] == st["ingress"]["submitted"]
        # completions flow through the egress sink — batched under burst
        # pressure, inline when quiet; together they cover every write
        assert st["egress_notified"] + st["egress_inline"] >= 32
    finally:
        on.stop()
    off = _single(False)
    try:
        vals_off = _drive(off, 16)
        assert off.hostplane is None
    finally:
        off.stop()
    # identical completion semantics: every command applied exactly once,
    # in submission order (CounterSM values are the apply sequence)
    assert vals_on == vals_off == list(range(1, 33))


def test_linearizable_read_through_egress():
    nh = _single(True)
    try:
        s = nh.get_noop_session(CID)
        for _ in range(3):
            nh.sync_propose(s, b"w", timeout=10.0)
        assert nh.sync_read(CID, None, timeout=10.0) == 3
    finally:
        nh.stop()


def test_session_responded_to_tracking():
    """Exactly-once sessions through the ingress tier: registration,
    session-managed proposals and the responded_to watermark ride the
    batched path unchanged."""
    nh = _single(True)
    try:
        s = nh.sync_get_session(CID, timeout=10.0)
        r1 = nh.sync_propose(s, b"a", timeout=10.0)
        r2 = nh.sync_propose(s, b"b", timeout=10.0)
        assert r2.value == r1.value + 1
        # responded_to advanced with each completed proposal
        assert s.responded_to == s.series_id - 1
        nh.sync_close_session(s, timeout=10.0)
    finally:
        nh.stop()


# ----------------------------------------------------------------------
# SystemBusy / PayloadTooBig semantics
# ----------------------------------------------------------------------


def test_payload_too_big_synchronous():
    nh = _single(True, config_kw=dict(max_in_mem_log_size=64 * 1024))
    try:
        s = nh.get_noop_session(CID)
        with pytest.raises(PayloadTooBigError):
            nh.propose(s, b"z" * (64 * 1024), timeout=5.0)
        with pytest.raises(PayloadTooBigError):
            nh.propose_batch(s, [b"ok", b"z" * (64 * 1024)], timeout=5.0)
        # small ones still go through
        assert nh.sync_propose(s, b"ok", timeout=10.0).value == 1
    finally:
        nh.stop()


def test_system_busy_on_full_ring():
    nh = _single(True, host_ingress_ring=4)
    try:
        s = nh.get_noop_session(CID)
        ing = nh.hostplane.ingress
        ing.pause()
        try:
            staged = []
            with pytest.raises(SystemBusyError):
                for _ in range(64):
                    # bursts always ring — with the batcher paused the
                    # bounded ring fills and rejects synchronously, the
                    # direct path's full-entry_q semantics
                    staged.extend(nh.propose_batch(s, [b"x"], timeout=10.0))
            assert staged  # some were accepted before the ring filled
            # an ACTIVE shard routes singles to the ring too — same
            # backpressure, never silent
            with pytest.raises(SystemBusyError):
                for _ in range(8):
                    staged.append(nh.propose(s, b"y", timeout=10.0))
        finally:
            ing.resume()
        # the accepted ones complete normally once the batcher resumes
        for rs in staged:
            assert rs.wait(10.0).completed
    finally:
        nh.stop()


def test_single_propose_on_active_shard_returns_request_state():
    """Regression: a bare ``propose`` landing on an ACTIVE shard rides
    the ring and must return the single RequestState, not the burst
    list (code review round 1)."""
    nh = _single(True)
    try:
        s = nh.get_noop_session(CID)
        ing = nh.hostplane.ingress
        ing.pause()
        try:
            burst = nh.propose_batch(s, [b"a", b"b"], timeout=10.0)
            rs = nh.propose(s, b"c", timeout=10.0)  # shard now active
        finally:
            ing.resume()
        assert not isinstance(rs, list)
        vals = [x.wait(10.0).result.value for x in burst + [rs]]
        assert vals == [1, 2, 3]  # ring order preserved behind the burst
    finally:
        nh.stop()


def test_entry_queue_add_batch_truncates_like_add():
    q = EntryQueue(4)
    es = [Entry(key=i + 1) for i in range(6)]
    assert q.add_batch(es) == 4
    assert not q.add(Entry(key=99))  # full, same as per-entry adds
    got = q.get()
    assert [e.key for e in got] == [1, 2, 3, 4]
    assert q.add_batch(es[4:]) == 2
    q.close()
    assert q.add_batch(es) == 0


# ----------------------------------------------------------------------
# group-commit flusher: merge, block-until-durable, error propagation
# ----------------------------------------------------------------------


class _GateDB:
    """Fake logdb whose save blocks on a gate (to line up riders)."""

    def __init__(self):
        self.gate = threading.Event()
        self.calls = []
        self.fail = None

    def save_raft_state(self, updates):
        self.gate.wait(5.0)
        if self.fail is not None:
            raise self.fail
        self.calls.append(list(updates))


def test_flusher_merges_concurrent_riders():
    db = _GateDB()
    wal = GroupCommitWAL(db)
    # the device probe has no journal here (fake logdb) and would take
    # the fast-device direct path — force the leader protocol, which is
    # what this test exercises
    wal._journal_engaged = True
    try:
        done = []

        def rider(tag):
            wal.flush([tag])
            done.append(tag)

        t1 = threading.Thread(target=rider, args=("a",))
        t1.start()
        time.sleep(0.05)  # flusher now blocked inside save (cycle 1)
        t2 = threading.Thread(target=rider, args=("b",))
        t3 = threading.Thread(target=rider, args=("c",))
        t2.start()
        t3.start()
        time.sleep(0.05)
        assert done == []  # nothing acked before the save returns
        db.gate.set()
        for t in (t1, t2, t3):
            t.join(5.0)
        assert sorted(done) == ["a", "b", "c"]
        # riders b and c merged into ONE second cycle: 2 flushes total,
        # 3 submissions — amortization > 1
        assert wal.flushes == 2
        assert wal.submissions == 3
        assert wal.amortization > 1.0
        assert [sorted(c) for c in db.calls] == [["a"], ["b", "c"]]
    finally:
        wal.stop()


def test_flusher_error_reaches_every_rider():
    db = _GateDB()
    db.fail = OSError("injected")
    wal = GroupCommitWAL(db)
    wal._journal_engaged = True  # force the leader protocol (see above)
    try:
        errs = []

        def rider(tag):
            try:
                wal.flush([tag])
            except OSError as e:
                errs.append((tag, str(e)))

        ts = [threading.Thread(target=rider, args=(t,)) for t in ("a", "b")]
        for t in ts:
            t.start()
        time.sleep(0.05)
        db.gate.set()
        for t in ts:
            t.join(5.0)
        assert sorted(tag for tag, _ in errs) == ["a", "b"]
    finally:
        wal.stop()


# ----------------------------------------------------------------------
# crash durability: nothing acked before its fsync (vfs.ErrorFS)
# ----------------------------------------------------------------------


def test_nothing_acked_before_fsync(tmp_path):
    """Journaled group commit: the flusher's ONE journal fsync is the
    durability point — while it fails, nothing is acked; healing lets the
    committer's retry path land the stranded proposal durably."""
    failing = [False]
    # fail EVERY fsync while armed: the adaptive persist rides either the
    # journal (merged cycles) or the shard's classic fsync (single-batch
    # cycles with an empty journal) — durability must block either way
    inj = vfs.Injector(lambda op, path: failing[0] and op == "fsync")
    efs = vfs.ErrorFS(vfs.OSFS(), inj)
    ldb_dir = str(tmp_path / "wal")

    def logdb_factory(nhc):
        return open_logdb(
            ldb_dir, shards=2,
            kv_factory=lambda d: WalKV(d, fsync=True, fs=efs),
        )

    router = ChanRouter()
    nh = _mk_host(
        "hp:1", router, True, tmpdir=str(tmp_path / "nh"),
        logdb_factory=logdb_factory,
        fs=efs,  # the hostplane journal rides the same injected vfs
    )
    try:
        nh.start_cluster(
            {1: "hp:1"}, False, CounterSM,
            Config(cluster_id=CID, node_id=1, election_rtt=10,
                   heartbeat_rtt=1),
        )
        _wait_leader([nh], CID)
        assert nh.hostplane.wal._journal is not None
        s = nh.get_noop_session(CID)
        assert nh.sync_propose(s, b"pre", timeout=10.0).value == 1
        fsyncs_before = nh.logdb.fsync_count()
        assert fsyncs_before > 0
        # journal fsyncs now fail: proposals must NOT complete — the
        # flusher releases its riders only after the journal append is
        # durable, and a failed cycle re-raises into every rider
        failing[0] = True
        rs = nh.propose(s, b"during", timeout=30.0)
        assert not rs.wait(1.0).completed
        assert not rs.done()
        assert inj.injected > 0
        # heal the disk: the committer's retry path re-arms the group and
        # the stranded proposal commits durably
        failing[0] = False
        r = rs.wait(10.0)
        assert r.completed
        assert nh.logdb.fsync_count() > fsyncs_before
    finally:
        nh.stop()


def test_journal_replay_after_unsynced_shard_apply(tmp_path):
    """Crash between journal fsync and shard apply: reopening the LogDB
    replays the journal into the shard stores (open_logdb replay path),
    so an acked write is never lost."""
    from dragonboat_tpu.logdb.journal import JOURNAL_NAME
    from dragonboat_tpu.wire import Entry as WEntry, State, Update

    ldb = open_logdb(str(tmp_path), shards=2)
    ldb.enable_host_journal()
    # two updates on different shards: a multi-batch cycle always rides
    # the journal (the single-batch/empty-journal cycle takes the classic
    # direct path instead — also asserted below)
    ud = Update(
        cluster_id=5, node_id=1,
        state=State(term=3, vote=1, commit=7),
        entries_to_save=[WEntry(index=7, term=3, key=1, cmd=b"v")],
    )
    ud2 = Update(
        cluster_id=4, node_id=1,
        state=State(term=2, vote=1, commit=1),
        entries_to_save=[WEntry(index=1, term=2, key=2, cmd=b"w")],
    )
    assert ldb.save_raft_state_journaled([ud, ud2]) is True
    assert ldb.journal.appends == 1
    # simulate the crash: drop the DB WITHOUT close (no checkpoint); the
    # shard stores' unsynced tails may be lost — wipe them to model that
    import os as _os
    import shutil as _shutil

    for i in range(2):
        _shutil.rmtree(str(tmp_path / f"shard-{i:02d}"), ignore_errors=True)
    assert _os.path.exists(str(tmp_path / JOURNAL_NAME))
    ldb2 = open_logdb(str(tmp_path), shards=2)
    st = ldb2.read_raft_state(5, 1, 0)
    assert st is not None and st.state.commit == 7
    ents, _ = ldb2.iterate_entries([], 0, 5, 1, 7, 8, 1 << 30)
    assert [e.index for e in ents] == [7]
    # single-batch cycle on an EMPTY journal takes the classic direct
    # fsynced path (nothing to amortize; and a direct write over an
    # unsynced journaled one would be regressed by replay — the bytes==0
    # guard is the correctness rule)
    assert ldb2.journal is None  # journal retired by replay; re-arm
    ldb2.enable_host_journal()
    assert ldb2.save_raft_state_journaled([ud]) is False
    assert ldb2.journal.appends == 0
    ldb2.close()


# ----------------------------------------------------------------------
# compartments OFF: structurally the pre-compartment build
# ----------------------------------------------------------------------


def test_compartments_off_is_bit_identical_shape():
    nh = _single(False)
    try:
        assert nh.hostplane is None
        assert nh.engine.hostplane is None
        node = nh.get_node(CID)
        assert node.ingress is None
        assert node.pending_proposals._egress is None
        assert node.pending_reads._egress is None
        # the classic in-engine apply workers exist only in OFF mode
        names = [t.name for t in nh.engine._threads]
        assert any(n.startswith("apply-worker") for n in names)
        assert not any(n.startswith("host-") for n in names)
    finally:
        nh.stop()


def test_compartments_on_skips_engine_apply_workers():
    nh = _single(True)
    try:
        names = [t.name for t in nh.engine._threads]
        assert not any(n.startswith("apply-worker") for n in names)
        assert nh.engine.hostplane is nh.hostplane
    finally:
        nh.stop()


# ----------------------------------------------------------------------
# host obs families (latched, off by default)
# ----------------------------------------------------------------------


def test_host_obs_families_publish():
    nh = None
    router = ChanRouter()
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT_MS,
            raft_address="hp:1",
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            enable_metrics=True,
            expert=ExpertConfig(host_compartments=True),
        )
    )
    try:
        nh.start_cluster(
            {1: "hp:1"}, False, CounterSM,
            Config(cluster_id=CID, node_id=1, election_rtt=10,
                   heartbeat_rtt=1),
        )
        _wait_leader([nh], CID)
        s = nh.get_noop_session(CID)
        for _ in range(4):
            nh.sync_propose(s, b"m", timeout=10.0)
        import io

        out = io.StringIO()
        nh.write_health_metrics(out)
        text = out.getvalue()
        for fam in (
            "dragonboat_host_ingress_submitted_total",
            "dragonboat_host_ingress_drains_total",
            "dragonboat_host_wal_flushes_total",
            "dragonboat_host_wal_riders_total",
            "dragonboat_host_egress_notified_total",
            "dragonboat_host_apply_batches_total",
        ):
            assert fam in text, fam
    finally:
        nh.stop()


def test_host_obs_off_keeps_latch_none():
    nh = _single(True)
    try:
        assert nh.hostplane._obs is None
        assert nh.hostplane.ingress._obs is None
        assert nh.hostplane.wal._obs is None
    finally:
        nh.stop()
