"""Multi-process host plane (hostproc/, ISSUE 12) differential suite.

Contracts under test:

- SPSC shared-memory rings: record integrity across wraparound, the
  record-size guard, and sustained-full backpressure surfacing as
  :class:`SystemBusyError`;
- worker round trips: the encode worker matches the inline
  ``get_encoded_payload`` oracle byte-for-byte; worker-reported errors
  surface as :class:`WorkerError`;
- the apply tier: ``ProcStateMachine`` ≡ the in-process machine on
  update results, lookup, snapshot round trips and the self-rebase
  bound; kill -9 mid-stream falls back in-process with every command
  applied EXACTLY once;
- the WAL worker: appends land the same bytes the in-process journal
  writes, an (injected) fsync failure fails the flush cycle — nothing
  acked — and heals on retry; a dead worker degrades to the in-process
  append+fsync; an ErrorFS host keeps the sink DETACHED so fault
  injection still reaches the in-process durability point;
- workers-off structural identity: ``host_workers=0`` constructs none
  of it — the compartmentalized plane is bit-identical to the
  pre-hostproc build;
- live stack: workers-on ≡ workers-off on completion values and apply
  order, and kill -9 under load loses no acks and duplicates none.
"""
import io
import os
import signal
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.hostproc import spawnable_spec
from dragonboat_tpu.hostproc import workers as wp
from dragonboat_tpu.hostproc.control import (
    HostProcPlane,
    RingClient,
    WalSink,
    WorkerError,
    WorkerGone,
)
from dragonboat_tpu.hostproc.rings import ShmRing
from dragonboat_tpu.hostproc.sm import ProcStateMachine
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import SystemBusyError
from dragonboat_tpu.testing import CounterSM
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT_MS = 5
CID = 910


class WorkerKVSM:
    """Module-level spawnable SM with observable apply ORDER: value is
    the running count, data echoes the command reversed — any reorder,
    loss or duplication anywhere in the pipeline shows up in either."""

    __hostproc_spawnable__ = True

    def __init__(self, cluster_id, node_id):
        self.log = []

    def update(self, cmd):
        self.log.append(bytes(cmd))
        return Result(value=len(self.log), data=bytes(cmd)[::-1])

    def lookup(self, query):
        return list(self.log)

    def save_snapshot(self, w, files, done):
        blob = b"\x00".join(self.log)
        w.write(len(blob).to_bytes(8, "little") + blob)

    def recover_from_snapshot(self, r, files, done):
        n = int.from_bytes(r.read(8), "little")
        blob = r.read(n)
        self.log = blob.split(b"\x00") if blob else []

    def close(self):
        pass


# ----------------------------------------------------------------------
# rings: wraparound integrity + sustained-full backpressure
# ----------------------------------------------------------------------


def test_ring_wraparound_integrity():
    import random

    rng = random.Random(7)
    r = ShmRing(capacity=256)
    try:
        sent = []
        for i in range(4000):
            blob = bytes([i % 251]) * rng.randint(0, 60)
            while not r.push(blob):
                assert r.pop() == sent.pop(0)
            sent.append(blob)
            if rng.random() < 0.5:
                got = r.pop()
                if got is not None:
                    assert got == sent.pop(0)
        while sent:
            assert r.pop() == sent.pop(0)
        assert r.pop() is None
        assert r.depth() == 0
    finally:
        r.close()


def test_ring_rejects_oversized_record():
    r = ShmRing(capacity=4096)
    try:
        with pytest.raises(ValueError):
            r.push(b"x" * (r.cap + 1))
    finally:
        r.close()


class _FakePlane:
    def __init__(self):
        self._obs = None
        self.busy = 0
        self.fallbacks = 0

    def _count_busy(self, role):
        self.busy += 1

    def _count_fallback(self, role):
        self.fallbacks += 1


def test_ring_sustained_full_raises_system_busy():
    """A request ring nobody drains stays full past the busy window —
    the client surfaces SystemBusy, the ingress backpressure contract."""
    plane = _FakePlane()
    c = RingClient(
        plane, "encode", ShmRing(capacity=4096), ShmRing(capacity=4096), 0
    )
    c.alive = True
    try:
        while c.req.push(b"z" * 1500):  # no consumer: fill the ring
            pass
        with pytest.raises(SystemBusyError):
            c.call(wp.OP_PING, b"z" * 1500, busy_timeout=0.05)
        assert plane.busy == 1
    finally:
        c.req.close()
        c.resp.close()


def test_spawnable_spec_rules():
    assert spawnable_spec(WorkerKVSM) == "test_hostproc:WorkerKVSM"
    assert spawnable_spec(CounterSM) == "dragonboat_tpu.testing:CounterSM"

    class Local:
        __hostproc_spawnable__ = True

    assert spawnable_spec(Local) is None  # <locals> qualname
    assert spawnable_spec(lambda c, n: None) is None  # not opted in


# ----------------------------------------------------------------------
# one shared plane for the worker round-trip suites (spawn amortized)
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def plane():
    p = HostProcPlane(workers=2, encode_lanes=2)
    yield p
    p.stop()


def test_encode_worker_matches_inline_oracle(plane):
    from dragonboat_tpu.rsm.encoded import get_encoded_payload

    lane = plane.encode_lane(0)
    cmds = [b"a", b"hello world", b"x" * 3000, b"\x00\xff" * 17]
    for ct in (0, 1):  # no-compression, snappy
        encs = lane.encode(ct, cmds)
        assert encs == [get_encoded_payload(ct, c) for c in cmds]


def test_worker_error_surfaces(plane):
    c = plane.apply_lanes[0]
    with pytest.raises(WorkerError):
        c.call(wp.OP_SM_UPDATE, (0).to_bytes(8, "little") * 2 + b"x")


def test_proc_sm_differential_and_rebase(plane):
    spec = spawnable_spec(WorkerKVSM)
    sm = ProcStateMachine(plane, spec, 42, 1, WorkerKVSM)
    oracle = WorkerKVSM(42, 1)
    assert sm.device_bound
    # force frequent self-rebase so the redo buffer's snapshot path runs
    sm.REBASE_CMDS = 4
    for i in range(25):
        cmd = b"cmd-%d" % i
        r, ro = sm.update(cmd), oracle.update(cmd)
        assert (r.value, r.data) == (ro.value, ro.data), i
    assert sm.lookup(None) == oracle.lookup(None)
    assert len(sm._redo) < 25  # rebase kept the buffer bounded
    # snapshot stream is byte-identical to the plain machine's
    w1, w2 = io.BytesIO(), io.BytesIO()
    sm.save_snapshot(w1, [], None)
    oracle.save_snapshot(w2, [], None)
    assert w1.getvalue() == w2.getvalue()
    # recover round trip into a fresh proxy
    sm2 = ProcStateMachine(plane, spec, 43, 1, WorkerKVSM)
    sm2.recover_from_snapshot(io.BytesIO(w1.getvalue()), [], None)
    assert sm2.lookup(None) == oracle.lookup(None)
    r, ro = sm2.update(b"after"), oracle.update(b"after")
    assert (r.value, r.data) == (ro.value, ro.data)
    sm.close()
    sm2.close()


def test_wal_sink_append_bytes_and_injected_fsync_failure(
    plane, tmp_path
):
    path = str(tmp_path / "j" / "host-journal.wal")
    sink = WalSink(plane.wal_lane)
    assert sink.append(path, b"REC-1|") is True
    assert sink.append(path, b"REC-2|") is True
    with open(path, "rb") as f:
        assert f.read() == b"REC-1|REC-2|"
    # injected fsync failure: the op RAN and FAILED — WorkerError (an
    # OSError) propagates so the flush cycle fails and nothing is acked
    plane.inject(plane.wal_lane.worker_id, {"wal_fail_fsyncs": 1})
    with pytest.raises(OSError):
        sink.append(path, b"REC-3|")
    # healed: the retry lands durably
    assert sink.append(path, b"REC-4|") is True
    # size-guarded truncate: a STALE expected size (an abandoned
    # truncate executing after further appends) is REFUSED — the
    # journal's caller falls back to its own in-process truncate —
    # while the correct size truncates durably
    assert sink.truncate(path, 1) is False
    with open(path, "rb") as f:
        assert f.read() != b""
    assert sink.truncate(path, os.path.getsize(path)) is True
    with open(path, "rb") as f:
        assert f.read() == b""


# ----------------------------------------------------------------------
# kill -9: fallback, exactly-once, bounded respawn
# ----------------------------------------------------------------------


def test_kill9_proc_sm_fallback_exactly_once():
    p = HostProcPlane(workers=1, encode_lanes=1)
    try:
        spec = spawnable_spec(WorkerKVSM)
        sm = ProcStateMachine(p, spec, 7, 1, WorkerKVSM)
        sent = []
        for i in range(10):
            cmd = b"pre-%d" % i
            sent.append(cmd)
            assert sm.update(cmd).value == i + 1
        os.kill(p.worker_pid(0), signal.SIGKILL)
        deadline = time.time() + 10
        while p.alive_count() and time.time() < deadline:
            time.sleep(0.02)
        # mid-flight command applies exactly once in the rebuilt state
        sent.append(b"during")
        r = sm.update(b"during")
        assert r.value == 11 and r.data == b"gnirud"
        assert not sm.device_bound
        assert sm.lookup(None) == sent  # nothing lost, nothing doubled
        # the monitor respawns the worker (bounded), the fallen-back
        # proxy stays in-process, and a FRESH proxy can bind remotely
        deadline = time.time() + 15
        while p.alive_count() == 0 and time.time() < deadline:
            time.sleep(0.05)
        assert p.alive_count() == 1
        assert p.restarts_total == 1
        sm2 = ProcStateMachine(p, spec, 8, 1, WorkerKVSM)
        assert sm2.device_bound
        assert sm2.update(b"fresh").value == 1
        st = p.stats()
        assert st["fallbacks"].get("apply", 0) >= 1
    finally:
        p.stop()


def test_kill9_wal_sink_falls_back_in_process(tmp_path):
    p = HostProcPlane(workers=1, encode_lanes=1)
    try:
        path = str(tmp_path / "host-journal.wal")
        sink = WalSink(p.wal_lane)
        assert sink.append(path, b"A|") is True
        os.kill(p.worker_pid(0), signal.SIGKILL)
        deadline = time.time() + 10
        while p.wal_lane.alive and time.time() < deadline:
            time.sleep(0.02)
        # dead worker: the sink reports unavailable — the journal's
        # caller falls back to its own in-process write+fsync
        assert sink.append(path, b"B|") is False
    finally:
        p.stop()


# ----------------------------------------------------------------------
# GroupCommitWAL through the WAL worker: nothing acked before fsync
# ----------------------------------------------------------------------


def test_wal_worker_flush_failure_reaches_riders(tmp_path, monkeypatch):
    """The journaled flush cycle rides the WAL worker; an injected
    worker-side fsync failure fails the WHOLE cycle (every rider sees
    the error — nothing acked), and the healed retry lands durably with
    a journal a fresh open replays consistently."""
    from dragonboat_tpu.hostplane import GroupCommitWAL
    from dragonboat_tpu.logdb import open_logdb
    from dragonboat_tpu.wire import Entry as WEntry, State, Update

    monkeypatch.setenv("DBTPU_HOSTPROC_OFFLOAD", "1")
    p = HostProcPlane(workers=1, encode_lanes=1)
    ldb = open_logdb(str(tmp_path), shards=2)
    try:
        wal = GroupCommitWAL(
            ldb, journal_mode="force", hostproc=p
        )
        assert wal.status()["worker_sink"] is True
        # two shards in one cycle => the cycle rides the journal (the
        # single-batch/empty-journal rule would take the classic path)
        ud = Update(
            cluster_id=5, node_id=1,
            state=State(term=3, vote=1, commit=7),
            entries_to_save=[WEntry(index=7, term=3, key=1, cmd=b"v")],
        )
        ud2 = Update(
            cluster_id=4, node_id=1,
            state=State(term=2, vote=1, commit=1),
            entries_to_save=[WEntry(index=1, term=2, key=2, cmd=b"w")],
        )
        p.inject(0, {"wal_fail_fsyncs": 1})
        with pytest.raises(OSError):
            wal.flush([ud, ud2])
        # heal: the caller's retry path re-flushes and is acked
        wal.flush([ud, ud2])
        assert ldb.journal.appends >= 1
        assert p.stats()["lanes"]["wal"]["calls"] > 0
    finally:
        ldb.close()
        p.stop()
    # both the failed and the healed append may sit in the journal —
    # replay is idempotent and must land exactly the acked state
    ldb2 = open_logdb(str(tmp_path), shards=2)
    try:
        st = ldb2.read_raft_state(5, 1, 0)
        assert st is not None and st.state.commit == 7
        ents, _ = ldb2.iterate_entries([], 0, 5, 1, 7, 8, 1 << 30)
        assert [e.index for e in ents] == [7]
    finally:
        ldb2.close()


def test_error_fs_keeps_wal_sink_detached(tmp_path, monkeypatch):
    """An ErrorFS host must keep fault injection wired to the ACTUAL
    durability point: the vfs cannot cross the process boundary, so the
    sink stays detached and the in-process journal path (the existing
    test_hostplane nothing-acked-before-fsync suite) keeps covering it."""
    from dragonboat_tpu import vfs
    from dragonboat_tpu.hostplane import GroupCommitWAL
    from dragonboat_tpu.logdb import open_logdb

    monkeypatch.setenv("DBTPU_HOSTPROC_OFFLOAD", "1")
    inj = vfs.Injector(lambda op, path: False)
    efs = vfs.ErrorFS(vfs.OSFS(), inj)
    p = HostProcPlane(workers=1, encode_lanes=1)
    ldb = open_logdb(str(tmp_path), shards=2)
    try:
        wal = GroupCommitWAL(
            ldb, journal_mode="force", hostproc=p, fs=efs
        )
        assert wal.status()["worker_sink"] is False
    finally:
        ldb.close()
        p.stop()


# ----------------------------------------------------------------------
# WAL probe strategy (ISSUE 12 satellite): modes, reprobe, status
# ----------------------------------------------------------------------


def test_wal_journal_modes_and_reprobe(tmp_path):
    from dragonboat_tpu.hostplane import GroupCommitWAL
    from dragonboat_tpu.logdb import open_logdb

    ldb = open_logdb(str(tmp_path), shards=2)
    try:
        off = GroupCommitWAL(ldb, journal_mode="off")
        assert off.status()["mode"] == "off"
        assert off.status()["journal"] is False
        assert off.status()["engaged"] is False
    finally:
        ldb.close()
    ldb = open_logdb(str(tmp_path / "b"), shards=2)
    try:
        forced = GroupCommitWAL(ldb, journal_mode="force")
        st = forced.status()
        assert st["mode"] == "force" and st["engaged"] is True
        # forced mode RE-probes at construction (the satellite fix: one
        # polluted startup sample must not pin the pacing window)
        assert st["probes"] >= 2
        p1 = st["probe_ms"]
        p2 = forced.reprobe() * 1e3
        assert forced.status()["probes"] >= 3
        assert p2 >= 0.0 and p1 >= 0.0
        # this box's disk fsyncs sub-ms: auto mode keeps classic saves
        auto = GroupCommitWAL(ldb, journal_mode="auto")
        if auto.status()["probe_ms"] < 0.5:
            assert auto.status()["engaged"] is False
    finally:
        ldb.close()


# ----------------------------------------------------------------------
# live stack
# ----------------------------------------------------------------------


def _mk_host(addr, router, tmpdir, host_workers=0, trace=0, **expert_kw):
    return NodeHost(
        NodeHostConfig(
            node_host_dir=tmpdir,
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            trace_sample_every=trace,
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            expert=ExpertConfig(
                host_compartments=True, host_workers=host_workers,
                **expert_kw,
            ),
        )
    )


def _wait_leader(nh, cid, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        lid, ok = nh.get_leader_id(cid)
        if ok:
            return lid
        time.sleep(0.02)
    raise AssertionError("no leader")


def _drive(nh, cid, n):
    s = nh.get_noop_session(cid)
    states = [nh.propose(s, b"s%d" % i, timeout=10.0) for i in range(n)]
    states += nh.propose_batch(s, [b"b%d" % i for i in range(n)], timeout=10.0)
    out = []
    for rs in states:
        r = rs.wait(10.0)
        assert r.completed, r.code
        out.append((r.result.value, bytes(r.result.data)))
    return out


def test_workers_off_structural_identity(tmp_path):
    """host_workers=0: no hostproc plane, no encode lanes, no journal
    sink, the user SM unwrapped — the compartmentalized plane is the
    pre-hostproc build exactly."""
    router = ChanRouter()
    nh = _mk_host("hw:1", router, str(tmp_path / "nh"))
    try:
        assert nh.hostproc is None
        assert nh.hostplane.hostproc is None
        assert nh.hostplane.ingress._encoders is None
        assert nh.hostplane.wal._journal.sink is None
        nh.start_cluster(
            {1: "hw:1"}, False, WorkerKVSM,
            Config(cluster_id=CID, node_id=1, election_rtt=10,
                   heartbeat_rtt=1),
        )
        _wait_leader(nh, CID)
        assert type(nh.get_node(CID).sm.managed.sm) is WorkerKVSM
        ws = nh.wal_status()
        assert ws is not None and ws["worker_sink"] is False
    finally:
        nh.stop()


def test_live_differential_workers_on_vs_off(tmp_path, monkeypatch):
    """Workers-on ≡ workers-off on completion values, apply order and
    payload echoes — with the apply tier REALLY remote (proxy bound,
    worker round trips observed)."""
    monkeypatch.setenv("DBTPU_HOSTPROC_OFFLOAD", "1")
    results = {}
    for mode, workers in (("off", 0), ("on", 2)):
        router = ChanRouter()
        nh = _mk_host(
            f"hw{mode}:1", router, str(tmp_path / f"nh-{mode}"),
            host_workers=workers, host_wal_journal="force",
            trace=1 if workers else 0,
        )
        try:
            nh.start_cluster(
                {1: f"hw{mode}:1"}, False, WorkerKVSM,
                Config(cluster_id=CID, node_id=1, election_rtt=10,
                       heartbeat_rtt=1),
            )
            _wait_leader(nh, CID)
            if workers:
                usm = nh.get_node(CID).sm.managed.sm
                assert isinstance(usm, ProcStateMachine)
                assert usm.device_bound
                assert nh.wal_status()["worker_sink"] is True
            results[mode] = _drive(nh, CID, 20)
            if workers:
                st = nh.hostproc.stats()
                assert st["lanes"]["apply"]["calls"] >= 40
                assert st["restarts"] == 0
                # ipc trace stage (ISSUE 12 satellite): a ring-staged
                # burst rode the encode worker, so its sampled traces
                # stamp the shared-memory handoff BEFORE ingress
                s2 = nh.get_noop_session(CID)
                brs = nh.propose_batch(
                    s2, [b"t%d" % i for i in range(8)], timeout=10.0
                )
                for rs in brs:
                    assert rs.wait(10.0).completed
                stamped = [
                    [e[0] for e in rs.trace.events]
                    for rs in brs if rs.trace is not None
                ]
                assert stamped and any("ipc" in ev for ev in stamped)
                for ev in stamped:
                    if "ipc" in ev:
                        assert ev.index("ipc") < ev.index("ingress")
                assert st["lanes"]["encode"]["calls"] >= 1 or (
                    nh.hostproc.stats()["lanes"]["encode"]["calls"] >= 1
                )
        finally:
            nh.stop()
    assert results["on"] == results["off"]


def test_live_kill9_under_load_no_lost_or_duplicate_acks(
    tmp_path, monkeypatch
):
    """kill -9 the (single) worker mid-load: every acked proposal is
    applied exactly once — the proxy's snapshot+redo rebuild — and the
    plane keeps serving (fallen back) afterwards."""
    monkeypatch.setenv("DBTPU_HOSTPROC_OFFLOAD", "1")
    router = ChanRouter()
    nh = _mk_host(
        "hwk:1", router, str(tmp_path / "nh"), host_workers=1,
        host_wal_journal="force",
    )
    try:
        nh.start_cluster(
            {1: "hwk:1"}, False, WorkerKVSM,
            Config(cluster_id=CID, node_id=1, election_rtt=10,
                   heartbeat_rtt=1),
        )
        _wait_leader(nh, CID)
        usm = nh.get_node(CID).sm.managed.sm
        assert isinstance(usm, ProcStateMachine) and usm.device_bound
        s = nh.get_noop_session(CID)
        acked = []
        stop = threading.Event()
        errs = []

        def loader():
            i = 0
            while not stop.is_set():
                try:
                    r = nh.sync_propose(s, b"k%d" % i, timeout=10.0)
                    acked.append((i, r.value))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
                    return
                i += 1

        t = threading.Thread(target=loader)
        t.start()
        deadline = time.time() + 5
        while len(acked) < 10 and time.time() < deadline:
            time.sleep(0.02)
        os.kill(nh.hostproc.worker_pid(0), signal.SIGKILL)
        deadline = time.time() + 5
        while usm.device_bound and time.time() < deadline:
            time.sleep(0.02)
        # keep loading through the fallback window, then stop
        time.sleep(0.5)
        stop.set()
        t.join(15)
        assert not errs, errs
        assert len(acked) >= 10
        # exactly-once: result values are the strictly increasing apply
        # counter with no gaps and no repeats, and the surviving state
        # holds exactly the acked commands in order
        assert [v for _, v in acked] == list(range(1, len(acked) + 1))
        log = nh.sync_read(CID, None, timeout=10.0)
        assert log[: len(acked)] == [b"k%d" % i for i, _ in acked]
        assert not usm.device_bound
        st = nh.hostproc.stats()
        assert st["fallbacks"].get("apply", 0) >= 1
        # still serving after the fallback
        r = nh.sync_propose(s, b"post", timeout=10.0)
        assert r.value == len(log) + 1
    finally:
        nh.stop()
