"""Port of the reference's etcd-derived in-memory log tests.

Reference: ``/root/reference/internal/raft/inmemory_etcd_test.go`` — same
test names and case tables, against :mod:`dragonboat_tpu.raft.inmemory`.
"""
from __future__ import annotations

from dragonboat_tpu.raft.inmemory import InMemory
from dragonboat_tpu.wire import Entry, Snapshot


def E(index, term=0):
    return Entry(index=index, term=term)


def mk(entries, marker, snap=None):
    u = InMemory(marker - 1 if marker else 0)
    u.entries = list(entries)
    u.marker_index = marker
    u.snapshot = snap
    return u


def sig(ents):
    return [(e.term, e.index) for e in ents]


def test_unstable_maybe_first_index():
    cases = [
        ([E(5, 1)], 5, None, False, 0),
        ([], 0, None, False, 0),
        ([E(5, 1)], 5, Snapshot(index=4, term=1), True, 5),
        ([], 5, Snapshot(index=4, term=1), True, 5),
    ]
    for i, (entries, offset, snap, wok, windex) in enumerate(cases):
        u = mk(entries, offset, snap)
        index, ok = u.get_snapshot_index()
        assert ok == wok, f"#{i}"
        if ok:
            assert index + 1 == windex, f"#{i}"


def test_maybe_last_index():
    cases = [
        ([E(5, 1)], 5, None, True, 5),
        ([E(5, 1)], 5, Snapshot(index=4, term=1), True, 5),
        ([], 5, Snapshot(index=4, term=1), True, 4),
        ([], 0, None, False, 0),
    ]
    for i, (entries, offset, snap, wok, windex) in enumerate(cases):
        u = mk(entries, offset, snap)
        index, ok = u.get_last_index()
        assert ok == wok, f"#{i}"
        assert index == windex, f"#{i}"


def test_unstable_maybe_term():
    cases = [
        ([E(5, 1)], 5, None, 5, True, 1),
        ([E(5, 1)], 5, None, 6, False, 0),
        ([E(5, 1)], 5, None, 4, False, 0),
        ([E(5, 1)], 5, Snapshot(index=4, term=1), 5, True, 1),
        ([E(5, 1)], 5, Snapshot(index=4, term=1), 6, False, 0),
        ([E(5, 1)], 5, Snapshot(index=4, term=1), 4, True, 1),
        ([E(5, 1)], 5, Snapshot(index=4, term=1), 3, False, 0),
        ([], 5, Snapshot(index=4, term=1), 5, False, 0),
        ([], 5, Snapshot(index=4, term=1), 4, True, 1),
        ([], 0, None, 5, False, 0),
    ]
    for i, (entries, offset, snap, index, wok, wterm) in enumerate(cases):
        u = mk(entries, offset, snap)
        term, ok = u.get_term(index)
        assert ok == wok, f"#{i}"
        assert term == wterm, f"#{i}"


def test_unstable_restore():
    u = mk([E(5, 1)], 5, Snapshot(index=4, term=1))
    s = Snapshot(index=6, term=2)
    u.restore(s)
    assert u.marker_index == s.index + 1
    assert len(u.entries) == 0
    assert u.snapshot == s


def test_unstable_truncate_and_append():
    cases = [
        # append to the end
        ([E(5, 1)], 5, None, [E(6, 1), E(7, 1)],
         5, [(1, 5), (1, 6), (1, 7)]),
        # replace the in-memory entries
        ([E(5, 1)], 5, None, [E(5, 2), E(6, 2)],
         5, [(2, 5), (2, 6)]),
        ([E(5, 1)], 5, None, [E(4, 2), E(5, 2), E(6, 2)],
         4, [(2, 4), (2, 5), (2, 6)]),
        # truncate existing entries and append
        ([E(5, 1), E(6, 1), E(7, 1)], 5, None, [E(6, 2)],
         5, [(1, 5), (2, 6)]),
        ([E(5, 1), E(6, 1), E(7, 1)], 5, None, [E(7, 2), E(8, 2)],
         5, [(1, 5), (1, 6), (2, 7), (2, 8)]),
    ]
    for i, (entries, offset, snap, to_append, woffset, wentries) in enumerate(cases):
        u = mk(entries, offset, snap)
        u.merge(list(to_append))
        assert u.marker_index == woffset, f"#{i}"
        assert sig(u.entries) == wentries, f"#{i}"


def test_entry_merge_thread_safety():
    cases = [
        ([E(5, 1), E(6, 1), E(7, 1)], 5, [E(7, 2), E(7, 2)], 7, 1),
        ([E(5, 1), E(6, 1), E(7, 1)], 5, [E(4, 2), E(5, 2)], 5, 1),
        ([E(5, 1), E(6, 1), E(7, 1)], 5, [E(5, 2), E(6, 2)], 5, 1),
    ]
    for idx, (entries, marker, merge, exp_index, exp_term) in enumerate(cases):
        im = mk(entries, marker)
        old = im.entries[0:]
        im.merge(list(merge))
        for e in old:
            if e.index == exp_index:
                assert e.term == exp_term, f"#{idx}: entry term changed"


def test_unstable_stable_to():
    cases = [
        ([], 0, None, 5, 1, 0, 0, 0),
        ([E(5, 1)], 5, None, 5, 1, 5, 6, 0),
        ([E(5, 1), E(6, 1)], 5, None, 5, 1, 5, 6, 1),
        ([E(6, 2)], 6, None, 6, 1, 0, 7, 0),
        ([E(5, 1)], 5, None, 4, 1, 0, 5, 1),
        ([E(5, 1)], 5, None, 4, 2, 0, 5, 1),
        # with snapshot
        ([E(5, 1)], 5, Snapshot(index=4, term=1), 5, 1, 5, 6, 0),
        ([E(5, 1), E(6, 1)], 5, Snapshot(index=4, term=1), 5, 1, 5, 6, 1),
        ([E(6, 2)], 6, Snapshot(index=5, term=1), 6, 1, 0, 7, 0),
        ([E(5, 1)], 5, Snapshot(index=4, term=1), 4, 1, 0, 5, 1),
        ([E(5, 2)], 5, Snapshot(index=4, term=2), 4, 1, 0, 5, 1),
    ]
    for i, (entries, offset, snap, index, term, saved_to, woffset, wlen) in enumerate(cases):
        u = mk(entries, offset, snap)
        u.saved_to = 0
        u.saved_log_to(index, term)
        u.applied_log_to(index)
        assert u.saved_to == saved_to, f"#{i}: saved_to {u.saved_to}"
        assert u.marker_index == woffset, f"#{i}: marker {u.marker_index}"
        assert len(u.entries) == wlen, f"#{i}: len {len(u.entries)}"
