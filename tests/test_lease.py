"""Leader-lease read plane differential suite (ISSUE 10).

Contracts under test:

- lease-off structural identity: ``read_lease=False`` keeps
  ``raft.lease is None`` and the READ_INDEX path byte-for-byte on the
  pending-request + hint-broadcast protocol (the ``_read_plane_used``
  precedent);
- lease reads ≡ ReadIndex ≡ scalar oracle on released values: the same
  scripted sequence releases identical (ctx → index) maps with the lease
  on and off, and both equal the committed watermark at read time;
- the invalidation matrix: expiry (no quorum acks for ``duration``
  ticks), leadership transfer (lease ceded BEFORE TIMEOUT_NOW can fire),
  membership change (add/remove node recycles the bases), term change;
- expiry mid-batch: reads served under the lease and reads falling back
  after expiry both release correct indices within one batch window;
- clock-jump fault injection: a negative jump makes a stale lease serve
  a read its (correct) clock would have refused — deterministically at
  the raft level, and end-to-end where the ``HistoryRecorder`` +
  ``check_linearizable`` catch the resulting stale read as a
  linearizability violation (not by luck);
- the live stack: lease-served ``read_index``/``sync_read`` on 3
  in-process NodeHosts across an injected cross-domain topology, the
  ``dragonboat_lease_*`` metric families, and the tpu coordinator's
  advisory ``LeaseTable``.
"""
from __future__ import annotations

import sys
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ConfigError, ExpertConfig
from dragonboat_tpu.lease import LeaderLease, LeaseTable
from dragonboat_tpu.linearizability import (
    HistoryRecorder,
    check_linearizable,
)
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.raft import InMemLogDB, Raft
from dragonboat_tpu.transport import ChanRouter, ChanTransport
from dragonboat_tpu.transport.latency import LatencyInjector, crossdomain
from dragonboat_tpu.wire import Entry, Message, MessageType, SystemCtx

from tests.raft_harness import Network
from tests.loadwait import wait_until

MT = MessageType


# ======================================================================
# raft-level harness
# ======================================================================


def mk_raft(nid: int, lease: bool = True, election: int = 10) -> Raft:
    c = Config(
        node_id=nid, cluster_id=1, election_rtt=election, heartbeat_rtt=1,
        check_quorum=True, read_lease=lease,
    )
    r = Raft(c, InMemLogDB(), seed=nid)
    r.has_not_applied_config_change = lambda: False
    return r


def mk_net(lease: bool = True, n: int = 3, election: int = 10) -> Network:
    return Network(*[mk_raft(i, lease, election) for i in range(1, n + 1)])


def elect(net: Network, nid: int = 1) -> Raft:
    net.send(Message(from_=nid, to=nid, type=MT.ELECTION))
    r = net.raft(nid)
    assert r.is_leader()
    return r


def hb_round(net: Network, leader: Raft) -> None:
    """One leader tick (fires a heartbeat broadcast) + full delivery of
    everything it triggers (acks included)."""
    leader.tick()
    net.send(*net.filter(net.take_msgs(leader)))


def read(r: Raft, lo: int) -> SystemCtx:
    ctx = SystemCtx(low=lo, high=lo + 1)
    r.handle(
        Message(type=MT.READ_INDEX, from_=r.node_id, hint=lo, hint_high=lo + 1)
    )
    return ctx


def propose(net: Network, leader: Raft, payload: bytes = b"x") -> None:
    leader.handle(
        Message(
            type=MT.PROPOSE, from_=leader.node_id,
            entries=[Entry(cmd=payload)],
        )
    )
    net.send(*net.filter(net.take_msgs(leader)))


# ======================================================================
# config gate
# ======================================================================


def test_read_lease_requires_check_quorum():
    with pytest.raises(ConfigError):
        Config(
            node_id=1, cluster_id=1, election_rtt=10, heartbeat_rtt=1,
            read_lease=True,
        ).validate()
    with pytest.raises(ConfigError):
        Config(
            node_id=1, cluster_id=1, election_rtt=10, heartbeat_rtt=1,
            check_quorum=True, quiesce=True, read_lease=True,
        ).validate()


def test_lease_off_structural_identity():
    """read_lease=False: raft.lease is None (the structural latch) and a
    READ_INDEX runs the full pending-request + hint-broadcast protocol."""
    net = mk_net(lease=False)
    r = elect(net)
    assert r.lease is None
    hb_round(net, r)
    net.take_msgs(r)  # drain
    r.handle(Message(type=MT.READ_INDEX, from_=1, hint=7, hint_high=8))
    assert r.read_index.has_pending_request()  # pending entry exists
    assert not r.ready_to_read  # nothing served locally
    # the confirmation hint rides a heartbeat broadcast
    hints = [m for m in r.msgs if m.type == MT.HEARTBEAT and m.hint == 7]
    assert len(hints) == 2


# ======================================================================
# the short path
# ======================================================================


def test_lease_read_serves_locally_with_zero_rounds():
    net = mk_net(lease=True)
    r = elect(net)
    for _ in range(2):
        hb_round(net, r)
    net.take_msgs(r)
    assert r.lease.valid(r.tick_count, r.quorum(), r.voting_members(), 1)
    ctx = read(r, 7)
    assert [(x.index, x.system_ctx, x.lease) for x in r.ready_to_read] == [
        (r.log.committed, ctx, True)
    ]
    assert not r.read_index.has_pending_request()
    # zero confirmation traffic: no hint-carrying heartbeat left raft
    assert not [m for m in r.msgs if m.type == MT.HEARTBEAT and m.hint == 7]
    assert r.lease.stats()["reads_local"] == 1


def test_lease_remote_requester_gets_read_index_resp():
    """A follower-forwarded read is answered directly with
    READ_INDEX_RESP at the committed index — the same routing a confirmed
    release uses (apply_read_releases)."""
    net = mk_net(lease=True)
    r = elect(net)
    for _ in range(2):
        hb_round(net, r)
    net.take_msgs(r)
    r.handle(Message(type=MT.READ_INDEX, from_=2, hint=9, hint_high=10))
    resp = [m for m in r.msgs if m.type == MT.READ_INDEX_RESP]
    assert len(resp) == 1
    assert resp[0].to == 2
    assert resp[0].log_index == r.log.committed
    assert resp[0].hint == 9 and resp[0].hint_high == 10
    assert not r.ready_to_read  # the requester is remote


# ======================================================================
# differential: lease ≡ ReadIndex ≡ scalar oracle on released values
# ======================================================================


def _run_scripted(lease: bool):
    """One scripted write+read interleave; returns [(ctx_low, index)]
    releases observed on the leader plus the oracle (committed at read
    time)."""
    net = mk_net(lease=lease)
    r = elect(net)
    released = []
    oracle = []
    lo = 100

    def do_read():
        nonlocal lo
        lo += 1
        oracle.append((lo, r.log.committed))
        read(r, lo)
        # deliver whatever the read produced (hint broadcasts + echoes on
        # the fallback path; nothing on the lease path)
        net.send(*net.filter(net.take_msgs(r)))
        for x in r.ready_to_read:
            released.append((x.system_ctx.low, x.index))
        r.clear_ready_to_read()

    for i in range(3):
        hb_round(net, r)
        propose(net, r, b"w%d" % i)
        do_read()
        do_read()
    return released, oracle


def test_differential_lease_equals_readindex_equals_oracle():
    with_lease, oracle_a = _run_scripted(True)
    without, oracle_b = _run_scripted(False)
    assert with_lease == without == oracle_a == oracle_b
    assert len(with_lease) == 6


# ======================================================================
# invalidation matrix
# ======================================================================


def test_lease_expires_without_quorum_acks_mid_batch():
    net = mk_net(lease=True)
    r = elect(net)
    for _ in range(2):
        hb_round(net, r)
    net.take_msgs(r)
    # batch half 1: served under the lease
    read(r, 50)
    assert len(r.ready_to_read) == 1
    # cut off the followers; tick past the lease duration (8 of the
    # 10-tick election timeout) but short of a second check-quorum window
    net.isolate(1)
    for _ in range(r.lease.duration + 1):
        r.tick()
        net.send(*net.filter(net.take_msgs(r)))  # all dropped
    assert r.is_leader()  # check-quorum hasn't deposed it yet
    # batch half 2: the lease is expired — full ReadIndex fallback
    read(r, 51)
    assert len(r.ready_to_read) == 1  # unchanged
    assert r.read_index.has_pending_request()
    assert r.lease.stats()["expiries"] == 1
    # heal; the pending ctx confirms through the echo quorum and releases
    # at the same committed watermark
    net.recover()
    hb_round(net, r)
    assert [(x.system_ctx.low, x.index) for x in r.ready_to_read] == [
        (50, r.log.committed), (51, r.log.committed)
    ]


def test_leadership_transfer_cedes_lease_before_timeout_now():
    net = mk_net(lease=True)
    r = elect(net)
    for _ in range(2):
        hb_round(net, r)
    net.take_msgs(r)
    assert r.lease.valid(r.tick_count, r.quorum(), r.voting_members(), 1)
    # transfer to 2 (caught up → TIMEOUT_NOW fires immediately); the
    # lease must already be ceded when that message is emitted
    r.handle(Message(type=MT.LEADER_TRANSFER, from_=2, hint=2))
    assert r.leader_transfering()
    assert r.lease.ceded
    # acks are still fresh — only the cede blocks the short path
    read(r, 60)
    assert not r.ready_to_read
    assert r.read_index.has_pending_request()
    # complete the transfer; node 2 leads at the higher term
    net.send(*net.filter(net.take_msgs(r)))
    r2 = net.raft(2)
    assert r2.is_leader() and not r.is_leader()
    # the new leader arms its own lease and serves locally
    hb_round(net, r2)
    net.take_msgs(r2)
    read(r2, 61)
    assert [x.system_ctx.low for x in r2.ready_to_read] == [61]


def test_membership_change_invalidates_and_rearms():
    net = mk_net(lease=True)
    r = elect(net)
    for _ in range(2):
        hb_round(net, r)
    net.take_msgs(r)
    assert r.lease.valid(r.tick_count, r.quorum(), r.voting_members(), 1)
    r.remove_node(3)
    assert not r.lease.bases  # bases recycled with the membership
    read(r, 70)
    assert not r.ready_to_read  # fallback until the new quorum acks
    assert r.read_index.has_pending_request()
    # one heartbeat round against the shrunk membership re-arms it (and
    # the echo releases the pending fallback read)
    hb_round(net, r)
    r.clear_ready_to_read()
    read(r, 71)
    assert [x.system_ctx.low for x in r.ready_to_read] == [71]


def test_term_change_invalidates():
    net = mk_net(lease=True)
    r = elect(net)
    for _ in range(2):
        hb_round(net, r)
    assert r.lease.bases
    r.handle(Message(type=MT.HEARTBEAT, from_=2, term=r.term + 5))
    assert r.is_follower()
    assert not r.lease.bases and not r.lease.ceded


# ======================================================================
# clock-jump fault injection (deterministic half)
# ======================================================================


def test_clock_jump_makes_stale_lease_serve_and_checker_catches_it():
    """The raft-level deterministic version of the soak fault: node 1's
    clock jumps backward while it is partitioned; a new leader commits a
    later write; node 1's (wrongly still-valid) lease serves a read of
    the OLD state.  The history is non-linearizable and the checker must
    say so — and the same history with the correct (un-jumped) refusal
    must pass."""
    net = mk_net(lease=True)
    r1 = elect(net)
    for _ in range(2):
        hb_round(net, r1)
    propose(net, r1, b"v1")
    committed_v1 = r1.log.committed
    net.isolate(1)
    # clock fault on the isolated leader
    r1.lease.inject_clock_jump(-1000)
    # node 2 eventually campaigns and wins over {2, 3} (the §6 vote
    # lease has expired for them once their clocks pass the timeout)
    r2, r3 = net.raft(2), net.raft(3)
    for _ in range(25):
        r2.tick()
        r3.tick()
        net.send(*net.filter(net.take_msgs(r2)))
        net.send(*net.filter(net.take_msgs(r3)))
        if r2.is_leader() or r3.is_leader():
            break
    new_leader = r2 if r2.is_leader() else r3
    assert new_leader.is_leader()
    net.send(
        Message(
            type=MT.PROPOSE, from_=new_leader.node_id, to=new_leader.node_id,
            entries=[Entry(cmd=b"v2")],
        )
    )
    assert new_leader.log.committed > committed_v1
    # meanwhile node 1 still believes it leads, and ticks have pushed it
    # far past its real lease expiry — only the jump keeps it "valid"
    for _ in range(r1.lease.duration + 1):
        r1.tick()
        net.send(*net.filter(net.take_msgs(r1)))
    assert r1.is_leader()  # first check-quorum window not yet consumed
    read(r1, 80)
    assert r1.ready_to_read, "jumped lease must (wrongly) serve"
    stale_index = r1.ready_to_read[0].index
    assert stale_index == committed_v1 < new_leader.log.committed
    # build the equivalent client history: put v1 ok, put v2 ok, then a
    # get that observed v1 — the checker must flag it
    rec = HistoryRecorder()
    rec.invoke(1, "put", "k", "v1")(True)
    rec.invoke(1, "put", "k", "v2")(True)
    rec.invoke(2, "get", "k", None)("v1")
    ok, bad = check_linearizable(rec.history())
    assert not ok and bad == ["k"]
    # the correct-clock refusal (read times out / retries on the new
    # leader) yields the linearizable history
    rec2 = HistoryRecorder()
    rec2.invoke(1, "put", "k", "v1")(True)
    rec2.invoke(1, "put", "k", "v2")(True)
    rec2.invoke(2, "get", "k", None)("v2")
    ok2, _ = check_linearizable(rec2.history())
    assert ok2
    # and indeed: without the jump the same lease refuses
    r1.lease.skew = 0
    r1.clear_ready_to_read()
    read(r1, 81)
    assert not r1.ready_to_read


# ======================================================================
# LeaderLease / LeaseTable units
# ======================================================================


def test_lease_ack_attribution_is_conservative():
    lease = LeaderLease(10)  # epsilon 2, duration 8
    lease.record_send(5, [2, 3])
    lease.record_send(6, [2, 3])
    # the ack attributes to the OLDEST recorded send
    lease.record_ack(2, 7)
    assert lease.bases[2] == 5
    lease.record_ack(2, 8)
    assert lease.bases[2] == 6
    # a full FIFO refuses NEW sends — but COUNTS them, because the
    # refused heartbeats are on the wire and will elicit acks
    for t in range(100):
        lease.record_send(t + 10, [2])
    dq = lease._pending[2]
    cap = LeaderLease.PENDING_CAP
    assert len(dq) == cap and dq[0] == [10, 1]
    assert lease._unrecorded[2] == 100 - cap
    # (review-caught hole) acks for refused sends must NOT pop sends
    # recorded after them: drain the cap'd entries, then the refusal
    # count absorbs the rest attributing NOTHING — even a send recorded
    # mid-drain waits behind the outstanding refusals
    for i in range(cap):
        lease.record_ack(2, 200)
    assert lease.bases[2] == 10 + cap - 1
    lease.record_send(300, [2])  # still suspended: refusals outstanding
    assert not lease._pending[2]
    for _ in range(100 - cap + 1):
        lease.record_ack(2, 201)
    assert lease.bases[2] == 10 + cap - 1  # unchanged — nothing newer
    assert lease._unrecorded[2] == 0
    # balance restored: recording and exact pairing resume
    lease.record_send(400, [2])
    lease.record_ack(2, 401)
    assert lease.bases[2] == 400


def test_lease_wall_guard_expires_starved_tick_clock():
    """ISSUE 17 churn-soak caught: the lease clock is the event loop's
    tick counter, so a starved/descheduled leader's tick-valid lease can
    outlive the majority's WALL-time election and serve a stale read.
    With ``tick_interval_s`` set, validity additionally requires the
    quorum-th newest ack to be wall-fresh — starvation expires the
    lease, never extends it."""
    wall = [100.0]
    lease = LeaderLease(10, tick_interval_s=0.05)  # duration 8 ticks
    lease.wall_clock = lambda: wall[0]
    voters, quorum, self_id = [1, 2, 3], 2, 1
    lease.record_send(5, [2, 3])
    lease.record_ack(2, 6)
    assert lease.valid(6, quorum, voters, self_id)
    # tick clock FROZEN at 6 (starved loop) while wall time runs past
    # duration * tick_interval_s = 0.4s: the guard must expire it even
    # though the tick arithmetic still says valid
    wall[0] += 0.39
    assert lease.valid(6, quorum, voters, self_id)
    wall[0] += 0.02
    assert not lease.valid(6, quorum, voters, self_id)
    # a fresh quorum ack re-arms it (tick basis AND wall basis move)
    lease.record_send(6, [2, 3])
    lease.record_ack(2, 7)
    assert lease.valid(7, quorum, voters, self_id)
    # without the knob the same freeze stays (unsafely) valid — the
    # default-off contract tick-driven tests rely on
    bare = LeaderLease(10)
    bare.record_send(5, [2, 3])
    bare.record_ack(2, 6)
    assert bare.valid(6, quorum, voters, self_id)


def test_lease_survives_sustained_hint_broadcast_load():
    """Review-caught liveness hole: every ReadIndex fallback broadcasts
    a hint heartbeat (= one record_send), so per-SEND FIFO capacity
    overflowed under sustained read load, pinned the refusal counter and
    froze the bases — the lease could never (re-)arm under exactly its
    target workload.  Tick-granular folding bounds the window by
    in-flight TICKS (the RTT), so heavy same-tick broadcast load must
    keep exact pairing and a current basis."""
    import collections as c

    lease = LeaderLease(10)
    rtt = 5
    in_flight = c.deque()
    last = 0
    for tick in range(200):
        for _ in range(8):  # 8 hint broadcasts per tick, RTT 5 ticks
            lease.record_send(tick, [2])
            in_flight.append(tick)
        while in_flight and in_flight[0] <= tick - rtt:
            in_flight.popleft()
            lease.record_ack(2, tick)
        last = tick
    assert not lease._unrecorded.get(2)  # never suspended
    assert len(lease._pending[2]) <= rtt + 1  # window = RTT ticks
    assert lease.bases[2] >= last - rtt - 1  # basis stays current
    assert lease.remaining(last, 2, [1, 2], 1) > 0


def test_membership_reset_keeps_fifo_aligned_with_inflight_acks():
    """Review-caught: a same-term membership change must NOT clear the
    send FIFO — acks still in flight pass raft's term filter, and with a
    cleared FIFO they would pop post-change sends and inflate the basis
    (persistently).  The partial reset drops only the bases; the stale
    ack then consumes the pre-change send it actually answers."""
    lease = LeaderLease(10)
    lease.record_send(3, [2])  # in flight when the membership changes
    lease.membership_changed()
    assert not lease.bases
    lease.record_send(7, [2])  # post-change send
    # the STALE ack (answers tick 3) arrives first — must attribute the
    # pre-change send, not the tick-7 one
    lease.record_ack(2, 8)
    assert lease.bases[2] == 3
    lease.record_ack(2, 9)
    assert lease.bases[2] == 7  # pairing stayed exact
    # a full (term-change) reset still clears everything: old-term acks
    # never reach record_ack (term-filtered), so alignment holds
    lease.reset()
    assert not lease._pending and not lease.bases


def test_lease_quorum_reduction_matches_kth_largest():
    lease = LeaderLease(10)
    # 5 voters, quorum 3: self counts at now; bases {2: 4, 3: 2}, 4/5 none
    lease.record_send(2, [3])
    lease.record_send(4, [2])
    lease.record_ack(3, 5)
    lease.record_ack(2, 6)
    voters = [1, 2, 3, 4, 5]
    # sorted bases: [-1, -1, 2, 4, now] → 3rd newest = 2
    assert lease.remaining(6, 3, voters, 1) == 2 + 8 - 6
    assert lease.remaining(10, 3, voters, 1) == 0
    # quorum 2: 2nd newest = 4
    assert lease.remaining(6, 2, voters, 1) == 4 + 8 - 6


def test_lease_table_round_tally():
    lt = LeaseTable()
    lt.configure(7, quorum=2, duration=8, self_id=1, voters=[1, 2, 3])
    assert lt.tracks(7) and not lt.tracks(8)
    assert not lt.valid(7, 0)
    lt.note_round({7: {2}}, 10)  # one follower + self = quorum
    assert lt.valid(7, 11) and not lt.valid(7, 18)
    assert lt.held_count(11) == 1
    lt.drop(7)
    assert not lt.valid(7, 11)
    # below-quorum tallies never extend
    lt.configure(9, quorum=3, duration=8, self_id=1, voters=[1, 2, 3, 4, 5])
    lt.note_round({9: {2}}, 10)
    assert not lt.valid(9, 11)
    # (review-caught) observer acks are filtered — hbresp ops are staged
    # for EVERY responder, but only voting members extend the deadline
    lt.configure(11, quorum=2, duration=8, self_id=1, voters=[1, 2, 3])
    lt.note_round({11: {8, 9}}, 10)  # observers only
    assert not lt.valid(11, 11)
    lt.note_round({11: {8, 2}}, 12)  # one voter + self = quorum
    assert lt.valid(11, 13)


# ======================================================================
# live stack: cross-domain lease reads, metrics, tpu lease table
# ======================================================================


class KVSM:
    def __init__(self, cluster_id, node_id):
        self.kv = {}

    def update(self, cmd):
        k, _, v = bytes(cmd).partition(b"=")
        self.kv[k.decode()] = v.decode()
        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        import json

        b = json.dumps(self.kv).encode()
        w.write(len(b).to_bytes(8, "little") + b)

    def recover_from_snapshot(self, r, files, done):
        import json

        n = int.from_bytes(r.read(8), "little")
        self.kv = json.loads(r.read(n).decode())

    def close(self):
        pass


CID = 770


def _mk_hosts(n=3, rtt_ms=5, engine="scalar", metrics=False, prefix="ls"):
    router = ChanRouter()
    nhs = []
    for i in range(1, n + 1):
        nhs.append(
            NodeHost(
                NodeHostConfig(
                    node_host_dir=":memory:",
                    rtt_millisecond=rtt_ms,
                    raft_address=f"{prefix}{i}:1",
                    raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                        s, rh, ch, router=router
                    ),
                    enable_metrics=metrics,
                    expert=ExpertConfig(
                        quorum_engine=engine,
                        engine_block_groups=64,
                        engine_warm_fused=False,
                    ),
                )
            )
        )
    return nhs, router


def _start(nhs, prefix="ls", cid=CID, election_rtt=10, lease=True,
           sm=KVSM):
    addrs = {i: f"{prefix}{i}:1" for i in range(1, len(nhs) + 1)}
    for i, nh in enumerate(nhs, start=1):
        nh.start_cluster(
            addrs, False, sm,
            Config(
                cluster_id=cid, node_id=i, election_rtt=election_rtt,
                heartbeat_rtt=1, check_quorum=True, read_lease=lease,
            ),
        )
    # host 1 must lead: the first campaign can race the bootstrap
    # config-change apply (campaign_skipped) or lose to a randomized
    # timeout elsewhere — retry, transferring back when another host won
    def _drive_leader1():
        n1 = nhs[0].get_node(cid)
        if n1.is_leader():
            return True
        lid, ok = n1.get_leader_id()
        if ok and lid != 1 and 1 <= lid <= len(nhs):
            try:
                nhs[lid - 1].request_leader_transfer(cid, 1)
            except Exception:
                pass
        else:
            n1.request_campaign()
        return False

    wait_until(
        _drive_leader1, timeout=20.0, interval=0.2, what="leader on host 1"
    )


def _stop(nhs):
    for nh in nhs:
        try:
            nh.stop()
        except Exception:
            pass


def _propose_retry(nh, s, data, timeout=30.0, attempts=3):
    """Noop-session propose with a load-scaled timeout and retry (the
    test_tpuquorum helper, ISSUE 13 deflake): under full-suite load one
    live-stack window can starve past a single timeout — the documented
    r07/r10/r12 rotating leadership-timing flake — while the cluster is
    perfectly healthy.  A noop-session duplicate is harmless here."""
    from dragonboat_tpu.requests import TimeoutError_
    from tests.loadwait import scaled

    for a in range(attempts):
        try:
            return nh.sync_propose(s, data, timeout=scaled(timeout))
        except TimeoutError_:
            if a == attempts - 1:
                raise


def _read_retry(nh, cid, query, timeout=10.0, attempts=3):
    """Load-scaled, retried sync_read (idempotent — safe to repeat)."""
    from dragonboat_tpu.requests import TimeoutError_
    from tests.loadwait import scaled

    for a in range(attempts):
        try:
            return nh.sync_read(cid, query, timeout=scaled(timeout))
        except TimeoutError_:
            if a == attempts - 1:
                raise


def test_live_lease_reads_cross_domain_and_metrics():
    """3 hosts, follower quorum one injected far link away: lease reads
    complete without paying the domain RTT; the dragonboat_lease_*
    families round-trip HELP+TYPE through the exposition."""
    nhs, _router = _mk_hosts(metrics=True)
    try:
        from dragonboat_tpu.monkey import set_latency

        set_latency(
            nhs, crossdomain(["ls1:1"], ["ls2:1", "ls3:1"], 0.015)
        )
        _start(nhs)
        nh = nhs[0]
        _propose_retry(nh, nh.get_noop_session(CID), b"a=1")
        # let a heartbeat/ack round trip arm the lease
        wait_until(
            lambda: (nh.lease_status(CID) or {}).get("held"),
            timeout=10.0, what="lease armed",
        )
        v = _read_retry(nh, CID, "a")
        assert v == "1"
        st = nh.lease_status(CID)
        assert st["reads_local"] >= 1
        assert st["grants"] >= 1
        # lease-served reads beat the 30ms domain RTT by construction:
        # time a burst and require it to complete far under ONE far RTT
        # per read.  The margin is load-scaled (scheduler pressure
        # stretches even a zero-round local read) but HARD-CAPPED below
        # the far round trip — a read that actually paid the link can
        # never pass (ISSUE 13 deflake of the r07/r10/r12 profile).
        from tests.loadwait import scaled as _scaled

        t0 = time.perf_counter()
        n = 10
        for _ in range(n):
            assert _read_retry(nh, CID, "a") == "1"
        per_read = (time.perf_counter() - t0) / n
        bound = min(_scaled(0.015), 0.028)
        assert per_read < bound, f"lease read paid the far link: {per_read}"
        # exposition: every lease family carries HELP + TYPE
        import io

        buf = io.StringIO()
        nh.write_health_metrics(buf)
        text = buf.getvalue()
        assert "# HELP dragonboat_lease_reads_local_total" in text
        assert "# TYPE dragonboat_lease_reads_local_total counter" in text
        assert "# TYPE dragonboat_lease_remaining_validity_ticks histogram" \
            in text
    finally:
        _stop(nhs)


def test_live_transfer_soak_linearizable_and_stale_lease_caught():
    """HistoryRecorder-checked lease reads under leadership transfer:
    (a) the correct protocol — transfer cedes the lease — yields a
    linearizable history; (b) the injected fault (cede suppressed, the
    old leader's inbound delayed so it serves during the handoff window)
    yields a history the checker FLAGS.  The checker catches the stale
    read; the pass in (a) is not luck."""
    # ---- (a) the correct protocol under transfer churn ----
    nhs, _router = _mk_hosts(rtt_ms=5)
    try:
        _start(nhs, election_rtt=10)
        rec = HistoryRecorder()
        stop = threading.Event()
        seq = [0]

        def current_leader():
            for nh in nhs:
                lid, ok = nh.get_leader_id(CID)
                if ok and 1 <= lid <= 3:
                    return nhs[lid - 1]
            return nhs[0]

        def writer():
            while not stop.is_set():
                seq[0] += 1
                v = str(seq[0])
                done = rec.invoke(1, "put", "k", v)
                try:
                    nh = current_leader()
                    nh.sync_propose(
                        nh.get_noop_session(CID), f"k={v}".encode(),
                        timeout=5.0,
                    )
                    done(True)
                except Exception:
                    done(unknown=True)

        def reader():
            while not stop.is_set():
                done = rec.invoke(2, "get", "k", None)
                try:
                    nh = current_leader()
                    done(nh.sync_read(CID, "k", timeout=5.0))
                except Exception:
                    done(unknown=True)
                time.sleep(0.005)

        ts = [threading.Thread(target=writer), threading.Thread(target=reader)]
        for t in ts:
            t.start()
        # transfer leadership around the ring under load
        for i in range(4):
            time.sleep(0.6)
            try:
                leader = current_leader()
                lid, _ = leader.get_leader_id(CID)
                target = (lid % 3) + 1
                leader.request_leader_transfer(CID, target)
            except Exception:
                pass
        time.sleep(0.6)
        stop.set()
        for t in ts:
            t.join(timeout=30)
        ok, bad = check_linearizable(rec.history())
        assert ok, f"non-linearizable keys under transfer churn: {bad}"
        # the lease actually served load (the soak exercised the short
        # path, not just the fallback)
        local = sum(
            (nh.lease_status(CID) or {}).get("reads_local", 0) for nh in nhs
        )
        assert local > 0
    finally:
        _stop(nhs)

    # ---- (b) the injected fault: suppressed cede + delayed handoff ----
    nhs, _router = _mk_hosts(rtt_ms=10, prefix="lf")
    try:
        _start(nhs, prefix="lf", election_rtt=60)
        nh1 = nhs[0]
        _propose_retry(nh1, nh1.get_noop_session(CID), b"k=v1")
        wait_until(
            lambda: (nh1.lease_status(CID) or {}).get("held"),
            timeout=10.0, what="lease armed",
        )
        rec = HistoryRecorder()
        rec.invoke(1, "put", "k", "v1")(True)
        # delay everything INBOUND to host 1: the handoff window in which
        # a non-ceding leader would serve stale reads becomes real
        inj = LatencyInjector()
        inj.set_pair("lf2:1", "lf1:1", 0.4)
        inj.set_pair("lf3:1", "lf1:1", 0.4)
        from dragonboat_tpu.monkey import set_latency

        set_latency(nhs, inj)
        node1 = nh1.get_node(CID)
        lease = node1.peer.raft.lease

        # a transfer can fizzle when the target's TIMEOUT_NOW campaign
        # races its apply watermark (has_config_change_to_apply guard) —
        # drive it until it lands.  Each attempt: request (the step
        # worker applies it and cedes — the protocol's correct
        # behavior), then inject the FAULT by un-ceding (as if the
        # transfer path forgot); with the correct cede this window
        # falls back (case (a)).
        def _drive_transfer():
            if nhs[1].get_node(CID).is_leader():
                return True
            if not node1.is_leader():
                return False
            try:
                nh1.request_leader_transfer(CID, 2)
            except Exception:
                pass
            # wait for the step worker to apply the transfer (which
            # cedes — the protocol's correct behavior), then promptly
            # inject the fault so the handoff window runs un-ceded
            t0 = time.time()
            while time.time() - t0 < 1.0 and not lease.ceded:
                time.sleep(0.01)
            if lease.ceded:
                with node1.raft_mu:
                    lease.ceded = False
            return nhs[1].get_node(CID).is_leader()

        wait_until(
            _drive_transfer, timeout=30.0, interval=0.1,
            what="transfer target leading",
        )
        # the target now leads and commits v2 with host 3 (near link)
        # while host 1 has not yet heard of the new term
        done_v2 = rec.invoke(1, "put", "k", "v2")
        _propose_retry(nhs[1], nhs[1].get_noop_session(CID), b"k=v2",
                       timeout=10.0)
        done_v2(True)
        # stale read on the old leader inside the delayed-handoff window
        assert node1.is_leader()
        done_get = rec.invoke(2, "get", "k", None)
        rs = nh1.read_index(CID, 5.0)
        r = rs.wait(5.0)
        assert r.completed, "un-ceded lease must (wrongly) serve"
        done_get(node1.sm.lookup("k"))
        ok, bad = check_linearizable(rec.history())
        assert not ok and bad == ["k"], (
            "the checker must catch the stale lease read"
        )
    finally:
        _stop(nhs)


def test_live_tpu_engine_lease_and_coordinator_table():
    """Lease reads with the batched device engine: the scalar lease still
    serves (the short path never stages device reads), and the
    coordinator's advisory LeaseTable tracks the group's validity from
    the heartbeat-ack ops it drains."""
    nhs, _router = _mk_hosts(engine="tpu", prefix="lt")
    try:
        _start(nhs, prefix="lt")
        nh = nhs[0]
        # retried + load-scaled: the first live-tpu propose shares the
        # core with the engine's first-dispatch compiles, and one
        # starved window was the documented r12 rotating flake
        _propose_retry(nh, nh.get_noop_session(CID), b"a=2", timeout=60.0)
        # generous, load-scaled waits: a live 3-host tpu-engine cluster
        # on a contended box arms slowly (first-dispatch compiles share
        # the core with raft) — the gate must not flake on weather.
        # 60s base: the 30s scaled budget still expired once per loaded
        # sweep (the r12 rotating profile's most frequent site) while
        # the same wait passes standalone in seconds — arming is
        # contention-bound, not broken, so only the margin widens.
        # r15 deflake (the ONE remaining rotating site of the r14
        # sweeps, observed at load >4): the no-arm mode was PROBED, not
        # guessed — on a starved box leadership CHURNS (one probe
        # caught host 1 twenty terms past its driven win, leader on
        # host 2), and a wait that only polls `held` then watches a
        # FOLLOWER forever: a follower's lease can never arm, so no
        # margin is wide enough.  The wait therefore re-drives host-1
        # leadership while it waits (the `_start` transfer/campaign
        # treatment applied continuously) under ONE hard-capped total
        # budget — load-scaled like every loadwait site but never past
        # 300s, so a pathological box surfaces one attributable
        # failure instead of eating the sweep's global timeout (naive
        # stacked retries of scaled 60s waits measured exactly that)
        from tests.loadwait import scaled as _lease_scaled

        def _lead_and_armed():
            n1 = nh.get_node(CID)
            if not n1.is_leader():
                lid, ok = n1.get_leader_id()
                if ok and lid != 1 and 1 <= lid <= len(nhs):
                    try:
                        nhs[lid - 1].request_leader_transfer(CID, 1)
                    except Exception:
                        pass
                else:
                    n1.request_campaign()
                return False
            return bool((nh.lease_status(CID) or {}).get("held"))

        arm_deadline = time.time() + min(300.0, _lease_scaled(90.0))
        while not _lead_and_armed():
            if time.time() >= arm_deadline:
                raise AssertionError(
                    f"lease armed not reached (leader "
                    f"{nh.get_leader_id(CID)!r}, status "
                    f"{nh.lease_status(CID)!r})"
                )
            time.sleep(0.2)
        before = (nh.lease_status(CID) or {}).get("reads_local", 0)
        assert _read_retry(nh, CID, "a", timeout=30.0) == "2"
        st = nh.lease_status(CID)
        assert st["reads_local"] > before
        qc = nh.quorum_coordinator
        assert qc is not None and qc.lease_table is not None
        assert qc.lease_table.tracks(CID)
        wait_until(
            lambda: qc.lease_table.valid(CID, qc._tick_seen),
            timeout=30.0, what="coordinator lease table armed",
        )
    finally:
        _stop(nhs)
