"""Differential: the live coordinator's adaptive K-batched path (ISSUE 7).

The same deterministic schedule — writes, follower acks, tick bursts,
ReadIndex ctxs with heartbeat echoes, a mid-schedule membership recycle
and a leader change landing inside a fused block — is driven through

  (1) a WARMED coordinator (tick backlogs replay as one fused
      multi-round dispatch; ``fused_dispatches`` asserts they did), and
  (2) an UNWARMED coordinator (the single-round per-step replay path),

and both must produce identical commitIndex sequences and read-release
outputs, which must equal the scalar oracle (kth-largest of the match
vector under the term guard — computed independently in numpy).
"""
import threading

import pytest

pytest.importorskip("jax")


class FakeNode:
    """Node shim: commit/read effects re-applied under raftMu with the
    scalar guards intact (the test_device_ticks pattern)."""

    def __init__(self, cid, raft):
        self.cluster_id = cid
        self.raft_mu = threading.RLock()

        class _P:
            pass

        self.peer = _P()
        self.peer.raft = raft
        self.commits = []
        self.read_releases = []

    def offload_commit(self, q):
        r = self.peer.raft
        with self.raft_mu:
            if r.is_leader() and r.log.try_commit(q, r.term):
                self.commits.append(int(q))

    def offload_read_confirm(self, low, high, term):
        r = self.peer.raft
        with self.raft_mu:
            if r.is_leader() and r.term == term:
                self.read_releases.append((int(low), int(high)))

    def offload_read_echo(self, from_, low, high):
        pass

    def offload_election(self, won, term):
        pass

    def offload_tick_elect(self):
        pass

    def offload_tick_heartbeat(self):
        pass

    def offload_tick_demote(self):
        pass


def _new_leader_raft(cid):
    from dragonboat_tpu.raft import InMemLogDB
    from tests.raft_harness import new_test_raft

    r = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
    r.cluster_id = cid
    r.become_candidate()
    r.become_leader()
    return r


def _register(coord, cid):
    n = FakeNode(cid, _new_leader_raft(cid))
    n.peer.raft.offload = coord
    coord._nodes[cid] = n
    with coord._mu:
        coord._sync_row_locked(n)
    return n


def _run_schedule(warm: bool) -> dict:
    """Drive the full scenario through one coordinator; returns the
    observable outcome (commit sequences, read releases, final
    committed/last per group, fused dispatch count)."""
    from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator
    from dragonboat_tpu.wire import Entry

    G = 6
    coord = TpuQuorumCoordinator(
        capacity=32, n_peers=4, drive_ticks=True, interval_s=60.0,
    )
    if warm:
        coord.eng.warmup_fused(background=False)
        assert coord.eng.fused_ready
    nodes = {}
    try:
        for g in range(G):
            nodes[1 + g] = _register(coord, 1 + g)
        coord.flush()

        def append(pairs):
            for cid, k in pairs:
                n = nodes[cid]
                with n.raft_mu:
                    n.peer.raft.append_entries(
                        [Entry(cmd=b"w") for _ in range(k)]
                    )

        def burst(acks=(), reads=(), echoes=(), ticks=3):
            """One live round's ingest: staged acks/reads/echoes, then a
            tick backlog and one flush."""
            for cid, nid, idx in acks:
                coord.ack(cid, nid, idx)
            for cid, low, high in reads:
                r = nodes[cid].peer.raft
                coord.read_stage(
                    cid, r.log.committed, low, high, r.term
                )
            for cid, nid, low, high in echoes:
                coord.read_ack_hint(cid, nid, low, high)
            for _ in range(ticks):
                coord.request_tick()
            coord.flush()

        def last(cid):
            return nodes[cid].peer.raft.log.last_index()

        # burst 1: every group appends 2, follower 2 acks all, follower 3
        # lags by 1 — quorum (self + f2) commits to last
        append([(c, 2) for c in nodes])
        burst(
            acks=[(c, 2, last(c)) for c in nodes]
            + [(c, 3, last(c) - 1) for c in nodes],
        )
        # burst 2: reads staged at the committed watermark; follower 2's
        # echo completes the quorum in the same fused block
        burst(
            reads=[(c, 100 + c, c) for c in nodes],
            echoes=[(c, 2, 100 + c, c) for c in nodes],
        )
        # burst 3: a leader change lands INSIDE the block for group 2 —
        # acks staged before the transition must die with it (epoch
        # purge; identical on both paths), and the demoted group must
        # not commit past its pre-transition watermark
        victim = nodes[2]
        append([(2, 1)])
        burst(acks=[(2, 2, last(2))])
        with victim.raft_mu:
            victim.peer.raft.become_follower(
                victim.peer.raft.term + 1, 3
            )
        coord.set_follower(2, victim.peer.raft.term)
        # stale acks for the now-follower row, staged same-drain as the
        # transition: purged on both paths
        coord.ack(2, 3, last(2))
        append([(c, 1) for c in nodes if c != 2])
        burst(acks=[(c, 2, last(c)) for c in nodes if c != 2])
        # burst 4: mid-schedule membership recycle — group 3 retires and
        # a fresh group 103 takes its row; acks staged for the dead
        # tenant in the same drain must not leak to the new one
        coord.ack(3, 3, last(3))
        coord.unregister(3)
        dead = nodes.pop(3)
        nodes[103] = _register(coord, 103)
        append([(103, 2)])
        burst(acks=[(103, 2, last(103))])
        # burst 5: the demoted group re-elects and resyncs (the rare
        # path), then commits fresh entries
        with victim.raft_mu:
            victim.peer.raft.become_candidate()
            victim.peer.raft.become_leader()
        nodes[2] = victim
        coord.membership_changed(2)
        append([(2, 2)])
        burst(acks=[(2, 2, last(2)), (2, 3, last(2))])
        # drain any trailing flags
        coord.flush()

        return {
            "commits": {c: tuple(n.commits) for c, n in nodes.items()},
            "reads": {
                c: tuple(n.read_releases) for c, n in nodes.items()
            },
            "dead_commits": tuple(dead.commits),
            "committed": {
                c: n.peer.raft.log.committed for c, n in nodes.items()
            },
            "last": {
                c: n.peer.raft.log.last_index() for c, n in nodes.items()
            },
            "fused": coord.fused_dispatches,
        }
    finally:
        coord.stop()


def test_live_fused_matches_single_round_and_oracle():
    single = _run_schedule(warm=False)
    fused = _run_schedule(warm=True)

    # the warmed run actually exercised the fused path; the unwarmed one
    # never did
    assert single["fused"] == 0
    assert fused["fused"] >= 4, fused["fused"]

    # identical observable outputs, round for round
    for key in ("commits", "reads", "dead_commits", "committed", "last"):
        assert single[key] == fused[key], (key, single[key], fused[key])

    # scalar oracle: every surviving leader group fully committed (self +
    # follower-2 acks reach quorum at every burst) ...
    for cid, committed in fused["committed"].items():
        assert committed == fused["last"][cid], (
            cid, committed, fused["last"][cid],
        )
    # ... the leader-changed group released no reads after its demotion
    # and the recycled tenant saw none of the dead tenant's acks
    assert fused["reads"][2] == ((102, 2),)
    # one commit advance for the fresh tenant: its promotion noop + the 2
    # appended entries land together at the first quorum ack (q=3); the
    # dead tenant's same-drain ack never reached it
    assert fused["commits"][103] == (3,)
    # every read staged on a stable leader was released exactly once, at
    # its staging identity
    for cid in fused["reads"]:
        if cid in (2, 103):
            continue
        assert fused["reads"][cid] == ((100 + cid, cid),), (
            cid, fused["reads"][cid],
        )
