"""LogDB layer tests (reference test model: ``internal/logdb/*_test.go``)."""
import os

import pytest

from dragonboat_tpu.logdb import InMemKV, LogReader, WalKV, open_logdb
from dragonboat_tpu.logdb.entries import BatchedEntries, PlainEntries
from dragonboat_tpu.logdb.rdb import RDB
from dragonboat_tpu.raft.log import CompactedError, UnavailableError
from dragonboat_tpu.wire import Bootstrap, Entry, Membership, Snapshot, State, Update


def make_entries(lo, hi, term=1, size=8):
    return [Entry(term=term, index=i, cmd=b"x" * size) for i in range(lo, hi)]


# ---------- KV ----------


def test_inmem_kv_ordered_iterate():
    kv = InMemKV()
    kv.put(b"b", b"2")
    kv.put(b"a", b"1")
    kv.put(b"c", b"3")
    assert [k for k, _ in kv.iterate(b"a", b"c", True)] == [b"a", b"b", b"c"]
    assert [k for k, _ in kv.iterate(b"a", b"c", False)] == [b"a", b"b"]


def test_inmem_kv_write_batch_atomic_delete_range():
    kv = InMemKV()
    for i in range(10):
        kv.put(bytes([i]), b"v")
    wb = kv.get_write_batch()
    wb.delete_range(bytes([2]), bytes([5]))
    wb.put(bytes([11]), b"w")
    kv.commit_write_batch(wb)
    assert kv.get(bytes([2])) is None
    assert kv.get(bytes([4])) is None
    assert kv.get(bytes([5])) == b"v"
    assert kv.get(bytes([11])) == b"w"


def test_walkv_survives_reopen(tmp_path):
    d = str(tmp_path / "kv")
    kv = WalKV(d, fsync=False)
    kv.put(b"k1", b"v1")
    wb = kv.get_write_batch()
    wb.put(b"k2", b"v2")
    wb.delete(b"k1")
    kv.commit_write_batch(wb)
    kv.close()
    kv2 = WalKV(d, fsync=False)
    assert kv2.get(b"k1") is None
    assert kv2.get(b"k2") == b"v2"
    kv2.close()


def test_walkv_drops_torn_tail(tmp_path):
    d = str(tmp_path / "kv")
    kv = WalKV(d, fsync=False)
    kv.put(b"k1", b"v1")
    kv.put(b"k2", b"v2")
    kv.close()
    path = os.path.join(d, "kv.wal")
    sz = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(sz - 3)  # corrupt the last record
    kv2 = WalKV(d, fsync=False)
    assert kv2.get(b"k1") == b"v1"
    assert kv2.get(b"k2") is None
    kv2.close()


def test_walkv_full_compaction_preserves_data(tmp_path):
    d = str(tmp_path / "kv")
    kv = WalKV(d, fsync=False)
    for i in range(100):
        kv.put(f"k{i:03d}".encode(), b"v" * 100)
    for i in range(50):
        kv.delete(f"k{i:03d}".encode())
    before = os.path.getsize(os.path.join(d, "kv.wal"))
    kv.full_compaction()
    after = os.path.getsize(os.path.join(d, "kv.wal"))
    assert after < before
    kv.close()
    kv2 = WalKV(d, fsync=False)
    assert kv2.get(b"k049") is None
    assert kv2.get(b"k050") == b"v" * 100
    kv2.close()


# ---------- entry managers ----------


@pytest.mark.parametrize("mgr_cls", [PlainEntries, BatchedEntries])
def test_entry_manager_roundtrip(mgr_cls):
    kv = InMemKV()
    mgr = mgr_cls(kv)
    wb = kv.get_write_batch()
    mi = mgr.record_entries(wb, 1, 2, make_entries(1, 101))
    kv.commit_write_batch(wb)
    assert mi == 100
    ents, size = mgr.iterate_entries([], 0, 1, 2, 1, 101, 1 << 62)
    assert [e.index for e in ents] == list(range(1, 101))
    assert size > 0
    assert mgr.get_entry(1, 2, 50).index == 50
    assert mgr.get_entry(1, 2, 101) is None


@pytest.mark.parametrize("mgr_cls", [PlainEntries, BatchedEntries])
def test_entry_manager_conflict_overwrite(mgr_cls):
    kv = InMemKV()
    mgr = mgr_cls(kv)
    wb = kv.get_write_batch()
    mgr.record_entries(wb, 1, 2, make_entries(1, 101, term=1))
    kv.commit_write_batch(wb)
    # overwrite a suffix with higher-term entries
    wb = kv.get_write_batch()
    mgr.record_entries(wb, 1, 2, make_entries(50, 81, term=2))
    kv.commit_write_batch(wb)
    ents, _ = mgr.iterate_entries([], 0, 1, 2, 1, 81, 1 << 62)
    assert [e.index for e in ents] == list(range(1, 81))
    assert all(e.term == 1 for e in ents if e.index < 50)
    assert all(e.term == 2 for e in ents if e.index >= 50)
    # stale entries beyond the new tail: rdb bounds `high` by max_index, so
    # emulate the caller passing high = max_index + 1 = 81
    ents2, _ = mgr.iterate_entries([], 0, 1, 2, 75, 81, 1 << 62)
    assert [e.index for e in ents2] == list(range(75, 81))
    assert all(e.term == 2 for e in ents2)
    if mgr_cls is BatchedEntries:
        # the batch rewrite physically drops stale entries beyond the tail
        ents3, _ = mgr.iterate_entries([], 0, 1, 2, 75, 101, 1 << 62)
        assert [e.index for e in ents3 if e.index > 80] == []


@pytest.mark.parametrize("mgr_cls", [PlainEntries, BatchedEntries])
def test_entry_manager_max_size_stops_iteration(mgr_cls):
    kv = InMemKV()
    mgr = mgr_cls(kv)
    wb = kv.get_write_batch()
    mgr.record_entries(wb, 1, 2, make_entries(1, 11, size=100))
    kv.commit_write_batch(wb)
    ents, _ = mgr.iterate_entries([], 0, 1, 2, 1, 11, 300)
    assert 1 <= len(ents) < 10
    # always returns at least one entry even if over budget
    ents1, _ = mgr.iterate_entries([], 0, 1, 2, 1, 11, 1)
    assert len(ents1) == 1


# ---------- rdb ----------


def make_update(cluster_id=1, node_id=2, lo=1, hi=11, term=1, commit=0, ss=None):
    st = State(term=term, vote=0, commit=commit or hi - 1)
    return Update(
        cluster_id=cluster_id,
        node_id=node_id,
        state=st,
        entries_to_save=make_entries(lo, hi, term=term),
        snapshot=ss,
    )


def test_rdb_save_and_read_state():
    rdb = RDB(InMemKV())
    ud = make_update()
    wb = rdb.kv.get_write_batch()
    rdb.save_raft_state([ud], wb)
    rs = rdb.read_raft_state(1, 2, 0)
    assert rs.state.term == 1
    assert rs.state.commit == 10
    assert rs.first_index == 1
    assert rs.entry_count == 10
    assert rdb.read_max_index(1, 2) == 10


def test_rdb_state_cache_suppresses_redundant_writes():
    rdb = RDB(InMemKV())
    ud = make_update()
    wb = rdb.kv.get_write_batch()
    rdb.save_raft_state([ud], wb)
    # same state again: nothing new in the batch
    ud2 = Update(cluster_id=1, node_id=2, state=ud.state)
    wb2 = rdb.kv.get_write_batch()
    rdb.save_raft_state([ud2], wb2)
    assert len(wb2) == 0


def test_rdb_bootstrap_roundtrip_and_listing():
    rdb = RDB(InMemKV())
    bs = Bootstrap(addresses={1: "a1:1", 2: "a2:2"}, type=1)
    rdb.save_bootstrap(5, 1, bs)
    rdb.save_bootstrap(7, 3, bs)
    got = rdb.get_bootstrap(5, 1)
    assert got.addresses == {1: "a1:1", 2: "a2:2"}
    infos = rdb.list_node_info()
    assert {(i.cluster_id, i.node_id) for i in infos} == {(5, 1), (7, 3)}


def test_rdb_snapshot_listing_ascending():
    rdb = RDB(InMemKV())
    for idx in (30, 10, 20):
        rdb.save_snapshot(1, 2, Snapshot(index=idx, term=1, cluster_id=1))
    lst = rdb.list_snapshots(1, 2)
    assert [s.index for s in lst] == [10, 20, 30]
    lst = rdb.list_snapshots(1, 2, 20)
    assert [s.index for s in lst] == [10, 20]
    rdb.delete_snapshot(1, 2, 20)
    assert [s.index for s in rdb.list_snapshots(1, 2)] == [10, 30]


def test_rdb_remove_node_data():
    rdb = RDB(InMemKV())
    ud = make_update()
    wb = rdb.kv.get_write_batch()
    rdb.save_raft_state([ud], wb)
    rdb.save_snapshot(1, 2, Snapshot(index=5, term=1, cluster_id=1))
    rdb.save_bootstrap(1, 2, Bootstrap(addresses={2: "a:1"}))
    rdb.remove_node_data(1, 2)
    assert rdb.read_state(1, 2) is None
    assert rdb.list_snapshots(1, 2) == []
    assert rdb.get_bootstrap(1, 2) is None
    ents, _ = rdb.iterate_entries([], 0, 1, 2, 1, 11, 1 << 62)
    assert ents == []


def test_rdb_remove_node_data_spares_other_nodes():
    # regression: tag-major keys mean a naive cross-tag range delete would
    # wipe every other node in the shard
    rdb = RDB(InMemKV())
    for cid, nid in ((1, 2), (3, 7)):
        wb = rdb.kv.get_write_batch()
        rdb.save_raft_state([make_update(cluster_id=cid, node_id=nid)], wb)
        rdb.save_snapshot(cid, nid, Snapshot(index=5, term=1, cluster_id=cid))
        rdb.save_bootstrap(cid, nid, Bootstrap(addresses={nid: "a:1"}))
    rdb.remove_node_data(1, 2)
    assert rdb.read_state(1, 2) is None
    assert rdb.read_state(3, 7) is not None
    assert rdb.read_max_index(3, 7) == 10
    assert [s.index for s in rdb.list_snapshots(3, 7)] == [5]
    assert rdb.get_bootstrap(3, 7) is not None
    ents, _ = rdb.iterate_entries([], 0, 3, 7, 1, 11, 1 << 62)
    assert [e.index for e in ents] == list(range(1, 11))


def test_rdb_import_snapshot():
    rdb = RDB(InMemKV())
    wb = rdb.kv.get_write_batch()
    rdb.save_raft_state([make_update()], wb)
    rdb.save_snapshot(1, 2, Snapshot(index=20, term=1, cluster_id=1))
    ss = Snapshot(
        index=15,
        term=2,
        cluster_id=1,
        type=1,
        membership=Membership(addresses={2: "a:1"}, config_change_id=1),
    )
    rdb.import_snapshot(ss, 2)
    snaps = rdb.list_snapshots(1, 2)
    assert [s.index for s in snaps] == [15]
    st = rdb.read_state(1, 2)
    assert st.term == 2 and st.commit == 15
    assert rdb.read_max_index(1, 2) == 15


# ---------- sharded ----------


def test_sharded_db_routes_by_cluster():
    db = open_logdb(shards=4)
    uds = [make_update(cluster_id=c, node_id=1) for c in range(8)]
    db.save_raft_state(uds)
    for c in range(8):
        rs = db.read_raft_state(c, 1, 0)
        assert rs is not None and rs.entry_count == 10
    infos = db.list_node_info()
    assert infos == []  # no bootstrap records yet
    db.close()


def test_sharded_db_remove_entries_and_compaction():
    db = open_logdb(shards=2)
    db.save_raft_state([make_update(cluster_id=1, node_id=2, lo=1, hi=101)])
    db.remove_entries_to(1, 2, 50)
    done = db.compact_entries_to(1, 2, 50)
    assert done.wait(timeout=5)
    ents, _ = db.iterate_entries([], 0, 1, 2, 1, 101, 1 << 62)
    assert ents == [] or ents[0].index > 50
    ents, _ = db.iterate_entries([], 0, 1, 2, 51, 101, 1 << 62)
    assert [e.index for e in ents] == list(range(51, 101))
    db.close()


def test_sharded_db_durable_reopen(tmp_path):
    d = str(tmp_path / "logdb")
    db = open_logdb(d, shards=2, fsync=False)
    db.save_bootstrap_info(1, 2, Bootstrap(addresses={2: "a:1"}))
    db.save_raft_state([make_update(cluster_id=1, node_id=2)])
    db.save_snapshot(1, 2, Snapshot(index=5, term=1, cluster_id=1))
    db.close()
    db2 = open_logdb(d, shards=2, fsync=False)
    assert db2.get_bootstrap_info(1, 2).addresses == {2: "a:1"}
    rs = db2.read_raft_state(1, 2, 0)
    assert rs.entry_count == 10
    assert [s.index for s in db2.list_snapshots(1, 2)] == [5]
    db2.close()


# ---------- LogReader ----------


def make_reader_with_entries(lo=1, hi=11):
    db = open_logdb(shards=1)
    db.save_raft_state([make_update(cluster_id=1, node_id=2, lo=lo, hi=hi)])
    lr = LogReader(1, 2, db)
    lr.append(make_entries(lo, hi))
    return db, lr


def test_logreader_range_term_entries():
    db, lr = make_reader_with_entries()
    assert lr.get_range() == (1, 10)
    assert lr.term(5) == 1
    assert lr.term(0) == 0  # marker
    ents = lr.entries(3, 8, 1 << 62)
    assert [e.index for e in ents] == [3, 4, 5, 6, 7]
    with pytest.raises(UnavailableError):
        lr.term(11)
    db.close()


def test_logreader_compact_moves_marker():
    db, lr = make_reader_with_entries()
    lr.compact(5)
    assert lr.get_range() == (6, 10)
    assert lr.term(5) == 1  # marker term retained
    with pytest.raises(CompactedError):
        lr.entries(4, 8, 1 << 62)
    with pytest.raises(CompactedError):
        lr.compact(3)
    db.close()


def test_logreader_apply_snapshot_resets_window():
    db, lr = make_reader_with_entries()
    ss = Snapshot(index=20, term=3, cluster_id=1)
    lr.apply_snapshot(ss)
    assert lr.get_range() == (21, 20)  # empty window
    assert lr.term(20) == 3
    assert lr.snapshot().index == 20
    db.close()


def test_logreader_load_from_storage():
    db = open_logdb(shards=1)
    db.save_raft_state([make_update(cluster_id=1, node_id=2, lo=1, hi=21)])
    db.save_snapshot(
        1, 2, Snapshot(index=5, term=1, cluster_id=1)
    )
    lr = LogReader.load(1, 2, db)
    assert lr.snapshot().index == 5
    assert lr.get_range() == (6, 20)
    assert lr.state.commit == 20
    db.close()


def test_logreader_set_range_merging():
    db = open_logdb(shards=1)
    lr = LogReader(1, 2, db)
    lr.set_range(1, 10)
    assert lr.get_range() == (1, 10)
    lr.set_range(5, 10)  # overlap
    assert lr.get_range() == (1, 14)
    lr.set_range(15, 5)  # contiguous
    assert lr.get_range() == (1, 19)
    with pytest.raises(RuntimeError):
        lr.set_range(30, 5)  # gap
    db.close()
