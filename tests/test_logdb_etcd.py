"""etcd conformance port: LogReader window semantics.

The reference carries etcd's storage-surface tests against its log-reader
double (``/root/reference/internal/raft/logdb_etcd_test.go`` — itself the
port of etcd's ``log_test.go`` storage tables: "testing your tests is
important").  Here the same behavior tables drive the REAL
:class:`dragonboat_tpu.logdb.LogReader` over the real in-memory LogDB —
no double: marker/term errors (compacted vs unavailable), range movement
under append/compact, snapshot record ordering, and the six-way
conflicting-append table.
"""
from __future__ import annotations

import pytest

from dragonboat_tpu.logdb import LogReader, open_logdb
from dragonboat_tpu.raft.log import (
    CompactedError,
    SnapshotOutOfDateError,
    UnavailableError,
)
from dragonboat_tpu.wire import Entry, Membership, Snapshot, Update


def _ents(pairs):
    return [Entry(index=i, term=t, cmd=b"") for i, t in pairs]


def _reader(pairs=((3, 3), (4, 4), (5, 5))):
    """LogReader whose marker sits at the first (index, term) pair and
    whose stable window covers the rest — the exact setup every table in
    the reference file uses (markerIndex 3 / markerTerm 3, entries 4,5)."""
    db = open_logdb(shards=1)
    marker_i, marker_t = pairs[0]
    rest = _ents(pairs[1:])
    if rest:
        db.save_raft_state(
            [Update(cluster_id=1, node_id=2, entries_to_save=rest)]
        )
    lr = LogReader(1, 2, db)
    lr.set_compact_to(marker_i, marker_t)
    if rest:
        lr.append(rest)
    return db, lr


def _membership():
    return Membership(
        addresses={1: "a1", 2: "a2", 3: "a3"}, config_change_id=1
    )


def test_logdb_term():
    """``TestLogDBTerm``: below the marker is compacted, the marker and
    window indexes answer, above the window is unavailable."""
    cases = [
        (2, CompactedError, 0),
        (3, None, 3),
        (4, None, 4),
        (5, None, 5),
        (6, UnavailableError, 0),
    ]
    for i, werr, wterm in cases:
        db, lr = _reader()
        if werr is not None:
            with pytest.raises(werr):
                lr.term(i)
        else:
            assert lr.term(i) == wterm, i
        db.close()


def test_logdb_last_index():
    """``TestLogDBLastIndex``: the window's last index, then append."""
    db, lr = _reader()
    assert lr.get_range()[1] == 5
    more = _ents([(6, 5)])
    db.save_raft_state([Update(cluster_id=1, node_id=2, entries_to_save=more)])
    lr.append(more)
    assert lr.get_range()[1] == 6
    db.close()


def test_logdb_first_index():
    """``TestLogDBFirstIndex``: first = marker+1; compaction advances it."""
    db, lr = _reader()
    assert lr.get_range()[0] == 4
    lr.compact(4)
    assert lr.get_range()[0] == 5
    db.close()


def test_logdb_compact():
    """``TestLogDBCompact``: compacting below the marker is ErrCompacted
    with the window untouched; beyond it moves marker index, marker term,
    and window length.  Deviation from the etcd table: compact(marker)
    is a NO-OP SUCCESS here — the table drives the reference's TestLogDB
    double, but its real LogReader uses strict ``<``
    (``/root/reference/internal/logdb/logreader.go:276``), and that is
    the surface this class models."""
    cases = [
        (2, CompactedError, 3, 3, 3),
        (3, None, 3, 3, 3),  # at-marker: no-op success (logreader.go:276)
        (4, None, 4, 4, 2),
        (5, None, 5, 5, 1),
    ]
    for i, werr, windex, wterm, wlen in cases:
        db, lr = _reader()
        if werr is not None:
            with pytest.raises(werr):
                lr.compact(i)
        else:
            lr.compact(i)
        assert lr.marker == windex, i
        assert lr.marker_term == wterm, i
        first, last = lr.get_range()
        assert last - first + 2 == wlen, i  # window + marker slot
        db.close()


def test_logdb_create_snapshot():
    """``TestLogDBCreateSnapshot``: recording snapshots at window indexes
    keeps (index, term, membership)."""
    for i in (4, 5):
        db, lr = _reader()
        ss = Snapshot(
            index=i, term=lr.term(i), membership=_membership(), cluster_id=1
        )
        lr.create_snapshot(ss)
        got = lr.snapshot()
        assert (got.index, got.term) == (i, i)
        assert got.membership.addresses == _membership().addresses
        db.close()


def test_logdb_apply_snapshot():
    """``TestLogDBApplySnapshot``: installing a snapshot resets the
    window; an older one is ErrSnapshotOutOfDate."""
    db, lr = _reader(pairs=((0, 0),))
    lr.apply_snapshot(
        Snapshot(index=4, term=4, membership=_membership(), cluster_id=1)
    )
    assert lr.get_range() == (5, 4)  # empty window at marker 4
    assert lr.term(4) == 4
    with pytest.raises(SnapshotOutOfDateError):
        lr.apply_snapshot(
            Snapshot(index=3, term=3, membership=_membership(), cluster_id=1)
        )
    db.close()


def test_logdb_append():
    """``TestLogDBAppend``: the six-way overwrite/merge table — re-append
    (idempotent), conflicting-term overwrite, extension, truncation of
    incoming entries below the marker, tail truncation, direct append."""
    cases = [
        # (incoming, expected window pairs incl. marker slot)
        ([(3, 3), (4, 4), (5, 5)], [(3, 3), (4, 4), (5, 5)]),
        ([(3, 3), (4, 6), (5, 6)], [(3, 3), (4, 6), (5, 6)]),
        (
            [(3, 3), (4, 4), (5, 5), (6, 5)],
            [(3, 3), (4, 4), (5, 5), (6, 5)],
        ),
        ([(2, 3), (3, 3), (4, 5)], [(3, 3), (4, 5)]),
        ([(4, 5)], [(3, 3), (4, 5)]),
        ([(6, 5)], [(3, 3), (4, 4), (5, 5), (6, 5)]),
    ]
    for n, (incoming, expected) in enumerate(cases):
        db, lr = _reader()
        ents = _ents(incoming)
        db.save_raft_state(
            [Update(cluster_id=1, node_id=2, entries_to_save=ents)]
        )
        lr.append(ents)
        exp_marker_i, exp_marker_t = expected[0]
        assert lr.marker == exp_marker_i, n
        first, last = lr.get_range()
        assert (first, last) == (expected[1][0], expected[-1][0]), n
        for i, t in expected[1:]:
            assert lr.term(i) == t, (n, i)
        db.close()
