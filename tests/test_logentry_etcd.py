"""Port of the reference's etcd-derived entry-log conformance tests.

Reference: ``/root/reference/internal/raft/logentry_etcd_test.go`` — same
test names and case tables, against :mod:`dragonboat_tpu.raft.log`'s
``EntryLog`` (the reference's three-stage ``entryLog``).
"""
from __future__ import annotations

import pytest

from dragonboat_tpu.raft import InMemLogDB
from dragonboat_tpu.raft.log import CompactedError, EntryLog
from dragonboat_tpu.wire import Entry, Snapshot, UpdateCommit

NO_LIMIT = 1 << 62


def E(index, term=0):
    return Entry(index=index, term=term)


def sig(ents):
    return [(e.term, e.index) for e in ents]


def get_all_entries(l: EntryLog):
    try:
        return l.entries(l.first_index(), NO_LIMIT)
    except CompactedError:
        return get_all_entries(l)


def must_term(l: EntryLog, index: int) -> int:
    return l.term(index)


def test_find_conflict():
    previous = [E(1, 1), E(2, 2), E(3, 3)]
    cases = [
        ([], 0),
        ([E(1, 1), E(2, 2), E(3, 3)], 0),
        ([E(2, 2), E(3, 3)], 0),
        ([E(3, 3)], 0),
        ([E(1, 1), E(2, 2), E(3, 3), E(4, 4), E(5, 4)], 4),
        ([E(2, 2), E(3, 3), E(4, 4), E(5, 4)], 4),
        ([E(3, 3), E(4, 4), E(5, 4)], 4),
        ([E(4, 4), E(5, 4)], 4),
        ([E(1, 4), E(2, 4)], 1),
        ([E(2, 1), E(3, 4), E(4, 4)], 2),
        ([E(3, 1), E(4, 2), E(5, 4), E(6, 4)], 3),
    ]
    for i, (ents, wconflict) in enumerate(cases):
        l = EntryLog(InMemLogDB())
        l.append(list(previous))
        assert l.get_conflict_index(ents) == wconflict, f"#{i}"


def test_is_up_to_date():
    previous = [E(1, 1), E(2, 2), E(3, 3)]
    l = EntryLog(InMemLogDB())
    l.append(previous)
    last = l.last_index()
    cases = [
        (last - 1, 4, True),
        (last, 4, True),
        (last + 1, 4, True),
        (last - 1, 2, False),
        (last, 2, False),
        (last + 1, 2, False),
        (last - 1, 3, False),
        (last, 3, True),
        (last + 1, 3, True),
    ]
    for i, (idx, term, w) in enumerate(cases):
        assert l.up_to_date(idx, term) == w, f"#{i}"


def test_append():
    previous = [E(1, 1), E(2, 2)]
    cases = [
        ([], 2, [(1, 1), (2, 2)], 3),
        ([E(3, 2)], 3, [(1, 1), (2, 2), (2, 3)], 3),
        # conflicts with index 1
        ([E(1, 2)], 1, [(2, 1)], 1),
        # conflicts with index 2
        ([E(2, 3), E(3, 3)], 3, [(1, 1), (3, 2), (3, 3)], 2),
    ]
    for i, (ents, windex, wents, wunstable) in enumerate(cases):
        storage = InMemLogDB()
        storage.append(list(previous))
        l = EntryLog(storage)
        l.append(list(ents))
        assert l.last_index() == windex, f"#{i}"
        assert sig(l.entries(1, NO_LIMIT)) == wents, f"#{i}"
        assert l.inmem.marker_index == wunstable, f"#{i}"


def test_log_maybe_append():
    previous = [E(1, 1), E(2, 2), E(3, 3)]
    lastindex, lastterm, commit = 3, 3, 1
    cases = [
        # not match: different term
        (lastterm - 1, lastindex, lastindex, [E(lastindex + 1, 4)], 0, False, commit, False),
        # not match: index out of bound
        (lastterm, lastindex + 1, lastindex, [E(lastindex + 2, 4)], 0, False, commit, False),
        # match with the last existing entry
        (lastterm, lastindex, lastindex, [], lastindex, True, lastindex, False),
        (lastterm, lastindex, lastindex + 1, [], lastindex, True, lastindex, False),
        (lastterm, lastindex, lastindex - 1, [], lastindex, True, lastindex - 1, False),
        (lastterm, lastindex, 0, [], lastindex, True, commit, False),
        (0, 0, lastindex, [], 0, True, commit, False),
        (lastterm, lastindex, lastindex, [E(lastindex + 1, 4)], lastindex + 1, True, lastindex, False),
        (lastterm, lastindex, lastindex + 1, [E(lastindex + 1, 4)], lastindex + 1, True, lastindex + 1, False),
        (lastterm, lastindex, lastindex + 2, [E(lastindex + 1, 4)], lastindex + 1, True, lastindex + 1, False),
        (lastterm, lastindex, lastindex + 2, [E(lastindex + 1, 4), E(lastindex + 2, 4)], lastindex + 2, True, lastindex + 2, False),
        # match with an entry in the middle
        (lastterm - 1, lastindex - 1, lastindex, [E(lastindex, 4)], lastindex, True, lastindex, False),
        (lastterm - 2, lastindex - 2, lastindex, [E(lastindex - 1, 4)], lastindex - 1, True, lastindex - 1, False),
        (lastterm - 3, lastindex - 3, lastindex, [E(lastindex - 2, 4)], lastindex - 2, True, lastindex - 2, True),
        (lastterm - 2, lastindex - 2, lastindex, [E(lastindex - 1, 4), E(lastindex, 4)], lastindex, True, lastindex, False),
    ]
    for i, (log_term, index, committed, ents, wlasti, wappend, wcommit, wpanic) in enumerate(cases):
        l = EntryLog(InMemLogDB())
        l.append(list(previous))
        l.committed = commit
        try:
            glasti = 0
            gappend = False
            if l.match_term(index, log_term):
                gappend = True
                l.try_append(index, list(ents))
                glasti = index + len(ents)
                l.commit_to(min(glasti, committed))
        except Exception:
            assert wpanic, f"#{i}: unexpected panic"
            continue
        assert not wpanic or glasti == wlasti, f"#{i}"
        assert glasti == wlasti, f"#{i}: lasti {glasti}"
        assert gappend == wappend, f"#{i}"
        assert l.committed == wcommit, f"#{i}: commit {l.committed}"
        if gappend and ents:
            gents = l.get_entries(
                l.last_index() - len(ents) + 1, l.last_index() + 1, NO_LIMIT
            )
            assert sig(gents) == sig(ents), f"#{i}"


def test_has_next_ents():
    snap = Snapshot(term=1, index=3)
    ents = [E(4, 1), E(5, 1), E(6, 1)]
    cases = [(0, True), (3, True), (4, True), (5, False)]
    for i, (applied, has_next) in enumerate(cases):
        storage = InMemLogDB()
        storage.apply_snapshot(snap)
        l = EntryLog(storage)
        l.append(list(ents))
        l.try_commit(5, 1)
        l.commit_update(UpdateCommit(processed=applied))
        assert l.has_entries_to_apply() == has_next, f"#{i}"


def test_next_ents():
    snap = Snapshot(term=1, index=3)
    ents = [E(4, 1), E(5, 1), E(6, 1)]
    cases = [
        (0, sig(ents[:2])),
        (3, sig(ents[:2])),
        (4, sig(ents[1:2])),
        (5, []),
    ]
    for i, (applied, wents) in enumerate(cases):
        storage = InMemLogDB()
        storage.apply_snapshot(snap)
        l = EntryLog(storage)
        l.append(list(ents))
        l.try_commit(5, 1)
        l.commit_update(UpdateCommit(processed=applied))
        assert sig(l.entries_to_apply()) == wents, f"#{i}"


def test_commit_to():
    previous = [E(1, 1), E(2, 2), E(3, 3)]
    commit = 2
    cases = [(3, 3, False), (1, 2, False), (4, 0, True)]
    for i, (to, wcommit, wpanic) in enumerate(cases):
        l = EntryLog(InMemLogDB())
        l.append(list(previous))
        l.committed = commit
        try:
            l.commit_to(to)
        except Exception:
            assert wpanic, f"#{i}"
            continue
        assert not wpanic, f"#{i}"
        assert l.committed == wcommit, f"#{i}"


def test_compaction():
    cases = [
        (1000, [1001], [-1], False),
        (1000, [300, 500, 800, 900], [700, 500, 200, 100], True),
        (1000, [300, 299], [700, -1], False),
    ]
    for i, (last_index, compacts, wleft, wallow) in enumerate(cases):
        storage = InMemLogDB()
        for j in range(1, last_index + 1):
            storage.append([E(j)])
        l = EntryLog(storage)
        l.try_commit(last_index, 0)
        l.commit_update(UpdateCommit(processed=l.committed))
        for j, c in enumerate(compacts):
            try:
                storage.compact(c)
            except Exception:
                assert not wallow, f"#{i}.{j}"
                continue
            assert len(get_all_entries(l)) == wleft[j], f"#{i}.{j}"


def test_log_restore():
    index, term = 1000, 1000
    storage = InMemLogDB()
    storage.apply_snapshot(Snapshot(index=index, term=term))
    l = EntryLog(storage)
    assert len(get_all_entries(l)) == 0
    assert l.first_index() == index + 1
    assert l.committed == index
    assert l.inmem.marker_index == index + 1
    assert must_term(l, index) == term


def test_is_out_of_bounds():
    offset, num = 100, 100
    storage = InMemLogDB()
    storage.apply_snapshot(Snapshot(index=offset))
    l = EntryLog(storage)
    for i in range(1, num + 1):
        l.append([E(i + offset)])
    first = offset + 1
    cases = [
        (first - 2, first + 1, False, True),
        (first - 1, first + 1, False, True),
        (first, first, False, False),
        (first + num // 2, first + num // 2, False, False),
        (first + num - 1, first + num - 1, False, False),
        (first + num, first + num, False, False),
        (first + num, first + num + 1, True, False),
        (first + num + 1, first + num + 1, True, False),
    ]
    for i, (lo, hi, wpanic, wcompacted) in enumerate(cases):
        try:
            l._check_bound(lo, hi)
        except CompactedError:
            assert wcompacted, f"#{i}"
            continue
        except RuntimeError:
            assert wpanic, f"#{i}"
            continue
        assert not wpanic and not wcompacted, f"#{i}"


def test_term():
    offset, num = 100, 100
    storage = InMemLogDB()
    storage.apply_snapshot(Snapshot(index=offset, term=1))
    l = EntryLog(storage)
    for i in range(1, num):
        l.append([E(offset + i, i)])
    cases = [
        (offset - 1, 0),
        (offset, 1),
        (offset + num // 2, num // 2),
        (offset + num - 1, num - 1),
        (offset + num, 0),
    ]
    for j, (index, w) in enumerate(cases):
        assert must_term(l, index) == w, f"#{j}"


def test_term_with_unstable_snapshot():
    storagesnapi = 100
    unstablesnapi = storagesnapi + 5
    storage = InMemLogDB()
    storage.apply_snapshot(Snapshot(index=storagesnapi, term=1))
    l = EntryLog(storage)
    l.restore(Snapshot(index=unstablesnapi, term=1))
    cases = [
        (storagesnapi, 0),
        (storagesnapi + 1, 0),
        (unstablesnapi - 1, 0),
        (unstablesnapi, 1),
    ]
    for i, (index, w) in enumerate(cases):
        assert must_term(l, index) == w, f"#{i}"


def test_slice():
    offset, num = 100, 100
    last = offset + num
    half = offset + num // 2
    halfe_size = E(half, half).size()

    storage = InMemLogDB()
    storage.apply_snapshot(Snapshot(index=offset))
    for i in range(1, num // 2):
        storage.append([E(offset + i, offset + i)])
    l = EntryLog(storage)
    for i in range(num // 2, num):
        l.append([E(offset + i, offset + i)])

    cases = [
        (offset - 1, offset + 1, NO_LIMIT, [], False),
        (offset, offset + 1, NO_LIMIT, [], False),
        (half - 1, half + 1, NO_LIMIT, [(half - 1, half - 1), (half, half)], False),
        (half, half + 1, NO_LIMIT, [(half, half)], False),
        (last - 1, last, NO_LIMIT, [(last - 1, last - 1)], False),
        (last, last + 1, NO_LIMIT, [], True),
        (half - 1, half + 1, 0, [(half - 1, half - 1)], False),
        (half - 1, half + 1, halfe_size + 1, [(half - 1, half - 1)], False),
        (half - 2, half + 1, halfe_size + 1, [(half - 2, half - 2)], False),
        (half - 1, half + 1, halfe_size * 2, [(half - 1, half - 1), (half, half)], False),
        (half - 1, half + 2, halfe_size * 3, [(half - 1, half - 1), (half, half), (half + 1, half + 1)], False),
        (half, half + 2, halfe_size, [(half, half)], False),
        (half, half + 2, halfe_size * 2, [(half, half), (half + 1, half + 1)], False),
    ]
    for j, (frm, to, limit, w, wpanic) in enumerate(cases):
        try:
            g = l.get_entries(frm, to, limit)
        except CompactedError:
            assert frm <= offset, f"#{j}"
            continue
        except RuntimeError:
            assert wpanic, f"#{j}"
            continue
        assert not wpanic, f"#{j}"
        assert sig(g) == w, f"#{j}: got {sig(g)} want {w}"


def test_compaction_side_effects():
    last_index = 1000
    unstable_index = 750
    last_term = last_index
    storage = InMemLogDB()
    for i in range(1, unstable_index + 1):
        storage.append([E(i, i)])
    l = EntryLog(storage)
    for i in range(unstable_index, last_index):
        l.append([E(i + 1, i + 1)])
    assert l.try_commit(last_index, last_term)
    offset = 500
    storage.compact(offset)
    assert l.last_index() == last_index
    for j in range(offset, l.last_index() + 1):
        assert must_term(l, j) == j, f"term({j})"
        assert l.match_term(j, j), f"match_term({j})"
    unstable = l.entries_to_save()
    assert len(unstable) == 250
    assert unstable[0].index == 751
    prev = l.last_index()
    l.append([E(l.last_index() + 1, l.last_index() + 1)])
    assert l.last_index() == prev + 1
    ents = l.entries(l.last_index(), NO_LIMIT)
    assert len(ents) == 1


def test_unstable_ents():
    previous = [E(1, 1), E(2, 2)]
    cases = [(3, []), (1, sig(previous))]
    for i, (unstable, wents) in enumerate(cases):
        storage = InMemLogDB()
        storage.append(list(previous[: unstable - 1]))
        l = EntryLog(storage)
        l.append(list(previous[unstable - 1 :]))
        ents = l.entries_to_save()
        if ents:
            last = ents[-1]
            l.try_commit(last.index, last.term)
            l.commit_update(
                UpdateCommit(
                    processed=last.index,
                    last_applied=last.index,
                    stable_log_to=last.index,
                    stable_log_term=last.term,
                )
            )
        assert sig(ents) == wents, f"#{i}"
        if ents:
            assert l.inmem.marker_index == ents[-1].index + 1, f"#{i}"


def test_stable_to():
    cases = [
        (1, 1, 1, 1),
        (2, 2, 1, 1),
        (2, 1, 0, 1),  # bad term
        (3, 1, 0, 1),  # bad index
    ]
    for i, (stablei, stablet, saved_to, wunstable) in enumerate(cases):
        l = EntryLog(InMemLogDB())
        l.append([E(1, 1), E(2, 2)])
        l.commit_update(
            UpdateCommit(stable_log_to=stablei, stable_log_term=stablet)
        )
        if saved_to > 0:
            assert l.inmem.saved_to == stablei, f"#{i}"
        assert l.inmem.marker_index == wunstable, f"#{i}"


def test_stable_to_with_snap():
    snapi, snapt = 5, 2
    cases = [
        (snapi + 1, snapt, [], snapi + 1),
        (snapi, snapt, [], snapi + 1),
        (snapi - 1, snapt, [], snapi + 1),
        (snapi + 1, snapt + 1, [], snapi + 1),
        (snapi, snapt + 1, [], snapi + 1),
        (snapi - 1, snapt + 1, [], snapi + 1),
        (snapi + 1, snapt, [E(snapi + 1, snapt)], snapi + 2),
        (snapi, snapt, [E(snapi + 1, snapt)], snapi + 1),
        (snapi - 1, snapt, [E(snapi + 1, snapt)], snapi + 1),
        (snapi + 1, snapt + 1, [E(snapi + 1, snapt)], snapi + 1),
        (snapi, snapt + 1, [E(snapi + 1, snapt)], snapi + 1),
        (snapi - 1, snapt + 1, [E(snapi + 1, snapt)], snapi + 1),
    ]
    for i, (stablei, stablet, new_ents, wunstable) in enumerate(cases):
        s = InMemLogDB()
        s.apply_snapshot(Snapshot(index=snapi, term=snapt))
        l = EntryLog(s)
        l.append(list(new_ents))
        l.commit_update(
            UpdateCommit(stable_log_to=stablei, stable_log_term=stablet)
        )
        assert l.inmem.saved_to == wunstable - 1, f"#{i}"
