"""Mesh-sharded dispatch plane (``ops/mesh.py``, ISSUE 16).

The facade runs one single-device ``BatchedQuorumEngine`` per shard,
each with its own dispatch stream — no global dispatch mutex.  These
suites pin the three claims that design rests on:

1. **Differential**: the mesh engine's commit watermarks and read
   releases are bit-identical to a single-device engine fed the same
   event schedule, and both match per-group scalar ``Raft`` oracles —
   sharding is a pure placement transform.
2. **Migration**: a live group moved between shards keeps its commit
   watermark to the index, keeps committing afterwards, and the move is
   REFUSED while the group has non-droppable in-flight work (pending
   reads) — the quiescence gate.
3. **Concurrency**: with obs attached, two shards' dispatch spans in
   the shared flight recorder genuinely overlap in time (the
   no-global-mutex proof the ISSUE's acceptance gate names), and the
   ``dragonboat_mesh_dispatch_concurrency`` histogram sees peak >= 2.

conftest.py forces an 8-device virtual CPU platform.
"""
import jax

from dragonboat_tpu import Config
from dragonboat_tpu.events import MetricsRegistry
from dragonboat_tpu.obs.recorder import FlightRecorder
from dragonboat_tpu.ops.engine import BatchedQuorumEngine
from dragonboat_tpu.ops.mesh import MeshQuorumEngine
from dragonboat_tpu.ops.sharding import GROUP_AXIS
from dragonboat_tpu.raft import InMemLogDB, Raft
from dragonboat_tpu.wire import Entry, Message, MessageType as MT

N_DEV = 8


def _devices(n=N_DEV):
    devs = jax.local_devices(backend="cpu")
    assert len(devs) >= n, "conftest must force 8 CPU devices"
    return devs[:n]


def _mesh(n_groups, n_peers=3, n_dev=N_DEV, **kw):
    return MeshQuorumEngine(
        n_groups, n_peers, event_cap=4 * n_groups,
        devices=_devices(n_dev), **kw,
    )


def _elect(eng, oracles, cid, peers):
    """Drive group ``cid`` to a seeded leader on engine + oracle."""
    r = Raft(
        Config(cluster_id=cid, node_id=1, election_rtt=10, heartbeat_rtt=1),
        InMemLogDB(), seed=cid,
    )
    for p in peers:
        r.add_node(p)
    oracles[cid] = (r, peers)
    eng.add_group(
        cid, node_ids=peers, self_id=1, election_timeout=10,
        rand_timeout=r.randomized_election_timeout,
    )
    r.become_candidate()
    eng.set_candidate(cid, term=r.term)
    for p in peers:
        if p != 1:
            r.handle(Message(from_=p, to=1, term=r.term,
                             type=MT.REQUEST_VOTE_RESP, reject=False))
        eng.vote(cid, p, True)
    assert r.is_leader()
    eng.set_leader(cid, term=r.term, term_start=r.log.last_index(),
                   last_index=r.log.last_index())
    return r


def test_mesh_commit_read_differential():
    """32 groups over 8 shards vs ONE single-device engine vs scalar
    oracles: random ack schedules + ReadIndex batches, full commit
    vector and read releases identical every dispatch."""
    import random

    n_groups = 32
    rng = random.Random(16)
    mesh = _mesh(n_groups)
    solo = BatchedQuorumEngine(n_groups, n_peers=3,
                               event_cap=4 * n_groups)
    oracles = {}
    try:
        for g in range(n_groups):
            cid = g + 1
            peers = [1, 2, 3]
            _elect(mesh, oracles, cid, peers)
            o2 = {}
            _elect(solo, o2, cid, peers)
        pending = {}  # cid -> set of staged read slots
        for rnd in range(30):
            for cid, (r, peers) in oracles.items():
                if rng.random() < 0.7:
                    r.handle(Message(from_=1, to=1, type=MT.PROPOSE,
                                     entries=[Entry(cmd=b"x")]))
                    idx = r.log.last_index()
                    for eng in (mesh, solo):
                        eng.ack(cid, 1, idx)
                    followers = [p for p in peers if p != 1]
                    rng.shuffle(followers)
                    for p in followers[: rng.randrange(0, 3)]:
                        r.handle(Message(from_=p, to=1, term=r.term,
                                         type=MT.REPLICATE_RESP,
                                         log_index=idx))
                        for eng in (mesh, solo):
                            eng.ack(cid, p, idx)
                if rng.random() < 0.3 and (
                    mesh.read_slots_free(cid) > 0
                    and solo.read_slots_free(cid) > 0
                ):
                    count = rng.randrange(1, 4)
                    sm = mesh.stage_read(cid, count=count)
                    ss = solo.stage_read(cid, count=count)
                    assert sm == ss  # same per-row slot rotation
                    for p in (2, 3):
                        mesh.read_ack(cid, p, sm)
                        solo.read_ack(cid, p, ss)
                    pending.setdefault(cid, set()).add(sm)
            rm = mesh.step(do_tick=False)
            rs = solo.step(do_tick=False)
            for cid, (r, _) in oracles.items():
                want = r.log.committed
                assert mesh.committed_index(cid) == want, (rnd, cid)
                assert solo.committed_index(cid) == want, (rnd, cid)
            assert sorted(rm.reads) == sorted(rs.reads), rnd
            for cid, slot, _idx, _count in rm.reads:
                pending[cid].discard(slot)
        assert not any(pending.values()), pending
        # the zero-copy global view keeps the GSPMD sharding contract
        spec = mesh.dev.match.sharding.spec
        assert spec[0] == GROUP_AXIS
    finally:
        mesh.stop()


def test_mesh_fused_block_differential():
    """Multi-round staged blocks through ``step_rounds`` (incl. the
    pipelined double-buffer) match the single-device engine."""
    n_groups = 16
    mesh = _mesh(n_groups)
    solo = BatchedQuorumEngine(n_groups, n_peers=3,
                               event_cap=4 * n_groups)
    oracles = {}
    try:
        for g in range(n_groups):
            cid = g + 1
            _elect(mesh, oracles, cid, [1, 2, 3])
            _elect(solo, {}, cid, [1, 2, 3])
        for block in range(4):
            for k in range(3):  # 3 staged rounds per block
                for cid, (r, _) in oracles.items():
                    r.handle(Message(from_=1, to=1, type=MT.PROPOSE,
                                     entries=[Entry(cmd=b"x")]))
                    idx = r.log.last_index()
                    for p in (1, 2):
                        if p != 1:
                            r.handle(Message(
                                from_=p, to=1, term=r.term,
                                type=MT.REPLICATE_RESP, log_index=idx))
                        mesh.ack(cid, p, idx)
                        solo.ack(cid, p, idx)
                    (r.handle(Message(from_=2, to=1, term=r.term,
                                      type=MT.REPLICATE_RESP,
                                      log_index=idx)))
                    mesh.ack(cid, 2, idx)
                    solo.ack(cid, 2, idx)
                mesh.begin_round()
                solo.begin_round()
            pipelined = block % 2 == 1
            mesh.step_rounds(pipelined=pipelined)
            solo.step_rounds(pipelined=pipelined)
        mesh.harvest()
        solo.harvest()
        snap_m = mesh.committed_snapshot()
        snap_s = solo.committed_snapshot()
        assert snap_m == snap_s
        for cid, (r, _) in oracles.items():
            assert snap_m[cid] == r.log.committed
    finally:
        mesh.stop()


def _commit_n(eng, r, cid, n):
    for _ in range(n):
        r.handle(Message(from_=1, to=1, type=MT.PROPOSE,
                         entries=[Entry(cmd=b"x")]))
        idx = r.log.last_index()
        eng.ack(cid, 1, idx)
        r.handle(Message(from_=2, to=1, term=r.term,
                         type=MT.REPLICATE_RESP, log_index=idx))
        eng.ack(cid, 2, idx)
    eng.step(do_tick=False)


def test_migration_preserves_watermark():
    """Live migration: watermark identical across the move, commits
    continue on the target shard, the held GroupInfo proxy follows."""
    mesh = _mesh(16, n_dev=4)
    oracles = {}
    try:
        for g in range(8):
            _elect(mesh, oracles, g + 1, [1, 2, 3])
        cid = 3
        r, _ = oracles[cid]
        gi = mesh.groups[cid]
        _commit_n(mesh, r, cid, 5)
        assert mesh.committed_index(cid) == r.log.committed
        src = mesh.shard_index(cid)
        dst = (src + 1) % mesh.n_shards
        row_before = gi.row
        assert mesh.migrate_group(cid, dst)
        assert mesh.shard_index(cid) == dst
        assert mesh.migrations == 1
        assert gi.row != row_before  # proxy repointed to the new shard
        assert mesh.committed_index(cid) == r.log.committed
        # the group keeps committing on its new shard, indexes continuous
        _commit_n(mesh, r, cid, 3)
        assert mesh.committed_index(cid) == r.log.committed
        # every OTHER group was untouched
        for ocid, (orc, _) in oracles.items():
            assert mesh.committed_index(ocid) == orc.log.committed
    finally:
        mesh.stop()


def test_migration_refused_until_quiescent():
    """A pending (unconfirmed) read pins the group to its shard; the
    move succeeds once the read confirms and releases."""
    mesh = _mesh(8, n_dev=2)
    oracles = {}
    try:
        _elect(mesh, oracles, 1, [1, 2, 3])
        r, _ = oracles[1]
        _commit_n(mesh, r, 1, 2)
        slot = mesh.stage_read(1, count=1)
        src = mesh.shard_index(1)
        dst = 1 - src
        assert not mesh.migrate_group(1, dst)  # read in flight -> pinned
        assert mesh.shard_index(1) == src
        for p in (2, 3):
            mesh.read_ack(1, p, slot)
        res = mesh.step(do_tick=False)
        assert any(c == 1 for c, *_ in res.reads)
        assert mesh.migrate_group(1, dst)
        assert mesh.shard_index(1) == dst
    finally:
        mesh.stop()


def test_rebalance_moves_group_on_count_skew():
    """Emptying one shard trips the count-skew trigger: the next
    ``maybe_rebalance`` migrates a group onto the idle shard and the
    placement gauges/counters follow."""
    reg = MetricsRegistry()
    rec = FlightRecorder(stall_ms=0)
    mesh = _mesh(8, n_dev=2)
    oracles = {}
    try:
        mesh.enable_obs(rec, registry=reg)
        for g in range(4):
            _elect(mesh, oracles, g + 1, [1, 2, 3])
        # placement alternated 2/2; vacate shard 0 entirely
        for cid, idx in list(mesh._assign.items()):
            if idx == 0:
                mesh.remove_group(cid)
        assert mesh.shard_counts() == [0, 2]
        moved = mesh.maybe_rebalance()
        assert moved == 1
        assert mesh.shard_counts() == [1, 1]
        assert mesh.migrations == 1
        assert reg.counter_value("dragonboat_mesh_migrations_total") == 1
        assert reg.gauge_value(
            "dragonboat_mesh_groups", labels={"shard": "0"}
        ) == 1
        # migrated group still healthy
        cid = next(iter(c for c, i in mesh._assign.items() if i == 0))
        r, _ = oracles[cid]
        _commit_n(mesh, r, cid, 2)
        assert mesh.committed_index(cid) == r.log.committed
        spans = [s for s in rec.spans() if s["kind"] == "mesh_migration"]
        assert len(spans) == 1 and spans[0]["cluster_id"] == cid
    finally:
        mesh.stop()


def test_concurrent_shard_dispatch_spans_overlap():
    """Two shards' fused dispatches verifiably overlap in time: shared
    recorder, heavy K-round blocks on both shards, spans tagged with
    their shard index intersect — impossible under the retired global
    dispatch mutex."""
    reg = MetricsRegistry()
    rec = FlightRecorder(stall_ms=0)
    n_groups = 512  # 256 per shard: enough device work to overlap
    mesh = _mesh(n_groups, n_dev=2)
    oracles = {}
    try:
        mesh.enable_obs(rec, registry=reg)
        for g in range(n_groups):
            _elect(mesh, oracles, g + 1, [1, 2, 3])
        for trial in range(8):
            for k in range(16):
                for cid, (r, _) in oracles.items():
                    r.handle(Message(from_=1, to=1, type=MT.PROPOSE,
                                     entries=[Entry(cmd=b"x")]))
                    idx = r.log.last_index()
                    mesh.ack(cid, 1, idx)
                    r.handle(Message(from_=2, to=1, term=r.term,
                                     type=MT.REPLICATE_RESP,
                                     log_index=idx))
                    mesh.ack(cid, 2, idx)
                mesh.begin_round()
            mesh.step_rounds()
        snap = mesh.committed_snapshot()
        for cid, (r, _) in oracles.items():
            assert snap[cid] == r.log.committed
        by_shard = {}
        for s in rec.spans():
            if s["kind"] not in ("fused", "dispatch"):
                continue
            if "shard" not in s or "egress_ms" not in s:
                continue
            start = s["ts"]
            end = start + (
                (s.get("dispatch_ms") or 0.0) + (s["egress_ms"] or 0.0)
            ) / 1e3
            by_shard.setdefault(s["shard"], []).append((start, end))
        assert set(by_shard) == {0, 1}, by_shard.keys()
        overlap = any(
            a0 < b1 and b0 < a1
            for a0, a1 in by_shard[0]
            for b0, b1 in by_shard[1]
        )
        assert overlap, "no overlapping cross-shard dispatch spans"
        # the histogram saw >= 2 simultaneously in-flight dispatches
        hist = reg.histogram_value("dragonboat_mesh_dispatch_concurrency")
        assert hist is not None
        # mu_wait is structurally zero on mesh engines (no global lock)
        assert all(
            not s.get("mu_wait_ms")
            for s in rec.spans() if s["kind"] in ("fused", "dispatch")
        )
    finally:
        mesh.stop()


def test_mesh_warmup_readiness():
    """The facade's sequential warm walk compiles every shard's program
    set and the readiness latches aggregate."""
    mesh = _mesh(8, n_dev=2)
    try:
        assert not mesh.fused_ready
        stats = mesh.warmup_fused(
            k_buckets=(4,), include_reads=False, include_single=False,
            background=False,
        )
        assert mesh.fused_ready
        assert stats["shards_ready"] == 2
        assert stats["programs"] >= 2  # >= one program per shard
        assert stats["error"] is None
    finally:
        mesh.stop()
