"""Differential tests for the K-round fused dispatch (ISSUE 1 tentpole).

The multi-round program (``kernels.quorum_multiround`` /
``BatchedQuorumEngine.begin_round``/``step_rounds``/``stage_recycle``)
must be observationally identical to K single-round dispatches — and,
through them, to the scalar Raft path the single-round differential
suites pin (``tests/test_ops_quorum.py``).  Every test here compares
full device state field-by-field, not just watermarks, including the
membership-recycle-mid-block case where churn travels inside the
dispatched program as masked row updates.
"""
from __future__ import annotations

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dragonboat_tpu.ops.engine import BatchedQuorumEngine, MultiRoundResult
from dragonboat_tpu.wire import Entry, Message, MessageType
from raft_harness import new_test_raft

MT = MessageType


def _state_equal(a, b, tag=""):
    for name, va in a._asdict().items():
        vb = getattr(b, name)
        assert np.array_equal(np.asarray(va), np.asarray(vb)), (tag, name)


def _build(n_groups=12, n_peers=3, cap=256):
    eng = BatchedQuorumEngine(n_groups, n_peers, event_cap=cap)
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=list(range(1, n_peers + 1)), self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    eng._upload_dirty()
    return eng


# ----------------------------------------------------------------------
# kernel level: fused scan ≡ K sequential dense dispatches
# ----------------------------------------------------------------------


@pytest.mark.parametrize("do_tick", [False, True])
def test_multiround_kernel_matches_dense_rounds(do_tick):
    from dragonboat_tpu.ops.kernels import quorum_multiround, quorum_step_dense
    from dragonboat_tpu.ops.state import VOTE_NONE

    rng = random.Random(501 + do_tick)
    g, p, k = 16, 3, 5
    eng_a = _build(g, p)
    eng_b = _build(g, p)
    _state_equal(eng_a.dev, eng_b.dev)

    # random per-round dense blocks with the -1 sentinel
    ack = np.full((k, g, p), -1, np.int32)
    votes = np.full((k, g, p), VOTE_NONE, np.int8)
    for r in range(k):
        for _ in range(rng.randrange(0, 24)):
            ack[r, rng.randrange(g), rng.randrange(p)] = rng.choice(
                [0, 1, 2, 5, 9]
            )
        for _ in range(rng.randrange(0, 4)):
            votes[r, rng.randrange(g), rng.randrange(p)] = rng.choice([0, 1])

    out_f = quorum_multiround(
        eng_a.dev,
        jnp.asarray(ack),
        jnp.asarray(votes),
        jnp.zeros((1, 1), jnp.int32),
        jnp.zeros((1, 1), jnp.int32),
        jnp.zeros((1, 1), jnp.int32),
        jnp.zeros((1, 1), jnp.int32),
        jnp.asarray(np.ones((k,), bool)),
        do_tick=do_tick,
        track_contact=True,
        has_votes=True,
        has_churn=False,
    )

    st = eng_b.dev
    won = lost = None
    flags_acc = None
    for r in range(k):
        am = ack[r]
        out = quorum_step_dense(
            st,
            jnp.asarray(np.maximum(am, 0)),
            jnp.asarray(am >= 0),
            jnp.asarray(votes[r]),
            do_tick=do_tick,
            track_contact=True,
            has_votes=True,
        )
        st = out.state
        w, l_ = np.asarray(out.won), np.asarray(out.lost)
        fl = [np.asarray(f) for f in out.flags]
        if won is None:
            won, lost, flags_acc = w, l_, fl
        else:
            won, lost = won | w, lost | l_
            flags_acc = [a | b for a, b in zip(flags_acc, fl)]

    _state_equal(out_f.state, st, "kernel")
    assert np.array_equal(np.asarray(out_f.won), won)
    assert np.array_equal(np.asarray(out_f.lost), lost)
    for i in range(3):
        assert np.array_equal(np.asarray(out_f.flags[i]), flags_acc[i]), i


# ----------------------------------------------------------------------
# engine level: begin_round/step_rounds ≡ per-round step()
# ----------------------------------------------------------------------


def test_multiround_engine_matches_per_round_steps():
    """Random multi-round workloads (acks, votes, heartbeat zero-acks)
    through the fused path vs one step() per round: final device state
    bit-identical; the fused commit egress equals the final watermarks of
    the per-round sequence."""
    seed = 902

    def drive(eng, fused):
        rng = random.Random(seed)
        per_round_commit = {}
        for _ in range(6):
            for _ in range(rng.randrange(4, 30)):
                cid = rng.randrange(1, 13)
                idx = rng.randrange(1, 8)
                eng.ack(cid, rng.choice([1, 2, 3]), idx)
            if rng.random() < 0.4:
                eng.heartbeat_resp(rng.randrange(1, 13), 2)
            if fused:
                eng.begin_round()
            else:
                res = eng.step(do_tick=False)
                per_round_commit.update(res.commit)
        if fused:
            res = eng.step_rounds(do_tick=False)
            return eng, res.commit
        return eng, per_round_commit

    eng_f, commit_f = drive(_build(), True)
    eng_s, commit_s = drive(_build(), False)
    _state_equal(eng_f.dev, eng_s.dev, "engine")
    # fused egress reports final watermarks; the per-round merge's last
    # value per cid is exactly that
    assert commit_f == {
        cid: q
        for cid, q in commit_s.items()
        if eng_s.committed_index(cid) == q
    }
    for cid in range(1, 13):
        assert eng_f.committed_index(cid) == eng_s.committed_index(cid)


def test_multiround_vote_quorum_mid_block():
    """A candidate reaching quorum in round r of a fused block must set
    the OR-accumulated won flag exactly as the per-round path does."""
    def build():
        eng = BatchedQuorumEngine(4, 3, event_cap=64)
        eng.add_group(1, node_ids=[1, 2, 3], self_id=1)
        eng.set_candidate(1, term=2)
        return eng

    a, b = build(), build()
    # round 0: self vote only; round 1: peer 2 grants -> quorum of 2
    a.vote(1, 1, True)
    a.begin_round()
    a.vote(1, 2, True)
    a.begin_round()
    ra = a.step_rounds(do_tick=False)
    b.vote(1, 1, True)
    r0 = b.step(do_tick=False)
    b.vote(1, 2, True)
    r1 = b.step(do_tick=False)
    assert r0.won == [] and r1.won == [1]
    assert ra.won == [1]
    _state_equal(a.dev, b.dev, "votes")


def test_multiround_padded_tick_mask_matches_sequential():
    """The coordinator's fixed-K catch-up shape: a block of 2 real tick
    rounds padded to K=4 with masked-off empty rounds must equal 2
    sequential step(do_tick=True) calls exactly — the padding rounds are
    provable no-ops (one compiled program serves every deficit)."""
    def build():
        eng = BatchedQuorumEngine(3, 3, event_cap=32)
        eng.add_group(
            1, node_ids=[1, 2, 3], self_id=1,
            election_timeout=4, rand_timeout=6,
        )
        eng.add_group(2, node_ids=[1, 2, 3], self_id=1)
        eng.set_leader(2, term=1, term_start=1, last_index=1)
        return eng

    for blocks in range(1, 4):  # 2, 4, 6 total ticks
        a, b = build(), build()
        for _ in range(blocks):
            a.ack(2, 1, 2)
            a.ack(2, 2, 2)
            a.begin_round()
            a.begin_round()
            ra = a.step_rounds(do_tick=True, pad_rounds_to=4)
            b.ack(2, 1, 2)
            b.ack(2, 2, 2)
            rb0 = b.step(do_tick=True)
            rb1 = b.step(do_tick=True)
        _state_equal(a.dev, b.dev, f"padded-ticks-{blocks}")
        assert sorted(ra.elect) == sorted(set(rb0.elect) | set(rb1.elect))
        assert ra.commit.get(2) == (rb0.commit | rb1.commit).get(2)


def test_multiround_tick_rounds_match_sequential_ticks():
    """K fused tick rounds (the coordinator's catch-up shape) fire
    election flags on exactly the same tick as K sequential step()s."""
    def build():
        eng = BatchedQuorumEngine(2, 3, event_cap=32)
        eng.add_group(
            1, node_ids=[1, 2, 3], self_id=1,
            election_timeout=4, rand_timeout=5,
        )
        return eng

    # sequential: find the firing tick
    eng = build()
    fired_seq = None
    for tick in range(1, 9):
        out = eng.step(do_tick=True)
        if out.elect:
            fired_seq = tick
            break
    assert fired_seq == 5

    # fused blocks of 2: the flag must surface in the block containing
    # tick 5 (OR-accumulated), and not before
    eng = build()
    fired_block = None
    for block in range(4):
        eng.begin_round()
        eng.begin_round()
        out = eng.step_rounds(do_tick=True)
        if out.elect and fired_block is None:
            fired_block = block
    assert fired_block == 2  # ticks 5-6 live in the third block of 2


# ----------------------------------------------------------------------
# membership recycle mid-block (churn inside the dispatched program)
# ----------------------------------------------------------------------


def test_multiround_recycle_mid_block_matches_host_churn():
    """stage_recycle (device-side masked row reset at round start) must
    be bit-identical to the host remove/add/set_leader path run between
    per-round dispatches — including purging same-round old-tenant
    events and ingesting same-round new-tenant acks."""
    a, b = _build(8, 3), _build(8, 3)

    # round 0: everyone commits index 2; group 3 also has a STALE ack
    # staged after the round that must die with the old tenant
    for cid in range(1, 9):
        a.ack(cid, 1, 2)
        a.ack(cid, 2, 2)
        b.ack(cid, 1, 2)
        b.ack(cid, 2, 2)
    a.begin_round()
    b.step(do_tick=False)

    # round 1: old-tenant ack staged BEFORE the recycle (must be purged),
    # then recycle 3 -> 103, then the new tenant commits 2
    a.ack(3, 2, 9)  # old tenant, same round: purged by the recycle
    a.stage_recycle(3, 103, term=1, term_start=1, last_index=1)
    a.ack(103, 1, 2)
    a.ack(103, 2, 2)
    for cid in (1, 5):
        a.ack(cid, 1, 3)
        a.ack(cid, 2, 3)
    a.begin_round()
    ra = a.step_rounds(do_tick=False)

    b.ack(3, 2, 9)
    b.remove_group(3)  # purges the staged old-tenant ack (epoch bump)
    b.add_group(103, node_ids=[1, 2, 3], self_id=1)
    b.set_leader(103, term=1, term_start=1, last_index=1)
    b.ack(103, 1, 2)
    b.ack(103, 2, 2)
    for cid in (1, 5):
        b.ack(cid, 1, 3)
        b.ack(cid, 2, 3)
    rb = b.step(do_tick=False)

    _state_equal(a.dev, b.dev, "recycle")
    assert a.committed_index(103) == b.committed_index(103) == 2
    assert a.committed_index(1) == b.committed_index(1) == 3
    assert ra.commit[103] == rb.commit[103] == 2
    # row bookkeeping: the new tenant owns the old tenant's row, base 0
    assert a.groups[103].row == b.groups[103].row
    assert 3 not in a.groups and 3 not in b.groups


def test_multiround_recycle_against_scalar_oracle():
    """Commit vectors of a fused churn block stay bit-identical to scalar
    Raft oracles driven through the same K rounds, with a recycle in the
    middle of the block (the ISSUE 1 acceptance case)."""
    peers = [1, 2, 3]

    def mk_leader(cid):
        r = new_test_raft(1, peers)
        r.cluster_id = cid
        r.handle(Message(from_=1, to=1, type=MT.ELECTION))
        for p in (2, 3):
            if not r.is_leader():
                r.handle(Message(
                    from_=p, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP
                ))
        assert r.is_leader()
        return r

    eng = BatchedQuorumEngine(4, 3, event_cap=128)
    oracles = {}
    for cid in (1, 2, 3):
        r = mk_leader(cid)
        oracles[cid] = r
        eng.add_group(cid, node_ids=peers, self_id=1)
        eng.set_leader(
            cid, term=r.term, term_start=r.log.last_index(),
            last_index=r.log.last_index(),
        )

    def propose_and_ack(r, cid):
        r.handle(Message(
            from_=1, to=1, type=MT.PROPOSE, entries=[Entry(cmd=b"x")]
        ))
        idx = r.log.last_index()
        eng.ack(cid, 1, idx)
        for p in (2, 3):
            r.handle(Message(
                from_=p, to=1, term=r.term, type=MT.REPLICATE_RESP,
                log_index=idx,
            ))
            eng.ack(cid, p, idx)

    # rounds 0-1: all three commit; round 2: group 2 is recycled into a
    # brand-new group 42 (fresh oracle) which commits in the same round;
    # round 3: everyone commits again
    for _ in range(2):
        for cid, r in oracles.items():
            propose_and_ack(r, cid)
        eng.begin_round()
    fresh = mk_leader(42)
    eng.stage_recycle(
        2, 42, term=fresh.term,
        term_start=fresh.log.last_index(),
        last_index=fresh.log.last_index(),
    )
    del oracles[2]
    oracles[42] = fresh
    for cid, r in oracles.items():
        propose_and_ack(r, cid)
    eng.begin_round()
    for cid, r in oracles.items():
        propose_and_ack(r, cid)
    res = eng.step_rounds(do_tick=False)
    assert isinstance(res, MultiRoundResult) and res.rounds == 4
    for cid, r in oracles.items():
        assert eng.committed_index(cid) == r.log.committed, cid
        assert res.commit[cid] == r.log.committed, cid


def test_stage_recycle_validation():
    eng = _build(4, 3)
    with pytest.raises(ValueError):
        eng.stage_recycle(99, 100, term=1, term_start=1, last_index=1)
    with pytest.raises(ValueError):
        eng.stage_recycle(1, 2, term=1, term_start=1, last_index=1)  # taken
    with pytest.raises(ValueError):  # geometry change (rand_timeout)
        eng.stage_recycle(
            1, 100, term=1, term_start=1, last_index=1, rand_timeout=99
        )
    with pytest.raises(ValueError):  # term_start > last_index
        eng.stage_recycle(1, 100, term=1, term_start=5, last_index=1)
    eng.stage_recycle(1, 100, term=1, term_start=1, last_index=1)
    with pytest.raises(ValueError):  # same row twice in one round
        eng.stage_recycle(100, 101, term=1, term_start=1, last_index=1)
    eng.begin_round()
    eng.stage_recycle(100, 101, term=1, term_start=1, last_index=1)  # ok now
    eng.step_rounds(do_tick=False)
    assert 101 in eng.groups and 100 not in eng.groups


def test_remove_group_drops_open_round_recycle():
    """remove_group after a same-round stage_recycle must not let the
    staged in-program reset revive the freed row."""
    eng = _build(4, 3)
    eng.stage_recycle(1, 100, term=1, term_start=1, last_index=1)
    eng.remove_group(100)
    eng.ack(2, 1, 2)
    eng.ack(2, 2, 2)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    row = 0  # group 1 was registered first -> row 0
    assert not bool(np.asarray(eng.dev.live)[row])
    assert eng.committed_index(2) == 2


def test_remove_group_drops_closed_round_recycle():
    """A recycle already CLOSED into a pending block must also die with
    remove_group — the stale record would otherwise revive the freed row
    (or clobber its next tenant) when the block dispatches."""
    eng = _build(4, 3)
    eng.stage_recycle(1, 100, term=7, term_start=1, last_index=1)
    eng.begin_round()  # churn record now lives in a closed block
    eng.remove_group(100)
    # the freed row goes to a NEW tenant via the normal host path
    eng.add_group(200, node_ids=[1, 2, 3], self_id=1)
    assert eng.groups[200].row == 0
    eng.set_leader(200, term=3, term_start=1, last_index=1)
    eng.ack(200, 1, 2)
    eng.ack(200, 2, 2)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    # the dead recycle's term=7 reset must NOT have clobbered tenant 200
    assert int(np.asarray(eng.dev.term)[0]) == 3
    assert eng.committed_index(200) == 2


def test_rare_path_transition_cancels_pending_recycle():
    """A host rare-path mutation on a recycled-but-undispatched row must
    keep the recycle's state as its baseline (the mirror, not the stale
    pre-recycle device row) and supersede the in-program reset — the
    transition must survive the dispatch."""
    eng = _build(4, 3)
    # advance group 1 so the old tenant's device row is distinguishable
    eng.ack(1, 1, 5)
    eng.ack(1, 2, 5)
    eng.step(do_tick=False)
    assert eng.committed_index(1) == 5
    eng.stage_recycle(1, 100, term=2, term_start=1, last_index=1)
    # host reads of the pending row resolve to the NEW tenant already
    assert eng.committed_index(100) == 0
    assert int(eng._read("term", 0)) == 2
    # rare-path transition on the new tenant before the block dispatches
    eng.set_leader(100, term=9, term_start=3, last_index=3)
    eng.ack(100, 1, 3)
    eng.ack(100, 2, 3)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    # the transition won (term 9), the dead recycle (term 2) did not,
    # and the old tenant's match state did not resurrect
    assert int(np.asarray(eng.dev.term)[0]) == 9
    assert eng.committed_index(100) == 3


def test_collapsed_recycle_purges_closed_round_events():
    """When a rare-path mutation collapses a staged recycle to pre-block
    ordering, the OLD tenant's events already sealed into closed blocks
    must die with it — they would otherwise scatter-max into the new
    tenant's freshly uploaded row."""
    eng = _build(4, 3)
    # closed round 0 carries old-tenant (group 1) acks at rel 5
    eng.ack(1, 1, 5)
    eng.ack(1, 2, 5)
    eng.ack(2, 1, 2)
    eng.ack(2, 2, 2)
    eng.begin_round()
    eng.stage_recycle(1, 100, term=2, term_start=1, last_index=1)
    # rare-path mutation on the new tenant -> recycle collapses pre-block
    eng.set_randomized_timeout(100, 20)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    # group 100 never replicated rel 5; the dead tenant's acks must not
    # have advanced it (fresh leader at last_index 1, no acks -> 0)
    assert eng.committed_index(100) == 0
    assert int(np.asarray(eng.dev.match)[0].max()) <= 1
    # unrelated group's closed events were untouched
    assert eng.committed_index(2) == 2


def test_pipelined_recycle_does_not_pollute_inflight_egress():
    """stage_recycle zeroes the host watermark cache in place; a dispatch
    already in flight must keep its own (snapshotted) commit baseline —
    no phantom commit deltas for the recycled row."""
    eng = _build(4, 3)
    for cid in range(1, 5):
        eng.ack(cid, 1, 2)
        eng.ack(cid, 2, 2)
    eng.step(do_tick=False)
    # block A: ONLY group 2 advances; group 1 stays at watermark 2
    eng.ack(2, 1, 3)
    eng.ack(2, 2, 3)
    eng.step_rounds(do_tick=False, pipelined=True)
    # while A is in flight: recycle group 1 (zeroes its cache row)
    eng.stage_recycle(1, 100, term=1, term_start=1, last_index=1)
    res = eng.harvest()  # block A's egress
    # group 1 did not advance in block A: its (old or new) cid must not
    # appear as a commit delta
    assert set(res.commit) == {2}, res.commit
    assert res.commit[2] == 3
    # and the pending new tenant still reads watermark 0
    assert eng.committed_index(100) == 0
    eng.ack(100, 1, 2)
    eng.ack(100, 2, 2)
    eng.begin_round()
    out = eng.step_rounds(do_tick=False)
    assert out.commit[100] == 2


# ----------------------------------------------------------------------
# pipelined double-buffered staging
# ----------------------------------------------------------------------


def test_pipelined_step_rounds_equivalent():
    """pipelined=True (ingress double-buffering) must produce the same
    final state and the same per-block egress as synchronous dispatch,
    one block late."""
    a, b = _build(6, 3), _build(6, 3)
    sync_results = []
    piped_results = []
    for blk in range(4):
        for cid in range(1, 7):
            a.ack(cid, 1, 2 + blk)
            a.ack(cid, 2, 2 + blk)
            b.ack(cid, 1, 2 + blk)
            b.ack(cid, 2, 2 + blk)
        sync_results.append(a.step_rounds(do_tick=False))
        r = b.step_rounds(do_tick=False, pipelined=True)
        if r is not None:
            piped_results.append(r)
    final = b.harvest()
    assert final is not None
    piped_results.append(final)
    _state_equal(a.dev, b.dev, "pipelined")
    assert len(sync_results) == len(piped_results)
    for rs, rp in zip(sync_results, piped_results):
        assert rs.commit == rp.commit
        assert np.array_equal(rs.committed_rel, rp.committed_rel)
    # a host read mid-pipeline harvests the in-flight block first
    for cid in range(1, 7):
        b.ack(cid, 1, 9)
        b.ack(cid, 2, 9)
    b.step_rounds(do_tick=False, pipelined=True)
    assert b.committed_index(1) == 9  # forced harvest, correct value
    assert b.harvest() is None       # already drained


def test_ack_block_rounds_matches_per_round_staging():
    """The bulk K-round staging API (one validation, aliased buffers,
    precomputed cells) must be bit-identical to K× ack_block+begin_round,
    including duplicate cells within a round (max-aggregation) and
    below-base clamping."""
    a, b = _build(8, 3), _build(8, 3)
    rows = np.array([0, 1, 2, 3, 4, 5, 6, 7, 0], np.int32)  # dup cell row 0
    slots = np.array([0, 0, 0, 0, 1, 1, 1, 1, 0], np.int32)
    k = 4
    rels = np.arange(2, 2 + k, dtype=np.int32)[:, None] + np.zeros(
        (1, rows.size), np.int32
    )
    rels[1, -1] = -3  # below-base retransmit: clamps to 0
    rels[2, 0] = 1    # stale (lower) ack: max-aggregation keeps 4

    a.ack_block_rounds(rows, slots, rels)
    ra = a.step_rounds(do_tick=False)
    for r in range(k):
        b.ack_block(rows, slots, np.maximum(rels[r], 0))
        b.begin_round()
    rb = b.step_rounds(do_tick=False)
    _state_equal(a.dev, b.dev, "ack_block_rounds")
    assert ra.commit == rb.commit
    # validation still fires on the bulk path
    with pytest.raises(ValueError):
        a.ack_block_rounds(rows, slots, rels[:, :3])  # shape mismatch
    with pytest.raises(ValueError):
        a.ack_block_rounds(
            np.array([99], np.int32), np.array([0], np.int32),
            np.array([[1]], np.int32),
        )


def test_committed_view_matches_committed_index():
    eng = _build(6, 3)
    for cid in range(1, 7):
        eng.ack(cid, 1, 1 + cid)
        eng.ack(cid, 2, 1 + cid)
    eng.step(do_tick=False)
    view = eng.committed_view()
    cids = eng.row_cids()
    for row in range(6):
        assert cids[row] == row + 1
        assert view[row] == eng.committed_index(int(cids[row]))
    # dead rows are excluded via the cid mask
    eng.remove_group(3)
    assert (eng.row_cids() >= 0).sum() == 5
