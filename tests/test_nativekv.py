"""Contract + recovery tests for the C++ native segmented-WAL KV engine.

Mirrors the reference's KV backend test surface
(``internal/logdb/kv/kv.go:28`` contract exercised through
``internal/logdb/*_test.go``) and adds crash-recovery cases the Go tests
cover via cross-version fixtures: torn-tail truncation, restart replay,
GC compaction keeping reads intact.
"""
import os
import struct

import pytest

from dragonboat_tpu.logdb.kv import InMemKV, WalKV
from dragonboat_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


@pytest.fixture
def kv(tmp_path):
    store = native.NativeKV(str(tmp_path / "kv"), fsync=False)
    yield store
    store.close()


def reopen(store, path):
    store.close()
    return native.NativeKV(str(path / "kv"), fsync=False)


def test_basic_ops(kv):
    assert kv.get(b"missing") is None
    kv.put(b"k1", b"v1")
    assert kv.get(b"k1") == b"v1"
    kv.put(b"k1", b"v2")  # overwrite
    assert kv.get(b"k1") == b"v2"
    kv.delete(b"k1")
    assert kv.get(b"k1") is None
    kv.delete(b"never-existed")  # no-op


def test_empty_value(kv):
    kv.put(b"k", b"")
    assert kv.get(b"k") == b""


def test_write_batch_atomic_and_ordered(kv):
    kv.put(b"a", b"old")
    wb = kv.get_write_batch()
    wb.put(b"a", b"1")
    wb.put(b"b", b"2")
    wb.delete(b"a")
    wb.put(b"c", b"3")
    kv.commit_write_batch(wb)
    # ops apply in order: the delete lands after the put of "a"
    assert kv.get(b"a") is None
    assert kv.get(b"b") == b"2"
    assert kv.get(b"c") == b"3"


def test_iterate_bounds(kv):
    for i in range(10):
        kv.put(b"k%02d" % i, b"v%d" % i)
    got = [k for k, _ in kv.iterate(b"k02", b"k05", True)]
    assert got == [b"k02", b"k03", b"k04", b"k05"]
    got = [k for k, _ in kv.iterate(b"k02", b"k05", False)]
    assert got == [b"k02", b"k03", b"k04"]
    assert list(kv.iterate(b"x", b"z", True)) == []


def test_bulk_remove_entries(kv):
    for i in range(10):
        kv.put(b"e%02d" % i, b"v")
    kv.bulk_remove_entries(b"e03", b"e07")  # [first, last)
    remaining = [k for k, _ in kv.iterate(b"e00", b"e99", True)]
    assert remaining == [b"e00", b"e01", b"e02", b"e07", b"e08", b"e09"]


def test_restart_recovery(tmp_path):
    kv = native.NativeKV(str(tmp_path / "kv"), fsync=False)
    for i in range(100):
        kv.put(struct.pack(">I", i), b"val-%d" % i)
    kv.bulk_remove_entries(struct.pack(">I", 10), struct.pack(">I", 20))
    kv = reopen(kv, tmp_path)
    assert kv.get(struct.pack(">I", 5)) == b"val-5"
    assert kv.get(struct.pack(">I", 15)) is None
    assert kv.get(struct.pack(">I", 99)) == b"val-99"
    kv.close()


def test_torn_tail_truncated(tmp_path):
    kv = native.NativeKV(str(tmp_path / "kv"), fsync=False)
    kv.put(b"good", b"committed")
    kv.close()
    seg = tmp_path / "kv" / "seg-00000001.nkv"
    data = seg.read_bytes()
    # append a torn record: valid-looking header, missing payload bytes
    seg.write_bytes(data + struct.pack("<III", 0xDEAD, 100, 1) + b"short")
    kv = native.NativeKV(str(tmp_path / "kv"), fsync=False)
    assert kv.get(b"good") == b"committed"
    kv.put(b"after", b"recovery")  # writable after truncation
    kv = reopen(kv, tmp_path)
    assert kv.get(b"after") == b"recovery"
    kv.close()


def test_corrupt_payload_crc_detected(tmp_path):
    kv = native.NativeKV(str(tmp_path / "kv"), fsync=False)
    kv.put(b"aa", b"x" * 64)
    kv.put(b"bb", b"y" * 64)
    kv.close()
    seg = tmp_path / "kv" / "seg-00000001.nkv"
    data = bytearray(seg.read_bytes())
    data[-1] ^= 0xFF  # flip a byte in the last record's payload
    seg.write_bytes(bytes(data))
    kv = native.NativeKV(str(tmp_path / "kv"), fsync=False)
    assert kv.get(b"aa") == b"x" * 64  # first record survives
    assert kv.get(b"bb") is None  # corrupt record dropped
    kv.close()


def test_full_compaction_preserves_data(tmp_path):
    kv = native.NativeKV(str(tmp_path / "kv"), fsync=False)
    for i in range(50):
        kv.put(b"k%03d" % i, os.urandom(128))
    for i in range(0, 50, 2):
        kv.delete(b"k%03d" % i)
    live = dict(kv.iterate(b"", b"\xff" * 8, True))
    kv.full_compaction()
    assert dict(kv.iterate(b"", b"\xff" * 8, True)) == live
    kv = reopen(kv, tmp_path)
    assert dict(kv.iterate(b"", b"\xff" * 8, True)) == live
    kv.close()


def test_compact_entries_after_range_delete(tmp_path):
    kv = native.NativeKV(str(tmp_path / "kv"), fsync=False)
    for i in range(200):
        kv.put(b"e%04d" % i, os.urandom(256))
    kv.bulk_remove_entries(b"e0000", b"e0190")
    kv.compact_entries(b"e0000", b"e0190")
    survivors = [k for k, _ in kv.iterate(b"e0000", b"e9999", True)]
    assert survivors == [b"e%04d" % i for i in range(190, 200)]
    kv = reopen(kv, tmp_path)
    survivors = [k for k, _ in kv.iterate(b"e0000", b"e9999", True)]
    assert survivors == [b"e%04d" % i for i in range(190, 200)]
    kv.close()


def test_large_values(kv):
    big = os.urandom(4 << 20)
    kv.put(b"big", big)
    assert kv.get(b"big") == big


@pytest.mark.parametrize("factory", ["inmem", "wal", "native"])
def test_cross_backend_equivalence(tmp_path, factory):
    """All three backends agree on a scripted op sequence
    (the differential discipline SURVEY.md §4 carries over)."""
    if factory == "inmem":
        kv = InMemKV()
    elif factory == "wal":
        kv = WalKV(str(tmp_path / "w"), fsync=False)
    else:
        kv = native.NativeKV(str(tmp_path / "n"), fsync=False)
    for i in range(64):
        kv.put(b"%04d" % (i * 7 % 64), b"v%d" % i)
    wb = kv.get_write_batch()
    wb.delete_range(b"0010", b"0030")
    wb.put(b"0011", b"resurrected")
    kv.commit_write_batch(wb)
    state = list(kv.iterate(b"0000", b"9999", True))
    expect_keys = sorted(
        {b"%04d" % k for k in range(64) if not (10 <= k < 30)} | {b"0011"}
    )
    assert [k for k, _ in state] == expect_keys
    assert dict(state)[b"0011"] == b"resurrected"
    kv.close()


def test_logdb_on_native_backend(tmp_path):
    """The full sharded LogDB stack runs on the native engine."""
    from dragonboat_tpu.logdb import open_logdb
    from dragonboat_tpu.wire import Bootstrap, Entry, State, Update

    db = open_logdb(str(tmp_path / "logdb"), shards=2, fsync=False)
    try:
        assert "nativekv" in db.name()
        db.save_bootstrap_info(1, 1, Bootstrap(addresses={1: "a"}, join=False))
        ents = [Entry(term=1, index=i, cmd=b"x" * 16) for i in range(1, 11)]
        ud = Update(
            cluster_id=1,
            node_id=1,
            state=State(term=1, vote=0, commit=5),
            entries_to_save=ents,
        )
        db.save_raft_state([ud])
        got, size = db.iterate_entries([], 0, 1, 1, 1, 11, 1 << 20)
        assert [e.index for e in got] == list(range(1, 11))
        assert size > 0
    finally:
        db.close()
    # restart: state survives the native engine's replay
    db = open_logdb(str(tmp_path / "logdb"), shards=2, fsync=False)
    try:
        got, _ = db.iterate_entries([], 0, 1, 1, 1, 11, 1 << 20)
        assert [e.index for e in got] == list(range(1, 11))
    finally:
        db.close()
