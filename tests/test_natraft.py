"""Unit tests for the native replication fast-lane core (natraft.cpp).

Wires one leader + two follower NatRaft instances by hand-shuttling the
transport frames they emit, with real NativeKV shards underneath, and
checks: replicate fan-out, follower append + durable ack ordering, quorum
commit, apply hand-off blobs, byte-exact WAL records vs the Python codec
(wire/codec.py + logdb/keys.py), eject state snapshots, heartbeats and
contact-loss events.  No Python raft objects are involved — this is the
native core in isolation; integration is covered by test_fastlane.py.
"""
from __future__ import annotations

import struct
import time

import pytest

from dragonboat_tpu import native
from dragonboat_tpu.native import natraft
from dragonboat_tpu.logdb import keys
from dragonboat_tpu.wire import Entry, EntryType, State
from dragonboat_tpu.wire.codec import (
    decode_entry_batch,
    decode_message_batch,
    decode_state,
    encode_entry,
)

pytestmark = pytest.mark.skipif(
    not natraft.available() or not native.available(),
    reason="native toolchain unavailable",
)

_HDR = struct.Struct(">HHQII")
CID = 7
HB_MS = 30
# the hand-driven pump below is far slower than a real transport, so the
# shared cluster uses a long election timeout; the contact-loss test builds
# its own cluster with a short one
ELECT_MS = 10_000


def split_frames(buf: bytes):
    """Parse concatenated transport frames -> list of payload bytes."""
    out = []
    pos = 0
    while pos < len(buf):
        magic, method, size, pcrc, hcrc = _HDR.unpack_from(buf, pos)
        assert magic == 0xAE7D and method == 100
        payload = buf[pos + _HDR.size : pos + _HDR.size + size]
        import zlib

        assert zlib.crc32(payload) == pcrc
        assert zlib.crc32(buf[pos : pos + _HDR.size - 4]) == hcrc
        out.append(payload)
        pos += _HDR.size + size
    return out


class Host:
    """One NatRaft + one NativeKV shard, with pump helpers."""

    def __init__(self, tmpdir, name, nid):
        self.nid = nid
        self.kv = native.NativeKV(str(tmpdir / f"kv-{name}"), fsync=False)
        self.nr = natraft.NatRaft(f"host{nid}:1", deployment_id=1)
        self.nr.set_shards([self.kv._h])
        self.nr.start()
        self.slots = {}  # peer node_id -> slot

    def connect(self, peers):
        for p in peers:
            self.slots[p] = self.nr.add_remote()

    def drain_to(self, hosts, timeout=1.0):
        """Pump frames to peers until quiet; returns leftover payloads."""
        leftovers = []
        deadline = time.time() + timeout
        quiet = 0
        while time.time() < deadline and quiet < 3:
            moved = False
            for pid, slot in self.slots.items():
                buf = self.nr.take_send(slot, timeout_ms=20)
                if buf:
                    moved = True
                    for payload in split_frames(buf):
                        n, left = hosts[pid].nr.ingest(payload)
                        if left is not None:
                            leftovers.append((pid, left))
            quiet = 0 if moved else quiet + 1
        return leftovers


@pytest.fixture()
def cluster(tmp_path):
    hosts = {
        1: Host(tmp_path, "a", 1),
        2: Host(tmp_path, "b", 2),
        3: Host(tmp_path, "c", 3),
    }
    for nid, h in hosts.items():
        h.connect([p for p in hosts if p != nid])
    # enroll: leader on 1, followers on 2/3; empty quiescent log
    peers = lambda h: [(p, h.slots[p], 5, 6) for p in sorted(h.slots)]
    assert hosts[1].nr.enroll(CID, 1, term=2, vote=1, leader_id=1,
                              is_leader=True, last_index=5, commit=5,
                              processed=5, log_first=6, prev_term=2,
                              shard=0, hb_period_ms=HB_MS,
                              elect_timeout_ms=ELECT_MS, term_commit_ok=True,
                              peers=peers(hosts[1]), tail=b"")
    for nid in (2, 3):
        h = hosts[nid]
        assert h.nr.enroll(CID, nid, term=2, vote=1, leader_id=1,
                           is_leader=False, last_index=5, commit=5,
                           processed=5, log_first=6, prev_term=2,
                           shard=0, hb_period_ms=HB_MS,
                           elect_timeout_ms=ELECT_MS, term_commit_ok=True,
                           peers=peers(h), tail=b"")
    yield hosts
    for h in hosts.values():
        h.nr.close()
        h.kv.close()


def pump(hosts, rounds=6):
    leftovers = []
    for _ in range(rounds):
        for h in hosts.values():
            leftovers.extend(h.drain_to(hosts, timeout=0.3))
    return leftovers


def collect_applies(h, timeout=1.0):
    spans = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = h.nr.next_apply(timeout_ms=50)
        if got is None:
            if spans:
                break
            continue
        spans.append(got)
    return spans


def test_propose_replicate_commit_apply(cluster):
    hosts = cluster
    idx = hosts[1].nr.propose(CID, key=11, client_id=0, series_id=0,
                              responded_to=0, etype=0, cmd=b"hello")
    assert idx == 6
    idx2 = hosts[1].nr.propose(CID, key=12, client_id=0, series_id=0,
                               responded_to=0, etype=0, cmd=b"world")
    assert idx2 == 7
    leftovers = pump(hosts)
    assert leftovers == []
    # leader applied span covers both entries
    spans = collect_applies(hosts[1])
    assert spans, "no apply spans on leader"
    cid, first, last, blob = spans[0]
    assert (cid, first) == (CID, 6)
    ents = decode_entry_batch(blob)
    assert [e.index for e in ents][0] == 6
    all_ents = [e for _, _, _, b in spans for e in decode_entry_batch(b)]
    assert [e.cmd for e in all_ents] == [b"hello", b"world"]
    assert all(e.term == 2 for e in all_ents)
    # followers apply after the commit broadcast
    for nid in (2, 3):
        fspans = collect_applies(hosts[nid])
        fents = [e for _, _, _, b in fspans for e in decode_entry_batch(b)]
        assert [e.cmd for e in fents] == [b"hello", b"world"]


def test_wal_records_byte_identical(cluster):
    hosts = cluster
    hosts[1].nr.propose(CID, key=33, client_id=4, series_id=9,
                        responded_to=3, etype=int(EntryType.ENCODED),
                        cmd=b"payload-bytes")
    pump(hosts)
    collect_applies(hosts[1])
    expect = encode_entry(Entry(
        term=2, index=6, type=EntryType.ENCODED, key=33, client_id=4,
        series_id=9, responded_to=3, cmd=b"payload-bytes",
    ))
    for nid in (1, 2, 3):
        kv = hosts[nid].kv
        got = kv.get(keys.entry_key(CID, nid, 6))
        assert got == expect, f"host {nid} entry record differs"
        mi = kv.get(keys.max_index_key(CID, nid))
        assert struct.unpack(">Q", mi)[0] == 6
        st_raw = kv.get(keys.state_key(CID, nid))
        st = decode_state(st_raw)
        assert st == State(term=2, vote=1, commit=6)


def test_eject_state_snapshot(cluster):
    hosts = cluster
    for i in range(3):
        hosts[1].nr.propose(CID, key=50 + i, client_id=0, series_id=0,
                            responded_to=0, etype=0, cmd=b"x%d" % i)
    pump(hosts)
    collect_applies(hosts[1])
    st = hosts[1].nr.eject(CID)
    assert st is not None
    assert st.term == 2 and st.vote == 1 and st.leader_id == 1
    assert st.last_index == 8 and st.commit == 8
    assert st.applied_handed == 8
    assert st.peers[2][0] == 8 and st.peers[3][0] == 8  # match
    assert not hosts[1].nr.active(CID)
    # double-eject reports unknown
    assert hosts[1].nr.eject(CID) is None
    # follower eject: its apply queue was never drained here, so the
    # committed entries come back in the eject blob, in order
    f = hosts[2].nr.eject(CID)
    assert f.commit == 8 and f.last_index == 8
    fents = decode_entry_batch(f.apply_blob)
    assert [e.index for e in fents] == [6, 7, 8]
    assert f.apply_first == 6


def test_eject_returns_unpumped_applies(cluster):
    hosts = cluster
    hosts[1].nr.propose(CID, key=1, client_id=0, series_id=0,
                        responded_to=0, etype=0, cmd=b"a")
    pump(hosts)
    # do NOT drain the apply queue; eject must hand the span back
    st = hosts[1].nr.eject(CID)
    ents = decode_entry_batch(st.apply_blob)
    assert [e.index for e in ents] == [6]
    assert st.apply_first == 6


def test_proposal_on_unknown_group_rejected(cluster):
    hosts = cluster
    assert hosts[1].nr.propose(999, 0, 0, 0, 0, 0, b"z") == 0
    # follower is not a leader: propose refused
    assert hosts[2].nr.propose(CID, 0, 0, 0, 0, 0, b"z") == 0


def test_heartbeats_and_contact_loss_event(tmp_path):
    elect_ms = 300
    hosts = {1: Host(tmp_path, "a", 1), 2: Host(tmp_path, "b", 2),
             3: Host(tmp_path, "c", 3)}
    for nid, h in hosts.items():
        h.connect([p for p in hosts if p != nid])
    peers = lambda h: [(p, h.slots[p], 5, 6) for p in sorted(h.slots)]
    for nid in (1, 2, 3):
        h = hosts[nid]
        assert h.nr.enroll(CID, nid, term=2, vote=1, leader_id=1,
                           is_leader=(nid == 1), last_index=5, commit=5,
                           processed=5, log_first=6, prev_term=2,
                           shard=0, hb_period_ms=HB_MS,
                           elect_timeout_ms=elect_ms, term_commit_ok=True,
                           peers=peers(h), tail=b"")
    try:
        # continuous pumping: heartbeats keep followers quiet
        deadline = time.time() + 3 * elect_ms / 1000
        while time.time() < deadline:
            for h in hosts.values():
                h.drain_to(hosts, timeout=0.05)
        assert hosts[2].nr.next_event(timeout_ms=10) is None
        assert hosts[2].nr.active(CID)
        # stop pumping the leader -> followers lose contact, raise events
        ev = None
        deadline = time.time() + 4 * elect_ms / 1000 + 2.0
        while time.time() < deadline and ev is None:
            ev = hosts[2].nr.next_event(timeout_ms=100)
        assert ev is not None
        cid, code = ev
        assert cid == CID and code == 1  # EV_CONTACT_LOST
        # the group is EJECTING now: fresh ingest goes leftover
        assert not hosts[2].nr.active(CID)
    finally:
        for h in hosts.values():
            h.nr.close()
            h.kv.close()


def test_foreign_term_message_goes_leftover(cluster):
    hosts = cluster
    from dragonboat_tpu.wire import Message, MessageBatch, MessageType
    from dragonboat_tpu.wire.codec import encode_message_batch

    m = Message(type=MessageType.REPLICATE, cluster_id=CID, from_=1, to=2,
                term=9, log_term=2, log_index=5, commit=5)
    payload = encode_message_batch(
        MessageBatch(requests=[m], deployment_id=1, source_address="x:1")
    )
    n, left = hosts[2].nr.ingest(payload)
    assert n == 0 and left is not None
    got = decode_message_batch(left)
    assert len(got.requests) == 1
    assert got.requests[0].term == 9
    assert got.requests[0].type == MessageType.REPLICATE
    # group flipped to EJECTING + event emitted
    ev = hosts[2].nr.next_event(timeout_ms=500)
    assert ev == (CID, 5)  # EV_TERM_MISMATCH


def test_non_fast_message_untouched(cluster):
    hosts = cluster
    from dragonboat_tpu.wire import Message, MessageBatch, MessageType
    from dragonboat_tpu.wire.codec import encode_message_batch

    m = Message(type=MessageType.REQUEST_VOTE, cluster_id=CID, from_=3, to=2,
                term=3, log_term=2, log_index=5)
    payload = encode_message_batch(
        MessageBatch(requests=[m], deployment_id=1, source_address="x:1")
    )
    n, left = hosts[2].nr.ingest(payload)
    assert n == 0
    got = decode_message_batch(left)
    assert got.requests[0].type == MessageType.REQUEST_VOTE
    assert got.deployment_id == 1
    assert got.source_address == "x:1"


def test_throughput_smoke(cluster):
    """Sanity: the native loop sustains a pipelined window without loss."""
    hosts = cluster
    total = 500
    done = 0
    for i in range(total):
        assert hosts[1].nr.propose(CID, key=100 + i, client_id=0, series_id=0,
                                   responded_to=0, etype=0, cmd=b"p") > 0
        if i % 50 == 49:
            pump(hosts, rounds=1)
    pump(hosts)
    deadline = time.time() + 5
    seen = set()
    while done < total and time.time() < deadline:
        got = hosts[1].nr.next_apply(timeout_ms=100)
        if got is None:
            pump(hosts, rounds=1)
            continue
        _, first, last, blob = got
        ents = decode_entry_batch(blob)
        assert len(ents) == last - first + 1
        for e in ents:
            assert e.index not in seen
            seen.add(e.index)
        done += len(ents)
    assert done == total
    st = hosts[1].nr.stats()
    assert st["commits_advanced"] > 0
