"""Native-unit ReadIndex protocol tests against a bare NatRaft engine.

These pin the wire-level contract of the follower-forwarded ReadIndex
path (natraft twins of ``handle_leader_read_index`` raft.py:1095,
``handle_follower_read_index`` raft.py:1258,
``handle_follower_read_index_resp`` raft.py:1271) without the full
NodeHost stack: enroll one group as leader, inject encoded frames via
``natr_ingest``, and observe the readyq / outbound queues directly —
no sockets; the only wait is the bounded negative-assertion window in
the observer quorum test (the commit tally runs on the round thread).
"""
from __future__ import annotations

import tempfile
import time

import pytest

from dragonboat_tpu.native import NativeKV, natraft
from dragonboat_tpu.wire import Message, MessageBatch, MessageType as MT
from dragonboat_tpu.wire.codec import encode_message_batch, decode_message_batch

pytestmark = pytest.mark.skipif(
    not natraft.available(), reason="libnatraft unavailable"
)

DEP = 7
CID = 9


def _leader_engine():
    kv = NativeKV(tempfile.mkdtemp())
    nat = natraft.NatRaft("127.0.0.1:1", deployment_id=DEP, bin_ver=1)
    nat.set_shards([kv._h])
    nat.add_remote()  # slot 0 -> peer 1
    nat.add_remote()  # slot 1 -> peer 3
    nat.start()
    assert nat.enroll(
        cluster_id=CID, node_id=2, term=2, vote=2, leader_id=2,
        is_leader=True, last_index=3, commit=3, processed=3, log_first=4,
        prev_term=2, shard=0, hb_period_ms=50, elect_timeout_ms=1000,
        term_commit_ok=True,
        peers=[(1, 0, 3, 4), (3, 1, 3, 4)], tail=b"",
    )
    return nat, kv


def _batch(*msgs):
    return encode_message_batch(MessageBatch(
        requests=list(msgs), deployment_id=DEP,
        source_address="127.0.0.1:9",
    ))


def _echo(from_, low, high):
    return Message(type=MT.HEARTBEAT_RESP, to=2, from_=from_,
                   cluster_id=CID, term=2, hint=low, hint_high=high)


def _drain_sends(nat, slot, n=20):
    out = []
    for _ in range(n):
        b = nat.take_send(slot, 50)
        if b is None:
            break
        out.append(bytes(b))
    return out


def _sent_types(nat, slot):
    """take_send returns framed wire bytes (tcp.py layout:
    magic(2) method(2) size(8) payload_crc(4) header_crc(4) payload);
    one buffer may carry several frames."""
    import struct

    types = []
    for raw in _drain_sends(nat, slot):
        pos = 0
        while pos + 20 <= len(raw):
            magic, _method, size = struct.unpack_from(">HHQ", raw, pos)
            assert magic == 0xAE7D, hex(magic)
            payload = raw[pos + 20:pos + 20 + size]
            mb = decode_message_batch(payload)
            types.extend((m.type, m) for m in mb.requests)
            pos += 20 + size
    return types


def test_forwarded_read_confirms_to_origin():
    """A peer's READ_INDEX registers an origin-tagged ctx; the echo
    quorum answers the ORIGIN with READ_INDEX_RESP (not the local
    readyq), directly — not behind the fsync-gated ack queue."""
    nat, _kv = _leader_engine()
    try:
        n, left = nat.ingest(_batch(Message(
            type=MT.READ_INDEX, to=2, from_=1, cluster_id=CID, term=2,
            hint=1234, hint_high=5678,
        )))
        assert (n, left) == (1, None)
        _drain_sends(nat, 0)  # hinted heartbeats out to peer 1
        _drain_sends(nat, 1)
        # echo quorum: leader (self) + one peer suffices for 3 voters
        n, left = nat.ingest(_batch(_echo(1, 1234, 5678)))
        assert (n, left) == (1, None)
        # the confirmation must NOT land in the local readyq...
        assert nat.next_read(100) is None
        # ...but go out to the origin as READ_INDEX_RESP with the index
        sent = _sent_types(nat, 0)
        resps = [m for t, m in sent if t == MT.READ_INDEX_RESP]
        assert resps, [t.name for t, _ in sent]
        assert resps[0].log_index == 3
        assert resps[0].hint == 1234 and resps[0].hint_high == 5678
    finally:
        nat.stop()


def test_termless_scalar_read_index_not_swallowed():
    """Scalar raft sends READ_INDEX with term 0 (a termless REQUEST —
    is_request_message raft.py:73); the native stale-term gate must not
    swallow it (regression: mixed scalar-follower/native-leader reads
    stranded until client timeout)."""
    nat, _kv = _leader_engine()
    try:
        n, left = nat.ingest(_batch(Message(
            type=MT.READ_INDEX, to=2, from_=1, cluster_id=CID, term=0,
            hint=77, hint_high=88,
        )))
        assert (n, left) == (1, None)
        _drain_sends(nat, 0)
        _drain_sends(nat, 1)
        nat.ingest(_batch(_echo(1, 77, 88)))
        resps = [m for t, m in _sent_types(nat, 0)
                 if t == MT.READ_INDEX_RESP]
        assert resps and resps[0].log_index == 3
    finally:
        nat.stop()


def test_local_read_still_served_via_readyq():
    nat, _kv = _leader_engine()
    try:
        assert nat.read_index(CID, 42, 43) == 3
        _drain_sends(nat, 0)
        _drain_sends(nat, 1)
        nat.ingest(_batch(_echo(3, 42, 43)))
        got = nat.next_read(500)
        assert got == (CID, 42, 43, 3)
    finally:
        nat.stop()


def _observer_engine():
    """Leader with ONE voting peer (1) and ONE observer (3): quorum 2 of
    the 2 voters (self + peer 1)."""
    kv = NativeKV(tempfile.mkdtemp())
    nat = natraft.NatRaft("127.0.0.1:1", deployment_id=DEP, bin_ver=1)
    nat.set_shards([kv._h])
    nat.add_remote()
    nat.add_remote()
    nat.start()
    assert nat.enroll(
        cluster_id=CID, node_id=2, term=2, vote=2, leader_id=2,
        is_leader=True, last_index=3, commit=3, processed=3, log_first=4,
        prev_term=2, shard=0, hb_period_ms=50, elect_timeout_ms=1000,
        term_commit_ok=True,
        peers=[(1, 0, 3, 4, True), (3, 1, 3, 4, False)], tail=b"",
    )
    return nat, kv


def _resp(from_, idx):
    return Message(type=MT.REPLICATE_RESP, to=2, from_=from_,
                   cluster_id=CID, term=2, log_index=idx)


def test_observer_ack_carries_no_commit_weight():
    """An observer's REPLICATE_RESP advances its progress (flow control)
    but never the commit index; a voter's ack commits (tally counts only
    voting members — reference nonVoting semantics)."""
    nat, _kv = _observer_engine()
    try:
        idx = nat.propose(CID, key=1, client_id=0, series_id=0,
                          responded_to=0, etype=0, cmd=b"")
        assert idx == 4
        # observer ack: commit must stay at 3 (read_index reports commit).
        # Negative assertion is necessarily time-bounded: the commit tally
        # runs on the round thread, so give it a bounded window to
        # (wrongly) commit before checking — the POSITIVE half below then
        # re-checks that commit was still 3 at voter-ack time
        nat.ingest(_batch(_resp(3, 4)))
        time.sleep(0.5)
        assert nat.read_index(CID, 1, 2) == 3, (
            "observer ack advanced the commit index"
        )
        # voter ack: commit advances to 4 once the leader's fsync covers it
        nat.ingest(_batch(_resp(1, 4)))
        deadline = time.time() + 5.0
        got = 0
        while time.time() < deadline:
            got = nat.read_index(CID, 3, 4)
            if got == 4:
                break
            time.sleep(0.01)
        assert got == 4, f"voter quorum did not commit (commit={got})"
    finally:
        nat.stop()


def test_observer_echo_confirms_no_read():
    """ReadIndex confirmation needs a VOTING echo quorum; the observer's
    heartbeat echo proves nothing (readindex.go confirm semantics)."""
    nat, _kv = _observer_engine()
    try:
        assert nat.read_index(CID, 42, 43) == 3
        _drain_sends(nat, 0)
        _drain_sends(nat, 1)
        nat.ingest(_batch(_echo(3, 42, 43)))  # observer echo
        assert nat.next_read(300) is None, "observer echo confirmed a read"
        nat.ingest(_batch(_echo(1, 42, 43)))  # voter echo -> quorum 2/2
        assert nat.next_read(500) == (CID, 42, 43, 3)
    finally:
        nat.stop()


def test_witness_gets_metadata_entries_and_counts_in_quorum():
    """A witness peer (role 2) receives METADATA-only twins of each
    entry (make_metadata_entries raft.py:104) but its ack IS quorum
    weight — reference witness semantics."""
    from dragonboat_tpu.wire import EntryType

    kv = NativeKV(tempfile.mkdtemp())
    nat = natraft.NatRaft("127.0.0.1:1", deployment_id=DEP, bin_ver=1)
    nat.set_shards([kv._h])
    nat.add_remote()
    nat.add_remote()
    nat.start()
    assert nat.enroll(
        cluster_id=CID, node_id=2, term=2, vote=2, leader_id=2,
        is_leader=True, last_index=3, commit=3, processed=3, log_first=4,
        prev_term=2, shard=0, hb_period_ms=50, elect_timeout_ms=1000,
        term_commit_ok=True,
        peers=[(1, 0, 3, 4, 1), (3, 1, 3, 4, 2)], tail=b"",
    )
    try:
        idx = nat.propose(CID, key=1, client_id=0, series_id=0,
                          responded_to=0, etype=0, cmd=b"payload-bytes")
        assert idx == 4

        # voter (slot 0) gets the real entry; witness (slot 1) metadata
        def entries_on(slot):
            out = []
            for t, m in _sent_types(nat, slot):
                if t == MT.REPLICATE and m.entries:
                    out.extend(m.entries)
            return out

        deadline = time.time() + 5
        ve = we = None
        while time.time() < deadline and not (ve and we):
            ve = ve or (entries_on(0) or None)
            we = we or (entries_on(1) or None)
            time.sleep(0.02)
        assert ve and we, (ve, we)
        assert ve[0].index == 4 and ve[0].cmd, "voter entry lost payload"
        assert we[0].index == 4 and we[0].term == ve[0].term
        assert we[0].type == EntryType.METADATA and not we[0].cmd, (
            "witness did not get a metadata twin"
        )
        # witness ack counts toward commit (3 voting members: self +
        # witness = quorum 2)
        nat.ingest(_batch(_resp(3, 4)))
        deadline = time.time() + 5.0
        got = 0
        while time.time() < deadline:
            got = nat.read_index(CID, 9, 10)
            if got == 4:
                break
            time.sleep(0.01)
        assert got == 4, f"witness ack did not count toward commit ({got})"
    finally:
        nat.stop()
