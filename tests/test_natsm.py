"""Native C-ABI state machine (natsm.cpp + natsm.py) tests.

Covers the adapter unit contract, and the fast-lane integration where
enrolled groups apply committed entries natively (natraft apply_native)
with only batched completion records crossing the GIL: client futures
still complete, lookups see the writes, ejects hand over cleanly (the
shared instance serves both planes), and replicas converge to identical
native hashes through kill/restart churn.
"""
from __future__ import annotations

import io
import socket

from tests import loadwait
import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.native import natraft, natsm
from dragonboat_tpu.native.natsm import NativeKVStateMachine

# heavy multi-NodeHost tests serialize on one xdist worker
# (--dist loadgroup): 4-way-parallel multiprocess clusters
# starve each other on an 8-vCPU box
pytestmark = [pytest.mark.skipif(
    not (natraft.available() and natsm.available()),
    reason="native libraries unavailable",
), pytest.mark.xdist_group("heavy-multiprocess")]

RTT = 20
CID = 41


# ------------------------------------------------------------------- unit


def test_adapter_roundtrip():
    sm = NativeKVStateMachine(1, 1)
    try:
        assert sm.update(b"a=1").value == 1
        assert sm.update(b"b=2").value == 2
        assert sm.update(b"a=3").value == 2  # overwrite: size unchanged
        assert sm.lookup("a") == "3"
        assert sm.lookup("b") == "2"
        assert sm.lookup("missing") is None
        h = sm.get_hash()
        buf = io.BytesIO()
        sm.save_snapshot(buf, None, None)
        sm2 = NativeKVStateMachine(1, 2)
        try:
            buf.seek(0)
            sm2.recover_from_snapshot(buf, None, None)
            assert sm2.get_hash() == h
            assert sm2.lookup("a") == "3"
        finally:
            sm2.close()
    finally:
        sm.close()


def test_adapter_matches_python_dict_sm():
    """Same command sequence -> same observable state as the dict SM."""
    import random

    sm = NativeKVStateMachine(1, 1)
    ref = {}
    rng = random.Random(7)
    try:
        for _ in range(500):
            k = f"k{rng.randrange(40)}"
            v = f"v{rng.randrange(1000)}"
            r = sm.update(f"{k}={v}".encode())
            ref[k] = v
            assert r.value == len(ref)
        for k, v in ref.items():
            assert sm.lookup(k) == v
    finally:
        sm.close()


# ------------------------------------------------------- fast-lane cluster


def _ports(n):
    return loadwait.ports(n)


def _mk(i, addrs, tmp_path, sms, snapshot_entries=0):
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / f"nh{i}"),
            rtt_millisecond=RTT,
            raft_address=addrs[i],
            expert=ExpertConfig(fast_lane=True, logdb_shards=2),
        )
    )
    assert nh.fastlane is not None and nh.fastlane.enabled

    def create(cluster_id, node_id):
        sm = NativeKVStateMachine(cluster_id, node_id)
        sms[i] = sm
        return sm

    nh.start_cluster(
        addrs, False, create,
        Config(cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
               snapshot_entries=snapshot_entries, compaction_overhead=5),
    )
    return nh


def _cluster(tmp_path, sms):
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = {i: _mk(i, addrs, tmp_path, sms) for i in addrs}
    return nhs, addrs


def _leader(nhs, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs.values():
            try:
                lid, ok = nh.get_leader_id(CID)
                if ok and lid in nhs:
                    return lid, nhs[lid]
            except Exception:
                pass
        time.sleep(0.05)
    raise TimeoutError("no leader")


def _wait_native_applies(nhs, timeout=20.0):
    """True once some rank reports native-SM attach + enrolled lane."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs.values():
            node = nh.get_node(CID)
            if node is not None and node.fast_lane and node._natsm_attached:
                return True
        time.sleep(0.05)
    return False


def _converged_hashes(sms, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        hs = {i: sm.get_hash() for i, sm in sms.items()}
        if len(set(hs.values())) == 1:
            return hs
        time.sleep(0.1)
    raise AssertionError(f"native hashes diverged: {hs}")


def test_native_apply_end_to_end(tmp_path):
    """Writes complete through the native apply path; lookups and
    cross-replica hashes agree; dropped spans stay zero."""
    sms = {}
    nhs, addrs = _cluster(tmp_path, sms)
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        s = leader.get_noop_session(CID)
        # first writes may ride the scalar plane (pre-enrollment)
        pend = [
            leader.propose(s, f"k{j}=v{j}".encode(), timeout=60.0)
            for j in range(200)
        ]
        for rs in pend:
            assert rs.wait(120.0).completed
        assert _wait_native_applies(nhs), "native SM never attached"
        # these complete through the NATIVE apply + completion pump
        pend = [
            leader.propose(s, f"n{j}=w{j}".encode(), timeout=60.0)
            for j in range(300)
        ]
        for rs in pend:
            assert rs.wait(120.0).completed
        assert leader.sync_read(CID, "n299", timeout=10.0) == "w299"
        _converged_hashes(sms)
        for i, nh in nhs.items():
            assert nh.fastlane.dropped_spans == 0
    finally:
        for nh in nhs.values():
            nh.stop()


def test_native_apply_eject_and_snapshot(tmp_path):
    """Snapshot triggers (periodic) force ejects mid-native-stream: the
    scalar plane resumes on the SAME instance, snapshots serialize through
    the C ABI, and the group re-enrolls and re-attaches."""
    sms = {}
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = {
        i: _mk(i, addrs, tmp_path, sms, snapshot_entries=40) for i in addrs
    }
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        s = leader.get_noop_session(CID)
        for j in range(150):  # crosses several snapshot boundaries
            rs = leader.propose(s, f"s{j}=x{j}".encode(), timeout=60.0)
            assert rs.wait(120.0).completed
        assert leader.sync_read(CID, "s149", timeout=10.0) == "x149"
        _converged_hashes(sms)
        # the lane must still be usable after the snapshot eject cycles
        assert _wait_native_applies(nhs, timeout=30.0)
    finally:
        for nh in nhs.values():
            nh.stop()


def test_native_apply_leader_kill_failover(tmp_path):
    sms = {}
    nhs, addrs = _cluster(tmp_path, sms)
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        s = leader.get_noop_session(CID)
        for j in range(100):
            rs = leader.propose(s, f"a{j}=b{j}".encode(), timeout=60.0)
            assert rs.wait(120.0).completed
        assert _wait_native_applies(nhs)
        leader.stop()
        del nhs[lid]
        new_lid, new_leader = _leader(nhs, timeout=90.0)
        assert new_lid != lid
        s2 = new_leader.get_noop_session(CID)
        for j in range(50):
            rs = new_leader.propose(s2, f"c{j}=d{j}".encode(), timeout=60.0)
            assert rs.wait(120.0).completed
        assert new_leader.sync_read(CID, "c49", timeout=20.0) == "d49"
        # restart the killed rank against its dirs; all three converge
        sms2 = dict(sms)
        nhs[lid] = _mk(lid, addrs, tmp_path, sms2)
        deadline = time.time() + 90
        while time.time() < deadline:
            hs = {i: sm.get_hash() for i, sm in sms2.items()}
            if len(set(hs.values())) == 1:
                break
            time.sleep(0.2)
        assert len(set(hs.values())) == 1, hs
    finally:
        for nh in nhs.values():
            try:
                nh.stop()
            except Exception:
                pass


# ------------------------------------------------------- native sessions


def test_native_session_manager_differential():
    """NativeSessionManager mirrors the Python SessionManager op-for-op:
    LRU registration/eviction, dedup history, clear_to GC — with BYTE-
    identical serialization (snapshots interop across planes) and equal
    hashes, checked after every op."""
    import random

    from dragonboat_tpu.native.natsm import NativeSessionManager
    from dragonboat_tpu.rsm.session import SessionManager
    from dragonboat_tpu.statemachine import Result

    user = NativeKVStateMachine(1, 1)
    try:
        nat = NativeSessionManager(user)
        py = SessionManager()
        rng = random.Random(77)
        for step in range(400):
            cid = rng.randrange(1, 40)
            op = rng.randrange(6)
            if op == 0:
                assert (
                    nat.register_client_id(cid).value
                    == py.register_client_id(cid).value
                )
            elif op == 1:
                assert (
                    nat.unregister_client_id(cid).value
                    == py.unregister_client_id(cid).value
                )
            else:
                a = nat.client_registered(cid)
                b = py.client_registered(cid)
                assert (a is None) == (b is None)
                if a is None:
                    continue
                sid = rng.randrange(1, 9)
                assert a.has_responded(sid) == b.has_responded(sid)
                ra, oka = a.get_response(sid)
                rb, okb = b.get_response(sid)
                assert oka == okb
                if oka:
                    assert ra.value == rb.value and ra.data == rb.data
                elif not a.has_responded(sid):
                    v = rng.randrange(1000)
                    a.add_response(sid, Result(value=v))
                    b.add_response(sid, Result(value=v))
                if rng.random() < 0.25:
                    ct = rng.randrange(1, 7)
                    a.clear_to(ct)
                    b.clear_to(ct)
            assert len(nat) == len(py)
            assert nat.save() == py.save(), f"image diverged at step {step}"
        assert nat.hash() == py.hash()
        # cross-plane snapshot interop, both directions
        img = py.save()
        nat.recover_image(img)
        assert nat.save() == img
        py2 = SessionManager.load(nat.save())
        assert py2.save() == nat.save()
    finally:
        user.close()


def test_native_session_lru_eviction_parity():
    """Eviction at the LRU cap replays identically native vs Python."""
    from dragonboat_tpu.native.natsm import NativeSessionManager
    from dragonboat_tpu.rsm.session import SessionManager

    user = NativeKVStateMachine(1, 1)
    try:
        nat = NativeSessionManager(user)
        py = SessionManager()
        cap = py._max
        for cid in range(1, cap + 10):
            nat.register_client_id(cid)
            py.register_client_id(cid)
        # touch a survivor so LRU order differs from insertion order
        assert nat.client_registered(cap // 2 + 8) is not None
        assert py.client_registered(cap // 2 + 8) is not None
        for cid in range(cap + 10, cap + 20):
            nat.register_client_id(cid)
            py.register_client_id(cid)
        assert len(nat) == len(py) == cap
        assert nat.save() == py.save()
    finally:
        user.close()


def test_native_session_exactly_once_end_to_end(tmp_path):
    """Session-managed clients stay on the native apply path: register,
    dedup (a re-proposed series returns the cached result and applies the
    command ONCE), responded_to GC, and unregister all complete natively
    — zero sm-punt ejects, session hashes equal across replicas."""
    sms = {}
    nhs, addrs = _cluster(tmp_path, sms)
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        # make sure the lane is up before session traffic (otherwise the
        # scalar plane serves it — also correct, but not what we test)
        s0 = leader.get_noop_session(CID)
        for j in range(20):
            assert leader.propose(
                s0, f"w{j}=v{j}".encode(), timeout=60.0
            ).wait(120.0).completed
        assert _wait_native_applies(nhs)

        sess = leader.sync_get_session(CID, timeout=60.0)
        first = leader.propose(sess, b"k=1", timeout=60.0)
        r1 = first.wait(120.0)
        assert r1.completed
        # duplicate retry of the SAME series id: cached result, no
        # re-apply — proposed with a DIFFERENT command so a re-apply
        # would be visible in the KV
        dup = leader.propose(sess, b"leaked=1", timeout=60.0)
        r2 = dup.wait(120.0)
        assert r2.completed
        assert r2.result.value == r1.result.value
        assert leader.sync_read(CID, "leaked", timeout=20.0) is None
        sess.proposal_completed()
        # next series: applies; the responded_to watermark GCs the history
        nxt = leader.propose(sess, b"k2=2", timeout=60.0)
        r3 = nxt.wait(120.0)
        assert r3.completed
        sess.proposal_completed()
        assert leader.sync_read(CID, "k", timeout=20.0) == "1"
        assert leader.sync_read(CID, "k2", timeout=20.0) == "2"
        leader.sync_close_session(sess, timeout=60.0)

        # the lane never punted: no sm-punt ejects anywhere, the leader
        # is still enrolled, and the session stores converged
        # (register/apply/unregister replicated)
        assert leader.get_node(CID).fast_lane
        for nh in nhs.values():
            st = nh.fastlane.stats()
            assert st["eject_reasons"].get("sm-punt", 0) == 0, st
        deadline = time.time() + 60
        while time.time() < deadline:
            hs = {
                i: nh.get_node(CID).sm.get_session_hash()
                for i, nh in nhs.items()
            }
            if len(set(hs.values())) == 1:
                break
            time.sleep(0.1)
        assert len(set(hs.values())) == 1, hs
        sizes = {i: len(nh.get_node(CID).sm.sessions) for i, nh in nhs.items()}
        assert set(sizes.values()) == {0}, sizes  # closed session evicted
    finally:
        for nh in nhs.values():
            nh.stop()


def test_periodic_snapshot_triggers_while_enrolled(tmp_path):
    """The periodic snapshot trigger rides the scalar update path, which
    is idle during native steady state — this pins the completion-pump
    trigger: sustained native-applied load must advance the snapshot
    index (bounding the log) with NO manual snapshot request, and —
    since the no-eject capture path (natr_capture_sm) — with ZERO
    snapshot-due ejects: the group never leaves the lane."""
    sms = {}
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = {i: _mk(i, addrs, tmp_path, sms, snapshot_entries=64)
           for i in addrs}
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        s = leader.get_noop_session(CID)
        # warm the lane, then record the snapshot index once enrolled
        for j in range(30):
            assert leader.propose(
                s, f"a{j}=b{j}".encode(), timeout=60.0
            ).wait(120.0).completed
        assert _wait_native_applies(nhs)
        node = leader.get_node(CID)
        si0 = node.sm.get_snapshot_index()
        # several snapshot_entries worth of writes through the native lane
        for j in range(300):
            assert leader.propose(
                s, f"k{j % 50}=v{j}".encode(), timeout=60.0
            ).wait(120.0).completed
        deadline = time.time() + 60
        while time.time() < deadline:
            if node.sm.get_snapshot_index() > si0:
                break
            time.sleep(0.1)
        assert node.sm.get_snapshot_index() > si0, (
            "periodic snapshot never fired under enrolled load"
        )
        # the native capture path snapshots IN PLACE: no snapshot-due
        # eject fired and the group never left the lane
        assert leader.fastlane.stats()["eject_reasons"].get(
            "snapshot-due", 0
        ) == 0
        assert node.fast_lane, "group left the lane for a snapshot"
        _converged_hashes(sms)
    finally:
        for nh in nhs.values():
            nh.stop()


def test_capture_snapshot_recovers_on_restart(tmp_path):
    """A snapshot produced by the no-eject native capture path
    (natr_capture_sm -> save_from_capture) must be a first-class
    snapshot: after a full-cluster stop, a cold restart recovers the KV
    AND the exactly-once session store from it (plus log replay), and
    the replicas converge on the pre-restart state.  This pins the
    format symmetry between _CaptureSavable's write and the shared
    adapter recover path."""
    from dragonboat_tpu.client import Session

    sms = {}
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = {i: _mk(i, addrs, tmp_path, sms, snapshot_entries=32)
           for i in addrs}
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        # a REGISTERED session: its dedup state must survive the restart
        # through the captured session image
        sess = leader.sync_get_session(CID, timeout=30.0)
        for j in range(80):
            rs = leader.propose(sess, f"k{j}=v{j}".encode(), timeout=60.0)
            assert rs.wait(120.0).completed
            if j != 79:
                # the LAST series id stays un-acked: its cached response
                # must survive the restart for the dedup assert below
                sess.proposal_completed()
        node = leader.get_node(CID)
        deadline = time.time() + 60
        while time.time() < deadline and node.sm.get_snapshot_index() == 0:
            time.sleep(0.1)
        si = node.sm.get_snapshot_index()
        assert si > 0, "no capture snapshot fired"
        assert leader.fastlane.stats()["eject_reasons"].get(
            "snapshot-due", 0
        ) == 0
        _converged_hashes(sms)
        pre_hash = {i: sms[i].get_hash() for i in addrs}
    finally:
        for nh in nhs.values():
            nh.stop()

    # ---- cold restart over the same dirs: recovery runs from the
    # captured snapshot + log tail ----
    sms2 = {}
    nhs = {i: _mk(i, addrs, tmp_path, sms2, snapshot_entries=32)
           for i in addrs}
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        _converged_hashes(sms2)
        v = leader.sync_read(CID, "k79", timeout=60.0)
        assert v == "v79"
        assert sms2[lid].get_hash() == next(iter(pre_hash.values()))
        # the recovered session store still dedups: retrying the
        # pre-restart session's un-acked series id (with DIFFERENT
        # bytes) must return the cached response, not re-apply
        rs = leader.propose(sess, b"k79=CLOBBER", timeout=60.0)
        assert rs.wait(120.0).completed
        assert leader.sync_read(CID, "k79", timeout=60.0) == "v79"
        assert sms2[lid].get_hash() == next(iter(pre_hash.values()))
    finally:
        for nh in nhs.values():
            nh.stop()


def test_cached_response_payload_completes_natively(tmp_path):
    """A cached session response that carries DATA bytes (a history entry
    from a Python-era apply whose Result had a payload — e.g. imported
    with the session image at attach) completes through the native path
    via the completion payload side-channel instead of ejecting the
    group (round-4: one sm-punt eject per such retry)."""
    from dragonboat_tpu.client import Session

    sms = {}
    nhs, addrs = _cluster(tmp_path, sms)
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        s0 = leader.get_noop_session(CID)
        for j in range(20):
            assert leader.propose(
                s0, f"w{j}=v{j}".encode(), timeout=60.0
            ).wait(120.0).completed
        assert _wait_native_applies(nhs)

        sess = leader.sync_get_session(CID, timeout=60.0)
        assert leader.propose(sess, b"k=1", timeout=60.0).wait(120.0).completed
        sess.proposal_completed()
        # inject a payload-bearing cached response at a FUTURE series id
        # on every replica's shared native store (the deterministic twin
        # of a session image whose history carries Result.data bytes)
        future_sid = sess.series_id + 3
        payload = b"cached-data-bytes" * 3
        from dragonboat_tpu.native import natsm as natsm_mod

        lib = natsm_mod._load()
        for i, nh in nhs.items():
            sm = sms[i]
            lib.natsm_sess_add_response(
                sm.natsm_sess_handle, sess.client_id, future_sid,
                7777, payload, len(payload),
            )
        # the client "retries" that series: the native dedup finds the
        # cached payload and the future completes WITH the data
        retry = Session(
            cluster_id=CID, client_id=sess.client_id, series_id=future_sid,
        )
        r = leader.propose(retry, b"ignored=1", timeout=60.0).wait(120.0)
        assert r.completed
        assert r.result.value == 7777
        assert r.result.data == payload
        # no re-apply, no punt, still enrolled
        assert leader.sync_read(CID, "ignored", timeout=20.0) is None
        assert leader.get_node(CID).fast_lane
        for nh in nhs.values():
            st = nh.fastlane.stats()
            assert st["eject_reasons"].get("sm-punt", 0) == 0, st
    finally:
        for nh in nhs.values():
            nh.stop()
