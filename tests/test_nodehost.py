"""End-to-end multi-replica NodeHost tests.

Reference model: ``nodehost_test.go`` — several NodeHosts in one process,
wired through the in-memory chan transport (the memfs test build's setup),
exercising propose / linearizable read / membership / snapshot / restart.
"""
import os
import time

import pytest

from tests import loadwait

from dragonboat_tpu import (
    Config,
    IStateMachine,
    NodeHost,
    NodeHostConfig,
    Result,
)
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT_MS = 5


class KVSM(IStateMachine):
    """cmd ``b"k=v"`` sets, lookup returns the value."""

    def __init__(self, cluster_id, node_id):
        self.kv = {}
        self.count = 0

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = repr(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import ast

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(ast.literal_eval(r.read(n).decode()))
        self.count = len(self.kv)


def make_nodehost(addr, router, tmpdir=None, **cfg_kw):
    def rpc_factory(src, rh, ch):
        return ChanTransport(src, rh, ch, router=router)

    nhc = NodeHostConfig(
        node_host_dir=tmpdir or ":memory:",
        rtt_millisecond=RTT_MS,
        raft_address=addr,
        raft_rpc_factory=rpc_factory,
        **cfg_kw,
    )
    return NodeHost(nhc)


def group_config(cluster_id, node_id, **kw):
    defaults = dict(
        cluster_id=cluster_id,
        node_id=node_id,
        election_rtt=10,
        heartbeat_rtt=1,
        check_quorum=False,
        snapshot_entries=0,
    )
    defaults.update(kw)
    return Config(**defaults)


def wait_for_leader(nhs, cluster_id, timeout=10.0):
    # load-scaled deadline (tests/loadwait.py): election timing under a
    # full tier-1 sweep on 1-2 vCPUs stretches far past the idle-box
    # margin — the r07/r11 leadership-timing flake class
    from tests.loadwait import scaled

    deadline = time.time() + scaled(timeout)
    while time.time() < deadline:
        for nh in nhs:
            try:
                lid, ok = nh.get_leader_id(cluster_id)
                if ok:
                    return lid
            except Exception:
                pass
        time.sleep(0.02)
    raise AssertionError("no leader elected")


@pytest.fixture
def cluster3():
    router = ChanRouter()
    addrs = {i: f"nh{i}:1" for i in (1, 2, 3)}
    nhs = [make_nodehost(addrs[i], router) for i in (1, 2, 3)]
    sms = {}

    def create_sm_for(nh_idx):
        def create(cluster_id, node_id):
            sm = KVSM(cluster_id, node_id)
            sms[node_id] = sm
            return sm

        return create

    for i, nh in enumerate(nhs, start=1):
        nh.start_cluster(addrs, False, create_sm_for(i), group_config(100, i))
    yield nhs, sms, addrs, router
    for nh in nhs:
        nh.stop()


def test_single_replica_propose_and_read():
    router = ChanRouter()
    nh = make_nodehost("solo:1", router)
    try:
        nh.start_cluster(
            {1: "solo:1"}, False,
            lambda c, n: KVSM(c, n), group_config(5, 1),
        )
        wait_for_leader([nh], 5)
        s = nh.get_noop_session(5)
        r = nh.sync_propose(s, b"a=1", timeout=loadwait.scaled(5.0))
        assert r.value == 1
        assert nh.sync_read(5, "a", timeout=loadwait.scaled(5.0)) == "1"
        assert nh.stale_read(5, "a") == "1"
    finally:
        nh.stop()


def test_three_replicas_propose_read(cluster3):
    nhs, sms, addrs, _ = cluster3
    wait_for_leader(nhs, 100)
    s = nhs[0].get_noop_session(100)
    for i in range(10):
        nhs[0].sync_propose(s, f"k{i}=v{i}".encode(), timeout=loadwait.scaled(5.0))
    # linearizable read from every replica
    for nh in nhs:
        assert nh.sync_read(100, "k9", timeout=loadwait.scaled(5.0)) == "v9"
    # all replicas converge to the same state (load-scaled poll: the
    # raw 0.3s nap lost this assert on loaded sweeps)
    loadwait.wait_until(
        lambda: sms[1].kv == sms[2].kv == sms[3].kv, 5.0,
        what="replica convergence",
    )


def test_propose_on_follower_forwards_to_leader(cluster3):
    nhs, sms, addrs, _ = cluster3
    lid = wait_for_leader(nhs, 100)
    follower_nh = nhs[0 if lid != 1 else 1]
    s = follower_nh.get_noop_session(100)
    r = follower_nh.sync_propose(s, b"fwd=yes", timeout=loadwait.scaled(5.0))
    assert r.value >= 1
    assert follower_nh.sync_read(100, "fwd", timeout=loadwait.scaled(5.0)) == "yes"


def test_session_exactly_once(cluster3):
    from dragonboat_tpu.requests import RejectedError, TimeoutError_

    nhs, sms, addrs, _ = cluster3
    wait_for_leader(nhs, 100)
    # the register proposal can race a leadership change under sweep
    # load (the r07/r11 timing class): DROPPED/timeout provably did not
    # commit a session, so re-resolve the leader and re-register — the
    # exactly-once property under test rides the proposal series id,
    # not the registration attempt count
    deadline = time.time() + loadwait.scaled(20.0)
    while True:
        try:
            s = nhs[0].sync_get_session(100, timeout=loadwait.scaled(5.0))
            break
        except (RejectedError, TimeoutError_):
            if time.time() > deadline:
                raise
            wait_for_leader(nhs, 100)
    r1 = nhs[0].sync_propose(s, b"x=1", timeout=loadwait.scaled(5.0))
    assert r1.value == 1
    nhs[0].sync_close_session(s, timeout=loadwait.scaled(5.0))


def test_membership_query_and_leader_transfer(cluster3):
    nhs, sms, addrs, _ = cluster3
    lid = wait_for_leader(nhs, 100)
    m = nhs[0].sync_get_cluster_membership(100, timeout=loadwait.scaled(5.0))
    assert set(m.addresses) == {1, 2, 3}
    target = 1 if lid != 1 else 2
    nhs[0].request_leader_transfer(100, target)
    deadline = time.time() + loadwait.scaled(5.0)
    while time.time() < deadline:
        nlid, ok = nhs[target - 1].get_leader_id(100)
        if ok and nlid == target:
            break
        time.sleep(0.02)
    else:
        raise AssertionError("leader transfer did not happen")


def test_snapshot_and_restart(tmp_path):
    router = ChanRouter()
    d = str(tmp_path / "nh")
    nh = make_nodehost("solo:1", router, tmpdir=d)
    try:
        nh.start_cluster(
            {1: "solo:1"}, False, lambda c, n: KVSM(c, n),
            group_config(7, 1, snapshot_entries=0, compaction_overhead=2),
        )
        wait_for_leader([nh], 7)
        s = nh.get_noop_session(7)
        for i in range(20):
            nh.sync_propose(s, f"k{i}=v{i}".encode(), timeout=loadwait.scaled(5.0))
        idx = nh.sync_request_snapshot(7, timeout=loadwait.scaled(5.0))
        assert idx > 0
        for i in range(20, 30):
            nh.sync_propose(s, f"k{i}=v{i}".encode(), timeout=loadwait.scaled(5.0))
    finally:
        nh.stop()
    # restart: state must come back from snapshot + log replay
    router2 = ChanRouter()
    nh2 = make_nodehost("solo:1", router2, tmpdir=d)
    try:
        nh2.start_cluster(
            {1: "solo:1"}, False, lambda c, n: KVSM(c, n),
            group_config(7, 1, compaction_overhead=2),
        )
        wait_for_leader([nh2], 7)
        assert nh2.sync_read(7, "k5", timeout=loadwait.scaled(5.0)) == "v5"
        assert nh2.sync_read(7, "k29", timeout=loadwait.scaled(5.0)) == "v29"
    finally:
        nh2.stop()


def test_add_node_membership_change(cluster3):
    nhs, sms, addrs, router = cluster3
    wait_for_leader(nhs, 100)
    # add a 4th replica on a new nodehost
    nh4 = make_nodehost("nh4:1", router)
    try:
        nhs[0].sync_request_add_node(100, 4, "nh4:1", timeout=loadwait.scaled(5.0))
        m = nhs[0].sync_get_cluster_membership(100, timeout=loadwait.scaled(5.0))
        assert 4 in m.addresses
        nh4.start_cluster(
            {}, True, lambda c, n: KVSM(c, n), group_config(100, 4),
        )
        s = nhs[0].get_noop_session(100)
        nhs[0].sync_propose(s, b"after=add", timeout=loadwait.scaled(5.0))
        deadline = time.time() + loadwait.scaled(10.0)
        while time.time() < deadline:
            try:
                if nh4.sync_read(100, "after", timeout=1.0) == "add":
                    break
            except Exception:
                time.sleep(0.05)
        else:
            raise AssertionError("new node never caught up")
    finally:
        nh4.stop()


def test_remove_node_membership_change(cluster3):
    nhs, sms, addrs, _ = cluster3
    wait_for_leader(nhs, 100)
    nhs[0].sync_request_delete_node(100, 3, timeout=loadwait.scaled(5.0))
    m = nhs[0].sync_get_cluster_membership(100, timeout=loadwait.scaled(5.0))
    assert 3 not in m.addresses
    s = nhs[0].get_noop_session(100)
    nhs[0].sync_propose(s, b"still=works", timeout=loadwait.scaled(5.0))
    assert nhs[0].sync_read(100, "still", timeout=loadwait.scaled(5.0)) == "works"


def test_node_host_info_and_has_node_info(cluster3):
    """get_node_host_info / has_node_info (reference GetNodeHostInfo /
    HasNodeInfo, nodehost.go:1319-1345)."""
    nhs, sms, addrs, router = cluster3
    lid = wait_for_leader(nhs, 100)
    leader = nhs[lid - 1]
    s = leader.get_noop_session(100)
    deadline = time.time() + loadwait.scaled(20.0)
    j = 0
    while j < 5:  # early proposes can be DROPPED while leadership settles
        try:
            leader.sync_propose(s, f"k{j}=v{j}".encode(), timeout=loadwait.scaled(5.0))
            j += 1
        except Exception:
            if time.time() > deadline:
                raise
            time.sleep(0.1)

    info = leader.get_node_host_info()
    assert info.raft_address == addrs[lid]
    assert len(info.cluster_info_list) == 1
    ci = info.cluster_info_list[0]
    assert ci.cluster_id == 100 and ci.node_id == lid
    assert ci.nodes == addrs and not ci.pending
    assert ci.is_leader and not ci.is_observer and not ci.is_witness
    assert (100, lid) in info.log_info
    assert leader.get_node_host_info(skip_log_info=True).log_info == []

    assert leader.has_node_info(100, lid)
    assert not leader.has_node_info(100, 99)
    assert not leader.has_node_info(999, lid)


def test_request_compaction(tmp_path):
    """request_compaction (reference RequestCompaction nodehost.go:980):
    rejected before any auto-compaction, completes after snapshots have
    moved the compaction watermark, and compacts removed-node data."""
    from dragonboat_tpu.requests import RejectedError

    router = ChanRouter()
    addrs = {1: "nh1:1"}
    nh = make_nodehost(addrs[1], router, tmpdir=str(tmp_path / "nh1"))
    sms = {}

    def create(cluster_id, node_id):
        sm = KVSM(cluster_id, node_id)
        sms[node_id] = sm
        return sm

    nh.start_cluster(
        addrs, False, create,
        group_config(100, 1, snapshot_entries=20, compaction_overhead=5),
    )
    try:
        wait_for_leader([nh], 100)
        with pytest.raises(RejectedError):
            nh.request_compaction(100, 1)
        s = nh.get_noop_session(100)
        for j in range(80):  # crosses several snapshot+compaction points
            nh.sync_propose(s, f"a{j}=b{j}".encode(), timeout=loadwait.scaled(5.0))
        deadline = time.time() + loadwait.scaled(30.0)
        ev = None
        while ev is None and time.time() < deadline:
            try:
                ev = nh.request_compaction(100, 1)
            except RejectedError:
                time.sleep(0.1)  # snapshot/compaction still in flight
        assert ev is not None, "compaction watermark never advanced"
        assert ev.wait(30), "compaction never completed"
        # swap-to-zero: an immediate second request has nothing to do
        with pytest.raises(RejectedError):
            nh.request_compaction(100, 1)
        # removed-node form: full-range compaction completes
        ev2 = nh.request_compaction(321, 9)
        assert ev2.wait(30)
    finally:
        nh.stop()
