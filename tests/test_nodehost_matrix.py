"""NodeHost/node behavioral matrix.

Ports the behavioral families of the reference's ``nodehost_test.go``
(4,731 LoC) that the basic suite (``test_nodehost.py``) does not cover:
config-validation failures, double start/stop, restart matrices
(same/changed membership, remove-data-then-restart), snapshot option
combinations (user-requested / exported / compaction override), session
error paths, the request error taxonomy (``requests.go:53-98`` analogs),
and stopped-NodeHost behavior.

All in-process over the chan transport + memory LogDB (the reference's
memfs test-build shape, ``docs/test.md``).
"""
import os
import time

import pytest

from dragonboat_tpu import (
    Config,
    IStateMachine,
    NodeHost,
    NodeHostConfig,
    Result,
)
from dragonboat_tpu.client import Session
from dragonboat_tpu.config import ConfigError, ExpertConfig
from dragonboat_tpu.requests import (
    ClusterAlreadyExistError,
    ClusterNotFoundError,
    InvalidSessionError,
    RejectedError,
    RequestError,
    TimeoutError_,
)
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT_MS = 5


class KVSM(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.kv = {}
        self.count = 0

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = repr(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import ast

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(ast.literal_eval(r.read(n).decode()))
        self.count = len(self.kv)


def mk_nh(addr, router, tmpdir=None, **kw):
    return NodeHost(
        NodeHostConfig(
            node_host_dir=tmpdir or ":memory:",
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            **kw,
        )
    )


def gcfg(cid, nid, **kw):
    d = dict(cluster_id=cid, node_id=nid, election_rtt=10, heartbeat_rtt=1)
    d.update(kw)
    return Config(**d)


def wait_leader(nhs, cid, timeout=15.0):
    # load-scaled deadline (tests/loadwait.py): the r07 contention-flake
    # class — sound standalone, starved under the full sweep
    from tests.loadwait import scaled

    deadline = time.time() + scaled(timeout)
    while time.time() < deadline:
        for nh in nhs:
            lid, ok = nh.get_leader_id(cid)
            if ok:
                return lid
        time.sleep(0.02)
    raise AssertionError(f"no leader for {cid}")


@pytest.fixture
def solo():
    router = ChanRouter()
    nh = mk_nh("m1:1", router)
    nh.start_cluster({1: "m1:1"}, False, KVSM, gcfg(1, 1))
    wait_leader([nh], 1)
    yield nh
    nh.stop()


@pytest.fixture
def trio():
    router = ChanRouter()
    addrs = {i: f"t{i}:1" for i in (1, 2, 3)}
    nhs = [mk_nh(addrs[i], router) for i in (1, 2, 3)]
    for i, nh in enumerate(nhs, 1):
        nh.start_cluster(addrs, False, KVSM, gcfg(9, i))
    lid = wait_leader(nhs, 9)
    yield nhs, addrs, lid, router
    for nh in nhs:
        nh.stop()


# ======================================================================
# config validation failures (reference config.Config.Validate paths)
# ======================================================================


def test_config_zero_node_id_rejected():
    with pytest.raises(ConfigError):
        Config(cluster_id=1, node_id=0).validate()


def test_config_zero_heartbeat_rejected():
    with pytest.raises(ConfigError):
        Config(cluster_id=1, node_id=1, heartbeat_rtt=0).validate()


def test_config_zero_election_rejected():
    with pytest.raises(ConfigError):
        Config(cluster_id=1, node_id=1, election_rtt=0,
               heartbeat_rtt=1).validate()


def test_config_election_not_gt_twice_heartbeat():
    with pytest.raises(ConfigError):
        Config(cluster_id=1, node_id=1, election_rtt=4,
               heartbeat_rtt=2).validate()


def test_config_small_inmem_log_size_rejected():
    with pytest.raises(ConfigError):
        Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=1,
               max_in_mem_log_size=1024).validate()


def test_config_unknown_compression_rejected():
    with pytest.raises(ConfigError):
        Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=1,
               snapshot_compression=7).validate()


def test_config_witness_with_snapshot_entries_rejected():
    with pytest.raises(ConfigError):
        Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=1,
               is_witness=True, snapshot_entries=10).validate()


def test_config_witness_observer_conflict_rejected():
    with pytest.raises(ConfigError):
        Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=1,
               is_witness=True, is_observer=True).validate()


def test_expert_unknown_engine_rejected():
    with pytest.raises(ConfigError):
        ExpertConfig(quorum_engine="gpu").validate()


def test_nodehost_config_requires_address():
    with pytest.raises(Exception):
        NodeHostConfig(node_host_dir=":memory:", rtt_millisecond=5,
                       raft_address="").validate()


# ======================================================================
# start/stop lifecycle (double start, unknown stop, start after stop)
# ======================================================================


def test_double_start_same_cluster_rejected(solo):
    with pytest.raises(ClusterAlreadyExistError):
        solo.start_cluster({1: "m1:1"}, False, KVSM, gcfg(1, 1))


def test_start_new_node_without_members_rejected(solo):
    with pytest.raises(ValueError):
        solo.start_cluster({}, False, KVSM, gcfg(2, 1))


def test_start_join_with_members_rejected(solo):
    with pytest.raises(ValueError):
        solo.start_cluster({1: "m1:1"}, True, KVSM, gcfg(3, 1))


def test_stop_unknown_cluster_raises(solo):
    with pytest.raises(ClusterNotFoundError):
        solo.stop_cluster(424242)


def test_stop_then_restart_same_cluster(tmp_path):
    router = ChanRouter()
    nh = mk_nh("r1:1", router, str(tmp_path / "nh"))
    try:
        nh.start_cluster({1: "r1:1"}, False, KVSM, gcfg(5, 1))
        wait_leader([nh], 5)
        s = nh.get_noop_session(5)
        assert nh.sync_propose(s, b"a=1", timeout=10.0).value == 1
        nh.stop_cluster(5)
        # restarting a stopped cluster on the same NodeHost resumes from
        # its bootstrap record (empty members + join=False)
        nh.start_cluster({}, False, KVSM, gcfg(5, 1))
        wait_leader([nh], 5)
        assert nh.sync_read(5, "a", timeout=10.0) == "1"
    finally:
        nh.stop()


def test_sm_type_change_across_restart_rejected(tmp_path):
    router = ChanRouter()
    nh = mk_nh("r2:1", router, str(tmp_path / "nh"))
    try:
        nh.start_cluster({1: "r2:1"}, False, KVSM, gcfg(6, 1))
        wait_leader([nh], 6)
        nh.stop_cluster(6)
        with pytest.raises(ValueError):
            nh.start_on_disk_cluster({}, False, KVSM, gcfg(6, 1))
    finally:
        nh.stop()


def test_requests_on_stopped_cluster_raise(solo):
    solo.stop_cluster(1)
    with pytest.raises(ClusterNotFoundError):
        solo.sync_propose(Session.noop_session(1), b"x=1", timeout=1.0)
    with pytest.raises(ClusterNotFoundError):
        solo.sync_read(1, "x", timeout=1.0)
    with pytest.raises(ClusterNotFoundError):
        solo.get_node(1)


def test_stopped_nodehost_rejects_requests():
    router = ChanRouter()
    nh = mk_nh("st1:1", router)
    nh.start_cluster({1: "st1:1"}, False, KVSM, gcfg(7, 1))
    wait_leader([nh], 7)
    nh.stop()
    with pytest.raises(RequestError):
        nh.sync_propose(nh.get_noop_session(7), b"x=1", timeout=1.0)


def test_stop_node_is_stop_cluster_alias(solo):
    solo.stop_node(1, 1)
    assert not solo.has_cluster(1)


def test_has_cluster_and_get_node(solo):
    assert solo.has_cluster(1)
    assert not solo.has_cluster(2)
    assert solo.get_node(1) is not None


# ======================================================================
# restart matrices
# ======================================================================


def test_restart_full_trio_preserves_data(tmp_path):
    router = ChanRouter()
    addrs = {i: f"rt{i}:1" for i in (1, 2, 3)}
    dirs = {i: str(tmp_path / f"nh{i}") for i in (1, 2, 3)}
    nhs = [mk_nh(addrs[i], router, dirs[i]) for i in (1, 2, 3)]
    try:
        for i, nh in enumerate(nhs, 1):
            nh.start_cluster(addrs, False, KVSM, gcfg(11, i))
        wait_leader(nhs, 11)
        lid = wait_leader(nhs, 11)
        s = nhs[lid - 1].get_noop_session(11)
        for k in range(8):
            nhs[lid - 1].sync_propose(s, f"k{k}=v{k}".encode(), timeout=10.0)
        for nh in nhs:
            nh.stop()
        # full restart from on-disk state: empty members + join False
        router2 = ChanRouter()
        nhs = [mk_nh(addrs[i], router2, dirs[i]) for i in (1, 2, 3)]
        for i, nh in enumerate(nhs, 1):
            nh.start_cluster({}, False, KVSM, gcfg(11, i))
        lid = wait_leader(nhs, 11)
        assert nhs[lid - 1].sync_read(11, "k7", timeout=10.0) == "v7"
    finally:
        for nh in nhs:
            try:
                nh.stop()
            except Exception:
                pass


def test_restart_with_changed_address_rejected(tmp_path):
    """Reusing a node's data dir under a DIFFERENT raft address is
    refused (reference server.Context ownership flag: a NodeHost dir
    belongs to the address that created it — nodehost_test.go's
    address-change error family)."""
    from dragonboat_tpu.server.context import NotOwnerError

    router = ChanRouter()
    d = str(tmp_path / "nh")
    nh = mk_nh("ca1:1", router, d)
    nh.start_cluster({1: "ca1:1"}, False, KVSM, gcfg(12, 1))
    wait_leader([nh], 12)
    nh.stop()
    with pytest.raises(NotOwnerError):
        mk_nh("ca1-new:1", router, d)


def test_remove_data_then_restart_is_clean(tmp_path):
    router = ChanRouter()
    nh = mk_nh("rd1:1", router, str(tmp_path / "nh"))
    try:
        nh.start_cluster({1: "rd1:1"}, False, KVSM, gcfg(13, 1))
        wait_leader([nh], 13)
        s = nh.get_noop_session(13)
        nh.sync_propose(s, b"a=1", timeout=10.0)
        nh.stop_cluster(13)
        nh.remove_data(13, 1)
        assert not nh.has_node_info(13, 1)
        # after RemoveData the node is brand new: restart requires members
        with pytest.raises(ValueError):
            nh.start_cluster({}, False, KVSM, gcfg(13, 1))
        nh.start_cluster({1: "rd1:1"}, False, KVSM, gcfg(13, 1))
        wait_leader([nh], 13)
        # data really is gone
        assert nh.sync_read(13, "a", timeout=10.0) is None
    finally:
        nh.stop()


def test_remove_data_on_running_cluster_rejected(solo):
    with pytest.raises(RuntimeError):
        solo.remove_data(1, 1)


# ======================================================================
# snapshot option combinations
# ======================================================================


def test_user_requested_snapshot_returns_index(tmp_path):
    router = ChanRouter()
    nh = mk_nh("ss1:1", router, str(tmp_path / "nh"))
    try:
        nh.start_cluster({1: "ss1:1"}, False, KVSM, gcfg(14, 1))
        wait_leader([nh], 14)
        s = nh.get_noop_session(14)
        for k in range(5):
            nh.sync_propose(s, f"k{k}=v".encode(), timeout=10.0)
        idx = nh.sync_request_snapshot(14, timeout=10.0)
        assert idx >= 5
        # a second request without new entries is rejected (reference
        # SnapshotIndexExist path)
        with pytest.raises(RequestError):
            nh.sync_request_snapshot(14, timeout=10.0)
    finally:
        nh.stop()


def test_exported_snapshot_lands_in_export_path(tmp_path):
    router = ChanRouter()
    nh = mk_nh("ss2:1", router, str(tmp_path / "nh"))
    export = tmp_path / "export"
    export.mkdir()
    try:
        nh.start_cluster({1: "ss2:1"}, False, KVSM, gcfg(15, 1))
        wait_leader([nh], 15)
        s = nh.get_noop_session(15)
        for k in range(4):
            nh.sync_propose(s, f"k{k}=v".encode(), timeout=10.0)
        rs = nh.request_snapshot(15, export_path=str(export), timeout=10.0)
        r = rs.wait(10.0)
        assert r.completed
        dirs = list(export.iterdir())
        assert dirs, "no exported snapshot directory"
        # exported snapshots don't register locally: a user-requested one
        # right after must still succeed
        idx = nh.sync_request_snapshot(15, timeout=10.0)
        assert idx > 0
    finally:
        nh.stop()


def test_snapshot_with_compaction_override(tmp_path):
    router = ChanRouter()
    nh = mk_nh("ss3:1", router, str(tmp_path / "nh"))
    try:
        nh.start_cluster({1: "ss3:1"}, False, KVSM, gcfg(16, 1))
        wait_leader([nh], 16)
        s = nh.get_noop_session(16)
        for k in range(10):
            nh.sync_propose(s, f"k{k}=v".encode(), timeout=10.0)
        rs = nh.request_snapshot(
            16, override_compaction_overhead=True, compaction_overhead=2,
            timeout=10.0,
        )
        r = rs.wait(10.0)
        assert r.completed
        node = nh.get_node(16)
        deadline = time.time() + 10
        while time.time() < deadline:
            if node.logreader.get_range()[0] > 1:
                break
            time.sleep(0.05)
        first, _ = node.logreader.get_range()
        assert first > 1, "compaction with override never happened"
    finally:
        nh.stop()


def test_snapshot_on_unknown_cluster_raises(solo):
    with pytest.raises(ClusterNotFoundError):
        solo.sync_request_snapshot(999, timeout=2.0)


def test_auto_snapshot_after_snapshot_entries(tmp_path):
    router = ChanRouter()
    nh = mk_nh("ss4:1", router, str(tmp_path / "nh"))
    try:
        nh.start_cluster(
            {1: "ss4:1"}, False, KVSM, gcfg(17, 1, snapshot_entries=8,
                                            compaction_overhead=2),
        )
        wait_leader([nh], 17)
        s = nh.get_noop_session(17)
        for k in range(20):
            nh.sync_propose(s, f"k{k}=v".encode(), timeout=10.0)
        node = nh.get_node(17)
        deadline = time.time() + 15
        while time.time() < deadline:
            if node.sm.get_snapshot_index() > 0:
                break
            time.sleep(0.05)
        assert node.sm.get_snapshot_index() > 0, "auto snapshot never fired"
    finally:
        nh.stop()


# ======================================================================
# session error paths
# ======================================================================


def test_session_register_close_roundtrip(solo):
    s = solo.sync_get_session(1, timeout=10.0)
    assert s.client_id != 0
    r = solo.sync_propose(s, b"x=1", timeout=10.0)
    s.proposal_completed()
    assert r.value == 1
    solo.sync_close_session(s, timeout=10.0)


def test_closed_session_propose_rejected(solo):
    s = solo.sync_get_session(1, timeout=10.0)
    solo.sync_close_session(s, timeout=10.0)
    with pytest.raises(RequestError):
        r = solo.sync_propose(s, b"y=2", timeout=5.0)
        # an evicted session must not silently apply
        raise RejectedError(str(r))


def test_noop_session_never_registers(solo):
    s = solo.get_noop_session(1)
    assert s.is_noop_session()
    assert solo.sync_propose(s, b"a=1", timeout=10.0).value == 1


def test_session_dedup_same_series(solo):
    """Re-proposing the same series id must not re-apply (exactly-once)."""
    s = solo.sync_get_session(1, timeout=10.0)
    # async propose path: series id advances only on proposal_completed
    r1 = solo.propose(s, b"k=1", timeout=10.0).wait(10.0)
    assert r1.completed
    # retry under the SAME series id (client crash-retry shape)
    r2 = solo.propose(s, b"k=1", timeout=10.0).wait(10.0)
    assert r2.completed
    assert r1.result.value == r2.result.value, "duplicate series applied twice"
    s.proposal_completed()
    r3 = solo.propose(s, b"k=2", timeout=10.0).wait(10.0)
    assert r3.result.value == r1.result.value + 1
    solo.sync_close_session(s, timeout=10.0)


def test_invalid_session_for_other_cluster(trio):
    nhs, addrs, lid, router = trio
    leader = nhs[lid - 1]
    s = leader.sync_get_session(9, timeout=10.0)
    bad = Session(client_id=s.client_id, series_id=s.series_id,
                  cluster_id=777)
    with pytest.raises((InvalidSessionError, ClusterNotFoundError)):
        leader.sync_propose(bad, b"x=1", timeout=5.0)


# ======================================================================
# request error taxonomy
# ======================================================================


def test_propose_unknown_cluster(solo):
    with pytest.raises(ClusterNotFoundError):
        solo.sync_propose(Session.noop_session(999), b"x=1", timeout=1.0)


def test_read_unknown_cluster(solo):
    with pytest.raises(ClusterNotFoundError):
        solo.sync_read(999, "x", timeout=1.0)


def test_stale_read_known_and_unknown(solo):
    s = solo.get_noop_session(1)
    solo.sync_propose(s, b"sr=1", timeout=10.0)
    assert solo.stale_read(1, "sr") == "1"
    with pytest.raises(ClusterNotFoundError):
        solo.stale_read(999, "sr")


def test_zero_timeout_times_out(trio):
    nhs, addrs, lid, router = trio
    follower = nhs[lid % 3]  # any non-leader
    rs = follower.read_index(9, 0.001)
    r = rs.wait(2.0)
    # with an RTT-quantized deadline this must resolve quickly as either
    # a timeout or (if confirmation won the race) completion
    assert r is not None


def test_leader_transfer_to_unknown_target_noops(trio):
    nhs, addrs, lid, router = trio
    nhs[lid - 1].request_leader_transfer(9, 99)  # unknown target id
    # cluster keeps working
    s = nhs[lid - 1].get_noop_session(9)
    assert nhs[lid - 1].sync_propose(s, b"x=1", timeout=10.0).value == 1


def test_leader_transfer_to_real_target(trio):
    nhs, addrs, lid, router = trio
    target = (lid % 3) + 1
    nhs[lid - 1].request_leader_transfer(9, target)
    deadline = time.time() + 15
    while time.time() < deadline:
        new_lid, ok = nhs[0].get_leader_id(9)
        if ok and new_lid == target:
            break
        time.sleep(0.05)
    new_lid, ok = nhs[0].get_leader_id(9)
    assert ok and new_lid == target


def test_concurrent_config_change_rejected(trio):
    nhs, addrs, lid, router = trio
    leader = nhs[lid - 1]
    rs = leader.request_add_node(9, 4, "t4:1", timeout=10.0)
    try:
        with pytest.raises(RequestError):
            leader.request_add_node(9, 5, "t5:1", timeout=10.0)
            raise RejectedError("second in-flight config change accepted")
    finally:
        rs.wait(10.0)


def test_membership_query_reflects_add_observer(trio):
    nhs, addrs, lid, router = trio
    leader = nhs[lid - 1]
    leader.sync_request_add_observer(9, 7, "t7:1", timeout=10.0)
    m = leader.sync_get_cluster_membership(9, timeout=10.0)
    assert 7 in m.observers
    assert set(m.addresses) == {1, 2, 3}


def test_get_node_host_info_shape(trio):
    nhs, addrs, lid, router = trio
    info = nhs[0].get_node_host_info()
    assert info.raft_address == addrs[1]
    assert any(ci.cluster_id == 9 for ci in info.cluster_info_list)
    assert info.log_info, "skip_log_info=False must include log info"
    info2 = nhs[0].get_node_host_info(skip_log_info=True)
    assert not info2.log_info


# ======================================================================
# observer / witness / join lifecycle
# ======================================================================


def test_observer_replica_serves_stale_read(trio):
    nhs, addrs, lid, router = trio
    leader = nhs[lid - 1]
    obs = mk_nh("t4:1", router)
    try:
        leader.sync_request_add_observer(9, 4, "t4:1", timeout=10.0)
        obs.start_cluster({}, True, KVSM, gcfg(9, 4, is_observer=True))
        s = leader.get_noop_session(9)
        leader.sync_propose(s, b"ob=1", timeout=10.0)
        deadline = time.time() + 15
        ok = False
        while time.time() < deadline and not ok:
            try:
                ok = obs.stale_read(9, "ob") == "1"
            except Exception:
                ok = False
            time.sleep(0.05)
        assert ok, "observer never caught up"
    finally:
        obs.stop()


def test_witness_join_and_data_free(trio):
    nhs, addrs, lid, router = trio
    leader = nhs[lid - 1]
    wit = mk_nh("t8:1", router)
    try:
        leader.sync_request_add_witness(9, 8, "t8:1", timeout=10.0)
        wit.start_cluster({}, True, KVSM, gcfg(9, 8, is_witness=True))
        s = leader.get_noop_session(9)
        for k in range(5):
            leader.sync_propose(s, f"w{k}=1".encode(), timeout=10.0)
        m = leader.sync_get_cluster_membership(9, timeout=10.0)
        assert 8 in m.witnesses
        # the witness replica never applies user data
        assert wit.get_node(9).sm.lookup("w0") is None
    finally:
        wit.stop()


def test_delete_node_then_requests_rejected(trio):
    nhs, addrs, lid, router = trio
    leader = nhs[lid - 1]
    victim = (lid % 3) + 1
    leader.sync_request_delete_node(9, victim, timeout=10.0)
    m = leader.sync_get_cluster_membership(9, timeout=10.0)
    assert victim not in m.addresses
    # the removed replica steps itself down into self_removed state; new
    # proposals through it fail once it learns (bounded wait)
    deadline = time.time() + 15
    removed = False
    while time.time() < deadline and not removed:
        node = nhs[victim - 1].get_node(9)
        removed = node.peer.raft.self_removed()
        time.sleep(0.05)
    assert removed


# ======================================================================
# on-disk / concurrent SM lifecycle through the facade
# ======================================================================


class ConcSM:
    def __init__(self, cluster_id, node_id):
        self.v = 0

    def update(self, entries):
        for e in entries:
            self.v += 1
            e.result = Result(value=self.v)
        return entries

    def lookup(self, q):
        return self.v

    def prepare_snapshot(self):
        return self.v

    def save_snapshot(self, ctx, w, files, done):
        w.write(int(ctx).to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.v = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def test_concurrent_sm_cluster_roundtrip():
    router = ChanRouter()
    nh = mk_nh("cc1:1", router)
    try:
        nh.start_concurrent_cluster({1: "cc1:1"}, False, ConcSM, gcfg(21, 1))
        wait_leader([nh], 21)
        s = nh.get_noop_session(21)
        for k in range(6):
            assert nh.sync_propose(s, b"x", timeout=10.0).value == k + 1
        assert nh.sync_read(21, None, timeout=10.0) == 6
    finally:
        nh.stop()


class DiskSM:
    def __init__(self, cluster_id, node_id):
        self.v = 0
        self.applied = 0

    def open(self, stopc):
        return self.applied

    def update(self, entries):
        for e in entries:
            self.v += 1
            self.applied = e.index
            e.result = Result(value=self.v)
        return entries

    def lookup(self, q):
        return self.v

    def sync(self):
        pass

    def prepare_snapshot(self):
        return self.v

    def save_snapshot(self, ctx, w, done):
        w.write(int(ctx).to_bytes(8, "little"))

    def recover_from_snapshot(self, r, done):
        self.v = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def test_on_disk_sm_cluster_roundtrip():
    router = ChanRouter()
    nh = mk_nh("od1:1", router)
    try:
        nh.start_on_disk_cluster({1: "od1:1"}, False, DiskSM, gcfg(22, 1))
        wait_leader([nh], 22)
        s = nh.get_noop_session(22)
        for k in range(6):
            assert nh.sync_propose(s, b"x", timeout=10.0).value == k + 1
    finally:
        nh.stop()


# ======================================================================
# misc API surface
# ======================================================================


def test_propose_batch_orders_and_completes(solo):
    s = solo.get_noop_session(1)
    states = solo.propose_batch(s, [f"b{i}=1".encode() for i in range(10)],
                                timeout=10.0)
    vals = [rs.wait(10.0).result.value for rs in states]
    assert vals == sorted(vals), "batch completions out of order"
    assert len(set(vals)) == 10


def test_read_index_on_leader_completes(solo):
    s = solo.get_noop_session(1)
    solo.sync_propose(s, b"ri=1", timeout=10.0)
    rs = solo.read_index(1, 10.0)
    r = rs.wait(10.0)
    assert r.completed


def test_compaction_wrong_node_id_raises(solo):
    # unknown cluster ids legitimately compact leftover data (the
    # post-remove_data path, reference RequestCompaction); a LIVE cluster
    # under a wrong node id is refused
    with pytest.raises(ClusterNotFoundError):
        solo.request_compaction(1, 42)


def test_get_node_user_matches_get_node(solo):
    assert solo.get_node_user(1) is solo.get_node(1)
