"""Device-plane observability tests (ISSUE 5).

Covers the ``dragonboat_tpu.obs`` package itself (a real package — the
seed shipped only a stale ``__pycache__`` with no sources, so import
behavior depended on interpreter caching), the flight recorder ring +
stall-watchdog auto-dump, the Prometheus exposition audit (escaping,
one ``# TYPE`` per name, round-trip), engine obs-on/obs-off parity, and
the health-metrics surface end to end through a tpu-engine NodeHost.
"""
import importlib
import io
import json
import os
import pkgutil
import time

import dragonboat_tpu
from dragonboat_tpu.events import MetricsRegistry, escape_label_value
from dragonboat_tpu.obs import FlightRecorder
from dragonboat_tpu.obs.instruments import CoordObs, EngineObs
from dragonboat_tpu.ops.engine import BatchedQuorumEngine

RTT_MS = 5


# ---------------------------------------------------------------------------
# packaging (satellite: the stale-__pycache__ bug)
# ---------------------------------------------------------------------------


def test_every_subpackage_imports_as_real_package():
    """Every ``dragonboat_tpu.*`` subpackage must import from real
    sources: a directory holding only a ``__pycache__`` imports as an
    EMPTY namespace package (Python 3 ignores ``__pycache__`` pycs whose
    sources are gone), so ``import dragonboat_tpu.obs`` silently
    succeeded while every attribute access failed."""
    root = os.path.dirname(dragonboat_tpu.__file__)
    found = []
    for entry in sorted(os.listdir(root)):
        d = os.path.join(root, entry)
        if os.path.isdir(d) and entry != "__pycache__":
            mod = importlib.import_module(f"dragonboat_tpu.{entry}")
            # a namespace package has no __file__ — the bug's signature
            assert getattr(mod, "__file__", None), (
                f"dragonboat_tpu.{entry} imported as a namespace package "
                "(missing __init__.py?)"
            )
            found.append(entry)
    assert "obs" in found and "ops" in found
    # and the walkable module tree stays importable (sources, not pycs)
    for info in pkgutil.iter_modules(
        dragonboat_tpu.obs.__path__, "dragonboat_tpu.obs."
    ):
        importlib.import_module(info.name)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_recorder_ring_wrap_order_and_json():
    rec = FlightRecorder(capacity=4, stall_ms=0)
    for i in range(7):
        rec.record("dispatch", rounds=i)
    spans = rec.spans()
    assert len(spans) == 4 == len(rec)
    assert [s["rounds"] for s in spans] == [3, 4, 5, 6]  # oldest -> newest
    assert [s["seq"] for s in spans] == [3, 4, 5, 6]
    d = rec.to_json(limit=2)
    assert d["count"] == 7 and len(d["spans"]) == 2
    json.dumps(d)  # must be serializable as-is


def test_recorder_stall_watchdog_autodump(tmp_path):
    path = str(tmp_path / "dump.json")
    rec = FlightRecorder(capacity=8, stall_ms=10.0, dump_path=path)
    rec.record("dispatch", gate="acks", dispatch_ms=1.0)  # healthy
    assert rec.stalls == 0 and rec.last_dump is None
    span = rec.record("dispatch", gate="tick+acks", dispatch_ms=1.0)
    rec.update(span, egress_ms=25.0)  # trips at finalize (slow egress)
    assert rec.stalls == 1
    assert span["stalled"] == "egress_ms"
    dump = rec.last_dump
    assert dump["trigger"] is span and "stall" in dump["reason"]
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["trigger"]["gate"] == "tick+acks"
    # a span stalls (and dumps) at most once
    rec.update(span, egress_ms=50.0)
    assert rec.stalls == 1


# ---------------------------------------------------------------------------
# Prometheus exposition (satellite audit)
# ---------------------------------------------------------------------------


def _parse_exposition(text):
    """Minimal text-format parser: returns ({name: type}, {(name, labels
    frozenset): value}) with label values UNescaped.  Also asserts the
    ISSUE 9 HELP invariant: every family carries exactly one ``# HELP``
    line immediately before its ``# TYPE``."""
    types, samples, helps = {}, {}, {}
    pending_help = None
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, name, help_text = line.split(" ", 3)
            assert name not in helps, f"duplicate # HELP for {name}"
            helps[name] = (
                help_text.replace("\\n", "\n").replace("\\\\", "\\")
            )
            pending_help = name
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            assert name not in types, f"duplicate # TYPE for {name}"
            assert pending_help == name, (
                f"# TYPE {name} not immediately preceded by its # HELP"
            )
            pending_help = None
            types[name] = kind
            continue
        assert not line.startswith("#")
        metric, value = line.rsplit(" ", 1)
        if "{" in metric:
            name, rest = metric.split("{", 1)
            body = rest.rsplit("}", 1)[0]
            labels = []
            # split on '",' boundaries so escaped quotes stay intact
            for part in body.split('",'):
                k, v = part.split("=", 1)
                v = v.strip('"')
                v = (
                    v.replace("\\n", "\n")
                    .replace('\\"', '"')
                    .replace("\\\\", "\\")
                )
                labels.append((k, v))
            samples[(name, frozenset(labels))] = float(value)
        else:
            samples[(metric, frozenset())] = float(value)
    return types, samples


def test_exposition_escaping_and_single_type_roundtrip():
    reg = MetricsRegistry()
    nasty = 'quo"te\\slash\nnewline'
    reg.counter_add("x_total", 3, labels={"a": nasty})
    reg.counter_add("x_total", 2, labels={"a": "plain"})  # same family
    reg.gauge_set("depth", 7.5, labels={"q": "r"})
    reg.histogram_observe("lat_ms", 3.0, buckets=(1.0, 5.0, 10.0))
    reg.histogram_observe("lat_ms", 100.0, buckets=(1.0, 5.0, 10.0))
    reg.describe("x_total", "an x\ncounter with back\\slash")
    out = io.StringIO()
    reg.write_health_metrics(out)
    text = out.getvalue()
    # HELP escaping (backslash + newline only — quotes stay literal per
    # the exposition spec) and presence for EVERY family: described ones
    # carry their text, undescribed ones the deterministic placeholder
    assert "# HELP x_total an x\\ncounter with back\\\\slash\n" in text
    assert "# HELP depth dragonboat_tpu metric depth\n" in text
    assert "# HELP lat_ms dragonboat_tpu metric lat_ms\n" in text
    # escaping: raw specials never appear inside a label value
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    assert "\n" not in text.split('a="')[1].split('"')[0]
    types, samples = _parse_exposition(text)  # asserts one TYPE per name
    assert types == {
        "x_total": "counter", "depth": "gauge", "lat_ms": "histogram",
    }
    # round-trip: parsed values match what was registered
    assert samples[("x_total", frozenset({("a", nasty)}))] == 3
    assert samples[("x_total", frozenset({("a", "plain")}))] == 2
    assert samples[("depth", frozenset({("q", "r")}))] == 7.5
    # histogram: cumulative buckets, +Inf == count, sum preserved
    assert samples[("lat_ms_bucket", frozenset({("le", "5")}))] == 1
    assert samples[("lat_ms_bucket", frozenset({("le", "+Inf")}))] == 2
    assert samples[("lat_ms_sum", frozenset())] == 103.0
    assert samples[("lat_ms_count", frozenset())] == 2
    # stable ordering: a second write is byte-identical
    out2 = io.StringIO()
    reg.write_health_metrics(out2)
    assert out2.getvalue() == text


def test_escape_label_value_order():
    # backslash escapes FIRST: escaping a pre-escaped quote must not
    # double-mangle
    assert escape_label_value('\\"') == '\\\\\\"'
    assert escape_label_value("a\nb") == "a\\nb"


# ---------------------------------------------------------------------------
# engine hooks
# ---------------------------------------------------------------------------


def _drive(eng):
    for cid in (1, 2):
        eng.add_group(cid, node_ids=[1, 2, 3], self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    outs = []
    for r in range(3):
        for cid in (1, 2):
            eng.ack(cid, 1, 2 + r)
            eng.ack(cid, 2, 2 + r)
        eng.begin_round()
        outs.append(dict(eng.step_rounds(do_tick=False).commit))
    eng.ack(1, 2, 10)
    outs.append(dict(eng.step(do_tick=False).commit))  # single-round path
    return outs


def test_engine_obs_off_by_default_and_parity():
    plain = BatchedQuorumEngine(8, 3, device_ticks=False)
    assert plain._obs is None  # obs-off: no instruments, no recorder
    rec = FlightRecorder(capacity=32, stall_ms=0)
    reg = MetricsRegistry()
    instrumented = BatchedQuorumEngine(8, 3, device_ticks=False)
    instrumented.enable_obs(recorder=rec, registry=reg)
    assert _drive(plain) == _drive(instrumented)  # identical egress
    spans = rec.spans()
    assert len(spans) == 4
    fused = spans[0]
    assert fused["kind"] == "fused" and fused["gate"] == "acks"
    assert fused["rounds"] == 1 and fused["acks"] == 4
    assert fused["upload_bytes"] > 0 and "egress_ms" in fused
    assert fused["egress_rows"] == 2  # both groups advanced
    single = spans[-1]
    assert single["kind"] == "dispatch" and single["acks"] == 1
    # counters followed the spans
    assert reg.counter_value("dragonboat_device_dispatch_total") == 4
    assert reg.counter_value("dragonboat_device_acks_staged_total") == 13
    assert reg.histogram_value("dragonboat_device_dispatch_latency_ms")[3] == 4


def test_enable_obs_rebinds_registry_after_latch():
    """A latch-attached engine must not swallow a later explicit wiring:
    NodeHost routes the families into ITS registry after the module latch
    already self-attached the default one."""
    import dragonboat_tpu.obs as obs_mod

    obs_mod.enable(stall_ms=0)
    try:
        eng = BatchedQuorumEngine(4, 3, device_ticks=False)
        assert eng._obs is not None  # latch self-attached
        mine = MetricsRegistry()
        eng.enable_obs(registry=mine)  # the NodeHost-style rebind
        assert eng._obs.registry is mine
        assert "dragonboat_device_dispatch_total" in mine.families()
        same = eng.enable_obs()  # argument-free repeat: no-op
        assert same is eng._obs and same.registry is mine
    finally:
        obs_mod.disable()


def test_engine_obs_recycle_and_gate_reasons():
    rec = FlightRecorder(capacity=32, stall_ms=0)
    eng = BatchedQuorumEngine(8, 3, device_ticks=False)
    eng.enable_obs(recorder=rec, registry=MetricsRegistry())
    eng.add_group(1, node_ids=[1, 2, 3], self_id=1)
    eng.set_leader(1, term=1, term_start=1, last_index=1)
    eng.step(do_tick=False)
    eng.stage_recycle(1, 2, term=1, term_start=1, last_index=1)
    eng.ack(2, 2, 2)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    last = rec.spans()[-1]
    assert last["recycles"] == 1
    assert "churn" in last["gate"] and "acks" in last["gate"]


def test_engine_stall_autodump_names_blocked_dispatch(monkeypatch, tmp_path):
    """Acceptance: a forced dispatch stall (slow egress) auto-dumps the
    recorder with the stalled span — its kind, gate reason, and staged
    counts name the blocked dispatch."""
    import jax

    path = str(tmp_path / "stall.json")
    rec = FlightRecorder(capacity=16, stall_ms=20.0, dump_path=path)
    reg = MetricsRegistry()
    eng = BatchedQuorumEngine(8, 3, device_ticks=False)
    eng.enable_obs(recorder=rec, registry=reg)
    eng.add_group(1, node_ids=[1, 2, 3], self_id=1)
    eng.set_leader(1, term=1, term_start=1, last_index=1)
    # warmup: compile the fused program so the stall below is attributable
    # to the forced-slow egress, not a first-use jit dispatch (which the
    # watchdog would legitimately flag as a dispatch_ms stall)
    eng.ack(1, 2, 2)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    assert rec.stalls == 0 or rec.last_dump["trigger"]["stalled"] != "egress_ms"
    rec.stalls = 0

    real_get = jax.device_get

    def slow_get(x):  # a wedged egress (tunnel stall, device hang)
        time.sleep(0.05)
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", slow_get)
    eng.ack(1, 2, 3)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    assert rec.stalls >= 1
    assert reg.counter_value("dragonboat_device_stalls_total") >= 1
    dump = rec.last_dump
    trigger = dump["trigger"]
    assert trigger["stalled"] == "egress_ms"
    assert trigger["kind"] == "fused" and trigger["gate"] == "acks"
    assert trigger["acks"] == 1 and trigger["egress_ms"] >= 20.0
    with open(path) as f:  # the on-demand artifact names it too
        assert json.load(f)["trigger"]["kind"] == "fused"


# ---------------------------------------------------------------------------
# metric families through write_health_metrics
# ---------------------------------------------------------------------------


def test_devsm_apply_kernel_span_and_families():
    """ISSUE 11 satellite: a kv-carrying dispatch opens an
    ``apply_kernel`` span (staged ops/reads at dispatch, applied/served
    at harvest) and the ``dragonboat_devsm_*`` families track the fold's
    work; kv-free engines never record the kind."""
    rec = FlightRecorder(capacity=32, stall_ms=0)
    reg = MetricsRegistry()
    eng = BatchedQuorumEngine(8, 3, device_ticks=False)
    eng.enable_obs(recorder=rec, registry=reg)
    eng.add_group(1, node_ids=[1, 2, 3], self_id=1)
    eng.set_leader(1, term=1, term_start=1, last_index=1)
    eng.ack(1, 1, 3)
    eng.step(do_tick=False)
    assert not [s for s in rec.spans() if s["kind"] == "apply_kernel"]
    # now a kv round: 2 ops commit, 1 read captures — single-round path
    eng.stage_kv_ops(1, [2, 3], [0, 1], [5, 6])
    eng.ack(1, 2, 3)
    eng.stage_kv_read(1, 0)
    eng.step(do_tick=False)
    # ... and a fused block: 1 op + 1 read
    eng.stage_kv_ops(1, [4], [2], [7])
    eng.ack(1, 1, 4)
    eng.ack(1, 2, 4)
    eng.stage_kv_read(1, 2)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    spans = [s for s in rec.spans() if s["kind"] == "apply_kernel"]
    assert len(spans) == 2
    assert spans[0]["ops"] == 2 and spans[0]["reads"] == 1
    assert spans[0]["applied"] == 2 and spans[0]["reads_served"] == 1
    assert spans[1]["ops"] == 1 and spans[1]["applied"] == 1
    assert reg.counter_value("dragonboat_devsm_ops_staged_total") == 3
    assert reg.counter_value("dragonboat_devsm_applied_total") == 3
    assert reg.counter_value("dragonboat_devsm_reads_staged_total") == 2
    assert reg.counter_value("dragonboat_devsm_reads_served_total") == 2
    # exposition carries the families with their described HELP text
    out = io.StringIO()
    reg.write_health_metrics(out)
    text = out.getvalue()
    for fam in (
        "dragonboat_devsm_ops_staged_total",
        "dragonboat_devsm_applied_total",
        "dragonboat_devsm_reads_staged_total",
        "dragonboat_devsm_reads_served_total",
        "dragonboat_devsm_slot_occupancy",
    ):
        assert f"# TYPE {fam} " in text, fam
        help_line = next(
            l for l in text.splitlines() if l.startswith(f"# HELP {fam} ")
        )
        assert "dragonboat_tpu metric" not in help_line, help_line


def test_device_plane_metric_families_exposed():
    """ISSUE acceptance: with obs enabled, the health exposition carries
    >= 8 device-plane families (engine + coordinator planes)."""
    rec = FlightRecorder(capacity=8, stall_ms=0)
    reg = MetricsRegistry()
    EngineObs(rec, reg)
    CoordObs(rec, reg)
    out = io.StringIO()
    reg.write_health_metrics(out)
    types, _ = _parse_exposition(out.getvalue())
    dev = [n for n in types if n.startswith("dragonboat_device_")]
    coord = [n for n in types if n.startswith("dragonboat_coord_")]
    assert len(dev) >= 8, dev
    assert len(dev) + len(coord) >= 14
    # the latency families expose as proper histograms
    assert types["dragonboat_device_dispatch_latency_ms"] == "histogram"
    assert types["dragonboat_coord_round_latency_ms"] == "histogram"


def test_nodehost_health_metrics_device_plane():
    """Live wiring: NodeHostConfig.enable_metrics + quorum_engine="tpu"
    puts the device plane into nh.write_health_metrics, the recorder on
    nh.flight_recorder, and node offload application into the registry."""
    from dragonboat_tpu import Config, NodeHostConfig, Result
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport import ChanRouter, ChanTransport

    class CountSM:
        def __init__(self, cluster_id, node_id):
            self.count = 0

        def update(self, cmd):
            self.count += 1
            return Result(value=self.count)

        def lookup(self, query):
            return self.count

        def save_snapshot(self, w, files, done):
            w.write(self.count.to_bytes(8, "little"))

        def recover_from_snapshot(self, r, files, done):
            self.count = int.from_bytes(r.read(8), "little")

        def close(self):
            pass

    router = ChanRouter()
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT_MS,
            raft_address="obs:1",
            raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                src, rh, ch, router=router
            ),
            enable_metrics=True,
            expert=ExpertConfig(quorum_engine="tpu", engine_block_groups=64),
        )
    )
    try:
        assert nh.flight_recorder is not None
        out = io.StringIO()
        nh.write_health_metrics(out)
        types, _ = _parse_exposition(out.getvalue())
        assert len(
            [n for n in types if n.startswith("dragonboat_device_")]
        ) >= 8
        nh.start_cluster(
            {1: "obs:1"},
            False,
            CountSM,
            Config(cluster_id=5, node_id=1, election_rtt=10, heartbeat_rtt=1),
        )
        deadline = time.time() + 10
        while time.time() < deadline:
            _, ok = nh.get_leader_id(5)
            if ok:
                break
            time.sleep(0.01)
        s = nh.get_noop_session(5)
        for _ in range(5):
            nh.sync_propose(s, b"x", timeout=5.0)
        reg = nh.metrics_registry
        # the device plane actually served the writes: dispatches ran,
        # commits offloaded back, and the node applied them
        deadline = time.time() + 10
        while time.time() < deadline:
            if reg.counter_value(
                "dragonboat_node_offload_applied_total", {"kind": "commit"}
            ) > 0:
                break
            time.sleep(0.05)
        assert reg.counter_value("dragonboat_device_dispatch_total") > 0
        assert reg.counter_value("dragonboat_coord_rounds_total") > 0
        assert reg.counter_value(
            "dragonboat_node_offload_applied_total", {"kind": "commit"}
        ) > 0
        assert len(nh.flight_recorder.spans()) > 0
    finally:
        nh.stop()


def test_hostproc_obs_live_plane_families():
    """ISSUE 12: a live HostProcPlane with obs enabled publishes the
    ``dragonboat_hostproc_*`` families into the given registry — the
    monitor keeps workers_alive current and a worker round trip lands
    calls_total + the worker-wall histogram observation."""
    import time as _time

    from dragonboat_tpu.events import MetricsRegistry
    from dragonboat_tpu.hostproc.control import HostProcPlane

    reg = MetricsRegistry()
    p = HostProcPlane(workers=1, encode_lanes=1)
    try:
        p.enable_obs(registry=reg)
        assert reg.gauge_value("dragonboat_hostproc_workers_alive") == 1
        lane = p.encode_lane(0)
        assert lane.encode(0, [b"abc"]) is not None
        assert (
            reg.counter_value(
                "dragonboat_hostproc_calls_total", {"role": "encode"}
            )
            == 1
        )
        deadline = _time.time() + 5
        while (
            reg.gauge_value("dragonboat_hostproc_ring_depth") != 0
            and _time.time() < deadline
        ):
            _time.sleep(0.05)
    finally:
        p.stop()
