"""NodeHost-level observer and witness lifecycle tests.

Reference: observer catch-up + promotion (``raft.go:1145-1152``), witness
replicas that store metadata-only entries and vote but never lead
(§4.2.1 of the raft thesis; ``raft.go`` witness paths).  Raft-level suites
cover the protocol; these exercise the public NodeHost surface:
start_cluster with is_observer/is_witness, runtime add + promote.
"""
from __future__ import annotations

import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.transport import ChanRouter, ChanTransport

# heavy multi-NodeHost tests serialize on one xdist worker
# (--dist loadgroup): 4-way-parallel multiprocess clusters
# starve each other on an 8-vCPU box
pytestmark = pytest.mark.xdist_group("heavy-multiprocess")


RTT = 10
CID = 5


class KVSM:
    def __init__(self, cluster_id, node_id):
        self.kv = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        import json

        data = json.dumps(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import json

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(json.loads(r.read(n).decode()))

    def close(self):
        pass


def _mk(i, router, sms, addrs, initial_members, **cfg_kw):
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT,
            raft_address=addrs[i],
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
        )
    )

    def create(cluster_id, node_id):
        sm = KVSM(cluster_id, node_id)
        sms[i] = sm
        return sm

    join = i not in initial_members
    nh.start_cluster(
        {} if join else {j: addrs[j] for j in initial_members},
        join,
        create,
        Config(cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
               snapshot_entries=0, **cfg_kw),
    )
    return nh


def _leader(nhs, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs.values():
            lid, ok = nh.get_leader_id(CID)
            if ok and lid in nhs:
                return lid, nhs[lid]
        time.sleep(0.02)
    raise AssertionError("no leader")


def _propose_ok(leader, cmd, timeout=10.0):
    s = leader.get_noop_session(CID)
    rs = leader.propose(s, cmd, timeout=timeout)
    return rs.wait(timeout).completed


def test_observer_replicates_and_promotes():
    router = ChanRouter()
    addrs = {i: f"ow{i}:1" for i in (1, 2, 3, 4)}
    sms = {}
    nhs = {i: _mk(i, router, sms, addrs, (1, 2, 3)) for i in (1, 2, 3)}
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        assert _propose_ok(leader, b"a=1")
        # add node 4 as a non-voting observer, then start it with join=True
        leader.sync_request_add_observer(CID, 4, addrs[4], timeout=10.0)
        nhs[4] = _mk(4, router, sms, addrs, (1, 2, 3),
                     is_observer=True)
        # the observer catches up with replicated entries
        assert _propose_ok(leader, b"b=2")
        deadline = time.time() + 20
        while time.time() < deadline:
            if sms.get(4) is not None and sms[4].kv.get("b") == "2":
                break
            time.sleep(0.05)
        assert sms[4].kv.get("b") == "2", "observer never caught up"
        # the observer never becomes leader / never votes: membership says so
        m = leader.sync_get_cluster_membership(CID, timeout=10.0)
        assert 4 in m.observers and 4 not in m.addresses
        # promote: add_node on the same id turns the observer into a voter
        leader.sync_request_add_node(CID, 4, addrs[4], timeout=10.0)
        deadline = time.time() + 20
        while time.time() < deadline:
            m = leader.sync_get_cluster_membership(CID, timeout=10.0)
            if 4 in m.addresses and 4 not in m.observers:
                break
            time.sleep(0.1)
        assert 4 in m.addresses and 4 not in m.observers
        # the promoted voter participates: writes still commit after
        # stopping one ORIGINAL voter (quorum now needs 3 of 4)
        assert _propose_ok(leader, b"c=3")
        stop_id = next(i for i in (1, 2, 3) if i != lid)
        nhs[stop_id].stop()
        del nhs[stop_id]
        lid2, leader = _leader(nhs)
        assert _propose_ok(leader, b"d=4", timeout=15.0), (
            "cluster with promoted observer lost availability"
        )
    finally:
        for nh in nhs.values():
            nh.stop()


def test_witness_votes_but_stores_no_payloads():
    router = ChanRouter()
    addrs = {i: f"wt{i}:1" for i in (1, 2, 3)}
    sms = {}
    # 2 full replicas; the witness is ADDED then joins (witnesses are never
    # part of the bootstrap membership — reference startCluster semantics)
    nhs = {
        1: _mk(1, router, sms, addrs, (1, 2)),
        2: _mk(2, router, sms, addrs, (1, 2)),
    }
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader({1: nhs[1], 2: nhs[2]})
        assert _propose_ok(leader, b"pre=w")
        leader.sync_request_add_witness(CID, 3, addrs[3], timeout=10.0)
        nhs[3] = _mk(3, router, sms, addrs, (1, 2), is_witness=True)
        deadline = time.time() + 20
        while time.time() < deadline:
            m = leader.sync_get_cluster_membership(CID, timeout=10.0)
            if 3 in m.witnesses:
                break
            time.sleep(0.1)
        assert 3 in m.witnesses
        for j in range(10):
            assert _propose_ok(leader, f"k{j}=v{j}".encode())
        # give replication a beat to reach the witness
        deadline = time.time() + 10
        while time.time() < deadline:
            r3 = nhs[3].get_node(CID).peer.raft
            if r3.log.last_index() >= 10:
                break
            time.sleep(0.05)
        # the witness's raft log holds only metadata entries (no payloads)
        wnode = nhs[3].get_node(CID)
        r = wnode.peer.raft
        assert r.is_witness()
        ents = r.log.get_entries(
            r.log.first_index(), r.log.last_index() + 1, 1 << 62
        )
        from dragonboat_tpu.wire import EntryType

        assert ents, "witness received no entries"
        # application payloads are stripped to METADATA; config changes are
        # replicated in full (the witness needs membership)
        assert all(
            e.type in (EntryType.METADATA, EntryType.CONFIG_CHANGE)
            or not e.cmd
            for e in ents
        ), "witness stored application payloads"
        # witness's SM applies nothing
        assert sms[3].kv == {}
        # availability with witness as the tie-breaker: stop the non-leader
        # full replica; leader + witness still form a quorum of 2/3
        stop_id = 2 if lid == 1 else 1
        nhs[stop_id].stop()
        del nhs[stop_id]
        time.sleep(0.5)
        assert _propose_ok(nhs[lid], b"tie=breaker", timeout=15.0), (
            "leader+witness quorum failed to commit"
        )
    finally:
        for nh in nhs.values():
            nh.stop()


def test_user_operations_on_witness_are_rejected():
    """Reference node.go:352-442 (ErrInvalidOperation) — a witness
    replica serves NO user operations: proposals (plain, batch and
    session ops), reads, config changes, snapshot requests and leader
    transfers are all rejected locally, before anything is enqueued.
    Ports TestConfigChangeOnWitnessWillBeRejected / ReadOnWitness /
    MakingProposalOnWitnessNode / ProposingSessionOnWitnessNode /
    RequestingSnapshotOnWitness (node_test.go)."""
    from dragonboat_tpu import InvalidOperationError
    from dragonboat_tpu.rsm import SSReqType, SSRequest

    router = ChanRouter()
    addrs = {i: f"wr{i}:1" for i in (1, 2, 3)}
    sms = {}
    nhs = {
        1: _mk(1, router, sms, addrs, (1, 2)),
        2: _mk(2, router, sms, addrs, (1, 2)),
    }
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader({1: nhs[1], 2: nhs[2]})
        assert _propose_ok(leader, b"pre=w")
        leader.sync_request_add_witness(CID, 3, addrs[3], timeout=10.0)
        nhs[3] = _mk(3, router, sms, addrs, (1, 2), is_witness=True)
        wnode = nhs[3].get_node(CID)
        deadline = time.time() + 20
        while time.time() < deadline and not wnode.peer.raft.is_witness():
            time.sleep(0.1)
        assert wnode.peer.raft.is_witness()

        s = nhs[3].get_noop_session(CID)
        with pytest.raises(InvalidOperationError):
            nhs[3].propose(s, b"k=v", timeout=5.0)
        with pytest.raises(InvalidOperationError):
            wnode.propose_batch(s, [b"k=v"], 5.0)
        with pytest.raises(InvalidOperationError):
            wnode.propose_session(s, 5.0)
        with pytest.raises(InvalidOperationError):
            nhs[3].sync_read(CID, "pre", timeout=5.0)
        with pytest.raises(InvalidOperationError):
            nhs[3].request_add_node(CID, 9, "wr9:1", timeout=5.0)
        with pytest.raises(InvalidOperationError):
            wnode.request_snapshot(
                SSRequest(type=SSReqType.USER_REQUESTED), 5.0
            )
        with pytest.raises(InvalidOperationError):
            wnode.request_leader_transfer(1, 5.0)
        # the full replicas still serve everything
        assert _propose_ok(leader, b"post=w")
    finally:
        for nh in nhs.values():
            nh.stop()


def test_payload_too_big_rejected():
    """Reference node.go:363-381 (ErrPayloadTooBig): with
    max_in_mem_log_size configured, an oversized payload is rejected
    before it is enqueued; a small one passes."""
    from dragonboat_tpu import PayloadTooBigError

    router = ChanRouter()
    addrs = {1: "pb1:1"}
    sms = {}
    nh = _mk(1, router, sms, addrs, (1,), max_in_mem_log_size=64 * 1024)
    try:
        nh.get_node(CID).request_campaign()
        _leader({1: nh})
        s = nh.get_noop_session(CID)
        assert _propose_ok(nh, b"small=ok")
        with pytest.raises(PayloadTooBigError):
            nh.propose(s, b"x" * (64 * 1024), timeout=5.0)
        node = nh.get_node(CID)
        with pytest.raises(PayloadTooBigError):
            node.propose_batch(s, [b"ok", b"y" * (64 * 1024)], 5.0)
    finally:
        nh.stop()


def test_stale_read_on_witness_rejected():
    """A witness's SM never applies payloads, so even the relaxed
    stale-read path must refuse (reference StaleRead:
    ErrInvalidOperation) rather than answer from permanently empty
    state."""
    from dragonboat_tpu import InvalidOperationError

    router = ChanRouter()
    addrs = {i: f"sr{i}:1" for i in (1, 2, 3)}
    sms = {}
    nhs = {
        1: _mk(1, router, sms, addrs, (1, 2)),
        2: _mk(2, router, sms, addrs, (1, 2)),
    }
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader({1: nhs[1], 2: nhs[2]})
        assert _propose_ok(leader, b"sk=sv")
        leader.sync_request_add_witness(CID, 3, addrs[3], timeout=10.0)
        nhs[3] = _mk(3, router, sms, addrs, (1, 2), is_witness=True)
        wnode = nhs[3].get_node(CID)
        deadline = time.time() + 20
        while time.time() < deadline and not wnode.peer.raft.is_witness():
            time.sleep(0.1)
        with pytest.raises(InvalidOperationError):
            nhs[3].stale_read(CID, "sk")
        # the full replicas still serve stale reads
        deadline = time.time() + 10
        while time.time() < deadline and leader.stale_read(CID, "sk") != "sv":
            time.sleep(0.05)
        assert leader.stale_read(CID, "sk") == "sv"
    finally:
        for nh in nhs.values():
            nh.stop()
