"""Differential tests: batched quorum kernels vs the scalar raft oracle.

The north star demands the batched engine's commitIndex outputs be
bit-identical to the scalar path (SURVEY.md §6); these tests replay the
exact same event streams through both and compare watermarks after every
round.  This is the conformance-gate analog of the reference's etcd-ported
suite (``internal/raft/raft_etcd_test.go``) applied to the tensor path.
"""
from __future__ import annotations

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dragonboat_tpu.ops import BatchedQuorumEngine, commit_quorum, vote_tally
from dragonboat_tpu.ops.kernels import check_quorum
from dragonboat_tpu.wire import Message, MessageType
from raft_harness import new_test_raft

MT = MessageType


# ----------------------------------------------------------------------
# kernel-level randomized differential tests
# ----------------------------------------------------------------------


def scalar_quorum_index(matches, quorum):
    """The reference's tryCommit pick: sort ascending, take [n - quorum]
    (raft.go:888-909)."""
    s = sorted(matches)
    return s[len(s) - quorum]


def test_commit_quorum_matches_scalar_sort():
    rng = random.Random(7)
    G, P = 128, 7
    match = np.zeros((G, P), np.int32)
    voting = np.zeros((G, P), bool)
    quorum = np.zeros((G,), np.int32)
    expected = np.zeros((G,), np.int32)
    for g in range(G):
        n = rng.choice([1, 3, 5, 7])
        slots = rng.sample(range(P), n)
        vals = [rng.randrange(0, 1000) for _ in range(n)]
        for s, v in zip(slots, vals):
            voting[g, s] = True
            match[g, s] = v
            # noise in non-voting slots must not affect the result
        for s in range(P):
            if not voting[g, s]:
                match[g, s] = rng.randrange(0, 2000)
        quorum[g] = n // 2 + 1
        expected[g] = scalar_quorum_index(vals, int(quorum[g]))
    got = np.asarray(
        commit_quorum(jnp.asarray(match), jnp.asarray(voting), jnp.asarray(quorum))
    )
    np.testing.assert_array_equal(got, expected)


def test_vote_tally_matches_scalar_count():
    rng = random.Random(11)
    G, P = 64, 5
    votes = np.full((G, P), -1, np.int8)
    voting = np.zeros((G, P), bool)
    quorum = np.zeros((G,), np.int32)
    exp_granted = np.zeros((G,), np.int32)
    exp_rejected = np.zeros((G,), np.int32)
    for g in range(G):
        n = rng.choice([3, 5])
        for s in range(n):
            voting[g, s] = True
            v = rng.choice([-1, 0, 1])
            votes[g, s] = v
            if v == 1:
                exp_granted[g] += 1
            elif v == 0:
                exp_rejected[g] += 1
        quorum[g] = n // 2 + 1
    granted, rejected = vote_tally(
        jnp.asarray(votes), jnp.asarray(voting), jnp.asarray(quorum)
    )
    np.testing.assert_array_equal(np.asarray(granted), exp_granted)
    np.testing.assert_array_equal(np.asarray(rejected), exp_rejected)


def test_check_quorum_matches_leader_has_quorum():
    # scalar twin: raft.go:380-390 — count self + active voters, clear flags
    G, P = 8, 5
    active = np.zeros((G, P), bool)
    voting = np.zeros((G, P), bool)
    voting[:, :3] = True
    self_slot = np.zeros((G,), np.int32)
    quorum = np.full((G,), 2, np.int32)
    active[0, 1] = True          # self + 1 active  -> quorum
    active[1, 1] = active[1, 2] = True  # 3          -> quorum
    # row 2: only self active                        -> no quorum
    active[3, 4] = True          # non-voting activity doesn't count
    has_q, cleared = check_quorum(
        jnp.asarray(active),
        jnp.asarray(voting),
        jnp.asarray(self_slot),
        jnp.asarray(quorum),
    )
    np.testing.assert_array_equal(
        np.asarray(has_q), [True, True, False, False, False, False, False, False]
    )
    # voting members' activity consumed, non-voting preserved
    assert not np.asarray(cleared)[1, 1]
    assert np.asarray(cleared)[3, 4]


# ----------------------------------------------------------------------
# engine-level differential: scalar Raft leader vs BatchedQuorumEngine
# ----------------------------------------------------------------------


def make_scalar_leader(peers):
    """Elect node 1 leader of a fresh group and return the Raft."""
    r = new_test_raft(1, peers)
    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    for p in peers:
        if p != 1:
            r.handle(
                Message(from_=p, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP)
            )
        if r.is_leader():
            break
    assert r.is_leader()
    return r


def mirror_leader(eng, cid, r, peers):
    """Mirror freshly-elected scalar leader state into the engine."""
    # term_start = the noop appended at promotion (become_leader)
    eng.set_leader(
        cid,
        term=r.term,
        term_start=r.log.last_index(),
        last_index=r.log.last_index(),
    )


@pytest.mark.parametrize("peers", [[1, 2, 3], [1, 2, 3, 4, 5]])
def test_commit_differential_ordered_acks(peers):
    r = make_scalar_leader(peers)
    eng = BatchedQuorumEngine(n_groups=4, n_peers=len(peers))
    eng.add_group(1, node_ids=peers, self_id=1)
    mirror_leader(eng, 1, r, peers)
    assert eng.committed_index(1) == r.log.committed == 0

    # propose 10 entries, acking each from a rotating quorum subset
    rng = random.Random(3)
    for i in range(10):
        r.handle(
            Message(from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()])
        )
        eng.ack(1, 1, r.log.last_index())  # self append
        followers = [p for p in peers if p != 1]
        rng.shuffle(followers)
        for p in followers[: len(peers) // 2 + rng.randrange(0, 2)]:
            r.handle(
                Message(
                    from_=p,
                    to=1,
                    term=r.term,
                    type=MT.REPLICATE_RESP,
                    log_index=r.log.last_index(),
                )
            )
            eng.ack(1, p, r.log.last_index())
        out = eng.step(do_tick=False)
        assert eng.committed_index(1) == r.log.committed
        if 1 in out.commit:
            assert out.commit[1] == r.log.committed


def __propose_entry():
    from dragonboat_tpu.wire import Entry

    return Entry(cmd=b"x")


def test_commit_differential_random_stale_acks():
    """Stale, duplicate, and out-of-order acks must commit identically."""
    peers = [1, 2, 3, 4, 5]
    r = make_scalar_leader(peers)
    eng = BatchedQuorumEngine(n_groups=2, n_peers=5)
    eng.add_group(1, node_ids=peers, self_id=1)
    mirror_leader(eng, 1, r, peers)

    rng = random.Random(99)
    for _ in range(40):
        for _ in range(rng.randrange(0, 3)):
            r.handle(
                Message(from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()])
            )
            eng.ack(1, 1, r.log.last_index())
        last = r.log.last_index()
        for _ in range(rng.randrange(0, 6)):
            p = rng.choice(peers[1:])
            idx = rng.randrange(0, last + 1)  # may be stale
            r.handle(
                Message(
                    from_=p,
                    to=1,
                    term=r.term,
                    type=MT.REPLICATE_RESP,
                    log_index=idx,
                )
            )
            eng.ack(1, p, idx)
        eng.step(do_tick=False)
        assert eng.committed_index(1) == r.log.committed


def test_commit_differential_many_groups():
    """64 independent groups with interleaved random ack streams."""
    G = 64
    rng = random.Random(42)
    eng = BatchedQuorumEngine(n_groups=G, n_peers=5)
    scalars = {}
    for cid in range(1, G + 1):
        peers = [1, 2, 3] if cid % 2 else [1, 2, 3, 4, 5]
        r = make_scalar_leader(peers)
        scalars[cid] = (r, peers)
        eng.add_group(cid, node_ids=peers, self_id=1)
        mirror_leader(eng, cid, r, peers)

    for _ in range(10):
        for cid, (r, peers) in scalars.items():
            if rng.random() < 0.7:
                r.handle(
                    Message(
                        from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()]
                    )
                )
                eng.ack(cid, 1, r.log.last_index())
            for p in peers[1:]:
                if rng.random() < 0.6:
                    idx = rng.randrange(0, r.log.last_index() + 1)
                    r.handle(
                        Message(
                            from_=p,
                            to=1,
                            term=r.term,
                            type=MT.REPLICATE_RESP,
                            log_index=idx,
                        )
                    )
                    eng.ack(cid, p, idx)
        eng.step(do_tick=False)
        for cid, (r, _) in scalars.items():
            assert eng.committed_index(cid) == r.log.committed, f"group {cid}"


def test_election_differential():
    """Vote quorum flags fire exactly when the scalar candidate wins."""
    peers = [1, 2, 3, 4, 5]
    r = new_test_raft(1, peers)
    eng = BatchedQuorumEngine(n_groups=2, n_peers=5)
    eng.add_group(1, node_ids=peers, self_id=1)

    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    assert r.is_candidate()
    eng.set_candidate(1, term=r.term)
    eng.vote(1, 1, granted=True)  # campaign self-vote (raft.go:1098)

    out = eng.step(do_tick=False)
    assert not out.won and not out.lost

    r.handle(Message(from_=2, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP))
    eng.vote(1, 2, granted=True)
    out = eng.step(do_tick=False)
    assert not r.is_leader() and not out.won  # 2 of 5: no quorum yet

    r.handle(Message(from_=3, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP))
    eng.vote(1, 3, granted=True)
    out = eng.step(do_tick=False)
    assert r.is_leader()
    assert out.won == [1]


def test_election_rejection_differential():
    peers = [1, 2, 3]
    r = new_test_raft(1, peers)
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1)
    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    eng.set_candidate(1, term=r.term)
    eng.vote(1, 1, granted=True)
    for p in (2, 3):
        r.handle(
            Message(
                from_=p, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP, reject=True
            )
        )
        eng.vote(1, p, granted=False)
    out = eng.step(do_tick=False)
    assert r.is_follower()
    assert out.lost == [1]


def test_tick_election_due_matches_scalar_timing():
    """elect_due fires on exactly the tick the scalar oracle campaigns."""
    peers = [1, 2, 3]
    r = new_test_raft(1, peers)
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(
        1,
        node_ids=peers,
        self_id=1,
        election_timeout=10,
        rand_timeout=r.randomized_election_timeout,
    )
    fired_scalar = None
    fired_batched = None
    for tick in range(1, 30):
        was_candidate = r.is_candidate()
        r.tick()
        if fired_scalar is None and r.is_candidate() and not was_candidate:
            fired_scalar = tick
        out = eng.step(do_tick=True)
        if fired_batched is None and out.elect:
            fired_batched = tick
        if fired_scalar is not None:
            break
    assert fired_scalar is not None
    assert fired_batched == fired_scalar


def test_heartbeat_due_matches_scalar_timing():
    peers = [1, 2, 3]
    r = make_scalar_leader(peers)
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1, heartbeat_timeout=3)
    mirror_leader(eng, 1, r, peers)
    # scalar heartbeat_timeout from config: election=10, heartbeat=1; use
    # a dedicated engine row with timeout 3 and check periodicity instead
    fires = []
    for tick in range(1, 10):
        out = eng.step(do_tick=True)
        if out.heartbeat:
            fires.append(tick)
    assert fires == [3, 6, 9]


def test_rebase_preserves_commit_semantics():
    peers = [1, 2, 3]
    r = make_scalar_leader(peers)
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1)
    mirror_leader(eng, 1, r, peers)
    for i in range(5):
        r.handle(Message(from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()]))
        eng.ack(1, 1, r.log.last_index())
        for p in (2, 3):
            r.handle(
                Message(
                    from_=p,
                    to=1,
                    term=r.term,
                    type=MT.REPLICATE_RESP,
                    log_index=r.log.last_index(),
                )
            )
            eng.ack(1, p, r.log.last_index())
    eng.step(do_tick=False)
    assert eng.committed_index(1) == r.log.committed == 6  # noop + 5

    eng.rebase(1)
    assert eng.committed_index(1) == r.log.committed
    assert eng.groups[1].base == 6

    # progress continues identically post-rebase
    r.handle(Message(from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()]))
    eng.ack(1, 1, r.log.last_index())
    for p in (2, 3):
        r.handle(
            Message(
                from_=p,
                to=1,
                term=r.term,
                type=MT.REPLICATE_RESP,
                log_index=r.log.last_index(),
            )
        )
        eng.ack(1, p, r.log.last_index())
    eng.step(do_tick=False)
    assert eng.committed_index(1) == r.log.committed == 7


def test_group_lifecycle_row_reuse():
    eng = BatchedQuorumEngine(n_groups=2, n_peers=3)
    eng.add_group(1, node_ids=[1, 2, 3], self_id=1)
    eng.add_group(2, node_ids=[1, 2, 3], self_id=1)
    with pytest.raises(RuntimeError):
        eng.add_group(3, node_ids=[1, 2, 3], self_id=1)
    eng.remove_group(1)
    eng.add_group(3, node_ids=[1, 2, 3], self_id=1)
    eng.set_leader(3, term=1, term_start=1, last_index=1)
    eng.ack(3, 1, 1)
    eng.ack(3, 2, 1)
    eng.step(do_tick=False)
    assert eng.committed_index(3) == 1


def test_stale_queued_votes_purged_on_new_campaign():
    """Votes queued before a state transition belong to the old term and
    must not count toward the new term's tally (scalar twin drops
    mismatched-term responses, raft.go:1062-1080)."""
    peers = [1, 2, 3, 4, 5]
    eng = BatchedQuorumEngine(n_groups=1, n_peers=5)
    eng.add_group(1, node_ids=peers, self_id=1)
    eng.set_candidate(1, term=1)
    eng.vote(1, 2, granted=True)  # queued, never stepped — term-1 vote
    # campaign restarts at term 2 before the engine ever dispatched
    eng.set_candidate(1, term=2)
    eng.vote(1, 1, granted=True)
    eng.vote(1, 3, granted=True)
    out = eng.step(do_tick=False)
    # only 2 of quorum-3 granted in term 2: must NOT have won
    assert out.won == []
    # peer 2's real term-2 vote still lands (first-vote guard was purged)
    eng.vote(1, 2, granted=True)
    out = eng.step(do_tick=False)
    assert out.won == [1]


def test_stale_queued_acks_purged_on_leader_transition():
    peers = [1, 2, 3]
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1)
    eng.set_leader(1, term=1, term_start=1, last_index=4)
    eng.ack(1, 2, 3)  # queued old-term ack, never stepped
    eng.set_follower(1, term=2)
    eng.set_leader(1, term=3, term_start=5, last_index=5)
    eng.ack(1, 1, 5)
    out = eng.step(do_tick=False)
    # without peer 2's (purged) stale ack nothing past term_start commits
    assert eng.committed_index(1) == 0
    eng.ack(1, 2, 5)
    eng.step(do_tick=False)
    assert eng.committed_index(1) == 5


def test_ack_block_equivalent_to_per_event_acks():
    """The vectorized bulk-ingest path (ack_block) must produce exactly the
    same commit outcomes as per-event ack() staging."""
    import numpy as np

    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    def build():
        eng = BatchedQuorumEngine(8, 3, event_cap=64)
        for cid in range(1, 9):
            eng.add_group(cid, node_ids=[1, 2, 3], self_id=1)
            eng.set_leader(cid, term=1, term_start=1, last_index=1)
        return eng

    a, b = build(), build()
    # per-event staging on a
    for cid in range(1, 9):
        a.ack(cid, 1, 5)
        a.ack(cid, 2, 5)
    ra = a.step(do_tick=False)
    # block staging on b (same rows/slots/rels)
    rows = np.tile(np.arange(8, dtype=np.int32), 2)
    slots = np.concatenate([np.zeros(8, np.int32), np.ones(8, np.int32)])
    rels = np.full(16, 5, np.int32)  # base is 0 for fresh groups
    b.ack_block(rows, slots, rels)
    rb = b.step(do_tick=False)
    assert ra.commit == rb.commit
    for cid in range(1, 9):
        assert a.committed_index(cid) == b.committed_index(cid) == 5

    # oversized blocks chunk without recompilation or loss
    c = build()
    big_rows = np.tile(np.arange(8, dtype=np.int32), 40)  # 320 > cap 64
    big_slots = np.tile(slots, 20)
    big_rels = np.tile(np.arange(1, 41, dtype=np.int32).repeat(8), 1)[:320]
    c.ack_block(big_rows, np.resize(big_slots, 320), np.sort(big_rels))
    c.step(do_tick=False)  # must not raise

    # bounds are validated
    import pytest

    with pytest.raises(ValueError):
        a.ack_block(np.array([99], np.int32), np.array([0], np.int32),
                    np.array([1], np.int32))


# ----------------------------------------------------------------------
# dense-ingestion kernel: bit-identity with the sparse scatter kernel
# ----------------------------------------------------------------------


def _random_engine(rng, n_groups=24, n_peers=3, cap=256):
    eng = BatchedQuorumEngine(n_groups, n_peers, event_cap=cap)
    for cid in range(1, n_groups + 1):
        peers = list(range(1, n_peers + 1))
        eng.add_group(cid, node_ids=peers, self_id=1)
        role = rng.random()
        if role < 0.6:
            eng.set_leader(cid, term=2, term_start=3, last_index=3 + rng.randrange(4))
        elif role < 0.8:
            eng.set_candidate(cid, term=2)
        # else: stays follower
    eng._upload_dirty()
    return eng


def _state_equal(a, b):
    for name, va in a._asdict().items():
        vb = getattr(b, name)
        assert np.array_equal(np.asarray(va), np.asarray(vb)), name


@pytest.mark.parametrize("do_tick", [False, True])
def test_dense_kernel_matches_sparse_kernel(do_tick):
    """quorum_step_dense(aggregated batch) ≡ quorum_step(sparse batch).

    Scatter-max aggregation is order-independent, so collapsing a round's
    events into per-cell maxima must leave every state field and output
    flag bit-identical — including duplicate acks, stale (lower) acks,
    zero-value heartbeat acks, and first-wins-deduped votes.
    """
    from dragonboat_tpu.ops.kernels import quorum_step, quorum_step_dense

    rng = random.Random(1234 + do_tick)
    g, p, cap = 24, 3, 256
    sparse_eng = _random_engine(rng, g, p, cap)
    dense_eng = _random_engine(random.Random(1234 + do_tick), g, p, cap)
    _state_equal(sparse_eng.dev, dense_eng.dev)

    for round_no in range(6):
        # random ack batch: duplicates, stale values, heartbeat zero-acks
        n_acks = rng.randrange(0, 64)
        acks = [
            (rng.randrange(g), rng.randrange(p), rng.choice([0, 1, 2, 5, 9]))
            for _ in range(n_acks)
        ]
        # votes: first-wins per cell (the engine dedups within a batch;
        # duplicate sparse vote scatters would be scatter-order-defined)
        vote_cells = {}
        for _ in range(rng.randrange(0, 8)):
            cell = (rng.randrange(g), rng.randrange(p))
            vote_cells.setdefault(cell, rng.choice([0, 1]))
        votes = [(r, s, v) for (r, s), v in vote_cells.items()]

        # sparse dispatch
        ag = np.zeros((cap,), np.int32)
        ap = np.zeros((cap,), np.int32)
        av = np.zeros((cap,), np.int32)
        avalid = np.zeros((cap,), bool)
        for i, (r, s, v) in enumerate(acks):
            ag[i], ap[i], av[i], avalid[i] = r, s, v, True
        vg = np.zeros((cap,), np.int32)
        vp = np.zeros((cap,), np.int32)
        vv = np.zeros((cap,), np.int8)
        vvalid = np.zeros((cap,), bool)
        for i, (r, s, v) in enumerate(votes):
            vg[i], vp[i], vv[i], vvalid[i] = r, s, v, True
        out_s = quorum_step(
            sparse_eng.dev,
            jnp.asarray(ag), jnp.asarray(ap), jnp.asarray(av),
            jnp.asarray(avalid), jnp.asarray(vg), jnp.asarray(vp),
            jnp.asarray(vv), jnp.asarray(vvalid),
            do_tick=do_tick, track_contact=True, has_votes=True,
        )
        sparse_eng.dev = out_s.state

        # dense dispatch of the SAME events, host-aggregated
        ack_max = np.zeros((g, p), np.int32)
        touched = np.zeros((g, p), bool)
        for r, s, v in acks:
            ack_max[r, s] = max(ack_max[r, s], v)
            touched[r, s] = True
        vote_new = np.full((g, p), -1, np.int8)
        for r, s, v in votes:
            vote_new[r, s] = v
        out_d = quorum_step_dense(
            dense_eng.dev,
            jnp.asarray(ack_max), jnp.asarray(touched), jnp.asarray(vote_new),
            do_tick=do_tick, track_contact=True, has_votes=True,
        )
        dense_eng.dev = out_d.state

        _state_equal(out_s.state, out_d.state)
        for field_ in ("committed", "won", "lost"):
            assert np.array_equal(
                np.asarray(getattr(out_s, field_)),
                np.asarray(getattr(out_d, field_)),
            ), (field_, round_no)
        for i, fname in enumerate(("elect_due", "hb_due", "checkq_demote")):
            assert np.array_equal(
                np.asarray(out_s.flags[i]), np.asarray(out_d.flags[i])
            ), (fname, round_no)


def test_engine_dense_ingest_matches_sparse():
    """The engine's dense auto-path must be observationally identical to
    the sparse path across multi-round workloads with ticks."""
    rng_seed = 77

    def run(dense):
        rng = random.Random(rng_seed)
        eng = BatchedQuorumEngine(16, 3, event_cap=128, dense_ingest=dense)
        for cid in range(1, 17):
            eng.add_group(cid, node_ids=[1, 2, 3], self_id=1)
            eng.set_leader(cid, term=1, term_start=1, last_index=1)
        results = []
        idx = {cid: 1 for cid in range(1, 17)}
        for _ in range(8):
            for _ in range(rng.randrange(4, 40)):
                cid = rng.randrange(1, 17)
                idx[cid] += 1
                eng.ack(cid, 1, idx[cid])
                if rng.random() < 0.8:
                    eng.ack(cid, 2, idx[cid])
                if rng.random() < 0.2:
                    eng.heartbeat_resp(cid, 3)
            res = eng.step(do_tick=True)
            results.append((dict(res.commit), list(res.heartbeat)))
        return results, {cid: eng.committed_index(cid) for cid in range(1, 17)}

    res_sparse, final_sparse = run(False)
    res_dense, final_dense = run(True)
    assert res_sparse == res_dense
    assert final_sparse == final_dense


def test_has_votes_false_matches_empty_vote_batch():
    """has_votes=False (compiled-out vote ingest) ≡ an empty vote batch."""
    from dragonboat_tpu.ops.kernels import quorum_step

    eng_a = _random_engine(random.Random(9), 12, 3, 64)
    eng_b = _random_engine(random.Random(9), 12, 3, 64)
    cap = 64
    ag = np.array([0, 1, 2, 5] + [0] * (cap - 4), np.int32)
    ap = np.array([1, 2, 0, 1] + [0] * (cap - 4), np.int32)
    av = np.array([4, 5, 6, 7] + [0] * (cap - 4), np.int32)
    avalid = np.array([True] * 4 + [False] * (cap - 4))
    zero_votes = (
        jnp.zeros((cap,), jnp.int32), jnp.zeros((cap,), jnp.int32),
        jnp.zeros((cap,), jnp.int8), jnp.zeros((cap,), bool),
    )
    dummy_votes = (
        jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
        jnp.zeros((1,), jnp.int8), jnp.zeros((1,), bool),
    )
    out_a = quorum_step(
        eng_a.dev, jnp.asarray(ag), jnp.asarray(ap), jnp.asarray(av),
        jnp.asarray(avalid), *zero_votes, do_tick=True, has_votes=True,
    )
    out_b = quorum_step(
        eng_b.dev, jnp.asarray(ag), jnp.asarray(ap), jnp.asarray(av),
        jnp.asarray(avalid), *dummy_votes, do_tick=True, has_votes=False,
    )
    _state_equal(out_a.state, out_b.state)
    assert np.array_equal(np.asarray(out_a.committed), np.asarray(out_b.committed))


def test_multistep_has_votes_false_accepts_dummies():
    """Both multisteps must accept arbitrary-shape vote dummies when
    has_votes=False and match the has_votes=True/empty-votes result."""
    from dragonboat_tpu.ops.kernels import (
        quorum_multistep,
        quorum_multistep_dense,
    )

    g, p, cap, r = 8, 3, 16, 4
    eng_a = _random_engine(random.Random(3), g, p, cap)
    eng_b = _random_engine(random.Random(3), g, p, cap)

    rows = np.arange(g, dtype=np.int32)
    ag = np.broadcast_to(np.concatenate([rows, rows]), (r, cap)).copy()
    ap = np.broadcast_to(
        np.concatenate([np.zeros(g, np.int32), np.ones(g, np.int32)]), (r, cap)
    ).copy()
    av = np.broadcast_to(
        4 + np.arange(r, dtype=np.int32)[:, None], (r, cap)
    ).copy()
    avalid = np.ones((r, cap), bool)
    zi = np.zeros((r, cap), np.int32)
    z8 = np.zeros((r, cap), np.int8)
    zb = np.zeros((r, cap), bool)

    out_t = quorum_multistep(
        eng_a.dev, *(jnp.asarray(x) for x in (ag, ap, av, avalid, zi, zi, z8, zb)),
        do_tick=True, has_votes=True,
    )
    out_f = quorum_multistep(
        eng_b.dev, jnp.asarray(ag), jnp.asarray(ap), jnp.asarray(av),
        jnp.asarray(avalid),
        # dummies of unrelated shape — must not be scanned
        jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
        jnp.zeros((1,), jnp.int8), jnp.zeros((1,), bool),
        do_tick=True, has_votes=False,
    )
    _state_equal(out_t.state, out_f.state)

    # dense multistep: same contract
    eng_c = _random_engine(random.Random(3), g, p, cap)
    eng_d = _random_engine(random.Random(3), g, p, cap)
    ack_max = np.zeros((r, g, p), np.int32)
    touched = np.zeros((r, g, p), bool)
    for rr in range(r):
        ack_max[rr, :, 0] = 4 + rr
        ack_max[rr, :, 1] = 4 + rr
        touched[rr, :, :2] = True
    vt = np.full((r, g, p), -1, np.int8)
    out_dt = quorum_multistep_dense(
        eng_c.dev, jnp.asarray(ack_max), jnp.asarray(touched), jnp.asarray(vt),
        do_tick=True, has_votes=True,
    )
    out_df = quorum_multistep_dense(
        eng_d.dev, jnp.asarray(ack_max), jnp.asarray(touched),
        jnp.zeros((1, 1), jnp.int8),  # dummy, not scanned
        do_tick=True, has_votes=False,
    )
    _state_equal(out_dt.state, out_df.state)
    _state_equal(out_t.state, out_dt.state)  # sparse ≡ dense end state


def test_engine_dense_ingest_validation():
    with pytest.raises(ValueError):
        BatchedQuorumEngine(4, 3, dense_ingest=1)
    with pytest.raises(ValueError):
        BatchedQuorumEngine(4, 3, dense_ingest="always")


def test_kth_largest_network_all_widths():
    """_kth_largest across every specialized width (P=1..8 use the
    elementwise compare-exchange network; P=9 exercises the (G,P,P)
    rank-select fallback) against a NumPy sort oracle, including
    all-masked rows, ties, and every valid k."""
    from dragonboat_tpu.ops.kernels import _kth_largest
    from dragonboat_tpu.ops.state import INDEX_MIN

    rng = random.Random(23)
    for P in range(1, 10):
        G = 160
        vals = np.zeros((G, P), np.int32)
        mask = np.zeros((G, P), bool)
        k = np.ones((G,), np.int32)
        expected = np.zeros((G,), np.int32)
        for g in range(G):
            n = rng.randrange(0, P + 1)
            slots = rng.sample(range(P), n)
            # small value range forces ties; non-masked slots hold noise
            for s in range(P):
                vals[g, s] = rng.randrange(0, 6)
            for s in slots:
                mask[g, s] = True
            masked = sorted(
                (vals[g, s] for s in slots), reverse=True
            )
            if n == 0:
                k[g] = 1
                expected[g] = INDEX_MIN  # all-masked row: min sentinel
            else:
                k[g] = rng.randrange(1, n + 1)
                expected[g] = masked[k[g] - 1]
        got = np.asarray(
            _kth_largest(jnp.asarray(vals), jnp.asarray(mask), jnp.asarray(k))
        )
        np.testing.assert_array_equal(got, expected, err_msg=f"P={P}")
