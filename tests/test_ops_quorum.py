"""Differential tests: batched quorum kernels vs the scalar raft oracle.

The north star demands the batched engine's commitIndex outputs be
bit-identical to the scalar path (SURVEY.md §6); these tests replay the
exact same event streams through both and compare watermarks after every
round.  This is the conformance-gate analog of the reference's etcd-ported
suite (``internal/raft/raft_etcd_test.go``) applied to the tensor path.
"""
from __future__ import annotations

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dragonboat_tpu.ops import BatchedQuorumEngine, commit_quorum, vote_tally
from dragonboat_tpu.ops.kernels import check_quorum
from dragonboat_tpu.wire import Message, MessageType
from raft_harness import new_test_raft

MT = MessageType


# ----------------------------------------------------------------------
# kernel-level randomized differential tests
# ----------------------------------------------------------------------


def scalar_quorum_index(matches, quorum):
    """The reference's tryCommit pick: sort ascending, take [n - quorum]
    (raft.go:888-909)."""
    s = sorted(matches)
    return s[len(s) - quorum]


def test_commit_quorum_matches_scalar_sort():
    rng = random.Random(7)
    G, P = 128, 7
    match = np.zeros((G, P), np.int32)
    voting = np.zeros((G, P), bool)
    quorum = np.zeros((G,), np.int32)
    expected = np.zeros((G,), np.int32)
    for g in range(G):
        n = rng.choice([1, 3, 5, 7])
        slots = rng.sample(range(P), n)
        vals = [rng.randrange(0, 1000) for _ in range(n)]
        for s, v in zip(slots, vals):
            voting[g, s] = True
            match[g, s] = v
            # noise in non-voting slots must not affect the result
        for s in range(P):
            if not voting[g, s]:
                match[g, s] = rng.randrange(0, 2000)
        quorum[g] = n // 2 + 1
        expected[g] = scalar_quorum_index(vals, int(quorum[g]))
    got = np.asarray(
        commit_quorum(jnp.asarray(match), jnp.asarray(voting), jnp.asarray(quorum))
    )
    np.testing.assert_array_equal(got, expected)


def test_vote_tally_matches_scalar_count():
    rng = random.Random(11)
    G, P = 64, 5
    votes = np.full((G, P), -1, np.int8)
    voting = np.zeros((G, P), bool)
    quorum = np.zeros((G,), np.int32)
    exp_granted = np.zeros((G,), np.int32)
    exp_rejected = np.zeros((G,), np.int32)
    for g in range(G):
        n = rng.choice([3, 5])
        for s in range(n):
            voting[g, s] = True
            v = rng.choice([-1, 0, 1])
            votes[g, s] = v
            if v == 1:
                exp_granted[g] += 1
            elif v == 0:
                exp_rejected[g] += 1
        quorum[g] = n // 2 + 1
    granted, rejected = vote_tally(
        jnp.asarray(votes), jnp.asarray(voting), jnp.asarray(quorum)
    )
    np.testing.assert_array_equal(np.asarray(granted), exp_granted)
    np.testing.assert_array_equal(np.asarray(rejected), exp_rejected)


def test_check_quorum_matches_leader_has_quorum():
    # scalar twin: raft.go:380-390 — count self + active voters, clear flags
    G, P = 8, 5
    active = np.zeros((G, P), bool)
    voting = np.zeros((G, P), bool)
    voting[:, :3] = True
    self_slot = np.zeros((G,), np.int32)
    quorum = np.full((G,), 2, np.int32)
    active[0, 1] = True          # self + 1 active  -> quorum
    active[1, 1] = active[1, 2] = True  # 3          -> quorum
    # row 2: only self active                        -> no quorum
    active[3, 4] = True          # non-voting activity doesn't count
    has_q, cleared = check_quorum(
        jnp.asarray(active),
        jnp.asarray(voting),
        jnp.asarray(self_slot),
        jnp.asarray(quorum),
    )
    np.testing.assert_array_equal(
        np.asarray(has_q), [True, True, False, False, False, False, False, False]
    )
    # voting members' activity consumed, non-voting preserved
    assert not np.asarray(cleared)[1, 1]
    assert np.asarray(cleared)[3, 4]


# ----------------------------------------------------------------------
# engine-level differential: scalar Raft leader vs BatchedQuorumEngine
# ----------------------------------------------------------------------


def make_scalar_leader(peers):
    """Elect node 1 leader of a fresh group and return the Raft."""
    r = new_test_raft(1, peers)
    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    for p in peers:
        if p != 1:
            r.handle(
                Message(from_=p, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP)
            )
        if r.is_leader():
            break
    assert r.is_leader()
    return r


def mirror_leader(eng, cid, r, peers):
    """Mirror freshly-elected scalar leader state into the engine."""
    # term_start = the noop appended at promotion (become_leader)
    eng.set_leader(
        cid,
        term=r.term,
        term_start=r.log.last_index(),
        last_index=r.log.last_index(),
    )


@pytest.mark.parametrize("peers", [[1, 2, 3], [1, 2, 3, 4, 5]])
def test_commit_differential_ordered_acks(peers):
    r = make_scalar_leader(peers)
    eng = BatchedQuorumEngine(n_groups=4, n_peers=len(peers))
    eng.add_group(1, node_ids=peers, self_id=1)
    mirror_leader(eng, 1, r, peers)
    assert eng.committed_index(1) == r.log.committed == 0

    # propose 10 entries, acking each from a rotating quorum subset
    rng = random.Random(3)
    for i in range(10):
        r.handle(
            Message(from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()])
        )
        eng.ack(1, 1, r.log.last_index())  # self append
        followers = [p for p in peers if p != 1]
        rng.shuffle(followers)
        for p in followers[: len(peers) // 2 + rng.randrange(0, 2)]:
            r.handle(
                Message(
                    from_=p,
                    to=1,
                    term=r.term,
                    type=MT.REPLICATE_RESP,
                    log_index=r.log.last_index(),
                )
            )
            eng.ack(1, p, r.log.last_index())
        out = eng.step(do_tick=False)
        assert eng.committed_index(1) == r.log.committed
        if 1 in out.commit:
            assert out.commit[1] == r.log.committed


def __propose_entry():
    from dragonboat_tpu.wire import Entry

    return Entry(cmd=b"x")


def test_commit_differential_random_stale_acks():
    """Stale, duplicate, and out-of-order acks must commit identically."""
    peers = [1, 2, 3, 4, 5]
    r = make_scalar_leader(peers)
    eng = BatchedQuorumEngine(n_groups=2, n_peers=5)
    eng.add_group(1, node_ids=peers, self_id=1)
    mirror_leader(eng, 1, r, peers)

    rng = random.Random(99)
    for _ in range(40):
        for _ in range(rng.randrange(0, 3)):
            r.handle(
                Message(from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()])
            )
            eng.ack(1, 1, r.log.last_index())
        last = r.log.last_index()
        for _ in range(rng.randrange(0, 6)):
            p = rng.choice(peers[1:])
            idx = rng.randrange(0, last + 1)  # may be stale
            r.handle(
                Message(
                    from_=p,
                    to=1,
                    term=r.term,
                    type=MT.REPLICATE_RESP,
                    log_index=idx,
                )
            )
            eng.ack(1, p, idx)
        eng.step(do_tick=False)
        assert eng.committed_index(1) == r.log.committed


def test_commit_differential_many_groups():
    """64 independent groups with interleaved random ack streams."""
    G = 64
    rng = random.Random(42)
    eng = BatchedQuorumEngine(n_groups=G, n_peers=5)
    scalars = {}
    for cid in range(1, G + 1):
        peers = [1, 2, 3] if cid % 2 else [1, 2, 3, 4, 5]
        r = make_scalar_leader(peers)
        scalars[cid] = (r, peers)
        eng.add_group(cid, node_ids=peers, self_id=1)
        mirror_leader(eng, cid, r, peers)

    for _ in range(10):
        for cid, (r, peers) in scalars.items():
            if rng.random() < 0.7:
                r.handle(
                    Message(
                        from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()]
                    )
                )
                eng.ack(cid, 1, r.log.last_index())
            for p in peers[1:]:
                if rng.random() < 0.6:
                    idx = rng.randrange(0, r.log.last_index() + 1)
                    r.handle(
                        Message(
                            from_=p,
                            to=1,
                            term=r.term,
                            type=MT.REPLICATE_RESP,
                            log_index=idx,
                        )
                    )
                    eng.ack(cid, p, idx)
        eng.step(do_tick=False)
        for cid, (r, _) in scalars.items():
            assert eng.committed_index(cid) == r.log.committed, f"group {cid}"


def test_election_differential():
    """Vote quorum flags fire exactly when the scalar candidate wins."""
    peers = [1, 2, 3, 4, 5]
    r = new_test_raft(1, peers)
    eng = BatchedQuorumEngine(n_groups=2, n_peers=5)
    eng.add_group(1, node_ids=peers, self_id=1)

    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    assert r.is_candidate()
    eng.set_candidate(1, term=r.term)
    eng.vote(1, 1, granted=True)  # campaign self-vote (raft.go:1098)

    out = eng.step(do_tick=False)
    assert not out.won and not out.lost

    r.handle(Message(from_=2, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP))
    eng.vote(1, 2, granted=True)
    out = eng.step(do_tick=False)
    assert not r.is_leader() and not out.won  # 2 of 5: no quorum yet

    r.handle(Message(from_=3, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP))
    eng.vote(1, 3, granted=True)
    out = eng.step(do_tick=False)
    assert r.is_leader()
    assert out.won == [1]


def test_election_rejection_differential():
    peers = [1, 2, 3]
    r = new_test_raft(1, peers)
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1)
    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    eng.set_candidate(1, term=r.term)
    eng.vote(1, 1, granted=True)
    for p in (2, 3):
        r.handle(
            Message(
                from_=p, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP, reject=True
            )
        )
        eng.vote(1, p, granted=False)
    out = eng.step(do_tick=False)
    assert r.is_follower()
    assert out.lost == [1]


def test_tick_election_due_matches_scalar_timing():
    """elect_due fires on exactly the tick the scalar oracle campaigns."""
    peers = [1, 2, 3]
    r = new_test_raft(1, peers)
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(
        1,
        node_ids=peers,
        self_id=1,
        election_timeout=10,
        rand_timeout=r.randomized_election_timeout,
    )
    fired_scalar = None
    fired_batched = None
    for tick in range(1, 30):
        was_candidate = r.is_candidate()
        r.tick()
        if fired_scalar is None and r.is_candidate() and not was_candidate:
            fired_scalar = tick
        out = eng.step(do_tick=True)
        if fired_batched is None and out.elect:
            fired_batched = tick
        if fired_scalar is not None:
            break
    assert fired_scalar is not None
    assert fired_batched == fired_scalar


def test_heartbeat_due_matches_scalar_timing():
    peers = [1, 2, 3]
    r = make_scalar_leader(peers)
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1, heartbeat_timeout=3)
    mirror_leader(eng, 1, r, peers)
    # scalar heartbeat_timeout from config: election=10, heartbeat=1; use
    # a dedicated engine row with timeout 3 and check periodicity instead
    fires = []
    for tick in range(1, 10):
        out = eng.step(do_tick=True)
        if out.heartbeat:
            fires.append(tick)
    assert fires == [3, 6, 9]


def test_rebase_preserves_commit_semantics():
    peers = [1, 2, 3]
    r = make_scalar_leader(peers)
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1)
    mirror_leader(eng, 1, r, peers)
    for i in range(5):
        r.handle(Message(from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()]))
        eng.ack(1, 1, r.log.last_index())
        for p in (2, 3):
            r.handle(
                Message(
                    from_=p,
                    to=1,
                    term=r.term,
                    type=MT.REPLICATE_RESP,
                    log_index=r.log.last_index(),
                )
            )
            eng.ack(1, p, r.log.last_index())
    eng.step(do_tick=False)
    assert eng.committed_index(1) == r.log.committed == 6  # noop + 5

    eng.rebase(1)
    assert eng.committed_index(1) == r.log.committed
    assert eng.groups[1].base == 6

    # progress continues identically post-rebase
    r.handle(Message(from_=1, to=1, type=MT.PROPOSE, entries=[__propose_entry()]))
    eng.ack(1, 1, r.log.last_index())
    for p in (2, 3):
        r.handle(
            Message(
                from_=p,
                to=1,
                term=r.term,
                type=MT.REPLICATE_RESP,
                log_index=r.log.last_index(),
            )
        )
        eng.ack(1, p, r.log.last_index())
    eng.step(do_tick=False)
    assert eng.committed_index(1) == r.log.committed == 7


def test_group_lifecycle_row_reuse():
    eng = BatchedQuorumEngine(n_groups=2, n_peers=3)
    eng.add_group(1, node_ids=[1, 2, 3], self_id=1)
    eng.add_group(2, node_ids=[1, 2, 3], self_id=1)
    with pytest.raises(RuntimeError):
        eng.add_group(3, node_ids=[1, 2, 3], self_id=1)
    eng.remove_group(1)
    eng.add_group(3, node_ids=[1, 2, 3], self_id=1)
    eng.set_leader(3, term=1, term_start=1, last_index=1)
    eng.ack(3, 1, 1)
    eng.ack(3, 2, 1)
    eng.step(do_tick=False)
    assert eng.committed_index(3) == 1


def test_stale_queued_votes_purged_on_new_campaign():
    """Votes queued before a state transition belong to the old term and
    must not count toward the new term's tally (scalar twin drops
    mismatched-term responses, raft.go:1062-1080)."""
    peers = [1, 2, 3, 4, 5]
    eng = BatchedQuorumEngine(n_groups=1, n_peers=5)
    eng.add_group(1, node_ids=peers, self_id=1)
    eng.set_candidate(1, term=1)
    eng.vote(1, 2, granted=True)  # queued, never stepped — term-1 vote
    # campaign restarts at term 2 before the engine ever dispatched
    eng.set_candidate(1, term=2)
    eng.vote(1, 1, granted=True)
    eng.vote(1, 3, granted=True)
    out = eng.step(do_tick=False)
    # only 2 of quorum-3 granted in term 2: must NOT have won
    assert out.won == []
    # peer 2's real term-2 vote still lands (first-vote guard was purged)
    eng.vote(1, 2, granted=True)
    out = eng.step(do_tick=False)
    assert out.won == [1]


def test_stale_queued_acks_purged_on_leader_transition():
    peers = [1, 2, 3]
    eng = BatchedQuorumEngine(n_groups=1, n_peers=3)
    eng.add_group(1, node_ids=peers, self_id=1)
    eng.set_leader(1, term=1, term_start=1, last_index=4)
    eng.ack(1, 2, 3)  # queued old-term ack, never stepped
    eng.set_follower(1, term=2)
    eng.set_leader(1, term=3, term_start=5, last_index=5)
    eng.ack(1, 1, 5)
    out = eng.step(do_tick=False)
    # without peer 2's (purged) stale ack nothing past term_start commits
    assert eng.committed_index(1) == 0
    eng.ack(1, 2, 5)
    eng.step(do_tick=False)
    assert eng.committed_index(1) == 5


def test_ack_block_equivalent_to_per_event_acks():
    """The vectorized bulk-ingest path (ack_block) must produce exactly the
    same commit outcomes as per-event ack() staging."""
    import numpy as np

    from dragonboat_tpu.ops.engine import BatchedQuorumEngine

    def build():
        eng = BatchedQuorumEngine(8, 3, event_cap=64)
        for cid in range(1, 9):
            eng.add_group(cid, node_ids=[1, 2, 3], self_id=1)
            eng.set_leader(cid, term=1, term_start=1, last_index=1)
        return eng

    a, b = build(), build()
    # per-event staging on a
    for cid in range(1, 9):
        a.ack(cid, 1, 5)
        a.ack(cid, 2, 5)
    ra = a.step(do_tick=False)
    # block staging on b (same rows/slots/rels)
    rows = np.tile(np.arange(8, dtype=np.int32), 2)
    slots = np.concatenate([np.zeros(8, np.int32), np.ones(8, np.int32)])
    rels = np.full(16, 5, np.int32)  # base is 0 for fresh groups
    b.ack_block(rows, slots, rels)
    rb = b.step(do_tick=False)
    assert ra.commit == rb.commit
    for cid in range(1, 9):
        assert a.committed_index(cid) == b.committed_index(cid) == 5

    # oversized blocks chunk without recompilation or loss
    c = build()
    big_rows = np.tile(np.arange(8, dtype=np.int32), 40)  # 320 > cap 64
    big_slots = np.tile(slots, 20)
    big_rels = np.tile(np.arange(1, 41, dtype=np.int32).repeat(8), 1)[:320]
    c.ack_block(big_rows, np.resize(big_slots, 320), np.sort(big_rels))
    c.step(do_tick=False)  # must not raise

    # bounds are validated
    import pytest

    with pytest.raises(ValueError):
        a.ack_block(np.array([99], np.int32), np.array([0], np.int32),
                    np.array([1], np.int32))
