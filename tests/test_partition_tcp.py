"""Asymmetric network partitions over the REAL transport.

The reference's monkey harness partitions NodeHosts at the transport
layer (``monkey.go:184-213``).  Here the injection lives in the native
engine (``natr_set_partition``): in fast-lane deployments every raft
message for a remote — fast-path AND scalar-path — rides the single
ordered native stream, so dropping at the ingest choke point (inbound)
and the flush pass (outbound) is a true netsplit: a partitioned leader
loses its quorum, the majority side elects and commits without it, and
healing lets the protocol's own machinery (resends, ejects,
re-enrollment, catch-up) reconverge the fleet.
"""
from __future__ import annotations

import socket
import time

import pytest

from tests import loadwait

from dragonboat_tpu import Config, NodeHost, NodeHostConfig
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.native import natraft, natsm
from dragonboat_tpu.native.natsm import NativeKVStateMachine

pytestmark = [pytest.mark.skipif(
    not (natraft.available() and natsm.available()),
    reason="native libraries unavailable",
), pytest.mark.xdist_group("heavy-multiprocess")]

CID = 61


def _ports(n):
    return loadwait.ports(n)


def _mk(i, addrs, tmp_path, sms):
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / f"nh{i}"),
            rtt_millisecond=20,
            raft_address=addrs[i],
            expert=ExpertConfig(fast_lane=True, logdb_shards=2),
        )
    )

    def create(cluster_id, node_id):
        sm = NativeKVStateMachine(cluster_id, node_id)
        sms[i] = sm
        return sm

    nh.start_cluster(
        addrs, False, create,
        Config(cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
               check_quorum=True, snapshot_entries=0),
    )
    return nh


def _leader_id(nhs, exclude=None, timeout=60.0):
    # load-scaled (tests/loadwait.py): elections under a loaded tier-1
    # sweep stretch far past the idle-box margin (r07/r11 flake class).
    # The budget RE-SAMPLES while waiting (the r14 wait_until treatment)
    # — a deadline priced at an idle instant underprices a heavy
    # neighbor spinning up mid-election
    start = time.time()
    budget = loadwait.scaled(timeout)
    while True:
        for i, nh in nhs.items():
            if exclude is not None and i == exclude:
                continue  # the isolated rank's own (stale) view
            try:
                lid, ok = nh.get_leader_id(CID)
                if ok and lid in nhs and lid != exclude:
                    return lid
            except Exception:
                pass
        budget = max(budget, timeout * loadwait.scale())
        if time.time() - start >= budget:
            raise TimeoutError("no leader")
        time.sleep(0.05)


def test_partitioned_leader_deposed_then_heals(tmp_path):
    sms = {}
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = {i: _mk(i, addrs, tmp_path, sms) for i in addrs}
    try:
        nhs[1].get_node(CID).request_campaign()
        lid = _leader_id(nhs)
        leader = nhs[lid]
        s = leader.get_noop_session(CID)
        for j in range(50):
            assert leader.propose(
                s, f"a{j}=b{j}".encode(), timeout=60.0
            ).wait(120.0).completed

        # full symmetric netsplit: {leader} | {other two}
        others = [i for i in nhs if i != lid]
        for i in others:
            nhs[i].fastlane.set_partition(addrs[lid], True)
            leader.fastlane.set_partition(addrs[i], True)

        # the majority side must elect a replacement and commit without
        # the isolated rank
        new_lid = _leader_id(nhs, exclude=lid, timeout=90.0)
        assert new_lid != lid
        nh2 = nhs[new_lid]
        s2 = nh2.get_noop_session(CID)
        for j in range(50):
            assert nh2.propose(
                s2, f"c{j}=d{j}".encode(), timeout=60.0
            ).wait(120.0).completed
        assert nh2.sync_read(CID, "c49", timeout=20.0) == "d49"

        # the partition actually dropped traffic at the native layer
        dropped = sum(
            nhs[i].fastlane.stats().get("part_in_dropped", 0)
            + nhs[i].fastlane.stats().get("part_out_dropped", 0)
            for i in nhs
        )
        assert dropped > 0, "partition injection never dropped a message"

        # heal; the deposed rank rejoins and catches up
        for i in others:
            nhs[i].fastlane.set_partition(addrs[lid], False)
            leader.fastlane.set_partition(addrs[i], False)
        deadline = time.time() + loadwait.scaled(90.0)
        while time.time() < deadline:
            hs = {i: sm.get_hash() for i, sm in sms.items()}
            if len(set(hs.values())) == 1:
                break
            time.sleep(0.2)
        assert len(set(hs.values())) == 1, f"diverged after heal: {hs}"

        # and the healed fleet still commits (from the ex-leader's host,
        # which must now route to the current leader or have retaken it)
        s3 = nh2.get_noop_session(CID)
        assert nh2.propose(s3, b"post=heal", timeout=60.0).wait(120.0).completed
        assert nh2.sync_read(CID, "post", timeout=20.0) == "heal"
    finally:
        for nh in nhs.values():
            nh.stop()


def test_partition_minority_follower_no_disruption(tmp_path):
    """Isolating ONE follower must not disturb the majority: the leader
    keeps committing throughout, and the follower reconverges on heal."""
    sms = {}
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = {i: _mk(i, addrs, tmp_path, sms) for i in addrs}
    try:
        nhs[1].get_node(CID).request_campaign()
        lid = _leader_id(nhs)
        leader = nhs[lid]
        s = leader.get_noop_session(CID)
        for j in range(30):
            assert leader.propose(
                s, f"w{j}=x{j}".encode(), timeout=60.0
            ).wait(120.0).completed

        victim = [i for i in nhs if i != lid][0]
        for i in nhs:
            if i != victim:
                nhs[i].fastlane.set_partition(addrs[victim], True)
                nhs[victim].fastlane.set_partition(addrs[i], True)

        for j in range(60):
            assert leader.propose(
                s, f"m{j}=n{j}".encode(), timeout=60.0
            ).wait(120.0).completed
        # the leader never lost its quorum: still the same leader (no
        # wall-clock assert — per-op completion + stable leadership is
        # the load-tolerant form of "no disruption")
        cur, ok = leader.get_leader_id(CID)
        assert ok and cur == lid, (cur, lid)

        for i in nhs:
            if i != victim:
                nhs[i].fastlane.set_partition(addrs[victim], False)
                nhs[victim].fastlane.set_partition(addrs[i], False)
        deadline = time.time() + loadwait.scaled(90.0)
        while time.time() < deadline:
            hs = {i: sm.get_hash() for i, sm in sms.items()}
            if len(set(hs.values())) == 1:
                break
            time.sleep(0.2)
        assert len(set(hs.values())) == 1, f"diverged after heal: {hs}"
    finally:
        for nh in nhs.values():
            nh.stop()


def test_partition_blocks_snapshot_catchup_until_heal(tmp_path):
    """The snapshot path must respect the partition too (it rides its own
    transfer connections, not the native streams): a partitioned lagging
    follower stays stale — no snapshot sneaks through the split — and
    catches up only after heal (by whatever mix of entries/snapshot the
    leader chooses)."""
    sms = {}
    ports = _ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = {}
    for i in addrs:
        nh = NodeHost(
            NodeHostConfig(
                node_host_dir=str(tmp_path / f"nh{i}"),
                rtt_millisecond=20,
                raft_address=addrs[i],
                expert=ExpertConfig(fast_lane=True, logdb_shards=2),
            )
        )

        def create(cluster_id, node_id, i=i):
            sm = NativeKVStateMachine(cluster_id, node_id)
            sms[i] = sm
            return sm

        nh.start_cluster(
            addrs, False, create,
            Config(cluster_id=CID, node_id=i, election_rtt=10,
                   heartbeat_rtt=1, check_quorum=True,
                   snapshot_entries=40, compaction_overhead=5),
        )
        nhs[i] = nh
    try:
        nhs[1].get_node(CID).request_campaign()
        lid = _leader_id(nhs)
        leader = nhs[lid]
        s = leader.get_noop_session(CID)
        for j in range(30):
            assert leader.propose(
                s, f"a{j}=b{j}".encode(), timeout=60.0
            ).wait(120.0).completed

        victim = [i for i in nhs if i != lid][0]
        # settle BEFORE partitioning: pre-split entries may still be in
        # the victim's apply pipeline, and a baseline captured mid-flight
        # would later read as a "leak" when they finish applying
        deadline = time.time() + loadwait.scaled(60.0)
        while time.time() < deadline:
            if len({sm.get_hash() for sm in sms.values()}) == 1:
                break
            time.sleep(0.1)
        assert len({sm.get_hash() for sm in sms.values()}) == 1
        for i in nhs:
            if i != victim:
                nhs[i].fastlane.set_partition(addrs[victim], True)
                nhs[victim].fastlane.set_partition(addrs[i], True)
        stale = sms[victim].get_hash()

        # push the leader far past several snapshot boundaries so catching
        # the victim up will want a snapshot, not just entries
        for j in range(160):
            assert leader.propose(
                s, f"z{j}=w{j}".encode(), timeout=60.0
            ).wait(120.0).completed
        time.sleep(2.0)  # window in which a leaky snapshot would land
        assert sms[victim].get_hash() == stale, (
            "snapshot/entries leaked through the partition"
        )

        for i in nhs:
            if i != victim:
                nhs[i].fastlane.set_partition(addrs[victim], False)
                nhs[victim].fastlane.set_partition(addrs[i], False)
        deadline = time.time() + loadwait.scaled(120.0)
        while time.time() < deadline:
            hs = {i: sm.get_hash() for i, sm in sms.items()}
            if len(set(hs.values())) == 1:
                break
            time.sleep(0.2)
        assert len(set(hs.values())) == 1, f"victim never caught up: {hs}"
    finally:
        for nh in nhs.values():
            nh.stop()
