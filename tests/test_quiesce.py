"""Quiesce behavior at the NodeHost level.

Ports the reference's node-level quiesce family
(``/root/reference/node_test.go``: TestRaftNodeQuiesceCanBeDisabled,
TestNodesCanEnterQuiesce, TestNodesCanExitQuiesceByMakingProposal /
ByReadIndex / ByConfigChange; mechanism in ``quiesce.go``): a group with
no message activity for 10x election ticks enters quiesce on every
replica, stops heartbeating, and wakes on any user activity.  The runs
use the in-proc chan transport and a small rtt so the 10x window
elapses in wall-clock seconds.
"""
from __future__ import annotations

import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT = 5
CID = 3


class KVSM:
    def __init__(self, cluster_id, node_id):
        self.kv = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def get_hash(self):
        return 0

    def save_snapshot(self, w, files, done):
        import json

        data = json.dumps(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import json

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(json.loads(r.read(n).decode()))

    def close(self):
        pass


def _mk_trio(quiesce=True):
    addrs = {1: "q1:1", 2: "q2:1", 3: "q3:1"}
    router = ChanRouter()
    nhs = {}
    for i in addrs:
        nh = NodeHost(
            NodeHostConfig(
                node_host_dir=":memory:",
                rtt_millisecond=RTT,
                raft_address=addrs[i],
                raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                    src, rh, ch, router=router
                ),
            )
        )
        nh.start_cluster(
            addrs, False, lambda c, n: KVSM(c, n),
            Config(cluster_id=CID, node_id=i, election_rtt=10,
                   heartbeat_rtt=1, quiesce=quiesce),
        )
        nhs[i] = nh
    return nhs


def _leader(nhs, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs.values():
            lid, ok = nh.get_leader_id(CID)
            if ok and lid in nhs:
                return lid, nhs[lid]
        time.sleep(0.05)
    raise AssertionError("no leader")


def _quiesced(nhs):
    return [
        nh.get_node(CID).quiesce_mgr.quiesced() for nh in nhs.values()
    ]


def _wait_all_quiesced(nhs, timeout=60.0):
    """The 10x-election-tick idle window at rtt 5ms / election_rtt 10 is
    ~0.5s of ticks; generous deadline for slow CI."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(_quiesced(nhs)):
            return True
        time.sleep(0.1)
    return False


def _stop_all(nhs):
    for nh in nhs.values():
        nh.stop()


def test_nodes_can_enter_quiesce():
    """Reference TestNodesCanEnterQuiesce: an idle group quiesces on
    every replica (leader included) after the idle window."""
    nhs = _mk_trio(quiesce=True)
    try:
        nhs[1].get_node(CID).request_campaign()
        _leader(nhs)
        assert _wait_all_quiesced(nhs), _quiesced(nhs)
    finally:
        _stop_all(nhs)


def test_quiesce_can_be_disabled():
    """Reference TestRaftNodeQuiesceCanBeDisabled: with quiesce off
    (the default) the idle window never quiesces anybody."""
    nhs = _mk_trio(quiesce=False)
    try:
        nhs[1].get_node(CID).request_campaign()
        _leader(nhs)
        # the enter window at these settings is ~0.5s; wait well past it
        time.sleep(3.0)
        assert not any(_quiesced(nhs)), _quiesced(nhs)
    finally:
        _stop_all(nhs)


def test_exit_quiesce_by_proposal():
    """Reference TestNodesCanExitQuiesceByMakingProposal — and the
    proposal commits, proving replication actually resumed."""
    nhs = _mk_trio(quiesce=True)
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        assert _wait_all_quiesced(nhs)
        s = leader.get_noop_session(CID)
        rs = leader.propose(s, b"k=v", timeout=30.0)
        assert rs.wait(60.0).completed
        assert not leader.get_node(CID).quiesce_mgr.quiesced()
        # peers wake too (the exchanged activity exits their quiesce)
        deadline = time.time() + 30
        while time.time() < deadline and any(_quiesced(nhs)):
            time.sleep(0.1)
        assert not any(_quiesced(nhs)), _quiesced(nhs)
    finally:
        _stop_all(nhs)


def test_exit_quiesce_by_read_index():
    """Reference TestNodesCanExitQuiesceByReadIndex."""
    nhs = _mk_trio(quiesce=True)
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        s = leader.get_noop_session(CID)
        assert leader.propose(s, b"a=b", timeout=30.0).wait(60.0).completed
        assert _wait_all_quiesced(nhs)
        v = leader.sync_read(CID, "a", timeout=30.0)
        assert v == "b"
        assert not leader.get_node(CID).quiesce_mgr.quiesced()
    finally:
        _stop_all(nhs)


def test_exit_quiesce_by_config_change():
    """Reference TestNodesCanExitQuiesceByConfigChange: a membership
    request wakes the group and completes."""
    nhs = _mk_trio(quiesce=True)
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        assert _wait_all_quiesced(nhs)
        rs = leader.request_add_observer(CID, 9, "q9:1", timeout=30.0)
        assert rs.wait(60.0).completed
        assert not leader.get_node(CID).quiesce_mgr.quiesced()
        members = leader.sync_get_cluster_membership(CID, timeout=30.0)
        assert 9 in members.observers
    finally:
        _stop_all(nhs)


def test_requiesce_after_activity_settles():
    """After a wake, a second idle window re-enters quiesce — the cycle
    is repeatable, not one-shot (quiesce.go's tick clock resets on
    activity)."""
    nhs = _mk_trio(quiesce=True)
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        assert _wait_all_quiesced(nhs)
        s = leader.get_noop_session(CID)
        assert leader.propose(s, b"x=1", timeout=30.0).wait(60.0).completed
        assert not leader.get_node(CID).quiesce_mgr.quiesced()
        assert _wait_all_quiesced(nhs), "group never re-quiesced"
    finally:
        _stop_all(nhs)
