"""Port of the reference's own (dragonboat-native) raft tests for
observers and witnesses.

Reference: ``/root/reference/internal/raft/raft_test.go`` — the
observer/witness behavior block (TestObserver* / TestWitness*), the
thinnest-covered protocol area.  Same names and scenarios.
"""
from __future__ import annotations

import pytest

from dragonboat_tpu.config import Config
from dragonboat_tpu.raft import InMemLogDB, Raft
from dragonboat_tpu.raft.raft import RaftState
from dragonboat_tpu.raft.remote import Remote
from dragonboat_tpu.wire import (
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    Snapshot,
)
from tests.raft_harness import (
    Network,
    campaign,
    new_test_config,
    new_test_raft,
    propose,
    read_messages,
    readindex,
    tick_until_election,
)

MT = MessageType
NO_LIMIT = 1 << 62


def new_test_observer(node_id, peers, observers, election=10, heartbeat=1,
                      logdb=None):
    """Reference ``newTestObserver`` (raft_etcd_test.go:3022)."""
    assert node_id in observers, "observer id must be in the observers list"
    cfg = new_test_config(node_id, election, heartbeat)
    cfg.is_observer = True
    r = Raft(cfg, logdb or InMemLogDB(), seed=node_id)
    if not r.remotes:
        for p in peers or []:
            r.remotes[p] = Remote(next=1)
    if not r.observers:
        for p in observers:
            r.observers[p] = Remote(next=1)
    r.has_not_applied_config_change = lambda: False
    return r


def new_test_witness(node_id, peers, witnesses, election=10, heartbeat=1,
                     logdb=None):
    """Reference ``newTestWitness`` (raft_etcd_test.go:3049)."""
    cfg = new_test_config(node_id, election, heartbeat)
    cfg.is_witness = True
    r = Raft(cfg, logdb or InMemLogDB(), seed=node_id)
    if not r.remotes:
        for p in peers or []:
            r.remotes[p] = Remote(next=1)
    if not r.witnesses:
        for p in witnesses:
            r.witnesses[p] = Remote(next=1)
    r.has_not_applied_config_change = lambda: False
    return r


def mk_members(addresses=(), observers=(), witnesses=()):
    m = Membership()
    for n in addresses:
        m.addresses[n] = f"a{n}"
    for n in observers:
        m.observers[n] = f"a{n}"
    for n in witnesses:
        m.witnesses[n] = f"a{n}"
    return m


def noop():
    return Message(from_=1, to=1, type=MT.NOOP)


# ------------------------------------------------------------- observers


def test_observer_will_not_start_election():
    p = new_test_observer(1, None, [1])
    assert p.is_observer()
    assert len(p.remotes) == 0
    for _ in range(p.randomized_election_timeout * 10):
        p.tick()
    assert p.msgs == []


def test_observer_will_not_vote_in_election():
    p = new_test_observer(1, None, [1])
    p.handle(Message(from_=2, to=1, type=MT.REQUEST_VOTE,
                     log_term=100, log_index=100))
    assert p.msgs == []


def test_observer_can_be_promoted_to_voting_member():
    p = new_test_observer(1, None, [1])
    p.add_node(1)
    assert not p.is_observer()
    assert len(p.remotes) == 1
    assert len(p.observers) == 0


def test_observer_can_act_as_regular_node_after_promotion():
    p = new_test_observer(1, None, [1])
    p.add_node(1)
    assert not p.is_observer()
    tick_until_election(p)
    assert p.state == RaftState.LEADER


def test_observer_replication():
    p1 = new_test_observer(1, None, [1, 2])
    p2 = new_test_observer(2, None, [1, 2])
    p1.add_node(1)
    p2.add_node(1)
    assert not p1.is_observer()
    assert p2.is_observer()
    nt = Network(p1, p2)
    assert len(p1.remotes) == 1
    for _ in range(p1.randomized_election_timeout + 1):
        p1.tick()
    nt.send(*read_messages(p1))
    assert p1.state == RaftState.LEADER
    committed = p1.log.committed
    nt.send(propose(1, b"test-data"))
    assert p1.log.committed == committed + 1
    # the promotion noop is replicated to the observer too
    assert p2.log.committed == committed + 1
    assert p1.observers[2].match == committed + 1


def test_observer_can_propose():
    p1 = new_test_observer(1, None, [1, 2])
    p2 = new_test_observer(2, None, [1, 2])
    p1.add_node(1)
    p2.add_node(1)
    nt = Network(p1, p2)
    nt.send(campaign(p1))
    assert p1.state == RaftState.LEADER
    for _ in range(p1.randomized_election_timeout + 1):
        p1.tick()
        nt.send(noop())
    assert p2.is_observer()
    committed = p1.log.committed
    for _ in range(10):
        nt.send(propose(2, b"test-data"))
    assert p1.log.committed == committed + 10
    assert p2.log.committed == committed + 10
    assert p1.observers[2].match == committed + 10


def test_observer_can_read_index_quorum1():
    p1 = new_test_observer(1, None, [1, 2])
    p2 = new_test_observer(2, None, [1, 2])
    p1.add_node(1)
    p2.add_node(1)
    nt = Network(p1, p2)
    nt.send(campaign(p1))
    assert p1.state == RaftState.LEADER
    for _ in range(p1.randomized_election_timeout + 1):
        p1.tick()
        nt.send(noop())
    committed0 = p1.log.committed
    for _ in range(10):
        nt.send(propose(2, b"test-data"))
    assert p1.log.committed == committed0 + 10
    nt.send(readindex(2, 12345, 1))
    assert len(p2.ready_to_read) == 1
    assert p2.ready_to_read[0].index == p1.log.committed


def test_observer_can_read_index_quorum2():
    p1 = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    p2 = new_test_raft(2, [1, 2], 10, 1, InMemLogDB())
    p3 = new_test_observer(3, [1, 2], [3])
    p1.add_observer(3)
    p2.add_observer(3)
    nt = Network(p1, p2, p3)
    nt.send(campaign(p1))
    assert p1.state == RaftState.LEADER
    assert p2.state == RaftState.FOLLOWER
    assert p3.is_observer()
    for _ in range(p1.randomized_election_timeout + 1):
        p1.tick()
        nt.send(noop())
    committed0 = p1.log.committed
    for _ in range(10):
        nt.send(propose(2, b"test-data"))
    assert p1.log.committed == committed0 + 10
    nt.send(readindex(3, 12345, 1))
    assert len(p3.ready_to_read) == 1
    assert p3.ready_to_read[0].index == p1.log.committed


def test_observer_can_receive_snapshot():
    ss = Snapshot(index=20, term=20, membership=mk_members(addresses=[1, 2]))
    p1 = new_test_observer(3, [1], [2, 3])
    m = Message(from_=2, to=1, type=MT.INSTALL_SNAPSHOT)
    m.snapshot = ss
    p1.handle(m)
    assert p1.log.committed == 20


def test_observer_can_receive_heartbeat_message():
    p1 = new_test_observer(2, [1], [2])
    m = Message(
        from_=1, to=2, type=MT.REPLICATE, log_index=0, log_term=0, commit=0,
        entries=[
            Entry(index=1, term=1, cmd=b"test-data1"),
            Entry(index=2, term=1, cmd=b"test-data2"),
            Entry(index=3, term=1, cmd=b"test-data3"),
        ],
    )
    p1.handle(m)
    assert p1.log.last_index() == 3
    assert p1.log.committed == 0
    p1.handle(Message(from_=1, to=2, type=MT.HEARTBEAT, commit=3))
    assert p1.log.committed == 3


def test_observer_can_be_restored():
    ss = Snapshot(index=20, term=20,
                  membership=mk_members(addresses=[1, 2], observers=[3]))
    p1 = new_test_observer(3, [1, 2], [3])
    assert p1.restore(ss)


def test_observer_can_be_promoted_by_snapshot():
    ss = Snapshot(index=20, term=20, membership=mk_members(addresses=[1, 2]))
    p1 = new_test_observer(1, None, [1, 2])
    assert p1.is_observer()
    assert p1.restore(ss)
    p1.restore_remotes(ss)
    assert not p1.is_observer()


def test_correct_observer_can_be_promoted_by_snapshot():
    ss = Snapshot(index=20, term=20,
                  membership=mk_members(addresses=[2, 3], observers=[1]))
    p1 = new_test_observer(1, [2], [1, 3])
    assert p1.is_observer()
    assert 1 in p1.observers and 3 in p1.observers
    p1.restore_remotes(ss)
    assert p1.is_observer()


def test_observer_cannot_move_node_back_to_observer_by_snapshot():
    ss = Snapshot(index=20, term=20,
                  membership=mk_members(addresses=[1, 2], observers=[3]))
    p1 = new_test_raft(3, [1, 2, 3], 10, 1, InMemLogDB())
    with pytest.raises(Exception):
        p1.restore(ss)


def test_observer_can_be_added():
    p1 = new_test_raft(1, [1], 10, 1, InMemLogDB())
    assert len(p1.observers) == 0
    p1.add_observer(2)
    assert len(p1.observers) == 1
    assert not p1.is_observer()


def test_observer_can_be_removed():
    p1 = new_test_observer(1, None, [1, 2])
    assert len(p1.observers) == 2
    p1.remove_node(2)
    assert len(p1.observers) == 1
    assert 2 not in p1.observers


# ------------------------------------------------------------- witnesses


def set_up_leader_and_witness():
    """Reference ``setUpLeaderAndWitness`` (raft_test.go:930)."""
    leader = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    witness = new_test_witness(2, None, [2])
    leader.add_witness(2)
    witness.add_node(1)
    assert witness.is_witness()
    nt = Network(leader, witness)
    assert len(leader.remotes) == 1
    nt.send(campaign(leader))
    assert leader.is_leader()
    for _ in range(leader.randomized_election_timeout + 1):
        leader.tick()
        nt.send(noop())
    assert witness.is_witness()
    return leader, witness, nt


def test_witness_cannot_become_observer():
    _, witness, _ = set_up_leader_and_witness()
    with pytest.raises(Exception):
        witness.become_observer(1, 1)


def test_witness_cannot_become_follower():
    _, witness, _ = set_up_leader_and_witness()
    with pytest.raises(Exception):
        witness.become_follower(1, 1)


def test_witness_cannot_become_candidate():
    _, witness, _ = set_up_leader_and_witness()
    with pytest.raises(Exception):
        witness.become_candidate()


def test_witness_will_not_start_election():
    p = new_test_witness(1, None, [1])
    assert p.is_witness()
    assert len(p.remotes) == 0
    for _ in range(p.randomized_election_timeout * 10):
        p.tick()
    assert p.msgs == []


def test_witness_will_vote_in_election():
    p = new_test_witness(1, None, [1])
    p.handle(Message(from_=2, to=1, type=MT.REQUEST_VOTE, term=100,
                     log_term=100, log_index=100))
    msgs = read_messages(p)
    assert len(msgs) == 1
    assert msgs[0].type == MT.REQUEST_VOTE_RESP


def test_witness_cannot_be_promoted_to_full_member():
    p = new_test_witness(1, None, [1])
    with pytest.raises(Exception):
        p.add_node(1)


def test_non_witness_panics_when_remote_snapshot_assumes_witness():
    ss = Snapshot(index=20, term=20, membership=mk_members(addresses=[1, 2]))
    p1 = new_test_observer(1, [1], [1])
    assert p1.is_observer()
    assert p1.restore(ss)
    p1.restore_remotes(ss)
    assert not p1.is_observer()
    p1.witnesses[2] = Remote()
    with pytest.raises(Exception):
        p1.restore_remotes(ss)


def test_witness_replication():
    leader, witness, nt = set_up_leader_and_witness()
    committed = leader.log.committed
    nt.send(propose(1, b"test-data"))
    assert leader.log.committed == committed + 1
    assert witness.log.committed == committed + 1
    assert leader.witnesses[2].match == committed + 1


def test_application_message_sent_to_witness_is_empty():
    _, witness, _ = set_up_leader_and_witness()
    ents = witness.log.get_entries(1, 2, NO_LIMIT)
    e = ents[0]
    assert e.type == EntryType.METADATA
    assert e.term == 1 and e.index == 1
    assert not e.cmd


def test_config_change_message_sent_to_witness_is_empty():
    leader, witness, nt = set_up_leader_and_witness()
    cc_entry = Entry(term=1, index=2, type=EntryType.CONFIG_CHANGE,
                     cmd=b"test-data")
    leader.log.append([cc_entry])
    leader.broadcast_replicate_message()
    msgs = read_messages(leader)
    assert len(msgs) == 1
    nt.send(*msgs)
    ents = witness.log.get_entries(1, 3, NO_LIMIT)
    got = ents[1]
    # config changes reach the witness with type and payload intact
    assert got.type == EntryType.CONFIG_CHANGE
    assert got.term == 1 and got.index == 2
    assert got.cmd == b"test-data"


def test_witness_snapshot():
    leader, _, _ = set_up_leader_and_witness()
    leader.log.logdb.apply_snapshot(Snapshot(index=10, term=2))
    m = Message()
    idx = leader.make_install_snapshot_message(2, m)
    assert idx == 10
    assert m.type == MT.INSTALL_SNAPSHOT
    assert m.snapshot.index == 10 and m.snapshot.term == 2
    assert m.snapshot.witness and not m.snapshot.dummy


def test_non_witness_cannot_add_itself_as_witness():
    p = new_test_raft(1, [1], 10, 1, InMemLogDB())
    with pytest.raises(Exception):
        p.add_witness(1)


def test_witness_cannot_be_added_as_node():
    _, witness, _ = set_up_leader_and_witness()
    with pytest.raises(Exception):
        witness.add_node(2)


def test_witness_cannot_read_index():
    witness = new_test_witness(1, None, [1])
    nt = Network(witness)
    nt.send(readindex(1, 12345, 1))
    assert witness.ready_to_read == []


def test_witness_can_receive_snapshot():
    ss = Snapshot(index=20, term=20, membership=mk_members(addresses=[1, 2]))
    p1 = new_test_witness(3, [1], [2])
    assert p1.is_witness()
    m = Message(from_=2, to=1, type=MT.INSTALL_SNAPSHOT)
    m.snapshot = ss
    p1.handle(m)
    assert p1.log.committed == 20
    msgs = read_messages(p1)
    assert len(msgs) == 1
    assert msgs[-1].log_index == 20


def test_witness_can_receive_heartbeat_message():
    p1 = new_test_witness(2, [1], [2])
    m = Message(
        from_=1, to=2, type=MT.REPLICATE, log_index=0, log_term=0, commit=0,
        entries=[
            Entry(index=1, term=1, type=EntryType.METADATA),
            Entry(index=2, term=1, type=EntryType.METADATA),
            Entry(index=3, term=1, type=EntryType.METADATA),
        ],
    )
    p1.handle(m)
    assert p1.log.last_index() == 3
    assert p1.log.committed == 0
    p1.handle(Message(from_=1, to=2, type=MT.HEARTBEAT, commit=3))
    assert p1.log.committed == 3


def test_witness_can_be_restored():
    ss = Snapshot(index=20, term=20,
                  membership=mk_members(addresses=[1, 2], witnesses=[3]))
    p1 = new_test_witness(3, [1, 2], [3])
    assert p1.restore(ss)


def test_witness_cannot_move_node_back_to_witness_by_snapshot():
    ss = Snapshot(index=20, term=20,
                  membership=mk_members(addresses=[1, 2], witnesses=[3]))
    p1 = new_test_raft(3, [1, 2, 3], 10, 1, InMemLogDB())
    with pytest.raises(Exception):
        p1.restore(ss)


def test_witness_can_be_added():
    p1 = new_test_raft(1, [1], 10, 1, InMemLogDB())
    assert len(p1.witnesses) == 0
    p1.add_witness(2)
    assert len(p1.witnesses) == 1
    assert not p1.is_witness()


def test_witness_can_be_removed():
    p1 = new_test_witness(1, [1], [2])
    assert len(p1.witnesses) == 1
    p1.remove_node(2)
    assert len(p1.witnesses) == 0
