"""Election conformance tests, modeled on the reference's ported etcd suite
(internal/raft/raft_etcd_test.go, raft_etcd_paper_test.go §5.2)."""
from raft_harness import (
    BlackHole,
    Network,
    RaftState,
    campaign,
    new_test_raft,
    propose,
)
from dragonboat_tpu.raft import InMemLogDB
from dragonboat_tpu.wire import Message, MessageType

MT = MessageType


def test_leader_election_3_nodes():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    assert nt.raft(1).state == RaftState.LEADER
    assert nt.raft(2).state == RaftState.FOLLOWER
    assert nt.raft(3).state == RaftState.FOLLOWER
    assert nt.raft(1).term == 1
    for nid in (2, 3):
        assert nt.raft(nid).term == 1
        assert nt.raft(nid).leader_id == 1


def test_leader_election_one_vote_missing():
    # one unresponsive node: candidate still wins 2/3
    nt = Network(None, None, BlackHole())
    nt.send(campaign(nt.raft(1)))
    assert nt.raft(1).state == RaftState.LEADER


def test_leader_election_no_quorum():
    # two black holes: candidate stays candidate
    nt = Network(None, BlackHole(), BlackHole())
    nt.send(campaign(nt.raft(1)))
    assert nt.raft(1).state == RaftState.CANDIDATE


def test_leader_election_quorum_of_5():
    nt = Network(None, BlackHole(), BlackHole(), None, None)
    nt.send(campaign(nt.raft(1)))
    assert nt.raft(1).state == RaftState.LEADER


def test_election_with_higher_term_log_rejects():
    # node with shorter/older log cannot win over up-to-date voters
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.send(propose(1))
    # now node 2/3 logs contain entries from term 1
    # isolate 1; let 2 campaign and win
    nt.isolate(1)
    nt.send(campaign(nt.raft(2)))
    assert nt.raft(2).state == RaftState.LEADER


def test_single_node_election():
    nt = Network(None)
    nt.send(campaign(nt.raft(1)))
    assert nt.raft(1).state == RaftState.LEADER
    assert nt.raft(1).term == 1


def test_candidate_steps_down_on_majority_rejection():
    nt = Network(None, None, None)
    # make 2 the leader first, so 1's log stays behind after proposals
    nt.send(campaign(nt.raft(2)))
    nt.isolate(1)
    nt.send(propose(2))
    nt.recover()
    # 1 campaigns with a stale log: 2 and 3 both reject; etcd behavior is to
    # become follower when a quorum rejects
    r1 = nt.raft(1)
    nt.send(campaign(r1))
    assert r1.state == RaftState.FOLLOWER
    assert nt.raft(2).log.committed >= 2


def test_leader_steps_down_on_higher_term_message():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    assert nt.raft(1).state == RaftState.LEADER
    nt.send(Message(from_=2, to=1, type=MT.REPLICATE_RESP, term=99))
    assert nt.raft(1).state == RaftState.FOLLOWER
    assert nt.raft(1).term == 99


def test_vote_granted_once_per_term():
    r = new_test_raft(1, [1, 2, 3])
    r.handle(Message(from_=2, to=1, type=MT.REQUEST_VOTE, term=1,
                     log_index=0, log_term=0))
    resp = r.msgs[-1]
    assert resp.type == MT.REQUEST_VOTE_RESP and not resp.reject
    assert r.vote == 2
    # different candidate same term is rejected
    r.handle(Message(from_=3, to=1, type=MT.REQUEST_VOTE, term=1,
                     log_index=0, log_term=0))
    resp = r.msgs[-1]
    assert resp.reject
    # same candidate same term re-granted
    r.handle(Message(from_=2, to=1, type=MT.REQUEST_VOTE, term=1,
                     log_index=0, log_term=0))
    resp = r.msgs[-1]
    assert not resp.reject


def test_vote_rejected_for_stale_log():
    logdb = InMemLogDB()
    r = new_test_raft(1, [1, 2, 3], logdb=logdb)
    # local log: term 2 entry at index 1
    from dragonboat_tpu.wire import Entry

    r.log.append([Entry(term=2, index=1)])
    r.term = 2
    r.handle(Message(from_=2, to=1, type=MT.REQUEST_VOTE, term=3,
                     log_index=0, log_term=0))
    # candidate's log (0,0) is older than ours (1, term2) -> reject
    resp = r.msgs[-1]
    assert resp.type == MT.REQUEST_VOTE_RESP and resp.reject
    # up-to-date candidate gets the vote
    r.handle(Message(from_=3, to=1, type=MT.REQUEST_VOTE, term=3,
                     log_index=5, log_term=2))
    resp = r.msgs[-1]
    assert not resp.reject


def test_randomized_election_timeout_in_range():
    r = new_test_raft(1, [1, 2, 3], election=10)
    seen = set()
    for _ in range(50):
        r.set_randomized_election_timeout()
        assert 10 <= r.randomized_election_timeout < 20
        seen.add(r.randomized_election_timeout)
    assert len(seen) > 1  # actually randomized


def test_randomized_election_timeout_deterministic_for_seed():
    a = new_test_raft(1, [1, 2, 3], seed=42)
    b = new_test_raft(1, [1, 2, 3], seed=42)
    seq_a = []
    seq_b = []
    for _ in range(10):
        a.set_randomized_election_timeout()
        b.set_randomized_election_timeout()
        seq_a.append(a.randomized_election_timeout)
        seq_b.append(b.randomized_election_timeout)
    assert seq_a == seq_b


def test_tick_drives_election():
    r = new_test_raft(1, [1], election=10)
    for _ in range(r.randomized_election_timeout + 1):
        r.tick()
    # single-node quorum: becomes leader immediately after campaigning
    assert r.state == RaftState.LEADER


def test_observer_does_not_campaign():
    from raft_harness import new_test_config
    from dragonboat_tpu.raft import Raft

    cfg = new_test_config(4)
    cfg.is_observer = True
    logdb = InMemLogDB()
    r = Raft(cfg, logdb)
    r.observers[4] = __import__(
        "dragonboat_tpu.raft.remote", fromlist=["Remote"]
    ).Remote(next=1)
    for _ in range(50):
        r.tick()
    assert r.state == RaftState.OBSERVER
    assert not r.msgs or all(m.type != MT.REQUEST_VOTE for m in r.msgs)


def test_check_quorum_leader_steps_down():
    nt = Network(None, None, None)
    for nid in (1, 2, 3):
        nt.raft(nid).check_quorum = True
    nt.send(campaign(nt.raft(1)))
    r1 = nt.raft(1)
    assert r1.state == RaftState.LEADER
    # no responses flow: after 2 election timeouts without quorum contact the
    # leader must step down (reference raft.go:1582-1588)
    for _ in range(2 * r1.election_timeout + 1):
        r1.tick()
        r1.msgs = []
    assert r1.state == RaftState.FOLLOWER


def test_leader_transfer_basic():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    r1 = nt.raft(1)
    # ask the leader to transfer to 2
    nt.send(Message(from_=2, to=1, type=MT.LEADER_TRANSFER, hint=2))
    assert nt.raft(2).state == RaftState.LEADER
    assert r1.state == RaftState.FOLLOWER
    assert nt.raft(2).term == 2
