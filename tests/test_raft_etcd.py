"""Port of the reference's etcd-derived conformance corpus.

Reference: ``/root/reference/internal/raft/raft_etcd_test.go`` (itself a
port of the etcd raft tests).  Test names and scenarios mirror the Go file
one-for-one (same order) so parity can be audited; helpers live in
``tests/raft_harness.py``.  Scenarios that depend on etcd/dragonboat
features this build intentionally omits (prevote) are skipped with the
same name.
"""
from __future__ import annotations

import pytest

from dragonboat_tpu.raft import InMemLogDB, Raft
from dragonboat_tpu.raft.raft import NO_LEADER, NO_NODE, RaftState
from dragonboat_tpu.raft.remote import Remote, RemoteState
from dragonboat_tpu.wire import (
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    Snapshot,
    State,
    UpdateCommit,
)
from tests.raft_harness import (
    BlackHole,
    Network,
    campaign,
    ents_with_config,
    voted_with_config,
    commit_noop_entry,
    ent_sig,
    get_all_entries,
    logs_equal,
    new_test_raft,
    propose,
    read_messages,
)

MT = MessageType
NO_LIMIT = 1 << 62


def msg(from_=0, to=0, type=None, term=0, log_term=0, log_index=0, commit=0,
        entries=(), hint=0, reject=False, hint_high=0):
    return Message(
        from_=from_, to=to, type=type, term=term, log_term=log_term,
        log_index=log_index, commit=commit, entries=list(entries), hint=hint,
        reject=reject, hint_high=hint_high,
    )


def next_ents(r: Raft, s: InMemLogDB):
    """Reference ``nextEnts`` (raft_etcd_test.go:98): stabilize + apply."""
    s.append(r.log.entries_to_save())
    r.log.commit_update(
        UpdateCommit(
            stable_log_to=r.log.last_index(), stable_log_term=r.log.last_term()
        )
    )
    ents = r.log.entries_to_apply()
    r.log.commit_update(UpdateCommit(processed=r.log.committed))
    return ents


def mk_membership(nodes):
    m = Membership(config_change_id=1)
    for n in nodes:
        m.addresses[n] = str(n)
    return m


def get_snapshot(logdb: InMemLogDB, index: int, membership: Membership) -> Snapshot:
    return Snapshot(index=index, term=logdb.term(index), membership=membership)


def check_leader_transfer_state(r: Raft, state: RaftState, lead: int) -> None:
    assert r.state == state and r.leader_id == lead, (
        f"state {r.state} lead {r.leader_id}, want {state} {lead}"
    )
    assert r.leader_transfer_target == NO_NODE


# ----------------------------------------------------------------------
# leader transfer (raft_etcd_test.go:137-385)
# ----------------------------------------------------------------------

def test_leader_transfer_to_up_to_date_node():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    lead = nt.raft(1)
    assert lead.leader_id == 1
    nt.send(msg(from_=2, to=1, hint=2, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.FOLLOWER, 2)
    nt.send(propose(1, b""))
    nt.send(msg(from_=1, to=2, hint=1, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.LEADER, 1)


def test_leader_transfer_to_up_to_date_node_from_follower():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    lead = nt.raft(1)
    assert lead.leader_id == 1
    nt.send(msg(from_=2, to=2, hint=2, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.FOLLOWER, 2)
    nt.send(propose(1, b""))
    nt.send(msg(from_=1, to=1, hint=1, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.LEADER, 1)


def test_leader_transfer_with_check_quorum():
    nt = Network(None, None, None)
    for i in (1, 2, 3):
        r = nt.raft(i)
        r.check_quorum = True
        r.randomized_election_timeout = r.election_timeout + i
    # let peer 2's election tick reach timeout so it can vote for peer 1
    f = nt.raft(2)
    for _ in range(f.election_timeout):
        f.tick()
    nt.send(campaign(nt.raft(1)))
    lead = nt.raft(1)
    assert lead.leader_id == 1
    nt.send(msg(from_=2, to=1, hint=2, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.FOLLOWER, 2)
    nt.send(propose(1, b""))
    nt.send(msg(from_=1, to=2, hint=1, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.LEADER, 1)


def test_leader_transfer_to_slow_follower():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.isolate(3)
    nt.send(propose(1, b""))
    nt.recover()
    lead = nt.raft(1)
    assert lead.remotes[3].match == 1
    # transferring to a log-lacking node is not forced through
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    assert lead.state == RaftState.LEADER and lead.leader_id == 1
    assert lead.leader_transfering()
    lead.abort_leader_transfer()
    nt.send(propose(1, b""))
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.FOLLOWER, 3)


def test_leader_transfer_after_snapshot():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.isolate(3)
    nt.send(propose(1, b""))
    lead = nt.raft(1)
    next_ents(lead, nt.storage[1])
    m = mk_membership(lead.nodes_sorted())
    ss = get_snapshot(nt.storage[1], lead.log.processed, m)
    nt.storage[1].create_snapshot(ss)
    nt.storage[1].compact(lead.log.processed)
    nt.recover()
    assert lead.remotes[3].match == 1
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    # HeartbeatResp triggers the snapshot for node 3
    nt.send(msg(from_=3, to=1, type=MT.HEARTBEAT_RESP))
    check_leader_transfer_state(lead, RaftState.FOLLOWER, 3)


def test_leader_transfer_to_self():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    lead = nt.raft(1)
    nt.send(msg(from_=1, to=1, hint=1, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.LEADER, 1)


def test_leader_transfer_to_non_existing_node():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    lead = nt.raft(1)
    nt.send(msg(from_=4, to=1, hint=4, type=MT.LEADER_TRANSFER))
    check_leader_transfer_state(lead, RaftState.LEADER, 1)


def test_leader_transfer_timeout():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.isolate(3)
    lead = nt.raft(1)
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    assert lead.leader_transfer_target == 3
    for _ in range(lead.heartbeat_timeout):
        lead.tick()
    assert lead.leader_transfer_target == 3
    for _ in range(lead.election_timeout):
        lead.tick()
    check_leader_transfer_state(lead, RaftState.LEADER, 1)


def test_leader_transfer_ignore_proposal():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.isolate(3)
    lead = nt.raft(1)
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    assert lead.leader_transfer_target == 3
    nt.send(propose(1, b""))
    matched = lead.remotes[2].match
    nt.send(propose(1, b""))
    assert lead.remotes[2].match == matched


def test_leader_transfer_receive_higher_term_vote():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.isolate(3)
    lead = nt.raft(1)
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    assert lead.leader_transfer_target == 3
    nt.send(msg(from_=2, to=2, type=MT.ELECTION, log_index=1, term=2))
    check_leader_transfer_state(lead, RaftState.FOLLOWER, 2)


def test_leader_transfer_remove_node():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.ignore(MT.TIMEOUT_NOW)
    lead = nt.raft(1)
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    assert lead.leader_transfer_target == 3
    lead.remove_node(3)
    check_leader_transfer_state(lead, RaftState.LEADER, 1)


def test_new_leader_transfer_cannot_override_ongoing_transfer():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.isolate(3)
    lead = nt.raft(1)
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    assert lead.leader_transfer_target == 3
    ot = lead.election_tick
    nt.send(msg(from_=1, to=1, hint=1, type=MT.LEADER_TRANSFER))
    assert lead.leader_transfer_target == 3
    assert lead.election_tick == ot


def test_leader_transfer_second_transfer_to_same_node():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.isolate(3)
    lead = nt.raft(1)
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    assert lead.leader_transfer_target == 3
    for _ in range(lead.heartbeat_timeout):
        lead.tick()
    nt.send(msg(from_=3, to=1, hint=3, type=MT.LEADER_TRANSFER))
    for _ in range(lead.election_timeout - lead.heartbeat_timeout):
        lead.tick()
    check_leader_transfer_state(lead, RaftState.LEADER, 1)


# ----------------------------------------------------------------------
# remote pause/resume (raft_etcd_test.go:388-418)
# ----------------------------------------------------------------------

def test_remote_resume_by_heartbeat_resp():
    r = new_test_raft(1, [1, 2], 5, 1, InMemLogDB())
    r.become_candidate()
    r.become_leader()
    r.remotes[2].retry_to_wait()
    r.handle(msg(from_=1, to=1, type=MT.LEADER_HEARTBEAT))
    assert r.remotes[2].state == RemoteState.WAIT
    r.remotes[2].become_replicate()
    r.handle(msg(from_=2, to=1, type=MT.HEARTBEAT_RESP))
    assert r.remotes[2].state != RemoteState.WAIT


def test_remote_paused():
    r = new_test_raft(1, [1, 2], 5, 1, InMemLogDB())
    r.become_candidate()
    r.become_leader()
    r.handle(propose(1))
    r.handle(propose(1))
    r.handle(propose(1))
    assert len(read_messages(r)) == 1


# ----------------------------------------------------------------------
# elections (raft_etcd_test.go:420-562)
# ----------------------------------------------------------------------

def test_leader_election():
    cases = [
        (Network(None, None, None), RaftState.LEADER, 1),
        (Network(None, None, BlackHole()), RaftState.LEADER, 1),
        (Network(None, BlackHole(), BlackHole()), RaftState.CANDIDATE, 1),
        (Network(None, BlackHole(), BlackHole(), None), RaftState.CANDIDATE, 1),
        (Network(None, BlackHole(), BlackHole(), None, None), RaftState.LEADER, 1),
        # three logs further along than 0, same term so rejections return
        (
            Network(
                None,
                ents_with_config([1]),
                ents_with_config([1]),
                ents_with_config([1, 1]),
                None,
            ),
            RaftState.FOLLOWER,
            1,
        ),
    ]
    for i, (nt, state, exp_term) in enumerate(cases):
        nt.send(campaign(nt.raft(1)))
        sm = nt.raft(1)
        assert sm.state == state, f"#{i}: state {sm.state}, want {state}"
        assert sm.term == exp_term, f"#{i}: term {sm.term}, want {exp_term}"


def test_leader_cycle():
    n = Network(None, None, None)
    for campaigner in (1, 2, 3):
        n.send(msg(from_=campaigner, to=campaigner, type=MT.ELECTION))
        for nid in n.peers:
            sm = n.raft(nid)
            if sm.node_id == campaigner:
                assert sm.state == RaftState.LEADER
            else:
                assert sm.state == RaftState.FOLLOWER


def test_leader_election_overwrite_newer_logs():
    n = Network(
        ents_with_config([1]),          # node 1: won first election
        ents_with_config([1]),          # node 2: got logs from node 1
        ents_with_config([2]),          # node 3: won second election
        voted_with_config(3, 2),        # node 4: voted but no logs
        voted_with_config(3, 2),        # node 5: voted but no logs
    )
    n.send(campaign(n.raft(1)))
    sm1 = n.raft(1)
    assert sm1.state == RaftState.FOLLOWER
    assert sm1.term == 2
    n.send(campaign(n.raft(1)))
    assert sm1.state == RaftState.LEADER
    assert sm1.term == 3
    for nid in n.peers:
        sm = n.raft(nid)
        entries = get_all_entries(sm.log)
        assert len(entries) == 2, f"node {nid}: {len(entries)} entries"
        assert entries[0].term == 1
        assert entries[1].term == 3


def test_vote_from_any_state():
    for st in (RaftState.FOLLOWER, RaftState.CANDIDATE, RaftState.LEADER):
        r = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
        r.term = 1
        if st == RaftState.FOLLOWER:
            r.become_follower(r.term, 3)
        elif st == RaftState.CANDIDATE:
            r.become_candidate()
        else:
            r.become_candidate()
            r.become_leader()
        orig_term = r.term
        new_term = r.term + 1
        r.handle(
            msg(from_=2, to=1, type=MT.REQUEST_VOTE, term=new_term,
                log_term=new_term, log_index=42)
        )
        assert len(r.msgs) == 1, (st, r.msgs)
        resp = r.msgs[0]
        assert resp.type == MT.REQUEST_VOTE_RESP
        assert not resp.reject, (st,)
        assert r.state == RaftState.FOLLOWER
        assert r.term == new_term
        assert r.vote == 2
        del orig_term


# ----------------------------------------------------------------------
# replication + commit (raft_etcd_test.go:638-784)
# ----------------------------------------------------------------------

def test_log_replication():
    cases = [
        (
            Network(None, None, None),
            [propose(1)],
            2,
        ),
        (
            Network(None, None, None),
            [
                propose(1),
                msg(from_=1, to=2, type=MT.ELECTION),
                propose(2),
            ],
            4,
        ),
    ]
    for i, (nt, msgs, wcommitted) in enumerate(cases):
        nt.send(campaign(nt.raft(1)))
        for m in msgs:
            nt.send(m)
        props = [m for m in msgs if m.type == MT.PROPOSE]
        for nid in nt.peers:
            sm = nt.raft(nid)
            assert sm.log.committed == wcommitted, (
                f"#{i}.{nid}: committed {sm.log.committed}, want {wcommitted}"
            )
            ents = [e for e in next_ents(sm, nt.storage[nid]) if e.cmd]
            for k, m in enumerate(props):
                assert ents[k].cmd == m.entries[0].cmd


def test_single_node_commit():
    tt = Network(None)
    tt.send(campaign(tt.raft(1)))
    tt.send(propose(1, b"some data"))
    tt.send(propose(1, b"some data"))
    assert tt.raft(1).log.committed == 3


def test_cannot_commit_without_new_term_entry():
    tt = Network(None, None, None, None, None)
    tt.send(campaign(tt.raft(1)))
    tt.cut(1, 3)
    tt.cut(1, 4)
    tt.cut(1, 5)
    tt.send(propose(1, b"some data"))
    tt.send(propose(1, b"some data"))
    sm = tt.raft(1)
    assert sm.log.committed == 1
    tt.recover()
    tt.ignore(MT.REPLICATE)  # avoid committing the new leader's noop
    tt.send(campaign(tt.raft(2)))
    sm = tt.raft(2)
    assert sm.log.committed == 1
    tt.recover()
    tt.send(msg(from_=2, to=2, type=MT.LEADER_HEARTBEAT))
    tt.send(propose(2, b"some data"))
    assert sm.log.committed == 5


def test_commit_without_new_term_entry():
    tt = Network(None, None, None, None, None)
    tt.send(campaign(tt.raft(1)))
    tt.cut(1, 3)
    tt.cut(1, 4)
    tt.cut(1, 5)
    tt.send(propose(1, b"some data"))
    tt.send(propose(1, b"some data"))
    sm = tt.raft(1)
    assert sm.log.committed == 1
    tt.recover()
    # electing 2 appends a noop at the new term; replicating it commits
    # everything before it too
    tt.send(campaign(tt.raft(2)))
    assert sm.log.committed == 4


def test_dueling_candidates():
    a = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
    b = new_test_raft(2, [1, 2, 3], 10, 1, InMemLogDB())
    c = new_test_raft(3, [1, 2, 3], 10, 1, InMemLogDB())
    nt = Network(a, b, c)
    nt.cut(1, 3)
    nt.send(campaign(nt.raft(1)))
    nt.send(campaign(nt.raft(3)))
    assert nt.raft(1).state == RaftState.LEADER
    assert nt.raft(3).state == RaftState.CANDIDATE
    nt.recover()
    # candidate 3 increases its term and campaigns again: disrupts leader 1
    # but loses the election (short log)
    nt.send(campaign(nt.raft(3)))
    for i, (sm, state, term, sig, committed) in enumerate(
        [
            (a, RaftState.FOLLOWER, 2, [(1, 1)], 1),
            (b, RaftState.FOLLOWER, 2, [(1, 1)], 1),
            (c, RaftState.FOLLOWER, 2, [], 0),
        ]
    ):
        assert sm.state == state, f"#{i}: {sm.state}"
        assert sm.term == term, f"#{i}: {sm.term}"
        assert ent_sig(get_all_entries(sm.log)) == sig, f"#{i}"
        assert sm.log.committed == committed, f"#{i}"


def test_candidate_concede():
    tt = Network(None, None, None)
    tt.isolate(1)
    tt.send(campaign(tt.raft(1)))
    tt.send(campaign(tt.raft(3)))
    tt.recover()
    # heal the partition, then heartbeat so node 1 learns of the leader
    tt.send(msg(from_=3, to=3, type=MT.LEADER_HEARTBEAT))
    data = b"force follower"
    tt.send(propose(3, data))
    # send heartbeat again; flush out committed entries
    tt.send(msg(from_=3, to=3, type=MT.LEADER_HEARTBEAT))
    a = tt.raft(1)
    assert a.state == RaftState.FOLLOWER
    assert a.term == 1
    want = [(1, 1), (1, 2)]
    for nid in tt.peers:
        sm = tt.raft(nid)
        assert ent_sig(get_all_entries(sm.log)) == want
        assert sm.log.committed == 2


def test_single_node_candidate():
    tt = Network(None)
    tt.send(campaign(tt.raft(1)))
    assert tt.raft(1).state == RaftState.LEADER


def test_old_messages():
    tt = Network(None, None, None)
    # make 0 leader @ term 3
    tt.send(campaign(tt.raft(1)))
    tt.send(campaign(tt.raft(2)))
    tt.send(campaign(tt.raft(1)))
    # pretend we're an old leader trying to make progress; this entry is
    # expected to be ignored.
    tt.send(
        msg(from_=2, to=1, type=MT.REPLICATE, term=2,
            entries=[Entry(index=3, term=2)])
    )
    # commit a new entry
    tt.send(propose(1, b"somedata"))
    want = [(1, 1), (2, 2), (3, 3), (3, 4)]
    for nid in tt.peers:
        sm = tt.raft(nid)
        assert ent_sig(get_all_entries(sm.log)) == want
        assert sm.log.committed == 4


# ----------------------------------------------------------------------
# proposals + commit math (raft_etcd_test.go:1013-1194)
# ----------------------------------------------------------------------

def test_proposal():
    cases = [
        (Network(None, None, None), True),
        (Network(None, None, BlackHole()), True),
        (Network(None, BlackHole(), BlackHole()), False),
        (Network(None, BlackHole(), BlackHole(), None), False),
        (Network(None, BlackHole(), BlackHole(), None, None), True),
    ]
    data = b"somedata"
    for j, (tt, success) in enumerate(cases):
        def send(m):
            try:
                tt.send(m)
            except Exception:
                if success:
                    raise
        send(campaign(tt.raft(1)))
        send(propose(1, data))
        if success:
            want = [(1, 1), (1, 2)]
            wcommitted = 2
        else:
            want = []
            wcommitted = 0
        for nid, p in tt.peers.items():
            if isinstance(p, Raft):
                assert ent_sig(get_all_entries(p.log)) == want, f"#{j}.{nid}"
                assert p.log.committed == wcommitted, f"#{j}.{nid}"
        assert tt.raft(1).term == 1


def test_proposal_by_proxy():
    data = b"somedata"
    for j, tt in enumerate(
        [Network(None, None, None), Network(None, None, BlackHole())]
    ):
        tt.send(campaign(tt.raft(1)))
        tt.send(propose(2, data))
        want = [(1, 1), (1, 2)]
        for nid, p in tt.peers.items():
            if isinstance(p, Raft):
                assert ent_sig(get_all_entries(p.log)) == want, f"#{j}.{nid}"
                assert p.log.committed == 2, f"#{j}.{nid}"
        assert tt.raft(1).term == 1


def test_commit():
    cases = [
        # single
        ([1], [Entry(index=1, term=1)], 1, 1),
        ([1], [Entry(index=1, term=1)], 2, 0),
        ([2], [Entry(index=1, term=1), Entry(index=2, term=2)], 2, 2),
        ([1], [Entry(index=1, term=2)], 2, 1),
        # odd
        ([2, 1, 1], [Entry(index=1, term=1), Entry(index=2, term=2)], 1, 1),
        ([2, 1, 1], [Entry(index=1, term=1), Entry(index=2, term=1)], 2, 0),
        ([2, 1, 2], [Entry(index=1, term=1), Entry(index=2, term=2)], 2, 2),
        ([2, 1, 2], [Entry(index=1, term=1), Entry(index=2, term=1)], 2, 0),
        # even
        ([2, 1, 1, 1], [Entry(index=1, term=1), Entry(index=2, term=2)], 1, 1),
        ([2, 1, 1, 1], [Entry(index=1, term=1), Entry(index=2, term=1)], 2, 0),
        ([2, 1, 1, 2], [Entry(index=1, term=1), Entry(index=2, term=2)], 1, 1),
        ([2, 1, 1, 2], [Entry(index=1, term=1), Entry(index=2, term=1)], 2, 0),
        ([2, 1, 2, 2], [Entry(index=1, term=1), Entry(index=2, term=2)], 2, 2),
        ([2, 1, 2, 2], [Entry(index=1, term=1), Entry(index=2, term=1)], 2, 0),
    ]
    for i, (matches, logs, sm_term, w) in enumerate(cases):
        storage = InMemLogDB()
        storage.append(logs)
        storage.set_state(State(term=sm_term))
        sm = new_test_raft(1, [1], 5, 1, storage)
        for j, m in enumerate(matches):
            sm.set_remote(j + 1, m, m + 1)
        sm.state = RaftState.LEADER
        sm.try_commit()
        assert sm.log.committed == w, f"#{i}: {sm.log.committed} want {w}"


def test_past_election_timeout():
    import math

    cases = [
        (5, 0.0, False),
        (10, 0.1, True),
        (13, 0.4, True),
        (15, 0.6, True),
        (18, 0.9, True),
        (20, 1.0, False),
    ]
    for i, (elapse, wprob, rnd) in enumerate(cases):
        sm = new_test_raft(1, [1], 10, 1, InMemLogDB())
        sm.election_tick = elapse
        c = 0
        for _ in range(10000):
            sm.set_randomized_election_timeout()
            if sm.time_for_election():
                c += 1
        got = c / 10000.0
        if rnd:
            got = math.floor(got * 10 + 0.5) / 10.0
        assert got == wprob, f"#{i}: probability {got}, want {wprob}"


def test_step_ignore_old_term_msg():
    sm = new_test_raft(1, [1], 10, 1, InMemLogDB())
    sm.term = 2
    # a message from an older term is answered with NoOP (or dropped); the
    # state handler must not run — verify no state change and no append
    sm.handle(msg(from_=2, to=1, type=MT.REPLICATE, term=sm.term - 1,
                  entries=[Entry(index=1, term=1)]))
    assert sm.log.last_index() == 0
    assert sm.term == 2


# ----------------------------------------------------------------------
# replicate / heartbeat handling (raft_etcd_test.go:1217-1428)
# ----------------------------------------------------------------------

def test_handle_mt_replicate():
    cases = [
        # ensure 1: reject when prev log mismatches
        (msg(type=MT.REPLICATE, term=2, log_term=3, log_index=2, commit=3), 2, 0, True),
        (msg(type=MT.REPLICATE, term=2, log_term=3, log_index=3, commit=3), 2, 0, True),
        # ensure 2
        (msg(type=MT.REPLICATE, term=2, log_term=1, log_index=1, commit=1), 2, 1, False),
        (msg(type=MT.REPLICATE, term=2, log_term=0, log_index=0, commit=1,
             entries=[Entry(index=1, term=2)]), 1, 1, False),
        (msg(type=MT.REPLICATE, term=2, log_term=2, log_index=2, commit=3,
             entries=[Entry(index=3, term=2), Entry(index=4, term=2)]), 4, 3, False),
        (msg(type=MT.REPLICATE, term=2, log_term=2, log_index=2, commit=4,
             entries=[Entry(index=3, term=2)]), 3, 3, False),
        (msg(type=MT.REPLICATE, term=2, log_term=1, log_index=1, commit=4,
             entries=[Entry(index=2, term=2)]), 2, 2, False),
        # ensure 3
        (msg(type=MT.REPLICATE, term=1, log_term=1, log_index=1, commit=3), 2, 1, False),
        (msg(type=MT.REPLICATE, term=1, log_term=1, log_index=1, commit=3,
             entries=[Entry(index=2, term=2)]), 2, 2, False),
        (msg(type=MT.REPLICATE, term=2, log_term=2, log_index=2, commit=3), 2, 2, False),
        (msg(type=MT.REPLICATE, term=2, log_term=2, log_index=2, commit=4), 2, 2, False),
    ]
    for i, (m, w_index, w_commit, w_reject) in enumerate(cases):
        storage = InMemLogDB()
        storage.append([Entry(index=1, term=1), Entry(index=2, term=2)])
        sm = new_test_raft(1, [1], 10, 1, storage)
        sm.become_follower(2, NO_LEADER)
        sm.handle_replicate_message(m)
        assert sm.log.last_index() == w_index, f"#{i}"
        assert sm.log.committed == w_commit, f"#{i}"
        ms = read_messages(sm)
        assert len(ms) == 1, f"#{i}"
        assert ms[0].reject == w_reject, f"#{i}"


def test_handle_heartbeat():
    commit = 2
    cases = [
        (msg(from_=2, to=1, type=MT.HEARTBEAT, term=2, commit=commit + 1), commit + 1),
        (msg(from_=2, to=1, type=MT.HEARTBEAT, term=2, commit=commit - 1), commit),
    ]
    for i, (m, w_commit) in enumerate(cases):
        storage = InMemLogDB()
        storage.append(
            [Entry(index=1, term=1), Entry(index=2, term=2), Entry(index=3, term=3)]
        )
        sm = new_test_raft(1, [1, 2], 5, 1, storage)
        sm.become_follower(2, 2)
        sm.log.commit_to(commit)
        sm.handle_heartbeat_message(m)
        assert sm.log.committed == w_commit, f"#{i}"
        ms = read_messages(sm)
        assert len(ms) == 1, f"#{i}"
        assert ms[0].type == MT.HEARTBEAT_RESP, f"#{i}"


def test_handle_heartbeat_resp():
    storage = InMemLogDB()
    storage.append(
        [Entry(index=1, term=1), Entry(index=2, term=2), Entry(index=3, term=3)]
    )
    sm = new_test_raft(1, [1, 2], 5, 1, storage)
    sm.become_candidate()
    sm.become_leader()
    sm.log.commit_to(sm.log.last_index())
    # a heartbeat response from a lagging node re-sends Replicate
    sm.handle(msg(from_=2, to=1, type=MT.HEARTBEAT_RESP))
    msgs = read_messages(sm)
    assert len(msgs) == 1
    assert msgs[0].type == MT.REPLICATE
    sm.handle(msg(from_=2, to=1, type=MT.HEARTBEAT_RESP))
    msgs = read_messages(sm)
    assert len(msgs) == 1
    assert msgs[0].type == MT.REPLICATE
    # once ReplicateResp arrives, heartbeats stop re-sending
    sm.handle(
        msg(from_=2, to=1, type=MT.REPLICATE_RESP,
            log_index=msgs[0].log_index + len(msgs[0].entries))
    )
    read_messages(sm)
    sm.handle(msg(from_=2, to=1, type=MT.HEARTBEAT_RESP))
    assert read_messages(sm) == []


def test_mt_replicate_resp_wait_reset():
    sm = new_test_raft(1, [1, 2, 3], 5, 1, InMemLogDB())
    sm.become_candidate()
    sm.become_leader()
    sm.broadcast_replicate_message()
    read_messages(sm)
    # node 2 acks the first entry, committing it
    sm.handle(msg(from_=2, to=1, type=MT.REPLICATE_RESP, log_index=1))
    assert sm.log.committed == 1
    read_messages(sm)
    # a new command proposed on node 1
    sm.handle(msg(from_=1, to=1, type=MT.PROPOSE, entries=[Entry()]))
    # broadcast reaches only node 2 (3 is still waiting)
    msgs = read_messages(sm)
    assert len(msgs) == 1, msgs
    assert msgs[0].type == MT.REPLICATE and msgs[0].to == 2
    assert len(msgs[0].entries) == 1 and msgs[0].entries[0].index == 2
    assert sm.remotes[3].state == RemoteState.WAIT
    # node 3 acks the first entry: leaves wait, entry 2 is sent
    sm.handle(msg(from_=3, to=1, type=MT.REPLICATE_RESP, log_index=1))
    assert sm.remotes[3].state == RemoteState.REPLICATE
    msgs = read_messages(sm)
    assert len(msgs) == 1
    assert msgs[0].type == MT.REPLICATE and msgs[0].to == 3
    assert len(msgs[0].entries) == 1 and msgs[0].entries[0].index == 2


# ----------------------------------------------------------------------
# votes + state transitions + stepdown (raft_etcd_test.go:1430-1643)
# ----------------------------------------------------------------------

def test_recv_msg_vote():
    from dragonboat_tpu.raft.log import EntryLog

    cases = [
        (RaftState.FOLLOWER, 0, 0, NO_LEADER, True),
        (RaftState.FOLLOWER, 0, 1, NO_LEADER, True),
        (RaftState.FOLLOWER, 0, 2, NO_LEADER, True),
        (RaftState.FOLLOWER, 0, 3, NO_LEADER, False),
        (RaftState.FOLLOWER, 1, 0, NO_LEADER, True),
        (RaftState.FOLLOWER, 1, 1, NO_LEADER, True),
        (RaftState.FOLLOWER, 1, 2, NO_LEADER, True),
        (RaftState.FOLLOWER, 1, 3, NO_LEADER, False),
        (RaftState.FOLLOWER, 2, 0, NO_LEADER, True),
        (RaftState.FOLLOWER, 2, 1, NO_LEADER, True),
        (RaftState.FOLLOWER, 2, 2, NO_LEADER, False),
        (RaftState.FOLLOWER, 2, 3, NO_LEADER, False),
        (RaftState.FOLLOWER, 3, 0, NO_LEADER, True),
        (RaftState.FOLLOWER, 3, 1, NO_LEADER, True),
        (RaftState.FOLLOWER, 3, 2, NO_LEADER, False),
        (RaftState.FOLLOWER, 3, 3, NO_LEADER, False),
        (RaftState.FOLLOWER, 3, 2, 2, False),
        (RaftState.FOLLOWER, 3, 2, 1, True),
        (RaftState.LEADER, 3, 3, 1, True),
        (RaftState.CANDIDATE, 3, 3, 1, True),
    ]
    for i, (state, idx, term, vote_for, wreject) in enumerate(cases):
        sm = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
        sm.state = state
        sm.vote = vote_for
        storage = InMemLogDB()
        storage.append([Entry(index=1, term=2), Entry(index=2, term=2)])
        sm.log = EntryLog(storage)
        sm.handle(
            msg(type=MT.REQUEST_VOTE, from_=2, to=1, log_index=idx, log_term=term)
        )
        msgs = read_messages(sm)
        assert len(msgs) == 1, f"#{i}"
        assert msgs[0].reject == wreject, f"#{i}: reject {msgs[0].reject}"


def test_state_transition():
    cases = [
        (RaftState.FOLLOWER, RaftState.FOLLOWER, True, 1, NO_LEADER),
        (RaftState.FOLLOWER, RaftState.CANDIDATE, True, 1, NO_LEADER),
        (RaftState.FOLLOWER, RaftState.LEADER, False, 0, NO_LEADER),
        (RaftState.CANDIDATE, RaftState.FOLLOWER, True, 0, NO_LEADER),
        (RaftState.CANDIDATE, RaftState.CANDIDATE, True, 1, NO_LEADER),
        (RaftState.CANDIDATE, RaftState.LEADER, True, 0, 1),
        (RaftState.LEADER, RaftState.FOLLOWER, True, 1, NO_LEADER),
        (RaftState.LEADER, RaftState.CANDIDATE, False, 1, NO_LEADER),
        (RaftState.LEADER, RaftState.LEADER, True, 0, 1),
    ]
    for i, (frm, to, wallow, wterm, wlead) in enumerate(cases):
        sm = new_test_raft(1, [1], 10, 1, InMemLogDB())
        sm.state = frm
        try:
            if to == RaftState.FOLLOWER:
                sm.become_follower(wterm, wlead)
            elif to == RaftState.CANDIDATE:
                sm.become_candidate()
            else:
                sm.become_leader()
        except RuntimeError:
            assert not wallow, f"#{i}: unexpected disallow"
            continue
        assert wallow, f"#{i}: transition allowed unexpectedly"
        assert sm.term == wterm, f"#{i}: term {sm.term}"
        assert sm.leader_id == wlead, f"#{i}: lead {sm.leader_id}"


def test_all_server_stepdown():
    cases = [
        (RaftState.FOLLOWER, RaftState.FOLLOWER, 3, 0),
        (RaftState.CANDIDATE, RaftState.FOLLOWER, 3, 0),
        (RaftState.LEADER, RaftState.FOLLOWER, 3, 1),
    ]
    tterm = 3
    for i, (state, wstate, wterm, windex) in enumerate(cases):
        sm = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
        if state == RaftState.FOLLOWER:
            sm.become_follower(1, NO_LEADER)
        elif state == RaftState.CANDIDATE:
            sm.become_candidate()
        else:
            sm.become_candidate()
            sm.become_leader()
        for j, mtype in enumerate((MT.REQUEST_VOTE, MT.REPLICATE)):
            sm.handle(msg(from_=2, to=1, type=mtype, term=tterm, log_term=tterm))
            assert sm.state == wstate, f"#{i}.{j}"
            assert sm.term == wterm, f"#{i}.{j}"
            assert sm.log.last_index() == windex, f"#{i}.{j}"
            assert len(get_all_entries(sm.log)) == windex, f"#{i}.{j}"
            wlead = NO_LEADER if mtype == MT.REQUEST_VOTE else 2
            assert sm.leader_id == wlead, f"#{i}.{j}"


def test_leader_stepdown_when_quorum_active():
    sm = new_test_raft(1, [1, 2, 3], 5, 1, InMemLogDB())
    sm.check_quorum = True
    sm.become_candidate()
    sm.become_leader()
    for _ in range(sm.election_timeout + 1):
        sm.handle(msg(from_=2, to=1, type=MT.HEARTBEAT_RESP, term=sm.term))
        sm.tick()
    assert sm.state == RaftState.LEADER


def test_leader_stepdown_when_quorum_lost():
    sm = new_test_raft(1, [1, 2, 3], 5, 1, InMemLogDB())
    sm.check_quorum = True
    sm.become_candidate()
    sm.become_leader()
    for _ in range(sm.election_timeout + 1):
        sm.tick()
    assert sm.state == RaftState.FOLLOWER


def test_leader_superseding_with_check_quorum():
    a = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
    b = new_test_raft(2, [1, 2, 3], 10, 1, InMemLogDB())
    c = new_test_raft(3, [1, 2, 3], 10, 1, InMemLogDB())
    for r in (a, b, c):
        r.check_quorum = True
    nt = Network(a, b, c)
    b.randomized_election_timeout = b.election_timeout + 1
    for _ in range(b.election_timeout):
        b.tick()
    nt.send(campaign(a))
    assert a.state == RaftState.LEADER
    assert c.state == RaftState.FOLLOWER
    nt.send(campaign(c))
    # b rejects c's vote: election tick below timeout
    assert c.state == RaftState.CANDIDATE
    for _ in range(b.election_timeout):
        b.tick()
    nt.send(campaign(c))
    assert c.state == RaftState.LEADER


def test_leader_election_with_check_quorum():
    a = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
    b = new_test_raft(2, [1, 2, 3], 10, 1, InMemLogDB())
    c = new_test_raft(3, [1, 2, 3], 10, 1, InMemLogDB())
    for r in (a, b, c):
        r.check_quorum = True
    nt = Network(a, b, c)
    a.randomized_election_timeout = a.election_timeout + 1
    b.randomized_election_timeout = b.election_timeout + 2
    # right after creation, votes are cast regardless of election timeout
    nt.send(campaign(a))
    assert a.state == RaftState.LEADER
    assert c.state == RaftState.FOLLOWER
    a.randomized_election_timeout = a.election_timeout + 1
    b.randomized_election_timeout = b.election_timeout + 2
    for _ in range(a.election_timeout):
        a.tick()
    for _ in range(b.election_timeout):
        b.tick()
    nt.send(campaign(c))
    assert a.state == RaftState.FOLLOWER
    assert c.state == RaftState.LEADER


def test_free_stuck_candidate_with_check_quorum():
    a = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
    b = new_test_raft(2, [1, 2, 3], 10, 1, InMemLogDB())
    c = new_test_raft(3, [1, 2, 3], 10, 1, InMemLogDB())
    for r in (a, b, c):
        r.check_quorum = True
    nt = Network(a, b, c)
    b.randomized_election_timeout = b.election_timeout + 1
    for _ in range(b.election_timeout):
        b.tick()
    nt.send(campaign(a))
    nt.isolate(1)
    nt.send(campaign(c))
    assert b.state == RaftState.FOLLOWER
    assert c.state == RaftState.CANDIDATE
    assert c.term == b.term + 1
    nt.send(campaign(c))
    assert b.state == RaftState.FOLLOWER
    assert c.state == RaftState.CANDIDATE
    assert c.term == b.term + 2
    nt.recover()
    nt.send(msg(from_=1, to=3, type=MT.HEARTBEAT, term=a.term))
    # the stuck candidate's higher term disrupts the leader
    assert a.state == RaftState.FOLLOWER
    assert c.term == a.term
    nt.send(campaign(c))
    assert c.state == RaftState.LEADER


def test_non_promotable_voter_with_check_quorum():
    a = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    b = new_test_raft(2, [1], 10, 1, InMemLogDB())
    a.check_quorum = True
    b.check_quorum = True
    nt = Network(a, b)
    b.randomized_election_timeout = b.election_timeout + 1
    # remove 2 again: Network rebuilt internal peer sets (the reference's
    # deleteRemote is a bare map delete)
    del b.remotes[2]
    assert b.self_removed()
    for _ in range(b.election_timeout):
        b.tick()
    nt.send(campaign(a))
    assert a.state == RaftState.LEADER
    assert b.state == RaftState.FOLLOWER
    assert b.leader_id == 1


# ----------------------------------------------------------------------
# readindex + leader resp/heartbeat behavior (raft_etcd_test.go:1847-2208)
# ----------------------------------------------------------------------

def test_read_only_option_safe():
    from dragonboat_tpu.wire import SystemCtx

    a = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
    b = new_test_raft(2, [1, 2, 3], 10, 1, InMemLogDB())
    c = new_test_raft(3, [1, 2, 3], 10, 1, InMemLogDB())
    nt = Network(a, b, c)
    b.randomized_election_timeout = b.election_timeout + 1
    for _ in range(b.election_timeout):
        b.tick()
    nt.send(campaign(a))
    assert a.state == RaftState.LEADER
    cases = [
        (a, 10, 11, SystemCtx(low=10001, high=10001)),
        (b, 10, 21, SystemCtx(low=10002, high=10002)),
        (c, 10, 31, SystemCtx(low=10003, high=10003)),
        (a, 10, 41, SystemCtx(low=10004, high=10004)),
        (b, 10, 51, SystemCtx(low=10005, high=10005)),
        (c, 10, 61, SystemCtx(low=10006, high=10006)),
    ]
    for i, (sm, proposals, wri, wctx) in enumerate(cases):
        for _ in range(proposals):
            nt.send(msg(from_=1, to=1, type=MT.PROPOSE, entries=[Entry()]))
        nt.send(
            msg(from_=sm.node_id, to=sm.node_id, type=MT.READ_INDEX,
                hint=wctx.low, hint_high=wctx.high)
        )
        assert sm.ready_to_read, f"#{i}: no ready_to_read"
        rs = sm.ready_to_read[0]
        assert rs.index == wri, f"#{i}: {rs.index} want {wri}"
        assert rs.system_ctx == wctx, f"#{i}"
        sm.ready_to_read = []


def test_leader_app_resp():
    from dragonboat_tpu.raft.log import EntryLog

    cases = [
        (3, True, 0, 3, 0, 0, 0),   # stale resp
        (2, True, 0, 2, 1, 1, 0),   # denied resp: decrease next, probe
        (2, False, 2, 4, 2, 2, 2),  # accepted: commit + broadcast
        (0, False, 0, 3, 0, 0, 0),  # ignore heartbeat replies
    ]
    for i, (index, reject, wmatch, wnext, wmsg_num, windex, wcommitted) in enumerate(cases):
        sm = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
        storage = InMemLogDB()
        storage.append([Entry(index=1, term=0), Entry(index=2, term=1)])
        sm.log = EntryLog(storage)
        sm.become_candidate()
        sm.become_leader()
        read_messages(sm)
        sm.handle(
            msg(from_=2, to=1, type=MT.REPLICATE_RESP, log_index=index,
                term=sm.term, reject=reject, hint=index)
        )
        p = sm.remotes[2]
        assert p.match == wmatch, f"#{i}: match {p.match}"
        assert p.next == wnext, f"#{i}: next {p.next}"
        msgs = read_messages(sm)
        assert len(msgs) == wmsg_num, f"#{i}: {len(msgs)} msgs"
        for j, m in enumerate(msgs):
            assert m.log_index == windex, f"#{i}.{j}"
            assert m.commit == wcommitted, f"#{i}.{j}"


def test_bcast_beat():
    offset = 1000
    ss = Snapshot(index=offset, term=1, membership=mk_membership([1, 2, 3]))
    storage = InMemLogDB()
    storage.apply_snapshot(ss)
    sm = new_test_raft(1, [], 10, 1, storage)
    sm.term = 1
    sm.become_candidate()
    sm.become_leader()
    for i in range(10):
        sm.append_entries([Entry(index=i + 1)])
    # slow follower / normal follower
    sm.remotes[2].match, sm.remotes[2].next = 5, 6
    sm.remotes[3].match = sm.log.last_index()
    sm.remotes[3].next = sm.log.last_index() + 1
    sm.handle(msg(type=MT.LEADER_HEARTBEAT, from_=1, to=1))
    msgs = read_messages(sm)
    msgs = [m for m in msgs if m.type == MT.HEARTBEAT]
    assert len(msgs) == 2
    want_commit = {
        2: min(sm.log.committed, sm.remotes[2].match),
        3: min(sm.log.committed, sm.remotes[3].match),
    }
    for i, m in enumerate(msgs):
        assert m.log_index == 0, f"#{i}"
        assert m.log_term == 0, f"#{i}"
        assert want_commit.pop(m.to, 0) == m.commit, f"#{i}"
        assert len(m.entries) == 0, f"#{i}"


def test_recv_msg_leader_heartbeat():
    from dragonboat_tpu.raft.log import EntryLog

    cases = [
        (RaftState.LEADER, 2),
        (RaftState.CANDIDATE, 0),
        (RaftState.FOLLOWER, 0),
    ]
    for i, (state, wmsg) in enumerate(cases):
        sm = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
        storage = InMemLogDB()
        storage.append([Entry(index=1, term=0), Entry(index=2, term=1)])
        sm.log = EntryLog(storage)
        sm.term = 1
        sm.state = state
        sm.handle(msg(from_=1, to=1, type=MT.LEADER_HEARTBEAT))
        msgs = read_messages(sm)
        assert len(msgs) == wmsg, f"#{i}: {len(msgs)}"
        for m in msgs:
            assert m.type == MT.HEARTBEAT, f"#{i}"


def test_leader_increase_next():
    previous = [Entry(term=1, index=1), Entry(term=1, index=2), Entry(term=1, index=3)]
    cases = [
        # replicate state: optimistically increase next
        (RemoteState.REPLICATE, 2, len(previous) + 1 + 1 + 1),
        # retry state: no optimistic increase
        (RemoteState.RETRY, 2, 2),
    ]
    for i, (state, next_, wnext) in enumerate(cases):
        sm = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
        sm.log.append(list(previous))
        sm.become_candidate()
        sm.become_leader()
        sm.remotes[2].state = state
        sm.remotes[2].next = next_
        sm.handle(propose(1))
        assert sm.remotes[2].next == wnext, f"#{i}: {sm.remotes[2].next}"


def test_send_append_for_remote_retry():
    r = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    r.become_candidate()
    r.become_leader()
    read_messages(r)
    r.remotes[2].become_retry()
    for i in range(3):
        if i == 0:
            # only one Replicate goes out; then the remote is paused until
            # a heartbeat response arrives
            r.append_entries([Entry(cmd=b"somedata")])
            r.send_replicate_message(2)
            ms = read_messages(r)
            assert len(ms) == 1
            assert ms[0].log_index == 0
        assert r.remotes[2].state == RemoteState.WAIT
        for _ in range(10):
            r.append_entries([Entry(cmd=b"somedata")])
            r.send_replicate_message(2)
            assert read_messages(r) == []
        for _ in range(r.heartbeat_timeout):
            r.handle(msg(from_=1, to=1, type=MT.LEADER_HEARTBEAT))
        assert r.remotes[2].state == RemoteState.WAIT
        ms = read_messages(r)
        assert len(ms) == 1
        assert ms[0].type == MT.HEARTBEAT
    r.handle(msg(from_=2, to=1, type=MT.HEARTBEAT_RESP))
    ms = read_messages(r)
    assert len(ms) == 1
    assert ms[0].log_index == 0
    assert r.remotes[2].state == RemoteState.WAIT


def test_send_append_for_remote_replicate():
    r = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    r.become_candidate()
    r.become_leader()
    read_messages(r)
    r.remotes[2].become_replicate()
    for _ in range(10):
        r.append_entries([Entry(cmd=b"somedata")])
        r.send_replicate_message(2)
        assert len(read_messages(r)) == 1


def test_send_append_for_remote_snapshot():
    r = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    r.become_candidate()
    r.become_leader()
    read_messages(r)
    r.remotes[2].become_snapshot(10)
    for _ in range(10):
        r.append_entries([Entry(cmd=b"somedata")])
        r.send_replicate_message(2)
        assert read_messages(r) == []


def test_recv_msg_unreachable():
    previous = [Entry(term=1, index=1), Entry(term=1, index=2), Entry(term=1, index=3)]
    s = InMemLogDB()
    s.append(previous)
    r = new_test_raft(1, [1, 2], 10, 1, s)
    r.become_candidate()
    r.become_leader()
    read_messages(r)
    r.remotes[2].match = 3
    r.remotes[2].become_replicate()
    r.remotes[2].try_update(5)
    r.handle(msg(from_=2, to=1, type=MT.UNREACHABLE))
    assert r.remotes[2].state == RemoteState.RETRY
    assert r.remotes[2].next == r.remotes[2].match + 1


# ----------------------------------------------------------------------
# snapshot restore + config change (raft_etcd_test.go:2234-2792)
# ----------------------------------------------------------------------

TESTING_SNAP_NODES = [1, 2]


def _testing_snap():
    return Snapshot(index=11, term=11, membership=mk_membership(TESTING_SNAP_NODES))


def test_restore():
    s = Snapshot(index=11, term=11, membership=mk_membership([1, 2, 3]))
    sm = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    assert sm.restore(s)
    assert sm.log.last_index() == s.index
    assert sm.log.term(s.index) == s.term
    assert sorted(sm.nodes_sorted()) != sorted(s.membership.addresses)
    sm.restore_remotes(s)
    assert sorted(sm.nodes_sorted()) == sorted(s.membership.addresses)
    assert not sm.restore(s)


def test_restore_ignore_snapshot():
    previous = [Entry(term=1, index=1), Entry(term=1, index=2), Entry(term=1, index=3)]
    commit = 1
    sm = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    sm.log.append(previous)
    sm.log.commit_to(commit)
    s = Snapshot(index=commit, term=1, membership=mk_membership([1, 2]))
    # ignore snapshot
    assert not sm.restore(s)
    assert sm.log.committed == commit
    # matching index/term: no restore needed but commit fast-forwards
    s.index = commit + 1
    assert not sm.restore(s)
    assert sm.log.committed == commit + 1


def test_provide_snap():
    s = _testing_snap()
    sm = new_test_raft(1, [1], 10, 1, InMemLogDB())
    sm.restore(s)
    sm.restore_remotes(s)
    sm.become_candidate()
    sm.become_leader()
    # node 2 needs a snapshot
    sm.remotes[2].next = sm.log.first_index()
    sm.handle(
        msg(from_=2, to=1, type=MT.REPLICATE_RESP,
            log_index=sm.remotes[2].next - 1, reject=True,
            hint=sm.remotes[2].next - 1)
    )
    msgs = read_messages(sm)
    assert len(msgs) == 1
    assert msgs[0].type == MT.INSTALL_SNAPSHOT


def test_ignore_providing_snap():
    s = _testing_snap()
    sm = new_test_raft(1, [1], 10, 1, InMemLogDB())
    sm.restore(s)
    sm.restore_remotes(s)
    sm.become_candidate()
    sm.become_leader()
    # node 2 needs a snapshot but is inactive: don't send
    sm.remotes[2].next = sm.log.first_index() - 1
    sm.remotes[2].active = False
    sm.handle(propose(1))
    assert read_messages(sm) == []


def test_restore_from_snap_msg():
    s = _testing_snap()
    m = msg(type=MT.INSTALL_SNAPSHOT, from_=1, to=2, term=2)
    m.snapshot = s
    sm = new_test_raft(2, [1, 2], 10, 1, InMemLogDB())
    sm.handle(m)
    assert sm.leader_id == 1


def test_slow_node_restore():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.isolate(3)
    for _ in range(101):
        nt.send(msg(from_=1, to=1, type=MT.PROPOSE, entries=[Entry()]))
    lead = nt.raft(1)
    next_ents(lead, nt.storage[1])
    m = mk_membership(lead.nodes_sorted())
    ss = get_snapshot(nt.storage[1], lead.log.processed, m)
    nt.storage[1].create_snapshot(ss)
    nt.storage[1].compact(lead.log.processed)
    follower = nt.raft(3)
    nt.recover()
    # heartbeats until the leader learns node 3 is active
    for _ in range(1000):
        nt.send(msg(from_=1, to=1, type=MT.LEADER_HEARTBEAT))
        if lead.remotes[3].active:
            break
    assert lead.remotes[3].active
    # trigger snapshot + commit
    nt.send(msg(from_=1, to=1, type=MT.PROPOSE, entries=[Entry()]))
    nt.send(msg(from_=1, to=1, type=MT.PROPOSE, entries=[Entry()]))
    assert follower.log.committed == lead.log.committed


def test_step_config():
    r = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    r.become_candidate()
    r.become_leader()
    index = r.log.last_index()
    r.handle(
        msg(from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(type=EntryType.CONFIG_CHANGE)])
    )
    assert r.log.last_index() == index + 1
    assert r.pending_config_change


def test_step_ignore_config():
    r = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    r.become_candidate()
    r.become_leader()
    r.handle(
        msg(from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(type=EntryType.CONFIG_CHANGE)])
    )
    index = r.log.last_index()
    pending = r.pending_config_change
    r.handle(
        msg(from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(type=EntryType.CONFIG_CHANGE)])
    )
    ents = r.log.get_entries(index + 1, r.log.last_index() + 1, NO_LIMIT)
    assert len(ents) == 1
    assert ents[0].type == EntryType.APPLICATION and not ents[0].cmd
    assert ents[0].term == 1 and ents[0].index == 3
    assert r.pending_config_change == pending


def test_recover_pending_config():
    for i, (etype, wpending) in enumerate(
        [(EntryType.APPLICATION, False), (EntryType.CONFIG_CHANGE, True)]
    ):
        r = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
        r.append_entries([Entry(type=etype)])
        r.become_candidate()
        r.become_leader()
        assert r.pending_config_change == wpending, f"#{i}"


def test_recover_double_pending_config():
    r = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    r.append_entries([Entry(type=EntryType.CONFIG_CHANGE)])
    r.append_entries([Entry(type=EntryType.CONFIG_CHANGE)])
    r.become_candidate()
    with pytest.raises(Exception):
        r.become_leader()


def test_add_node():
    r = new_test_raft(1, [1], 10, 1, InMemLogDB())
    r.pending_config_change = True
    r.add_node(2)
    assert not r.pending_config_change
    assert r.nodes_sorted() == [1, 2]


def test_remove_node():
    r = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    r.pending_config_change = True
    r.remove_node(2)
    assert not r.pending_config_change
    assert r.nodes_sorted() == [1]
    r.remove_node(1)
    assert r.nodes_sorted() == []


def test_promotable():
    cases = [
        ([1], True),
        ([1, 2, 3], True),
        ([], False),
        ([2, 3], False),
    ]
    for i, (peers, wp) in enumerate(cases):
        r = new_test_raft(1, peers, 5, 1, InMemLogDB())
        assert (not r.self_removed()) == wp, f"#{i}"


def test_raft_nodes():
    cases = [
        ([1, 2, 3], [1, 2, 3]),
        ([3, 2, 1], [1, 2, 3]),
    ]
    for i, (ids, wids) in enumerate(cases):
        r = new_test_raft(1, ids, 10, 1, InMemLogDB())
        assert r.nodes_sorted() == wids, f"#{i}"


def test_campaign_while_leader():
    r = new_test_raft(1, [1], 5, 1, InMemLogDB())
    assert r.state == RaftState.FOLLOWER
    r.handle(campaign(r))
    assert r.state == RaftState.LEADER
    term = r.term
    r.handle(campaign(r))
    assert r.state == RaftState.LEADER
    assert r.term == term


def test_commit_after_remove_node():
    from dragonboat_tpu.wire import ConfigChange, ConfigChangeType
    from dragonboat_tpu.wire.codec import encode_config_change

    s = InMemLogDB()
    r = new_test_raft(1, [1, 2], 5, 1, s)
    r.become_candidate()
    r.become_leader()
    cc = ConfigChange(type=ConfigChangeType.REMOVE_NODE, node_id=2)
    r.handle(
        msg(from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(type=EntryType.CONFIG_CHANGE,
                           cmd=encode_config_change(cc))])
    )
    assert next_ents(r, s) == []
    cc_index = r.log.last_index()
    r.handle(
        msg(from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(type=EntryType.APPLICATION, cmd=b"hello")])
    )
    # node 2 acks the config change, committing it
    r.handle(msg(from_=2, to=1, type=MT.REPLICATE_RESP, log_index=cc_index))
    ents = next_ents(r, s)
    assert len(ents) == 2
    assert ents[0].type == EntryType.APPLICATION and not ents[0].cmd
    assert ents[1].type == EntryType.CONFIG_CHANGE
    # applying the config change reduces quorum; the pending command commits
    r.remove_node(2)
    ents = next_ents(r, s)
    assert len(ents) == 1
    assert ents[0].type == EntryType.APPLICATION and ents[0].cmd == b"hello"


def test_sending_snapshot_set_pending_snapshot():
    sm = new_test_raft(1, [1], 10, 1, InMemLogDB())
    snap = _testing_snap()
    sm.restore(snap)
    sm.restore_remotes(snap)
    sm.become_candidate()
    sm.become_leader()
    sm.remotes[2].next = sm.log.first_index()
    sm.handle(
        msg(from_=2, to=1, type=MT.REPLICATE_RESP,
            log_index=sm.remotes[2].next - 1, reject=True,
            hint=sm.remotes[2].next - 1)
    )
    assert sm.remotes[2].snapshot_index == 11


def test_pending_snapshot_pause_replication():
    sm = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    snap = _testing_snap()
    sm.restore(snap)
    sm.restore_remotes(snap)
    sm.become_candidate()
    sm.become_leader()
    sm.remotes[2].become_snapshot(11)
    sm.handle(propose(1))
    assert read_messages(sm) == []


def test_snapshot_failure():
    sm = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    snap = _testing_snap()
    sm.restore(snap)
    sm.restore_remotes(snap)
    sm.become_candidate()
    sm.become_leader()
    sm.remotes[2].next = 1
    sm.remotes[2].become_snapshot(11)
    sm.handle(msg(from_=2, to=1, type=MT.SNAPSHOT_STATUS, reject=True))
    assert sm.remotes[2].snapshot_index == 0
    assert sm.remotes[2].next == 1
    assert sm.remotes[2].state == RemoteState.WAIT


def test_snapshot_succeed():
    sm = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    snap = _testing_snap()
    sm.restore(snap)
    sm.restore_remotes(snap)
    sm.become_candidate()
    sm.become_leader()
    sm.remotes[2].next = 1
    sm.remotes[2].become_snapshot(11)
    sm.handle(msg(from_=2, to=1, type=MT.SNAPSHOT_STATUS, reject=False))
    assert sm.remotes[2].snapshot_index == 0
    assert sm.remotes[2].next == 12
    assert sm.remotes[2].state == RemoteState.WAIT


def test_snapshot_abort():
    sm = new_test_raft(1, [1, 2], 10, 1, InMemLogDB())
    snap = _testing_snap()
    sm.restore(snap)
    sm.restore_remotes(snap)
    sm.become_candidate()
    sm.become_leader()
    sm.remotes[2].next = 1
    sm.remotes[2].become_snapshot(11)
    # an accepted resp at/above the pending snapshot index aborts it
    sm.handle(msg(from_=2, to=1, type=MT.REPLICATE_RESP, log_index=11))
    assert sm.remotes[2].snapshot_index == 0
    assert sm.remotes[2].next == 12
