"""Differential re-runs of ported etcd conformance scenarios with the
batched device quorum engine in the offload seat.

VERDICT r2 item 3 asks for ported scenarios re-run under
``quorum_engine="tpu"`` with identical outcomes.  Each harness raft gets a
:class:`SyncDeviceOffload` — the synchronous twin of
``tpuquorum.TpuQuorumCoordinator``: the raft's hot-path events (acks, votes,
state transitions) are staged into a :class:`BatchedQuorumEngine` row, a
device round runs after every network delivery, and commit/election
outcomes are applied back exactly like ``Node.offload_commit`` /
``Node.offload_election`` (term guard re-applied scalar-side).  The final
cluster state must be bit-identical to the pure-scalar run of the same
ported scenario (commit indexes, terms, leadership, log signatures).

Runs on the CPU backend in CI (conftest forces ``JAX_PLATFORM_NAME=cpu``);
the engine path is identical on TPU.
"""
from __future__ import annotations

from dragonboat_tpu.ops.engine import BatchedQuorumEngine
from dragonboat_tpu.raft import InMemLogDB, Raft
from dragonboat_tpu.raft.raft import NO_LEADER, RaftState
from dragonboat_tpu.wire import Entry, EntryType, Message, MessageType
from tests.raft_harness import (
    Network,
    campaign,
    ent_sig,
    get_all_entries,
    new_test_raft,
    propose,
)

MT = MessageType


class SyncDeviceOffload:
    """Synchronous single-raft twin of the TpuQuorumCoordinator."""

    def __init__(self, raft: Raft, n_peers: int = 8):
        self.r = raft
        self.eng = BatchedQuorumEngine(1, n_peers, event_cap=256)
        self._register()
        raft.offload = self

    def _register(self) -> None:
        r = self.r
        cid = r.cluster_id
        if cid in self.eng.groups:
            self.eng.remove_group(cid)
        voters = sorted(set(r.remotes) | {r.node_id})
        self.eng.add_group(
            cid,
            node_ids=voters,
            self_id=r.node_id,
            election_timeout=r.election_timeout,
            heartbeat_timeout=r.heartbeat_timeout,
            check_quorum=r.check_quorum,
            witnesses=tuple(sorted(r.witnesses)),
            observers=tuple(sorted(r.observers)),
        )
        if r.is_leader():
            self.eng.set_leader(
                cid, term=r.term, term_start=r.log.last_index(),
                last_index=r.log.last_index(),
            )
            for nid, rp in r.remotes.items():
                if rp.match > 0:
                    self.eng.ack(cid, nid, rp.match)
        elif r.is_candidate():
            self.eng.set_candidate(cid, term=r.term)
            for nid, granted in r.votes.items():
                self.eng.vote(cid, nid, granted)
        else:
            self.eng.set_follower(cid, term=r.term)

    # -- staging hooks (raft calls these under its step) --

    def ack(self, cluster_id, node_id, index):
        try:
            self.eng.ack(cluster_id, node_id, index)
        except (ValueError, KeyError):
            self._register()

    def vote(self, cluster_id, node_id, granted):
        try:
            self.eng.vote(cluster_id, node_id, granted)
        except (ValueError, KeyError):
            self._register()

    def heartbeat_resp(self, cluster_id, node_id):
        try:
            self.eng.heartbeat_resp(cluster_id, node_id)
        except (ValueError, KeyError):
            self._register()

    def set_leader(self, cluster_id, term, term_start, last_index):
        self.eng.set_leader(
            cluster_id, term=term, term_start=term_start, last_index=last_index
        )

    def set_candidate(self, cluster_id, term):
        self.eng.set_candidate(cluster_id, term=term)

    def set_follower(self, cluster_id, term):
        self.eng.set_follower(cluster_id, term=term)

    def membership_changed(self, cluster_id):
        self._register()

    # -- the round (Node.offload_commit / offload_election twins) --

    def pump(self) -> None:
        res = self.eng.step(do_tick=False)
        r = self.r
        q = res.commit.get(r.cluster_id)
        if q is not None and r.is_leader() and r.log.try_commit(q, r.term):
            r.broadcast_replicate_message()
        gi = self.eng.groups.get(r.cluster_id)
        term = int(self.eng._read("term", gi.row)) if gi is not None else 0
        if r.cluster_id in res.won:
            if r.is_candidate() and r.term == term:
                r.become_leader()
                r.broadcast_replicate_message()
        elif r.cluster_id in res.lost:
            if r.is_candidate() and r.term == term:
                r.become_follower(r.term, NO_LEADER)


class DeviceNetwork(Network):
    """Network that runs a device round for every peer after each delivery
    (message effects stage events; the round applies outcomes and may emit
    follow-up messages, which keep flowing through the same queue)."""

    def attach(self):
        self.offloads = {}
        for nid, p in self.peers.items():
            if isinstance(p, Raft):
                self.offloads[nid] = SyncDeviceOffload(p)
        return self

    def send(self, *msgs: Message) -> None:
        queue = list(msgs)
        while queue:
            m = queue.pop(0)
            p = self.peers.get(m.to)
            if p is None:
                continue
            p.handle(m)
            off = getattr(self, "offloads", {}).get(m.to)
            if off is not None:
                off.pump()
            if isinstance(p, Raft):
                queue.extend(self.filter(self.take_msgs(p)))


def cluster_fingerprint(nt: Network) -> dict:
    out = {}
    for nid, p in nt.peers.items():
        if isinstance(p, Raft):
            out[nid] = {
                "state": p.state,
                "term": p.term,
                "leader": p.leader_id,
                "committed": p.log.committed,
                "log": ent_sig(get_all_entries(p.log)),
            }
    return out


def _run_both(scenario):
    scalar = Network(None, None, None)
    scenario(scalar)
    device = DeviceNetwork(None, None, None).attach()
    scenario(device)
    fs, fd = cluster_fingerprint(scalar), cluster_fingerprint(device)
    assert fs == fd, f"scalar {fs} != device {fd}"
    return fs


# -- scenario 1: ported test_log_replication --

def test_differential_log_replication():
    def scenario(nt):
        nt.send(campaign(nt.raft(1)))
        nt.send(propose(1))
        nt.send(msg_election(2))
        nt.send(propose(2))

    def msg_election(nid):
        return Message(from_=nid, to=nid, type=MT.ELECTION)

    fp = _run_both(scenario)
    # sanity vs the ported scalar expectation (committed == 4)
    assert all(v["committed"] == 4 for v in fp.values())


# -- scenario 2: ported test_cannot_commit_without_new_term_entry (5 nodes) --

def test_differential_cannot_commit_without_new_term_entry():
    def scenario(nt):
        nt.send(campaign(nt.raft(1)))
        nt.cut(1, 3)
        nt.cut(1, 4)
        nt.cut(1, 5)
        nt.send(propose(1, b"some data"))
        nt.send(propose(1, b"some data"))
        assert nt.raft(1).log.committed == 1
        nt.recover()
        nt.ignore(MT.REPLICATE)
        nt.send(campaign(nt.raft(2)))
        assert nt.raft(2).log.committed == 1
        nt.recover()
        nt.send(Message(from_=2, to=2, type=MT.LEADER_HEARTBEAT))
        nt.send(propose(2, b"some data"))
        assert nt.raft(2).log.committed == 5

    scalar = Network(None, None, None, None, None)
    scenario(scalar)
    device = DeviceNetwork(None, None, None, None, None).attach()
    scenario(device)
    assert cluster_fingerprint(scalar) == cluster_fingerprint(device)


# -- scenario 3: ported test_dueling_candidates --

def test_differential_dueling_candidates():
    def build():
        a = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
        b = new_test_raft(2, [1, 2, 3], 10, 1, InMemLogDB())
        c = new_test_raft(3, [1, 2, 3], 10, 1, InMemLogDB())
        return a, b, c

    def scenario(nt):
        nt.cut(1, 3)
        nt.send(campaign(nt.raft(1)))
        nt.send(campaign(nt.raft(3)))
        assert nt.raft(1).state == RaftState.LEADER
        assert nt.raft(3).state == RaftState.CANDIDATE
        nt.recover()
        nt.send(campaign(nt.raft(3)))

    scalar = Network(*build())
    scenario(scalar)
    device = DeviceNetwork(*build()).attach()
    scenario(device)
    assert cluster_fingerprint(scalar) == cluster_fingerprint(device)


# -- scenario 4: ported test_single_node_commit + leader cycle --

def test_differential_leader_cycle_and_commit():
    def scenario(nt):
        for campaigner in (1, 2, 3):
            nt.send(Message(from_=campaigner, to=campaigner, type=MT.ELECTION))
        nt.send(propose(3, b"x"))
        nt.send(propose(3, b"y"))

    _run_both(scenario)
