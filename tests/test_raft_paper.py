"""Raft-paper conformance tests, ported from the reference's etcd suite.

Each test reproduces the scenario of the same-named test in
``/root/reference/internal/raft/raft_etcd_paper_test.go`` (itself the etcd
raft-paper suite): init state, drive via ``Raft.handle``, check outgoing
messages and state.  Section numbers refer to the raft paper
(https://raft.github.io/raft.pdf).
"""
import pytest

from raft_harness import (
    BlackHole,
    Network,
    RaftState,
    accept_and_reply,
    commit_noop_entry,
    ent_sig,
    get_all_entries,
    ids_by_size,
    logs_equal,
    new_test_raft,
    read_messages,
)
from dragonboat_tpu.raft import InMemLogDB
from dragonboat_tpu.wire import Entry, Message, MessageType, State

MT = MessageType
F, C, L = RaftState.FOLLOWER, RaftState.CANDIDATE, RaftState.LEADER


def _enter_state(r, state, term=1, leader=2):
    if state == F:
        r.become_follower(term, leader)
    elif state == C:
        r.become_candidate()
    elif state == L:
        r.become_candidate()
        r.become_leader()


# ---------------------------------------------------------------------------
# §5.1 term handling
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("state", [F, C, L])
def test_update_term_from_message(state):
    """§5.1: a server seeing a larger term adopts it and reverts to
    follower (reference testUpdateTermFromMessage)."""
    r = new_test_raft(1, [1, 2, 3])
    _enter_state(r, state)
    r.handle(Message(type=MT.REPLICATE, term=2))
    assert r.term == 2
    assert r.state == F


def test_reject_stale_term_message():
    """§5.1: requests with a stale term are ignored (the implementation
    drops them before any per-state handler runs)."""
    r = new_test_raft(1, [1, 2, 3])
    r.load_state(State(term=2))
    r.handle(Message(type=MT.REPLICATE, term=r.term - 1))
    # no response, no state change
    assert read_messages(r) == []
    assert r.term == 2
    assert r.state == F


# ---------------------------------------------------------------------------
# §5.2 leader election
# ---------------------------------------------------------------------------


def test_start_as_follower():
    r = new_test_raft(1, [1, 2, 3])
    assert r.state == F


def test_leader_bcast_beat():
    """§5.2: on a heartbeat tick the leader broadcasts heartbeats."""
    hi = 1
    r = new_test_raft(1, [1, 2, 3], election=10, heartbeat=hi)
    r.become_candidate()
    r.become_leader()
    for i in range(10):
        r.append_entries([Entry(index=i + 1)])
    read_messages(r)
    for _ in range(hi):
        r.tick()
    msgs = sorted(read_messages(r), key=lambda m: m.to)
    assert [(m.from_, m.to, m.term, m.type) for m in msgs] == [
        (1, 2, 1, MT.HEARTBEAT),
        (1, 3, 1, MT.HEARTBEAT),
    ]


@pytest.mark.parametrize("state", [F, C])
def test_nonleader_start_election(state):
    """§5.2: without leader contact past the election timeout, a
    follower/candidate campaigns: term+1, votes for itself, RequestVote
    fan-out."""
    et = 10
    r = new_test_raft(1, [1, 2, 3], election=et, heartbeat=1)
    if state == F:
        r.become_follower(1, 2)
    else:
        r.become_candidate()
    read_messages(r)
    for _ in range(1, 2 * et):
        r.tick()
    assert r.term == 2
    assert r.state == C
    assert r.votes[r.node_id]
    msgs = sorted(
        [m for m in read_messages(r) if m.type == MT.REQUEST_VOTE],
        key=lambda m: m.to,
    )
    assert [(m.from_, m.to, m.term) for m in msgs] == [(1, 2, 2), (1, 3, 2)]


@pytest.mark.parametrize(
    "size, votes, want",
    [
        (1, {}, L),
        (3, {2: True, 3: True}, L),
        (3, {2: True}, L),
        (5, {2: True, 3: True, 4: True, 5: True}, L),
        (5, {2: True, 3: True, 4: True}, L),
        (5, {2: True, 3: True}, L),
        (3, {2: False, 3: False}, F),
        (5, {2: False, 3: False, 4: False, 5: False}, F),
        (5, {2: True, 3: False, 4: False, 5: False}, F),
        (3, {}, C),
        (5, {2: True}, C),
        (5, {2: False, 3: False}, C),
        (5, {}, C),
    ],
)
def test_leader_election_in_one_round_rpc(size, votes, want):
    """§5.2: win with a majority, lose on majority denial, else stay
    candidate."""
    r = new_test_raft(1, ids_by_size(size))
    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    for nid, granted in votes.items():
        r.handle(
            Message(
                from_=nid, to=1, term=r.term,
                type=MT.REQUEST_VOTE_RESP, reject=not granted,
            )
        )
    assert r.state == want
    assert r.term == 1


@pytest.mark.parametrize(
    "vote, nvote, wreject",
    [
        (0, 1, False),
        (0, 2, False),
        (1, 1, False),
        (2, 2, False),
        (1, 2, True),
        (2, 1, True),
    ],
)
def test_follower_vote(vote, nvote, wreject):
    """§5.2: at most one vote per term, first-come-first-served."""
    r = new_test_raft(1, [1, 2, 3])
    r.load_state(State(term=1, vote=vote))
    r.handle(Message(from_=nvote, to=1, term=1, type=MT.REQUEST_VOTE))
    msgs = read_messages(r)
    assert [(m.from_, m.to, m.term, m.type, m.reject) for m in msgs] == [
        (1, nvote, 1, MT.REQUEST_VOTE_RESP, wreject)
    ]


@pytest.mark.parametrize("term", [1, 2])
def test_candidate_fallback(term):
    """§5.2: a candidate receiving Replicate at >= its term recognizes the
    leader and falls back to follower."""
    r = new_test_raft(1, [1, 2, 3])
    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
    assert r.state == C
    r.handle(Message(from_=2, to=1, term=term, type=MT.REPLICATE))
    assert r.state == F
    assert r.term == term


@pytest.mark.parametrize("state", [F, C])
def test_nonleader_election_timeout_randomized(state):
    """§5.2: the election timeout is randomized within [et, 2*et)."""
    et = 10
    r = new_test_raft(1, [1, 2, 3], election=et, heartbeat=1)
    fire_times = set()
    for _ in range(50 * et):
        if state == F:
            r.become_follower(r.term + 1, 2)
        else:
            r.become_candidate()
        read_messages(r)
        time = 0
        while not read_messages(r):
            r.tick()
            time += 1
        fire_times.add(time)
    assert all(et <= t <= 2 * et + 1 for t in fire_times), fire_times
    # randomization must actually spread: most of the window is hit
    assert len(fire_times) >= et - 2, fire_times


@pytest.mark.parametrize("state", [F, C])
def test_nonleaders_election_timeout_nonconflict(state):
    """§5.2: randomized timeouts make simultaneous campaigns rare."""
    et = 10
    size = 5
    ids = ids_by_size(size)
    rs = [new_test_raft(nid, ids, election=et, heartbeat=1) for nid in ids]
    conflicts = 0
    rounds = 300
    for _ in range(rounds):
        for r in rs:
            if state == F:
                r.become_follower(r.term + 1, 0)
            else:
                r.become_candidate()
            read_messages(r)
        fired = 0
        while fired == 0:
            for r in rs:
                r.tick()
                if read_messages(r):
                    fired += 1
        if fired > 1:
            conflicts += 1
    assert conflicts / rounds <= 0.3


# ---------------------------------------------------------------------------
# §5.3 log replication
# ---------------------------------------------------------------------------


def test_leader_start_replication():
    """§5.3: the leader appends a proposal and fans out Replicate carrying
    it, without committing yet."""
    s = InMemLogDB()
    r = new_test_raft(1, [1, 2, 3], logdb=s)
    r.become_candidate()
    r.become_leader()
    commit_noop_entry(r, s)
    li = r.log.last_index()
    r.handle(
        Message(
            from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(cmd=b"some data")],
        )
    )
    assert r.log.last_index() == li + 1
    assert r.log.committed == li
    msgs = sorted(read_messages(r), key=lambda m: m.to)
    assert [
        (m.from_, m.to, m.term, m.type, m.log_index, m.log_term, m.commit)
        for m in msgs
    ] == [
        (1, 2, 1, MT.REPLICATE, li, 1, li),
        (1, 3, 1, MT.REPLICATE, li, 1, li),
    ]
    for m in msgs:
        assert ent_sig(m.entries) == [(1, li + 1)]
        assert m.entries[0].cmd == b"some data"
    assert ent_sig(r.log.entries_to_save()) == [(1, li + 1)]


def test_leader_commit_entry():
    """§5.3: once safely replicated, the leader commits and exposes the
    entry to apply, and advertises the commit index."""
    s = InMemLogDB()
    r = new_test_raft(1, [1, 2, 3], logdb=s)
    r.become_candidate()
    r.become_leader()
    commit_noop_entry(r, s)
    li = r.log.last_index()
    r.handle(
        Message(
            from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(cmd=b"some data")],
        )
    )
    for m in read_messages(r):
        r.handle(accept_and_reply(m))
    assert r.log.committed == li + 1
    ents = r.log.entries_to_apply()
    assert ent_sig(ents) == [(1, li + 1)]
    assert ents[0].cmd == b"some data"
    msgs = sorted(read_messages(r), key=lambda m: m.to)
    for i, m in enumerate(msgs):
        assert m.to == i + 2
        assert m.type == MT.REPLICATE
        assert m.commit == li + 1


@pytest.mark.parametrize(
    "size, acceptors, wack",
    [
        (1, {}, True),
        (3, {}, False),
        (3, {2}, True),
        (3, {2, 3}, True),
        (5, {}, False),
        (5, {2}, False),
        (5, {2, 3}, True),
        (5, {2, 3, 4}, True),
        (5, {2, 3, 4, 5}, True),
    ],
)
def test_leader_acknowledge_commit(size, acceptors, wack):
    """§5.3: an entry commits once a majority has replicated it."""
    s = InMemLogDB()
    r = new_test_raft(1, ids_by_size(size), logdb=s)
    r.become_candidate()
    r.become_leader()
    commit_noop_entry(r, s)
    li = r.log.last_index()
    r.handle(
        Message(
            from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(cmd=b"some data")],
        )
    )
    for m in read_messages(r):
        if m.to in acceptors:
            r.handle(accept_and_reply(m))
    assert (r.log.committed > li) == wack


@pytest.mark.parametrize(
    "prev",
    [
        [],
        [(2, 1)],
        [(1, 1), (2, 2)],
        [(1, 1)],
    ],
)
def test_leader_commit_preceding_entries(prev):
    """§5.3: committing an entry commits all preceding entries, including
    ones from previous terms."""
    s = InMemLogDB()
    s.append([Entry(term=t, index=i) for t, i in prev])
    r = new_test_raft(1, [1, 2, 3], logdb=s)
    r.load_state(State(term=2))
    r.become_candidate()
    r.become_leader()
    r.handle(
        Message(
            from_=1, to=1, type=MT.PROPOSE,
            entries=[Entry(cmd=b"some data")],
        )
    )
    for m in read_messages(r):
        r.handle(accept_and_reply(m))
    li = len(prev)
    want = prev + [(3, li + 1), (3, li + 2)]
    assert ent_sig(r.log.entries_to_apply()) == want


@pytest.mark.parametrize(
    "ents, commit",
    [
        ([(1, 1)], 1),
        ([(1, 1), (1, 2)], 2),
        ([(1, 1), (1, 2)], 1),
    ],
)
def test_follower_commit_entry(ents, commit):
    """§5.3: a follower applies entries once it learns they are
    committed."""
    r = new_test_raft(1, [1, 2, 3])
    r.become_follower(1, 2)
    r.handle(
        Message(
            from_=2, to=1, type=MT.REPLICATE, term=1,
            entries=[Entry(term=t, index=i, cmd=b"d%d" % i) for t, i in ents],
            commit=commit,
        )
    )
    assert r.log.committed == commit
    assert ent_sig(r.log.entries_to_apply()) == ents[:commit]


@pytest.mark.parametrize(
    "logterm, index, windex, wreject, whint",
    [
        # match with committed entries
        (0, 0, 1, False, 0),
        (1, 1, 1, False, 0),
        # match with uncommitted entries
        (2, 2, 2, False, 0),
        # mismatch with an existing entry
        (1, 2, 2, True, 2),
        # nonexistent entry
        (3, 3, 3, True, 2),
    ],
)
def test_follower_check_replicate(logterm, index, windex, wreject, whint):
    """§5.3: the follower rejects Replicate whose (prev index, prev term)
    doesn't match its log."""
    ents = [Entry(term=1, index=1), Entry(term=2, index=2)]
    s = InMemLogDB()
    s.append(ents)
    r = new_test_raft(1, [1, 2, 3], logdb=s)
    r.load_state(State(commit=1))
    r.become_follower(2, 2)
    r.handle(
        Message(
            from_=2, to=1, type=MT.REPLICATE, term=2,
            log_term=logterm, log_index=index,
        )
    )
    msgs = read_messages(r)
    assert [
        (m.from_, m.to, m.type, m.term, m.log_index, m.reject, m.hint)
        for m in msgs
    ] == [(1, 2, MT.REPLICATE_RESP, 2, windex, wreject, whint)]


@pytest.mark.parametrize(
    "index, term, ents, wents, wunstable",
    [
        (2, 2, [(3, 3)], [(1, 1), (2, 2), (3, 3)], [(3, 3)]),
        (1, 1, [(3, 2), (4, 3)], [(1, 1), (3, 2), (4, 3)], [(3, 2), (4, 3)]),
        (0, 0, [(1, 1)], [(1, 1), (2, 2)], []),
        (0, 0, [(3, 1)], [(3, 1)], [(3, 1)]),
    ],
)
def test_follower_append_entries(index, term, ents, wents, wunstable):
    """§5.3: on a valid Replicate the follower deletes conflicting
    entries and appends the new ones."""
    s = InMemLogDB()
    s.append([Entry(term=1, index=1), Entry(term=2, index=2)])
    r = new_test_raft(1, [1, 2, 3], logdb=s)
    r.become_follower(2, 2)
    r.handle(
        Message(
            from_=2, to=1, type=MT.REPLICATE, term=2,
            log_term=term, log_index=index,
            entries=[Entry(term=t, index=i) for t, i in ents],
        )
    )
    assert ent_sig(get_all_entries(r.log)) == wents
    assert ent_sig(r.log.entries_to_save()) == wunstable


# the six follower log shapes of raft paper figure 7 (a)-(f)
_FIGURE7_LEADER = (
    [(1, 1), (1, 2), (1, 3), (4, 4), (4, 5), (5, 6), (5, 7), (6, 8),
     (6, 9), (6, 10)]
)
_FIGURE7_FOLLOWERS = [
    [(1, 1), (1, 2), (1, 3), (4, 4), (4, 5), (5, 6), (5, 7), (6, 8), (6, 9)],
    [(1, 1), (1, 2), (1, 3), (4, 4)],
    [(1, 1), (1, 2), (1, 3), (4, 4), (4, 5), (5, 6), (5, 7), (6, 8),
     (6, 9), (6, 10), (6, 11)],
    [(1, 1), (1, 2), (1, 3), (4, 4), (4, 5), (5, 6), (5, 7), (6, 8),
     (6, 9), (6, 10), (7, 11), (7, 12)],
    [(1, 1), (1, 2), (1, 3), (4, 4), (4, 5), (4, 6), (4, 7)],
    [(1, 1), (1, 2), (1, 3), (2, 4), (2, 5), (2, 6), (3, 7), (3, 8),
     (3, 9), (3, 10), (3, 11)],
]


@pytest.mark.parametrize("fidx", range(len(_FIGURE7_FOLLOWERS)))
def test_leader_sync_follower_log(fidx):
    """§5.3 figure 7: the leader reconciles every divergent follower log
    shape back to its own."""
    term = 8
    lead_s = InMemLogDB()
    lead_s.append([Entry(term=t, index=i) for t, i in _FIGURE7_LEADER])
    lead = new_test_raft(1, [1, 2, 3], logdb=lead_s)
    lead.load_state(State(commit=lead.log.last_index(), term=term))
    fol_s = InMemLogDB()
    fol_s.append(
        [Entry(term=t, index=i) for t, i in _FIGURE7_FOLLOWERS[fidx]]
    )
    follower = new_test_raft(2, [1, 2, 3], logdb=fol_s)
    follower.load_state(State(term=term - 1))
    # three-node cluster: the leader needs node 3's vote since the
    # follower's log may be more up-to-date
    nt = Network(lead, follower, BlackHole())
    nt.send(Message(from_=1, to=1, type=MT.ELECTION))
    nt.send(
        Message(
            from_=3, to=1, term=term + 1, type=MT.REQUEST_VOTE_RESP,
        )
    )
    nt.send(
        Message(from_=1, to=1, type=MT.PROPOSE, entries=[Entry()])
    )
    assert logs_equal(lead.log, follower.log)


# ---------------------------------------------------------------------------
# §5.4 safety
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "ents, wterm",
    [
        ([(1, 1)], 2),
        ([(1, 1), (2, 2)], 3),
    ],
)
def test_vote_request(ents, wterm):
    """§5.4.1: RequestVote carries the candidate's last log (term, index)
    and goes to every other node."""
    r = new_test_raft(1, [1, 2, 3])
    r.handle(
        Message(
            from_=2, to=1, type=MT.REPLICATE, term=wterm - 1,
            log_term=0, log_index=0,
            entries=[Entry(term=t, index=i) for t, i in ents],
        )
    )
    read_messages(r)
    for _ in range(1, r.election_timeout * 2):
        r.non_leader_tick()
    msgs = sorted(
        [m for m in read_messages(r) if m.type == MT.REQUEST_VOTE],
        key=lambda m: m.to,
    )
    assert len(msgs) == 2
    wlogterm, windex = ents[-1]
    for i, m in enumerate(msgs):
        assert m.to == i + 2
        assert m.term == wterm
        assert m.log_index == windex
        assert m.log_term == wlogterm


@pytest.mark.parametrize(
    "ents, logterm, index, wreject",
    [
        # same logterm
        ([(1, 1)], 1, 1, False),
        ([(1, 1)], 1, 2, False),
        ([(1, 1), (1, 2)], 1, 1, True),
        # candidate higher logterm
        ([(1, 1)], 2, 1, False),
        ([(1, 1)], 2, 2, False),
        ([(1, 1), (1, 2)], 2, 1, False),
        # voter higher logterm
        ([(2, 1)], 1, 1, True),
        ([(2, 1)], 1, 2, True),
        ([(2, 1), (1, 2)], 1, 1, True),
    ],
)
def test_voter(ents, logterm, index, wreject):
    """§5.4.1: deny the vote if the voter's own log is more up-to-date."""
    s = InMemLogDB()
    s.append([Entry(term=t, index=i) for t, i in ents])
    r = new_test_raft(1, [1, 2], logdb=s)
    r.handle(
        Message(
            from_=2, to=1, type=MT.REQUEST_VOTE, term=3,
            log_term=logterm, log_index=index,
        )
    )
    msgs = read_messages(r)
    assert len(msgs) == 1
    assert msgs[0].type == MT.REQUEST_VOTE_RESP
    assert msgs[0].reject == wreject


@pytest.mark.parametrize(
    "index, wcommit",
    [
        # entries from previous terms never commit by counting
        (1, 0),
        (2, 0),
        # current-term entry commits (and everything before it)
        (3, 3),
    ],
)
def test_leader_only_commits_log_from_current_term(index, wcommit):
    """§5.4.2: only current-term entries commit by counting replicas."""
    s = InMemLogDB()
    s.append([Entry(term=1, index=1), Entry(term=2, index=2)])
    r = new_test_raft(1, [1, 2], logdb=s)
    r.load_state(State(term=2))
    r.become_candidate()  # term 3
    r.become_leader()
    read_messages(r)
    r.handle(Message(from_=1, to=1, type=MT.PROPOSE, entries=[Entry()]))
    r.handle(
        Message(
            from_=2, to=1, term=r.term,
            type=MT.REPLICATE_RESP, log_index=index,
        )
    )
    assert r.log.committed == wcommit
