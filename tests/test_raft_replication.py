"""Log replication / commit conformance tests (reference etcd suite §5.3/5.4)."""
from raft_harness import (
    BlackHole,
    Network,
    RaftState,
    campaign,
    new_test_raft,
    propose,
    readindex,
)
from dragonboat_tpu.wire import Entry, Message, MessageType

MT = MessageType


def committed_entries(nt: Network, nid: int):
    r = nt.raft(nid)
    return r.log.get_entries(1, r.log.committed + 1, 1 << 30)


def test_proposal_commits_on_all_nodes():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.send(propose(1, b"hello"))
    for nid in (1, 2, 3):
        r = nt.raft(nid)
        # noop (index 1) + proposal (index 2)
        assert r.log.committed == 2
        ents = committed_entries(nt, nid)
        assert ents[-1].cmd == b"hello"


def test_proposal_by_follower_is_forwarded():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.send(propose(2, b"via-follower"))
    assert nt.raft(1).log.committed == 2
    assert committed_entries(nt, 1)[-1].cmd == b"via-follower"


def test_proposal_dropped_without_leader():
    nt = Network(None, None, None)
    # no leader elected; proposal via node 1 is dropped
    nt.send(propose(1, b"nope"))
    r = nt.raft(1)
    assert r.log.committed == 0


def test_commit_requires_quorum():
    nt = Network(None, BlackHole(), BlackHole(), None, None)
    nt.send(campaign(nt.raft(1)))
    assert nt.raft(1).state == RaftState.LEADER
    nt.send(propose(1))
    # quorum 3 of {1,4,5} reachable -> commit advances
    assert nt.raft(1).log.committed == 2
    # now cut 4 and 5 too
    nt.isolate(4)
    nt.isolate(5)
    nt.send(propose(1))
    assert nt.raft(1).log.committed == 2  # cannot commit w/o quorum


def test_old_term_entries_not_committed_by_counting():
    # raft paper p8 fig 8: leader only commits entries from its own term by
    # counting replicas
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.send(propose(1, b"t1"))
    committed_before = nt.raft(1).log.committed
    # partition, 2 becomes leader at term 2
    nt.isolate(1)
    nt.send(campaign(nt.raft(2)))
    assert nt.raft(2).state == RaftState.LEADER
    # its noop at term 2 commits (quorum 2,3), which also commits older entries
    assert nt.raft(2).log.committed > committed_before


def test_follower_log_repair_after_divergence():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    # 3 is partitioned; leader appends entries
    nt.isolate(3)
    nt.send(propose(1, b"a"))
    nt.send(propose(1, b"b"))
    assert nt.raft(3).log.last_index() == 1  # only the noop
    nt.recover()
    # heartbeat response triggers replication catch-up
    nt.send(Message(from_=1, to=1, type=MT.LEADER_HEARTBEAT))
    assert nt.raft(3).log.last_index() == nt.raft(1).log.last_index()
    assert nt.raft(3).log.committed == nt.raft(1).log.committed


def test_divergent_follower_entries_overwritten():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    # 1 gets a proposal it can't commit (everyone partitioned)
    nt.isolate(1)
    nt.send(propose(1, b"uncommitted"))
    nt.send(propose(1, b"uncommitted2"))
    # 2 wins a new term and commits different entries
    nt.send(campaign(nt.raft(2)))
    nt.send(propose(2, b"committed"))
    # heal: 1 rejoins and must adopt 2's log
    nt.recover()
    nt.send(Message(from_=2, to=2, type=MT.LEADER_HEARTBEAT))
    r1 = nt.raft(1)
    assert r1.state == RaftState.FOLLOWER
    ents = committed_entries(nt, 1)
    cmds = [e.cmd for e in ents if e.cmd]
    assert b"uncommitted" not in cmds
    assert b"committed" in cmds
    assert r1.log.committed == nt.raft(2).log.committed


def test_leader_sync_sends_empty_replicate_on_heartbeat_resp():
    r = new_test_raft(1, [1, 2])
    r.become_candidate()
    r.become_leader()
    r.msgs = []
    # follower responds to heartbeat while behind
    r.handle(Message(from_=2, to=1, type=MT.HEARTBEAT_RESP, term=r.term))
    assert any(m.type == MT.REPLICATE for m in r.msgs)


def test_duplicate_replicate_resp_ignored():
    r = new_test_raft(1, [1, 2, 3])
    r.become_candidate()
    r.become_leader()
    last = r.log.last_index()
    r.handle(Message(from_=2, to=1, type=MT.REPLICATE_RESP, term=r.term,
                     log_index=last))
    committed = r.log.committed
    # replaying the same ack must not change anything
    r.handle(Message(from_=2, to=1, type=MT.REPLICATE_RESP, term=r.term,
                     log_index=last))
    assert r.log.committed == committed


def test_reject_decrements_next_and_retries():
    r = new_test_raft(1, [1, 2])
    r.become_candidate()
    r.become_leader()
    r.msgs = []
    rp = r.remotes[2]
    rp.become_replicate()
    rp.next = 10
    rp.match = 0
    r.handle(
        Message(from_=2, to=1, type=MT.REPLICATE_RESP, term=r.term,
                reject=True, log_index=9, hint=3)
    )
    assert rp.next == 1  # replicate state resets next to match+1
    assert any(m.type == MT.REPLICATE for m in r.msgs)


def test_single_node_commits_immediately():
    nt = Network(None)
    nt.send(campaign(nt.raft(1)))
    nt.send(propose(1, b"x"))
    assert nt.raft(1).log.committed == 2


def test_read_index_round():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.send(propose(1, b"x"))
    r1 = nt.raft(1)
    r1.ready_to_read = []
    nt.send(readindex(1, 7, 9))
    assert len(r1.ready_to_read) == 1
    rtr = r1.ready_to_read[0]
    assert rtr.index == r1.log.committed
    assert rtr.system_ctx.low == 7 and rtr.system_ctx.high == 9


def test_read_index_forwarded_by_follower():
    nt = Network(None, None, None)
    nt.send(campaign(nt.raft(1)))
    nt.send(propose(1, b"x"))
    r2 = nt.raft(2)
    r2.ready_to_read = []
    nt.send(readindex(2, 3, 4))
    # follower receives ReadIndexResp and surfaces ready-to-read
    assert len(r2.ready_to_read) == 1
    assert r2.ready_to_read[0].index == nt.raft(1).log.committed


def test_witness_replicates_metadata_only():
    from raft_harness import new_test_config
    from dragonboat_tpu.raft import InMemLogDB, Raft
    from dragonboat_tpu.raft.remote import Remote
    from dragonboat_tpu.wire import EntryType

    r = new_test_raft(1, [1, 2])
    r.witnesses[3] = Remote(next=1)
    r.reset_match_value_array()
    r.campaign()  # self-votes; one more vote reaches quorum (2 of 3)
    r.handle(Message(from_=2, to=1, type=MT.REQUEST_VOTE_RESP, term=r.term))
    assert r.state == RaftState.LEADER
    # witness acks the noop so its remote unpauses into Replicate state
    r.handle(Message(from_=3, to=1, type=MT.REPLICATE_RESP, term=r.term,
                     log_index=r.log.last_index()))
    r.msgs = []
    r.handle(Message(from_=1, to=1, type=MT.PROPOSE, entries=[Entry(cmd=b"data")]))
    witness_msgs = [m for m in r.msgs if m.to == 3 and m.type == MT.REPLICATE]
    assert witness_msgs
    for m in witness_msgs:
        for e in m.entries:
            if e.type != EntryType.CONFIG_CHANGE:
                assert e.type == EntryType.METADATA
                assert e.cmd == b""
