"""Differential tests for the device ReadIndex plane (ISSUE 3 tentpole).

The fused read plane (``kernels.read_confirm`` / ``_read_plane``, the
``has_reads`` variants of ``quorum_step_dense`` and ``quorum_multiround``,
and ``BatchedQuorumEngine.stage_read``/``read_ack``) must be
observationally identical to K single-round dispatches — and, through
them, to the scalar ``ReadIndex.confirm`` oracle (``raft/readindex.py``,
reference ``readindex.go:77-116``): same confirmed batches, same release
indices, bit-identical device state.  Includes the ISSUE acceptance
corners — a membership recycle and a leader change with pending read
ctxs mid-block — plus the live coordinator path (reads batched per
round, released through the scalar prefix pop).
"""
from __future__ import annotations

import random
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dragonboat_tpu.ops.engine import BatchedQuorumEngine
from dragonboat_tpu.raft.readindex import ReadIndex
from dragonboat_tpu.wire import SystemCtx


def _state_equal(a, b, tag=""):
    for name, va in a._asdict().items():
        vb = getattr(b, name)
        assert np.array_equal(np.asarray(va), np.asarray(vb)), (tag, name)


def _build(n_groups=8, n_peers=3, cap=256, read_slots=None):
    kw = {} if read_slots is None else {"n_read_slots": read_slots}
    eng = BatchedQuorumEngine(n_groups, n_peers, event_cap=cap, **kw)
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=list(range(1, n_peers + 1)), self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=1)
    eng._upload_dirty()
    return eng


# ----------------------------------------------------------------------
# kernel level: fused scan ≡ K sequential dense read dispatches
# ----------------------------------------------------------------------


def test_read_multiround_kernel_matches_dense_rounds():
    from dragonboat_tpu.ops.kernels import quorum_multiround, quorum_step_dense

    rng = random.Random(611)
    g, p, k = 12, 3, 6
    eng_a, eng_b = _build(g, p), _build(g, p)
    s = eng_a.n_read_slots

    ack = np.full((k, g, p), -1, np.int32)
    stage_idx = np.full((k, g, s), -1, np.int32)
    stage_cnt = np.zeros((k, g, s), np.int32)
    echo = np.zeros((k, g, s, p), bool)
    for r in range(k):
        for _ in range(rng.randrange(0, 12)):
            ack[r, rng.randrange(g), rng.randrange(p)] = rng.choice([1, 2, 5])
        for _ in range(rng.randrange(0, 6)):
            gi, sl = rng.randrange(g), rng.randrange(s)
            stage_idx[r, gi, sl] = rng.randrange(0, 6)
            stage_cnt[r, gi, sl] = rng.randrange(1, 9)
        for _ in range(rng.randrange(0, 10)):
            echo[r, rng.randrange(g), rng.randrange(s), rng.randrange(p)] = True

    z = jnp.zeros((1, 1), jnp.int32)
    out_f = quorum_multiround(
        eng_a.dev,
        jnp.asarray(ack),
        jnp.zeros((1, 1, 1), jnp.int8),
        z, z, z, z,
        jnp.zeros((k,), bool),
        jnp.asarray(stage_idx),
        jnp.asarray(stage_cnt),
        jnp.asarray(echo),
        do_tick=False,
        track_contact=True,
        has_votes=False,
        has_churn=False,
        has_reads=True,
    )

    st = eng_b.dev
    cnt_acc = np.zeros((g, s), np.int64)
    idx_acc = np.full((g, s), -1, np.int64)
    for r in range(k):
        am = ack[r]
        out = quorum_step_dense(
            st,
            jnp.asarray(np.maximum(am, 0)),
            jnp.asarray(am >= 0),
            jnp.zeros((1, 1), jnp.int8),
            jnp.asarray(stage_idx[r]),
            jnp.asarray(stage_cnt[r]),
            jnp.asarray(echo[r]),
            do_tick=False,
            track_contact=True,
            has_votes=False,
            has_reads=True,
        )
        st = out.state
        cnt_acc += np.asarray(out.read_done_count)
        idx_acc = np.maximum(idx_acc, np.asarray(out.read_done_index))

    _state_equal(out_f.state, st, "read-kernel")
    assert np.array_equal(np.asarray(out_f.read_done_count), cnt_acc)
    assert np.array_equal(np.asarray(out_f.read_done_index), idx_acc)


# ----------------------------------------------------------------------
# engine level: fused ≡ per-round step() ≡ scalar ReadIndex oracle
# ----------------------------------------------------------------------


class _Oracle:
    """Scalar ReadIndex twin of one engine group.  Each engine pending-
    read SLOT confirms independently by its own echo quorum, so its twin
    is one ``ReadIndex`` instance per staged batch (a batch of count N =
    one ctx carrying N reads).  The scalar queue's PREFIX release is a
    batching optimization the coordinator layer reconstitutes
    (``tpuquorum._collect_read_confirms`` + ``read_index.release``);
    the quorum arithmetic and release indices pinned here are the same
    ``confirm`` code path either way."""

    def __init__(self, quorum):
        self.quorum = quorum
        self.next_ctx = 1
        self.released = []  # (index, count)

    def stage(self, index, count):
        ctx = SystemCtx(low=self.next_ctx, high=0)
        self.next_ctx += 1
        ri = ReadIndex()
        ri.add_request(index, ctx, from_=0)
        return (ri, ctx, count)

    def echo(self, batch, peer):
        ri, ctx, count = batch
        for s_ in ri.confirm(ctx, peer, self.quorum):
            self.released.append((s_.index, count))


def _drive(eng, oracles, seed, fused, rounds=6):
    """Random read workload, identical for every backend: per round some
    groups stage a batch at their current committed rel, then random
    follower echoes land for the newest UNCONFIRMED batch (the
    heartbeat-hint protocol).  The driver tracks echo quorums itself —
    deterministically, independent of harvest timing — so fused and
    per-round runs generate the identical event stream."""
    rng = random.Random(seed)
    # driver-side pending: (slot, ctx_count, echoed_peers)
    pending = {cid: [] for cid in oracles}
    released = {cid: [] for cid in oracles}

    def harvest(res):
        if res is None or res.read_cids is None:
            return
        for cid, _slot, idx, count in res.reads:
            released[cid].append((idx, count))

    for _ in range(rounds):
        for cid, orc in oracles.items():
            if rng.random() < 0.7 and eng.read_slots_free(cid) > 0:
                count = rng.randrange(1, 5)
                idx = eng.committed_index(cid)
                slot = eng.stage_read(cid, count=count, index=idx)
                pending[cid].append((slot, orc.stage(idx, count), set()))
            if pending[cid] and rng.random() < 0.8:
                slot, cc, echoed = pending[cid][-1]
                for peer in (2, 3):
                    if rng.random() < 0.7:
                        eng.read_ack(cid, peer, slot)
                        orc.echo(cc, peer)
                        echoed.add(peer)
                if len(echoed) + 1 >= 2:  # quorum reached: batch done
                    pending[cid].pop()
        if fused:
            eng.begin_round()
        else:
            harvest(eng.step(do_tick=False))
    if fused:
        harvest(eng.step_rounds(do_tick=False))
    else:
        harvest(eng.step(do_tick=False))
    return released


def test_read_engine_matches_scalar_oracle_and_per_round():
    # 8 slots so no slot is reused within the fused block: a same-slot
    # re-confirm merges (count-sum / index-max) in the block accumulators
    # by design — distinct slots keep the comparison per-batch exact
    # (the merge itself is pinned by the kernel-level test above)
    seed = 77
    n = 6
    eng_f, eng_s = _build(n, read_slots=8), _build(n, read_slots=8)
    orc_f = {cid: _Oracle(2) for cid in range(1, n + 1)}
    orc_s = {cid: _Oracle(2) for cid in range(1, n + 1)}
    rel_f = _drive(eng_f, orc_f, seed, fused=True)
    rel_s = _drive(eng_s, orc_s, seed, fused=False)
    _state_equal(eng_f.dev, eng_s.dev, "engine-read")
    for cid in range(1, n + 1):
        # scalar oracle releases == engine releases, for BOTH backends:
        # same batches, same (bit-identical) confirmation indices.
        # Sorted: a fused block egresses confirmed slots in slot order,
        # the oracle records them in echo order — same multiset.
        assert sorted(rel_f[cid]) == sorted(orc_f[cid].released), cid
        assert sorted(rel_s[cid]) == sorted(orc_s[cid].released), cid
        assert sorted(rel_f[cid]) == sorted(rel_s[cid]), cid
    # the workload actually confirmed something
    assert sum(len(v) for v in rel_f.values()) > 0


def test_read_single_round_dense_matches_fused_single():
    """step() (single-round dense kernel) ≡ step_rounds with one round —
    the two read-capable dispatch shapes."""
    a, b = _build(4), _build(4)
    for eng in (a, b):
        eng.ack(1, 2, 4)
        sl = eng.stage_read(1, count=5)
        eng.read_ack(1, 2, sl)
        eng.read_ack(1, 3, sl)
    ra = a.step(do_tick=False)
    b.begin_round()
    rb = b.step_rounds(do_tick=False)
    _state_equal(a.dev, b.dev, "single-vs-fused")
    assert ra.reads == rb.reads
    assert ra.reads[0][3] == 5


# ----------------------------------------------------------------------
# ISSUE acceptance corners: recycle / leader change with pending ctxs
# ----------------------------------------------------------------------


def test_read_membership_recycle_mid_block_purges_pending():
    """A recycle mid-block kills the old tenant's pending read ctxs (the
    scalar twin builds a fresh ReadIndex): batches sealed into closed
    pre-recycle rounds are DROPPED — even with quorum echoes staged — a
    confirmation there could only egress misattributed to the new
    tenant, and reads are droppable by contract.  The NEW tenant's reads
    staged in the same block confirm normally."""
    eng = _build(6)
    s_old = eng.stage_read(3, count=7)   # old tenant
    eng.read_ack(3, 2, s_old)            # even a full echo quorum...
    eng.read_ack(3, 3, s_old)
    eng.begin_round()
    eng.stage_recycle(3, 103, term=2, term_start=1, last_index=1)
    s_new = eng.stage_read(103, count=2)
    eng.read_ack(103, 2, s_new)
    eng.begin_round()
    res = eng.step_rounds(do_tick=False)
    # ...yields no release for the dead tenant, and no misattribution
    assert res.reads == [(103, s_new, 0, 2)]
    # device slots of the new tenant's row carry no leftovers
    row = eng.groups[103].row
    assert int(np.asarray(eng.dev.read_count)[row].sum()) == 0
    assert eng.read_slots_free(103) == eng.n_read_slots


def test_read_pending_from_earlier_dispatch_dies_with_recycle():
    """A batch staged and DISPATCHED (unconfirmed) in block i must not
    confirm after a block i+1 recycle: the in-program row reset clears
    the carried read slots."""
    eng = _build(6)
    s_old = eng.stage_read(4, count=3)
    eng.step(do_tick=False)              # dispatched, still pending
    assert int(np.asarray(eng.dev.read_count)[eng.groups[4].row].sum()) == 3
    eng.stage_recycle(4, 104, term=2, term_start=1, last_index=1)
    s_new = eng.stage_read(104, count=1)
    eng.read_ack(104, 2, s_new)
    eng.begin_round()
    res = eng.step_rounds(do_tick=False)
    assert res.reads == [(104, s_new, 0, 1)]
    row = eng.groups[104].row
    assert int(np.asarray(eng.dev.read_count)[row].sum()) == 0
    del s_old


def test_read_leader_change_with_pending_ctxs():
    """Leader changes with pending read ctxs: the reads die with the
    leadership, exactly like the scalar path's fresh ReadIndex — even
    when the echoes that would have confirmed them are already staged
    (same open round: the epoch purge drops them), and even when the
    batch already DISPATCHED and sits pending on the device (the
    transition's row upload clears the slots)."""
    # (a) stage + quorum echoes in the OPEN round, then the transition:
    # every staged event dies with the epoch bump (single-round-path
    # semantics; mid-block host transitions are out of contract and must
    # split the block — engine.step_rounds docstring)
    eng = _build(6)
    orc = ReadIndex()
    ctx = SystemCtx(low=9, high=0)
    orc.add_request(5, ctx, 0)
    sl = eng.stage_read(2, count=3, index=5)
    eng.read_ack(2, 2, sl)
    eng.read_ack(2, 3, sl)     # quorum echoes staged...
    eng.set_follower(2, term=3)
    orc2 = ReadIndex()         # scalar twin: become_follower resets
    eng.begin_round()
    res = eng.step_rounds(do_tick=False)
    assert res.reads == []
    assert orc2.confirm(ctx, 2, 2) == []   # oracle agrees: nothing pending
    row = eng.groups[2].row
    assert int(np.asarray(eng.dev.read_count)[row].sum()) == 0
    # a fresh leader term serves new reads again
    eng.set_leader(2, term=4, term_start=6, last_index=6)
    sl = eng.stage_read(2, count=1, index=6)
    eng.read_ack(2, 2, sl)
    res = eng.step(do_tick=False)
    assert res.reads == [(2, sl, 6, 1)]

    # (b) batch dispatched and pending on device, THEN the leader falls:
    # the transition clears the device slots; later echoes confirm nothing
    sl = eng.stage_read(3, count=4)
    eng.step(do_tick=False)    # pending on device now
    assert int(np.asarray(eng.dev.read_count)[eng.groups[3].row].sum()) == 4
    eng.set_follower(3, term=5)
    eng.read_ack(3, 2, sl)     # stale echo after the fall
    eng.read_ack(3, 3, sl)
    res = eng.step(do_tick=False)
    assert res.reads == []
    assert int(np.asarray(eng.dev.read_count)[eng.groups[3].row].sum()) == 0


def test_read_slot_backpressure_and_cancel():
    eng = _build(4)
    slots = [eng.stage_read(1) for _ in range(eng.n_read_slots)]
    with pytest.raises(RuntimeError):
        eng.stage_read(1)
    assert eng.read_slots_free(1) == 0
    # cancelling one frees it for the NEXT round (not the current one)
    eng.cancel_read(1, slots[0])
    with pytest.raises(RuntimeError):
        eng.stage_read(1)
    eng.begin_round()
    s2 = eng.stage_read(1)
    assert s2 == slots[0]
    res = eng.step(do_tick=False)
    assert res.reads == []  # nothing echoed, nothing confirmed
    # unconfirmed batches survive the dispatch and confirm LATER
    eng.read_ack(1, 2, slots[1])
    res = eng.step(do_tick=False)
    assert [(c, s, n) for c, s, _i, n in res.reads] == [(1, slots[1], 1)]


def test_read_pipelined_step_rounds_equivalent():
    """Read egress through pipelined double-buffering == synchronous,
    one block late."""
    a, b = _build(4), _build(4)
    got_a, got_b = [], []
    for blk in range(3):
        for eng, got in ((a, got_a), (b, got_b)):
            sl = eng.stage_read(1, count=blk + 1)
            eng.read_ack(1, 2, sl)
            eng.begin_round()
        got_a.append(a.step_rounds(do_tick=False).reads)
        rb = b.step_rounds(do_tick=False, pipelined=True)
        if rb is not None:
            got_b.append(rb.reads)
    final = b.harvest()
    got_b.append(final.reads)
    _state_equal(a.dev, b.dev, "read-pipelined")
    assert got_a == got_b


def test_read_rebase_shifts_pending_watermark():
    """rebase with a batch PENDING ON DEVICE: the slot's rel watermark
    shifts with the base (clamped at the new floor — the release index
    may only move UP, which ReadIndex permits) so the eventual absolute
    release index is preserved.  Like staged acks, events still in the
    staging buffers at rebase time are the caller's contract to avoid —
    the rare-path callers purge or drain first."""
    eng = _build(4)
    eng.ack(1, 1, 9)
    eng.ack(1, 2, 9)
    eng.step(do_tick=False)
    assert eng.committed_index(1) == 9
    sl = eng.stage_read(1, count=1)  # captured at abs 9 (rel 9)
    eng.step(do_tick=False)          # batch now pending on device
    eng.rebase(1)                    # base -> 9, pending rel 9 -> 0
    eng.read_ack(1, 2, sl)
    res = eng.step(do_tick=False)
    assert res.reads == [(1, sl, 9, 1)]  # abs index preserved


# ----------------------------------------------------------------------
# live coordinator: reads batched per round, device-confirmed
# ----------------------------------------------------------------------


def test_read_only_round_dispatches_without_ticks():
    """A staged ReadIndex ctx plus its echoes must trigger a dispatch on
    their own: with ticks off and no queued write/vote events the round
    gate has nothing else to fire on, and a gate that ignores the read
    plane leaves the ctx pending until the client times out."""
    from dragonboat_tpu.raft import InMemLogDB
    from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator
    from tests.raft_harness import new_test_raft

    coord = TpuQuorumCoordinator(capacity=8, n_peers=4, drive_ticks=False)
    try:
        cid = 7
        r = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
        r.cluster_id = cid
        r.become_candidate()
        r.become_leader()
        confirms = []

        class _Node:
            cluster_id = cid

            class peer:
                raft = r

            def offload_read_confirm(self, low, high, term):
                confirms.append((low, high, term))

        n = _Node()
        coord._nodes[cid] = n
        with coord._mu:
            coord._sync_row_locked(n)
        # absorb registration dirt: the next round must be driven by the
        # read plane alone
        coord.flush()
        coord.read_stage(cid, r.log.committed, low=1, high=1, term=r.term)
        coord.read_ack_hint(cid, 2, low=1, high=1)
        coord.flush()
        assert confirms == [(1, 1, r.term)]
        assert coord.read_confirms == 1
    finally:
        coord.stop()


def test_live_coordinator_batches_read_confirmations():
    """3-replica cluster on the tpu engine: linearizable reads flow
    through the device read plane (staged ctxs, per-round fused echo
    quorum, scalar prefix release) and return correct values; the
    coordinator's confirm counter proves the device — not the scalar
    fallback — served them."""
    from dragonboat_tpu import Config, NodeHostConfig, Result
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine
    from dragonboat_tpu.transport import ChanRouter, ChanTransport

    CID = 31

    class KVSM(IStateMachine):
        def __init__(self, cluster_id, node_id):
            self.kv = {}

        def update(self, cmd):
            k, v = cmd.decode().split("=", 1)
            self.kv[k] = v
            return Result(value=len(self.kv))

        def lookup(self, query):
            return self.kv.get(query)

        def save_snapshot(self, w, files, done):
            w.write(repr(sorted(self.kv.items())).encode())

        def recover_from_snapshot(self, r, files, done):
            import ast

            self.kv = dict(ast.literal_eval(r.read(-1).decode()))

    router = ChanRouter()
    addrs = {i: f"rc{i}:1" for i in range(1, 4)}
    nhs = [
        NodeHost(
            NodeHostConfig(
                node_host_dir=":memory:",
                rtt_millisecond=5,
                raft_address=addrs[i],
                raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                    src, rh, ch, router=router
                ),
                expert=ExpertConfig(quorum_engine="tpu", engine_block_groups=64),
            )
        )
        for i in range(1, 4)
    ]
    try:
        for i, nh in enumerate(nhs, start=1):
            nh.start_cluster(
                addrs, False, KVSM,
                Config(
                    cluster_id=CID, node_id=i,
                    election_rtt=10, heartbeat_rtt=1,
                ),
            )
        deadline = time.time() + 15
        while time.time() < deadline:
            if any(nh.get_leader_id(CID)[1] for nh in nhs):
                break
            time.sleep(0.01)
        s = nhs[0].get_noop_session(CID)
        for i in range(8):
            nhs[0].sync_propose(s, f"k{i}=v{i}".encode(), timeout=30.0)
        for i in range(8):
            assert nhs[0].sync_read(CID, f"k{i}", timeout=30.0) == f"v{i}"
        # the device plane (not the scalar fallback) confirmed reads on
        # whichever host leads the group
        confirms = sum(
            nh.quorum_coordinator.read_confirms for nh in nhs
        )
        assert confirms > 0, [
            (nh.quorum_coordinator.read_confirms,
             nh.quorum_coordinator.read_fallbacks)
            for nh in nhs
        ]
        # and the leader's raft is wired into the read plane
        assert any(
            n.peer.raft.device_reads
            for nh in nhs
            for n in [nh._clusters.get(CID)]
            if n is not None and n.peer is not None
        )
    finally:
        for nh in nhs:
            nh.stop()
