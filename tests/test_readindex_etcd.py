"""Port of the reference's ReadIndex protocol-state tests.

Reference: ``/root/reference/internal/raft/readindex_test.go`` — same
names and case tables, against :mod:`dragonboat_tpu.raft.readindex`.
"""
from __future__ import annotations

import pytest

from dragonboat_tpu.raft import InMemLogDB
from dragonboat_tpu.raft.readindex import ReadIndex
from dragonboat_tpu.wire import SystemCtx
from tests.raft_harness import new_test_raft


def ctx_of(v: int) -> SystemCtx:
    return SystemCtx(low=v, high=v + 1)


def test_same_ctx_cannot_be_added_twice():
    r = ReadIndex()
    r.add_request(1, ctx_of(10001), 1)
    assert len(r.pending) == 1
    r.add_request(2, ctx_of(10001), 2)
    assert len(r.pending) == 1


def test_inconsistent_pending_queue():
    r = ReadIndex()
    r.add_request(1, ctx_of(10001), 1)
    r.queue.append(ctx_of(10003))
    with pytest.raises(Exception):
        r.add_request(2, ctx_of(10002), 2)


def test_read_index_request_can_be_added():
    r = ReadIndex()
    r.add_request(1, ctx_of(10001), 1)
    r.add_request(2, ctx_of(10002), 2)
    assert r.has_pending_request()
    assert len(r.queue) == 2 and len(r.pending) == 2
    p = r.pending[ctx_of(10002)]
    assert p.index == 2
    assert p.from_ == 2
    assert p.ctx == ctx_of(10002)
    assert r.peep_ctx() == ctx_of(10002)


def test_read_index_checks_input_index():
    r = ReadIndex()
    r.add_request(3, ctx_of(10001), 1)
    r.add_request(5, ctx_of(10002), 3)
    with pytest.raises(Exception):
        r.add_request(4, ctx_of(10003), 2)


def test_add_confirmation_checks_inconsistent_pending_queue():
    r = ReadIndex()
    ctx, ctx2, ctx3 = ctx_of(10001), ctx_of(10002), ctx_of(10003)
    r.add_request(3, ctx2, 1)
    r.add_request(4, ctx, 3)
    r.add_request(5, ctx3, 2)
    q = list(r.queue)
    r.queue = [ctx_of(10004)] + q
    with pytest.raises(Exception):
        r.confirm(ctx, 1, 3)
        r.confirm(ctx, 3, 3)


def test_read_index_leader_can_be_confirmed():
    r = ReadIndex()
    ctx, ctx2, ctx3 = ctx_of(10001), ctx_of(10002), ctx_of(10003)
    r.add_request(3, ctx2, 1)
    r.add_request(4, ctx, 3)
    r.add_request(5, ctx3, 2)
    assert not r.confirm(ctx, 1, 3)  # quorum not yet reached
    ris = r.confirm(ctx, 3, 3)
    assert len(ris) == 2
    assert ris[1].index == 4 and ris[1].from_ == 3 and ris[1].ctx == ctx
    assert ris[0].index == 4 and ris[0].from_ == 1 and ris[0].ctx == ctx2
    assert len(r.pending) == 1 and len(r.queue) == 1


def test_read_index_is_reset_after_raft_state_change():
    r = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
    r.read_index.add_request(3, ctx_of(10001), 1)
    assert len(r.read_index.queue) == 1 and len(r.read_index.pending) == 1
    r.reset(2)
    assert len(r.read_index.queue) == 0 and len(r.read_index.pending) == 0
