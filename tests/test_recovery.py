"""Closed-loop recovery plane suite (ISSUE 17).

Contracts under test:

- recovery-OFF structural identity: ``auto_recover=False`` constructs
  nothing — no controller, no sampler subscription (the ``_subs`` latch
  stays ``None``), no ``dragonboat_recovery_*`` families;
  ``auto_recover`` without the health plane degrades to a warning;
- the actuation matrix on synthetic detector events over a fake
  NodeHost: ``quorum_at_risk`` evicts the unreachable voter then
  promotes the standing observer (and commits a witness add from the
  standby pool when no observer stands by), ``leader_flap`` transfers
  to a voter outside the flap window's recent leaders, ``commit_stall``
  re-drives the fast-lane eject, ``devsm_rebind`` force-releases the
  binding, ``worker_flap`` is observe-only;
- guardrails: per-group rate limit, per-detector cooldown, flap
  suppression after ``max_reopens`` re-opens (reported + gauged),
  dry-run executes nothing while counting intent, not-leader retries;
- live: a 3-voter + standby-observer group under a netsplit heals
  MTTR-faster with ``auto_recover=on`` (evict + promote closes the
  detector long before the split heals) than off (the detector can
  only close when the partition does) — the A/B the churn soak scores
  at fleet scale; a flapping group's leadership is transferred off the
  flapping pair; one kill -9 produces exactly one hostproc restart
  (double-actuation guard).
"""
from __future__ import annotations

import os
import signal
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.events import MetricsRegistry
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.obs.health import HealthSampler
from dragonboat_tpu.obs.recovery import MATRIX, RecoveryController
from dragonboat_tpu.transport import ChanRouter, ChanTransport
from dragonboat_tpu.wire.types import Membership

from tests.loadwait import wait_until

# heavy multi-NodeHost tests serialize on one xdist worker
pytestmark = pytest.mark.xdist_group("heavy-multiprocess")

RTT_MS = 5
CID = 940


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


# ----------------------------------------------------------------------
# fakes: a recording NodeHost for matrix-level unit tests
# ----------------------------------------------------------------------


class _FakeEngine:
    def set_step_ready(self, cid):
        pass


class _FakeNode:
    def __init__(self, node_id=1, leader=True, fast_lane=False,
                 membership=None):
        self.node_id = node_id
        self._leader = leader
        self.fast_lane = fast_lane
        self._membership = membership or Membership(
            addresses={1: "h1", 2: "h2", 3: "h3"}
        )
        self.ejects = 0
        self.devsm_plane = None

    def is_leader(self):
        return self._leader

    def get_membership(self):
        return self._membership

    def fast_eject(self):
        self.ejects += 1


class _FakeNH:
    quorum_coordinator = None

    def __init__(self, node):
        self.node = node
        self.engine = _FakeEngine()
        self.calls = []

    def get_node(self, cid):
        return self.node

    def sync_request_delete_node(self, cid, nid, timeout=5.0):
        self.calls.append(("delete", cid, nid))

    def sync_request_add_node(self, cid, nid, addr, timeout=5.0):
        self.calls.append(("add_node", cid, nid, addr))

    def sync_request_add_witness(self, cid, nid, addr, timeout=5.0):
        self.calls.append(("add_witness", cid, nid, addr))

    def request_leader_transfer(self, cid, target):
        self.calls.append(("transfer", cid, target))


def _rig(node=None, registry=None, **knobs):
    """A unit sampler + controller pair over a fake NodeHost."""
    kw = dict(rate_limit_s=0.0, cooldown_s=0.0, max_reopens=3,
              reopen_window_s=60.0, workers=1, retry_delay_s=0.05,
              max_attempts=4)
    kw.update(knobs)
    hs = HealthSampler(nh=None, registry=registry or MetricsRegistry())
    nh = _FakeNH(node or _FakeNode())
    rc = RecoveryController(nh, hs, registry=registry, **kw)
    return hs, nh, rc


def _open(hs, detector, detail, key=None):
    hs._set(detector, key or f"group:{detail.get('cluster_id', 7)}",
            True, time.monotonic(), detail)


def _close(hs, detector, detail=None, key=None):
    hs._set(detector, key or f"group:{(detail or {}).get('cluster_id', 7)}",
            False, time.monotonic(), detail or {})


# ----------------------------------------------------------------------
# actuation matrix (synthetic events, fake host)
# ----------------------------------------------------------------------


def test_quorum_at_risk_evicts_dead_then_promotes_observer():
    node = _FakeNode(membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"}, observers={4: "h4"},
    ))
    hs, nh, rc = _rig(node)
    try:
        _open(hs, "quorum_at_risk", {
            "cluster_id": 7, "reachable": 2, "voters": 3, "quorum": 2,
            "unreachable_ids": [3],
        })
        wait_until(lambda: len(nh.calls) >= 2, timeout=5.0,
                   what="quorum actions")
        # order matters: the eviction restores the quorum margin (and
        # closes the detector) BEFORE the promotion re-adds capacity
        assert nh.calls[0] == ("delete", 7, 3)
        assert nh.calls[1] == ("add_node", 7, 4, "h4")
        assert rc.actions[("quorum_at_risk", "evict_dead")] == 1
        assert rc.actions[("quorum_at_risk", "promote_standby")] == 1
    finally:
        rc.stop()


def test_quorum_at_risk_adds_standby_witness_when_no_observer():
    """The BlackWater move: with no standing observer, durability
    capacity is restored by committing an ADD_WITNESS config change
    from the standby pool (witness promotion IS a config change — the
    raft core forbids in-place witness→voter, so the fresh-witness add
    is the legal spelling)."""
    node = _FakeNode(membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"}, witnesses={9: "w9"},
    ))
    hs, nh, rc = _rig(node, standby_witness_addrs=("spare:1",))
    try:
        _open(hs, "quorum_at_risk", {
            "cluster_id": 7, "reachable": 2, "voters": 4, "quorum": 3,
            "unreachable_ids": [3],
        })
        wait_until(lambda: len(nh.calls) >= 2, timeout=5.0,
                   what="witness add")
        assert nh.calls[0] == ("delete", 7, 3)
        kind, cid, wid, addr = nh.calls[1]
        assert kind == "add_witness" and cid == 7 and addr == "spare:1"
        # a fresh id past every known member — never a reused witness id
        assert wid > 9
    finally:
        rc.stop()


def test_leader_flap_transfers_off_the_flapping_hosts():
    node = _FakeNode(node_id=1, membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"}, witnesses={4: "w4"},
    ))
    hs, nh, rc = _rig(node)
    try:
        _open(hs, "leader_flap", {
            "cluster_id": 7, "changes": 4, "leader_id": 1,
            "recent_leaders": [1, 2],
        })
        wait_until(lambda: nh.calls, timeout=5.0, what="transfer")
        # off the flapping pair {1,2}, never to a witness
        assert nh.calls[0] == ("transfer", 7, 3)
        assert rc.actions[("leader_flap", "transfer_leader")] == 1
    finally:
        rc.stop()


def test_leader_flap_no_action_when_leadership_already_escaped():
    """A leader that is NOT itself in the flap window's recent set is
    the remediation's end state — another transfer would re-enter the
    churn (the soak's bounce-back race)."""
    node = _FakeNode(node_id=3, membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"},
    ))
    hs, nh, rc = _rig(node)
    try:
        _open(hs, "leader_flap", {
            "cluster_id": 7, "changes": 4, "leader_id": 3,
            "recent_leaders": [1, 2],
        })
        wait_until(lambda: rc.skips.get("no_target", 0) >= 1, timeout=5.0,
                   what="no_target skip")
        assert not nh.calls
    finally:
        rc.stop()


def test_leader_flap_holds_when_every_voter_flapped():
    """No stable host to move to: a transfer is itself a leader change
    that resets the detector's quiet window, so the controller must hold
    leadership rather than ping-pong inside the flapping set (the churn
    soak's netsplit-election tail)."""
    node = _FakeNode(node_id=1, membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"},
    ))
    hs, nh, rc = _rig(node)
    try:
        _open(hs, "leader_flap", {
            "cluster_id": 7, "changes": 5, "leader_id": 1,
            "recent_leaders": [3, 2, 1],
        })
        wait_until(lambda: rc.skips.get("no_target", 0) >= 1, timeout=5.0,
                   what="no_target skip")
        assert not nh.calls
    finally:
        rc.stop()


def test_commit_stall_redrives_fast_lane_only():
    node = _FakeNode(fast_lane=True)
    hs, nh, rc = _rig(node)
    try:
        _open(hs, "commit_stall", {"cluster_id": 7, "samples": 3})
        wait_until(lambda: node.ejects >= 1, timeout=5.0, what="eject")
        assert rc.actions[("commit_stall", "fastlane_redrive")] == 1
    finally:
        rc.stop()
    # a scalar-lane group has no native lane to re-drive: no action
    node2 = _FakeNode(fast_lane=False)
    hs2, nh2, rc2 = _rig(node2)
    try:
        _open(hs2, "commit_stall", {"cluster_id": 7, "samples": 3})
        wait_until(lambda: rc2.skips.get("no_target", 0) >= 1, timeout=5.0,
                   what="no_target skip")
        assert node2.ejects == 0
    finally:
        rc2.stop()


def test_devsm_rebind_force_releases_binding():
    released = []

    class _FakeCoord:
        class devsm:
            @staticmethod
            def tracks(cid):
                return True

        @staticmethod
        def devsm_force_release(cid):
            released.append(cid)
            return True

    node = _FakeNode()
    hs, nh, rc = _rig(node)
    nh.quorum_coordinator = _FakeCoord()
    try:
        _open(hs, "devsm_rebind", {"cluster_id": 7, "binds": 5})
        wait_until(lambda: released, timeout=5.0, what="release")
        assert released == [7]
        assert rc.actions[("devsm_rebind", "devsm_release")] == 1
    finally:
        rc.stop()


def test_worker_flap_is_observe_only():
    hs, nh, rc = _rig()
    try:
        _open(hs, "worker_flap", {"alive": 1, "workers": 2, "restarts": 1},
              key="host")
        wait_until(lambda: rc.skips.get("observe_only", 0) >= 1,
                   timeout=5.0, what="observe-only skip")
        assert not nh.calls
        assert rc.observed.get("worker_flap") == 1
        rep = rc.report()
        assert rep["observed"]["worker_flap"] == 1
        assert not rep["actions"]
    finally:
        rc.stop()


def test_not_leader_retries_until_leadership_lands():
    node = _FakeNode(leader=False)
    # a long retry runway: the flip below must land inside it even on
    # a loaded box
    hs, nh, rc = _rig(node, retry_delay_s=0.2, max_attempts=100)
    try:
        _open(hs, "leader_flap", {
            "cluster_id": 7, "changes": 4, "leader_id": 2,
            "recent_leaders": [1, 2],
        })
        wait_until(lambda: rc.skips.get("not_leader", 0) >= 1,
                   timeout=5.0, what="not_leader skip")
        assert not nh.calls
        node._leader = True  # leadership landed between retries
        wait_until(lambda: nh.calls, timeout=5.0, what="retried transfer")
        assert nh.calls[0][0] == "transfer"
    finally:
        rc.stop()


# ----------------------------------------------------------------------
# guardrails
# ----------------------------------------------------------------------


def test_rate_limit_per_group_spans_detectors():
    node = _FakeNode(fast_lane=True, membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"}, observers={4: "h4"},
    ))
    hs, nh, rc = _rig(node, rate_limit_s=30.0)
    try:
        _open(hs, "quorum_at_risk", {
            "cluster_id": 7, "reachable": 2, "voters": 3, "quorum": 2,
            "unreachable_ids": [3],
        })
        wait_until(lambda: nh.calls, timeout=5.0, what="first action")
        n0 = len(nh.calls)
        # a different detector on the SAME group inside the rate window
        _open(hs, "commit_stall", {"cluster_id": 7, "samples": 3})
        wait_until(lambda: rc.skips.get("rate_limited", 0) >= 1,
                   timeout=5.0, what="rate-limit skip")
        assert len(nh.calls) == n0 and node.ejects == 0
    finally:
        rc.stop()


def test_cooldown_per_detector_key():
    node = _FakeNode(membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"},
    ))
    hs, nh, rc = _rig(node, cooldown_s=30.0)
    try:
        detail = {"cluster_id": 7, "changes": 4, "leader_id": 1,
                  "recent_leaders": [1, 2]}
        _open(hs, "leader_flap", detail)
        wait_until(lambda: nh.calls, timeout=5.0, what="first transfer")
        _close(hs, "leader_flap", detail)
        _open(hs, "leader_flap", detail)
        wait_until(lambda: rc.skips.get("cooldown", 0) >= 1, timeout=5.0,
                   what="cooldown skip")
        assert len(nh.calls) == 1
    finally:
        rc.stop()


def test_flap_suppression_after_max_reopens():
    """An action whose detector re-opens ``max_reopens`` times inside
    the window gets suppressed — reported, gauged, no further actions
    — and a full quiet window lifts the suppression."""
    reg = MetricsRegistry()
    node = _FakeNode(membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"},
    ))
    hs, nh, rc = _rig(node, registry=reg, max_reopens=2,
                      reopen_window_s=60.0)
    try:
        detail = {"cluster_id": 7, "changes": 4, "leader_id": 1,
                  "recent_leaders": [1, 2]}
        for i in range(2):
            _open(hs, "leader_flap", detail)
            wait_until(lambda i=i: len(nh.calls) == i + 1, timeout=5.0,
                       what=f"transfer {i + 1}")
            _close(hs, "leader_flap", detail)
        # the second re-open hit max_reopens: suppressed from here on
        _open(hs, "leader_flap", detail)
        wait_until(lambda: rc.skips.get("suppressed", 0) >= 1, timeout=5.0,
                   what="suppressed skip")
        assert len(nh.calls) == 2
        rep = rc.report()
        assert {"detector": "leader_flap", "key": "group:7"} in (
            rep["suppressed"]
        )
        assert reg.gauge_value(
            "dragonboat_recovery_suppressed_keys",
            {"detector": "leader_flap"},
        ) == 1
        assert reg.counter_value(
            "dragonboat_recovery_skipped_total", {"reason": "suppressed"}
        ) >= 1
        # a full quiet window after the last strike lifts the damper
        # (backdate the action stamp too: a fresh open inside the
        # reopen window of a real action would legitimately re-strike)
        k = ("leader_flap", "group:7")
        with rc._mu:
            count, last = rc._strikes[k]
            rc._strikes[k] = (count, last - 120.0)
            rc._last_det_action[k] -= 120.0
        _close(hs, "leader_flap", detail)
        _open(hs, "leader_flap", detail)
        wait_until(lambda: len(nh.calls) == 3, timeout=5.0,
                   what="post-quiet transfer")
        assert reg.gauge_value(
            "dragonboat_recovery_suppressed_keys",
            {"detector": "leader_flap"},
        ) == 0
    finally:
        rc.stop()


def test_dry_run_executes_nothing():
    reg = MetricsRegistry()
    node = _FakeNode(fast_lane=True, membership=Membership(
        addresses={1: "h1", 2: "h2", 3: "h3"}, observers={4: "h4"},
    ))
    hs, nh, rc = _rig(node, registry=reg, dry_run=True)
    try:
        _open(hs, "quorum_at_risk", {
            "cluster_id": 7, "reachable": 2, "voters": 3, "quorum": 2,
            "unreachable_ids": [3],
        })
        wait_until(
            lambda: rc.dryruns.get(("quorum_at_risk", "evict_dead"), 0) >= 1,
            timeout=5.0, what="dry-run decision",
        )
        # the full decision ran (both actions intended), nothing executed
        assert rc.dryruns[("quorum_at_risk", "promote_standby")] == 1
        assert not nh.calls and node.ejects == 0
        assert rc.actions[("quorum_at_risk", "evict_dead")] == 0
        assert reg.counter_value(
            "dragonboat_recovery_dryrun_total",
            {"detector": "quorum_at_risk", "action": "evict_dead"},
        ) == 1
        assert reg.counter_value(
            "dragonboat_recovery_actions_total",
            {"detector": "quorum_at_risk", "action": "evict_dead"},
        ) == 0
        rep = rc.report()
        assert rep["dry_run"] and rep["dryruns"]
    finally:
        rc.stop()


# ----------------------------------------------------------------------
# off structural identity + wiring
# ----------------------------------------------------------------------


def _mk_host(addr="rc:1", router=None, health_ms=0, auto=False,
             dry_run=False, knobs=None):
    router = router or ChanRouter()
    return NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            enable_metrics=True,
            health_sample_ms=health_ms,
            auto_recover=auto,
            auto_recover_dry_run=dry_run,
            auto_recover_knobs=knobs or {},
            expert=ExpertConfig(
                quorum_engine="scalar", engine_warm_fused=False,
            ),
        )
    )


def _start(nh, cid=CID, node_id=1, addrs=None, join=False, **cfg_kw):
    nh.start_cluster(
        {} if join else (addrs or {node_id: nh.raft_address()}),
        join, CounterSM,
        Config(cluster_id=cid, node_id=node_id, election_rtt=10,
               heartbeat_rtt=1, **cfg_kw),
    )


def test_recovery_off_structural_identity():
    nh = _mk_host(health_ms=20, auto=False)
    try:
        _start(nh)
        wait_until(lambda: nh.get_leader_id(CID)[1], timeout=10.0,
                   what="leader")
        assert nh.recovery is None
        # no subscriber was registered: the sampler's latch stays None
        assert nh.health._subs is None
        assert not any(
            f.startswith("dragonboat_recovery_")
            for f in nh.metrics_registry.families()
        )
        assert nh.recovery_report() == {
            "enabled": False, "recovery_plane": "off",
        }
    finally:
        nh.stop()


def test_auto_recover_without_health_plane_degrades():
    nh = _mk_host(health_ms=0, auto=True)
    try:
        assert nh.health is None and nh.recovery is None
        assert nh.recovery_report()["enabled"] is False
    finally:
        nh.stop()


def test_auto_recover_wires_controller_and_families():
    nh = _mk_host(
        health_ms=20, auto=True, dry_run=True,
        knobs={"rate_limit_s": 1.0, "max_reopens": 5},
    )
    try:
        _start(nh)
        assert nh.recovery is not None and nh.recovery.dry_run
        assert nh.recovery.rate_limit_s == 1.0
        assert nh.recovery.max_reopens == 5
        assert nh.health._subs is not None
        fams = nh.metrics_registry.families()
        for fam in ("dragonboat_recovery_actions_total",
                    "dragonboat_recovery_skipped_total"):
            assert fam in fams, fam
        rep = nh.recovery_report()
        assert rep["enabled"] and rep["guardrails"]["rate_limit_s"] == 1.0
    finally:
        nh.stop()
    assert nh.recovery._stopped.is_set()


def test_unknown_knob_raises():
    with pytest.raises(TypeError):
        _mk_host(health_ms=20, auto=True, knobs={"not_a_knob": 1})


# ----------------------------------------------------------------------
# live: netsplit MTTR A/B (the churn soak's per-group scenario)
# ----------------------------------------------------------------------


def _mttr_netsplit_arm(auto: bool, hold_s: float) -> float:
    """One arm of the A/B: 3 check-quorum voters + a standby observer,
    host 3 netsplit for ``hold_s``; returns the quorum_at_risk MTTR
    measured on host 1 (the leader)."""
    router = ChanRouter()
    addrs = {i: f"ab{i}:1" for i in (1, 2, 3)}
    knobs = {"rate_limit_s": 0.2, "cooldown_s": 0.5, "retry_delay_s": 0.1,
             "max_attempts": 5, "action_timeout_s": 10.0}
    nhs = {
        i: _mk_host(addr=f"ab{i}:1", router=router, health_ms=25,
                    auto=auto, knobs=knobs)
        for i in (1, 2, 3, 4)
    }
    try:
        for i in (1, 2, 3):
            _start(nhs[i], node_id=i, addrs=addrs, check_quorum=True)

        def _drive_leader1():
            n1 = nhs[1].get_node(CID)
            if n1.is_leader():
                return True
            lid, ok = n1.get_leader_id()
            if ok and lid in (2, 3):
                try:
                    nhs[lid].request_leader_transfer(CID, 1)
                except Exception:
                    pass
            else:
                n1.request_campaign()
            return False

        wait_until(_drive_leader1, timeout=20.0, interval=0.2,
                   what="leader on host 1")
        # standby observer on host 4 (the promotion target)
        nhs[1].sync_request_add_observer(CID, 4, "ab4:1", timeout=10.0)
        _start(nhs[4], node_id=4, join=True, is_observer=True)
        s = nhs[1].get_noop_session(CID)
        assert nhs[1].sync_propose(s, b"x", timeout=30.0)
        health = nhs[1].health
        health.quorum_risk_samples = 2
        wait_until(lambda: len(health) >= 3, timeout=10.0, what="samples")
        # cut host 3 from everyone, hold, then heal
        router.partition("ab3:1", "ab1:1")
        router.partition("ab3:1", "ab2:1")
        wait_until(
            lambda: any(
                e["detector"] == "quorum_at_risk"
                for e in health.open_events()
            ),
            timeout=20.0, what="quorum_at_risk open",
        )
        healed = threading.Timer(hold_s, router.heal)
        healed.daemon = True
        healed.start()
        wait_until(
            lambda: health.recovery_stats().get("quorum_at_risk"),
            timeout=hold_s + 30.0, what="quorum_at_risk close",
        )
        healed.join()
        if auto:
            # the remediation committed: the dead voter is out, the
            # observer serves as a voter now
            m = nhs[1].sync_get_cluster_membership(CID, timeout=10.0)
            assert 3 not in m.addresses and 4 in m.addresses, m
            rep = nhs[1].recovery_report()
            assert rep["actions"].get("quorum_at_risk:evict_dead", 0) >= 1
            assert rep["actions"].get(
                "quorum_at_risk:promote_standby", 0
            ) >= 1
            # writes still land on the remediated quorum
            assert nhs[1].sync_propose(s, b"post", timeout=30.0)
        return health.recovery_stats()["quorum_at_risk"]["max_s"]
    finally:
        for nh in nhs.values():
            nh.stop()


def test_live_netsplit_mttr_on_beats_off():
    """The acceptance A/B at unit scale: with auto_recover the detector
    closes when the evict commits (seconds), without it the close can
    only arrive after the partition heals (the hold time)."""
    hold_s = 6.0
    mttr_off = _mttr_netsplit_arm(False, hold_s)
    mttr_on = _mttr_netsplit_arm(True, hold_s)
    # off cannot close before the heal; on must beat the hold window
    assert mttr_off >= hold_s * 0.8, (mttr_off, mttr_on)
    assert mttr_on < mttr_off, (mttr_off, mttr_on)


def test_live_leader_flap_transferred_off_flapping_pair():
    """Bounce leadership 1<->2 exactly ``leader_flap_changes`` times;
    the flap detector opens, the controller on the current leader
    transfers to host 3 (outside the flap window's recent leaders) and
    leadership settles there.  Host 3 runs recovery OFF so the newly
    elected host cannot re-actuate on its own open event."""
    router = ChanRouter()
    addrs = {i: f"lf{i}:1" for i in (1, 2, 3)}
    knobs = {"rate_limit_s": 0.2, "cooldown_s": 0.5, "retry_delay_s": 0.2,
             "max_attempts": 25}
    nhs = {
        i: _mk_host(addr=f"lf{i}:1", router=router, health_ms=25,
                    auto=(i != 3), knobs=knobs)
        for i in (1, 2, 3)
    }
    try:
        for i in (1, 2, 3):
            _start(nhs[i], node_id=i, addrs=addrs)
        for hs in (nhs[i].health for i in (1, 2, 3)):
            hs.leader_flap_changes = 3
            hs.flap_window_s = 60.0

        def _leader():
            for i in (1, 2, 3):
                lid, ok = nhs[i].get_leader_id(CID)
                if ok and lid in (1, 2, 3):
                    return lid
            return None

        def _drive(target):
            lid = _leader()
            if lid == target:
                return True
            if lid is not None:
                try:
                    nhs[lid].request_leader_transfer(CID, target)
                except Exception:
                    pass
            return False

        wait_until(lambda: _leader() is not None, timeout=20.0,
                   what="leader")
        wait_until(lambda: _drive(1), timeout=20.0, interval=0.3,
                   what="leader on host 1")
        # forget the election churn that got us here: only the
        # deliberate bounces below may count as flap participants
        # (otherwise host 3 can land in recent_leaders and the "away
        # from the flappers" target set goes empty)
        time.sleep(0.3)
        for i in (1, 2, 3):
            for dq in nhs[i].health._leader_changes.values():
                dq.clear()

        def _flap_open():
            return any(
                e["detector"] == "leader_flap"
                for i in (1, 2)
                for e in nhs[i].health.open_events()
            )

        # bounce inside the pair {1,2} until the detector opens, then
        # STOP: a manual transfer still in flight at open time would
        # race the controller's (stale leader views make the exact
        # bounce count nondeterministic); the controllers' not_leader
        # retry runway absorbs any stray landing
        deadline = time.time() + 60.0
        while not _flap_open():
            assert time.time() < deadline, "flap detector never opened"
            lid = _leader()
            if lid not in (1, 2):
                time.sleep(0.1)
                continue
            try:
                nhs[lid].request_leader_transfer(CID, 2 if lid == 1 else 1)
            except Exception:
                pass
            settle = time.time() + 3.0
            while (time.time() < settle and _leader() == lid
                   and not _flap_open()):
                time.sleep(0.05)

        def _acted():
            for i in (1, 2):
                rep = nhs[i].recovery_report()
                if rep["actions"].get("leader_flap:transfer_leader"):
                    return rep
            return None

        rep = wait_until(_acted, timeout=30.0, what="controller transfer")
        # a transfer's election can lose to the old pair under sweep
        # load; the detector stays open (the bounce-phase changes age
        # out only after flap_window_s) so the controller keeps
        # re-transferring every cooldown_s — the wait must cover
        # several election rounds, not one (the r15 re-drive lesson:
        # here the controller is the re-driver, the budget just has to
        # match its runway)
        wait_until(lambda: _leader() == 3, timeout=60.0,
                   what="leadership off the flapping pair")
        act = [r for r in rep["recent"]
               if r["action"] == "transfer_leader"][0]
        assert act["detail"]["target"] == 3
        assert set(act["detail"]["away_from"]) <= {1, 2}
    finally:
        for nh in nhs.values():
            nh.stop()


# ----------------------------------------------------------------------
# worker_flap double-actuation guard (live hostproc)
# ----------------------------------------------------------------------


def test_kill9_worker_single_respawn_with_recovery_on(tmp_path):
    """Satellite: the hostproc monitor owns respawn — with the
    controller subscribed, one kill -9 still produces exactly ONE
    restart-counter bump (observe-and-attribute, never a second
    respawn)."""
    router = ChanRouter()
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / "nh"),
            rtt_millisecond=RTT_MS,
            raft_address="wf:1",
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            enable_metrics=True,
            health_sample_ms=20,
            auto_recover=True,
            expert=ExpertConfig(
                quorum_engine="scalar", engine_warm_fused=False,
                host_workers=1,
            ),
        )
    )
    if nh.hostproc is None:
        nh.stop()
        pytest.skip("hostproc spawn unavailable")
    try:
        _start(nh)
        wait_until(lambda: nh.get_leader_id(CID)[1], timeout=10.0,
                   what="leader")
        wait_until(lambda: len(nh.health) >= 2, timeout=10.0,
                   what="samples")
        base_restarts = nh.hostproc.restarts_total
        pid = nh.hostproc.worker_pid(0)
        assert pid
        os.kill(pid, signal.SIGKILL)
        wait_until(
            lambda: nh.hostproc.restarts_total == base_restarts + 1,
            timeout=30.0, what="monitor respawn",
        )
        # the controller attributed the flap without acting
        wait_until(
            lambda: nh.recovery.observed.get("worker_flap", 0) >= 1,
            timeout=15.0, what="controller attribution",
        )
        # settle: no second bump arrives, no recovery action fired
        time.sleep(1.0)
        assert nh.hostproc.restarts_total == base_restarts + 1
        rep = nh.recovery_report()
        assert not any(
            k.startswith("worker_flap") for k in rep["actions"]
        )
        assert rep["skips"].get("observe_only", 0) >= 1
    finally:
        nh.stop()
