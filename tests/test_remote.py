"""Progress tracker conformance (reference internal/raft/remote_test.go)."""
from dragonboat_tpu.raft import Remote, RemoteState


def test_initial_state():
    r = Remote()
    assert r.state == RemoteState.RETRY
    assert r.match == 0 and r.next == 0


def test_become_retry_from_snapshot_uses_snapshot_index():
    r = Remote(match=5, next=10)
    r.become_snapshot(20)
    assert r.state == RemoteState.SNAPSHOT
    r.become_retry()
    assert r.next == 21
    assert r.state == RemoteState.RETRY
    assert r.snapshot_index == 0


def test_become_retry_from_other_state():
    r = Remote(match=5, next=10)
    r.become_retry()
    assert r.next == 6


def test_retry_wait_transitions():
    r = Remote()
    r.retry_to_wait()
    assert r.state == RemoteState.WAIT
    assert r.is_paused()
    r.wait_to_retry()
    assert r.state == RemoteState.RETRY
    assert not r.is_paused()


def test_become_replicate():
    r = Remote(match=7)
    r.become_replicate()
    assert r.state == RemoteState.REPLICATE
    assert r.next == 8
    assert not r.is_paused()


def test_try_update():
    r = Remote(match=5, next=6)
    assert r.try_update(10)
    assert r.match == 10 and r.next == 11
    # stale update is a no-op
    assert not r.try_update(3)
    assert r.match == 10
    # next never decreases
    assert r.next == 11


def test_try_update_unpauses_wait():
    r = Remote(match=5, next=6)
    r.retry_to_wait()
    assert r.try_update(8)
    assert r.state == RemoteState.RETRY


def test_progress_replicate_advances_next():
    r = Remote(match=5)
    r.become_replicate()
    r.progress(20)
    assert r.next == 21


def test_progress_retry_enters_wait():
    r = Remote()
    r.progress(10)
    assert r.state == RemoteState.WAIT


def test_responded_to_retry_becomes_replicate():
    r = Remote(match=3)
    r.responded_to()
    assert r.state == RemoteState.REPLICATE


def test_responded_to_snapshot_completion():
    r = Remote(match=5)
    r.become_snapshot(10)
    r.responded_to()  # match < snapshot index: stay
    assert r.state == RemoteState.SNAPSHOT
    r.match = 10
    r.responded_to()
    assert r.state == RemoteState.RETRY
    assert r.next == 11


def test_decrease_to_replicate_state():
    r = Remote(match=5, next=10)
    r.become_replicate()
    r.next = 10
    # rejected <= match: stale
    assert not r.decrease_to(4, 100)
    assert r.decrease_to(9, 100)
    assert r.next == r.match + 1


def test_decrease_to_retry_state():
    r = Remote(match=0, next=10)
    # mismatched rejection is stale
    assert not r.decrease_to(5, 100)
    assert r.decrease_to(9, 3)
    assert r.next == 4  # min(rejected, last+1)
    r2 = Remote(match=0, next=10)
    assert r2.decrease_to(9, 100)
    assert r2.next == 9


def test_active_flag():
    r = Remote()
    assert not r.is_active()
    r.set_active()
    assert r.is_active()
    r.set_not_active()
    assert not r.is_active()
