"""Replication-path tracing and commit quorum attribution (ISSUE 14).

Contracts under test:

- trace-OFF structural identity on both wires: with
  ``trace_sample_every=0`` no attribution plane exists anywhere
  (``NodeHost.replattr`` / ``Node.replattr`` / ``Raft.replattr`` all
  None) and ``Message.trace`` stays None; at the codec level a
  trace-less message's encoding is BIT-identical to the pre-trace
  layout — attaching a context changes exactly one flag byte and
  appends the payload, nothing else moves;
- stage completeness leader→follower→leader on the chan AND tcp wires:
  a sampled proposal's closed attribution record decomposes the
  quorum-closing ack into the five replication stages (wire_out /
  follower_append / follower_fsync / ack_send / wire_back) that sum to
  the measured RTT, the follower files the matching leg in ITS tracer,
  the leader trace gains the ``repl_quorum`` stage, and
  ``tools/trace_merge.py`` joins the per-host dumps into one flow;
- quorum-closing-peer correctness vs a scalar oracle (the
  ``kth_largest`` rule ``raft.try_commit`` runs) under an injected slow
  peer, driven deterministically through ``ReplAttr`` with a clamped
  clock;
- attribution under mid-trace leadership transfer: term-pinned records
  never cross terms (acks and commits from a later term drop the
  record instead of attributing), and ``Raft.reset`` clears the
  group's open records;
- satellites: ``dragonboat_transport_*`` counters land in the shared
  registry with ``# HELP`` round-trip, and
  ``LatencyInjector.health_snapshot`` labels peers by latency class.
"""
from __future__ import annotations

import io
import json
import socket
import time

import pytest

from tests import loadwait

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.events import MetricsRegistry
from dragonboat_tpu.monkey import set_latency
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.obs import replattr as replattr_mod
from dragonboat_tpu.obs.replattr import ReplAttr, STAGES
from dragonboat_tpu.transport import ChanRouter, ChanTransport
from dragonboat_tpu.transport.latency import LatencyInjector, crossdomain
from dragonboat_tpu.transport.metrics import TransportMetrics
from dragonboat_tpu.wire import Entry, Message, MessageType, ReplTrace
from dragonboat_tpu.wire.codec import decode_message, encode_message

from tests.loadwait import wait_until

CID = 940
RTT_MS = 5


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_chan_hosts(n=3, trace=1):
    router = ChanRouter()
    nhs = []
    for i in range(1, n + 1):
        nhs.append(
            NodeHost(
                NodeHostConfig(
                    node_host_dir=":memory:",
                    rtt_millisecond=RTT_MS,
                    raft_address=f"rt{i}:1",
                    raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                        s, rh, ch, router=router
                    ),
                    trace_sample_every=trace,
                    expert=ExpertConfig(quorum_engine="scalar"),
                )
            )
        )
    return nhs


def _ports(n):
    return loadwait.ports(n)


def _mk_tcp_hosts(tmp_path, n=3, trace=1):
    ports = _ports(n)
    nhs = []
    for i in range(1, n + 1):
        nhs.append(
            NodeHost(
                NodeHostConfig(
                    node_host_dir=str(tmp_path / f"nh{i}"),
                    rtt_millisecond=RTT_MS,
                    raft_address=f"127.0.0.1:{ports[i - 1]}",
                    trace_sample_every=trace,
                    expert=ExpertConfig(
                        quorum_engine="scalar", logdb_shards=2
                    ),
                )
            )
        )
    return nhs


def _start(nhs, cid=CID):
    addrs = {i: nh.raft_address() for i, nh in enumerate(nhs, start=1)}
    for i, nh in enumerate(nhs, start=1):
        nh.start_cluster(
            addrs, False, CounterSM,
            Config(cluster_id=cid, node_id=i, election_rtt=10,
                   heartbeat_rtt=1),
        )
    wait_until(
        lambda: nhs[0].get_leader_id(cid)[1], timeout=30.0, what="leader"
    )


def _force_leader(nhs, target=1, cid=CID):
    """Deterministic placement: transfer/campaign until nhs[target-1]
    leads (the run_crossdomain placement loop's shape)."""
    node = nhs[target - 1].get_node(cid)
    deadline = time.time() + 60

    def _try():
        if node.is_leader():
            return True
        lid, ok = node.get_leader_id()
        if ok and lid != target and 1 <= lid <= len(nhs):
            try:
                nhs[lid - 1].request_leader_transfer(cid, target)
            except Exception:
                pass
        else:
            node.request_campaign()
        return False

    while time.time() < deadline:
        if _try():
            return
        time.sleep(0.2)
    raise AssertionError(f"node {target} never became leader")


def _stop_all(nhs):
    for nh in nhs:
        try:
            nh.stop()
        except Exception:
            pass


# ----------------------------------------------------------------------
# trace OFF: structural identity (chan and tcp)
# ----------------------------------------------------------------------


def _assert_repl_off(nh, cid=CID):
    assert nh.replattr is None
    node = nh.get_node(cid)
    assert node.replattr is None
    assert node.peer.raft.replattr is None
    if nh.quorum_coordinator is not None:
        assert nh.quorum_coordinator.replattr is None


def test_trace_off_structural_identity_chan():
    nhs = _mk_chan_hosts(trace=0)
    try:
        _start(nhs)
        _force_leader(nhs)
        s = nhs[0].get_noop_session(CID)
        nhs[0].sync_propose(s, b"x", timeout=30.0)
        for nh in nhs:
            _assert_repl_off(nh)
    finally:
        _stop_all(nhs)


def test_trace_off_structural_identity_tcp(tmp_path):
    nhs = _mk_tcp_hosts(tmp_path, trace=0)
    try:
        _start(nhs)
        _force_leader(nhs)
        s = nhs[0].get_noop_session(CID)
        nhs[0].sync_propose(s, b"x", timeout=30.0)
        for nh in nhs:
            _assert_repl_off(nh)
    finally:
        _stop_all(nhs)


def test_codec_trace_none_bit_identity():
    """A trace-less message's bytes are the pre-trace layout: attaching
    a context flips exactly ONE header byte (the flags) and appends the
    payload — nothing in the original encoding moves."""
    m = Message(
        type=MessageType.REPLICATE, to=2, from_=1, cluster_id=CID,
        term=3, log_term=3, log_index=9, commit=8,
        entries=[Entry(term=3, index=10, key=77, cmd=b"payload")],
    )
    b_none = encode_message(m)
    m.trace = ReplTrace(
        tid=41, origin="rt1:1", index=10, t_send=1234.5, t_recv=1234.6,
        t_append=1234.61, t_fsync=1234.62, t_ack=1234.63,
        t_ack_recv=1234.7,
    )
    b_trace = encode_message(m)
    assert len(b_trace) > len(b_none)
    diffs = [
        i for i in range(len(b_none)) if b_none[i] != b_trace[i]
    ]
    assert len(diffs) == 1, (
        f"trace attachment moved bytes besides the flag: {diffs}"
    )
    # round trips on both shapes
    d_trace = decode_message(b_trace)
    assert d_trace.trace is not None
    assert d_trace.trace.tid == 41
    assert d_trace.trace.origin == "rt1:1"
    assert d_trace.trace.index == 10
    assert d_trace.trace.t_ack_recv == 1234.7
    assert decode_message(b_none).trace is None
    # the clone a chan delivery hands the receiver is an isolated copy
    c = m.trace.clone()
    c.t_recv = 9.0
    assert m.trace.t_recv != 9.0


# ----------------------------------------------------------------------
# stage completeness leader -> follower -> leader (chan and tcp)
# ----------------------------------------------------------------------


def _propose_n(nh, n, cid=CID):
    s = nh.get_noop_session(cid)
    for _ in range(n):
        nh.sync_propose(s, b"x", timeout=30.0)


def _assert_complete(nhs, far_peer=None):
    ra = nhs[0].replattr
    assert ra is not None
    recs = wait_until(lambda: ra.records(), timeout=10.0, what="records")
    full = [r for r in recs if r["stages_ms"]]
    assert full, f"no record decomposed stages: {recs[:2]}"
    for rec in full:
        assert rec["closer"] is not None
        assert rec["close_ms"] is not None and rec["close_ms"] >= 0
        assert set(rec["stages_ms"]) == set(STAGES)
        # offset-corrected stages sum to the closer's measured RTT
        closer = str(rec["closer"])
        rtt = rec["peers"][closer]["rtt_ms"]
        assert rtt is not None
        assert sum(rec["stages_ms"].values()) == pytest.approx(
            rtt, abs=0.05
        )
        if far_peer is not None:
            assert rec["closer"] != far_peer
            assert far_peer in rec["laggards"]
    # the follower halves got filed in the FOLLOWERS' tracers, with
    # monotone stamps in the follower's own clock
    legs = [leg for nh in nhs[1:] for leg in nh.tracer.repl_legs()]
    assert legs, "no follower filed a replication leg"
    for leg in legs:
        assert leg["origin"] == nhs[0].raft_address()
        assert 0 < leg["t_recv"] <= leg["t_append"]
        assert leg["t_append"] <= leg["t_fsync"] <= leg["t_ack"]
    # the sampled leader traces carry the repl_quorum stage + summary
    done = [t for t in nhs[0].tracer.traces() if t.done and t.repl]
    assert done, "no completed leader trace carries a repl summary"
    assert any(
        any(e[0] == "repl_quorum" for e in t.events) for t in done
    )
    return recs


def test_stage_completeness_chan_slow_peer():
    nhs = _mk_chan_hosts(trace=1)
    try:
        _start(nhs)
        _force_leader(nhs)
        # peer 2 sits one 15ms far link away; leader + peer 3 are near
        set_latency(
            nhs,
            crossdomain(["rt1:1", "rt3:1"], ["rt2:1"], 0.015),
        )
        _propose_n(nhs[0], 8)
        time.sleep(0.3)
        recs = _assert_complete(nhs, far_peer=2)
        # the slow peer's late acks still priced its RTT.  Pipelined
        # sends coalesce onto one far round trip (the ack covering a
        # batch closes every record in it), so only the FIRST record of
        # a burst pays the full 30ms — p99 sees it, p50 still sees at
        # least the one-way leg.  Lower bounds NOT load-scaled.
        wait_until(
            lambda: (nhs[0].replattr.summary()["peers"].get("2") or {})
            .get("rtt_p50_ms"),
            timeout=10.0, what="far-peer rtt",
        )
        summary = nhs[0].replattr.summary()
        assert summary["peers"]["2"]["rtt_p99_ms"] >= 30.0
        assert summary["peers"]["2"]["rtt_p50_ms"] >= 15.0
        assert summary["peers"]["2"]["laggard"] >= len(recs) - 1
        assert summary["peers"]["2"]["cls"] == "B"
        assert summary["peers"]["3"]["closer"] >= 1
        # quorum-closing-peer vs the scalar oracle on the live records:
        # reconstruct each peer's ack time (t_send + rtt) and check the
        # kth-smallest (leader self-acks at fan-out) names the closer
        for rec in recs:
            acks = {
                int(p): d["t_send"] + d["rtt_ms"] / 1e3
                for p, d in rec["peers"].items()
                if d["acked"] and d["t_send"] and d["rtt_ms"] is not None
            }
            t0 = min(d["t_send"] for d in rec["peers"].values()
                     if d["t_send"])
            oracle = _oracle_closer(t0, acks, rec["quorum"])
            if oracle and rec["closer"] in acks:
                assert rec["closer"] == oracle
    finally:
        _stop_all(nhs)


def test_stage_completeness_and_merge_tcp(tmp_path):
    nhs = _mk_tcp_hosts(tmp_path, trace=1)
    try:
        _start(nhs)
        _force_leader(nhs)
        _propose_n(nhs[0], 6)
        time.sleep(0.3)
        wait_until(
            lambda: [
                r for r in nhs[0].replattr.records() if r["stages_ms"]
            ],
            timeout=10.0, what="tcp records",
        )
        _assert_complete(nhs)
        # multi-host merge: the per-host dumps join into one timeline
        # with every host on the leader's clock and the leader's flow
        # ids preserved across processes
        import os
        import sys
        tools_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        )
        sys.path.insert(0, tools_dir)
        try:
            from trace_merge import merge_dumps
        finally:
            sys.path.remove(tools_dir)
        dumps = [nh.dump_trace() for nh in nhs]
        merged = merge_dumps(dumps)
        md = merged["metadata"]
        assert md["reference_host"] == nhs[0].raft_address()
        assert set(md["merged_hosts"]) == {
            nh.raft_address() for nh in nhs
        }
        # every follower that filed a leg got a clock shift estimate
        legged = {
            nh.raft_address() for nh in nhs[1:] if nh.tracer.repl_legs()
        }
        assert legged - set(md["unsynced_hosts"]) == legged
        pids = {
            ev["pid"] for ev in merged["traceEvents"]
            if ev.get("cat") == "repl"
        }
        assert pids, "merged file lost the follower replication slices"
        # a leader flow id appears in >1 process: the cross-host join
        by_id = {}
        for ev in merged["traceEvents"]:
            if "id" in ev:
                by_id.setdefault(ev["id"], set()).add(ev["pid"])
        assert any(len(p) > 1 for p in by_id.values()), (
            "no flow spans leader and follower processes"
        )
    finally:
        _stop_all(nhs)


# ----------------------------------------------------------------------
# quorum-closing peer vs the scalar oracle (deterministic clock)
# ----------------------------------------------------------------------


class _FakeTrace:
    def __init__(self, tid):
        self.tid = tid
        self.done = False
        self.repl = None
        self.events = []

    def add(self, stage):
        self.events.append(stage)


class _FakeTracer:
    def __init__(self, by_key):
        self._by_key = by_key


def _oracle_closer(self_t0, acks, quorum):
    """The scalar oracle: ``try_commit`` advances when the quorum-th
    voter's match covers the index — sorted ack times ascending, the
    quorum-th smallest is the closing ack (leader counts at t0)."""
    times = sorted([(self_t0, 0)] + [(t, p) for p, t in acks.items()])
    return times[quorum - 1][1] if len(times) >= quorum else None


@pytest.fixture
def clock(monkeypatch):
    state = {"t": 1000.0}

    def now():
        return state["t"]

    monkeypatch.setattr(replattr_mod.time, "time", now)

    def advance(dt):
        state["t"] += dt
        return state["t"]

    return advance


def _open_record(ra, tr, peers=(2, 3), index=10, term=5, cid=CID):
    msgs = [
        Message(
            type=MessageType.REPLICATE, to=p, from_=1, cluster_id=cid,
            term=term, entries=[Entry(term=term, index=index, key=tr.tid)],
        )
        for p in peers
    ]
    ra.attach_sends(cid, msgs, _FakeTracer({tr.tid: tr}))
    assert all(m.trace is not None for m in msgs)
    return msgs


def test_quorum_closer_matches_oracle(clock):
    ra = ReplAttr(host="rt1:1", registry=MetricsRegistry())
    tr = _FakeTrace(tid=7)
    t0 = 1000.0
    _open_record(ra, tr, peers=(2, 3), index=10, term=5)
    # peer 3 acks first (fast), peer 2 is the injected slow peer
    t3 = clock(0.002)
    ra.on_ack(CID, 3, 10, 5)
    ra.on_commit(CID, 10, 5, {1: None, 2: None, 3: None}, 2, 1)
    rec = ra.records()[-1]
    assert rec["closer"] == 3
    assert rec["closer"] == _oracle_closer(t0, {3: t3}, 2)
    assert rec["laggards"] == [2]
    assert rec["close_ms"] == pytest.approx(2.0, abs=1e-6)
    assert tr.repl is rec
    assert "repl_quorum" in tr.events
    # the slow peer's ack lands AFTER the close: laggard keeps its
    # measured RTT in the summary (straggler window)
    clock(0.050)
    ra.on_ack(CID, 2, 10, 5)
    assert rec["peers"]["2"]["acked"]
    assert rec["peers"]["2"]["rtt_ms"] == pytest.approx(52.0, abs=1e-3)
    assert rec["peers"]["2"]["after_close_ms"] == pytest.approx(
        50.0, abs=1e-3
    )
    assert ra.commits_attributed == 1
    assert ra.records_dropped == 0


def test_quorum_closer_oracle_five_voters(clock):
    ra = ReplAttr(host="rt1:1", registry=MetricsRegistry())
    tr = _FakeTrace(tid=9)
    t0 = 1000.0
    voters = {1: None, 2: None, 3: None, 4: None, 5: None}
    _open_record(ra, tr, peers=(2, 3, 4, 5), index=20, term=5)
    acks = {}
    for dt, peer in ((0.001, 4), (0.003, 2), (0.009, 5)):
        acks[peer] = clock(dt)
        ra.on_ack(CID, peer, 20, 5)
    # quorum 3 of 5: self@t0, peer4, peer2 — peer 2's ack closes
    ra.on_commit(CID, 20, 5, voters, 3, 1)
    rec = ra.records()[-1]
    oracle = _oracle_closer(t0, acks, 3)
    assert rec["closer"] == 2 == oracle
    assert rec["laggards"] == [3]


def test_stage_decomposition_sums_to_rtt(clock):
    ra = ReplAttr(host="rt1:1", registry=MetricsRegistry())
    ra.resolver = lambda cid, nid: f"peer{nid}:1"
    tr = _FakeTrace(tid=11)
    _open_record(ra, tr, peers=(2,), index=30, term=5)
    # follower clock runs 1h ahead: the ack-pair estimate must still
    # reconcile the stages to the leader-measured RTT
    skew = 3600.0
    t_send = 1000.0
    ctx = ReplTrace(
        tid=11, origin="rt1:1", index=30, t_send=t_send,
        t_recv=t_send + skew + 0.010,   # 10ms wire out (follower clock)
        t_append=t_send + skew + 0.012,
        t_fsync=t_send + skew + 0.015,
        t_ack=t_send + skew + 0.016,
    )
    t_ack_recv = clock(0.026)
    ctx.t_ack_recv = t_ack_recv
    ra.on_ack(CID, 2, 30, 5, ctx)
    ra.on_commit(CID, 30, 5, {1: None, 2: None, 3: None}, 2, 1)
    rec = ra.records()[-1]
    assert rec["closer"] == 2
    st = rec["stages_ms"]
    assert set(st) == set(STAGES)
    assert sum(st.values()) == pytest.approx(26.0, abs=1e-3)
    assert st["follower_append"] == pytest.approx(2.0, abs=1e-3)
    assert st["follower_fsync"] == pytest.approx(3.0, abs=1e-3)
    assert st["ack_send"] == pytest.approx(1.0, abs=1e-3)
    # the 1h skew never leaks into a stage (offset-corrected)
    assert all(0 <= v < 30.0 for v in st.values())
    # and the offset estimate recovers the skew for trace_merge
    off = ra.offsets()
    assert off and all(abs(v - skew) < 0.1 for v in off.values())


# ----------------------------------------------------------------------
# mid-trace leadership transfer: no cross-term attribution
# ----------------------------------------------------------------------


def test_no_cross_term_attribution(clock):
    ra = ReplAttr(host="rt1:1", registry=MetricsRegistry())
    tr = _FakeTrace(tid=13)
    _open_record(ra, tr, peers=(2, 3), index=40, term=5)
    clock(0.002)
    # acks arriving with a LATER term never fold into the term-5 record
    ra.on_ack(CID, 3, 40, 6)
    assert ra.records() == []
    assert ra.records_dropped == 1
    # a commit in the later term covering the index attributes nothing
    tr2 = _FakeTrace(tid=14)
    _open_record(ra, tr2, peers=(2, 3), index=41, term=5)
    ra.on_commit(CID, 41, 6, {1: None, 2: None, 3: None}, 2, 1)
    assert ra.commits_attributed == 0
    assert ra.records_dropped == 2
    assert tr2.repl is None


def test_reset_drops_open_records(clock):
    ra = ReplAttr(host="rt1:1", registry=MetricsRegistry())
    tr = _FakeTrace(tid=15)
    _open_record(ra, tr, peers=(2, 3), index=50, term=5)
    ra.on_reset(CID)
    assert ra.records_dropped == 1
    # post-reset commits find nothing to misattribute
    ra.on_commit(CID, 50, 6, {1: None, 2: None, 3: None}, 2, 1)
    assert ra.commits_attributed == 0


def test_live_transfer_no_cross_term(clock):
    """Live half of the transfer contract: records opened under the old
    leader never close against the new leader's commits."""
    ra = ReplAttr(host="rt1:1", registry=MetricsRegistry())
    tr = _FakeTrace(tid=16)
    _open_record(ra, tr, peers=(2, 3), index=60, term=5)
    # transfer: raft.reset fires on the stepped-down leader
    ra.on_reset(CID)
    # the new leader (this host again, later term) re-proposes the
    # entry at the same index — a fresh record in the new term
    tr3 = _FakeTrace(tid=17)
    _open_record(ra, tr3, peers=(2, 3), index=60, term=7)
    clock(0.001)
    ra.on_ack(CID, 2, 60, 7)
    ra.on_commit(CID, 60, 7, {1: None, 2: None, 3: None}, 2, 1)
    recs = ra.records()
    assert len(recs) == 1
    assert recs[0]["term"] == 7
    assert recs[0]["tid"] == 17


def test_observer_ack_keeps_straggler_window_open(clock):
    """A non-voter (observer/witness) ack must not count toward the
    straggler-window release: with voters {1,2,3} and observer 9, the
    closed record stays registered until the lagging VOTER acks, so its
    late RTT still enriches the summary."""
    ra = ReplAttr(host="rt1:1", registry=MetricsRegistry())
    tr = _FakeTrace(tid=19)
    _open_record(ra, tr, peers=(2, 3, 9), index=80, term=5)
    voters = {1: None, 2: None, 3: None}
    clock(0.001)
    ra.on_ack(CID, 3, 80, 5)       # fast voter
    ra.on_commit(CID, 80, 5, voters, 2, 1)
    rec = ra.records()[-1]
    assert rec["closer"] == 3 and rec["laggards"] == [2]
    clock(0.001)
    ra.on_ack(CID, 9, 80, 5)       # observer ack — window must survive
    clock(0.050)
    ra.on_ack(CID, 2, 80, 5)       # the lagging voter, 52ms out
    assert rec["peers"]["2"]["acked"]
    assert rec["peers"]["2"]["rtt_ms"] == pytest.approx(52.0, abs=1e-3)


def test_sweep_expires_abandoned_records(clock):
    ra = ReplAttr(host="rt1:1", registry=MetricsRegistry(), expire_s=1.0)
    tr = _FakeTrace(tid=18)
    _open_record(ra, tr, peers=(2, 3), index=70, term=5)
    assert ra.sweep() == 0
    clock(2.0)
    assert ra.sweep() == 1
    assert ra.records_dropped == 1


# ----------------------------------------------------------------------
# satellites: transport metric families + latency-class introspection
# ----------------------------------------------------------------------


def test_transport_metrics_help_roundtrip():
    reg = MetricsRegistry()
    tm = TransportMetrics(registry=reg)
    tm.message_sent(3)
    tm.batch_sent(128)
    tm.batch_received(64)
    tm.snapshot_chunks_sent(4)
    tm.snapshot_chunks_received()
    out = io.StringIO()
    reg.write_health_metrics(out)
    text = out.getvalue()
    seen_help = set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            seen_help.add(line.split()[2])
        elif line.startswith("# TYPE "):
            name = line.split()[2]
            if name.startswith("dragonboat_transport_"):
                assert name in seen_help, f"{name} TYPE without HELP"
    # every family is zero-registered at construction: an idle
    # transport scrapes as zeros, not as absent families
    for name in TransportMetrics.NAMES:
        assert f"\n{name}" in text or text.startswith(name), (
            f"{name} missing from the exposition"
        )
    assert tm.value("dragonboat_transport_batch_sent_total") == 1
    assert tm.value("dragonboat_transport_bytes_sent_total") == 128
    assert tm.value("dragonboat_transport_bytes_received_total") == 64
    assert tm.value(
        "dragonboat_transport_snapshot_chunk_sent_total"
    ) == 4


def test_latency_injector_health_snapshot():
    inj = crossdomain(["a:1", "b:1"], ["c:1"], 0.04)
    assert inj.domain_of("a:1") == "A"
    assert inj.domain_of("c:1") == "B"
    assert inj.domain_of("nope:1") is None
    snap = inj.health_snapshot()
    assert snap["domains"] == {"a:1": "A", "b:1": "A", "c:1": "B"}
    assert snap["classes"]
    link = snap["links"].get("A|B")
    assert link is not None
    assert link["one_way_s"] == pytest.approx(0.04)
    assert link["cls"] is not None  # labeled by latency class
    # the nearest-class resolver tolerates unknown delays
    assert inj.class_name(12345.0) is None


def test_repl_metric_families_help_roundtrip():
    reg = MetricsRegistry()
    ReplAttr(host="rt1:1", registry=reg)
    out = io.StringIO()
    reg.write_health_metrics(out)
    text = out.getvalue()
    seen_help = set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            seen_help.add(line.split()[2])
        elif line.startswith("# TYPE "):
            name = line.split()[2]
            if name.startswith("dragonboat_repl_"):
                assert name in seen_help, f"{name} TYPE without HELP"
    assert "dragonboat_repl_commits_attributed_total" in text
