"""RSM layer tests (reference model: ``internal/rsm/*_test.go``)."""
import io

import pytest

from dragonboat_tpu.rsm import (
    MembershipState,
    SessionManager,
    StateMachine,
    Task,
    TaskQueue,
    from_concurrent_sm,
    from_regular_sm,
)
from dragonboat_tpu.rsm.session import Session
from dragonboat_tpu.rsm.snapshotio import (
    SnapshotFormatError,
    SnapshotReader,
    SnapshotWriter,
    shrink_snapshot,
    validate_snapshot_file,
)
from dragonboat_tpu.statemachine import (
    IStateMachine,
    Result,
    SMEntry,
    IConcurrentStateMachine,
)
from dragonboat_tpu.wire import (
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    SERIES_ID_FOR_REGISTER,
    SERIES_ID_FOR_UNREGISTER,
)
from dragonboat_tpu.wire.codec import encode_config_change


# ---------- sessions ----------


def test_session_response_cache_and_clear():
    s = Session(7)
    s.add_response(1, Result(value=11))
    s.add_response(2, Result(value=22))
    s.add_response(3, Result(value=33))
    r, ok = s.get_response(2)
    assert ok and r.value == 22
    s.clear_to(2)
    assert s.has_responded(2)
    assert not s.has_responded(3)
    _, ok = s.get_response(1)
    assert not ok
    _, ok = s.get_response(2)
    assert not ok
    r, ok = s.get_response(3)
    assert ok and r.value == 33


def test_session_duplicate_response_rejected():
    s = Session(7)
    s.add_response(1, Result(value=1))
    with pytest.raises(RuntimeError):
        s.add_response(1, Result(value=2))


def test_session_manager_lru_eviction():
    sm = SessionManager(max_sessions=3)
    for cid in (1, 2, 3):
        sm.register_client_id(cid)
    sm.client_registered(1)  # touch 1 → 2 is now LRU
    sm.register_client_id(4)
    assert sm.client_registered(2) is None
    assert sm.client_registered(1) is not None
    assert len(sm) == 3


def test_session_manager_serialization_roundtrip_and_hash():
    sm = SessionManager(max_sessions=10)
    sm.register_client_id(100)
    s = sm.client_registered(100)
    s.add_response(1, Result(value=7, data=b"seven"))
    sm.register_client_id(200)
    data = sm.save()
    sm2 = SessionManager.load(data, max_sessions=10)
    assert len(sm2) == 2
    assert sm.hash() == sm2.hash()  # hash before any divergent touches
    s2 = sm2.client_registered(100)
    r, ok = s2.get_response(1)
    assert ok and r.data == b"seven"
    # client_registered touches LRU order on sm2 only → hashes now diverge,
    # mirroring why every replica must apply the same lookup sequence
    assert sm.hash() != sm2.hash()
    # identical further ops on identically-ordered stores stay identical
    sm3 = SessionManager.load(data, max_sessions=10)
    sm4 = SessionManager.load(data, max_sessions=10)
    for m in (sm3, sm4):
        m.client_registered(100)
        m.register_client_id(300)
    assert sm3.hash() == sm4.hash()


# ---------- membership ----------


def cc(t, node_id, addr="a:1", ccid=0, initialize=False):
    return ConfigChange(
        type=t, node_id=node_id, address=addr, config_change_id=ccid,
        initialize=initialize,
    )


def test_membership_add_remove():
    m = MembershipState(1, 1, ordered=False)
    assert m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 1, "a:1"), 1)
    assert m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 2, "b:1"), 2)
    assert m.members.addresses == {1: "a:1", 2: "b:1"}
    assert m.handle_config_change(cc(ConfigChangeType.REMOVE_NODE, 2), 3)
    assert 2 in m.members.removed
    # adding a removed node back is rejected
    assert not m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 2, "b:1"), 4)


def test_membership_rejects_removing_only_node():
    m = MembershipState(1, 1, ordered=False)
    m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 1, "a:1"), 1)
    assert not m.handle_config_change(cc(ConfigChangeType.REMOVE_NODE, 1), 2)


def test_membership_ordered_config_change():
    m = MembershipState(1, 1, ordered=True)
    assert m.handle_config_change(
        cc(ConfigChangeType.ADD_NODE, 1, "a:1", initialize=True), 1
    )
    # stale config change id rejected
    assert not m.handle_config_change(
        cc(ConfigChangeType.ADD_NODE, 2, "b:1", ccid=0), 5
    )
    # correct id (== last applied index) accepted
    assert m.handle_config_change(
        cc(ConfigChangeType.ADD_NODE, 2, "b:1", ccid=1), 6
    )


def test_membership_observer_promotion():
    m = MembershipState(1, 1, ordered=False)
    m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 1, "a:1"), 1)
    m.handle_config_change(cc(ConfigChangeType.ADD_OBSERVER, 2, "b:1"), 2)
    assert 2 in m.members.observers
    # promotion with same address ok
    assert m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 2, "b:1"), 3)
    assert 2 in m.members.addresses and 2 not in m.members.observers
    # observer promotion with different address rejected
    m.handle_config_change(cc(ConfigChangeType.ADD_OBSERVER, 3, "c:1"), 4)
    assert not m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 3, "x:9"), 5)


def test_membership_add_existing_member_different_address_rejected():
    m = MembershipState(1, 1, ordered=False)
    m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 1, "a:1"), 1)
    assert not m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 1, "z:9"), 2)
    # same address re-add is a no-op accept (dedup)
    assert m.handle_config_change(cc(ConfigChangeType.ADD_NODE, 1, "a:1"), 3)


# ---------- snapshot io ----------


def test_snapshot_writer_reader_roundtrip(tmp_path):
    p = str(tmp_path / "snap.ss")
    w = SnapshotWriter(p)
    w.write_session(b"SESSIONDATA")
    w.write(b"A" * (3 * 1024 * 1024 + 17))  # multi-block payload
    w.finalize()
    assert validate_snapshot_file(p)
    r = SnapshotReader(p)
    assert r.read_session() == b"SESSIONDATA"
    body = r.read(-1)
    assert body == b"A" * (3 * 1024 * 1024 + 17)
    r.close()


def test_snapshot_corruption_detected(tmp_path):
    p = str(tmp_path / "snap.ss")
    w = SnapshotWriter(p)
    w.write_session(b"s")
    w.write(b"B" * 100_000)
    w.finalize()
    with open(p, "r+b") as f:
        f.seek(2048)
        f.write(b"\xff\xfe")
    assert not validate_snapshot_file(p)
    r = SnapshotReader(p)
    with pytest.raises(SnapshotFormatError):
        r.read_session()
        r.read(-1)
    r.close()


def test_snapshot_header_corruption_detected(tmp_path):
    p = str(tmp_path / "snap.ss")
    w = SnapshotWriter(p)
    w.write_session(b"s")
    w.finalize()
    with open(p, "r+b") as f:
        f.seek(10)
        f.write(b"\xff")
    with pytest.raises(SnapshotFormatError):
        SnapshotReader(p)


def test_shrink_snapshot(tmp_path):
    src, dst = str(tmp_path / "a.ss"), str(tmp_path / "b.ss")
    w = SnapshotWriter(src)
    w.write_session(b"sess")
    w.write(b"C" * 500_000)
    w.finalize()
    shrink_snapshot(src, dst)
    assert validate_snapshot_file(dst)
    r = SnapshotReader(dst)
    assert r.read_session() == b""
    assert r.read(-1) == b""
    r.close()


# ---------- StateMachine manager ----------


class KVSM(IStateMachine):
    """Tiny in-memory KV: cmd = b"set k v"."""

    def __init__(self):
        self.kv = {}
        self.update_count = 0

    def update(self, cmd):
        self.update_count += 1
        _, k, v = cmd.decode().split(" ")
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = repr(sorted(self.kv.items())).encode()
        w.write(data)

    def recover_from_snapshot(self, r, files, done):
        import ast

        self.kv = dict(ast.literal_eval(r.read(-1).decode()))


class RecordingProxy:
    def __init__(self):
        self.updates = []
        self.config_changes = []
        self.restored = []

    def node_ready(self):
        pass

    def apply_update(self, entry, result, rejected, ignored, notify_read):
        self.updates.append((entry.index, result, rejected, ignored))

    def apply_config_change(self, ccv, key, rejected):
        self.config_changes.append((ccv, key, rejected))

    def restore_remotes(self, ss):
        self.restored.append(ss)

    def should_stop(self):
        return False


def make_sm():
    proxy = RecordingProxy()
    kvsm = KVSM()
    sm = StateMachine(
        from_regular_sm(kvsm), None, proxy, cluster_id=1, node_id=1
    )
    return sm, kvsm, proxy


def entry(index, cmd=b"", client_id=0, series_id=0, responded_to=0, term=1):
    return Entry(
        term=term,
        index=index,
        cmd=cmd,
        client_id=client_id,
        series_id=series_id,
        responded_to=responded_to,
    )


def test_sm_applies_noop_session_entries():
    sm, kvsm, proxy = make_sm()
    t = Task(cluster_id=1, node_id=1, entries=[
        entry(1, b"set a 1"), entry(2, b"set b 2")])
    assert sm.handle([t]) is None
    assert kvsm.kv == {"a": "1", "b": "2"}
    assert sm.get_last_applied() == 2
    assert [u[0] for u in proxy.updates] == [1, 2]


def test_sm_out_of_order_entry_panics():
    sm, _, _ = make_sm()
    with pytest.raises(RuntimeError):
        sm.handle([Task(cluster_id=1, node_id=1, entries=[entry(5, b"set a 1")])])


def test_sm_session_lifecycle_and_dedup():
    sm, kvsm, proxy = make_sm()
    client = 42
    ents = [
        entry(1, client_id=client, series_id=SERIES_ID_FOR_REGISTER),
        entry(2, b"set a 1", client_id=client, series_id=1),
        entry(3, b"set a 2", client_id=client, series_id=1),  # dup retry
        entry(4, b"set b 3", client_id=client, series_id=2, responded_to=1),
        entry(5, client_id=client, series_id=SERIES_ID_FOR_UNREGISTER),
    ]
    sm.handle([Task(cluster_id=1, node_id=1, entries=ents)])
    # dup must not re-execute: 'a' stays '1', update ran twice total
    assert kvsm.kv == {"a": "1", "b": "3"}
    assert kvsm.update_count == 2
    # the dup got the cached result back
    assert proxy.updates[2][1] == proxy.updates[1][1]
    assert sm.get_last_applied() == 5


def test_sm_unregistered_session_rejected():
    sm, kvsm, proxy = make_sm()
    sm.handle([Task(cluster_id=1, node_id=1, entries=[
        entry(1, b"set a 1", client_id=99, series_id=1)])])
    assert kvsm.kv == {}
    assert proxy.updates[0][2] is True  # rejected


def test_sm_config_change_application():
    sm, _, proxy = make_sm()
    c = ConfigChange(type=ConfigChangeType.ADD_NODE, node_id=2, address="b:1")
    e = Entry(
        term=1, index=1, type=EntryType.CONFIG_CHANGE,
        cmd=encode_config_change(c), key=77,
    )
    sm.handle([Task(cluster_id=1, node_id=1, entries=[e])])
    assert 2 in sm.get_membership().addresses
    assert proxy.config_changes[0][2] is False
    assert proxy.config_changes[0][1] == 77
    assert sm.get_last_applied() == 1


def test_sm_handle_returns_snapshot_task():
    sm, _, _ = make_sm()
    t1 = Task(cluster_id=1, node_id=1, entries=[entry(1, b"set a 1")])
    t2 = Task(cluster_id=1, node_id=1, save=True)
    got = sm.handle([t1, t2])
    assert got is t2
    assert sm.get_last_applied() == 1


def test_sm_hash_deterministic_across_replicas():
    sm1, _, _ = make_sm()
    sm2, _, _ = make_sm()
    ents = [
        entry(1, client_id=5, series_id=SERIES_ID_FOR_REGISTER),
        entry(2, b"set x 9", client_id=5, series_id=1),
    ]
    sm1.handle([Task(cluster_id=1, node_id=1, entries=list(ents))])
    sm2.handle([Task(cluster_id=1, node_id=1, entries=list(ents))])
    assert sm1.get_hash() == sm2.get_hash()
    assert sm1.get_session_hash() == sm2.get_session_hash()


class ConcKVSM(IConcurrentStateMachine):
    def __init__(self):
        self.kv = {}

    def update(self, entries):
        for e in entries:
            _, k, v = e.cmd.decode().split(" ")
            self.kv[k] = v
            e.result = Result(value=len(self.kv))
        return entries

    def lookup(self, query):
        return self.kv.get(query)

    def prepare_snapshot(self):
        return dict(self.kv)  # point-in-time copy

    def save_snapshot(self, ctx, w, files, done):
        w.write(repr(sorted(ctx.items())).encode())

    def recover_from_snapshot(self, r, files, done):
        import ast

        self.kv = dict(ast.literal_eval(r.read(-1).decode()))


def test_sm_concurrent_batches_updates():
    proxy = RecordingProxy()
    csm = ConcKVSM()
    sm = StateMachine(from_concurrent_sm(csm), None, proxy, 1, 1)
    ents = [entry(i, b"set k%d v" % i) for i in range(1, 6)]
    sm.handle([Task(cluster_id=1, node_id=1, entries=ents)])
    assert len(csm.kv) == 5
    assert [u[0] for u in proxy.updates] == [1, 2, 3, 4, 5]
    # prepare_snapshot captures a point-in-time ctx
    meta = sm.prepare_snapshot(__import__(
        "dragonboat_tpu.rsm.statemachine", fromlist=["SSRequest"]
    ).SSRequest())
    assert meta.index == 5
    assert len(meta.ctx) == 5


# ---------- TaskQueue ----------


def test_task_queue_fifo_and_backpressure():
    q = TaskQueue()
    for i in range(5):
        q.enqueue(Task(index=i))
    assert q.get().index == 0
    rest = q.get_all()
    assert [t.index for t in rest] == [1, 2, 3, 4]
    assert q.get() is None
    assert q.more_entries_to_apply()


# ---------- on-disk SM recover/shrink corner tables ----------
#
# First slice of the reference's ``internal/rsm/statemachine_test.go``
# recover/shrink corner families (VERDICT r5 item 7), with vfs.ErrorFS
# fault injection on the snapshot path: on-disk init-index skipping,
# metadata-only recovery, recover/save under injected I/O errors (state
# must stay at the pre-fault watermarks), and shrink fault atomicity.

from dragonboat_tpu import vfs
from dragonboat_tpu.rsm import from_on_disk_sm
from dragonboat_tpu.rsm.statemachine import SSReqType, SSRequest, Task as SMTask
from dragonboat_tpu.snapshotter import Snapshotter
from dragonboat_tpu.statemachine import IOnDiskStateMachine


class DiskKVSM(IOnDiskStateMachine):
    """On-disk KV whose durable store is a plain dict + an applied index
    it persists conceptually (the tests inject the 'persisted' index via
    ``init_index``, the reference tests' OnDiskInitIndex knob)."""

    def __init__(self, init_index: int = 0):
        self.kv = {}
        self.init_index = init_index
        self.update_count = 0
        self.recovered = 0

    def open(self, stopc) -> int:
        return self.init_index

    def update(self, entries):
        for e in entries:
            self.update_count += 1
            _, k, v = e.cmd.decode().split(" ")
            self.kv[k] = v
            e.result = Result(value=len(self.kv))
        return entries

    def lookup(self, query):
        return self.kv.get(query)

    def sync(self):
        pass

    def prepare_snapshot(self):
        return dict(self.kv)

    def save_snapshot(self, ctx, w, done):
        w.write(repr(sorted(ctx.items())).encode())

    def recover_from_snapshot(self, r, done):
        import ast

        self.recovered += 1
        self.kv = dict(ast.literal_eval(r.read(-1).decode()))


class _FakeLogDB:
    def __init__(self):
        self.snapshots = []

    def save_snapshot(self, cluster_id, node_id, ss):
        self.snapshots.append(ss)

    def list_snapshots(self, cluster_id, node_id):
        return list(self.snapshots)


def make_disk_sm(tmp_path, fs=vfs.DEFAULT, init_index=0, sub="snaps"):
    proxy = RecordingProxy()
    dsm = DiskKVSM(init_index)
    snap = Snapshotter(
        str(tmp_path / sub), cluster_id=1, node_id=1, logdb=_FakeLogDB(),
        fs=fs,
    )
    sm = StateMachine(from_on_disk_sm(dsm), snap, proxy, 1, 1)
    sm.open()
    return sm, dsm, proxy, snap


def _apply(sm, lo, hi):
    ents = [entry(i, b"set k%d v%d" % (i, i)) for i in range(lo, hi + 1)]
    sm.handle([Task(cluster_id=1, node_id=1, entries=ents)])


def test_ondisk_entries_below_init_index_skipped(tmp_path):
    """shouldApplyEntry/onDiskInitIndex: entries the SM's own store
    already covers advance the watermark WITHOUT re-applying (reference
    statemachine_test.go on-disk init-index table)."""
    sm, dsm, proxy, _ = make_disk_sm(tmp_path, init_index=3)
    sm.set_batched_last_applied(3)
    sm.last_applied = 3
    _apply(sm, 4, 6)
    # only 4..6 executed; nothing from the covered prefix
    assert dsm.update_count == 3
    assert sm.get_last_applied() == 6
    assert sm.on_disk_index == 6
    # the skipped-prefix contract also holds when replay starts below:
    sm2, dsm2, proxy2, _ = make_disk_sm(tmp_path, init_index=2, sub="s2")
    _apply(sm2, 1, 3)
    assert dsm2.update_count == 1  # only index 3 executed
    assert sm2.get_last_applied() == 3
    # skipped entries still produced (ignored) apply notifications
    assert [u[3] for u in proxy2.updates] == [True, True, False]


def test_ondisk_recover_covered_snapshot_adopts_metadata_only(tmp_path):
    """Recover with ``ss.on_disk_index <= on_disk_init_index``: the SM's
    own store already covers the image — watermarks/membership adopt,
    recover_from_snapshot must NOT run (reference Recover :228-341)."""
    sm, dsm, _, snap = make_disk_sm(tmp_path, init_index=0)
    _apply(sm, 1, 5)
    ss, env = sm.save(SSRequest())
    snap.commit(ss, env)
    assert ss.on_disk_index == 5
    # second replica whose own store is AHEAD of the snapshot
    sm2, dsm2, _, _ = make_disk_sm(tmp_path, init_index=9, sub="s2")
    got = sm2.recover(SMTask(cluster_id=1, node_id=1, recover=True, ss=ss))
    assert got is ss
    assert dsm2.recovered == 0            # metadata-only
    assert sm2.get_last_applied() == ss.index
    assert sm2.on_disk_index == 9         # own store stays authoritative


def test_ondisk_recover_newer_snapshot_restores_image(tmp_path):
    sm, dsm, _, snap = make_disk_sm(tmp_path, init_index=0)
    _apply(sm, 1, 5)
    ss, env = sm.save(SSRequest())
    snap.commit(ss, env)
    sm2, dsm2, _, _ = make_disk_sm(tmp_path, init_index=2, sub="s2")
    sm2.recover(SMTask(cluster_id=1, node_id=1, recover=True, ss=ss))
    assert dsm2.recovered == 1
    assert dsm2.kv == dsm.kv
    assert sm2.get_last_applied() == 5
    assert sm2.on_disk_index == 5


def test_ondisk_recover_read_fault_leaves_state_unchanged(tmp_path):
    """ErrorFS read fault mid-recover: the exception propagates and the
    SM keeps its pre-fault watermarks and image (the reference's
    fault-injected recover corners)."""
    base = vfs.MemFS()
    sm, dsm, _, snap = make_disk_sm(tmp_path, fs=base, init_index=0)
    _apply(sm, 1, 5)
    ss, env = sm.save(SSRequest())
    snap.commit(ss, env)
    # reader SM on an ErrorFS that fails the 2nd read of the image file
    efs = vfs.ErrorFS(base, vfs.Injector.after_n(1, ops={"read"}))
    sm2, dsm2, _, _ = make_disk_sm(tmp_path, fs=efs, init_index=0, sub="s2")
    _apply(sm2, 1, 2)
    with pytest.raises(OSError):
        sm2.recover(SMTask(cluster_id=1, node_id=1, recover=True, ss=ss))
    assert sm2.get_last_applied() == 2      # pre-fault watermark
    assert sm2.snapshot_index == 0
    assert dsm2.kv == {"k1": "v1", "k2": "v2"}
    # the fs healed (injector only counts reads): recovery then succeeds
    sm3, dsm3, _, _ = make_disk_sm(tmp_path, fs=base, init_index=0, sub="s3")
    sm3.recover(SMTask(cluster_id=1, node_id=1, recover=True, ss=ss))
    assert dsm3.kv == dsm.kv


def test_ondisk_save_write_fault_cleans_tmp_and_keeps_index(tmp_path):
    """ErrorFS write fault mid-save: Snapshotter.save aborts, removes the
    temp dir, and snapshot_index does not advance — a later healthy save
    from the same SM succeeds at the same index."""
    base = vfs.MemFS()
    efs = vfs.ErrorFS(base, vfs.Injector.after_n(0, ops={"write"}))
    sm, dsm, _, snap = make_disk_sm(tmp_path, fs=efs, init_index=0)
    _apply(sm, 1, 4)
    with pytest.raises(OSError):
        sm.save(SSRequest())
    assert sm.snapshot_index == 0
    root = str(tmp_path / "snaps")
    leftovers = [d for d in base.listdir(root) if "generating" in d]
    assert leftovers == [], leftovers
    # heal the fs: same snapshotter, save succeeds and the index moves
    snap.fs = base
    sm.snapshotter.fs = base
    healthy = Snapshotter(root, 1, 1, logdb=_FakeLogDB(), fs=base)
    sm.snapshotter = healthy
    ss, env = sm.save(SSRequest())
    healthy.commit(ss, env)
    assert ss.index == 4 and sm.snapshot_index == 4


def test_shrink_snapshot_fault_atomicity(tmp_path):
    """shrink under a dst-write fault: the destination is not a valid
    snapshot, the source stays intact, and a healthy retry produces a
    valid shrunken image (reference shrink corner family)."""
    base = vfs.MemFS()
    src, dst = "/a.ss", "/b.ss"
    w = SnapshotWriter(src, fs=base)
    w.write_session(b"sess")
    w.write(b"D" * 300_000)
    w.finalize()
    efs = vfs.ErrorFS(base, vfs.Injector.on_path("b.ss", ops={"write"}))
    with pytest.raises(OSError):
        shrink_snapshot(src, dst, fs=efs)
    assert not validate_snapshot_file(dst, fs=base)
    assert validate_snapshot_file(src, fs=base)   # source untouched
    shrink_snapshot(src, dst, fs=base)            # healthy retry
    assert validate_snapshot_file(dst, fs=base)
    r = SnapshotReader(dst, fs=base)
    assert r.read_session() == b"" and r.read(-1) == b""
    r.close()


def test_ondisk_witness_snapshot_recover_is_metadata_only(tmp_path):
    """A witness/dummy snapshot adopts watermarks without touching the
    SM image (reference witness snapshot corners)."""
    from dragonboat_tpu.wire import Snapshot as WireSnapshot

    sm, dsm, _, _ = make_disk_sm(tmp_path, init_index=0)
    _apply(sm, 1, 2)
    ss = WireSnapshot(index=7, term=3, witness=True, cluster_id=1)
    got = sm.recover(SMTask(cluster_id=1, node_id=1, recover=True, ss=ss))
    assert got is ss
    assert dsm.recovered == 0
    assert sm.get_last_applied() == 7
    assert dsm.kv == {"k1": "v1", "k2": "v2"}  # image untouched
