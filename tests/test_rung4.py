"""Rung 4 of the config ladder: 64k groups × 5 peer slots at correctness
scale (BASELINE.md; reference scaling claim README.md Performance §).

Round-3 verdict: 64k appeared only in kernel micro-benches; nothing drove
the COORDINATOR at that scale with churn.  This test runs the live
TpuQuorumCoordinator (CPU backend) over 65,536 registered groups:

- sustained bulk load (every group commits every round via the
  vectorized ack_block ingest) with a 9:1 read:write interleave
  (committed_index queries against staged commits);
- a 256-group sampled differential: full scalar Raft oracles driven in
  lockstep, commitIndex asserted bit-identical every round;
- rolling membership churn: row recycling (unregister/re-register
  thousands of groups mid-load) plus add/remove-node membership resyncs
  on sampled oracles;
- leader transfers on sampled groups (step down, re-elect at a higher
  term, commit again).

Marked slow: one full run is a few minutes on the 8-vCPU CI box.
"""
from __future__ import annotations

import contextlib
import threading
import time

import numpy as np
import pytest

from dragonboat_tpu.raft import InMemLogDB
from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator
from dragonboat_tpu.wire import Entry, Message, MessageType as MT

from tests.raft_harness import new_test_raft

# heavy multi-NodeHost tests serialize on one xdist worker
# (--dist loadgroup): 4-way-parallel multiprocess clusters
# starve each other on an 8-vCPU box
pytestmark = [pytest.mark.slow, pytest.mark.xdist_group("heavy-multiprocess")]

N = 65_536
SAMPLE = 256
PEERS = [1, 2, 3, 4, 5]


class FakeNode:
    """Minimal node shim (same contract as test_device_ticks)."""

    def __init__(self, cid, raft):
        self.cluster_id = cid
        self.raft_mu = threading.RLock()

        class _P:
            pass

        self.peer = _P()
        self.peer.raft = raft
        self.commits = []

    def offload_commit(self, q):
        r = self.peer.raft
        with self.raft_mu:
            if r.is_leader() and r.log.try_commit(q, r.term):
                self.commits.append(q)

    def offload_election(self, won, term):
        # twin of Node.offload_election: the device tallies votes, the
        # host applies the outcome under raftMu, term-pinned
        r = self.peer.raft
        with self.raft_mu:
            if r.is_candidate() and r.term == term:
                if won:
                    r.become_leader()
                else:
                    r.become_follower(r.term, 0)

    def offload_tick_elect(self):
        pass

    def offload_tick_heartbeat(self):
        pass

    def offload_tick_demote(self):
        pass


def _assert_parity(eng, oracles, cids, tag, timeout=8.0, mu=None):
    """commitIndex bit-identity with callback-timing tolerance: the
    coordinator's background round thread delivers offload_commit OUTSIDE
    its lock, so the oracle may trail the engine by one callback for a
    moment — the VALUES still must match exactly at quiescence.

    ``mu`` (the coordinator lock) guards the device reads: a concurrent
    step() donates the previous device state, so an unlocked
    ``committed_index`` could touch a deleted buffer mid-dispatch."""
    deadline = time.time() + timeout
    while True:
        bad = []
        with (mu if mu is not None else contextlib.nullcontext()):
            for cid in cids:
                got = eng.committed_index(cid)
                want = oracles[cid].peer.raft.log.committed
                if got != want:
                    bad.append((cid, got, want))
        if not bad:
            return
        if time.time() > deadline:
            raise AssertionError((tag, bad[:4]))
        time.sleep(0.01)


def _mk_oracle(cid):
    r = new_test_raft(1, PEERS, 10, 1, InMemLogDB())
    r.cluster_id = cid
    r.become_candidate()
    r.become_leader()
    return r


@pytest.mark.slow
def test_rung4_64k_groups_mixed_load_with_churn():
    coord = TpuQuorumCoordinator(capacity=N, n_peers=5, drive_ticks=False)
    try:
        eng = coord.eng
        # --- sampled groups: real scalar oracles through the coordinator
        oracles = {}
        for g in range(SAMPLE):
            cid = 1 + g
            r = _mk_oracle(cid)
            n = FakeNode(cid, r)
            r.offload = coord
            oracles[cid] = n
            coord._nodes[cid] = n
            with coord._mu:
                coord._sync_row_locked(n)
        # --- bulk groups: engine rows driven by the block-ingest path
        with coord._mu:
            for g in range(SAMPLE, N):
                cid = 1 + g
                eng.add_group(cid, node_ids=PEERS, self_id=1)
                eng.set_leader(cid, term=1, term_start=1, last_index=1)
            eng._upload_dirty()
        bulk_rows = np.array(
            [eng.groups[1 + g].row for g in range(SAMPLE, N)], np.int32
        )
        n_bulk = bulk_rows.size

        reads = writes = 0
        t0 = time.perf_counter()
        rounds = 8
        for rnd in range(1, rounds + 1):
            # writes: every bulk group appends one entry (rel index rnd+1,
            # base 1) acked by self + 2 followers (quorum of 5)
            rows3 = np.concatenate([bulk_rows, bulk_rows, bulk_rows])
            slots = np.concatenate([
                np.zeros(n_bulk, np.int32),
                np.ones(n_bulk, np.int32),
                np.full(n_bulk, 2, np.int32),
            ])
            rels = np.full(3 * n_bulk, rnd + 1, np.int32)
            with coord._mu:
                eng.ack_block(rows3, slots, rels)
            # sampled: oracle in lockstep through the coordinator's
            # staging API (ack -> _drain -> step)
            for cid, node in oracles.items():
                r = node.peer.raft
                r.handle(Message(
                    from_=1, to=1, type=MT.PROPOSE, entries=[Entry(cmd=b"x")]
                ))
                idx = r.log.last_index()
                coord.ack(cid, 2, idx)
                coord.ack(cid, 3, idx)
            coord.flush()
            writes += n_bulk + SAMPLE
            # mixed 9:1: reads are commit-watermark queries (the
            # coordinator's read-side role); sample across the space —
            # under coord._mu (step() donates the previous device state)
            with coord._mu:
                for cid in range(1, N + 1, max(1, N // (9 * 64))):
                    eng.committed_index(cid)
                    reads += 1
            # bit-identity on every sampled group, every round
            _assert_parity(
                eng, oracles, list(oracles), f"round {rnd}", mu=coord._mu
            )
        elapsed = time.perf_counter() - t0
        # every bulk group committed every round
        with coord._mu:
            for g in (SAMPLE, SAMPLE + n_bulk // 2, N - 1):
                cid = 1 + g
                assert eng.committed_index(cid) == 1 + rounds, cid
        print(
            f"\nrung4: {N} groups x {rounds} rounds: "
            f"{writes / elapsed:.0f} writes/s {reads / elapsed:.0f} reads/s "
            f"(coordinator path, CPU backend)"
        )

        # --- rolling membership churn: recycle 4,096 bulk rows mid-life
        churn = [1 + g for g in range(SAMPLE, SAMPLE + 4096)]
        with coord._mu:
            for cid in churn:
                eng.remove_group(cid)
            for i, _ in enumerate(churn):
                cid = 200_000 + i
                eng.add_group(cid, node_ids=PEERS, self_id=1)
                eng.set_leader(cid, term=1, term_start=1, last_index=1)
            eng._upload_dirty()
        fresh_rows = np.array(
            [eng.groups[200_000 + i].row for i in range(4096)], np.int32
        )
        with coord._mu:
            eng.ack_block(
                np.concatenate([fresh_rows, fresh_rows, fresh_rows]),
                np.concatenate([
                    np.zeros(4096, np.int32), np.ones(4096, np.int32),
                    np.full(4096, 2, np.int32),
                ]),
                np.full(3 * 4096, 2, np.int32),
            )
        coord.flush()
        with coord._mu:
            for i in (0, 2048, 4095):
                assert eng.committed_index(200_000 + i) == 2
            # survivors untouched by the recycling
            assert eng.committed_index(1 + SAMPLE + 4096) == 1 + rounds

        # --- membership change on sampled oracles: 5 -> 4 voters, commit
        # quorum math must follow (resync via membership_changed)
        changed = list(oracles)[:32]
        for cid in changed:
            node = oracles[cid]
            r = node.peer.raft
            with node.raft_mu:
                r.remove_node(5)
            coord.membership_changed(cid)
        coord.flush()
        for cid in changed:
            node = oracles[cid]
            r = node.peer.raft
            r.handle(Message(
                from_=1, to=1, type=MT.PROPOSE, entries=[Entry(cmd=b"y")]
            ))
            idx = r.log.last_index()
            # 4 voters: quorum 3 = self + 2 acks
            coord.ack(cid, 2, idx)
            coord.ack(cid, 3, idx)
        coord.flush()
        _assert_parity(eng, oracles, changed, "membership-change", mu=coord._mu)
        for cid in changed:
            assert oracles[cid].peer.raft.log.committed >= 1 + rounds + 1

        # --- leader transfer on sampled groups: step down, win a new
        # election at a higher term, commit again
        transferred = list(oracles)[32:64]
        for cid in transferred:
            node = oracles[cid]
            r = node.peer.raft
            with node.raft_mu:
                r.become_follower(r.term + 1, 2)
            coord.set_follower(cid, r.term)
        coord.flush()
        for cid in transferred:
            node = oracles[cid]
            r = node.peer.raft
            with node.raft_mu:
                # campaign (includes the self-vote, raft.go:1098)
                r.handle(Message(from_=1, to=1, type=MT.ELECTION))
            assert r.is_candidate(), cid
            coord.set_candidate(cid, r.term)
            coord.vote(cid, 1, True)
            for p in (2, 3):
                r.handle(Message(
                    from_=p, to=1, term=r.term, type=MT.REQUEST_VOTE_RESP
                ))
                coord.vote(cid, p, True)
        coord.flush()
        deadline = time.time() + 8
        for cid in transferred:
            node = oracles[cid]
            r = node.peer.raft
            # the won-flag callback (offload_election) is delivered outside
            # the coordinator lock; poll briefly like _assert_parity
            while not r.is_leader() and time.time() < deadline:
                time.sleep(0.01)
            assert r.is_leader(), cid
            coord.set_leader(
                cid, term=r.term, term_start=r.log.last_index(),
                last_index=r.log.last_index(),
            )
            r.handle(Message(
                from_=1, to=1, type=MT.PROPOSE, entries=[Entry(cmd=b"z")]
            ))
            idx = r.log.last_index()
            for p in (2, 3):
                r.handle(Message(
                    from_=p, to=1, term=r.term, type=MT.REPLICATE_RESP,
                    log_index=idx,
                ))
                coord.ack(cid, p, idx)
        coord.flush()
        _assert_parity(eng, oracles, transferred, "leader-transfer", mu=coord._mu)
    finally:
        coord.stop()
